"""Training guardian (ISSUE 10): in-program NaN/Inf detection, dynamic
loss scaling, auto-rollback to the last-good checkpoint.

Acceptance contract: a chaos-injected NaN gradient at step k causes
exactly one ``guardian_skipped_steps`` bump and (with the retrying-loop
pattern) a final loss trajectory bitwise-identical to the clean run; a
persistent-NaN run exhausts the skip budget, rolls back to the pinned
last-good checkpoint, quarantines the batch window, and converges —
while ``xla_program_calls`` per step and graftcheck findings (zero,
tests/test_tracecheck_clean.py) are unchanged.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, chaos, checkpoint, gluon, guardian, \
    profiler, telemetry
from mxnet_tpu.gluon import fused_trainer, nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaks():
    """Every test leaves the process guardian- and chaos-free."""
    yield
    g = guardian.current()
    if g is not None:
        guardian.uninstall(g)
    chaos.configure(None)
    from mxnet_tpu.checkpoint import hooks
    m = hooks.active()
    if m is not None:
        hooks.unregister(m)


def _set_fused(value):
    if value is None:
        os.environ.pop("MXNET_FUSED_TRAINER", None)
    else:
        os.environ["MXNET_FUSED_TRAINER"] = value
    fused_trainer.refresh_from_env()


def _build(seed=0, optimizer="adam"):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.Sequential()
    net.add(nn.Dense(8, activation="relu"))
    net.add(nn.Dense(4))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), optimizer,
                            {"learning_rate": 0.05})
    return net, trainer


_RS = np.random.RandomState(1)
_X = _RS.randn(8, 8, 6).astype(np.float32)
_Y = _RS.randn(8, 8, 4).astype(np.float32)


def _run(steps=6, guard=None, poison=None, retry=False, fused=True,
         seed=0, optimizer_name="adam"):
    """Seeded mini-run; returns (losses, params, actions)."""
    prev = os.environ.get("MXNET_FUSED_TRAINER")
    _set_fused("1" if fused else "0")
    try:
        chaos.configure(poison)
        net, trainer = _build(seed, optimizer_name)
        loss_fn = gluon.loss.L2Loss()
        losses, actions = [], []
        for i in range(steps):
            while True:
                with autograd.record():
                    loss = loss_fn(net(mx.nd.array(_X[i])),
                                   mx.nd.array(_Y[i]))
                    scaled = guard.scale_loss(loss) if guard else loss
                scaled.backward()
                trainer.step(8)
                if guard is not None:
                    actions.append(guard.last_action())
                    if retry and guard.last_action() == "skipped":
                        continue
                break
            losses.append(float(np.float64(loss.asnumpy().sum())))
        params = {i: p.data().asnumpy()
                  for i, p in enumerate(net.collect_params().values())}
        return losses, params, actions
    finally:
        chaos.configure(None)
        _set_fused(prev)


def _assert_bitwise(a, b, what):
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(a[k], b[k],
                                      err_msg="%s[%s]" % (what, k))


# ---------------------------------------------------------------------------
# detection + in-program skip
# ---------------------------------------------------------------------------

def test_guarded_clean_run_is_bitwise_transparent():
    """Guardian on (even with dynamic scaling: power-of-two scales are
    exact) must not perturb a healthy run by one ulp."""
    ref_l, ref_p, _ = _run()
    g = guardian.TrainingGuardian(loss_scale="dynamic")
    try:
        got_l, got_p, actions = _run(guard=g)
    finally:
        g.close()
    assert got_l == ref_l
    _assert_bitwise(got_p, ref_p, "param")
    assert actions == ["applied"] * 6


def test_nan_gradient_skips_exactly_one_step():
    before = telemetry.counter("guardian_skipped_steps")
    g = guardian.TrainingGuardian()
    try:
        ref_l, ref_p, _ = _run()
        got_l, got_p, actions = _run(guard=g,
                                     poison="grad.bucket:nan@3")
    finally:
        g.close()
    assert telemetry.counter("guardian_skipped_steps") == before + 1
    assert actions.count("skipped") == 1 and "rollback" not in actions
    # the skipped step left params at their pre-step values: losses
    # before and AT the poisoned step match the clean run, later ones
    # diverge by exactly one missing update (no NaN anywhere)
    assert got_l[:3] == ref_l[:3]
    assert got_l[3:] != ref_l[3:]
    assert all(np.isfinite(v).all() for v in got_p.values())


def test_retrying_loop_recovers_bitwise():
    """The acceptance identity: skip the poisoned step, retry the same
    batch (the next chaos occurrence is clean), finish bitwise-identical
    to the unpoisoned run — on the fused path AND the
    MXNET_FUSED_TRAINER=0 oracle."""
    ref_l, ref_p, _ = _run()
    for fused in (True, False):
        g = guardian.TrainingGuardian()
        try:
            got_l, got_p, actions = _run(guard=g, retry=True, fused=fused,
                                         poison="grad.bucket:nan@3")
        finally:
            g.close()
        assert actions.count("skipped") == 1, (fused, actions)
        assert got_l == ref_l, "fused=%s diverged" % fused
        _assert_bitwise(got_p, ref_p, "param[fused=%s]" % fused)


def test_skip_does_not_advance_update_counts():
    """hyper['t'] (Adam bias correction) must not tick on a skipped
    step, or the retried update diverges from the clean trajectory."""
    g = guardian.TrainingGuardian()
    try:
        chaos.configure("grad.bucket:nan@2")
        net, trainer = _build()
        loss_fn = gluon.loss.L2Loss()
        for i in range(2):
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(_X[i])),
                               mx.nd.array(_Y[i]))
            g.observe_loss(loss)
            loss.backward()
            trainer.step(8)
        assert g.last_step_skipped()
        counts = set(trainer._optimizer._index_update_count.values())
        assert counts == {1}, counts       # one applied step only
        assert trainer._optimizer.num_update == 1
    finally:
        g.close()


def test_nonfinite_loss_triggers_skip():
    """The verdict folds the RECORDED loss in: a NaN loss with finite
    gradients still suppresses the update."""
    g = guardian.TrainingGuardian()
    try:
        net, trainer = _build()
        loss_fn = gluon.loss.L2Loss()
        with autograd.record():
            loss = loss_fn(net(mx.nd.array(_X[0])), mx.nd.array(_Y[0]))
        g.observe_loss(loss * float("nan"))
        loss.backward()
        before = {i: p.data().asnumpy().copy()
                  for i, p in enumerate(net.collect_params().values())}
        trainer.step(8)
        assert g.last_step_skipped()
        for i, p in enumerate(net.collect_params().values()):
            np.testing.assert_array_equal(p.data().asnumpy(), before[i])
    finally:
        g.close()


def test_verdict_costs_no_extra_program_on_fused_path():
    """The guard rides INSIDE the existing donated program: steady-state
    xla_program_calls per step are identical with and without it."""
    def steady_calls(guard):
        net, trainer = _build()
        loss_fn = gluon.loss.L2Loss()
        for i in range(3):
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(_X[i])),
                               mx.nd.array(_Y[i]))
            if guard:
                guard.observe_loss(loss)
            loss.backward()
            before = profiler.counter("xla_program_calls")
            trainer.step(8)
            delta = profiler.counter("xla_program_calls") - before
        return delta
    plain = steady_calls(None)
    g = guardian.TrainingGuardian()
    try:
        guarded = steady_calls(g)
    finally:
        g.close()
    assert guarded == plain == 1


# ---------------------------------------------------------------------------
# dynamic loss scaling
# ---------------------------------------------------------------------------

def test_dynamic_scale_halves_on_overflow_and_grows_when_clean():
    g = guardian.TrainingGuardian(loss_scale="dynamic", growth_interval=2)
    try:
        assert g.loss_scale == 2.0 ** 16
        scales = []
        _run(steps=2, guard=g, poison="grad.bucket:nan@1")
        scales.append(g.loss_scale)        # halved once on the overflow
        _run(steps=4, guard=g)
        scales.append(g.loss_scale)        # grew back on clean streaks
        assert scales[0] == 2.0 ** 15
        assert scales[1] > scales[0]
    finally:
        g.close()


def test_static_scale_is_bitwise_transparent():
    ref_l, ref_p, _ = _run()
    g = guardian.TrainingGuardian(loss_scale=8.0)
    try:
        got_l, got_p, _ = _run(guard=g)
    finally:
        g.close()
    assert got_l == ref_l
    _assert_bitwise(got_p, ref_p, "param")


def test_env_loss_scale_spec(monkeypatch):
    monkeypatch.setenv("MXNET_GUARDIAN_LOSS_SCALE", "dynamic")
    g = guardian.TrainingGuardian()
    assert g._dynamic and g.loss_scale == 2.0 ** 16
    g.close()
    monkeypatch.setenv("MXNET_GUARDIAN_LOSS_SCALE", "128")
    g = guardian.TrainingGuardian()
    assert not g._dynamic and g.loss_scale == 128.0
    g.close()
    monkeypatch.setenv("MXNET_GUARDIAN_LOSS_SCALE", "0")
    g = guardian.TrainingGuardian()
    assert g.loss_scale == 1.0
    g.close()


# ---------------------------------------------------------------------------
# EWMA spike detector
# ---------------------------------------------------------------------------

def test_loss_spike_books_counter_and_blocks_pinning():
    g = guardian.TrainingGuardian(spike_factor=5.0)
    try:
        for _ in range(12):                   # warm the EWMA past warmup
            g.observe_loss(mx.nd.array(np.float32([1.0])))
            g.after_step(True)
        before = telemetry.counter("guardian_loss_spikes")
        g.observe_loss(mx.nd.array(np.float32([100.0])))
        assert g.after_step(True) is False    # applied, not skipped
        assert telemetry.counter("guardian_loss_spikes") == before + 1
        # the spike did not poison the baseline
        assert g._ewma == pytest.approx(1.0)
    finally:
        g.close()


# ---------------------------------------------------------------------------
# rollback to last-good
# ---------------------------------------------------------------------------

def _iter_build(seed=7):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.Sequential()
    net.add(nn.Dense(8, activation="relu"))
    net.add(nn.Dense(4))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    rs = np.random.RandomState(3)
    data = mx.nd.array(rs.randn(64, 6).astype(np.float32))
    label = mx.nd.array(rs.randn(64, 4).astype(np.float32))
    it = mx.io.NDArrayIter(data, label, batch_size=8, shuffle=True,
                           last_batch_handle="discard")
    return net, trainer, it


def test_exhausted_skip_budget_rolls_back_and_recovers(tmp_path):
    chaos.configure("grad.bucket:nan@5-6")
    net, trainer, it = _iter_build()
    mgr = checkpoint.CheckpointManager(str(tmp_path), trainer=trainer,
                                       data_iter=it, every_steps=2,
                                       num_shards=2)
    g = guardian.TrainingGuardian(manager=mgr, max_skips=2)
    loss_fn = gluon.loss.L2Loss()
    actions, losses = [], []
    try:
        for _ in range(10):
            try:
                batch = it.next()
            except StopIteration:
                it.reset()
                batch = it.next()
            with autograd.record():
                loss = loss_fn(net(batch.data[0]), batch.label[0])
                scaled = g.scale_loss(loss)
            scaled.backward()
            trainer.step(8)
            actions.append(g.last_action())
            mgr.wait()                     # commits land promptly
            losses.append(float(np.float64(loss.asnumpy().sum())))
    finally:
        g.close()
        mgr.close()
    assert actions[4] == "skipped" and actions[5] == "rollback", actions
    assert actions[6:] == ["applied"] * 4, actions
    # the abandoned timeline was evicted: a restart's newest-first
    # restore() can never resume the rolled-away (unverified) state —
    # everything on disk is now <= the run's re-advanced frontier, and
    # the rollback target itself survived the eviction
    import glob as _glob
    steps_on_disk = sorted(
        int(os.path.basename(p).split("-")[1])
        for p in _glob.glob(str(tmp_path / "ckpt-*")))
    assert g._last_rollback[1] in steps_on_disk
    assert max(steps_on_disk) <= mgr.step
    # rolled back TO the pinned checkpoint, quarantined the window
    assert g._last_rollback is not None
    _, to_step, quarantined = g._last_rollback
    assert to_step == 2              # the pin at rollback time
    assert mgr.last_good_step >= to_step   # pin re-advanced post-recovery
    assert quarantined > 0
    assert all(np.isfinite(v) for v in losses)
    for p in net.collect_params().values():
        assert np.isfinite(p.data().asnumpy()).all()


def test_budget_without_manager_keeps_skipping_nonfatally():
    g = guardian.TrainingGuardian(max_skips=1)
    try:
        _, _, actions = _run(steps=4, guard=g,
                             poison="grad.bucket:nan@2-3")
        # no manager: rollback degrades to continued skips, run survives
        assert actions.count("skipped") == 2
        assert "rollback" not in actions
        assert actions[-1] == "applied"
    finally:
        g.close()


def test_rng_optimizer_retry_stays_bitwise():
    """A skipped step must not consume from the PRNG key stream: SGLD's
    retried batch has to draw the same noise the clean run drew."""
    ref_l, ref_p, _ = _run(optimizer_name="sgld")
    g = guardian.TrainingGuardian()
    try:
        got_l, got_p, actions = _run(guard=g, retry=True,
                                     optimizer_name="sgld",
                                     poison="grad.bucket:nan@3")
    finally:
        g.close()
    assert actions.count("skipped") == 1
    assert got_l == ref_l
    _assert_bitwise(got_p, ref_p, "param")


# ---------------------------------------------------------------------------
# clip_global_norm (the rebuilt satellite)
# ---------------------------------------------------------------------------


def test_global_norm_f16_does_not_saturate():
    """The norm reduction accumulates in f32: an f16 vdot saturates at
    65504 and would report inf for finite half-precision gradients —
    which the clipper would then 'fix' by zeroing them."""
    import jax.numpy as jnp
    from mxnet_tpu.guardian import health
    leaf = jnp.full((70000,), 1.0, jnp.float16)     # true norm ~264.6
    norm = float(np.asarray(health.global_norm([leaf])))
    assert np.isfinite(norm)
    assert norm == pytest.approx(np.sqrt(70000.0), rel=1e-3)

def test_clip_global_norm_single_program_and_nan_safe():
    from mxnet_tpu.gluon.utils import clip_global_norm
    arrs = [mx.nd.ones((2, 2)) * 10 for _ in range(2)]
    before = profiler.counter("xla_program_calls")
    norm = clip_global_norm(arrs, 1.0)
    assert profiler.counter("xla_program_calls") - before == 1
    assert norm == pytest.approx(np.sqrt(800.0), rel=1e-5)
    total = sum((a.asnumpy() ** 2).sum() for a in arrs)
    np.testing.assert_allclose(np.sqrt(total), 1.0, rtol=1e-4)
    # nonfinite gradients: arrays untouched, norm reports the sickness
    bad = [mx.nd.array(np.float32([np.nan, 1.0])), mx.nd.ones((2,))]
    norm = clip_global_norm(bad, 1.0)
    assert not np.isfinite(norm)
    assert np.isnan(bad[0].asnumpy()[0])
    np.testing.assert_array_equal(bad[1].asnumpy(), np.ones(2))


# ---------------------------------------------------------------------------
# introspection
# ---------------------------------------------------------------------------

def test_guardian_endpoint_and_http_view():
    import urllib.request
    from mxnet_tpu.telemetry import server as tserver
    view = guardian.http_view()
    assert view["active"] is False
    g = guardian.TrainingGuardian(loss_scale="dynamic")
    srv = tserver.IntrospectionServer(0).start()
    try:
        url = "http://127.0.0.1:%d/guardian" % srv.port
        payload = json.loads(urllib.request.urlopen(url).read())
        assert payload["active"] is True
        assert payload["loss_scale"] == 2.0 ** 16
        assert payload["max_skips"] >= 1
        assert "guardian_skipped_steps" in payload["counters"]
    finally:
        srv.stop()
        g.close()


def test_env_auto_install(monkeypatch):
    monkeypatch.setenv("MXNET_GUARDIAN", "1")
    assert guardian.refresh_from_env() is not None
    g = guardian.current()
    assert g is not None
    # disabling the env removes the auto-installed default...
    monkeypatch.setenv("MXNET_GUARDIAN", "0")
    guardian.refresh_from_env()
    assert guardian.current() is None
    # ...but never a programmatically constructed guardian
    mine = guardian.TrainingGuardian()
    guardian.refresh_from_env()
    assert guardian.current() is mine
    mine.close()
    assert guardian.current() is None


def test_rollback_without_pin_keeps_skipping(tmp_path):
    """No checkpoint was ever verified healthy: the rollback must NOT
    grab the newest (unverified) checkpoint — the run keeps skipping
    non-fatally."""
    chaos.configure("grad.bucket:nan@2-4")
    net, trainer, it = _iter_build()
    mgr = checkpoint.CheckpointManager(str(tmp_path), trainer=trainer,
                                       data_iter=it, num_shards=1)
    g = guardian.TrainingGuardian(manager=mgr, max_skips=1,
                                  spike_factor=0.0)
    loss_fn = gluon.loss.L2Loss()
    before = telemetry.counter("guardian_rollbacks")
    try:
        for i in range(5):              # step 1 clean, steps 2-4 poisoned
            batch = it.next()
            with autograd.record():
                loss = loss_fn(net(batch.data[0]), batch.label[0])
            loss.backward()
            trainer.step(8)
            if i == 0:
                # a committed but NEVER-pinned checkpoint (params are
                # materialized now); spike_factor=0 means pinning is off
                # too, so last_good stays None
                mgr.save(1, sync=True)
                mgr._pinned_step = None   # guard against pin leakage
    finally:
        g.close()
        mgr.close()
    assert telemetry.counter("guardian_rollbacks") == before
    assert g._last_rollback is None


# ---------------------------------------------------------------------------
# the tier-1 smoke (fast variant of tools/guardian_smoke.py)
# ---------------------------------------------------------------------------

def test_guardian_smoke_tier1():
    """Subprocess acceptance: transient NaN absorbed bitwise with exactly
    one skip; persistent NaN rolls back to last-good and recovers within
    the budget; per-step program calls unchanged."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "guardian_smoke.py"),
         "--steps", "8", "--window", "5-6", "--timeout", "150", "--json"],
        capture_output=True, text=True, timeout=500)
    assert out.returncode == 0, \
        "guardian_smoke failed:\n%s\n%s" % (out.stdout, out.stderr)
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    assert summary["ok"], summary
    assert summary["skipped"] == 1
    assert summary["rollbacks"] >= 1
    assert summary["calls_last_step"] == 1
