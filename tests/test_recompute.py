"""MXNET_BACKWARD_DO_MIRROR → jax.checkpoint rematerialisation
(ref src/executor/graph_executor.cc:281-304 mirror pass)."""
import os

import numpy as np
import jax
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn


def _fresh_mlp():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dense(8, activation="relu"))
    net.add(nn.Dense(4))
    net.collect_params().initialize()
    net.hybridize()
    return net


def _grad_jaxpr_of_block(net):
    """jaxpr of grad-of-sum through the block's cached pure function."""
    net(nd.zeros((2, 8)))          # builds the cache
    cached = net._cached_op
    pure = cached._jit[False].__wrapped__

    gvals = tuple(p._data._data for p in cached._grad_params)
    avals = tuple(p._data._data for p in cached._aux_params)
    x = jax.numpy.zeros((2, 8))
    key = jax.random.PRNGKey(0)

    def loss(gv):
        out, _ = pure(gv, avals, (x,), key)
        return sum(o.sum() for o in out)

    return str(jax.make_jaxpr(jax.grad(loss))(gvals))


def test_mirror_flag_inserts_remat(monkeypatch):
    monkeypatch.delenv("MXNET_BACKWARD_DO_MIRROR", raising=False)
    plain = _grad_jaxpr_of_block(_fresh_mlp())
    assert "remat" not in plain and "checkpoint" not in plain

    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    mirrored = _grad_jaxpr_of_block(_fresh_mlp())
    assert "remat" in mirrored or "checkpoint" in mirrored


def test_mirror_numerics_unchanged(monkeypatch):
    """Remat changes memory/compute, never values."""
    np.random.seed(0)
    x_np = np.random.randn(4, 8).astype(np.float32)

    grads = []
    for flag in ("0", "1"):
        monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", flag)
        np.random.seed(1)
        mx.random.seed(1)
        net = _fresh_mlp()
        x = nd.array(x_np)
        x.attach_grad()
        with mx.autograd.record():
            y = net(x)
            loss = (y * y).sum()
        loss.backward()
        grads.append(x.grad.asnumpy())
    np.testing.assert_allclose(grads[0], grads[1], rtol=1e-6)
