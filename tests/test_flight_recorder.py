"""Flight recorder (ISSUE 4 tentpole 1): always-on crash ring, crash
hooks, hang watchdog.

Acceptance contract: SIGTERMing (or excepthooking) a 3-step training
subprocess leaves ``flight_<pid>.json`` containing the event ring, the
telemetry snapshot, and every thread's Python stack; the hang watchdog
dumps when step-span exits stop.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import flight

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TRAIN_SNIPPET = """
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

net = nn.Sequential()
net.add(nn.Dense(4, activation="relu"))
net.add(nn.Dense(2))
net.initialize()
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1})
loss_fn = gluon.loss.L2Loss()
for _ in range(%(steps)d):
    x = mx.nd.array(np.ones((4, 3), np.float32))
    y = mx.nd.array(np.ones((4, 2), np.float32))
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(4)
"""


def _run_script(tmp_path, body, steps=3, extra_env=None, **popen):
    script = tmp_path / "job.py"
    script.write_text(_TRAIN_SNIPPET % {"steps": steps} + body)
    env = dict(os.environ, MXNET_TELEMETRY="1",
               MXNET_FLIGHT_DIR=str(tmp_path),
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.update(extra_env or {})
    return subprocess.Popen([sys.executable, str(script)],
                            cwd=str(tmp_path), env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, **popen)


def _load_flight(tmp_path, pid):
    path = tmp_path / ("flight_%d.json" % pid)
    assert path.exists(), "no flight file; dir: %s" % os.listdir(tmp_path)
    return json.loads(path.read_text())


def _assert_postmortem(dump, min_steps=3):
    """The three things the acceptance criteria name: ring events,
    snapshot, all-thread stacks."""
    kinds = {e["kind"] for e in dump["ring"]}
    assert "span" in kinds, kinds            # step spans made the ring
    assert "compile" in kinds, kinds         # watched-jit compile events
    assert dump["steps"] >= min_steps
    snap = dump["snapshot"]
    assert snap["counters"]["xla_program_calls"] > 0
    assert "gauges" in snap and "retraces" in snap
    stacks = dump["stacks"]
    assert stacks, "no thread stacks captured"
    assert any(k.startswith("MainThread") for k in stacks)
    for frames in stacks.values():           # each stack is a real trace
        assert frames and any("File" in ln for ln in frames)


# ---- crash hooks (subprocess) --------------------------------------------

def test_excepthook_dumps_flight_file(tmp_path):
    proc = _run_script(tmp_path, "raise RuntimeError('boom')\n")
    _, err = proc.communicate(timeout=120)
    assert proc.returncode != 0
    assert b"RuntimeError: boom" in err      # original traceback intact
    dump = _load_flight(tmp_path, proc.pid)
    assert dump["reason"] == "excepthook:RuntimeError"
    assert any(e["kind"] == "crash" and e["name"] == "RuntimeError"
               for e in dump["ring"])
    _assert_postmortem(dump)


def test_sigterm_dumps_flight_file(tmp_path):
    proc = _run_script(
        tmp_path,
        "import sys, time\n"
        "print('READY', flush=True)\n"
        "time.sleep(120)\n")
    try:
        assert proc.stdout.readline().strip() == b"READY"
        proc.send_signal(signal.SIGTERM)
        proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    # handler re-raises after dumping: exit status still says SIGTERM
    assert proc.returncode == -signal.SIGTERM
    dump = _load_flight(tmp_path, proc.pid)
    assert dump["reason"] == "signal:SIGTERM"
    assert any(e["kind"] == "signal" and e["name"] == "SIGTERM"
               for e in dump["ring"])
    _assert_postmortem(dump)


@pytest.mark.slow
def test_hang_watchdog_dumps_on_stall(tmp_path):
    proc = _run_script(
        tmp_path,
        "import time\n"
        "print('READY', flush=True)\n"
        "time.sleep(120)\n",                 # the 'hang'
        extra_env={"MXNET_HANG_DUMP_SECS": "1"})
    try:
        assert proc.stdout.readline().strip() == b"READY"
        path = tmp_path / ("flight_%d.json" % proc.pid)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not path.exists():
            time.sleep(0.25)
        assert path.exists(), "watchdog never dumped"
        # the file is atomically replaced, so a parse either sees the
        # full dump or (rarely) the previous full dump — never torn
        dump = json.loads(path.read_text())
        assert dump["reason"].startswith("hang:")
        assert any(e["kind"] == "hang" for e in dump["ring"])
        assert dump["last_step_age_s"] >= 1.0
    finally:
        proc.kill()
        proc.communicate(timeout=30)


# ---- ring behavior (in-process) ------------------------------------------

@pytest.fixture
def tel(monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    telemetry.refresh_from_env()
    telemetry.reset()
    yield telemetry
    telemetry.reset()
    monkeypatch.delenv("MXNET_TELEMETRY", raising=False)
    telemetry.refresh_from_env()


def test_ring_is_bounded_and_fifo(tel):
    old = flight.capacity()
    flight.configure(max_events=8)
    try:
        for i in range(20):
            flight.record("test", "ev%d" % i)
        evs = flight.events()
        assert len(evs) == 8
        assert evs[0]["name"] == "ev12" and evs[-1]["name"] == "ev19"
    finally:
        flight.configure(max_events=old)


def test_span_exits_feed_ring_and_progress_clock(tel):
    assert flight.step_count() == 0
    assert flight.last_step_age() is None
    with tel.span("unit_step", cat="step"):
        pass
    assert flight.step_count() == 1
    assert flight.last_step_age() < 10
    names = [(e["kind"], e["name"]) for e in flight.events()]
    assert ("span", "unit_step") in names


def test_progress_clock_ticks_with_telemetry_off():
    """The hang watchdog must see steps even when spans are inert."""
    telemetry.reset()
    telemetry.set_enabled(False)
    assert not telemetry.trace_active()
    with telemetry.span("off_step", cat="step"):
        pass
    assert flight.step_count() == 1
    assert any(e["name"] == "off_step" for e in flight.events())
    telemetry.reset()


def test_engine_pushes_land_in_ring(tel):
    from mxnet_tpu import engine
    eng = engine.engine()
    var = eng.new_variable()
    eng.push(lambda: None, mutable_vars=(var,))
    eng.wait_for_all()
    assert any(e["kind"] == "engine_push" for e in flight.events())


def test_manual_dump_roundtrip(tel, tmp_path):
    with tel.span("unit_step", cat="step"):
        pass
    path = telemetry.dump_flight("manual", directory=str(tmp_path))
    dump = json.loads(open(path).read())
    assert dump["reason"] == "manual"
    assert dump["pid"] == os.getpid()
    assert dump["ring"] and dump["stacks"] and dump["snapshot"]
    assert tel.counter("flight_dumps") == 1
