"""Transformer LM (models/) tests: sharded-vs-unsharded equivalence and
training sanity on the virtual 8-device mesh."""
import numpy as np
import jax
import jax.numpy as jnp

import mxnet_tpu  # noqa: F401  (configures platform via conftest)
from mxnet_tpu.parallel.mesh import make_mesh
from mxnet_tpu.models.transformer import (
    TransformerLMConfig, init_transformer_params, transformer_forward,
    make_train_step, place_batch)


def _data(cfg, b, s, seed=0):
    rng = np.random.RandomState(seed)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab, (b, s)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab, (b, s)), jnp.int32)
    return tokens, labels


def test_forward_sharded_matches_unsharded():
    cfg = TransformerLMConfig(vocab=32, d_model=16, n_heads=4, d_ff=32,
                              n_layers=2, max_len=16)
    params = init_transformer_params(jax.random.PRNGKey(0), cfg)
    tokens, _ = _data(cfg, 4, 16)
    ref = transformer_forward(params, tokens, cfg)  # single device

    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    out = jax.jit(lambda p, t: transformer_forward(p, t, cfg, mesh))(
        params, tokens)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-4), \
        np.abs(np.asarray(out) - np.asarray(ref)).max()


def test_train_step_loss_decreases():
    cfg = TransformerLMConfig(vocab=32, d_model=16, n_heads=4, d_ff=32,
                              n_layers=2, max_len=16)
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    params = init_transformer_params(jax.random.PRNGKey(0), cfg, mesh)
    tokens, labels = _data(cfg, 8, 16)
    tokens, labels = place_batch(tokens, labels, mesh)
    step = make_train_step(cfg, mesh, lr=0.5)
    losses = []
    for _ in range(20):
        params, loss = step(params, tokens, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::5]
    assert np.isfinite(losses[-1])


def test_zero1_step_matches_plain_sgd():
    """make_train_step_zero1 with momentum=0 is plain SGD with different
    placement: parameter trajectories must agree with make_train_step."""
    from mxnet_tpu.models.transformer import make_train_step_zero1
    cfg = TransformerLMConfig(vocab=32, d_model=16, n_heads=4, d_ff=32,
                              n_layers=2, max_len=16)
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    params_a = init_transformer_params(jax.random.PRNGKey(0), cfg, mesh)
    params_b = init_transformer_params(jax.random.PRNGKey(0), cfg, mesh)
    tokens, labels = _data(cfg, 8, 16)
    tokens, labels = place_batch(tokens, labels, mesh)

    plain = make_train_step(cfg, mesh, lr=0.3)
    zstep, momenta = make_train_step_zero1(cfg, mesh, params_b, lr=0.3,
                                           momentum=0.0)
    # some momentum buffer must actually be sharded over the DATA axis
    # (TP-sharded buffers don't count: that's inherited, not ZeRO-1)
    sharded = [m for m in jax.tree_util.tree_leaves(momenta)
               if "data" in tuple(getattr(m.sharding, "spec", ()) or ())]
    assert sharded, "no momentum buffer took the ZeRO-1 data sharding"

    for _ in range(3):
        params_a, loss_a = plain(params_a, tokens, labels)
        params_b, momenta, loss_b = zstep(params_b, momenta, tokens,
                                          labels)
    assert abs(float(loss_a) - float(loss_b)) < 1e-5
    for la, lb in zip(jax.tree_util.tree_leaves(params_a),
                      jax.tree_util.tree_leaves(params_b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=2e-4, atol=2e-4)
