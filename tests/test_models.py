"""Transformer LM (models/) tests: sharded-vs-unsharded equivalence and
training sanity on the virtual 8-device mesh."""
import numpy as np
import jax
import jax.numpy as jnp

import mxnet_tpu  # noqa: F401  (configures platform via conftest)
from mxnet_tpu.parallel.mesh import make_mesh
from mxnet_tpu.models.transformer import (
    TransformerLMConfig, init_transformer_params, transformer_forward,
    make_train_step, place_batch)


def _data(cfg, b, s, seed=0):
    rng = np.random.RandomState(seed)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab, (b, s)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab, (b, s)), jnp.int32)
    return tokens, labels


def test_forward_sharded_matches_unsharded():
    cfg = TransformerLMConfig(vocab=32, d_model=16, n_heads=4, d_ff=32,
                              n_layers=2, max_len=16)
    params = init_transformer_params(jax.random.PRNGKey(0), cfg)
    tokens, _ = _data(cfg, 4, 16)
    ref = transformer_forward(params, tokens, cfg)  # single device

    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    out = jax.jit(lambda p, t: transformer_forward(p, t, cfg, mesh))(
        params, tokens)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-4), \
        np.abs(np.asarray(out) - np.asarray(ref)).max()


def test_train_step_loss_decreases():
    cfg = TransformerLMConfig(vocab=32, d_model=16, n_heads=4, d_ff=32,
                              n_layers=2, max_len=16)
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    params = init_transformer_params(jax.random.PRNGKey(0), cfg, mesh)
    tokens, labels = _data(cfg, 8, 16)
    tokens, labels = place_batch(tokens, labels, mesh)
    step = make_train_step(cfg, mesh, lr=0.5)
    losses = []
    for _ in range(20):
        params, loss = step(params, tokens, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::5]
    assert np.isfinite(losses[-1])
