"""User-facing Pallas kernel registration (VERDICT r3 #5; RTC parity —
reference python/mxnet/rtc.py + src/common/rtc.cc:32-80)."""
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu import symbol as S


@pytest.fixture
def _cleanup():
    before = set(mx.pallas.registered_kernels())
    yield
    for name in list(mx.pallas.registered_kernels()):
        if name not in before:
            mx.pallas.unregister(name)


def _scale_body(x_ref, o_ref, *, alpha):
    o_ref[...] = x_ref[...] * alpha


def _register_scale(name="pl_scale", **kw):
    from jax.experimental import pallas as pl

    def pl_scale(x, alpha=2.0, interpret=False):
        return pl.pallas_call(
            functools.partial(_scale_body, alpha=float(alpha)),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=bool(interpret))(x)

    return mx.pallas.register(
        name, pl_scale,
        grad=lambda og, ins, outs, attrs:
        (og[0] * float(attrs.get("alpha", 2.0)),), **kw)


def test_eager_and_symbolic_invocation(_cleanup):
    fn = _register_scale()
    x = nd.array(np.arange(6.0).reshape(2, 3))
    np.testing.assert_allclose(fn(x, alpha=3.0).asnumpy(),
                               x.asnumpy() * 3.0)
    # exposed on the nd namespace like a built-in
    np.testing.assert_allclose(nd.pl_scale(x, alpha=3.0).asnumpy(),
                               x.asnumpy() * 3.0)
    # symbolic: bind + forward
    s = S.pl_scale(S.Variable("d"), alpha=4.0)
    ex = s.simple_bind(mx.cpu(), grad_req="write", d=(2, 3))
    ex.arg_dict["d"][:] = x.asnumpy()
    out = ex.forward()[0]
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy() * 4.0)


def test_semantic_grad_through_executor(_cleanup):
    _register_scale()
    s = S.sum(S.pl_scale(S.Variable("d"), alpha=5.0))
    ex = s.simple_bind(mx.cpu(), grad_req="write", d=(2, 3))
    ex.arg_dict["d"][:] = 1.0
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["d"].asnumpy(),
                               np.full((2, 3), 5.0))


def test_autograd_through_pure_jax_kernel(_cleanup):
    # a pure-JAX body needs no grad=: jax.vjp differentiates it
    mx.pallas.register("pl_cube", lambda x: x ** 3)
    x = nd.array(np.array([1.0, 2.0]))
    x.attach_grad()
    with mx.autograd.record():
        y = nd.pl_cube(x)
    y.backward(nd.array(np.ones(2)))
    np.testing.assert_allclose(x.grad.asnumpy(), 3 * x.asnumpy() ** 2)


def test_training_through_registered_kernel(_cleanup):
    """Train a tiny Module whose graph routes through the user kernel."""
    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.module import Module
    _register_scale()
    net = S.FullyConnected(S.Variable("data"), num_hidden=4, name="fc_a")
    net = S.pl_scale(net, alpha=0.5)
    net = S.FullyConnected(net, num_hidden=2, name="fc_b")
    net = S.SoftmaxOutput(net, S.Variable("softmax_label"), name="softmax")

    rng = np.random.RandomState(0)
    X = rng.randn(16, 3).astype(np.float32)
    Y = (X.sum(axis=1) > 0).astype(np.float32)
    it = NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")
    mod = Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.5),))
    w0 = mod._exec_group.execs[0].arg_dict["fc_a_weight"].asnumpy().copy()
    mod.fit(it, num_epoch=3)
    w1 = mod._exec_group.execs[0].arg_dict["fc_a_weight"].asnumpy()
    assert np.abs(w1 - w0).max() > 0, "no learning through the kernel"


def test_duplicate_name_rejected(_cleanup):
    _register_scale()
    with pytest.raises(mx.MXNetError):
        _register_scale()
    _register_scale(force=True)  # explicit replacement allowed
    assert mx.pallas.registered_kernels().count("pl_scale") == 1


def test_unregister_removes_wrappers(_cleanup):
    _register_scale("pl_gone")
    assert hasattr(nd, "pl_gone") and hasattr(S, "pl_gone")
    mx.pallas.unregister("pl_gone")
    assert not hasattr(nd, "pl_gone")
    assert not hasattr(S, "pl_gone")
    with pytest.raises(mx.MXNetError):
        mx.pallas.unregister("pl_gone")


def test_builtin_protected_from_unregister():
    with pytest.raises(mx.MXNetError):
        mx.pallas.unregister("Convolution")


def test_force_over_builtin_restored_on_unregister():
    """force=True over a built-in must stash the original op and restore
    it (registry + nd/sym wrappers) on unregister — r4 advice: deleting
    the built-in left the framework without a core operator."""
    from mxnet_tpu.ops.registry import OP_REGISTRY
    original = OP_REGISTRY["relu"]
    x = nd.array(np.array([-1.0, 2.0], np.float32))

    def fake_relu(a):
        return a * 0.0 + 7.0

    try:
        mx.pallas.register("relu", fake_relu, force=True)
        assert np.allclose(nd.relu(x).asnumpy(), 7.0)
    finally:
        mx.pallas.unregister("relu")
    assert OP_REGISTRY["relu"] is original
    assert np.allclose(nd.relu(x).asnumpy(), [0.0, 2.0])
    # double force-register then unregister still restores the ORIGINAL
    try:
        mx.pallas.register("relu", fake_relu, force=True)
        mx.pallas.register("relu", fake_relu, force=True)
    finally:
        mx.pallas.unregister("relu")
    assert OP_REGISTRY["relu"] is original
