"""Symbol + executor C API: a compiled C program loads a -symbol.json /
.params pair, binds, runs inference AND SGD training steps end-to-end.

Reference analogue: the MXSymbol* (29 fns) and MXExecutor* (11 fns)
groups of include/mxnet/c_api.h:837-1408, exercised the way the
reference's cpp-package drivers do (closes VERDICT r4 Missing #3 /
Next #5: "a C driver that binds and steps LeNet end-to-end").
"""
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SO = os.path.join(REPO, "mxnet_tpu", "_native", "libmxnet_c.so")

pytestmark = pytest.mark.skipif(not os.path.exists(SO),
                                reason="libmxnet_c.so not built")

DRIVER_C = r"""
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "mxnet_tpu_c.h"

#define CHECK(x) do { if ((x) != 0) { \
  fprintf(stderr, "FAIL %s: %s\n", #x, MXGetLastError()); return 1; } \
} while (0)

#define BATCH 8
#define NCLASS 4

/* mean NLL of softmax outputs vs labels */
static float mean_nll(ExecutorHandle exec, const float* labels) {
  mx_uint n_out = 0;
  NDArrayHandle* outs = NULL;
  if (MXExecutorOutputs(exec, &n_out, &outs) != 0) return -1.0f;
  float probs[BATCH * NCLASS];
  if (MXNDArraySyncCopyToCPU(outs[0], probs, BATCH * NCLASS) != 0)
    return -1.0f;
  float nll = 0.0f;
  for (int i = 0; i < BATCH; ++i)
    nll += -logf(probs[i * NCLASS + (int)labels[i]] + 1e-8f);
  for (mx_uint i = 0; i < n_out; ++i) MXNDArrayFree(outs[i]);
  free(outs);
  return nll / BATCH;
}

int main(int argc, char** argv) {
  const char* sym_file = argv[1];
  const char* param_file = argv[2];
  const char* data_file = argv[3];

  /* ---- load symbol, inspect it ---- */
  SymbolHandle net;
  CHECK(MXSymbolCreateFromFile(sym_file, &net));
  mx_uint n_args = 0;
  const char** arg_names = NULL;
  CHECK(MXSymbolListArguments(net, &n_args, &arg_names));
  if (n_args < 6) { fprintf(stderr, "n_args=%u\n", n_args); return 1; }
  mx_uint n_outs = 0;
  const char** out_names = NULL;
  CHECK(MXSymbolListOutputs(net, &n_outs, &out_names));
  if (n_outs != 1) return 1;

  /* ---- shape inference from the data shape alone ---- */
  const char* keys[1] = {"data"};
  mx_uint ind[2] = {0, 4};
  mx_uint dims[4] = {BATCH, 1, 16, 16};
  mx_uint in_sz, out_sz, aux_sz;
  const mx_uint *in_nd, *out_nd, *aux_nd;
  const mx_uint **in_dt, **out_dt, **aux_dt;
  int complete = 0;
  CHECK(MXSymbolInferShape(net, 1, keys, ind, dims, &in_sz, &in_nd,
                           &in_dt, &out_sz, &out_nd, &out_dt, &aux_sz,
                           &aux_nd, &aux_dt, &complete));
  if (out_sz != 1 || out_nd[0] != 2 || out_dt[0][0] != BATCH ||
      out_dt[0][1] != NCLASS) {
    fprintf(stderr, "bad inferred output shape\n");
    return 1;
  }

  /* ---- bind ---- */
  const char* bkeys[2] = {"data", "softmax_label"};
  mx_uint bndims[2] = {4, 1};
  mx_uint bdims[5] = {BATCH, 1, 16, 16, BATCH};
  ExecutorHandle exec;
  CHECK(MXExecutorSimpleBind(net, 1, 0, 2, bkeys, bndims, bdims,
                             "write", &exec));

  /* ---- load checkpoint params into the executor ---- */
  mx_uint n_loaded = 0, n_names = 0;
  NDArrayHandle* loaded = NULL;
  const char** names = NULL;
  CHECK(MXNDArrayLoad(param_file, &n_loaded, &loaded, &n_names, &names));
  CHECK(MXExecutorCopyParamsFrom(exec, n_loaded, names, loaded));

  /* ---- feed the stored batch ---- */
  mx_uint n_d = 0, n_dn = 0;
  NDArrayHandle* dat = NULL;
  const char** dnames = NULL;
  CHECK(MXNDArrayLoad(data_file, &n_d, &dat, &n_dn, &dnames));
  float xbuf[BATCH * 256], ybuf[BATCH];
  for (mx_uint i = 0; i < n_d; ++i) {
    if (strcmp(dnames[i], "x") == 0)
      CHECK(MXNDArraySyncCopyToCPU(dat[i], xbuf, BATCH * 256));
    else
      CHECK(MXNDArraySyncCopyToCPU(dat[i], ybuf, BATCH));
  }
  NDArrayHandle d_arg, l_arg;
  CHECK(MXExecutorArgArray(exec, "data", &d_arg));
  CHECK(MXExecutorArgArray(exec, "softmax_label", &l_arg));
  CHECK(MXNDArraySyncCopyFromCPU(d_arg, xbuf, BATCH * 256));
  CHECK(MXNDArraySyncCopyFromCPU(l_arg, ybuf, BATCH));

  /* ---- inference: rows are probability distributions ---- */
  CHECK(MXExecutorForward(exec, 0));
  mx_uint n_out = 0;
  NDArrayHandle* outs = NULL;
  CHECK(MXExecutorOutputs(exec, &n_out, &outs));
  float probs[BATCH * NCLASS];
  CHECK(MXNDArraySyncCopyToCPU(outs[0], probs, BATCH * NCLASS));
  for (int i = 0; i < BATCH; ++i) {
    float s = 0;
    for (int c = 0; c < NCLASS; ++c) s += probs[i * NCLASS + c];
    if (fabsf(s - 1.0f) > 1e-3f) {
      fprintf(stderr, "row %d sums to %f\n", i, s);
      return 1;
    }
  }
  for (mx_uint i = 0; i < n_out; ++i) MXNDArrayFree(outs[i]);
  free(outs);

  /* ---- training: fwd/bwd + sgd_update on every grad-bearing arg ---- */
  float nll0 = -1.0f, nll1 = -1.0f;
  const char* ukeys[1] = {"lr"};
  const char* uvals[1] = {"0.05"};
  for (int step = 0; step < 12; ++step) {
    CHECK(MXExecutorForward(exec, 1));
    if (step == 0) nll0 = mean_nll(exec, ybuf);
    CHECK(MXExecutorBackward(exec, 0, NULL));
    for (mx_uint i = 0; i < n_args; ++i) {
      if (strcmp(arg_names[i], "data") == 0 ||
          strcmp(arg_names[i], "softmax_label") == 0)
        continue;
      NDArrayHandle w, g;
      CHECK(MXExecutorArgArray(exec, arg_names[i], &w));
      CHECK(MXExecutorGradArray(exec, arg_names[i], &g));
      NDArrayHandle ins[2]; ins[0] = w; ins[1] = g;
      int one = 1;
      NDArrayHandle out_arr[1]; out_arr[0] = w;
      NDArrayHandle* outp = out_arr;
      CHECK(MXImperativeInvoke("sgd_update", 2, ins, &one, &outp,
                               1, ukeys, uvals));
      MXNDArrayFree(w);
      MXNDArrayFree(g);
    }
  }
  CHECK(MXExecutorForward(exec, 1));
  nll1 = mean_nll(exec, ybuf);
  printf("nll %f -> %f\n", nll0, nll1);
  if (!(nll1 < nll0 * 0.8f)) {
    fprintf(stderr, "no learning: %f -> %f\n", nll0, nll1);
    return 1;
  }

  /* ---- compose a graph natively: relu(data) via atomic+compose ---- */
  SymbolHandle v, act;
  CHECK(MXSymbolCreateVariable("x", &v));
  const char* akeys[1] = {"act_type"};
  const char* avals[1] = {"relu"};
  CHECK(MXSymbolCreateAtomicSymbol("Activation", 1, akeys, avals, &act));
  CHECK(MXSymbolCompose(act, "act0", 1, NULL, &v));
  const char* json = NULL;
  CHECK(MXSymbolSaveToJSON(act, &json));
  if (strstr(json, "Activation") == NULL) return 1;

  MXSymbolFree(v);
  MXSymbolFree(act);
  MXSymbolFree(net);
  MXExecutorFree(exec);
  printf("C-SYMBOL-EXEC-OK\n");
  return 0;
}
"""


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """LeNet symbol + trained-ish params + a data batch, saved to disk."""
    tmp = tmp_path_factory.mktemp("capi_lenet")
    import mxnet_tpu as mx

    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                             name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=16,
                             name="conv2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                               name="softmax")
    sym_file = str(tmp / "lenet-symbol.json")
    net.save(sym_file)

    rng = np.random.RandomState(0)
    x = rng.rand(8, 1, 16, 16).astype(np.float32)
    y = rng.randint(0, 4, (8,)).astype(np.float32)

    ex = net.simple_bind(mx.cpu(), data=(8, 1, 16, 16),
                         softmax_label=(8,))
    init = mx.initializer.Xavier()
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "softmax_label"):
            init(mx.initializer.InitDesc(name), arr)
    params = {"arg:" + n: a for n, a in ex.arg_dict.items()
              if n not in ("data", "softmax_label")}
    params.update({"aux:" + n: a for n, a in ex.aux_dict.items()})
    param_file = str(tmp / "lenet.params")
    mx.nd.save(param_file, params)

    data_file = str(tmp / "batch.params")
    mx.nd.save(data_file, {"x": mx.nd.array(x), "y": mx.nd.array(y)})
    return sym_file, param_file, data_file


def test_c_driver_lenet_train(artifacts, tmp_path):
    sym_file, param_file, data_file = artifacts
    driver = tmp_path / "lenet_driver.c"
    driver.write_text(DRIVER_C)
    exe = tmp_path / "lenet_driver"
    subprocess.run(
        ["gcc", str(driver), "-I", os.path.join(REPO, "native", "include"),
         "-o", str(exe), str(SO), "-lm",
         "-Wl,-rpath," + os.path.dirname(SO)],
        check=True, capture_output=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    out = subprocess.run([str(exe), sym_file, param_file, data_file],
                         capture_output=True, text=True, timeout=600,
                         env=env)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "C-SYMBOL-EXEC-OK" in out.stdout


def test_kvstore_c_surface():
    """MXKVStore* string-key group: create/init/push/pull/rank through
    ctypes (ref c_api.h MXKVStore* group)."""
    import ctypes
    import mxnet_tpu  # noqa: F401
    lib = ctypes.CDLL(SO)
    lib.MXGetLastError.restype = ctypes.c_char_p
    lib.MXKVStoreGetType.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_char_p)]
    lib.MXNDArraySyncCopyFromCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.MXNDArraySyncCopyToCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.MXNDArrayFree.argtypes = [ctypes.c_void_p]
    lib.MXKVStoreFree.argtypes = [ctypes.c_void_p]

    kv = ctypes.c_void_p()
    assert lib.MXKVStoreCreate(b"local", ctypes.byref(kv)) == 0, \
        lib.MXGetLastError()
    t = ctypes.c_char_p()
    assert lib.MXKVStoreGetType(kv, ctypes.byref(t)) == 0
    assert t.value == b"local"
    rank, size = ctypes.c_int(-1), ctypes.c_int(-1)
    assert lib.MXKVStoreGetRank(kv, ctypes.byref(rank)) == 0
    assert lib.MXKVStoreGetGroupSize(kv, ctypes.byref(size)) == 0
    assert rank.value == 0 and size.value == 1

    shape = (ctypes.c_uint * 1)(4)
    val, grad, out = (ctypes.c_void_p() for _ in range(3))
    for h in (val, grad, out):
        assert lib.MXNDArrayCreateEx(shape, 1, 1, 0, 0, 0,
                                     ctypes.byref(h)) == 0
    buf = (ctypes.c_float * 4)(1.0, 2.0, 3.0, 4.0)
    assert lib.MXNDArraySyncCopyFromCPU(val, buf, 4) == 0
    gbuf = (ctypes.c_float * 4)(0.5, 0.5, 0.5, 0.5)
    assert lib.MXNDArraySyncCopyFromCPU(grad, gbuf, 4) == 0

    keys = (ctypes.c_char_p * 1)(b"w0")
    vals = (ctypes.c_void_p * 1)(val.value)
    assert lib.MXKVStoreInitEx(kv, 1, keys, vals) == 0, \
        lib.MXGetLastError()
    grads = (ctypes.c_void_p * 1)(grad.value)
    assert lib.MXKVStorePushEx(kv, 1, keys, grads, 0) == 0, \
        lib.MXGetLastError()
    outs = (ctypes.c_void_p * 1)(out.value)
    assert lib.MXKVStorePullEx(kv, 1, keys, outs, 0) == 0, \
        lib.MXGetLastError()
    got = (ctypes.c_float * 4)()
    assert lib.MXNDArraySyncCopyToCPU(out, got, 4) == 0
    # local kvstore without an optimizer: pull returns the pushed sum
    np.testing.assert_allclose(list(got), [0.5] * 4, rtol=1e-6)
    assert lib.MXKVStoreBarrier(kv) == 0
    for h in (val, grad, out):
        lib.MXNDArrayFree(h)
    lib.MXKVStoreFree(kv)


def test_data_iter_c_surface(tmp_path):
    """MXDataIter* group: param-string CSVIter creation + cursor
    protocol from ctypes (ref c_api.h:1420-1500)."""
    import ctypes
    import mxnet_tpu  # noqa: F401
    csv = tmp_path / "d.csv"
    rows = np.arange(24, dtype=np.float32).reshape(8, 3)
    np.savetxt(csv, rows, delimiter=",", fmt="%.1f")

    lib = ctypes.CDLL(SO)
    lib.MXGetLastError.restype = ctypes.c_char_p
    lib.MXNDArraySyncCopyToCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.MXNDArrayFree.argtypes = [ctypes.c_void_p]
    lib.MXDataIterFree.argtypes = [ctypes.c_void_p]
    lib.MXDataIterBeforeFirst.argtypes = [ctypes.c_void_p]
    lib.MXDataIterNext.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_int)]
    lib.MXDataIterGetData.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_void_p)]

    n = ctypes.c_uint()
    arr = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXListDataIters(ctypes.byref(n), ctypes.byref(arr)) == 0
    names = [arr[i].decode() for i in range(n.value)]
    assert "CSVIter" in names and "ImageRecordIter" in names

    keys = (ctypes.c_char_p * 3)(b"data_csv", b"data_shape", b"batch_size")
    vals = (ctypes.c_char_p * 3)(str(csv).encode(), b"(3,)", b"4")
    it = ctypes.c_void_p()
    assert lib.MXDataIterCreateIter(b"CSVIter", 3, keys, vals,
                                    ctypes.byref(it)) == 0, \
        lib.MXGetLastError()
    assert lib.MXDataIterBeforeFirst(it) == 0
    seen = 0
    has = ctypes.c_int(0)
    while True:
        assert lib.MXDataIterNext(it, ctypes.byref(has)) == 0
        if not has.value:
            break
        d = ctypes.c_void_p()
        assert lib.MXDataIterGetData(it, ctypes.byref(d)) == 0, \
            lib.MXGetLastError()
        buf = (ctypes.c_float * 12)()
        assert lib.MXNDArraySyncCopyToCPU(d, buf, 12) == 0
        if seen == 0:
            np.testing.assert_allclose(list(buf)[:3], [0.0, 1.0, 2.0])
        lib.MXNDArrayFree(d)
        seen += 1
    assert seen == 2        # 8 rows / batch 4
    lib.MXDataIterFree(it)


def test_autograd_c_surface():
    """MXAutograd* group: record scope + mark_variables + BackwardEx
    from ctypes computes d(x^2)/dx = 2x into the marked grad handle
    (ref c_api.h:702-778)."""
    import ctypes
    import mxnet_tpu  # noqa: F401
    lib = ctypes.CDLL(SO)
    lib.MXGetLastError.restype = ctypes.c_char_p
    lib.MXNDArraySyncCopyFromCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.MXNDArraySyncCopyToCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.MXNDArrayFree.argtypes = [ctypes.c_void_p]

    shape = (ctypes.c_uint * 1)(3)
    x, g = ctypes.c_void_p(), ctypes.c_void_p()
    assert lib.MXNDArrayCreateEx(shape, 1, 1, 0, 0, 0,
                                 ctypes.byref(x)) == 0
    assert lib.MXNDArrayCreateEx(shape, 1, 1, 0, 0, 0,
                                 ctypes.byref(g)) == 0
    buf = (ctypes.c_float * 3)(1.0, 2.0, 3.0)
    assert lib.MXNDArraySyncCopyFromCPU(x, buf, 3) == 0

    reqs = (ctypes.c_uint * 1)(1)                    # write
    xs = (ctypes.c_void_p * 1)(x.value)
    gs = (ctypes.c_void_p * 1)(g.value)
    assert lib.MXAutogradMarkVariables(1, xs, reqs, gs) == 0, \
        lib.MXGetLastError()

    prev = ctypes.c_int(-1)
    assert lib.MXAutogradSetIsRecording(1, ctypes.byref(prev)) == 0
    assert prev.value == 0
    cur = ctypes.c_int(-1)
    assert lib.MXAutogradIsRecording(ctypes.byref(cur)) == 0
    assert cur.value == 1

    ins = (ctypes.c_void_p * 2)(x.value, x.value)
    n_out = ctypes.c_int(0)
    outs = ctypes.POINTER(ctypes.c_void_p)()
    assert lib.MXImperativeInvoke(b"elemwise_mul", 2, ins,
                                  ctypes.byref(n_out), ctypes.byref(outs),
                                  0, None, None) == 0, lib.MXGetLastError()
    assert lib.MXAutogradSetIsRecording(0, ctypes.byref(prev)) == 0

    oh = (ctypes.c_void_p * 1)(outs[0])
    assert lib.MXAutogradBackwardEx(1, oh, None, 0, 1) == 0, \
        lib.MXGetLastError()
    got = (ctypes.c_float * 3)()
    assert lib.MXNDArraySyncCopyToCPU(g, got, 3) == 0
    np.testing.assert_allclose(list(got), [2.0, 4.0, 6.0], rtol=1e-6)

    # a NULL slot in ograd_handles = ones_like default (ref contract);
    # must not crash and must produce the same gradient
    assert lib.MXAutogradSetIsRecording(1, ctypes.byref(prev)) == 0
    n2 = ctypes.c_int(0)
    outs2 = ctypes.POINTER(ctypes.c_void_p)()
    assert lib.MXImperativeInvoke(b"elemwise_mul", 2, ins,
                                  ctypes.byref(n2), ctypes.byref(outs2),
                                  0, None, None) == 0
    assert lib.MXAutogradSetIsRecording(0, ctypes.byref(prev)) == 0
    oh2 = (ctypes.c_void_p * 1)(outs2[0])
    null_ogs = (ctypes.c_void_p * 1)(None)
    assert lib.MXAutogradBackwardEx(1, oh2, null_ogs, 0, 1) == 0, \
        lib.MXGetLastError()
    assert lib.MXNDArraySyncCopyToCPU(g, got, 3) == 0
    np.testing.assert_allclose(list(got), [2.0, 4.0, 6.0], rtol=1e-6)
    lib.MXNDArrayFree(outs2[0])

    lib.MXNDArrayFree(x)
    lib.MXNDArrayFree(g)
    lib.MXNDArrayFree(outs[0])
