"""Tier-1 gate: the repo's own code stays graftlint-clean.

Runs the analyzer in-process over ``mxnet_tpu/``, ``tools/``, and
``examples/`` against the checked-in ``LINT_BASELINE.json`` and fails on
any NON-baselined finding — new code is held to zero TPU footguns while
the legacy entries (JG005 in test_utils/image augmenters/example mains,
JG002 in standalone tool scripts) stay visible-but-tolerated.  Also fails
on stale baseline entries, so the baseline only ever shrinks
(stale-suppression rot is the quiet way these systems die).

Fast by construction: pure-ast scan, no jax work beyond the package import
the test session already paid for.
"""
import os

from mxnet_tpu.lint import (default_baseline_path, lint_paths,
                            load_baseline, repo_root)

REPO = repo_root()
SCAN_ROOTS = [os.path.join(REPO, d)
              for d in ("mxnet_tpu", "tools", "examples")]


def _scan():
    findings = lint_paths(SCAN_ROOTS, rel_root=REPO)
    baseline = load_baseline(default_baseline_path())
    return baseline, baseline.apply(findings)


def test_repo_is_lint_clean():
    _, (new, _matched, _stale) = _scan()
    assert not new, (
        "new graftlint findings (fix them, or suppress with a justified "
        "'# graftlint: disable=JG00x' — do NOT grow the baseline):\n"
        + "\n".join(f.format_text() for f in new))


def test_baseline_has_no_stale_entries():
    # the FILE must exist (CI without it would silently judge nothing);
    # an empty entry list is the goal state and is fine
    assert os.path.exists(default_baseline_path()), \
        "LINT_BASELINE.json missing — regenerate with --write-baseline"
    baseline, (_new, _matched, stale) = _scan()
    assert not stale, (
        "stale LINT_BASELINE.json entries no longer fire — remove them "
        "(tools/graftlint.py --write-baseline):\n"
        + "\n".join("%s %s (x%d): %s" % (r, p, n, s)
                    for (r, p, s), n in sorted(stale.items())))


def test_no_naked_jit_in_mxnet_tpu():
    """ISSUE 3 satellite: JG002 burn-down — every owned jax.jit entry
    point is wrapped in telemetry.watch_jit, with nothing baselined."""
    findings = lint_paths([os.path.join(REPO, "mxnet_tpu")],
                          select={"JG002"}, rel_root=REPO)
    assert not findings, (
        "naked jax.jit sites (wrap in telemetry.watch_jit):\n"
        + "\n".join(f.format_text() for f in findings))


def test_jg002_baseline_fully_burned_down():
    """ISSUE 18 satellite: the standalone tools/examples JG002 debt is
    paid — zero JG002 entries remain in LINT_BASELINE.json and the scan
    roots produce none outside justified inline suppressions.  The
    baseline only ever shrinks; this pins the shrink."""
    import json
    with open(default_baseline_path()) as f:
        entries = json.load(f)["entries"]
    burned = [e for e in entries if e["rule"] == "JG002"]
    assert burned == [], (
        "JG002 re-entered the baseline (wrap the jit in watch_jit "
        "instead): %s" % [e["path"] for e in burned])
    findings = lint_paths(SCAN_ROOTS, select={"JG002"}, rel_root=REPO)
    assert not findings, (
        "un-suppressed naked jax.jit sites:\n"
        + "\n".join(f.format_text() for f in findings))


def test_legacy_baseline_shrunk_to_image_tier():
    """ISSUE 20 satellite: the tools/ and examples/ legacy debt is paid
    (np.random module-state seeds/draws -> mx.random, env read in the
    diagnose loop -> one snapshot).  What remains baselined is the
    mxnet_tpu/image augmenter tier only, and no more than the 25
    findings recorded at the burn-down — the baseline only ever
    shrinks; this pins both the count and the blast radius."""
    import json
    with open(default_baseline_path()) as f:
        entries = json.load(f)["entries"]
    stray = [e for e in entries
             if not e["path"].startswith("mxnet_tpu/image/")]
    assert stray == [], (
        "baseline grew outside mxnet_tpu/image/ (fix the finding or "
        "suppress inline with justification): %s"
        % [(e["rule"], e["path"]) for e in stray])
    total = sum(e["count"] for e in entries)
    assert total <= 25, (
        "legacy baseline grew to %d findings (was 25 after the ISSUE 20 "
        "burn-down) — the baseline only ever shrinks" % total)
