"""Tier-1 opprof gate: the committed perf ledger is fresh, and the
budget gate actually bites.

Mirrors ``test_memcheck_clean.py`` for the round-20 perf ledger.  One
module-scoped sweep (AOT-compile + measure all owned programs on the
pinned 8-device CPU mesh — seconds, once):

* PERF_BASELINE.json is fresh: present, topology-matched, every owned
  program budgeted under its committed digest, nothing stale, and the
  candidate ranking still names >= 2 concrete kernel targets;
* ``trace_report.py --ops --gate-perf`` exits 0 on the real artifact and
  3 on a deliberately shrunk budget re-gated through the REAL
  ``check_perf`` comparison — the CI wire, not just the library.

Measured medians on a shared CI host are noisy; the committed tolerance
(+150% of budget, 500us floor) is deliberately wide so this test gates
digests-and-order-of-magnitude, not microseconds.
"""
import json
import os
import subprocess
import sys

import pytest

from mxnet_tpu.telemetry import costs, opprof

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACE_REPORT = os.path.join(REPO, "tools", "trace_report.py")

MIN_PROGRAMS = 32            # same ledger floor as test_memcheck_clean
MIN_CANDIDATES = 2           # the ISSUE's "name >= 2 kernel targets"


@pytest.fixture(scope="module")
def sweep():
    programs, problems = opprof.sweep()
    assert problems == [], "sweep problems: %s" % problems
    return programs


@pytest.fixture(scope="module")
def artifact(sweep):
    perf = opprof.check_perf(sweep, opprof.load_perf_baseline())
    return opprof.build_report(sweep, [], perf, costs.peaks())


def gate(report, tmp_path, extra=()):
    path = tmp_path / "ops.json"
    path.write_text(json.dumps(report))
    proc = subprocess.run(
        [sys.executable, TRACE_REPORT, "--ops", str(path),
         "--gate-perf", *extra],
        capture_output=True, text=True)
    return proc.returncode, proc.stdout, proc.stderr


def test_perf_budgets_are_fresh(artifact):
    perf = artifact["perf"]
    assert perf["baseline_present"], \
        "PERF_BASELINE.json missing — run opprof --write-perf-baseline"
    assert perf["topology_match"], (
        "baseline captured on %s devices, test mesh has %s"
        % (perf["baseline_n_devices"], perf["n_devices"]))
    assert perf["stale_budgets"] == []
    bad = [p["name"] for p in perf["programs"] if p["unbudgeted"]]
    assert bad == [], (
        "unbudgeted programs (trace digest moved without refreshing the "
        "ledger — rerun opprof --write-perf-baseline): %s" % bad)
    assert len(perf["programs"]) >= MIN_PROGRAMS


def test_all_owned_programs_measured(sweep):
    assert len(sweep) >= MIN_PROGRAMS
    unmeasured = [n for n, p in sweep.items() if not p["measured"]]
    assert unmeasured == [], "programs that did not execute: %s" \
        % unmeasured


def test_candidates_named_with_ceilings(artifact):
    cands = artifact["candidates"]
    assert len(cands) >= MIN_CANDIDATES
    kinds = {c["kind"] for c in cands}
    assert kinds == {"compute", "comm"}, (
        "candidate list must span both roofline regimes, got %s" % kinds)
    for c in cands:
        assert c["program"] and c["unit"]
        assert c["ceiling"] > 0 and c["ceiling_kind"] in (
            "flops_per_s", "bytes_per_s")


def test_gate_perf_passes_on_real_artifact(artifact, tmp_path):
    rc, out, err = gate(artifact, tmp_path)
    assert rc == 0, "gate-perf failed on fresh sweep:\n%s%s" % (out, err)
    assert "gate-perf: ok" in out


def test_gate_perf_exits_3_on_shrunk_budget(sweep, tmp_path):
    """The injected regression: shrink the slowest program's committed
    budget twentyfold and re-run the REAL comparison (check_perf, not a
    doctored flag) — the gate must exit 3 and name the program."""
    baseline = opprof.load_perf_baseline()
    victim = max(baseline["programs"],
                 key=lambda n: baseline["programs"][n]["median_us"])
    doctored = json.loads(json.dumps(baseline))
    doctored["programs"][victim]["median_us"] /= 20.0
    perf = opprof.check_perf(sweep, doctored)
    report = opprof.build_report(sweep, [], perf, costs.peaks())
    assert any(p["over_budget"] for p in perf["programs"]
               if p["name"] == victim)
    rc, _out, err = gate(report, tmp_path)
    assert rc == 3
    assert "gate-perf: FAIL" in err and victim in err


def test_gate_perf_exits_3_on_unbudgeted(artifact, tmp_path):
    doctored = json.loads(json.dumps(artifact))
    doctored["perf"]["programs"][0]["unbudgeted"] = True
    rc, _out, err = gate(doctored, tmp_path)
    assert rc == 3 and "unbudgeted" in err


def test_gate_perf_exits_4_when_unmeasurable(artifact, tmp_path):
    doctored = json.loads(json.dumps(artifact))
    doctored["perf"]["topology_match"] = False
    rc, _out, err = gate(doctored, tmp_path)
    assert rc == 4 and "UNMEASURABLE" in err


def test_gate_perf_requires_ops_json():
    proc = subprocess.run(
        [sys.executable, TRACE_REPORT, "--gate-perf"],
        capture_output=True, text=True)
    assert proc.returncode == 2


def test_combined_gates_report_every_gate(artifact, tmp_path):
    """Regression for the silent-degradation bug: when perf and memory
    gates are requested together, BOTH verdict lines print and the exit
    code is the worst of the two — a failing second gate can no longer
    hide behind a passing first one."""
    ops_path = tmp_path / "ops.json"
    ops_path.write_text(json.dumps(artifact))
    mem_path = tmp_path / "mem.json"
    mem_path.write_text(json.dumps({
        "n_devices": 8, "baseline_present": True,
        "baseline_n_devices": 8, "topology_match": True,
        "stale_budgets": [],
        "programs": [{"name": "p", "origin": "o.py", "specimens": 1,
                      "total_bytes": 10, "argument_bytes": 5,
                      "output_bytes": 5, "temp_bytes": 0,
                      "generated_code_bytes": 0, "budget_bytes": 1,
                      "over_budget": True, "unbudgeted": False,
                      "headroom": -9.0}]}))
    proc = subprocess.run(
        [sys.executable, TRACE_REPORT,
         "--memory", str(mem_path), "--gate-memory",
         "--ops", str(ops_path), "--gate-perf"],
        capture_output=True, text=True)
    both = proc.stdout + proc.stderr
    assert "gate-memory: FAIL" in both
    assert "gate-perf: ok" in both
    assert proc.returncode == 3
