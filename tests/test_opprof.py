"""Per-op roofline attribution (telemetry.opprof): the cost model sees
what the HLO does.

Three synthetic programs with KNOWN rooflines probe the attribution
end-to-end through the real trace->compile->parse path (no mocked HLO):

* a dot-heavy matmul whose arithmetic intensity sits far above the CPU
  machine balance — must classify ``dot`` (or a dot-bearing fusion) and
  read compute-bound;
* a big elementwise add at intensity ~0.08 FLOP/B — must read
  HBM-bound;
* a psum under the substrate's shard_map on the 8-device test mesh —
  must surface a ``collective`` unit bound by ``comm``.

Plus the perf-budget comparison (check_perf) over synthetic measured
sets, the device->timeseries drift feed, and the bench trajectory tool.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mxnet_tpu.lint import tracecheck
from mxnet_tpu.parallel import mesh as mesh_mod
from mxnet_tpu.telemetry import costs, opprof

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def analyze(name, fn, args):
    rec = tracecheck.trace_program(name, jax.jit(fn), args)
    analysis, compiled = opprof.analyze_record(rec, costs.peaks())
    assert compiled is not None, "%s did not compile" % name
    assert analysis is not None
    return analysis


# ---------------------------------------------------------------------------
# op-class + roofline bucketing
# ---------------------------------------------------------------------------

def test_dot_heavy_program_reads_compute_bound():
    a = jnp.ones((256, 256), jnp.float32)
    analysis = analyze("opprof_dot", lambda x, y: x @ y, (a, a))
    dots = [u for u in analysis["units"]
            if u["op_class"] in ("dot", "fusion") and u["flops"] > 1e6]
    assert dots, "no dot-bearing unit found: %r" % (
        [(u["unit"], u["op_class"]) for u in analysis["units"]])
    top = max(dots, key=lambda u: u["flops"])
    # 2*256^3 flops over ~3*256*256*4 bytes: intensity ~40 FLOP/B,
    # far above the CPU balance of 2
    assert top["intensity"] > costs.machine_balance()
    assert top["bound"] == "compute"
    assert top["flops"] >= 2 * 256 ** 3
    assert top["ceiling"] == costs.peaks()["flops"]


def test_bandwidth_bound_program_reads_hbm():
    x = jnp.ones((1024 * 1024,), jnp.float32)
    analysis = analyze("opprof_bw", lambda a, b: a + b, (x, x))
    adds = [u for u in analysis["units"]
            if u["op_class"] in ("elementwise", "fusion")]
    assert adds
    top = max(adds, key=lambda u: u["bytes"])
    # 1 flop per element over 12 bytes moved: intensity ~0.08
    assert top["intensity"] < costs.machine_balance()
    assert top["bound"] == "hbm"
    # the slope region of the roofline: ceiling = intensity * HBM peak
    assert top["ceiling"] < costs.peaks()["flops"]


def test_collective_program_reads_comm():
    mesh = Mesh(np.array(jax.devices()), ("x",))

    def body(x):
        return jax.lax.psum(x, "x")

    fn = mesh_mod.shard_map(body, mesh=mesh, in_specs=P("x", None),
                            out_specs=P(None, None))
    x = jnp.ones((8, 64), jnp.float32)
    analysis = analyze("opprof_coll", fn, (x,))
    colls = [u for u in analysis["units"]
             if u["op_class"] == "collective"]
    assert colls, "no collective unit in: %r" % (
        [(u["unit"], u["opcode"]) for u in analysis["units"]])
    assert all(u["bound"] == "comm" for u in colls)
    assert all(u["ceiling"] == costs.peaks()["ici_bw"] for u in colls)
    assert all(u["ceiling_kind"] == "bytes_per_s" for u in colls)


def test_shares_sum_to_one_per_program():
    a = jnp.ones((64, 64), jnp.float32)

    def mixed(x, y):
        z = jnp.tanh(x @ y)
        return z.sum() + (x * y).mean()

    analysis = analyze("opprof_mixed", mixed, (a, a))
    assert len(analysis["units"]) > 1
    total = sum(u["share"] for u in analysis["units"])
    assert total == pytest.approx(1.0, abs=1e-6)
    assert all(0.0 <= u["share"] <= 1.0 for u in analysis["units"])


def test_classify_table():
    assert opprof.classify("dot") == "dot"
    assert opprof.classify("convolution") == "conv"
    assert opprof.classify("fusion") == "fusion"
    assert opprof.classify("while") == "fusion"
    assert opprof.classify("all-reduce") == "collective"
    assert opprof.classify("reduce-scatter") == "collective"
    assert opprof.classify("collective-permute") == "collective"
    assert opprof.classify("reduce") == "reduce"
    assert opprof.classify("add") == "elementwise"
    assert opprof.classify("exponential") == "elementwise"
    assert opprof.classify("parameter") == "other"


def test_parse_hlo_handles_tuple_operands_and_fusions():
    text = """\
HloModule m

%fused_computation.1 (p0: f32[16,16], p1: f32[16,16]) -> f32[16,16] {
  %p0 = f32[16,16]{1,0} parameter(0)
  %p1 = f32[16,16]{1,0} parameter(1)
  ROOT %add.1 = f32[16,16]{1,0} add(%p0, %p1)
}

ENTRY %main.9 (a: f32[16,16], t: (s32[], f32[16,16])) -> f32[16,16] {
  %a = f32[16,16]{1,0} parameter(0)
  %t = (s32[], f32[16,16]{1,0}) parameter(1)
  %gte = f32[16,16]{1,0} get-tuple-element((s32[], f32[16,16]{1,0}) %t), index=1
  ROOT %fusion = f32[16,16]{1,0} fusion(%a, %gte), kind=kLoop, calls=%fused_computation.1, metadata={op_name="jit(f)/add"}
}
"""
    comps, entry = opprof.parse_hlo(text)
    assert entry == "main.9"
    assert set(comps) == {"fused_computation.1", "main.9"}
    fusion = [i for i in comps["main.9"] if i["opcode"] == "fusion"][0]
    assert fusion["called"] == ["fused_computation.1"]
    assert fusion["operands"] == ["a", "gte"]
    assert fusion["op_name"] == "jit(f)/add"
    gte = [i for i in comps["main.9"] if i["name"] == "gte"][0]
    # the tuple-typed operand's internal parens must not truncate the
    # operand scan
    assert "t" in gte["operands"]
    analysis = opprof.analyze_hlo(text, costs.peaks())
    units = {u["unit"]: u for u in analysis["units"]}
    assert "%fusion" in units
    # the fusion recursed into its called computation: 16*16 adds
    assert units["%fusion"]["flops"] == 16 * 16


# ---------------------------------------------------------------------------
# check_perf: the budget comparison
# ---------------------------------------------------------------------------

def _measured(name="prog", us=1000.0, digest="d0", specimens=1):
    return {name: {"origin": "o.py", "specimens": specimens,
                   "digest": digest, "median_us": us, "measured": True,
                   "flops": 0, "bytes": 0, "units": []}}


def _baseline(name="prog", us=1000.0, digest="d0", specimens=1,
              n_devices=8):
    return {"version": 1, "n_devices": n_devices, "tolerance": 1.5,
            "programs": {name: {"specimens": specimens,
                                "digest": digest, "median_us": us}}}


def test_check_perf_within_budget():
    report = opprof.check_perf(_measured(us=1200.0), _baseline(),
                               tolerance=1.5, n_devices=8)
    (p,) = report["programs"]
    assert not p["over_budget"] and not p["unbudgeted"]
    assert report["topology_match"]


def test_check_perf_flags_regression_beyond_band_and_slack():
    # budget 1000us, tolerance +150% + 500us slack -> limit 3000us
    report = opprof.check_perf(_measured(us=3100.0), _baseline(),
                               tolerance=1.5, n_devices=8)
    (p,) = report["programs"]
    assert p["over_budget"]


def test_check_perf_slack_floor_absorbs_micro_jitter():
    # 10us budget: the fractional band is meaningless, the 500us
    # absolute floor keeps scheduler noise out of the verdict
    report = opprof.check_perf(_measured(us=400.0),
                               _baseline(us=10.0),
                               tolerance=1.5, n_devices=8)
    (p,) = report["programs"]
    assert not p["over_budget"]


def test_check_perf_digest_mismatch_is_unbudgeted():
    report = opprof.check_perf(_measured(digest="NEW"), _baseline(),
                               tolerance=1.5, n_devices=8)
    (p,) = report["programs"]
    assert p["unbudgeted"]


def test_check_perf_specimen_count_mismatch_is_unbudgeted():
    report = opprof.check_perf(_measured(specimens=2),
                               _baseline(specimens=1),
                               tolerance=1.5, n_devices=8)
    (p,) = report["programs"]
    assert p["unbudgeted"]


def test_check_perf_topology_mismatch_skips_comparison():
    report = opprof.check_perf(_measured(), _baseline(n_devices=2),
                               tolerance=1.5, n_devices=8)
    assert not report["topology_match"]
    (p,) = report["programs"]
    assert p["unbudgeted"] and not p["over_budget"]


def test_check_perf_stale_budgets_named():
    base = _baseline()
    base["programs"]["gone_program"] = {"specimens": 1, "digest": "x",
                                        "median_us": 5.0}
    report = opprof.check_perf(_measured(), base, tolerance=1.5,
                               n_devices=8)
    assert report["stale_budgets"] == ["gone_program"]


def test_perf_tolerance_env(monkeypatch):
    monkeypatch.delenv("MXNET_PERF_TOLERANCE", raising=False)
    assert opprof.perf_tolerance() == 1.5
    monkeypatch.setenv("MXNET_PERF_TOLERANCE", "0.5")
    assert opprof.perf_tolerance() == 0.5
    monkeypatch.setenv("MXNET_PERF_TOLERANCE", "junk")
    assert opprof.perf_tolerance() == 1.5
    monkeypatch.setenv("MXNET_PERF_TOLERANCE", "-1")
    assert opprof.perf_tolerance() == 1.5


def test_kernel_candidates_rank_compute_and_comm():
    programs = {
        "big": {"origin": "o", "specimens": 1, "digest": "a",
                "median_us": 900.0, "measured": True, "flops": 0,
                "bytes": 0, "units": [
                    {"unit": "%dot.1", "opcode": "dot",
                     "op_class": "dot", "op_name": None,
                     "bound": "compute", "intensity": 40.0,
                     "ceiling": 8e11, "ceiling_kind": "flops_per_s",
                     "est_us": 9.0, "share": 0.9,
                     "attributed_us": 810.0},
                    {"unit": "%all-reduce.1", "opcode": "all-reduce",
                     "op_class": "collective", "op_name": None,
                     "bound": "comm", "intensity": 0.1,
                     "ceiling": 8e10, "ceiling_kind": "bytes_per_s",
                     "est_us": 1.0, "share": 0.1,
                     "attributed_us": 90.0}]},
        "tiny": {"origin": "o", "specimens": 1, "digest": "b",
                 "median_us": 100.0, "measured": True, "flops": 0,
                 "bytes": 0, "units": [
                     {"unit": "%collective-permute.1",
                      "opcode": "collective-permute",
                      "op_class": "collective", "op_name": None,
                      "bound": "comm", "intensity": 0.0,
                      "ceiling": 8e10, "ceiling_kind": "bytes_per_s",
                      "est_us": 1.0, "share": 1.0,
                      "attributed_us": 100.0}]},
    }
    cands = opprof.kernel_candidates(programs)
    kinds = {c["kind"] for c in cands}
    assert kinds == {"compute", "comm"}
    compute = [c for c in cands if c["kind"] == "compute"]
    assert compute[0]["unit"] == "%dot.1"
    comm = [c for c in cands if c["kind"] == "comm"]
    # ranked within the comm class by attributed time: the permute's
    # 100us beats the all-reduce's 90us even though its global share
    # is small — the separate tier exists exactly so collective cores
    # are not buried under the matmuls
    assert comm[0]["unit"] == "%collective-permute.1"


# ---------------------------------------------------------------------------
# the device -> timeseries drift feed
# ---------------------------------------------------------------------------

def test_sampled_window_feeds_device_series():
    from mxnet_tpu.telemetry import device, timeseries
    device.reset()
    timeseries.reset()
    device.configure(rate=1, opprof=True)
    try:
        device.open_step_window()
        win = device._tls.window
        assert win is not None and win.sampled
        device.record_program("opprof_feed_prog", 123.0, window=win)
        device.close_step_window(500.0)
        pts = timeseries.series("device/opprof_feed_prog/us")
        assert pts == [(0, 123.0)]
    finally:
        device.configure(rate=0, opprof=True)
        device.reset()
        timeseries.reset()


def test_opprof_flag_gates_the_feed():
    from mxnet_tpu.telemetry import device, timeseries
    device.reset()
    timeseries.reset()
    device.configure(rate=1, opprof=False)
    try:
        assert not device.opprof_enabled()
        device.open_step_window()
        win = device._tls.window
        device.record_program("opprof_gated_prog", 55.0, window=win)
        device.close_step_window(100.0)
        assert timeseries.series("device/opprof_gated_prog/us") == []
    finally:
        device.configure(rate=0, opprof=True)
        device.reset()
        timeseries.reset()


def test_opprof_env_parse(monkeypatch):
    from mxnet_tpu.telemetry import device
    monkeypatch.setenv("MXNET_OPPROF", "0")
    device.refresh_from_env()
    assert not device.opprof_enabled()
    monkeypatch.delenv("MXNET_OPPROF", raising=False)
    device.refresh_from_env()
    assert device.opprof_enabled()   # default on


# ---------------------------------------------------------------------------
# bench trajectory tool
# ---------------------------------------------------------------------------

TRAJECTORY = os.path.join(REPO, "tools", "bench_trajectory.py")


def _round_files(tmp_path, rounds):
    for n, (bench_rc, calls, value) in rounds.items():
        (tmp_path / ("BENCH_r%02d.json" % n)).write_text(json.dumps({
            "n": n, "cmd": "x", "rc": bench_rc, "tail": "",
            "parsed": {"metric": "resnet50_infer", "value": value,
                       "unit": "img/s", "vs_baseline": None,
                       "program_calls_per_step": calls,
                       "overlap_ratio": None, "gate_overlap": None,
                       "health_gate": None}}))
        (tmp_path / ("MULTICHIP_r%02d.json" % n)).write_text(json.dumps(
            {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
             "legs": ["train"], "multihost": None, "health": None,
             "tail": ""}))


def _run_traj(tmp_path, *extra):
    return subprocess.run(
        [sys.executable, TRAJECTORY, "--root", str(tmp_path), *extra],
        capture_output=True, text=True)


def test_trajectory_merges_rounds(tmp_path):
    _round_files(tmp_path, {1: (0, 1.0, 100.0), 2: (0, 1.0, 110.0)})
    proc = _run_traj(tmp_path)
    assert proc.returncode == 0
    out = json.loads(proc.stdout)
    assert [r["round"] for r in out["rounds"]] == [1, 2]
    assert out["regressions"] == []


def test_trajectory_check_flags_calls_per_step_growth(tmp_path):
    _round_files(tmp_path, {1: (0, 1.0, 100.0), 2: (0, 2.0, 100.0)})
    proc = _run_traj(tmp_path, "--check")
    assert proc.returncode == 3
    assert "program_calls_per_step grew" in proc.stderr


def test_trajectory_check_flags_throughput_drop(tmp_path):
    _round_files(tmp_path, {1: (0, 1.0, 100.0), 2: (0, 1.0, 80.0)})
    proc = _run_traj(tmp_path, "--check")
    assert proc.returncode == 3
    assert "dropped" in proc.stderr


def test_trajectory_check_unmeasurable_below_two_rounds(tmp_path):
    _round_files(tmp_path, {1: (0, 1.0, 100.0)})
    proc = _run_traj(tmp_path, "--check")
    assert proc.returncode == 4


def test_trajectory_check_ok_on_clean_rounds(tmp_path):
    _round_files(tmp_path, {1: (0, 1.0, 100.0), 2: (0, 1.0, 99.0),
                            3: (0, 1.0, 101.0)})
    proc = _run_traj(tmp_path, "--check")
    assert proc.returncode == 0
    assert "trajectory: ok" in proc.stdout
