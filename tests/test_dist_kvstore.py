"""Multi-process distributed kvstore tests.

Mirrors the reference's nightly doctrine (SURVEY §4): distributed tests run
REAL local processes through the launcher — no mock network backend — and
assert exact numeric invariants on every worker
(reference ``tests/nightly/dist_sync_kvstore.py``).
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from launch import launch  # noqa: E402

WORKER = os.path.join(REPO, "tests", "dist_sync_kvstore.py")

ENV = {
    "JAX_PLATFORMS": "cpu",
    # shard the 6000-element 'big' key across servers
    "MXNET_KVSTORE_BIGARRAY_BOUND": "1000",
    "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
}


@pytest.mark.parametrize("nworkers,nservers", [(4, 2), (2, 1)])
def test_dist_sync_invariants(nworkers, nservers):
    rcs = launch(nworkers, nservers, [sys.executable, WORKER],
                 env_extra=ENV, timeout=300)
    assert rcs == [0] * nworkers, "worker exit codes: %r" % (rcs,)


def test_launch_cli_help():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"), "--help"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    assert "local" in out.stdout


ASYNC_WORKER = os.path.join(REPO, "tests", "dist_async_kvstore.py")
PJIT_WORKER = os.path.join(REPO, "tools", "dist_pjit_worker.py")


def test_dist_async_invariants():
    """Async PS: eventual-total invariant after barrier
    (ref kvstore.cc:49-51 async mode)."""
    rcs = launch(2, 1, [sys.executable, ASYNC_WORKER],
                 env_extra=ENV, timeout=300)
    assert rcs == [0, 0], "worker exit codes: %r" % (rcs,)


def test_multiprocess_pjit():
    """2 jax.distributed processes x 2 virtual devices run one SPMD pjit
    step over the global mesh with identical losses (SURVEY §5.8)."""
    env = dict(ENV, MX_LOCAL_DEVICES="2")
    env.pop("JAX_PLATFORMS", None)
    rcs = launch(2, 0, [sys.executable, PJIT_WORKER],
                 env_extra=env, timeout=400)
    assert rcs == [0, 0], "worker exit codes: %r" % (rcs,)


LENET_WORKER = os.path.join(REPO, "tests", "dist_lenet.py")


def test_dist_lenet_end_to_end():
    """Real Module.fit over dist_sync across 2 workers: parameters agree
    fleet-wide and the model converges (ref tests/nightly/dist_lenet.py)."""
    rcs = launch(2, 1, [sys.executable, LENET_WORKER],
                 env_extra=ENV, timeout=600)
    assert rcs == [0, 0], "worker exit codes: %r" % (rcs,)


SPARSE_WORKER = os.path.join(REPO, "tests", "sparse_linear_worker.py")


def test_dist_async_sparse_linear_end_to_end():
    """The load-bearing sparse workload (SURVEY §2.2): row_sparse weight
    + dist_async PS + per-batch row_sparse_pull, trained to improving
    loss on every worker (reference example/sparse/linear_classification
    run under the nightly dist doctrine)."""
    rcs = launch(2, 1, [sys.executable, SPARSE_WORKER],
                 env_extra=ENV, timeout=600)
    assert rcs == [0, 0], "worker exit codes: %r" % (rcs,)


def test_wire_framing_rejects_malformed_peers():
    """r4 advice: one malformed peer must not crash (or code-exec) a
    training job. Frame = magic + version + length; payload pickle is
    allowlist-restricted."""
    import pickle
    import socket
    import struct
    import threading

    import numpy as np
    from mxnet_tpu import dist_ps

    # 1. round-trip with numpy + containers still works
    a, b = socket.socketpair()
    ca, cb = dist_ps.Conn(a), dist_ps.Conn(b)
    msg = ("push", "w", 0, np.arange(6, dtype=np.float32), None)
    ca.send(msg)
    got = cb.recv()
    assert got[0] == "push" and np.array_equal(got[3], msg[3])

    # 2. garbage magic -> ProtocolError, not a pickle crash
    a.sendall(b"GARBAGE!" + b"\x00" * 6)
    with pytest.raises(dist_ps.ProtocolError, match="magic"):
        cb.recv()
    a.close(); b.close()

    # 3. wrong wire version -> loud version error
    a, b = socket.socketpair()
    blob = pickle.dumps(("barrier",))
    a.sendall(struct.pack("<4sHQ", b"MXPS", 999, len(blob)) + blob)
    with pytest.raises(dist_ps.ProtocolError, match="version"):
        dist_ps.Conn(b).recv()
    a.close(); b.close()

    # 4. well-framed but disallowed pickle global (code-exec attempt)
    class Evil:
        def __reduce__(self):
            import os as _os
            return (_os.system, ("true",))

    a, b = socket.socketpair()
    blob = pickle.dumps(Evil())
    a.sendall(struct.pack("<4sHQ", b"MXPS", 1, len(blob)) + blob)
    with pytest.raises(dist_ps.ProtocolError, match="disallowed"):
        dist_ps.Conn(b).recv()
    a.close(); b.close()

    # 5. a live Server drops the malformed peer and keeps serving others
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)
    addr = lsock.getsockname()
    server = dist_ps.Server(nworkers=1)
    stop = threading.Event()
    t = threading.Thread(target=server.serve_forever, args=(lsock, stop),
                         daemon=True)
    t.start()
    rogue = socket.create_connection(addr)
    rogue.sendall(b"\xde\xad\xbe\xef" * 8)
    rogue.close()
    good = dist_ps.Conn(socket.create_connection(addr))
    good.send(("init", "w", np.ones(4, np.float32), (4,), (0, 4)))
    assert good.recv() == ("ok",)
    good.send(("pull", "w"))
    tag, val = good.recv()
    assert tag == "val" and np.array_equal(val, np.ones(4, np.float32))
    stop.set()
    good.close()
    lsock.close()
