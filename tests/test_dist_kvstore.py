"""Multi-process distributed kvstore tests.

Mirrors the reference's nightly doctrine (SURVEY §4): distributed tests run
REAL local processes through the launcher — no mock network backend — and
assert exact numeric invariants on every worker
(reference ``tests/nightly/dist_sync_kvstore.py``).
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from launch import launch  # noqa: E402

WORKER = os.path.join(REPO, "tests", "dist_sync_kvstore.py")

ENV = {
    "JAX_PLATFORMS": "cpu",
    # shard the 6000-element 'big' key across servers
    "MXNET_KVSTORE_BIGARRAY_BOUND": "1000",
    "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
}


@pytest.mark.parametrize("nworkers,nservers", [(4, 2), (2, 1)])
def test_dist_sync_invariants(nworkers, nservers):
    rcs = launch(nworkers, nservers, [sys.executable, WORKER],
                 env_extra=ENV, timeout=300)
    assert rcs == [0] * nworkers, "worker exit codes: %r" % (rcs,)


def test_launch_cli_help():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"), "--help"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    assert "local" in out.stdout


ASYNC_WORKER = os.path.join(REPO, "tests", "dist_async_kvstore.py")
PJIT_WORKER = os.path.join(REPO, "tools", "dist_pjit_worker.py")


def test_dist_async_invariants():
    """Async PS: eventual-total invariant after barrier
    (ref kvstore.cc:49-51 async mode)."""
    rcs = launch(2, 1, [sys.executable, ASYNC_WORKER],
                 env_extra=ENV, timeout=300)
    assert rcs == [0, 0], "worker exit codes: %r" % (rcs,)


def test_multiprocess_pjit():
    """2 jax.distributed processes x 2 virtual devices run one SPMD pjit
    step over the global mesh with identical losses (SURVEY §5.8)."""
    env = dict(ENV, MX_LOCAL_DEVICES="2")
    env.pop("JAX_PLATFORMS", None)
    rcs = launch(2, 0, [sys.executable, PJIT_WORKER],
                 env_extra=env, timeout=400)
    assert rcs == [0, 0], "worker exit codes: %r" % (rcs,)


LENET_WORKER = os.path.join(REPO, "tests", "dist_lenet.py")


def test_dist_lenet_end_to_end():
    """Real Module.fit over dist_sync across 2 workers: parameters agree
    fleet-wide and the model converges (ref tests/nightly/dist_lenet.py)."""
    rcs = launch(2, 1, [sys.executable, LENET_WORKER],
                 env_extra=ENV, timeout=600)
    assert rcs == [0, 0], "worker exit codes: %r" % (rcs,)


SPARSE_WORKER = os.path.join(REPO, "tests", "sparse_linear_worker.py")


def test_dist_async_sparse_linear_end_to_end():
    """The load-bearing sparse workload (SURVEY §2.2): row_sparse weight
    + dist_async PS + per-batch row_sparse_pull, trained to improving
    loss on every worker (reference example/sparse/linear_classification
    run under the nightly dist doctrine)."""
    rcs = launch(2, 1, [sys.executable, SPARSE_WORKER],
                 env_extra=ENV, timeout=600)
    assert rcs == [0, 0], "worker exit codes: %r" % (rcs,)
