"""Multi-process distributed kvstore tests.

Mirrors the reference's nightly doctrine (SURVEY §4): distributed tests run
REAL local processes through the launcher — no mock network backend — and
assert exact numeric invariants on every worker
(reference ``tests/nightly/dist_sync_kvstore.py``).
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from launch import launch  # noqa: E402

WORKER = os.path.join(REPO, "tests", "dist_sync_kvstore.py")

ENV = {
    "JAX_PLATFORMS": "cpu",
    # shard the 6000-element 'big' key across servers
    "MXNET_KVSTORE_BIGARRAY_BOUND": "1000",
    "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
}


@pytest.mark.parametrize("nworkers,nservers", [(4, 2), (2, 1)])
def test_dist_sync_invariants(nworkers, nservers):
    rcs = launch(nworkers, nservers, [sys.executable, WORKER],
                 env_extra=ENV, timeout=300)
    assert rcs == [0] * nworkers, "worker exit codes: %r" % (rcs,)


def test_launch_cli_help():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"), "--help"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    assert "local" in out.stdout
