"""SSD contrib op tests (reference src/operator/contrib/multibox_*)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_multibox_prior_shapes_and_geometry():
    x = nd.zeros((1, 3, 2, 2))
    out = nd.contrib.MultiBoxPrior(x, sizes=(0.5, 0.25), ratios=(1.0, 2.0))
    # A = len(sizes) + len(ratios) - 1 = 3
    assert out.shape == (1, 2 * 2 * 3, 4)
    boxes = out.asnumpy()[0]
    # first anchor: center (0.25, 0.25), size 0.5, ratio 1 -> half 0.25
    np.testing.assert_allclose(boxes[0], [0.0, 0.0, 0.5, 0.5], atol=1e-6)
    # second anchor: size 0.25 -> half 0.125
    np.testing.assert_allclose(boxes[1], [0.125, 0.125, 0.375, 0.375],
                               atol=1e-6)
    # ratio-2 anchor: w = s*sqrt(2), h = s/sqrt(2)
    w = boxes[2][2] - boxes[2][0]
    h = boxes[2][3] - boxes[2][1]
    np.testing.assert_allclose(w / h, 2.0, rtol=1e-5)


def test_multibox_prior_clip():
    x = nd.zeros((1, 3, 1, 1))
    out = nd.contrib.MultiBoxPrior(x, sizes=(1.5,), clip=True).asnumpy()
    assert out.min() >= 0.0 and out.max() <= 1.0


def _toy_setup():
    # two anchors: one matching the gt box well, one far away
    anchors = np.array([[[0.1, 0.1, 0.5, 0.5],
                         [0.6, 0.6, 0.9, 0.9],
                         [0.0, 0.0, 0.05, 0.05]]], np.float32)
    # one gt: class 0 box overlapping anchor 0
    label = np.array([[[0.0, 0.1, 0.1, 0.45, 0.5],
                       [-1.0, 0.0, 0.0, 0.0, 0.0]]], np.float32)
    cls_pred = np.zeros((1, 3, 3), np.float32)  # (N, C+1, A)
    return nd.array(anchors), nd.array(label), nd.array(cls_pred)


def test_multibox_target_matching():
    anchor, label, cls_pred = _toy_setup()
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
        anchor, label, cls_pred, overlap_threshold=0.5)
    cls_np = cls_t.asnumpy()[0]
    assert cls_np[0] == 1.0          # matched -> class 0 + 1
    assert cls_np[1] == 0.0          # background
    assert cls_np[2] == 0.0
    mask = loc_m.asnumpy()[0].reshape(3, 4)
    assert mask[0].sum() == 4 and mask[1].sum() == 0


def test_multibox_target_encode_decode_roundtrip():
    from mxnet_tpu.ops.ssd import _encode_offsets, _decode_offsets
    import jax.numpy as jnp
    anchors = jnp.array([[0.1, 0.1, 0.5, 0.5], [0.2, 0.3, 0.8, 0.9]])
    gt = jnp.array([[0.15, 0.12, 0.55, 0.48], [0.25, 0.35, 0.75, 0.85]])
    var = (0.1, 0.1, 0.2, 0.2)
    dec = _decode_offsets(anchors, _encode_offsets(anchors, gt, var), var)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(gt), atol=1e-5)


def test_multibox_detection_end_to_end():
    anchor, label, cls_pred = _toy_setup()
    # class probs: anchor 0 confident class-1 (fg idx 1), others background
    probs = np.array([[[0.05, 0.9, 0.9],    # background row
                       [0.9, 0.05, 0.05],   # class 0 row
                       [0.05, 0.05, 0.05]]], np.float32)
    loc_pred = np.zeros((1, 12), np.float32)   # zero offsets -> anchors
    out = nd.contrib.MultiBoxDetection(
        nd.array(probs), nd.array(loc_pred), anchor,
        threshold=0.1, nms_threshold=0.5)
    dets = out.asnumpy()[0]
    # one valid detection: class 0, score 0.9, box == anchor 0
    valid = dets[dets[:, 0] >= 0]
    assert valid.shape[0] == 1
    np.testing.assert_allclose(valid[0, :2], [0.0, 0.9], atol=1e-5)
    np.testing.assert_allclose(valid[0, 2:], [0.1, 0.1, 0.5, 0.5], atol=1e-5)


def test_multibox_detection_nms_suppression():
    # two overlapping confident anchors, same class -> NMS keeps one
    anchors = np.array([[[0.1, 0.1, 0.5, 0.5],
                         [0.12, 0.12, 0.52, 0.52]]], np.float32)
    probs = np.array([[[0.1, 0.2],
                       [0.9, 0.8]]], np.float32)
    loc_pred = np.zeros((1, 8), np.float32)
    out = nd.contrib.MultiBoxDetection(
        nd.array(probs), nd.array(loc_pred), nd.array(anchors),
        threshold=0.1, nms_threshold=0.5)
    dets = out.asnumpy()[0]
    valid = dets[dets[:, 0] >= 0]
    assert valid.shape[0] == 1
    assert abs(valid[0, 1] - 0.9) < 1e-5


def test_multibox_prior_steps_offsets_are_y_then_x():
    """steps/offsets follow the reference (y, x) order."""
    x = nd.zeros((1, 3, 2, 4))     # H=2, W=4
    out = nd.contrib.MultiBoxPrior(x, sizes=(0.2,),
                                   steps=(0.5, 0.25),      # (y, x)
                                   offsets=(0.0, 0.5)).asnumpy()[0]
    # first anchor center: cy = (0+0.0)*0.5 = 0, cx = (0+0.5)*0.25 = 0.125
    cy = (out[0, 1] + out[0, 3]) / 2
    cx = (out[0, 0] + out[0, 2]) / 2
    assert abs(cy - 0.0) < 1e-6 and abs(cx - 0.125) < 1e-6
