"""Serving fleet (ISSUE 13 tentpole): multi-replica router with
health-gated failover, hedged retries, and zero-downtime rollout.

Acceptance contract pinned here:

* an accepted request completes — hedged or failed over — through a
  replica death, within its deadline (``test_failover...``, and the
  kill -9 subprocess variant via ``tools/fleet_smoke.py``);
* a dead replica is shed within 2x the heartbeat interval and a
  restarted replica re-registers into its dead rank, warms from the
  checkpoint tier, and takes traffic again;
* a rolling reload of every replica completes with zero failed
  requests and actually swaps the weights;
* the half-open circuit breaker admits EXACTLY one probe under real
  thread contention (the PR-8 review fix, stress-locked);
* the ``fleet.route`` / ``replica.predict`` chaos sites parse, inject,
  and replay deterministically.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
import mxnet_tpu.serving as serving
from mxnet_tpu import chaos, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.model import save_checkpoint
from mxnet_tpu.serving import fleet as fleet_mod
from mxnet_tpu.serving.batcher import Overloaded
from mxnet_tpu.serving.fleet import FleetRouter
from mxnet_tpu.serving.replica import ReplicaServer
from mxnet_tpu.serving.slots import CircuitBreaker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FEATURES = 6
CLASSES = 3
BUCKETS = (1, 4)          # small ladder: 2 compiles per replica


def _save_mlp(prefix, seed=0):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fl_fc1")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fl_fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    shapes = {"data": (1, FEATURES)}
    arg_shapes, _, _ = net.infer_shape(**shapes)
    host = np.random.RandomState(seed)
    args = {name: mx.nd.array((host.randn(*shape) * 0.3)
                              .astype(np.float32))
            for name, shape in zip(net.list_arguments(), arg_shapes)
            if name not in shapes and not name.endswith("_label")}
    save_checkpoint(prefix, 0, net, args, {})
    return prefix


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fleet")
    return _save_mlp(str(tmp / "mlp"))


@pytest.fixture
def fast_fleet_env(monkeypatch):
    """Tight heartbeats so dead-detection tests run in milliseconds."""
    monkeypatch.setenv("MXNET_FLEET_HEARTBEAT_S", "0.15")
    fleet_mod.refresh_from_env()
    yield
    fleet_mod.refresh_from_env()


def _spawn_replica(router, checkpoint, rank_hint=None):
    rep = ReplicaServer(router=router.addr, port=0,
                        rank_hint=rank_hint).start()
    rep.load("mlp", prefix=checkpoint, epoch=0,
             input_shapes={"data": (1, FEATURES)}, buckets=BUCKETS)
    rep.advertise_ready()
    return rep


@pytest.fixture
def fleet(checkpoint, fast_fleet_env):
    """Router + two in-process replicas, torn down hard."""
    router = FleetRouter(port=0).start()
    replicas = [_spawn_replica(router, checkpoint) for _ in range(2)]
    assert router.wait_ready(2, timeout=30.0), router.http_view()
    yield router, replicas
    chaos.configure(None)
    router.stop()
    for rep in replicas:
        try:
            rep.stop(drain=False)
        except Exception:
            pass


def _x(n, seed=0):
    return np.random.RandomState(seed).randn(n, FEATURES) \
        .astype(np.float32)


# ---------------------------------------------------------------------------
# satellite: breaker half-open stress (the PR-8 review fix, under real
# concurrency)
# ---------------------------------------------------------------------------

def test_breaker_half_open_admits_exactly_one_probe_under_threads():
    """8 threads hammer a half-open breaker through a barrier: exactly
    one leased probe admits; everyone else sheds until record()."""
    breaker = CircuitBreaker(threshold=1, cooldown_s=0.05)
    breaker.record(ok=False)               # open
    assert breaker.state() == "open"
    time.sleep(0.08)                       # cooldown elapsed: half-open
    assert breaker.state() == "half-open"
    n = 8
    barrier = threading.Barrier(n)
    admitted = []
    lock = threading.Lock()

    def prober():
        barrier.wait()
        ok = breaker.allow()
        with lock:
            admitted.append(ok)

    threads = [threading.Thread(target=prober) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
    assert sum(admitted) == 1, admitted
    # the probe resolves: success closes, the next allow is free again
    breaker.record(ok=True)
    assert breaker.state() == "closed"
    assert breaker.allow()


# ---------------------------------------------------------------------------
# satellite: chaos grammar — the new fleet sites
# ---------------------------------------------------------------------------

def test_chaos_spec_round_trip_fleet_sites():
    spec = "seed=3;fleet.route:exc@2;replica.predict:delay@1-2=3ms"
    seed, rules = chaos.parse_spec(spec)
    assert seed == 3
    assert [r.describe() for r in rules] == [
        "fleet.route:exc@2", "replica.predict:delay@1-2=0.003s"]
    # prefix matching: a bare "fleet" clause covers fleet.route
    _, rules = chaos.parse_spec("fleet:exc@1")
    assert rules[0].matches("fleet.route")
    # unknown sites still refused loudly
    with pytest.raises(chaos.ChaosSpecError):
        chaos.parse_spec("fleet.rouet:exc@1")


def test_chaos_fleet_route_site_fires_and_replays(fleet):
    """Seeded router-side chaos injects deterministically and the fault
    log replays bitwise from the same spec + seed."""
    router, _ = fleet
    spec = "seed=11;fleet.route:exc@2"
    chaos.configure(spec)
    logs = []
    for _ in range(2):
        errors = 0
        for i in range(4):
            try:
                router.predict("mlp", {"data": _x(1, seed=i)},
                               timeout_s=10.0)
            except chaos.ChaosError:
                errors += 1
        assert errors == 1       # exactly the @2 occurrence
        logs.append(chaos.fault_log())
        chaos.reset()
    assert logs[0] == logs[1] == [
        ("fleet.route", "fleet.route", "exc", 2)]
    chaos.configure(None)


# ---------------------------------------------------------------------------
# tentpole: routing, failover, hedging
# ---------------------------------------------------------------------------

def test_least_outstanding_routing_spreads_idle_traffic(fleet):
    """Sequential (never-concurrent) requests round-robin via the
    least-served tie-break — the per-replica distribution both
    serve_bench --fleet and /fleet report."""
    router, _ = fleet
    for i in range(8):
        router.predict("mlp", {"data": _x(2, seed=i)}, timeout_s=10.0)
    view = router.http_view()
    served = {rank: rep["served"]
              for rank, rep in view["replicas"].items()}
    assert sum(served.values()) == 8
    assert all(n == 4 for n in served.values()), served
    assert view["models"] == ["mlp"]


def test_predict_results_match_local_and_unknown_model_404s(fleet,
                                                           checkpoint):
    router, replicas = fleet
    x = _x(3, seed=7)
    outs, meta = router.predict_detail("mlp", {"data": x},
                                       timeout_s=10.0)
    # bitwise vs the replica's own slot (same AOT program, same weights)
    local = replicas[0].registry.get("mlp").predict({"data": x})
    np.testing.assert_array_equal(np.asarray(outs[0]),
                                  np.asarray(local[0]))
    assert meta["output_names"] == ["softmax_output"]
    with pytest.raises(MXNetError, match="is not loaded"):
        router.predict("nope", {"data": x}, timeout_s=5.0)


def test_failover_completes_accepted_request_through_replica_death(
        fleet, checkpoint):
    """(1) A replica-side fault on the first attempt fails over to the
    other replica and the accepted request completes (deterministic via
    the replica.predict chaos seam).  (2) An actually-killed replica is
    shed within 2x the heartbeat interval and the fleet keeps serving
    on the survivor."""
    router, replicas = fleet
    before = telemetry.counter("fleet_failovers")
    chaos.configure("seed=1;replica.predict:exc@1")
    outs, meta = router.predict_detail("mlp", {"data": _x(2)},
                                       timeout_s=10.0)
    chaos.configure(None)
    assert np.asarray(outs[0]).shape == (2, CLASSES)
    assert meta["attempts"] == 2
    assert telemetry.counter("fleet_failovers") == before + 1
    # now kill one replica for real (hard stop: listener + conns die)
    replicas[0].stop(drain=False)
    for i in range(4):
        outs = router.predict("mlp", {"data": _x(2, seed=i)},
                              timeout_s=10.0)
        assert np.asarray(outs[0]).shape == (2, CLASSES)
    # the dead replica is shed within 2x the heartbeat interval
    deadline = time.monotonic() + 2.0 * fleet_mod.heartbeat_s() + 0.5
    while time.monotonic() < deadline:
        if router.http_view()["replicas"]["0"]["state"] == "dead":
            break
        time.sleep(0.01)
    assert router.http_view()["replicas"]["0"]["state"] == "dead"
    assert router.ready_count() == 1


def test_hedge_fires_after_timeout_and_first_reply_wins(fleet,
                                                        monkeypatch):
    """A deterministically-slow replica RPC (chaos delay on the first
    replica.predict) triggers one hedged duplicate after the pinned
    hedge timeout; the fast replica's reply wins well before the slow
    one lands."""
    router, _ = fleet
    monkeypatch.setenv("MXNET_FLEET_HEDGE_MS", "50")
    fleet_mod.refresh_from_env()
    chaos.configure("seed=5;replica.predict:delay@1=600ms")
    before = telemetry.counter("fleet_hedges")
    t0 = time.perf_counter()
    outs, meta = router.predict_detail("mlp", {"data": _x(1)},
                                       timeout_s=10.0)
    wall = time.perf_counter() - t0
    assert np.asarray(outs[0]).shape == (1, CLASSES)
    assert telemetry.counter("fleet_hedges") == before + 1
    assert meta["hedged_win"] and meta["attempts"] == 2
    assert wall < 0.55, "hedge did not cut the slow replica's tail " \
        "(%.3fs)" % wall
    chaos.configure(None)
    fleet_mod.refresh_from_env()


def test_dead_rank_takeover_and_warm_rejoin(fleet, checkpoint):
    """A replacement replica re-registers into the dead rank, warms its
    slots from the checkpoint tier, and takes traffic."""
    router, replicas = fleet
    replicas[0].stop(drain=False)
    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline \
            and router.http_view()["replicas"]["0"]["state"] != "dead":
        time.sleep(0.01)
    fresh = _spawn_replica(router, checkpoint, rank_hint=0)
    replicas.append(fresh)                  # fixture teardown owns it
    assert fresh.rank == 0
    assert router.wait_ready(2, timeout=15.0)
    for i in range(4):
        router.predict("mlp", {"data": _x(1, seed=i)}, timeout_s=10.0)
    assert router.http_view()["replicas"]["0"]["served"] > 0


# ---------------------------------------------------------------------------
# tentpole: zero-downtime rolling reload
# ---------------------------------------------------------------------------

def test_rolling_reload_zero_failed_requests_and_new_weights(
        fleet, tmp_path):
    """Roll both replicas onto fresh weights while background load
    runs: zero failed requests, and the fleet actually serves the new
    weights afterwards."""
    router, _ = fleet
    new_prefix = _save_mlp(str(tmp_path / "mlp2"), seed=99)
    x = _x(2, seed=3)
    before = np.asarray(router.predict("mlp", {"data": x},
                                       timeout_s=10.0)[0])
    stop = threading.Event()
    errors = []
    completed = [0]

    def load_loop():
        i = 0
        while not stop.is_set():
            i += 1
            try:
                router.predict("mlp", {"data": _x(1, seed=i)},
                               timeout_s=10.0)
                completed[0] += 1
            except Exception as exc:
                errors.append(repr(exc))

    thread = threading.Thread(target=load_loop, daemon=True)
    thread.start()
    results = router.rolling_reload("mlp", prefix=new_prefix, epoch=0)
    stop.set()
    thread.join(30.0)
    assert results == {0: "ok", 1: "ok"}
    assert not errors, errors[:3]
    assert completed[0] > 0
    after = np.asarray(router.predict("mlp", {"data": x},
                                      timeout_s=10.0)[0])
    assert not np.array_equal(before, after), \
        "reload did not swap the weights"
    assert router.ready_count() == 2


# ---------------------------------------------------------------------------
# satellite: /readyz (readiness) split from /healthz (liveness)
# ---------------------------------------------------------------------------

@pytest.fixture
def live_server():
    from mxnet_tpu.telemetry import server
    srv = server.start_server(port=0, sample_ms=100)
    yield srv
    server.stop_server()


def _http_get(srv, path):
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:%d%s" % (srv.port, path),
                timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def test_readyz_liveness_split_and_slot_compile_state(live_server,
                                                      checkpoint):
    serving.reset_registry()
    try:
        registry = serving.get_registry()
        registry.load("mlp", prefix=checkpoint, epoch=0,
                      input_shapes={"data": (1, FEATURES)},
                      buckets=BUCKETS)
        code, detail = _http_get(live_server, "/readyz")
        assert code == 200 and detail["ok"] and detail["serving"]
        assert detail["slots"]["slots"] == {"mlp": "ready"}
        # a compiling/reloading slot flips readiness, NOT liveness
        registry.get("mlp").status = "reloading"
        code, detail = _http_get(live_server, "/readyz")
        assert code == 503 and not detail["ok"]
        assert detail["slots"]["not_ready"] == ["mlp"]
        code, health = _http_get(live_server, "/healthz")
        assert code == 200 and health["ok"], \
            "liveness must not inherit readiness"
        registry.get("mlp").status = "ready"
        code, detail = _http_get(live_server, "/readyz")
        assert code == 200
    finally:
        serving.reset_registry()


def test_readyz_tracks_replica_state_and_fleet_view(live_server,
                                                   fleet):
    router, replicas = fleet
    code, detail = _http_get(live_server, "/readyz")
    assert code == 200
    assert detail["fleet"]["replicas_ready"] == 2
    # the process's replica view: warming = not ready
    replicas[-1].state = "warming"
    code, detail = _http_get(live_server, "/readyz")
    assert code == 503 and detail["replica"]["state"] == "warming"
    replicas[-1].state = "ready"
    # /fleet carries the serving fleet table
    code, view = _http_get(live_server, "/fleet")
    assert code == 200
    assert view["serving_fleet"]["replicas_total"] == 2


def test_router_http_surface_predict_and_rolling_reload(live_server,
                                                        fleet,
                                                        checkpoint):
    """The /v1 surface fronts the fleet when a router is live: predict
    routes through the balancer (response names the replica), reload is
    the rolling rollout, load is refused."""
    import urllib.request
    router, _ = fleet

    def post(path, obj):
        import urllib.error
        req = urllib.request.Request(
            "http://127.0.0.1:%d%s" % (live_server.port, path),
            data=json.dumps(obj).encode(), method="POST")
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            return exc.code, json.loads(exc.read())

    code, reply = post("/v1/models/mlp/predict",
                       {"inputs": {"data": _x(2).tolist()}})
    assert code == 200, reply
    assert reply["replica"] in (0, 1)
    assert len(reply["outputs"]["softmax_output"]) == 2
    code, reply = post("/v1/models/mlp/reload",
                       {"prefix": checkpoint, "epoch": 0})
    assert code == 200 and reply["ok"], reply
    assert set(reply["replicas"]) == {"0", "1"}
    code, reply = post("/v1/models/other/load", {"prefix": "x"})
    assert code == 400 and "per-replica" in reply["error"]
    code, body = _http_get(live_server, "/v1/models")
    assert code == 200 and body["fleet"]["replicas_ready"] == 2


# ---------------------------------------------------------------------------
# acceptance: the kill -9 subprocess smoke (fast tier-1 variant of
# tools/fleet_smoke.py)
# ---------------------------------------------------------------------------

def test_fleet_smoke_tier1():
    """Router + 3 replica subprocesses; kill -9 one mid-load: shed
    within 2x heartbeat, zero lost accepted requests, bounded p99, and
    the restarted replica re-registers into its dead rank and serves.
    The full-fat surface lives in tools/fleet_smoke.py; this is the
    CI-gated fast variant."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_smoke.py"),
         "--replicas", "3", "--clients", "3", "--requests", "10",
         "--json"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert out.returncode == 0, \
        "fleet_smoke failed:\n%s\n%s" % (out.stdout, out.stderr[-3000:])
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    report = json.loads(line)
    assert report["ok"], report["problems"]
    assert report["phase_a"]["errors"] == 0
    assert report["dead_detect_s"] <= 2.0 * 0.5 + 0.5
    assert report["phase_b"]["revived_rank_state"] == "ready"
    assert report["phase_b"]["revived_rank_served"] > 0


@pytest.mark.slow
def test_serve_bench_fleet_mode_scales_and_balances(tmp_path):
    """serve_bench --fleet 2 --rolling-reload: per-replica distribution
    reported, zero errors, rolling reload ok (the --fleet 1 vs 4 QPS
    scaling comparison is the operator-run acceptance)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--fleet", "2", "--clients", "3", "--requests", "12",
         "--rolling-reload"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=str(tmp_path))
    assert out.returncode == 0, out.stderr[-3000:]
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    report = json.loads(line)
    assert report["closed_loop"]["errors"] == 0
    assert report["fleet"]["rolling_reload"]["ok"]
    assert sum(int(n) for n
               in report["fleet"]["distribution"].values()) > 0
