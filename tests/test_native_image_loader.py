"""Native threaded image loader tests (native/image_loader.cc, the
reference iter_image_recordio_2.cc decode-pipeline analogue)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, recordio

cv2 = pytest.importorskip("cv2")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SO = os.path.join(REPO, "mxnet_tpu", "_native", "libimageloader.so")
pytestmark = pytest.mark.skipif(not os.path.exists(SO),
                                reason="libimageloader.so not built")


def _write_rec(path, n=12, hw=24):
    """n JPEG records; label i; image i is a solid gray level."""
    rec = recordio.MXRecordIO(str(path), "w")
    levels = []
    for i in range(n):
        level = int(255 * (i + 1) / (n + 1))
        levels.append(level)
        img = np.full((hw, hw, 3), level, np.uint8)
        ok, enc = cv2.imencode(".jpg", img, [cv2.IMWRITE_JPEG_QUALITY, 98])
        assert ok
        header = recordio.IRHeader(0, float(i), i, 0)
        rec.write(recordio.pack(header, enc.tobytes()))
    rec.close()
    return levels


def test_loader_batches_and_values(tmp_path):
    from mxnet_tpu.image import ImageRecordIter
    path = tmp_path / "toy.rec"
    levels = _write_rec(path, n=12, hw=24)
    it = ImageRecordIter(path_imgrec=str(path), data_shape=(3, 16, 16),
                         batch_size=4, preprocess_threads=3)
    assert it.num_samples == 12
    seen = []
    total = 0
    while True:
        try:
            batch = it.next()
        except StopIteration:
            break
        arr = batch.data[0].asnumpy()
        labels = batch.label[0].asnumpy()
        n = batch.data[0].shape[0] - (batch.pad or 0)
        total += n
        for j in range(n):
            i = int(labels[j])
            seen.append(i)
            # solid-gray JPEG decodes back to its level (±2/255)
            np.testing.assert_allclose(arr[j].mean(), levels[i] / 255.0,
                                       atol=0.02)
    assert total == 12
    assert sorted(seen) == list(range(12))


def test_loader_shuffle_and_reset(tmp_path):
    from mxnet_tpu.image import ImageRecordIter
    path = tmp_path / "toy.rec"
    _write_rec(path, n=16)
    it = ImageRecordIter(path_imgrec=str(path), data_shape=(3, 8, 8),
                         batch_size=8, shuffle=True, seed=3)
    first = it.next().label[0].asnumpy().copy()
    it.reset()
    again = it.next().label[0].asnumpy().copy()
    # same seeded stream still yields a permutation of labels overall
    assert set(first) <= set(range(16))
    assert set(again) <= set(range(16))


def test_loader_mean_scale(tmp_path):
    from mxnet_tpu.image import ImageRecordIter
    path = tmp_path / "toy.rec"
    _write_rec(path, n=4)
    it = ImageRecordIter(path_imgrec=str(path), data_shape=(3, 8, 8),
                         batch_size=4, mean_rgb=(0, 0, 0), scale=2.0)
    arr = it.next().data[0].asnumpy()
    assert arr.max() <= 2.0 and arr.max() > 1.0   # scaled past [0, 1]
