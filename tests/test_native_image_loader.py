"""Native threaded image loader tests (native/image_loader.cc, the
reference iter_image_recordio_2.cc decode-pipeline analogue)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, recordio

cv2 = pytest.importorskip("cv2")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SO = os.path.join(REPO, "mxnet_tpu", "_native", "libimageloader.so")
pytestmark = pytest.mark.skipif(not os.path.exists(SO),
                                reason="libimageloader.so not built")


def _write_rec(path, n=12, hw=24):
    """n JPEG records; label i; image i is a solid gray level."""
    rec = recordio.MXRecordIO(str(path), "w")
    levels = []
    for i in range(n):
        level = int(255 * (i + 1) / (n + 1))
        levels.append(level)
        img = np.full((hw, hw, 3), level, np.uint8)
        ok, enc = cv2.imencode(".jpg", img, [cv2.IMWRITE_JPEG_QUALITY, 98])
        assert ok
        header = recordio.IRHeader(0, float(i), i, 0)
        rec.write(recordio.pack(header, enc.tobytes()))
    rec.close()
    return levels


def test_loader_batches_and_values(tmp_path):
    from mxnet_tpu.image import ImageRecordIter
    path = tmp_path / "toy.rec"
    levels = _write_rec(path, n=12, hw=24)
    it = ImageRecordIter(path_imgrec=str(path), data_shape=(3, 16, 16),
                         batch_size=4, preprocess_threads=3)
    assert it.num_samples == 12
    seen = []
    total = 0
    while True:
        try:
            batch = it.next()
        except StopIteration:
            break
        arr = batch.data[0].asnumpy()
        labels = batch.label[0].asnumpy()
        n = batch.data[0].shape[0] - (batch.pad or 0)
        total += n
        for j in range(n):
            i = int(labels[j])
            seen.append(i)
            # solid-gray JPEG decodes back to its level (±2/255)
            np.testing.assert_allclose(arr[j].mean(), levels[i] / 255.0,
                                       atol=0.02)
    assert total == 12
    assert sorted(seen) == list(range(12))


def test_loader_shuffle_and_reset(tmp_path):
    from mxnet_tpu.image import ImageRecordIter
    path = tmp_path / "toy.rec"
    _write_rec(path, n=16)
    it = ImageRecordIter(path_imgrec=str(path), data_shape=(3, 8, 8),
                         batch_size=8, shuffle=True, seed=3)
    first = it.next().label[0].asnumpy().copy()
    it.reset()
    again = it.next().label[0].asnumpy().copy()
    # same seeded stream still yields a permutation of labels overall
    assert set(first) <= set(range(16))
    assert set(again) <= set(range(16))


def test_loader_mean_scale(tmp_path):
    from mxnet_tpu.image import ImageRecordIter
    path = tmp_path / "toy.rec"
    _write_rec(path, n=4)
    it = ImageRecordIter(path_imgrec=str(path), data_shape=(3, 8, 8),
                         batch_size=4, mean_rgb=(0, 0, 0), scale=2.0)
    arr = it.next().data[0].asnumpy()
    assert arr.max() <= 2.0 and arr.max() > 1.0   # scaled past [0, 1]


def test_non_jpeg_payload_fails_loudly(tmp_path):
    """Corrupt/non-JPEG records must raise, not train on silent zeros
    (round-5 regression: PNG payloads used to yield all-zero batches);
    with allow_corrupt=True they are COMPACTED out (skip-and-count)."""
    import numpy as np
    import pytest
    import mxnet_tpu as mx
    from mxnet_tpu import recordio
    from mxnet_tpu.image import ImageRecordIter
    path = str(tmp_path / "bad")
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(4):
        hdr = recordio.IRHeader(0, float(i + 1), i, 0)
        if i == 2:   # one corrupt record among three valid JPEGs
            w.write_idx(i, recordio.pack(hdr, b"\x89PNG not a jpeg" * 10))
        else:
            img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
            w.write_idx(i, recordio.pack_img(hdr, img, img_fmt=".jpg"))
    w.close()
    it = ImageRecordIter(path_imgrec=path + ".rec",
                         data_shape=(3, 8, 8), batch_size=4)
    with pytest.raises(IOError, match="failed to decode"):
        next(it)
    # opting in: the corrupt record is dropped, NOT fed as zeros/class-0
    it2 = ImageRecordIter(path_imgrec=path + ".rec",
                          data_shape=(3, 8, 8), batch_size=4,
                          allow_corrupt=True)
    batch = next(it2)
    kept = 4 - batch.pad
    assert kept == 3
    labels = sorted(batch.label[0].asnumpy()[:kept].tolist())
    assert labels == [1.0, 2.0, 4.0], labels   # corrupt record 3 skipped
    # an ALL-corrupt file reports a clean epoch end, not garbage batches
    path2 = str(tmp_path / "allbad")
    w = recordio.MXIndexedRecordIO(path2 + ".idx", path2 + ".rec", "w")
    for i in range(3):
        w.write_idx(i, recordio.pack(recordio.IRHeader(0, 1.0, i, 0),
                                     b"nope" * 20))
    w.close()
    it3 = ImageRecordIter(path_imgrec=path2 + ".rec",
                          data_shape=(3, 8, 8), batch_size=2,
                          allow_corrupt=True)
    with pytest.raises(StopIteration):
        next(it3)
