"""Data IO: NDArrayIter / CSVIter / ResizeIter / RecordIO round trips
(reference tests/python/unittest/test_io.py, test_recordio.py)."""
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_ndarrayiter_basic():
    data = np.arange(40, dtype=np.float32).reshape(10, 4)
    label = np.arange(10, dtype=np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=4, shuffle=False,
                           last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 4)
    assert batches[-1].pad == 2
    assert_almost_equal(batches[0].data[0].asnumpy(), data[:4])
    it.reset()
    assert len(list(it)) == 3


def test_ndarrayiter_discard_and_rollover():
    data = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = mx.io.NDArrayIter(data, batch_size=4,
                           last_batch_handle="discard")
    assert len(list(it)) == 2


def test_ndarrayiter_dict_data():
    data = {"a": np.zeros((6, 2), np.float32),
            "b": np.ones((6, 3), np.float32)}
    it = mx.io.NDArrayIter(data, batch_size=3)
    names = [d.name for d in it.provide_data]
    assert sorted(names) == ["a", "b"]
    b0 = next(iter(it))
    assert len(b0.data) == 2


def test_resize_iter():
    data = np.zeros((8, 2), np.float32)
    base = mx.io.NDArrayIter(data, batch_size=2)
    it = mx.io.ResizeIter(base, 2)
    assert len(list(it)) == 2


def test_csv_iter(tmp_path):
    path = str(tmp_path / "data.csv")
    arr = np.random.randint(0, 9, (12, 3)).astype(np.float32)
    np.savetxt(path, arr, delimiter=",", fmt="%g")
    it = mx.io.CSVIter(data_csv=path, data_shape=(3,), batch_size=4)
    got = np.concatenate([b.data[0].asnumpy() for b in it])
    assert_almost_equal(got[:12], arr, rtol=1e-5)


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "x.rec")
    rec = mx.recordio.MXRecordIO(path, "w")
    for i in range(5):
        rec.write(b"rec%d" % i)
    rec.close()
    rec = mx.recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert rec.read() == b"rec%d" % i
    assert rec.read() is None
    rec.close()


def test_indexed_recordio_seek(tmp_path):
    path = str(tmp_path / "y.rec")
    idx = str(tmp_path / "y.idx")
    rec = mx.recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(6):
        rec.write_idx(i, b"item%d" % i)
    rec.close()
    rec = mx.recordio.MXIndexedRecordIO(idx, path, "r")
    assert rec.read_idx(4) == b"item4"
    assert rec.read_idx(1) == b"item1"
    assert sorted(rec.keys) == list(range(6))
    rec.close()


def test_irheader_pack_unpack():
    header = mx.recordio.IRHeader(0, [1.0, 2.0], 7, 0)
    s = mx.recordio.pack(header, b"payload")
    h2, blob = mx.recordio.unpack(s)
    assert list(h2.label) == [1.0, 2.0]
    assert h2.id == 7
    assert blob == b"payload"


def test_pack_img_unpack_img(tmp_path):
    img = (np.random.rand(8, 8, 3) * 255).astype(np.uint8)
    header = mx.recordio.IRHeader(0, 3.0, 1, 0)
    s = mx.recordio.pack_img(header, img, quality=95, img_fmt=".png")
    h2, img2 = mx.recordio.unpack_img(s)
    assert float(np.asarray(h2.label)) == 3.0
    assert img2.shape == (8, 8, 3)
    assert np.abs(img2.astype(int) - img.astype(int)).mean() < 3


def test_dataiter_provide_semantics():
    data = np.zeros((8, 2, 3), np.float32)
    it = mx.io.NDArrayIter(data, batch_size=4)
    desc = it.provide_data[0]
    assert tuple(desc.shape) == (4, 2, 3)
    assert desc.name == "data"


def test_native_recordio_backend_roundtrip(tmp_path):
    """When librecordio.so is built, MXRecordIO must use it and interop
    byte-for-byte with the pure-python writer."""
    from mxnet_tpu import _native
    if not _native.available():
        import pytest
        pytest.skip("native codec not built")
    # write native, read native
    p1 = str(tmp_path / "n.rec")
    w = mx.recordio.MXRecordIO(p1, "w")
    assert w._h is not None, "native writer not engaged"
    payloads = [os.urandom(n) for n in (1, 3, 4, 1000)]
    for b in payloads:
        w.write(b)
    w.close()
    r = mx.recordio.MXRecordIO(p1, "r")
    assert r._h is not None
    for b in payloads:
        assert r.read() == b
    assert r.read() is None
    r.close()
    # python-format file written earlier in this suite is identical format:
    # force the python writer and cross-read with native
    p2 = str(tmp_path / "py.rec")
    w2 = mx.recordio.MXRecordIO.__new__(mx.recordio.MXRecordIO)
    w2.uri, w2.flag, w2.is_open = p2, "w", False
    w2fd = open(p2, "wb")
    import struct as st
    for b in payloads:
        w2fd.write(st.pack("<II", 0xced7230a, len(b)))
        w2fd.write(b + b"\x00" * ((4 - len(b) % 4) % 4))
    w2fd.close()
    r2 = mx.recordio.MXRecordIO(p2, "r")
    for b in payloads:
        assert r2.read() == b
    r2.close()


def test_im2rec_cli(tmp_path):
    import subprocess
    binpath = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(mx.__file__))), "native", "bin", "im2rec")
    if not os.path.exists(binpath):
        import pytest
        pytest.skip("im2rec not built")
    for i in range(3):
        (tmp_path / ("f%d.bin" % i)).write_bytes(b"data%d" % i)
    lst = tmp_path / "d.lst"
    lst.write_text("".join("%d\t%.1f\tf%d.bin\n" % (i, i * 2.0, i)
                           for i in range(3)))
    subprocess.run([binpath, str(lst), str(tmp_path),
                    str(tmp_path / "out")], check=True,
                   capture_output=True)
    rec = mx.recordio.MXIndexedRecordIO(str(tmp_path / "out.idx"),
                                        str(tmp_path / "out.rec"), "r")
    h, blob = mx.recordio.unpack(rec.read_idx(1))
    assert float(np.asarray(h.label)) == 2.0
    assert blob == b"data1"
    rec.close()


def test_recordio_empty_record_not_eof(tmp_path):
    """A zero-length record must not truncate the stream (native + python)."""
    p = str(tmp_path / "e.rec")
    w = mx.recordio.MXRecordIO(p, "w")
    w.write(b"")
    w.write(b"after")
    w.close()
    r = mx.recordio.MXRecordIO(p, "r")
    assert r.read() == b""
    assert r.read() == b"after"
    assert r.read() is None
    r.close()


def test_recordio_bytearray_payload(tmp_path):
    p = str(tmp_path / "ba.rec")
    w = mx.recordio.MXRecordIO(p, "w")
    w.write(bytearray(b"abc"))
    w.close()
    r = mx.recordio.MXRecordIO(p, "r")
    assert r.read() == b"abc"
    r.close()


def test_libsvm_iter(tmp_path):
    """LibSVMIter parses 0-based idx:val lines into CSR batches
    (ref src/io/iter_libsvm.cc:200)."""
    import pytest
    from mxnet_tpu import io
    f = tmp_path / "train.libsvm"
    f.write_text("1 0:1.5 3:2.0\n"
                 "0 1:0.5\n"
                 "1 2:3.0 3:1.0\n"
                 "0 0:2.5\n")
    it = io.LibSVMIter(data_libsvm=str(f), data_shape=(4,), batch_size=2)
    batch = it.next()
    d = batch.data[0]
    assert d.stype == "csr"
    np.testing.assert_allclose(
        d.asnumpy(), [[1.5, 0, 0, 2.0], [0, 0.5, 0, 0]])
    np.testing.assert_allclose(batch.label[0].asnumpy(), [1.0, 0.0])
    batch2 = it.next()
    np.testing.assert_allclose(
        batch2.data[0].asnumpy(), [[0, 0, 3.0, 1.0], [2.5, 0, 0, 0]])
    with pytest.raises(StopIteration):
        it.next()


def test_rec2idx_tool_rebuilds_index(tmp_path):
    """tools/rec2idx.py regenerates an .idx equivalent to the one the
    indexed writer produced (ref tools/rec2idx.py)."""
    import runpy
    import sys as _sys
    from mxnet_tpu.recordio import MXIndexedRecordIO
    rec = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    w = MXIndexedRecordIO(idx, rec, "w")
    payloads = [bytes([i]) * (10 + i) for i in range(7)]
    for i, p in enumerate(payloads):
        w.write_idx(i, p)
    w.close()
    original = open(idx).read()
    rebuilt_path = str(tmp_path / "rebuilt.idx")
    argv = _sys.argv
    _sys.argv = ["rec2idx", rec, rebuilt_path]
    try:
        runpy.run_path(os.path.join(os.path.dirname(__file__), "..",
                                    "tools", "rec2idx.py"),
                       run_name="__main__")
    finally:
        _sys.argv = argv
    assert open(rebuilt_path).read() == original
    r = MXIndexedRecordIO(rebuilt_path, rec, "r")
    assert r.read_idx(3) == payloads[3]
    r.close()
