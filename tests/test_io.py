"""Data IO: NDArrayIter / CSVIter / ResizeIter / RecordIO round trips
(reference tests/python/unittest/test_io.py, test_recordio.py)."""
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_ndarrayiter_basic():
    data = np.arange(40, dtype=np.float32).reshape(10, 4)
    label = np.arange(10, dtype=np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=4, shuffle=False,
                           last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 4)
    assert batches[-1].pad == 2
    assert_almost_equal(batches[0].data[0].asnumpy(), data[:4])
    it.reset()
    assert len(list(it)) == 3


def test_ndarrayiter_discard_and_rollover():
    data = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = mx.io.NDArrayIter(data, batch_size=4,
                           last_batch_handle="discard")
    assert len(list(it)) == 2


def test_ndarrayiter_dict_data():
    data = {"a": np.zeros((6, 2), np.float32),
            "b": np.ones((6, 3), np.float32)}
    it = mx.io.NDArrayIter(data, batch_size=3)
    names = [d.name for d in it.provide_data]
    assert sorted(names) == ["a", "b"]
    b0 = next(iter(it))
    assert len(b0.data) == 2


def test_resize_iter():
    data = np.zeros((8, 2), np.float32)
    base = mx.io.NDArrayIter(data, batch_size=2)
    it = mx.io.ResizeIter(base, 2)
    assert len(list(it)) == 2


def test_csv_iter(tmp_path):
    path = str(tmp_path / "data.csv")
    arr = np.random.randint(0, 9, (12, 3)).astype(np.float32)
    np.savetxt(path, arr, delimiter=",", fmt="%g")
    it = mx.io.CSVIter(data_csv=path, data_shape=(3,), batch_size=4)
    got = np.concatenate([b.data[0].asnumpy() for b in it])
    assert_almost_equal(got[:12], arr, rtol=1e-5)


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "x.rec")
    rec = mx.recordio.MXRecordIO(path, "w")
    for i in range(5):
        rec.write(b"rec%d" % i)
    rec.close()
    rec = mx.recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert rec.read() == b"rec%d" % i
    assert rec.read() is None
    rec.close()


def test_indexed_recordio_seek(tmp_path):
    path = str(tmp_path / "y.rec")
    idx = str(tmp_path / "y.idx")
    rec = mx.recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(6):
        rec.write_idx(i, b"item%d" % i)
    rec.close()
    rec = mx.recordio.MXIndexedRecordIO(idx, path, "r")
    assert rec.read_idx(4) == b"item4"
    assert rec.read_idx(1) == b"item1"
    assert sorted(rec.keys) == list(range(6))
    rec.close()


def test_irheader_pack_unpack():
    header = mx.recordio.IRHeader(0, [1.0, 2.0], 7, 0)
    s = mx.recordio.pack(header, b"payload")
    h2, blob = mx.recordio.unpack(s)
    assert list(h2.label) == [1.0, 2.0]
    assert h2.id == 7
    assert blob == b"payload"


def test_pack_img_unpack_img(tmp_path):
    img = (np.random.rand(8, 8, 3) * 255).astype(np.uint8)
    header = mx.recordio.IRHeader(0, 3.0, 1, 0)
    s = mx.recordio.pack_img(header, img, quality=95, img_fmt=".png")
    h2, img2 = mx.recordio.unpack_img(s)
    assert float(np.asarray(h2.label)) == 3.0
    assert img2.shape == (8, 8, 3)
    assert np.abs(img2.astype(int) - img.astype(int)).mean() < 3


def test_dataiter_provide_semantics():
    data = np.zeros((8, 2, 3), np.float32)
    it = mx.io.NDArrayIter(data, batch_size=4)
    desc = it.provide_data[0]
    assert tuple(desc.shape) == (4, 2, 3)
    assert desc.name == "data"
