"""RCNN contrib op tests (reference src/operator/contrib/proposal*,
psroi_pooling, deformable_convolution)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_proposal_shapes_and_validity():
    np.random.seed(0)
    N, A, H, W = 2, 3, 4, 4
    cls = nd.array(np.random.rand(N, 2 * A, H, W).astype("float32"))
    bbox = nd.array((np.random.randn(N, 4 * A, H, W) * 0.1)
                    .astype("float32"))
    info = nd.array(np.array([[64, 64, 1.0], [64, 64, 1.0]], "float32"))
    rois = nd.contrib.Proposal(cls, bbox, info, rpn_pre_nms_top_n=20,
                               rpn_post_nms_top_n=6, feature_stride=16,
                               scales=(8,), ratios=(0.5, 1, 2))
    assert rois.shape == (N * 6, 5)
    r = rois.asnumpy()
    # batch indices: first 6 rows sample 0, next 6 sample 1
    assert set(r[:6, 0]) <= {0.0}
    assert set(r[6:, 0]) <= {1.0}
    # boxes clipped into the image
    assert r[:, 1:].min() >= 0.0 and r[:, 1:].max() <= 63.0


def test_multi_proposal_matches_proposal():
    np.random.seed(1)
    cls = nd.array(np.random.rand(1, 6, 3, 3).astype("float32"))
    bbox = nd.array((np.random.randn(1, 12, 3, 3) * 0.05).astype("float32"))
    info = nd.array(np.array([[48, 48, 1.0]], "float32"))
    kw = dict(rpn_pre_nms_top_n=10, rpn_post_nms_top_n=4,
              feature_stride=16, scales=(8,), ratios=(0.5, 1, 2))
    a = nd.contrib.Proposal(cls, bbox, info, **kw).asnumpy()
    b = nd.contrib.MultiProposal(cls, bbox, info, **kw).asnumpy()
    np.testing.assert_allclose(a, b)


def test_psroi_pooling_position_sensitivity():
    """Each output bin reads its own channel group: uniform per-channel
    planes make the expected output exactly the channel index pattern."""
    D, g = 1, 2
    C = D * g * g
    data = np.zeros((1, C, 8, 8), np.float32)
    for c in range(C):
        data[0, c] = c + 1                      # constant plane per channel
    rois = np.array([[0, 0, 0, 31, 31]], np.float32)
    out = nd.contrib.PSROIPooling(nd.array(data), nd.array(rois),
                                  spatial_scale=0.25, output_dim=D,
                                  pooled_size=2, group_size=g)
    got = out.asnumpy()[0, 0]
    # bin (i, j) reads channel i*g + j -> values [[1, 2], [3, 4]]
    np.testing.assert_allclose(got, [[1.0, 2.0], [3.0, 4.0]], atol=1e-5)


def test_deformable_conv_zero_offset_equals_conv():
    from mxnet_tpu.ops.rcnn import _deform_conv_one
    np.random.seed(2)
    img = jnp.asarray(np.random.rand(3, 6, 6), jnp.float32)
    wgt = jnp.asarray(np.random.rand(4, 3, 3, 3), jnp.float32)
    offs = jnp.zeros((2 * 1 * 3 * 3, 4, 4), jnp.float32)
    out = _deform_conv_one(img, offs, wgt, None, (3, 3), (1, 1), (0, 0),
                           (1, 1), 1)
    ref = lax.conv_general_dilated(img[None], wgt, (1, 1), "VALID")[0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_deformable_conv_op_with_shift():
    """Integer offset (0, 1) must equal convolving the x-shifted image."""
    from mxnet_tpu.ops.rcnn import _deform_conv_one
    np.random.seed(3)
    img_np = np.random.rand(1, 7, 7).astype(np.float32)
    img = jnp.asarray(img_np)
    wgt = jnp.asarray(np.random.rand(2, 1, 3, 3), jnp.float32)
    offs = np.zeros((2 * 9, 5, 5), np.float32)
    offs[1::2] = 1.0                           # dx = +1 everywhere
    out = _deform_conv_one(img, jnp.asarray(offs), wgt, None, (3, 3),
                           (1, 1), (0, 0), (1, 1), 1)
    shifted = np.zeros_like(img_np)
    shifted[:, :, :-1] = img_np[:, :, 1:]
    ref = lax.conv_general_dilated(jnp.asarray(shifted)[None], wgt,
                                   (1, 1), "VALID")[0]
    # interior columns agree exactly (border sees clamp-vs-zero padding)
    np.testing.assert_allclose(np.asarray(out)[:, :, :-1],
                               np.asarray(ref)[:, :, :-1], atol=1e-4)


def test_deformable_psroi_no_trans_matches_psroi():
    np.random.seed(4)
    D, g = 2, 2
    data = np.random.rand(1, D * g * g, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 0, 28, 28]], np.float32)
    a = nd.contrib.PSROIPooling(nd.array(data), nd.array(rois),
                                spatial_scale=0.25, output_dim=D,
                                pooled_size=2, group_size=g).asnumpy()
    b = nd.contrib.DeformablePSROIPooling(
        nd.array(data), nd.array(rois), spatial_scale=0.25, output_dim=D,
        pooled_size=2, group_size=g, no_trans=True).asnumpy()
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_deformable_conv_grouped():
    """num_group > 1 contracts each output group against its input slice."""
    from mxnet_tpu.ops.rcnn import _deform_conv_one
    np.random.seed(5)
    img = jnp.asarray(np.random.rand(4, 6, 6), jnp.float32)
    wgt = jnp.asarray(np.random.rand(4, 2, 3, 3), jnp.float32)  # groups=2
    offs = jnp.zeros((2 * 9, 4, 4), jnp.float32)
    out = _deform_conv_one(img, offs, wgt, None, (3, 3), (1, 1), (0, 0),
                           (1, 1), 1, num_group=2)
    ref = lax.conv_general_dilated(img[None], wgt, (1, 1), "VALID",
                                   feature_group_count=2)[0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)
