"""Fused Gluon Trainer step (one donated XLA program + bucketed
all-reduce) vs the per-slot loop oracle.

Contract (ISSUE 1): with MXNET_FUSED_TRAINER on (default) a
``Trainer.step`` issues O(1) + O(n_buckets) XLA program calls — gated at
<= 4 by the profiler counters on a >= 20-parameter model — and its
parameter/opt-state results are bitwise identical to the per-slot loop
(``MXNET_FUSED_TRAINER=0``).  Mirrors tests/test_cached_step.py for the
Module side.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, profiler
from mxnet_tpu.gluon import fused_trainer, nn


def _set_fused_env(value):
    """Set/unset MXNET_FUSED_TRAINER and refresh the import-time cached
    bool (the JG006 cached-value pattern) so the change takes effect."""
    if value is None:
        os.environ.pop("MXNET_FUSED_TRAINER", None)
    else:
        os.environ["MXNET_FUSED_TRAINER"] = value
    fused_trainer.refresh_from_env()


def _net(n_layers=3, width=8):
    net = nn.Sequential()
    for _ in range(n_layers - 1):
        net.add(nn.Dense(width, activation="relu"))
    net.add(nn.Dense(3))
    return net


def _train(optimizer, opt_params, fused, steps=4, n_layers=3, width=8,
           batch_size=16, kvstore="device", lr_schedule=None, seed=0):
    """Run a small regression net for *steps*; return params + states."""
    prev_env = os.environ.get("MXNET_FUSED_TRAINER")
    _set_fused_env("1" if fused else "0")
    try:
        np.random.seed(seed)
        mx.random.seed(seed)
        rng = np.random.RandomState(seed + 1)
        net = _net(n_layers, width)
        net.initialize(init=mx.initializer.Xavier())
        trainer = gluon.Trainer(net.collect_params(), optimizer,
                                dict(opt_params), kvstore=kvstore)
        loss_fn = gluon.loss.L2Loss()
        X = rng.randn(steps, batch_size, 6).astype(np.float32)
        Y = rng.randn(steps, batch_size, 3).astype(np.float32)
        for step in range(steps):
            if lr_schedule is not None:
                trainer.set_learning_rate(lr_schedule(step))
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(X[step])),
                               mx.nd.array(Y[step]))
            loss.backward()
            trainer.step(batch_size)
        # key by slot index: block name prefixes auto-number globally
        params = {i: p.data().asnumpy()
                  for i, p in enumerate(net.collect_params().values())}
        states = {}
        for idx, st in trainer._updater.states.items():
            leaves = []
            def _collect(s):
                if s is None:
                    leaves.append(None)
                elif isinstance(s, (tuple, list)):
                    for x in s:
                        _collect(x)
                else:
                    leaves.append(s.asnumpy())
            _collect(st)
            states[idx] = leaves
        return params, states, trainer
    finally:
        _set_fused_env(prev_env)


def _assert_bitwise(fast, slow, what):
    assert fast.keys() == slow.keys()
    for k in fast:
        f, s = fast[k], slow[k]
        if isinstance(f, list):
            for i, (a, b) in enumerate(zip(f, s)):
                if a is None:
                    assert b is None
                    continue
                np.testing.assert_array_equal(
                    a, b, err_msg="%s[%s][%d] not bitwise equal"
                    % (what, k, i))
        else:
            np.testing.assert_array_equal(
                f, s, err_msg="%s[%s] not bitwise equal" % (what, k))


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", (("learning_rate", 0.1), ("momentum", 0.9))),
    ("adam", (("learning_rate", 0.01),)),
    ("sgd", (("learning_rate", 0.05), ("momentum", 0.9), ("wd", 1e-3),
             ("rescale_grad", 0.5), ("clip_gradient", 0.1))),
    ("adam", (("learning_rate", 0.01), ("wd", 1e-4),
              ("rescale_grad", 2.0))),
    ("rmsprop", (("learning_rate", 0.01),)),
])
def test_fused_matches_loop_bitwise(optimizer, opt_params):
    fp, fs, _ = _train(optimizer, opt_params, fused=True)
    sp, ss, _ = _train(optimizer, opt_params, fused=False)
    _assert_bitwise(fp, sp, "param")
    _assert_bitwise(fs, ss, "state")


def test_fused_matches_loop_without_kvstore():
    fp, fs, _ = _train("sgd", (("learning_rate", 0.1), ("momentum", 0.9)),
                       fused=True, kvstore=None)
    sp, ss, _ = _train("sgd", (("learning_rate", 0.1), ("momentum", 0.9)),
                       fused=False, kvstore=None)
    _assert_bitwise(fp, sp, "param")
    _assert_bitwise(fs, ss, "state")


def test_no_retrace_across_lr_schedule():
    """A changing lr schedule (and the changing update counts t) must hit
    the ONE compiled step program — lr/wd/t enter as traced scalars
    (mirror of test_cached_step.py::test_no_retrace_across_steps)."""
    _, _, trainer = _train("adam", (("learning_rate", 0.01),), fused=True,
                           steps=5, lr_schedule=lambda s: 0.01 * 0.5 ** s)
    assert trainer._fused_step_jit._cache_size() == 1


def test_fused_program_call_count():
    """>= 20-parameter model, one step: <= 4 XLA program calls
    (ISSUE 1 acceptance gate, via the new profiler counters)."""
    prev_env = os.environ.get("MXNET_FUSED_TRAINER")
    _set_fused_env("1")
    try:
        np.random.seed(0)
        net = _net(n_layers=12, width=8)   # 12 Dense layers -> 24 params
        net.initialize(init=mx.initializer.Xavier())
        assert len(net.collect_params()) >= 20
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9})
        loss_fn = gluon.loss.L2Loss()
        x = mx.nd.array(np.random.randn(8, 6).astype(np.float32))
        y = mx.nd.array(np.random.randn(8, 3).astype(np.float32))

        def one_step():
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            before = profiler.counter("xla_program_calls")
            trainer.step(8)
            return profiler.counter("xla_program_calls") - before

        one_step()                      # warmup (compile)
        calls = one_step()              # steady state
        assert calls <= 4, "fused step issued %d program calls" % calls
        assert profiler.counter("trainer_fused_step") >= 2
    finally:
        _set_fused_env(prev_env)


def test_loop_program_call_count_is_per_slot():
    """The fallback loop really is O(n_params) — the collapse the fused
    path claims is measurable, not definitional."""
    prev_env = os.environ.get("MXNET_FUSED_TRAINER")
    _set_fused_env("0")
    try:
        np.random.seed(0)
        net = _net(n_layers=12, width=8)
        net.initialize(init=mx.initializer.Xavier())
        n_params = len(net.collect_params())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9})
        loss_fn = gluon.loss.L2Loss()
        x = mx.nd.array(np.random.randn(8, 6).astype(np.float32))
        y = mx.nd.array(np.random.randn(8, 3).astype(np.float32))
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        before = profiler.counter("xla_program_calls")
        trainer.step(8)
        delta = profiler.counter("xla_program_calls") - before
        assert delta >= n_params
    finally:
        _set_fused_env(prev_env)


def test_ignore_stale_grad():
    """Reference trainer.py:148 parity: a slot whose grad was not freshly
    written raises by default and is skipped with ignore_stale_grad."""
    np.random.seed(0)
    used = nn.Dense(4, in_units=6)
    used.initialize()
    unused = nn.Dense(4, in_units=6)
    unused.initialize()
    # force real (non-deferred) init of the unused branch
    unused(mx.nd.array(np.random.randn(2, 6).astype(np.float32)))
    params = list(used.collect_params().values()) \
        + list(unused.collect_params().values())
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": 0.1})

    x = mx.nd.array(np.random.randn(2, 6).astype(np.float32))
    with autograd.record():
        loss = (used(x) ** 2).sum()
    loss.backward()

    with pytest.raises(UserWarning):
        trainer.step(2)                     # unused branch is stale

    before = {p.name: p.data().asnumpy().copy() for p in params}
    trainer.step(2, ignore_stale_grad=True)
    for p in used.collect_params().values():
        assert np.abs(p.data().asnumpy() - before[p.name]).max() > 0, \
            "used parameter %s was not updated" % p.name
    for p in unused.collect_params().values():
        np.testing.assert_array_equal(
            p.data().asnumpy(), before[p.name],
            err_msg="stale parameter %s was updated" % p.name)

    # after a step every grad is stale again until the next backward
    with pytest.raises(UserWarning):
        trainer.step(2)


def test_stale_grad_loop_path_parity():
    """ignore_stale_grad behaves identically on the fallback loop."""
    prev_env = os.environ.get("MXNET_FUSED_TRAINER")
    _set_fused_env("0")
    try:
        test_ignore_stale_grad()
    finally:
        _set_fused_env(prev_env)


def test_loop_path_honors_hyper_mutation():
    """The jitted per-slot update bakes static hypers (clip_gradient,
    momentum) into the trace; mutating them mid-training must rebuild
    the program, not silently keep the stale constant."""
    import mxnet_tpu.optimizer as opt_mod
    from mxnet_tpu import nd
    opt = opt_mod.create("sgd", learning_rate=1.0)
    w = nd.array(np.zeros(4, np.float32))
    g = nd.array(np.full(4, 10.0, np.float32))
    opt.update(0, w, g, opt.create_state(0, w))
    np.testing.assert_allclose(w.asnumpy(), -10.0 * np.ones(4))
    opt.clip_gradient = 1.0            # mid-training mutation
    w2 = nd.array(np.zeros(4, np.float32))
    opt.update(1, w2, g, opt.create_state(1, w2))
    np.testing.assert_allclose(w2.asnumpy(), -1.0 * np.ones(4))


def _trajectory(fused, total_steps, reload_at=None, tmp_path=None):
    """Per-step losses of an adam run; optionally checkpoint the trainer
    via save_states/load_states into a FRESH trainer at *reload_at*."""
    prev_env = os.environ.get("MXNET_FUSED_TRAINER")
    _set_fused_env("1" if fused else "0")
    try:
        np.random.seed(0)
        mx.random.seed(0)
        rng = np.random.RandomState(1)
        X = rng.randn(total_steps, 8, 6).astype(np.float32)
        Y = rng.randn(total_steps, 8, 3).astype(np.float32)

        def fresh():
            net = _net(3, 8)
            net.initialize(init=mx.initializer.Xavier())
            tr = gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 0.05})
            return net, tr

        net, trainer = fresh()
        loss_fn = gluon.loss.L2Loss()
        losses = []
        for step in range(total_steps):
            if reload_at is not None and step == reload_at:
                fname = str(tmp_path / "trainer.states")
                trainer.save_states(fname)
                weights = [p.data().asnumpy()
                           for p in net.collect_params().values()]
                net, trainer = fresh()
                for p, w in zip(net.collect_params().values(), weights):
                    p.set_data(mx.nd.array(w))
                trainer.load_states(fname)
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(X[step])),
                               mx.nd.array(Y[step]))
            loss.backward()
            trainer.step(8)
            losses.append(float(np.float64(loss.asnumpy().sum())))
        return losses
    finally:
        _set_fused_env(prev_env)


def test_save_load_step_bitwise_roundtrip(tmp_path):
    """save_states → fresh trainer → load_states → step must continue
    the trajectory BITWISE for a t-dependent optimizer (adam): the
    serialized payload has to carry the fused-trainer step cache (the
    per-slot update counts feeding hyper['t']), not just the legacy
    ``_updater`` state trees.  Gated on both the fused path and the
    ``MXNET_FUSED_TRAINER=0`` oracle, which must agree with each other.
    """
    ref_by_path = {}
    for fused in (True, False):
        ref = _trajectory(fused, 5)
        resumed = _trajectory(fused, 5, reload_at=3, tmp_path=tmp_path)
        assert resumed == ref, \
            "save/load diverged the trajectory (fused=%s)" % fused
        ref_by_path[fused] = ref
    assert ref_by_path[True] == ref_by_path[False]


def test_load_states_accepts_legacy_blob(tmp_path):
    """Pre-versioning states files (a raw Updater.get_states pickle, no
    version marker) still load."""
    _, _, trainer = _train("sgd", (("learning_rate", 0.1),
                                   ("momentum", 0.9)), fused=True)
    f = str(tmp_path / "legacy.states")
    with open(f, "wb") as fh:
        fh.write(trainer._updater.get_states())
    _, _, fresh = _train("sgd", (("learning_rate", 0.1),
                                 ("momentum", 0.9)), fused=True, steps=1)
    fresh.load_states(f)
    for idx, st in trainer._updater.states.items():
        if st is None:
            continue
        np.testing.assert_array_equal(st.asnumpy(),
                                      fresh._updater.states[idx].asnumpy())


def test_fused_save_load_states_roundtrip(tmp_path):
    """Checkpointed Updater state written by the fused path loads into a
    fresh Trainer (same layout as the loop path)."""
    _, _, trainer = _train("sgd", (("learning_rate", 0.1),
                                   ("momentum", 0.9)), fused=True)
    f = str(tmp_path / "trainer.states")
    trainer.save_states(f)
    _, _, fresh = _train("sgd", (("learning_rate", 0.1),
                                 ("momentum", 0.9)), fused=True, steps=1)
    fresh.load_states(f)
    for idx, st in trainer._updater.states.items():
        if st is None:
            continue
        np.testing.assert_array_equal(st.asnumpy(),
                                      fresh._updater.states[idx].asnumpy())
