"""contrib package, torch bridge, tool scripts."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_contrib_autograd_old_api():
    x = nd.array(np.array([2.0, 3.0], np.float32))
    g = nd.zeros((2,))
    mx.contrib.autograd.mark_variables([x], [g])
    with mx.contrib.autograd.train_section():
        y = x * x
    mx.contrib.autograd.compute_gradient([y])
    np.testing.assert_allclose(g.asnumpy(), 2 * x.asnumpy())


def test_tensorboard_callback_jsonl(tmp_path):
    from mxnet_tpu.contrib.tensorboard import LogMetricsCallback, _JsonlWriter
    cb = LogMetricsCallback(str(tmp_path), prefix="train")
    cb._writer = _JsonlWriter(str(tmp_path))   # force the hermetic writer
    metric = mx.metric.Accuracy()
    metric.update([nd.array(np.array([1.0]))],
                  [nd.array(np.array([[0.2, 0.8]]))])
    from mxnet_tpu.model import BatchEndParam
    cb(BatchEndParam(epoch=0, nbatch=1, eval_metric=metric))
    rows = [json.loads(l) for l in
            open(tmp_path / "scalars.jsonl").read().splitlines()]
    assert rows and rows[0]["tag"] == "train-accuracy"
    assert rows[0]["value"] == 1.0


def test_torch_bridge_roundtrip():
    torch = pytest.importorskip("torch")
    x = nd.array(np.random.randn(3, 4).astype(np.float32))
    t = mx.torch.to_torch(x)
    assert torch.is_tensor(t)
    back = mx.torch.from_torch(t)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy())

    relu = mx.torch.pytorch_fn(torch.nn.functional.relu)
    y = relu(x)
    np.testing.assert_allclose(y.asnumpy(), np.maximum(x.asnumpy(), 0))


def test_parse_log_tool(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(
        "INFO:root:Epoch[0] Batch [50]\tSpeed: 1000.00 samples/sec\n"
        "INFO:root:Epoch[0] Train-accuracy=0.80\n"
        "INFO:root:Epoch[0] Time cost=1.500\n"
        "INFO:root:Epoch[0] Validation-accuracy=0.75\n"
        "INFO:root:Epoch[1] Train-accuracy=0.90\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         str(log), "--format", "csv"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    lines = out.stdout.strip().splitlines()
    assert lines[0].startswith("epoch,")
    assert "0.8" in lines[1] and "0.75" in lines[1]


def test_diagnose_tool():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "diagnose.py")],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0
    assert "mxnet_tpu" in out.stdout and "operators" in out.stdout
