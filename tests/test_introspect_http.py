"""Introspection HTTP server (ISSUE 4 tentpole 2): live /metrics,
/healthz, /snapshot, /trace, /flight, /stacks + the background sampler.

Acceptance contract: scrape /metrics and /healthz from the LIVE server
and parse them.
"""
import json
import re
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, telemetry
from mxnet_tpu.telemetry import flight, server


@pytest.fixture
def live_server(monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    telemetry.refresh_from_env()
    telemetry.reset()
    srv = server.start_server(port=0, sample_ms=100)
    yield srv
    server.stop_server()
    telemetry.reset()
    monkeypatch.delenv("MXNET_TELEMETRY", raising=False)
    telemetry.refresh_from_env()


def _get(srv, path):
    url = "http://127.0.0.1:%d%s" % (srv.port, path)
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type"), resp.read()


# one sample line: name{labels} value  |  name value
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE+.\-]+|inf$')


def test_metrics_scrape_parses(live_server):
    telemetry.bump("xla_program_calls", 7)
    telemetry.set_gauge("io_batch_wait_us", 42.0)
    telemetry.observe("step_time_us", 1234.0)

    status, ctype, body = _get(live_server, "/metrics")
    assert status == 200
    assert ctype.startswith("text/plain")
    text = body.decode()
    assert "xla_program_calls 7" in text
    # every non-comment line is a well-formed sample — the exposition
    # format promise /metrics makes to a Prometheus scraper
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert line.startswith(("# HELP ", "# TYPE "))
            assert "\n" not in line
        else:
            assert _SAMPLE_RE.match(line), "unparseable: %r" % line
    assert 'step_time_us_bucket{le="+Inf"} 1' in text


def test_healthz_healthy_and_unhealthy(live_server):
    status, _, body = _get(live_server, "/healthz")
    health = json.loads(body)
    assert status == 200
    assert health["ok"] is True
    assert health["steps"]["count"] == 0
    assert health["steps"]["stalled"] is False
    assert health["retrace_storms"] == 0
    assert health["sanitizer_violations"] == 0

    telemetry.bump("sanitizer_violations")     # a footgun fired
    try:
        with pytest.raises(urllib.error.HTTPError) as einfo:
            _get(live_server, "/healthz")
        assert einfo.value.code == 503
        sick = json.loads(einfo.value.read())
        assert sick["ok"] is False
        assert sick["sanitizer_violations"] == 1
    finally:
        telemetry.reset_counters()


def test_snapshot_trace_flight_stacks_endpoints(live_server):
    with telemetry.span("http_step", cat="step"):
        a = nd.array(np.ones((4, 4), np.float32))
        nd.dot(a, a).wait_to_read()

    status, _, body = _get(live_server, "/snapshot")
    snap = json.loads(body)
    assert status == 200 and snap["enabled"] is True
    assert "costs" in snap and "counters" in snap

    status, _, body = _get(live_server, "/trace")
    trace = json.loads(body)
    assert any(e.get("name") == "http_step"
               for e in trace["traceEvents"])

    status, _, body = _get(live_server, "/flight")
    fl = json.loads(body)
    assert fl["reason"] == "http"
    assert any(e["name"] == "http_step" for e in fl["ring"])
    assert any(k.startswith("MainThread") for k in fl["stacks"])

    status, ctype, body = _get(live_server, "/stacks")
    assert status == 200 and ctype.startswith("text/plain")
    assert b"MainThread" in body and b"File" in body

    with pytest.raises(urllib.error.HTTPError) as einfo:
        _get(live_server, "/no_such")
    assert einfo.value.code == 404


def test_checkpoints_endpoint(live_server):
    # mxnet_tpu.checkpoint is imported with the package, so the endpoint
    # answers the inactive stub (or the live manager when one exists)
    status, ctype, body = _get(live_server, "/checkpoints")
    assert status == 200 and ctype == "application/json"
    view = json.loads(body)
    assert "checkpoints" in view and "active" in view


def test_sampler_feeds_engine_and_step_rate_gauges(live_server):
    from mxnet_tpu import engine
    eng = engine.engine()
    var = eng.new_variable()
    eng.push(lambda: None, mutable_vars=(var,))
    eng.wait_for_all()

    with telemetry.span("rate_step", cat="step"):
        pass
    state = server.sample_once((flight.step_count() - 1, 0.0))
    gauges = telemetry.snapshot()["gauges"]
    assert "engine_pending_tasks" in gauges     # wired, not test-only
    assert gauges["engine_pending_tasks"] == 0  # drained
    assert gauges["step_rate_per_s"] > 0        # 1 step since prev tick
    assert state[0] == flight.step_count()


def test_step_exit_samples_engine_backlog(live_server):
    """Satellite: engine_pending_tasks is refreshed at step-span exits,
    not only by the sampler thread."""
    from mxnet_tpu import engine
    engine.engine()                             # singleton exists
    telemetry.snapshot()
    with telemetry.span("exit_step", cat="step"):
        pass
    assert "engine_pending_tasks" in telemetry.snapshot()["gauges"]


def test_start_from_env_no_op_without_gate(monkeypatch):
    monkeypatch.delenv("MXNET_TELEMETRY_HTTP", raising=False)
    assert server.start_from_env() is None
