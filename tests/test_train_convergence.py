"""Small end-to-end convergence gates.

Reference analogue: ``tests/python/train/`` (test_mlp.py, test_conv.py,
test_bucketing.py) — train tiny models to an accuracy/perplexity threshold
as integration tests (SURVEY §4 testing doctrine, tier 4).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.test_utils import get_mnist_iterator


def test_mlp_mnist_module_fit():
    """MLP through Module.fit reaches >=97% validation accuracy
    (ref tests/python/train/test_mlp.py threshold)."""
    np.random.seed(0)
    mx.random.seed(0)
    train_iter, val_iter = get_mnist_iterator(batch_size=64, flat=True)

    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=64, name="fc2")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc3")
    net = mx.sym.SoftmaxOutput(h, name="softmax")

    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(train_iter, num_epoch=3, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    acc = mod.score(val_iter, "acc")[0][1]
    assert acc >= 0.97, "MLP validation accuracy %.4f < 0.97" % acc


def test_conv_gluon_trainer():
    """Small conv net via Gluon Trainer converges
    (ref tests/python/train/test_conv.py)."""
    np.random.seed(0)
    mx.random.seed(0)
    train_iter, val_iter = get_mnist_iterator(batch_size=64, flat=False)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=3, activation="relu"))
    net.add(gluon.nn.MaxPool2D(2))
    net.add(gluon.nn.Conv2D(16, kernel_size=3, activation="relu"))
    net.add(gluon.nn.MaxPool2D(2))
    net.add(gluon.nn.Flatten())
    net.add(gluon.nn.Dense(10))
    net.collect_params().initialize(mx.init.Xavier())
    net.hybridize()

    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.005})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(2):
        train_iter.reset()
        for batch in train_iter:
            with autograd.record():
                out = net(batch.data[0])
                loss = loss_fn(out, batch.label[0])
            loss.backward()
            trainer.step(batch.data[0].shape[0])

    metric = mx.metric.Accuracy()
    val_iter.reset()
    for batch in val_iter:
        metric.update([batch.label[0]], [net(batch.data[0])])
    acc = metric.get()[1]
    assert acc >= 0.95, "conv validation accuracy %.4f < 0.95" % acc


def test_lstm_bucketing_convergence():
    """BucketingModule + symbolic LSTM drives perplexity far below the
    uniform baseline (ref tests/python/train/test_bucketing.py)."""
    np.random.seed(0)
    mx.random.seed(0)
    vocab = 21
    sents = []
    rng = np.random.RandomState(5)
    for _ in range(300):
        length = rng.randint(4, 17)
        start = rng.randint(1, vocab - 1)
        sents.append([(start + t) % (vocab - 1) + 1 for t in range(length)])
    it = mx.rnn.BucketSentenceIter(sents, batch_size=16, buckets=[8, 12, 16],
                                   invalid_label=0)

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=16,
                                 name="embed")
        cell = mx.rnn.LSTMCell(num_hidden=32, prefix="lstm_")
        outputs, _ = cell.unroll(seq_len, inputs=embed, merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, 32))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
        lab = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, lab, use_ignore=True,
                                    ignore_label=0, name="softmax")
        return pred, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=it.default_bucket_key,
                                 context=mx.cpu())
    mod.fit(it, eval_metric=mx.metric.Perplexity(ignore_label=0),
            num_epoch=6, optimizer="adam",
            optimizer_params={"learning_rate": 0.01})
    it.reset()
    ppl = mod.score(it, mx.metric.Perplexity(ignore_label=0))[0][1]
    # deterministic next-token corpus: uniform baseline is ~vocab (21)
    assert ppl < 5.0, "perplexity %.2f not < 5.0" % ppl


def test_mlp_bf16_converges():
    """bf16 training reaches accuracy parity with fp32 on the MNIST MLP
    (ref tests/python/train/test_dtype.py — dtype sweeps as convergence
    gates; bf16 replaces fp16 as the TPU compute dtype)."""
    np.random.seed(0)
    mx.random.seed(0)
    train_iter, val_iter = get_mnist_iterator(batch_size=64, flat=True)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(128, activation="relu"))
    net.add(gluon.nn.Dense(64, activation="relu"))
    net.add(gluon.nn.Dense(10))
    net.collect_params().initialize(mx.init.Xavier())
    net.cast("bfloat16")
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(2):
        train_iter.reset()
        for batch in train_iter:
            x = batch.data[0].astype("bfloat16")
            y = batch.label[0]
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(x.shape[0])
    correct = total = 0
    val_iter.reset()
    for batch in val_iter:
        out = net(batch.data[0].astype("bfloat16")).asnumpy()
        correct += (out.argmax(1) == batch.label[0].asnumpy()).sum()
        total += out.shape[0]
    acc = correct / total
    assert acc >= 0.95, "bf16 MLP accuracy %.4f < 0.95" % acc
