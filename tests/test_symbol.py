"""Symbol composition/inference/serialization tests (modeled on reference
tests/python/unittest/{test_symbol,test_infer_shape}.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.base import MXNetError


def _mlp():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu0")
    net = sym.FullyConnected(net, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def test_compose_and_listing():
    net = _mlp()
    assert net.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                    "fc2_weight", "fc2_bias", "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]


def test_auto_variable_creation():
    d = sym.var("x")
    c = sym.Convolution(d, kernel=(3, 3), num_filter=4, name="conv0")
    assert "conv0_weight" in c.list_arguments()
    assert "conv0_bias" in c.list_arguments()
    c2 = sym.Convolution(d, kernel=(3, 3), num_filter=4, no_bias=True,
                         name="c2")
    assert "c2_bias" not in c2.list_arguments()


def test_infer_shape_mlp():
    net = _mlp()
    a, o, x = net.infer_shape(data=(32, 100))
    args = dict(zip(net.list_arguments(), a))
    assert args["fc1_weight"] == (16, 100)
    assert args["fc1_bias"] == (16,)
    assert args["fc2_weight"] == (10, 16)
    assert o == [(32, 10)]


def test_infer_shape_conv_bn():
    d = sym.var("data")
    net = sym.Convolution(d, kernel=(3, 3), num_filter=8, pad=(1, 1),
                          stride=(2, 2), name="conv")
    net = sym.BatchNorm(net, name="bn")
    a, o, x = net.infer_shape(data=(2, 3, 32, 32))
    args = dict(zip(net.list_arguments(), a))
    auxs = dict(zip(net.list_auxiliary_states(), x))
    assert args["conv_weight"] == (8, 3, 3, 3)
    assert args["bn_gamma"] == (8,)
    assert auxs["bn_moving_mean"] == (8,)
    assert o == [(2, 8, 16, 16)]
    assert net.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]


def test_infer_type():
    d = sym.var("data")
    net = sym.FullyConnected(d, num_hidden=4)
    a, o, x = net.infer_type(data=np.float32)
    assert all(t == np.float32 for t in a)


def test_symbol_arith_operators():
    a, b = sym.var("a"), sym.var("b")
    c = 2 * a + b ** 2 - 3 / b
    args = sorted(c.list_arguments())
    assert args == ["a", "b"]
    ash, osh, _ = c.infer_shape(a=(2, 2), b=(2, 2))
    assert osh == [(2, 2)]


def test_group_and_getitem():
    a, b = sym.var("a"), sym.var("b")
    g = sym.Group([a + b, a * b])
    assert len(g.list_outputs()) == 2
    first = g[0]
    assert len(first.list_outputs()) == 1


def test_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    back = sym.load_json(js)
    assert back.list_arguments() == net.list_arguments()
    assert back.list_outputs() == net.list_outputs()
    assert back.list_auxiliary_states() == net.list_auxiliary_states()
    # attrs survive
    a1, o1, _ = back.infer_shape(data=(4, 50))
    a2, o2, _ = net.infer_shape(data=(4, 50))
    assert o1 == o2 and a1 == a2


def test_json_file_roundtrip(tmp_path):
    net = _mlp()
    f = str(tmp_path / "m-symbol.json")
    net.save(f)
    assert sym.load(f).tojson() == net.tojson()


def test_get_internals():
    net = _mlp()
    internals = net.get_internals()
    assert "fc1_output" in internals.list_outputs()
    fc1 = internals["fc1_output"]
    assert fc1.list_arguments() == ["data", "fc1_weight", "fc1_bias"]


def test_multi_output_split():
    d = sym.var("data")
    s = sym.SliceChannel(d, num_outputs=3, name="split")
    assert s.num_outputs == 3
    assert s.list_outputs() == ["split_output0", "split_output1",
                                "split_output2"]
    one = s[1]
    a, o, _ = one.infer_shape(data=(2, 6))
    assert o == [(2, 2)]


def test_attr_scope_and_var_attrs():
    with mx.AttrScope(ctx_group="dev1"):
        v = sym.var("w")
    assert v.attr("ctx_group") == "dev1"
    v2 = sym.var("x", shape=(3, 4), lr_mult=2.0)
    a, o, _ = v2.infer_shape()
    assert o == [(3, 4)]


def test_name_uniqueness():
    d = sym.var("d")
    c1 = sym.FullyConnected(d, num_hidden=2)
    c2 = sym.FullyConnected(d, num_hidden=2)
    assert c1.name != c2.name


def test_infer_shape_error_message():
    d = sym.var("data")
    net = sym.FullyConnected(d, num_hidden=4)
    with pytest.raises(MXNetError):
        net.infer_shape()  # no shapes at all


def test_debug_str_lists_graph():
    """Symbol.debug_str dumps every node with its wiring (ref
    symbol.debug_str / GraphExecutor::Print introspection)."""
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    out = mx.sym.Activation(h, act_type="relu", name="act1")
    s = out.debug_str()
    assert "Variable:data" in s
    assert "Op:FullyConnected, Name=fc1" in s
    assert "Op:Activation, Name=act1" in s
    assert "act_type=relu" in s
    # positional wiring: FC's three inputs get distinct arg slots
    fc_block = s.split("Op:FullyConnected")[1].split("---")[0]
    assert "arg[0]=data" in fc_block
    assert "arg[1]=fc1_weight" in fc_block
    assert "arg[2]=fc1_bias" in fc_block
    # grouped outputs are numbered by position, not producer out-index
    g = mx.sym.Group([h, out]).debug_str()
    assert "output[0]=fc1_output" in g and "output[1]=act1_output" in g


def test_one_element_tuple_attr_roundtrip():
    """attr stringify: 1-tuples must survive JSON ("(64,)" not "(64)",
    which parses back as int); old files with the bare form still load."""
    from mxnet_tpu.ops.registry import attr_to_string, parse_attr_string
    assert attr_to_string((64,)) == "(64,)"
    assert parse_attr_string("(64,)") == (64,)
    s = sym.Variable("w", shape=(64,))
    loaded = sym.load_json(s.tojson())
    a, _, _ = loaded.infer_shape()
    assert a[0] == (64,)
    # legacy bare-int form still infers
    import json as _json
    g = _json.loads(s.tojson())
    for n in g["nodes"]:
        if n["name"] == "w":
            n["attrs"]["__shape__"] = "(64)"
    legacy = sym.load_json(_json.dumps(g))
    a, _, _ = legacy.infer_shape()
    assert a[0] == (64,)
