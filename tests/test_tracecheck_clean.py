"""Tier-1 gate: the programs the framework ships are JX-clean.

Lowers EVERY owned jit entry point AOT on CPU (ShapeDtypeStruct
specimens, nothing executed) and fails on any JX finding — the trace-tier
twin of tests/test_lint_clean.py.  A provider that cannot build or trace
its program surfaces as a JX000 finding rather than silently shrinking
coverage, and the coverage list itself is asserted so removing an entry
point from the driver (instead of migrating it) also fails.
"""
import os
import subprocess
import sys

from mxnet_tpu.lint import tracecheck

# every program the framework owns, by watch_jit/driver name; growing the
# framework's jit surface means growing BOTH tracecheck.ENTRY_POINTS and
# this list (ISSUE 5 acceptance: coverage is part of the contract)
OWNED_PROGRAMS = {
    "executor_eval",
    "executor_train",
    "executor_fwd_vjp",
    "executor_bwd",
    "executor_fwd_bwd_ones",
    "executor_fwd_bwd",
    "fused_trainer_step",
    "fused_trainer_step_guarded",
    "fused_trainer_step_zero1",
    "fused_trainer_step_zero1_guarded",
    # MXNET_MODEL_STATS: the health side-output composed onto every
    # fused path, plus the oracle loop's one extra program (ISSUE 17)
    "fused_trainer_step_stats",
    "fused_trainer_step_guarded_stats",
    "fused_trainer_step_zero1_stats",
    "fused_trainer_step_zero1_guarded_stats",
    "model_stats",
    "gluon_cached_op",
    "guardian_verdict",
    "clip_global_norm",
    "kvstore_stack_sum",
    "kvstore_bucket_reduce",
    "collective_chunk_sum",
    "collective_chunk_write",
    "module_cached_step",
    "optimizer_update_step",
    "predictor_forward",
    "serving_predict",
    # the SPMD tier (PR 16: one mesh substrate under models/parallel)
    "pipeline_apply",
    "ring_attention",
    "sharded_train_step",
    "sharded_forward",
    "transformer_train_step",
    "transformer_train_step_zero1",
}


def test_owned_programs_are_jx_clean():
    findings, names = tracecheck.check_entry_points()
    assert not findings, (
        "trace-tier findings in shipped programs (fix the program — the "
        "JX baseline is reserved for justified legacy entries):\n"
        + "\n".join(f.format_text() for f in findings))
    missing = OWNED_PROGRAMS - set(names)
    assert not missing, (
        "owned entry points not analyzed (provider lost or renamed): %s"
        % sorted(missing))


def test_zero1_step_is_jx102_clean_at_one_device():
    """The int64 position findings on ``transformer_train_step_zero1``
    (the 6 burned down in ISSUE 20) only reproduce at n_devices=1, where
    the ring shard collapses onto the ``local_attention`` path — and the
    tier-1 rig above forces 8 devices, so the main gate never sees that
    topology.  Pin the 1-device sweep in a subprocess."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.lint", "--trace", "--no-memory",
         "--select", "JX102", "--no-baseline", "transformer"],
        capture_output=True, text=True, env=env, cwd=repo, timeout=600)
    assert out.returncode == 0, (
        "JX102 findings in the 1-device transformer sweep:\n"
        + out.stdout + out.stderr)
