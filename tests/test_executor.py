"""Executor tests (modeled on reference tests/python/unittest/test_executor.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd


def test_bind_forward_matches_numpy():
    a = sym.var("a")
    b = sym.var("b")
    c = a + b * 2
    ex = c.bind(mx.cpu(), {"a": nd.array([1., 2.]), "b": nd.array([3., 4.])})
    out = ex.forward()
    np.testing.assert_allclose(out[0].asnumpy(), [7., 10.])


def test_backward_grads():
    x = sym.var("x")
    y = sym.var("y")
    z = x * y + sym.square(x)
    xg, yg = nd.zeros((3,)), nd.zeros((3,))
    ex = z.bind(mx.cpu(), {"x": nd.array([1., 2., 3.]),
                           "y": nd.array([4., 5., 6.])},
                args_grad={"x": xg, "y": yg})
    ex.forward(is_train=True)
    ex.backward(nd.ones((3,)))
    np.testing.assert_allclose(xg.asnumpy(), [4 + 2, 5 + 4, 6 + 6])
    np.testing.assert_allclose(yg.asnumpy(), [1., 2., 3.])


def test_grad_req_add_and_null():
    x = sym.var("x")
    z = sym.sum(sym.square(x))
    xg = nd.zeros((2,))
    ex = z.bind(mx.cpu(), {"x": nd.array([1., 2.])}, args_grad={"x": xg},
                grad_req="add")
    for _ in range(3):
        ex.forward(is_train=True)
        ex.backward()
    np.testing.assert_allclose(xg.asnumpy(), 3 * 2 * np.array([1., 2.]))


def test_simple_bind_infers_params():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=5, name="fc")
    ex = net.simple_bind(mx.cpu(), data=(7, 3))
    assert ex.arg_dict["fc_weight"].shape == (5, 3)
    assert ex.arg_dict["fc_bias"].shape == (5,)
    assert ex.grad_dict["fc_weight"].shape == (5, 3)


def test_forward_kwargs_update_args():
    data = sym.var("data")
    out = sym.square(data)
    ex = out.simple_bind(mx.cpu(), data=(2, 2))
    r1 = ex.forward(data=np.full((2, 2), 3.0, np.float32))
    np.testing.assert_allclose(r1[0].asnumpy(), 9 * np.ones((2, 2)))


def test_aux_state_update_only_in_train():
    d = sym.var("d")
    bn = sym.BatchNorm(d, name="bn", momentum=0.0, fix_gamma=True)
    net = sym.sum(bn)
    ex = net.simple_bind(mx.cpu(), d=(16, 4))
    ex.aux_dict["bn_moving_var"][:] = 1.0
    x = np.random.RandomState(0).rand(16, 4).astype(np.float32) * 5
    mm_before = ex.aux_dict["bn_moving_mean"].asnumpy().copy()
    ex.forward(is_train=False, d=x)
    np.testing.assert_allclose(ex.aux_dict["bn_moving_mean"].asnumpy(),
                               mm_before)
    ex.forward(is_train=True, d=x)
    # momentum=0 -> moving_mean == batch mean
    np.testing.assert_allclose(ex.aux_dict["bn_moving_mean"].asnumpy(),
                               x.mean(axis=0), rtol=1e-5)


def test_copy_params_from_and_outputs_dict():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=2, name="fc")
    ex = net.simple_bind(mx.cpu(), data=(1, 2))
    ex.copy_params_from({"fc_weight": nd.array([[1., 0.], [0., 1.]]),
                         "fc_bias": nd.array([1., 1.])})
    out = ex.forward(data=np.array([[2., 3.]], np.float32))
    np.testing.assert_allclose(out[0].asnumpy(), [[3., 4.]])
    assert "fc_output" in ex.output_dict


def test_monitor_sees_intermediates():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=2, name="fc")
    net = sym.Activation(net, act_type="relu", name="act")
    ex = net.simple_bind(mx.cpu(), data=(1, 2))
    seen = []
    ex.set_monitor_callback(lambda name, arr: seen.append(name))
    ex.forward(is_train=False, data=np.ones((1, 2), np.float32))
    assert any("fc_output" in s for s in seen)
    assert any("act_output" in s for s in seen)


def test_reshape():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=4, name="fc")
    ex = net.simple_bind(mx.cpu(), data=(2, 6))
    ex.arg_dict["fc_weight"][:] = 1.0
    ex2 = ex.reshape(data=(5, 6))
    assert ex2.arg_dict["data"].shape == (5, 6)
    np.testing.assert_allclose(ex2.arg_dict["fc_weight"].asnumpy(),
                               np.ones((4, 6)))


def test_dropout_train_vs_eval_in_graph():
    d = sym.var("d")
    net = sym.Dropout(d, p=0.5)
    ex = net.simple_bind(mx.cpu(), d=(100, 100))
    x = np.ones((100, 100), np.float32)
    out_eval = ex.forward(is_train=False, d=x)[0].asnumpy()
    np.testing.assert_allclose(out_eval, x)
    out_train = ex.forward(is_train=True, d=x)[0].asnumpy()
    assert 0.3 < (out_train == 0).mean() < 0.7


def test_group2ctx_places_nodes():
    """Manual model parallelism: __ctx_group__ attrs + group2ctx place
    each group's compute on its context (ref graph_executor.cc:403)."""
    import jax
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs 2 devices")
    data = mx.sym.Variable("data")
    with mx.AttrScope(ctx_group="dev1"):
        h = mx.sym.FullyConnected(data, num_hidden=8, name="g1fc")
        h = mx.sym.Activation(h, act_type="tanh")
    with mx.AttrScope(ctx_group="dev2"):
        out = mx.sym.FullyConnected(h, num_hidden=4, name="g2fc")

    g2c = {"dev1": mx.cpu(0), "dev2": mx.cpu(1)}
    x = mx.nd.array(np.random.randn(2, 6).astype(np.float32))
    args = {"data": x}
    for name, shape in zip(out.list_arguments(),
                           out.infer_shape(data=(2, 6))[0]):
        if name != "data":
            args[name] = mx.nd.array(
                np.random.randn(*shape).astype(np.float32) * 0.1)
    exe = out.bind(mx.cpu(0), args, group2ctx=g2c)
    y = exe.forward()[0]
    assert y.shape == (2, 4)

    # numerics match the ungrouped single-device bind
    exe2 = out.bind(mx.cpu(0), args)
    y2 = exe2.forward()[0]
    np.testing.assert_allclose(y.asnumpy(), y2.asnumpy(), rtol=1e-5)

    # backward works through the grouped path
    grads = {n: mx.nd.zeros(a.shape) for n, a in args.items()
             if n != "data"}
    exe3 = out.bind(mx.cpu(0), args, args_grad=grads, group2ctx=g2c)
    exe3.forward(is_train=True)
    exe3.backward(out_grads=mx.nd.ones((2, 4)))
    assert float(np.abs(grads["g1fc_weight"].asnumpy()).sum()) > 0
