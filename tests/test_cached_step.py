"""Cached train-step guarantees (VERDICT r3 #2).

The reference's contract after bind is zero per-step graph work
(``graph_executor.cc:1403`` RunOps only pushes cached engine ops). The
TPU analogue: a bound executor compiles its train-forward, backward, and
fused fwd+bwd programs ONCE and every later step is a cache hit — no
Python-level retracing, no relinearisation.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu import symbol as sym_api


def _mlp():
    x = sym_api.Variable("data")
    w1 = sym_api.Variable("w1")
    w2 = sym_api.Variable("w2")
    h = sym_api.relu(sym_api.dot(x, w1))
    y = sym_api.dot(h, w2)
    label = sym_api.Variable("softmax_label")
    return sym_api.SoftmaxOutput(y, label, name="softmax")


def _bind(s, bs=4):
    return s.simple_bind(mx.cpu(), grad_req="write",
                         data=(bs, 6), w1=(6, 8), w2=(8, 3),
                         softmax_label=(bs,))


def test_no_retrace_across_steps():
    ex = _bind(_mlp())
    rng = np.random.RandomState(0)
    for step in range(4):
        ex.forward(is_train=True,
                   data=nd.array(rng.randn(4, 6)),
                   softmax_label=nd.array(rng.randint(0, 3, (4,))))
        ex.backward()
    # one compiled program per leg, regardless of step count
    assert ex._fwd_train_jit._cache_size() == 1
    assert ex._bwd_jit._cache_size() == 1


def test_fused_forward_backward_matches_two_call():
    rng = np.random.RandomState(1)
    data = nd.array(rng.randn(4, 6))
    label = nd.array(rng.randint(0, 3, (4,)))
    w1 = rng.randn(6, 8) * 0.1
    w2 = rng.randn(8, 3) * 0.1

    ex_a = _bind(_mlp())
    ex_b = _bind(_mlp())
    for ex in (ex_a, ex_b):
        ex.arg_dict["w1"][:] = w1
        ex.arg_dict["w2"][:] = w2

    mx.random.seed(7)
    ex_a.forward(is_train=True, data=data, softmax_label=label)
    ex_a.backward()
    mx.random.seed(7)
    ex_b.forward_backward(data=data, softmax_label=label)

    np.testing.assert_allclose(ex_a.outputs[0].asnumpy(),
                               ex_b.outputs[0].asnumpy(), rtol=1e-6)
    for n in ("w1", "w2"):
        np.testing.assert_allclose(ex_a.grad_dict[n].asnumpy(),
                                   ex_b.grad_dict[n].asnumpy(),
                                   rtol=1e-5, atol=1e-6)
    assert ex_b._fwd_bwd_ones_jit._cache_size() == 1


def test_fused_forward_backward_explicit_out_grads():
    rng = np.random.RandomState(2)
    x = sym_api.Variable("data")
    w = sym_api.Variable("w")
    y = sym_api.dot(x, w)
    ex_a = y.simple_bind(mx.cpu(), grad_req="write", data=(3, 5), w=(5, 2))
    ex_b = y.simple_bind(mx.cpu(), grad_req="write", data=(3, 5), w=(5, 2))
    data = nd.array(rng.randn(3, 5))
    wv = rng.randn(5, 2)
    og = nd.array(rng.randn(3, 2))
    for ex in (ex_a, ex_b):
        ex.arg_dict["w"][:] = wv
    ex_a.forward(is_train=True, data=data)
    ex_a.backward([og])
    ex_b.forward_backward(out_grads=[og], data=data)
    np.testing.assert_allclose(ex_a.grad_dict["w"].asnumpy(),
                               ex_b.grad_dict["w"].asnumpy(), rtol=1e-6)


def test_grad_req_add_accumulates_in_fused_path():
    rng = np.random.RandomState(3)
    x = sym_api.Variable("data")
    w = sym_api.Variable("w")
    y = sym_api.sum(sym_api.dot(x, w))
    ex = y.simple_bind(mx.cpu(), grad_req="add", data=(2, 4), w=(4, 3))
    data = nd.array(rng.randn(2, 4))
    ex.arg_dict["w"][:] = rng.randn(4, 3)
    ex.grad_dict["w"][:] = 0
    ex.forward_backward(data=data)
    once = ex.grad_dict["w"].asnumpy().copy()
    ex.forward_backward(data=data)
    np.testing.assert_allclose(ex.grad_dict["w"].asnumpy(), 2 * once,
                               rtol=1e-6)


def _small_net():
    net = sym_api.FullyConnected(sym_api.Variable("data"), num_hidden=8,
                                 name="fc1")
    net = sym_api.Activation(net, act_type="relu", name="relu1")
    net = sym_api.FullyConnected(net, num_hidden=3, name="fc2")
    return sym_api.SoftmaxOutput(net, sym_api.Variable("softmax_label"),
                                 name="softmax")


def _fit_module(it, optimizer="sgd", opt_params=(("learning_rate", 0.1),
                                                 ("momentum", 0.9)),
                num_epoch=2):
    from mxnet_tpu.module import Module
    it.reset()
    mod = Module(_small_net(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier(rnd_type="uniform",
                                                      magnitude=2.0))
    mod.init_optimizer(optimizer=optimizer, optimizer_params=opt_params)
    mod.fit(it, num_epoch=num_epoch)
    return mod


def _data_iter(seed=4):
    from mxnet_tpu.io import NDArrayIter
    rng = np.random.RandomState(seed)
    X = rng.randn(32, 6).astype(np.float32)
    Y = rng.randint(0, 3, (32,)).astype(np.float32)
    return NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")


def test_module_fit_uses_one_donated_program():
    it = _data_iter()
    mod = _fit_module(it)
    step = mod._cached_step
    assert step is not None, "fit did not take the fused-step fast path"
    assert step._step_jit._cache_size() == 1
    ex = mod._exec_group.execs[0]
    # the split-leg programs were never needed during fit
    assert ex._fwd_train_jit._cache_size() == 0
    assert ex._fwd_bwd_ones_jit._cache_size() == 0


def test_module_fused_step_matches_slow_path():
    import os
    for optimizer, params in (
            ("sgd", (("learning_rate", 0.1), ("momentum", 0.9))),
            ("adam", (("learning_rate", 0.01),))):
        it = _data_iter()
        np.random.seed(0); mx.random.seed(0)
        fast = _fit_module(it, optimizer, params)
        os.environ["MXNET_MODULE_FUSED_STEP"] = "0"
        try:
            np.random.seed(0); mx.random.seed(0)
            slow = _fit_module(it, optimizer, params)
        finally:
            del os.environ["MXNET_MODULE_FUSED_STEP"]
        assert slow._cached_step is None or not slow._cached_step
        fa, _ = fast.get_params()
        sa, _ = slow.get_params()
        for name in fa:
            np.testing.assert_allclose(
                fa[name].asnumpy(), sa[name].asnumpy(),
                rtol=2e-5, atol=1e-6,
                err_msg="%s/%s diverged" % (optimizer, name))


def test_fused_step_optimizer_state_checkpoint_roundtrip():
    import tempfile, os as _os
    it = _data_iter()
    mod = _fit_module(it)
    assert mod._cached_step is not None
    with tempfile.TemporaryDirectory() as td:
        f = _os.path.join(td, "opt.states")
        mod.save_optimizer_states(f)
        mod2 = _fit_module(it, num_epoch=1)
        mod2.load_optimizer_states(f)
        # momentum buffers round-trip through the updater layout
        for idx, st in mod._updater.states.items():
            if st is None:
                continue
            np.testing.assert_allclose(st.asnumpy(),
                                       mod2._updater.states[idx].asnumpy())


def test_reshape_alternation_reuses_groups_and_programs():
    """Alternating input shapes (bucketing / final partial batch) must
    reuse the cached exec group AND its compiled step program instead of
    rebinding from scratch (reference shares the memory pool; here the
    costly resource is the compiled program)."""
    from mxnet_tpu.io import DataBatch, DataDesc
    from mxnet_tpu.module import Module
    rng = np.random.RandomState(7)
    mod = Module(_small_net(), context=mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (8, 6))],
             label_shapes=[DataDesc("softmax_label", (8,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),))

    def batch(bs):
        return DataBatch([nd.array(rng.randn(bs, 6))],
                         [nd.array(rng.randint(0, 3, (bs,)))])

    groups, steps = set(), set()
    for _ in range(3):
        for bs in (8, 5):          # alternate full/partial batch shapes
            mod._fit_step(batch(bs))
            groups.add(id(mod._exec_group))
            assert mod._cached_step is not None
            steps.add(id(mod._cached_step))
    assert len(groups) == 2, "groups rebuilt instead of cached"
    assert len(steps) == 2, "step programs rebuilt instead of cached"
    for step in (mod._cached_step,):
        assert step._step_jit._cache_size() == 1


def test_reshape_preserves_grad_req_add():
    """reshape must rebuild groups with the BOUND grad_req (accumulation
    was silently downgraded to 'write' for reshaped shapes)."""
    from mxnet_tpu.io import DataBatch, DataDesc
    from mxnet_tpu.module import Module
    rng = np.random.RandomState(11)
    mod = Module(_small_net(), context=mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (8, 6))],
             label_shapes=[DataDesc("softmax_label", (8,))],
             grad_req="add")
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.reshape([DataDesc("data", (4, 6))],
                [DataDesc("softmax_label", (4,))])
    ex = mod._exec_group.execs[0]
    assert ex.grad_req["fc1_weight"] == "add"
    batch = DataBatch([nd.array(rng.randn(4, 6))],
                      [nd.array(rng.randint(0, 3, (4,)))])
    mod.forward_backward(batch)
    once = ex.grad_dict["fc1_weight"].asnumpy().copy()
    mod.forward_backward(batch)
    np.testing.assert_allclose(ex.grad_dict["fc1_weight"].asnumpy(),
                               2 * once, rtol=1e-5)


def test_reshape_cache_bounded(monkeypatch):
    from mxnet_tpu.io import DataDesc
    from mxnet_tpu.module import Module
    monkeypatch.setenv("MXNET_MODULE_RESHAPE_CACHE", "3")
    mod = Module(_small_net(), context=mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (8, 6))],
             label_shapes=[DataDesc("softmax_label", (8,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    for bs in (7, 6, 5, 4, 3, 2):
        mod.reshape([DataDesc("data", (bs, 6))],
                    [DataDesc("softmax_label", (bs,))])
    assert len(mod._reshape_cache) <= 3


def test_bucketing_default_bucket_updates_survive_switch():
    """A fused step on the DEFAULT bucket updates device params only;
    switching buckets must sync those updates down before seeding the
    next bucket (they used to be silently reverted)."""
    from mxnet_tpu.io import DataBatch, DataDesc
    from mxnet_tpu.module import BucketingModule
    rng = np.random.RandomState(13)

    def sym_gen(key):
        # weights shared across buckets (seq-len varies, dims don't)
        emb = sym_api.Embedding(sym_api.Variable("data"), input_dim=10,
                                output_dim=6, name="emb")
        pooled = sym_api.mean(emb, axis=1)
        net = sym_api.FullyConnected(pooled, num_hidden=4, name="fc")
        net = sym_api.SoftmaxOutput(net, sym_api.Variable("softmax_label"),
                                    name="softmax")
        return net, ("data",), ("softmax_label",)

    def batch(n, key):
        return DataBatch(
            [nd.array(rng.randint(0, 10, (4, n)).astype(np.float32))],
            [nd.array(rng.randint(0, 4, (4,)))],
            bucket_key=key,
            provide_data=[DataDesc("data", (4, n))],
            provide_label=[DataDesc("softmax_label", (4,))])

    mod = BucketingModule(sym_gen, default_bucket_key=8)
    mod.bind(data_shapes=[DataDesc("data", (4, 8))],
             label_shapes=[DataDesc("softmax_label", (4,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.5),))

    w0 = mod._leader._exec_group.execs[0].arg_dict["emb_weight"].asnumpy()
    w0 = w0.copy()
    mod._fit_step(batch(8, 8))       # default bucket: device-only update
    w1 = mod._leader._exec_group.execs[0].arg_dict["emb_weight"].asnumpy()
    w1 = w1.copy()
    assert np.abs(w1 - w0).max() > 0, "leader step had no effect"
    mod._fit_step(batch(5, 5))       # switch must carry w1 forward
    # the non-default bucket must have STARTED from w1, and its update
    # must not regress behind w1's step
    arg, _ = mod.get_params()
    assert np.abs(arg["emb_weight"].asnumpy() - w0).max() > 0, \
        "default-bucket update was reverted by the switch"
