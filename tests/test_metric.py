"""EvalMetric registry parity (reference tests/python/unittest/test_metric.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_accuracy():
    m = mx.metric.Accuracy()
    pred = nd.array(np.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]]))
    label = nd.array(np.array([1, 0, 0]))
    m.update([label], [pred])
    name, acc = m.get()
    assert name == "accuracy"
    assert abs(acc - 2.0 / 3) < 1e-6


def test_topk_accuracy():
    m = mx.metric.TopKAccuracy(top_k=2)
    pred = nd.array(np.array([[0.1, 0.5, 0.4], [0.8, 0.15, 0.05]]))
    label = nd.array(np.array([2, 2]))
    m.update([label], [pred])
    _, acc = m.get()
    assert abs(acc - 0.5) < 1e-6


def test_mse_mae_rmse():
    pred = nd.array(np.array([[1.0], [2.0]]))
    label = nd.array(np.array([[1.5], [1.0]]))
    for cls, expect in [(mx.metric.MSE, (0.25 + 1.0) / 2),
                        (mx.metric.MAE, (0.5 + 1.0) / 2),
                        (mx.metric.RMSE, np.sqrt((0.25 + 1.0) / 2))]:
        m = cls()
        m.update([label], [pred])
        _, v = m.get()
        assert abs(v - expect) < 1e-5, cls


def test_cross_entropy_and_nll():
    pred = nd.array(np.array([[0.2, 0.8], [0.9, 0.1]]))
    label = nd.array(np.array([1, 0]))
    m = mx.metric.CrossEntropy()
    m.update([label], [pred])
    _, v = m.get()
    expect = -(np.log(0.8) + np.log(0.9)) / 2
    assert abs(v - expect) < 1e-5


def test_perplexity():
    pred = nd.array(np.array([[0.5, 0.5], [0.5, 0.5]]))
    label = nd.array(np.array([0, 1]))
    m = mx.metric.Perplexity(ignore_label=None)
    m.update([label], [pred])
    _, v = m.get()
    assert abs(v - 2.0) < 1e-4


def test_f1():
    m = mx.metric.F1()
    pred = nd.array(np.array([[0.7, 0.3], [0.2, 0.8], [0.1, 0.9],
                              [0.6, 0.4]]))
    label = nd.array(np.array([0, 1, 1, 1]))
    m.update([label], [pred])
    _, f1 = m.get()
    # tp=2 fp=0 fn=1 -> precision 1, recall 2/3 -> f1 = 0.8
    assert abs(f1 - 0.8) < 1e-6


def test_composite_and_custom():
    comp = mx.metric.CompositeEvalMetric()
    comp.add(mx.metric.Accuracy())
    comp.add(mx.metric.MSE())
    pred = nd.array(np.array([[0.3, 0.7]]))
    label = nd.array(np.array([1]))
    comp.update([label], [pred])
    names, vals = comp.get()
    assert len(names) == 2 and len(vals) == 2

    def feval(l, p):
        return float(np.abs(l - p.argmax(axis=1)).mean())
    m = mx.metric.create(feval)
    m.update([label], [pred])
    _, v = m.get()
    assert v == 0.0


def test_metric_create_by_name_and_reset():
    m = mx.metric.create("acc")
    pred = nd.array(np.array([[0.3, 0.7]]))
    m.update([nd.array(np.array([1]))], [pred])
    _, v1 = m.get()
    assert v1 == 1.0
    m.reset()
    name, v = m.get()
    assert np.isnan(v)


def test_pearson():
    m = mx.metric.PearsonCorrelation()
    pred = nd.array(np.array([[1.0], [2.0], [3.0], [4.0]]))
    label = nd.array(np.array([[1.1], [2.2], [2.9], [4.1]]))
    m.update([label], [pred])
    _, v = m.get()
    assert v > 0.99


def test_nonfinite_updates_are_excluded_and_counted():
    """A NaN contribution must not poison the running sum forever
    (ISSUE 10 satellite): it is excluded and booked as
    ``metric_nonfinite_updates``."""
    from mxnet_tpu import telemetry
    before = telemetry.counter("metric_nonfinite_updates")
    m = mx.metric.MAE()
    good = nd.array(np.array([[1.0], [2.0]]))
    m.update([good], [good])                       # contributes 0.0
    bad = nd.array(np.array([[np.nan], [2.0]]))
    m.update([good], [bad])                        # NaN: excluded
    m.update([good], [good])
    name, value = m.get()
    assert value == 0.0 and m.num_inst == 2        # only the finite pair
    assert telemetry.counter("metric_nonfinite_updates") == before + 1

    # Loss-style raw accumulators are gated too
    loss = mx.metric.Loss()
    loss.update(None, [nd.array(np.array([1.0, 2.0]))])
    loss.update(None, [nd.array(np.array([np.inf, 2.0]))])
    _, v = loss.get()
    assert np.isfinite(v) and v == 1.5
    assert telemetry.counter("metric_nonfinite_updates") == before + 2

    # Perplexity: a NaN probability row is excluded, not folded
    p = mx.metric.Perplexity(ignore_label=None)
    pred = nd.array(np.array([[0.5, 0.5], [0.4, 0.6]]))
    label = nd.array(np.array([0, 1]))
    p.update([label], [pred])
    nan_pred = nd.array(np.array([[np.nan, 0.5], [0.4, 0.6]]))
    p.update([label], [nan_pred])
    _, ppl = p.get()
    assert np.isfinite(ppl)
    assert telemetry.counter("metric_nonfinite_updates") == before + 3
