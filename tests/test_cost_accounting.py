"""XLA cost accounting (ISSUE 4 tentpole 3): per-program
cost_analysis() capture, the step MFU/bandwidth gauges, the peak table,
and the trace_report MFU/roofline surfaces.

Acceptance contract: a watched jitted step yields nonzero
``step_model_flops`` and an MFU in (0, 1] on CPU with an env-pinned
peak; ``tools/trace_report.py --json`` smoke via subprocess.
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, sym, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.telemetry import costs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tel(monkeypatch):
    """Telemetry on, peaks pinned via env so MFU is deterministic-ish:
    1e18 FLOP/s is far above anything the CPU does, so MFU lands in
    (0, 1] regardless of machine speed."""
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_PEAK_FLOPS", "1e18")
    monkeypatch.setenv("MXNET_PEAK_HBM_BW", "1e18")
    telemetry.refresh_from_env()                # also refreshes costs
    telemetry.reset()
    yield telemetry
    telemetry.reset()
    for var in ("MXNET_TELEMETRY", "MXNET_PEAK_FLOPS",
                "MXNET_PEAK_HBM_BW"):
        monkeypatch.delenv(var, raising=False)
    telemetry.refresh_from_env()


def test_watched_step_yields_flops_and_mfu(tel):
    """The acceptance case, minimal form: one watched jitted program
    inside a step span."""
    f = telemetry.watch_jit(jax.jit(lambda x: x @ x), "cost_test_step")
    x = jnp.ones((32, 32), jnp.float32)
    with telemetry.span("cost_step", cat="step"):
        f(x).block_until_ready()

    cost = telemetry.program_cost("cost_test_step")
    assert cost is not None
    flops, nbytes = cost
    # a 32x32 matmul is 2*n^3 = 65536 model FLOPs
    assert flops >= 2 * 32 ** 3
    assert nbytes > 0

    gauges = telemetry.snapshot()["gauges"]
    assert gauges["step_model_flops"] == flops
    assert 0 < gauges["step_mfu"] <= 1.0
    assert 0 < gauges["step_hbm_bw_util"] <= 1.0


def test_cached_cost_accumulates_without_recompiles(tel):
    """Steps after the first recompile nothing; the window still fills
    from the per-name cost cache, and two programs sum."""
    f = telemetry.watch_jit(jax.jit(lambda x: x @ x), "cost_prog_a")
    g = telemetry.watch_jit(jax.jit(lambda x: x + x), "cost_prog_b")
    x = jnp.ones((16, 16), jnp.float32)
    for _ in range(3):
        with telemetry.span("cost_step", cat="step"):
            f(x).block_until_ready()
            g(x).block_until_ready()
    per_step = (telemetry.program_cost("cost_prog_a")[0]
                + telemetry.program_cost("cost_prog_b")[0])
    assert telemetry.gauge("step_model_flops") == per_step
    assert telemetry.counter("jit_compiles") == 2   # one compile each


def test_trainer_step_mfu_end_to_end(tel):
    """The real step: fused Trainer under telemetry reports MFU."""
    np.random.seed(0)
    net = nn.Sequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dense(4))
    net.initialize(init=mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="device")
    loss_fn = gluon.loss.L2Loss()
    for _ in range(2):
        x = mx.nd.array(np.random.randn(8, 6).astype(np.float32))
        y = mx.nd.array(np.random.randn(8, 4).astype(np.float32))
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(8)

    snap = telemetry.snapshot()
    assert snap["gauges"]["step_model_flops"] > 0
    assert 0 < snap["gauges"]["step_mfu"] <= 1.0
    programs = snap["costs"]["programs"]
    assert "fused_trainer_step" in programs
    assert programs["fused_trainer_step"]["flops"] > 0
    peaks = snap["costs"]["peaks"]
    assert peaks["flops"] == 1e18 and peaks["source"]["flops"] == "env"


def test_donated_programs_still_capture_cost(tel):
    """The re-lower uses ShapeDtypeStruct specs, so a program that
    donated (and deleted) its inputs still gets cost-accounted."""
    f = telemetry.watch_jit(
        jax.jit(lambda x: x * 2.0, donate_argnums=(0,)),
        "cost_donated")
    x = jnp.ones((64,), jnp.float32)
    with telemetry.span("cost_step", cat="step"):
        f(x).block_until_ready()
    assert telemetry.program_cost("cost_donated") is not None


def test_peak_table_fallback_and_env_override(monkeypatch):
    monkeypatch.delenv("MXNET_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("MXNET_PEAK_HBM_BW", raising=False)
    costs.refresh_from_env()
    pk = costs.peaks()
    n = len(jax.local_devices())
    assert pk["device_kind"] == "cpu" and pk["n_devices"] == n
    assert pk["flops"] == costs.PEAK_TABLE["cpu"][0] * n
    assert pk["source"]["flops"] == "table"

    monkeypatch.setenv("MXNET_PEAK_FLOPS", "2.5e14")
    costs.refresh_from_env()
    pk = costs.peaks()
    assert pk["flops"] == 2.5e14                 # aggregate, verbatim
    assert pk["source"]["flops"] == "env"
    costs.refresh_from_env()


def test_executor_cost_analysis_aot(tel):
    """Per-executor AOT cost: nothing executed, PRNG stream untouched."""
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc")
    ex = net.simple_bind(mx.cpu(), data=(4, 16))
    report = ex.cost_analysis()
    assert report["eval"]["flops"] >= 2 * 4 * 16 * 8   # the matmul
    assert report["fwd_bwd"]["flops"] > report["eval"]["flops"]
    assert report["eval"]["bytes_accessed"] > 0


def test_capture_env_kill_switch(tel, monkeypatch):
    monkeypatch.setenv("MXNET_COST_ANALYSIS", "0")
    costs.refresh_from_env()
    try:
        f = telemetry.watch_jit(jax.jit(lambda x: x @ x),
                                "cost_gated_off")
        with telemetry.span("cost_step", cat="step"):
            f(jnp.ones((8, 8), jnp.float32)).block_until_ready()
        assert telemetry.program_cost("cost_gated_off") is None
        assert telemetry.gauge("step_model_flops") == 0.0
    finally:
        monkeypatch.delenv("MXNET_COST_ANALYSIS", raising=False)
        costs.refresh_from_env()


# ---- trace_report surfaces -----------------------------------------------

def _dump_artifacts(tmp_path):
    trace = telemetry.dump_chrome_trace(str(tmp_path / "trace.json"))
    snap = telemetry.dump_snapshot(str(tmp_path / "snap.json"))
    return trace, snap


def test_trace_report_json_smoke_subprocess(tel, tmp_path):
    """Acceptance: --json machine-readable output from a live dump."""
    f = telemetry.watch_jit(jax.jit(lambda x: x @ x), "cost_test_step")
    for _ in range(2):
        with telemetry.span("cost_step", cat="step"):
            f(jnp.ones((32, 32), jnp.float32)).block_until_ready()
    trace, snap = _dump_artifacts(tmp_path)

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         trace, "--snapshot", snap, "--json"],
        capture_output=True, timeout=60)
    assert proc.returncode == 0, proc.stderr.decode()
    report = json.loads(proc.stdout)
    assert report["steps"]["count"] == 2
    assert report["mfu"]["step_model_flops"] > 0
    assert 0 < report["mfu"]["step_mfu"] <= 1
    rows = {r["program"]: r for r in report["mfu"]["programs"]}
    assert rows["cost_test_step"]["flops"] > 0
    assert rows["cost_test_step"]["bound"] in ("compute", "memory")


def test_trace_report_degrades_on_empty_and_legacy_inputs(tmp_path):
    """Satellite: no traceback on empty traces or pre-cost snapshots."""
    empty = tmp_path / "empty.json"
    empty.write_text("{}")
    legacy_snap = tmp_path / "legacy.json"
    legacy_snap.write_text(json.dumps(
        {"counters": {}, "gauges": {}}))     # no retraces/costs keys

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(empty)], capture_output=True, timeout=60)
    assert out.returncode == 0, out.stderr.decode()
    assert b"no events" in out.stdout

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         str(empty), "--snapshot", str(legacy_snap), "--json"],
        capture_output=True, timeout=60)
    assert out.returncode == 0, out.stderr.decode()
    report = json.loads(out.stdout)
    assert report["steps"] is None and report["mfu"] is None


# ---- prometheus escaping (satellite) -------------------------------------

def test_prometheus_help_and_label_escaping(tel, monkeypatch):
    monkeypatch.setitem(telemetry.COUNTERS, "esc_test_total",
                        'line1\nline2 with \\backslash and "quotes"')
    telemetry.bump("esc_test_total")
    text = telemetry.prometheus_text()
    help_lines = [ln for ln in text.splitlines()
                  if ln.startswith("# HELP esc_test_total")]
    assert len(help_lines) == 1                  # newline did not split it
    assert "line1\\nline2" in help_lines[0]
    assert "\\\\backslash" in help_lines[0]
    # escape helpers honor the exposition format for label values too
    from mxnet_tpu.telemetry import core
    assert core._escape_label('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
