"""Engine-facade semantics: the observable contract SURVEY §3.3 requires.

Reference analogue: ``tests/cpp/engine/threaded_engine_test.cc`` — ops
issue asynchronously, ``wait_to_read`` blocks until the value is real,
writes to one logical variable serialize, ``WaitForAll`` drains. On jax
the engine is XLA/PJRT dispatch; these tests pin the *contract*, not the
mechanism.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine, nd


def test_wait_to_read_blocks_until_value_is_real():
    """asnumpy()/wait_to_read observe the completed value (the only sync
    point the reference requires, SURVEY §3.5)."""
    x = nd.array(np.ones((64, 64), np.float32))
    y = x
    for _ in range(20):
        y = nd.dot(y, x) * 1e-3
    y.wait_to_read()
    v = y.asnumpy()
    assert np.isfinite(v).all()


def test_writes_serialize_per_variable():
    """A chain of in-place mutations lands in program order: the final
    value reflects every write exactly once (ThreadedVar queue semantics,
    threaded_engine.h:112-214)."""
    x = nd.zeros((8, 8))
    for i in range(1, 51):
        x += i
    expect = sum(range(1, 51))
    np.testing.assert_allclose(x.asnumpy(), np.full((8, 8), expect))


def test_reads_do_not_corrupt_concurrent_state():
    """Parallel readers of one variable all observe the same committed
    value while a writer thread mutates a different variable."""
    shared = nd.array(np.full((16,), 7.0, np.float32))
    other = nd.zeros((16,))
    results = []
    errors = []

    def reader():
        try:
            for _ in range(50):
                results.append(float(shared.asnumpy()[0]))
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    def writer():
        try:
            for i in range(50):
                other[:] = other + 1     # in-place write, no rebinding
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=reader) for _ in range(3)]
    threads.append(threading.Thread(target=writer))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert set(results) == {7.0}
    np.testing.assert_allclose(other.asnumpy(), np.full((16,), 50.0))


def test_wait_for_all_drains():
    x = nd.array(np.random.rand(32, 32).astype(np.float32))
    for _ in range(10):
        x = nd.dot(x, x) * 0.01
    engine.wait_for_all()
    assert np.isfinite(x.asnumpy()).all()


def test_sync_dispatch_mode_toggle():
    """NaiveEngine analogue (MXNET_ENGINE_TYPE=NaiveEngine): sync dispatch
    forces completion inside push (ref naive_engine.cc:95-130)."""
    prev = engine.is_sync_dispatch()
    try:
        engine.set_sync_dispatch(True)
        assert engine.is_sync_dispatch()
        out = engine.push(lambda: nd.ones((4,)) * 3)
        np.testing.assert_allclose(out.asnumpy(), 3.0)
        engine.set_sync_dispatch(False)
        assert not engine.is_sync_dispatch()
    finally:
        engine.set_sync_dispatch(prev)


def test_delete_variable_while_pending_is_safe():
    """Dropping the last handle to an array with pending compute must not
    crash (engine delete-var GC, threaded_engine.cc:369-418)."""
    x = nd.array(np.random.rand(128, 128).astype(np.float32))
    y = nd.dot(x, x)
    del x
    del y          # no sync before deletion
    z = nd.ones((2, 2))
    np.testing.assert_allclose(z.asnumpy(), 1.0)


# ---------------------------------------------------------------------------
# Native threaded engine (host-task scheduler, native/engine.cc)
# ---------------------------------------------------------------------------

@pytest.fixture()
def native_engine():
    eng = engine.ThreadedEngine(num_workers=4, sync=False)
    if not eng.native:
        pytest.skip("native engine library not built")
    yield eng
    eng.close()


def test_native_writes_serialize_in_push_order(native_engine):
    """Writers on one variable run one at a time, in push order
    (AppendWriteDependency FIFO, ref threaded_engine.h:96-136)."""
    eng = native_engine
    v = eng.new_variable()
    order = []

    def writer(i):
        def run():
            time.sleep(0.001)
            order.append(i)
        return run

    for i in range(40):
        eng.push(writer(i), mutable_vars=[v])
    eng.wait_for_all()
    assert order == list(range(40))


def test_native_reads_run_in_parallel(native_engine):
    """Readers between writes overlap: N sleeping readers finish in far
    less than N * sleep (parallel-read dispatch, SURVEY §3.3)."""
    eng = native_engine
    v = eng.new_variable()
    barrier = threading.Barrier(4, timeout=5)

    def reader():
        barrier.wait()        # deadlocks unless all 4 run concurrently

    t0 = time.perf_counter()
    for _ in range(4):
        eng.push(reader, const_vars=[v])
    eng.wait_for_all()
    assert time.perf_counter() - t0 < 5.0


def test_native_write_excludes_reads(native_engine):
    """Reads pushed after a write only observe the written state; the
    write waits for earlier reads (ThreadedVar protocol)."""
    eng = native_engine
    v = eng.new_variable()
    state = {"x": 0}
    seen = []

    def slow_read_before():
        time.sleep(0.05)
        seen.append(("pre", state["x"]))

    def write():
        state["x"] = 1

    def read_after():
        seen.append(("post", state["x"]))

    eng.push(slow_read_before, const_vars=[v])
    eng.push(write, mutable_vars=[v])
    for _ in range(3):
        eng.push(read_after, const_vars=[v])
    eng.wait_for_all()
    assert ("pre", 0) in seen
    assert seen.count(("post", 1)) == 3
    assert ("post", 0) not in seen


def test_native_wait_for_var_blocks_until_writes_land(native_engine):
    eng = native_engine
    v, other = eng.new_variable(), eng.new_variable()
    log = []
    eng.push(lambda: (time.sleep(0.05), log.append("w1"))[-1],
             mutable_vars=[v])
    eng.push(lambda: (time.sleep(0.2), log.append("slow"))[-1],
             mutable_vars=[other])
    eng.wait_for_var(v)
    assert "w1" in log            # target var's writes done...
    eng.wait_for_all()
    assert "slow" in log


def test_native_disjoint_vars_run_concurrently(native_engine):
    """Tasks with disjoint mutable vars overlap (per-device-queue
    parallelism in the reference; worker pool here)."""
    eng = native_engine
    vs = [eng.new_variable() for _ in range(4)]
    barrier = threading.Barrier(4, timeout=5)
    for v in vs:
        eng.push(lambda: barrier.wait(), mutable_vars=[v])
    eng.wait_for_all()      # would deadlock if writes were serialized


def test_native_mixed_dependency_chain(native_engine):
    """A read-modify-write fan: w(a); r(a)+w(b) x2; r(b) — completion
    respects the dependency DAG."""
    eng = native_engine
    a, b = eng.new_variable(), eng.new_variable()
    log = []
    eng.push(lambda: log.append("init_a"), mutable_vars=[a])
    for i in range(2):
        eng.push(lambda i=i: log.append(f"a_to_b{i}"),
                 const_vars=[a], mutable_vars=[b])
    eng.push(lambda: log.append("read_b"), const_vars=[b])
    eng.wait_for_all()
    assert log[0] == "init_a"
    assert log[-1] == "read_b"
    assert {"a_to_b0", "a_to_b1"} == set(log[1:3])


def test_native_exception_surfaces_at_wait(native_engine):
    eng = native_engine
    v = eng.new_variable()

    def boom():
        raise ValueError("task failed")

    eng.push(boom, mutable_vars=[v])
    with pytest.raises(ValueError, match="task failed"):
        eng.wait_for_all()


def test_native_delete_variable_after_pending(native_engine):
    eng = native_engine
    v = eng.new_variable()
    ran = []
    for i in range(5):
        eng.push(lambda i=i: ran.append(i), mutable_vars=[v])
    eng.delete_variable(v)
    eng.wait_for_all()
    assert ran == list(range(5))


def test_native_sync_mode_completes_inline(native_engine):
    """NaiveEngine mode: push returns only after the task ran
    (ref naive_engine.cc:95-130)."""
    eng = native_engine
    eng.set_sync(True)
    try:
        v = eng.new_variable()
        ran = []
        eng.push(lambda: ran.append(1), mutable_vars=[v])
        assert ran == [1]
    finally:
        eng.set_sync(False)


def test_native_priority_prefers_urgent_tasks():
    """Higher-priority ready tasks dispatch first (FnProperty priority
    classes, ref engine.h:77-90)."""
    eng = engine.ThreadedEngine(num_workers=1, sync=False)
    if not eng.native:
        pytest.skip("native engine library not built")
    try:
        gate = eng.new_variable()
        order = []
        # Hold the single worker so subsequent pushes queue up.
        eng.push(lambda: time.sleep(0.1), mutable_vars=[gate])
        for i in range(3):
            eng.push(lambda i=i: order.append(("lo", i)), priority=0)
        eng.push(lambda: order.append(("hi", 0)), priority=10)
        eng.wait_for_all()
        assert order[0] == ("hi", 0)
    finally:
        eng.close()


def test_native_stress_many_tasks_random_deps(native_engine):
    """Randomized stress (ref threaded_engine_test.cc): per-variable
    write counters must land exactly once per write, in order."""
    rng = np.random.RandomState(0)
    eng = native_engine
    nvars = 8
    vs = [eng.new_variable() for _ in range(nvars)]
    logs = [[] for _ in range(nvars)]
    counts = [0] * nvars
    for _ in range(300):
        wi = int(rng.randint(nvars))
        reads = [vs[i] for i in np.nonzero(rng.rand(nvars) < 0.3)[0]
                 if i != wi]
        seqno = counts[wi]
        counts[wi] += 1
        eng.push(lambda wi=wi, s=seqno: logs[wi].append(s),
                 const_vars=reads, mutable_vars=[vs[wi]])
    eng.wait_for_all()
    for i in range(nvars):
        assert logs[i] == list(range(counts[i]))


def test_module_level_engine_singleton():
    eng = engine.engine()
    v = eng.new_variable()
    done = []
    eng.push(lambda: done.append(1), mutable_vars=[v])
    engine.wait_for_var(v)          # module facade dispatches on int handle
    assert done == [1]


def test_native_overlapping_read_write_deps_do_not_deadlock(native_engine):
    """A var listed in both const and mutable counts once, as a write
    (Engine::DeduplicateVarHandle, ref engine.h:251-269)."""
    eng = native_engine
    v = eng.new_variable()
    ran = []
    eng.push(lambda: ran.append(1), const_vars=[v], mutable_vars=[v, v])
    eng.wait_for_all()
    assert ran == [1]


def test_native_push_on_deleted_var_is_safe(native_engine):
    """Pushing/waiting on a GC'd variable neither crashes nor hangs."""
    eng = native_engine
    v = eng.new_variable()
    eng.push(lambda: None, mutable_vars=[v])
    eng.delete_variable(v)
    eng.wait_for_all()
    ran = []
    eng.push(lambda: ran.append(1), mutable_vars=[v])  # v already GC'd
    eng.wait_for_var(v)
    eng.wait_for_all()
    assert ran == [1]


def test_native_sync_push_from_inside_task_no_deadlock(native_engine):
    """A task chaining a follow-up push in sync mode must not deadlock
    (NaiveEngine executes inline, ref naive_engine.cc:95-130)."""
    eng = native_engine
    eng.set_sync(True)
    try:
        order = []

        def stage2():
            order.append("stage2")

        def stage1():
            order.append("stage1")
            eng.push(stage2)

        eng.push(stage1)
        assert order == ["stage1", "stage2"]
    finally:
        eng.set_sync(False)


def test_native_task_registry_stays_bounded(native_engine):
    """A continuously-fed pipeline must not accrete per-task state: after
    a drain, the shared live-task registry is empty again."""
    eng = native_engine
    v = eng.new_variable()
    for _ in range(200):
        eng.push(lambda: None, mutable_vars=[v])
    eng.wait_for_all()
    assert len(engine._LIVE_TASKS) == 0


def test_native_close_is_idempotent_and_blocks_new_pushes():
    eng = engine.ThreadedEngine(num_workers=2, sync=False)
    if not eng.native:
        pytest.skip("native engine library not built")
    ran = []
    v = eng.new_variable()
    eng.push(lambda: ran.append(1), mutable_vars=[v])
    eng.close()
    eng.close()                      # idempotent
    assert ran == [1]                # close drained the queue
    # post-close pushes degrade to synchronous inline execution (the
    # same fallback as a missing native library) instead of crashing
    eng.push(lambda: ran.append(2))
    assert ran == [1, 2]


def test_native_delete_var_with_trailing_reads(native_engine):
    """A doomed variable whose last pending op is a read still drains and
    GCs without wedging later work (FinishRead GC path)."""
    eng = native_engine
    v = eng.new_variable()
    log = []
    eng.push(lambda: log.append("w"), mutable_vars=[v])
    eng.delete_variable(v)
    eng.push(lambda: log.append("r"), const_vars=[v])
    eng.wait_for_all()
    assert log[0] == "w" and "r" in log
    w2 = eng.new_variable()
    eng.push(lambda: log.append("w2"), mutable_vars=[w2])
    eng.wait_for_all()
    assert log[-1] == "w2"


def test_native_dropped_engine_is_finalized():
    """An engine dropped without close() frees its native resources via
    the GC finalizer (no thread/engine leak)."""
    import gc
    eng = engine.ThreadedEngine(num_workers=2, sync=False)
    if not eng.native:
        pytest.skip("native engine library not built")
    ran = []
    v = eng.new_variable()
    eng.push(lambda: ran.append(1), mutable_vars=[v])
    fin = eng._finalizer
    core = eng._core
    # _LIVE_TASKS strongly references the engine until the task runs:
    # wait for the queue to drain before dropping the last reference.
    deadline = time.time() + 5
    while ran != [1] and time.time() < deadline:
        time.sleep(0.01)
    assert ran == [1]
    del eng
    while fin.alive and time.time() < deadline:   # worker-side refs drop
        gc.collect()
        time.sleep(0.01)
    assert not fin.alive          # finalizer fired...
    assert core.h is None         # ...and released the native handle


def test_native_push_error_does_not_leak_registry(native_engine):
    eng = native_engine
    v = eng.new_variable()
    before = len(engine._LIVE_TASKS)
    with pytest.raises(TypeError):
        eng.push(lambda: None, const_vars=[v, None])   # bad var handle
    assert len(engine._LIVE_TASKS) == before


def test_async_checkpoint_callback_overlaps_and_lands(tmp_path):
    """do_checkpoint(async_write=True) snapshots at callback time and
    serializes saves per prefix on the host engine."""
    from mxnet_tpu.io import NDArrayIter
    prefix = str(tmp_path / "async_ckpt")

    data = mx.sym.Variable("data")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=4, name="fc"),
        name="softmax")
    X = np.random.RandomState(0).randn(32, 6).astype(np.float32)
    Y = np.random.RandomState(1).randint(0, 4, 32).astype(np.float32)
    mod = mx.mod.Module(net)
    mod.fit(NDArrayIter(X, Y, batch_size=8), num_epoch=3,
            initializer=mx.init.Xavier(), optimizer="sgd",
            epoch_end_callback=mx.callback.do_checkpoint(
                prefix, async_write=True))
    engine.engine().wait_for_all()
    from mxnet_tpu.model import load_checkpoint
    for epoch in (1, 2, 3):
        sym_l, arg, aux = load_checkpoint(prefix, epoch)
        assert "fc_weight" in arg
    # final checkpoint matches the module's final parameters exactly
    final_arg, _ = mod.get_params()
    _, arg3, _ = load_checkpoint(prefix, 3)
    np.testing.assert_allclose(arg3["fc_weight"].asnumpy(),
                               final_arg["fc_weight"].asnumpy())
    # ...and epoch 1 holds the values SNAPSHOTTED at callback time, not
    # the end-of-training values a late aliasing save would produce
    _, arg1, _ = load_checkpoint(prefix, 1)
    assert not np.allclose(arg1["fc_weight"].asnumpy(),
                           final_arg["fc_weight"].asnumpy())
