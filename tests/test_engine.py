"""Engine-facade semantics: the observable contract SURVEY §3.3 requires.

Reference analogue: ``tests/cpp/engine/threaded_engine_test.cc`` — ops
issue asynchronously, ``wait_to_read`` blocks until the value is real,
writes to one logical variable serialize, ``WaitForAll`` drains. On jax
the engine is XLA/PJRT dispatch; these tests pin the *contract*, not the
mechanism.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine, nd


def test_wait_to_read_blocks_until_value_is_real():
    """asnumpy()/wait_to_read observe the completed value (the only sync
    point the reference requires, SURVEY §3.5)."""
    x = nd.array(np.ones((64, 64), np.float32))
    y = x
    for _ in range(20):
        y = nd.dot(y, x) * 1e-3
    y.wait_to_read()
    v = y.asnumpy()
    assert np.isfinite(v).all()


def test_writes_serialize_per_variable():
    """A chain of in-place mutations lands in program order: the final
    value reflects every write exactly once (ThreadedVar queue semantics,
    threaded_engine.h:112-214)."""
    x = nd.zeros((8, 8))
    for i in range(1, 51):
        x += i
    expect = sum(range(1, 51))
    np.testing.assert_allclose(x.asnumpy(), np.full((8, 8), expect))


def test_reads_do_not_corrupt_concurrent_state():
    """Parallel readers of one variable all observe the same committed
    value while a writer thread mutates a different variable."""
    shared = nd.array(np.full((16,), 7.0, np.float32))
    other = nd.zeros((16,))
    results = []
    errors = []

    def reader():
        try:
            for _ in range(50):
                results.append(float(shared.asnumpy()[0]))
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    def writer():
        try:
            for i in range(50):
                other[:] = other + 1     # in-place write, no rebinding
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=reader) for _ in range(3)]
    threads.append(threading.Thread(target=writer))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert set(results) == {7.0}
    np.testing.assert_allclose(other.asnumpy(), np.full((16,), 50.0))


def test_wait_for_all_drains():
    x = nd.array(np.random.rand(32, 32).astype(np.float32))
    for _ in range(10):
        x = nd.dot(x, x) * 0.01
    engine.wait_for_all()
    assert np.isfinite(x.asnumpy()).all()


def test_sync_dispatch_mode_toggle():
    """NaiveEngine analogue (MXNET_ENGINE_TYPE=NaiveEngine): sync dispatch
    forces completion inside push (ref naive_engine.cc:95-130)."""
    prev = engine.is_sync_dispatch()
    try:
        engine.set_sync_dispatch(True)
        assert engine.is_sync_dispatch()
        out = engine.push(lambda: nd.ones((4,)) * 3)
        np.testing.assert_allclose(out.asnumpy(), 3.0)
        engine.set_sync_dispatch(False)
        assert not engine.is_sync_dispatch()
    finally:
        engine.set_sync_dispatch(prev)


def test_delete_variable_while_pending_is_safe():
    """Dropping the last handle to an array with pending compute must not
    crash (engine delete-var GC, threaded_engine.cc:369-418)."""
    x = nd.array(np.random.rand(128, 128).astype(np.float32))
    y = nd.dot(x, x)
    del x
    del y          # no sync before deletion
    z = nd.ones((2, 2))
    np.testing.assert_allclose(z.asnumpy(), 1.0)
