"""Deterministic dist worker for the chaos tier (smoke + kill tests).

A tiny optimizer-on-server training loop over ``dist_sync`` whose loss
trajectory is a pure function of (nworkers, iters): gradients derive
from the *pulled* weights, so the authoritative state genuinely lives on
the servers and a wrong server-state restore diverges bitwise.

Sync discipline: NO scheduler barriers inside the loop — every sync
point is a *fence push* (a sync-mode push blocks until all workers
contribute, bounded by the per-RPC deadline), so any sync point a dead
peer would wedge instead raises :class:`~mxnet_tpu.dist_ps.PeerLost`
within the deadline.  Double fence around the rank-0 checkpoint gives
every iteration a consistent end-of-iter cut in ``CHAOS_STATE_DIR``.

Recovery (``CHAOS_EXPECT_KILL=1``): on PeerLost, every worker
``kv.reconnect()``s (waits for the replacement server to re-register
with the scheduler), syncs through the shared state dir — deliberately
NOT through server RPCs, which are exactly what just failed — rank 0
restores the servers from the last checkpoint blob
(``kv.set_checkpoint_state``), and everyone rolls its host state back
to the same cut and resumes.  The resumed trajectory must be bitwise
identical to an uninterrupted run (the acceptance criterion).

Env contract (set by tools/chaos_smoke.py / tests/test_chaos.py):
  CHAOS_STATE_DIR    shared scratch dir (required)
  CHAOS_ITERS        training iterations (default 4)
  CHAOS_EXPECT_KILL  "1": recover from PeerLost instead of dying
  MXNET_CHAOS        optional fault spec (inherited by every role)
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import json      # noqa: E402
import pickle    # noqa: E402
import time      # noqa: E402

import numpy as np  # noqa: E402

import mxnet_tpu as mx        # noqa: E402
from mxnet_tpu import chaos, dist_ps  # noqa: E402

ITERS = int(os.environ.get("CHAOS_ITERS", "4"))
STATE = os.environ["CHAOS_STATE_DIR"]
EXPECT_KILL = os.environ.get("CHAOS_EXPECT_KILL") == "1"

# placement (adler32 % 2): w0,w2,fence2 -> server0; w1,fence1 -> server1
# — both servers hold real keys AND a fence, so killing either one
# surfaces at the next sync point of every worker.
KEYS = ["w0", "w1", "w2"]
SHAPES = {"w0": (8,), "w1": (4, 4), "w2": (6,)}
RATE = 0.5
STATE_FILE = os.path.join(STATE, "ckpt.pkl")


def _atomic_write(path, data):
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "wb") as fh:
        fh.write(data)
    os.replace(tmp, path)


def _wait_for(paths, timeout=120.0, what="peer files"):
    deadline = time.monotonic() + timeout
    while not all(os.path.exists(p) for p in paths):
        if time.monotonic() > deadline:
            raise RuntimeError("timed out waiting for %s: %s"
                               % (what, paths))
        time.sleep(0.05)


def fence(kv, name):
    """Deadline-bounded barrier: a sync push completes only when every
    worker has contributed (PeerLost, never a hang, if one cannot)."""
    kv.push(name, mx.nd.ones((1,)))


def main():
    kv = mx.kv.create("dist_sync")
    rank, nworkers = kv.rank, kv.num_workers

    for i, k in enumerate(KEYS):
        kv.init(k, mx.nd.ones(SHAPES[k]) * (i + 1))
    kv.init("fence1", mx.nd.zeros((1,)))
    kv.init("fence2", mx.nd.zeros((1,)))
    # optimizer ON the servers: w -= rescale * sum(worker grads)
    kv.set_optimizer(mx.optimizer.create("test",
                                         rescale_grad=RATE / nworkers))

    w = {k: (np.ones(SHAPES[k], np.float32) * (i + 1))
         for i, k in enumerate(KEYS)}
    losses = []
    recoveries = []
    t = 0
    while t < ITERS:
        try:
            grads = {k: w[k] * np.float32(0.25)
                     + np.float32((rank + 1) * (t + 1) * 0.0625)
                     for k in KEYS}
            for k in KEYS:
                kv.push(k, mx.nd.array(grads[k], dtype="float32"))
            for k in KEYS:
                out = mx.nd.zeros(SHAPES[k])
                kv.pull(k, out=out)
                w[k] = out.asnumpy().copy()
            losses.append(float(sum(np.sum(w[k], dtype=np.float64)
                                    for k in KEYS)))
            fence(kv, "fence1")
            t += 1
            if rank == 0:
                blob = kv.get_checkpoint_state()
                _atomic_write(STATE_FILE, pickle.dumps(
                    {"it": t, "blob": blob, "w": w, "losses": losses}))
            fence(kv, "fence2")
        except dist_ps.PeerLost as exc:
            if not EXPECT_KILL:
                raise
            detect_wall = time.time()
            gen = len(recoveries) + 1
            # 1. transport recovery: wait for the replacement server to
            #    re-register, redial, reset push timestamps (all ranks)
            kv.reconnect(timeout=120)
            # 2. rank sync through the FILESYSTEM (server RPCs are what
            #    just failed; the scheduler stays out of it too so no
            #    anonymous-barrier counts can desynchronize)
            _atomic_write(os.path.join(STATE, "ready-%d-%d"
                                       % (gen, rank)), b"1")
            if rank == 0:
                _wait_for([os.path.join(STATE, "ready-%d-%d" % (gen, r))
                           for r in range(nworkers)],
                          what="worker ready markers")
                with open(STATE_FILE, "rb") as fh:
                    saved = pickle.load(fh)
                # 3. pour the last consistent cut back into the servers
                kv.set_checkpoint_state(saved["blob"])
                _atomic_write(os.path.join(STATE, "restored-%d" % gen),
                              b"1")
            else:
                _wait_for([os.path.join(STATE, "restored-%d" % gen)],
                          what="rank-0 restore marker")
            # 4. roll host state back to the same cut and resume
            with open(STATE_FILE, "rb") as fh:
                saved = pickle.load(fh)
            t = saved["it"]
            w = {k: np.array(v) for k, v in saved["w"].items()}
            losses = list(saved["losses"])
            recoveries.append({
                "gen": gen, "detect_wall": detect_wall,
                "resumed_at_iter": t, "reason": exc.reason,
                "peer_role": exc.role, "peer_rank": exc.rank})
            continue

    result = {
        "rank": rank,
        "nworkers": nworkers,
        "iters": t,
        "losses_hex": [np.float64(x).tobytes().hex() for x in losses],
        "losses": losses,
        "recoveries": recoveries,
        "fault_log": chaos.fault_log(),
        "chaos": chaos.describe(),
        "done_wall": time.time(),
    }
    from mxnet_tpu.lint import lockwitness
    if lockwitness.enabled():
        # MXNET_LOCKCHECK=1 turns the chaos run into a lock-order
        # witness: the smoke driver asserts this graph is cycle-free
        result["lockgraph"] = lockwitness.snapshot()
    _atomic_write(os.path.join(STATE, "result-%d.json" % rank),
                  json.dumps(result, indent=1).encode())
    print("worker %d/%d: %d iters, %d recoveries, %d injected faults"
          % (rank, nworkers, t, len(recoveries), len(chaos.fault_log())),
          flush=True)


if __name__ == "__main__":
    main()
