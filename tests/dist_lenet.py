"""Worker: train a small net with kvstore dist_sync via Module.fit.

Reference counterpart: ``tests/nightly/dist_lenet.py:30-50`` — the
end-to-end distributed gate: every worker runs the SAME Module.fit over
its shard of the data with a dist_sync kvstore; sync semantics must leave
all workers with identical parameters, and the model must actually learn.

Run through the launcher:

    python tools/launch.py -n 2 -s 1 python tests/dist_lenet.py
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.io import NDArrayIter  # noqa: E402


def build_net():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def main():
    kv = mx.kv.create("dist_sync")
    rank, nworkers = kv.rank, kv.num_workers

    # deterministic dataset, sharded by rank (reference num_parts/part_index)
    rng = np.random.RandomState(42)
    X = rng.randn(256, 10).astype(np.float32)
    Y = rng.randint(0, 4, 256).astype(np.float32)
    X[np.arange(256), Y.astype(int)] += 3.0
    shard = slice(rank * 256 // nworkers, (rank + 1) * 256 // nworkers)
    it = NDArrayIter(X[shard], Y[shard], batch_size=16)

    np.random.seed(7)             # identical init on every worker
    mod = mx.mod.Module(build_net(), context=mx.cpu())
    mod.fit(it, num_epoch=8, kvstore=kv, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.1})

    # all workers must hold identical parameters after sync training
    args, _ = mod.get_params()
    digest = np.concatenate([args[k].asnumpy().ravel()
                             for k in sorted(args)])
    kv.init("param_digest_sum", mx.nd.zeros(digest.shape))
    kv.set_optimizer(mx.optimizer.create("test", rescale_grad=-1.0))
    kv.push("param_digest_sum", mx.nd.array(digest))
    kv.barrier()
    summed = mx.nd.zeros(digest.shape)
    kv.pull("param_digest_sum", out=summed)
    mean_digest = summed.asnumpy() / nworkers
    if not np.allclose(mean_digest, digest, rtol=1e-5, atol=1e-6):
        raise AssertionError("rank %d parameters diverged from the fleet "
                             "mean (max diff %.3g)"
                             % (rank, np.abs(mean_digest - digest).max()))

    acc = mod.score(NDArrayIter(X, Y, batch_size=16), "acc")[0][1]
    assert acc > 0.9, "rank %d accuracy %.3f" % (rank, acc)
    kv.barrier()
    print("dist_lenet rank %d/%d OK acc=%.3f" % (rank, nworkers, acc))


if __name__ == "__main__":
    main()
