"""Pallas kernel tests: interpret mode on CPU vs jnp reference (SURVEY §4
doctrine: interpret-mode Pallas ↔ compiled cross-check)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu  # noqa: F401
from mxnet_tpu.ops import pallas_kernels as pk
from mxnet_tpu.parallel.ring_attention import local_attention

pytestmark = pytest.mark.skipif(not pk.HAS_PALLAS,
                                reason="pallas unavailable")


def _rand(b, h, s, d, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _rand(2, 3, 64, 16)
    out = pk.flash_attention(q, k, v, causal, None, 32, 32, True)
    ref = local_attention(q, k, v, causal=causal)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5), \
        np.abs(np.asarray(out) - np.asarray(ref)).max()


def test_flash_uneven_blocks():
    # seq not a multiple of the block size exercises the tail path
    q, k, v = _rand(1, 2, 48, 8, seed=1)
    out = pk.flash_attention(q, k, v, True, None, 32, 32, True)
    ref = local_attention(q, k, v, causal=True)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_gradients_match_reference():
    q, k, v = _rand(1, 2, 32, 8, seed=2)

    def loss_pallas(q, k, v):
        return jnp.sum(pk.flash_attention(q, k, v, True, None, 16, 16,
                                          True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(local_attention(q, k, v, causal=True) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-4), \
            np.abs(np.asarray(a) - np.asarray(b)).max()


def test_flash_sm_scale():
    q, k, v = _rand(1, 1, 16, 4, seed=3)
    out = pk.flash_attention(q, k, v, False, 0.5, 16, 16, True)
    ref = local_attention(q, k, v, sm_scale=0.5)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
