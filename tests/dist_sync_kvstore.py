"""Worker script for the dist_sync kvstore invariant test.

Reference counterpart: ``tests/nightly/dist_sync_kvstore.py:28-80`` — every
worker pushes rank-dependent values and asserts the EXACT aggregate on all
workers, covering dense keys, a big range-sharded key, and row_sparse.

Run via the local launcher (the pytest wrapper in test_dist_kvstore.py
does this automatically):

    python tools/launch.py -n 4 -s 2 python tests/dist_sync_kvstore.py
"""
import os

# Pin CPU before any jax backend touch: the axon sitecustomize plugin
# force-selects "axon,cpu", so the env var alone is NOT enough — the config
# update after import is what actually keeps worker processes off the TPU
# tunnel (same recipe as tests/conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import mxnet_tpu as mx  # noqa: E402

RATE = 2.0
ITERS = 3
# 'big' exceeds MXNET_KVSTORE_BIGARRAY_BOUND (set low by the test harness)
# so it range-shards across every server
SHAPES = {"3": (4, 4), "99": (50, 50), "big": (100, 60)}


def test_dense(kv, nworkers, rank):
    for k, s in SHAPES.items():
        kv.init(k, mx.nd.ones(s))
    tri = nworkers * (nworkers + 1) // 2
    for it in range(ITERS):
        for k, s in SHAPES.items():
            kv.push(k, mx.nd.ones(s) * (rank + 1))
            out = mx.nd.zeros(s)
            kv.pull(k, out=out)
            want = 1.0 - RATE * (it + 1) * tri
            got = out.asnumpy()
            assert np.all(got == want), \
                "dense key %s iter %d: got %r want %r" % (k, it, got.flat[0], want)


def test_row_sparse(kv, nworkers, rank, key="rsp", shape=None):
    shape = shape or (4 * nworkers + 4, 8)
    kv.init(key, mx.nd.zeros(shape))
    # every worker touches shared row 0 plus its own row (rank+1)
    rows = np.array([0, rank + 1], np.int64)
    dense = np.zeros(shape, np.float32)
    dense[rows] = rank + 1
    grad = mx.nd.sparse.row_sparse_array(
        (dense[rows], rows), shape=shape)
    kv.push("rsp", grad)

    all_rows = mx.nd.array(np.arange(shape[0]), dtype="int64")
    out = mx.nd.zeros(shape)
    kv.row_sparse_pull("rsp", out=out, row_ids=all_rows)
    got = out.asnumpy()

    want = np.zeros(shape, np.float32)
    tri = nworkers * (nworkers + 1) // 2
    want[0] = -RATE * tri
    for r in range(nworkers):
        want[r + 1] += -RATE * (r + 1)
    assert np.all(got == want), \
        "row_sparse: got rows %r want %r" % (got[:nworkers + 2, 0],
                                             want[:nworkers + 2, 0])


def test_bucketed_push_pull_all(kv, nworkers, rank):
    """Bucketed gradient all-reduce (kvstore.push_pull_all): every worker
    contributes rank-dependent grads for several keys; the flat-bucket
    transport round must return the exact global sum for each key."""
    shapes = [(5, 3), (7,), (2, 2, 2), (11,)]
    keys = ["pb%d" % i for i in range(len(shapes))]
    for k, s in zip(keys, shapes):
        kv.init(k, mx.nd.zeros(s))
    tri = nworkers * (nworkers + 1) // 2
    for it in range(2):
        vals = [mx.nd.ones(s) * (rank + 1 + it) for s in shapes]
        outs = kv.push_pull_all(keys, vals)
        want = tri + nworkers * it
        for k, o in zip(keys, outs):
            got = o.asnumpy()
            assert np.all(got == want), \
                "bucketed key %s iter %d: got %r want %r" \
                % (k, it, got.flat[0], want)
    kv.barrier()


def main():
    kv = mx.kv.create("dist_sync")
    nworkers, rank = kv.num_workers, kv.rank
    test_bucketed_push_pull_all(kv, nworkers, rank)
    kv.set_optimizer(mx.optimizer.create("test", rescale_grad=RATE))
    test_dense(kv, nworkers, rank)
    test_row_sparse(kv, nworkers, rank)
    kv.barrier()
    # liveness surface: everyone is still here (ref kvstore.h:328)
    assert kv.get_num_dead_node() == 0
    print("worker %d/%d: dist_sync invariants OK" % (rank, nworkers))


if __name__ == "__main__":
    main()
