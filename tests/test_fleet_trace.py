"""Fleet trace merging: the ISSUE-12 multi-process fixture.

One real scheduler + 2 servers + 2 workers run
tests/fleet_trace_worker.py with telemetry on and
``MXNET_TRACE_DUMP_DIR`` set, leaving one ``trace_<role>_<rank>.json``
artifact per role.  The assertions then go through the *tool* (the
artifact consumers a human would use):

* ``trace_report.py --fleet`` merges all five artifacts into one
  clock-aligned Chrome trace whose per-rank event streams stay
  monotonic under the clock shift;
* one trace id minted by a worker's step span crosses a push RPC's
  wire frame: the sender's ``ps_send:push`` and a server's
  ``ps_recv:push`` share it (joined by a flow-arrow pair in the merge);
* deleting a rank's artifact degrades the merge to a warning + partial
  timeline, never a traceback.
"""
import json
import os
import subprocess
import sys
from collections import defaultdict

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "fleet_trace_worker.py")
TRACE_REPORT = os.path.join(REPO, "tools", "trace_report.py")

sys.path.insert(0, os.path.join(REPO, "tools"))


def _run_fleet(tmp_path, iters=3):
    from launch import launch
    state = tmp_path / "state"
    traces = tmp_path / "traces"
    state.mkdir()
    traces.mkdir()
    env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "FLEET_STATE_DIR": str(state),
        "FLEET_ITERS": str(iters),
        "MXNET_TELEMETRY": "1",
        "MXNET_TRACE_DUMP_DIR": str(traces),
        "MXNET_PS_RPC_TIMEOUT_S": "30",
        "MXNET_PS_HEARTBEAT_S": "0.2",
        "MXNET_FLIGHT_DIR": str(state),
    }
    rcs = launch(2, 2, [sys.executable, WORKER], env_extra=env,
                 timeout=180)
    assert rcs == [0, 0], "fleet workers failed: %r" % (rcs,)
    results = []
    for r in range(2):
        with open(state / ("result-%d.json" % r)) as fh:
            results.append(json.load(fh))
    return traces, results


def _merge(traces, extra=()):
    proc = subprocess.run(
        [sys.executable, TRACE_REPORT, "--fleet", str(traces), "--json",
         *extra],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr + proc.stdout
    return json.loads(proc.stdout)


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("fleet")
    traces, results = _run_fleet(tmp_path)
    return tmp_path, traces, results


def test_artifacts_written_per_role(fleet):
    _, traces, _ = fleet
    names = sorted(os.listdir(traces))
    assert "trace_scheduler_0.json" in names
    assert "trace_worker_0.json" in names and "trace_worker_1.json" in names
    assert sum(n.startswith("trace_server_") for n in names) == 2
    with open(traces / "trace_worker_0.json") as fh:
        payload = json.load(fh)
    meta = payload["rank_meta"]
    assert meta["role"] == "worker" and meta["rank"] == 0
    assert "clock_offset_us" in meta
    assert meta["steps"] >= 3          # the step spans ticked the clock


def test_fleet_merge_clock_monotonic_per_rank(fleet):
    _, traces, _ = fleet
    summary = _merge(traces)
    assert not summary["problems"], summary["problems"]
    assert len(summary["ranks"]) == 5
    with open(summary["merged"]) as fh:
        merged = json.load(fh)["traceEvents"]
    # clock-monotonic per rank: the merge applies ONE constant shift per
    # rank (its heartbeat-estimated offset), so each rank's aligned
    # event stream is elementwise src_ts + offset — same order, same
    # deltas, no skew or reordering inside a rank
    by_pid = defaultdict(list)
    for e in merged:
        if e.get("ph") == "X" and isinstance(e.get("ts"), (int, float)):
            by_pid[e["pid"]].append(e["ts"])
    assert len(by_pid) == 5
    for rank_row in summary["ranks"]:
        pid, offset = rank_row["pid"], rank_row["clock_offset_us"]
        label = rank_row["label"]
        role = label.split("-")[0]
        src_path = traces / ("trace_%s_%s.json"
                             % (role, label.split("-")[1]))
        with open(src_path) as fh:
            src_ts = [e["ts"] for e in json.load(fh)["traceEvents"]
                      if e.get("ph") == "X"
                      and isinstance(e.get("ts"), (int, float))]
        aligned = by_pid[pid]
        assert len(aligned) == len(src_ts)
        assert all(abs(a - (s + offset)) < 1e-6
                   for a, s in zip(aligned, src_ts)), (
            "rank %s not shifted by one constant offset" % label)
    # and every rank contributed a labelled track
    labels = {e["args"]["name"] for e in merged
              if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert {"scheduler-0", "worker-0", "worker-1"} <= labels


def test_trace_id_crosses_push_rpc(fleet):
    _, traces, results = fleet
    step_ids = {tid for r in results for tid in r["step_trace_ids"] if tid}
    assert step_ids, "worker step spans minted no trace ids"
    with open(traces / "trace_worker_0.json") as fh:
        worker_events = json.load(fh)["traceEvents"]
    sends = [e for e in worker_events
             if e.get("name", "").startswith("ps_send:push")]
    assert sends, "no traced push sends in the worker artifact"
    send_ids = {e["args"]["trace_id"] for e in sends}
    assert send_ids & step_ids, (
        "push RPCs did not inherit the step span's trace id")
    # the same id arrived at a server
    recv_ids = set()
    for name in os.listdir(traces):
        if not name.startswith("trace_server_"):
            continue
        with open(traces / name) as fh:
            for e in json.load(fh)["traceEvents"]:
                if e.get("name", "").startswith("ps_recv:push"):
                    recv_ids.add(e["args"]["trace_id"])
    assert recv_ids & send_ids & step_ids, (
        "no push trace id observed on both the worker (send) and a "
        "server (recv)")
    # and the merge joined send/recv pairs with flow arrows
    summary = _merge(traces)
    assert summary["flows"] > 0


def test_fleet_degrades_on_missing_rank_artifact(fleet, tmp_path):
    _, traces, _ = fleet
    partial = tmp_path / "partial"
    partial.mkdir()
    import shutil
    for name in os.listdir(traces):
        if name.startswith("trace_") and name != "trace_server_1.json":
            shutil.copy(traces / name, partial / name)
    # a corrupt artifact rides along: must warn, not raise
    with open(partial / "trace_server_1.json", "w") as fh:
        fh.write("{torn")
    summary = _merge(partial, extra=("--out", str(partial / "m.json")))
    assert len(summary["ranks"]) == 4
    assert any("trace_server_1.json" in p for p in summary["problems"])
    assert summary["merged"] and os.path.exists(summary["merged"])


def test_fleet_mode_empty_dir_fails_loudly(tmp_path):
    empty = tmp_path / "empty"
    empty.mkdir()
    proc = subprocess.run(
        [sys.executable, TRACE_REPORT, "--fleet", str(empty), "--json"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    summary = json.loads(proc.stdout)
    assert summary["problems"]
