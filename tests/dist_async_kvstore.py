"""Worker script for the dist_async kvstore test.

Reference counterpart: the async mode of the dist server
(``src/kvstore/kvstore.cc:49-51`` selects it; ``kvstore_dist_server.h``
applies each push immediately, no per-iteration barrier). The invariant
is eventual, not exact: after every worker pushes ``ITERS`` gradients of
+1 per element through the server-side SGD updater (lr so each push adds
+1) and a final barrier, the pulled value must equal
``1 + nworkers * ITERS`` on every worker — asynchrony changes the order,
never the total.

Run via the local launcher:

    python tools/launch.py -n 4 -s 2 python tests/dist_async_kvstore.py
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import mxnet_tpu as mx  # noqa: E402

ITERS = 5
SHAPES = {"a": (4, 4), "big": (100, 60)}


def main():
    kv = mx.kv.create("dist_async")
    assert "async" in kv.type
    rank, nworkers = kv.rank, kv.num_workers

    # server-side updater: w += -lr * grad with lr=-1 → each push of ones
    # adds exactly +1 per element regardless of arrival order
    opt = mx.optimizer.create("test", rescale_grad=-1.0)
    kv.set_optimizer(opt)

    for key, shape in SHAPES.items():
        kv.init(key, mx.nd.ones(shape))

    for _ in range(ITERS):
        for key, shape in SHAPES.items():
            kv.push(key, mx.nd.ones(shape))

    # async: no implicit sync — barrier makes every push visible first
    kv.barrier()

    expected = 1.0 + nworkers * ITERS
    for key, shape in SHAPES.items():
        out = mx.nd.zeros(shape)
        kv.pull(key, out=out)
        got = out.asnumpy()
        assert np.allclose(got, expected), \
            "rank %d key %s: got %r expected %r" % (rank, key,
                                                    got.ravel()[:4], expected)
    print("dist_async rank %d/%d OK (value %.1f)"
          % (rank, nworkers, expected))


if __name__ == "__main__":
    main()
