"""Deterministic dist worker for the fleet-trace fixture.

Launched by tests/test_fleet_trace.py as scheduler + servers + workers
(tools/launch.py runs this same script in every role; ``kv.create``
dispatches).  Each worker runs a few push/pull rounds inside
``trainer_step`` spans with telemetry ON, so every role's trace buffer
fills with step spans and ``ps_send``/``ps_recv`` RPC events carrying
propagated trace ids — and every role dumps its
``trace_<role>_<rank>.json`` artifact into ``MXNET_TRACE_DUMP_DIR`` at
exit (scheduler/server mains, worker finalize).  The artifacts are what
``tools/trace_report.py --fleet`` merges; the worker additionally writes
``result-<rank>.json`` with the trace ids it used per step so the test
can assert the same id crossed the wire.

Env contract (set by the test):
  FLEET_STATE_DIR        shared scratch dir (results; required)
  MXNET_TRACE_DUMP_DIR   where the per-rank artifacts land (required)
  FLEET_ITERS            push/pull rounds (default 3)
  MXNET_TELEMETRY=1      tracing on in every role
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_TELEMETRY", "1")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import json      # noqa: E402

import numpy as np  # noqa: E402

import mxnet_tpu as mx          # noqa: E402
from mxnet_tpu import telemetry  # noqa: E402

ITERS = int(os.environ.get("FLEET_ITERS", "3"))
STATE = os.environ["FLEET_STATE_DIR"]

KEYS = ["w0", "w1"]
SHAPES = {"w0": (8,), "w1": (4, 4)}


def main():
    telemetry.set_enabled(True)
    kv = mx.kv.create("dist_sync")        # scheduler/server roles exit in
    rank = kv.rank                        # create(); only workers return
    for i, k in enumerate(KEYS):
        kv.init(k, mx.nd.ones(SHAPES[k]) * (i + 1))

    step_trace_ids = []
    for _ in range(ITERS):
        with telemetry.span("trainer_step", cat="step",
                            hist="step_time_us"):
            step_trace_ids.append(telemetry.trace_context())
            for k in KEYS:
                kv.push(k, mx.nd.array(
                    np.full(SHAPES[k], 0.5, np.float32)))
            for k in KEYS:
                out = mx.nd.zeros(SHAPES[k])
                kv.pull(k, out=out)

    fleet = None
    try:
        # deterministic fleet fetch (heartbeat cadence is too slow for a
        # short fixture): also caches the snapshot for /fleet
        fleet = kv._trans.fleet_health()
    except Exception:
        pass

    result = {"rank": rank,
              "step_trace_ids": step_trace_ids,
              "fleet_ranks": sorted((fleet or {}).get("ranks", {}))}
    path = os.path.join(STATE, "result-%d.json" % rank)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as fh:
        json.dump(result, fh, indent=1)
    os.replace(tmp, path)
    print("fleet worker %d: %d steps" % (rank, ITERS), flush=True)


if __name__ == "__main__":
    main()
