"""Profiler / Monitor / visualization / log parity tests (SURVEY §5.1, §5.5)."""
import json
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler, monitor, visualization, log


def test_profiler_chrome_trace(tmp_path):
    fname = str(tmp_path / "trace.json")
    profiler.set_config(mode="all", filename=fname)
    profiler.set_state("run")
    a = nd.array(np.random.randn(8, 8).astype(np.float32))
    b = nd.dot(a, a)
    b.wait_to_read()
    profiler.set_state("stop")
    out = profiler.dump_profile()
    with open(out) as f:
        trace = json.load(f)
    names = [e["name"] for e in trace["traceEvents"]]
    assert "dot" in names
    assert all(e["ph"] == "X" for e in trace["traceEvents"])


def test_profiler_executor_span(tmp_path):
    fname = str(tmp_path / "trace2.json")
    profiler.set_config(mode="symbolic", filename=fname)
    profiler.set_state("run")
    x = mx.sym.var("x")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    ex = y.simple_bind(mx.cpu(), x=(2, 3))
    ex.forward(is_train=False)
    profiler.set_state("stop")
    with open(profiler.dump_profile()) as f:
        trace = json.load(f)
    assert any(e["name"] == "executor_forward"
               for e in trace["traceEvents"])


def test_profiler_marker(tmp_path):
    profiler.set_config(filename=str(tmp_path / "t3.json"))
    profiler.set_state("run")
    with profiler.Marker("data-load"):
        pass
    profiler.set_state("stop")
    with open(profiler.dump_profile()) as f:
        trace = json.load(f)
    assert any(e["name"] == "data-load" for e in trace["traceEvents"])


def test_monitor_collects_stats():
    x = mx.sym.var("x")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    y = mx.sym.Activation(y, act_type="relu", name="act")
    ex = y.simple_bind(mx.cpu(), x=(2, 3))
    mon = monitor.Monitor(interval=1)
    mon.install(ex)
    mon.tic()
    ex.forward(is_train=False)
    res = mon.toc()
    assert len(res) > 0
    names = [k for _, k, _ in res]
    assert any("fc" in n for n in names)


def test_monitor_pattern_filter():
    x = mx.sym.var("x")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    y = mx.sym.Activation(y, act_type="relu", name="act")
    ex = y.simple_bind(mx.cpu(), x=(2, 3))
    mon = monitor.Monitor(interval=1, pattern=".*act.*")
    mon.install(ex)
    mon.tic()
    ex.forward(is_train=False)
    res = mon.toc()
    assert res and all("act" in k for _, k, _ in res)


def test_print_summary(capsys):
    x = mx.sym.var("data")
    y = mx.sym.FullyConnected(x, num_hidden=16, name="fc1")
    y = mx.sym.Activation(y, act_type="relu", name="relu1")
    y = mx.sym.FullyConnected(y, num_hidden=4, name="fc2")
    total = visualization.print_summary(y, shape={"data": (2, 8)})
    out = capsys.readouterr().out
    assert "fc1" in out and "Total params" in out
    # fc1: 8*16+16, fc2: 16*4+4
    assert total == 8 * 16 + 16 + 16 * 4 + 4


def test_plot_network_graceful():
    x = mx.sym.var("data")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    try:
        dot = visualization.plot_network(y, shape={"data": (1, 3)})
        assert "fc" in dot.source
    except ImportError:
        pass  # graphviz not installed: reference behavior is to raise


def test_get_logger(tmp_path):
    logger = log.get_logger("mxtest", filename=str(tmp_path / "l.log"),
                            level=log.INFO)
    logger.info("hello")
    assert (tmp_path / "l.log").read_text().strip() != ""


def test_profiler_pause_resume_keeps_events(tmp_path):
    profiler.set_config(filename=str(tmp_path / "pr.json"))
    profiler.set_state("run")
    with profiler.Marker("phase1"):
        pass
    profiler.pause()
    with profiler.Marker("hidden"):
        pass
    profiler.resume()
    with profiler.Marker("phase2"):
        pass
    profiler.set_state("stop")
    with open(profiler.dump_profile()) as f:
        names = [e["name"] for e in json.load(f)["traceEvents"]]
    assert "phase1" in names and "phase2" in names
    assert "hidden" not in names


def test_monitor_interval_skips_eager_path():
    x = mx.sym.var("x")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    ex = y.simple_bind(mx.cpu(), x=(2, 3))
    mon = monitor.Monitor(interval=3)
    mon.install(ex)
    calls = []
    orig = ex._forward_monitored
    ex._forward_monitored = lambda *a, **k: (calls.append(1),
                                             orig(*a, **k))[1]
    for i in range(3):
        mon.tic()
        ex.forward(is_train=False)
        mon.toc()
    # only step 0 (i % 3 == 0) may take the slow monitored path
    assert len(calls) == 1, calls
