"""Profiler / Monitor / visualization / log parity tests (SURVEY §5.1, §5.5)."""
import json
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler, monitor, visualization, log


def test_profiler_chrome_trace(tmp_path):
    fname = str(tmp_path / "trace.json")
    profiler.set_config(mode="all", filename=fname)
    profiler.set_state("run")
    a = nd.array(np.random.randn(8, 8).astype(np.float32))
    b = nd.dot(a, a)
    b.wait_to_read()
    profiler.set_state("stop")
    out = profiler.dump_profile()
    with open(out) as f:
        trace = json.load(f)
    names = [e["name"] for e in trace["traceEvents"]]
    assert "dot" in names
    # spans are complete events; metadata ('M') events name the tracks
    assert all(e["ph"] in ("X", "M") for e in trace["traceEvents"])
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert any(e["name"] == "thread_name"
               and e["args"]["name"] == "eager-dispatch" for e in meta)


def test_profiler_executor_span(tmp_path):
    fname = str(tmp_path / "trace2.json")
    profiler.set_config(mode="symbolic", filename=fname)
    profiler.set_state("run")
    x = mx.sym.var("x")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    ex = y.simple_bind(mx.cpu(), x=(2, 3))
    ex.forward(is_train=False)
    profiler.set_state("stop")
    with open(profiler.dump_profile()) as f:
        trace = json.load(f)
    assert any(e["name"] == "executor_forward"
               for e in trace["traceEvents"])


def test_profiler_marker(tmp_path):
    profiler.set_config(filename=str(tmp_path / "t3.json"))
    profiler.set_state("run")
    with profiler.Marker("data-load"):
        pass
    profiler.set_state("stop")
    with open(profiler.dump_profile()) as f:
        trace = json.load(f)
    assert any(e["name"] == "data-load" for e in trace["traceEvents"])


def test_monitor_collects_stats():
    x = mx.sym.var("x")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    y = mx.sym.Activation(y, act_type="relu", name="act")
    ex = y.simple_bind(mx.cpu(), x=(2, 3))
    mon = monitor.Monitor(interval=1)
    mon.install(ex)
    mon.tic()
    ex.forward(is_train=False)
    res = mon.toc()
    assert len(res) > 0
    names = [k for _, k, _ in res]
    assert any("fc" in n for n in names)


def test_monitor_pattern_filter():
    x = mx.sym.var("x")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    y = mx.sym.Activation(y, act_type="relu", name="act")
    ex = y.simple_bind(mx.cpu(), x=(2, 3))
    mon = monitor.Monitor(interval=1, pattern=".*act.*")
    mon.install(ex)
    mon.tic()
    ex.forward(is_train=False)
    res = mon.toc()
    assert res and all("act" in k for _, k, _ in res)


def test_print_summary(capsys):
    x = mx.sym.var("data")
    y = mx.sym.FullyConnected(x, num_hidden=16, name="fc1")
    y = mx.sym.Activation(y, act_type="relu", name="relu1")
    y = mx.sym.FullyConnected(y, num_hidden=4, name="fc2")
    total = visualization.print_summary(y, shape={"data": (2, 8)})
    out = capsys.readouterr().out
    assert "fc1" in out and "Total params" in out
    # fc1: 8*16+16, fc2: 16*4+4
    assert total == 8 * 16 + 16 + 16 * 4 + 4


def test_plot_network_graceful():
    x = mx.sym.var("data")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    try:
        dot = visualization.plot_network(y, shape={"data": (1, 3)})
        assert "fc" in dot.source
    except ImportError:
        pass  # graphviz not installed: reference behavior is to raise


def test_get_logger(tmp_path):
    logger = log.get_logger("mxtest", filename=str(tmp_path / "l.log"),
                            level=log.INFO)
    logger.info("hello")
    assert (tmp_path / "l.log").read_text().strip() != ""


def test_profiler_pause_resume_keeps_events(tmp_path):
    profiler.set_config(filename=str(tmp_path / "pr.json"))
    profiler.set_state("run")
    with profiler.Marker("phase1"):
        pass
    profiler.pause()
    with profiler.Marker("hidden"):
        pass
    profiler.resume()
    with profiler.Marker("phase2"):
        pass
    profiler.set_state("stop")
    with open(profiler.dump_profile()) as f:
        names = [e["name"] for e in json.load(f)["traceEvents"]]
    assert "phase1" in names and "phase2" in names
    assert "hidden" not in names


def test_nested_marker_spans(tmp_path):
    """Nested Markers record parent/depth and nest by time containment
    (the hierarchical-span contract, ISSUE 2)."""
    profiler.set_config(filename=str(tmp_path / "nest.json"))
    profiler.set_state("run")
    with profiler.Marker("outer"):
        with profiler.Marker("inner"):
            pass
    profiler.set_state("stop")
    with open(profiler.dump_profile()) as f:
        events = json.load(f)["traceEvents"]
    outer = next(e for e in events if e["name"] == "outer")
    inner = next(e for e in events if e["name"] == "inner")
    assert inner["args"]["parent"] == "outer"
    assert inner["args"]["depth"] == 1
    assert outer["args"]["parent"] is None
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]


def test_counter_thread_safety():
    """Concurrent bump() must not lose increments (counters are the
    perf-contract currency; a lost bump fakes a passing gate)."""
    import threading
    name = "thread_safety_probe"
    base = profiler.counter(name)
    n_threads, n_bumps = 8, 5000

    def worker():
        for _ in range(n_bumps):
            profiler.bump(name)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert profiler.counter(name) - base == n_threads * n_bumps


def test_set_state_concurrent_transitions():
    """The set_state race fix: concurrent run/stop toggles must leave the
    profiler in a consistent state and never double-start jax tracing
    (jax_tracing transitions are claimed under the lock)."""
    import threading
    errors = []

    def toggler(state):
        try:
            for _ in range(200):
                profiler.set_state(state)
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=toggler,
                                args=("run" if i % 2 else "stop",))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    profiler.set_state("stop")
    assert not profiler.is_running()
    assert profiler._state["jax_tracing"] is False


def test_monitor_pattern_filter_eager_and_compiled_paths():
    """Pattern filtering on the monitored (eager) batch; the off-interval
    batch takes the compiled program and must collect nothing."""
    x = mx.sym.var("x")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    y = mx.sym.Activation(y, act_type="relu", name="act")
    ex = y.simple_bind(mx.cpu(), x=(2, 3))
    mon = monitor.Monitor(interval=2, pattern=".*act.*")
    mon.install(ex)

    eager_calls = []
    orig = ex._forward_monitored
    ex._forward_monitored = lambda *a, **k: (eager_calls.append(1),
                                             orig(*a, **k))[1]

    mon.tic()
    ex.forward(is_train=False)          # step 0: monitored eager walk
    res0 = mon.toc()
    assert res0 and all("act" in k for _, k, _ in res0)
    assert all("fc" not in k.split("_")[0] for _, k, _ in res0)

    mon.tic()
    ex.forward(is_train=False)          # step 1: compiled program
    res1 = mon.toc()
    assert res1 == []
    assert len(eager_calls) == 1        # only step 0 walked eagerly


def test_monitor_interval_skips_eager_path():
    x = mx.sym.var("x")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    ex = y.simple_bind(mx.cpu(), x=(2, 3))
    mon = monitor.Monitor(interval=3)
    mon.install(ex)
    calls = []
    orig = ex._forward_monitored
    ex._forward_monitored = lambda *a, **k: (calls.append(1),
                                             orig(*a, **k))[1]
    for i in range(3):
        mon.tic()
        ex.forward(is_train=False)
        mon.toc()
    # only step 0 (i % 3 == 0) may take the slow monitored path
    assert len(calls) == 1, calls
