"""Initializer parity (reference tests/python/unittest/test_init.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def _init_arr(init, name="weight", shape=(50, 100)):
    arr = nd.zeros(shape)
    desc = mx.init.InitDesc(name)
    init(desc, arr)
    return arr.asnumpy()


def test_zero_one_constant():
    assert (_init_arr(mx.init.Zero()) == 0).all()
    assert (_init_arr(mx.init.One()) == 1).all()
    assert (_init_arr(mx.init.Constant(3.5)) == 3.5).all()


def test_uniform_range():
    out = _init_arr(mx.init.Uniform(0.5))
    assert out.min() >= -0.5 and out.max() <= 0.5
    assert out.std() > 0.1


def test_normal_stats():
    out = _init_arr(mx.init.Normal(2.0), shape=(100, 100))
    assert abs(out.mean()) < 0.1
    assert 1.9 < out.std() < 2.1


def test_xavier_scale():
    shape = (64, 128)
    out = _init_arr(mx.init.Xavier(rnd_type="uniform", factor_type="avg",
                                   magnitude=3), shape=shape)
    bound = np.sqrt(3.0 / ((shape[0] + shape[1]) / 2))
    assert out.min() >= -bound - 1e-6 and out.max() <= bound + 1e-6


def test_orthogonal_is_orthogonal():
    out = _init_arr(mx.init.Orthogonal(), shape=(32, 32))
    eye = out @ out.T
    assert np.allclose(eye, np.eye(32) * eye[0, 0], atol=1e-3)


def test_name_pattern_dispatch():
    """Initializer dispatches on name suffix: bias→0, gamma→1, beta→0."""
    init = mx.init.Xavier()
    bias = nd.zeros((10,))
    init(mx.init.InitDesc("fc1_bias"), bias)
    assert (bias.asnumpy() == 0).all()
    gamma = nd.zeros((10,))
    init(mx.init.InitDesc("bn0_gamma"), gamma)
    assert (gamma.asnumpy() == 1).all()
    mean = nd.ones((10,))
    init(mx.init.InitDesc("bn0_running_mean"), mean)
    assert (mean.asnumpy() == 0).all()
    var = nd.zeros((10,))
    init(mx.init.InitDesc("bn0_running_var"), var)
    assert (var.asnumpy() == 1).all()


def test_msra_prelu():
    out = _init_arr(mx.init.MSRAPrelu(), shape=(64, 64))
    assert out.std() > 0


def test_bilinear_upsampling_kernel():
    arr = nd.zeros((1, 1, 4, 4))
    mx.init.Bilinear()(mx.init.InitDesc("upsample_weight"), arr)
    k = arr.asnumpy()[0, 0]
    assert k.max() <= 1.0 and k[1, 1] > k[0, 0]


def test_mixed_initializer():
    init = mx.init.Mixed(["bias", ".*"], [mx.init.Zero(), mx.init.One()])
    w = nd.zeros((4,))
    init(mx.init.InitDesc("fc_weight"), w)
    assert (w.asnumpy() == 1).all()
    b = nd.ones((4,))
    init(mx.init.InitDesc("fc_bias"), b)
    assert (b.asnumpy() == 0).all()


def test_variable_level_init_override_honored():
    """mx.sym.Variable(init=...) must WIN over both the suffix dispatch
    and the global initializer (attr_dict used to strip the __init__
    key, silently ignoring per-variable overrides)."""
    import mxnet_tpu as mx
    net = mx.sym.FullyConnected(
        mx.sym.Variable("data"),
        weight=mx.sym.Variable("fcw", init=mx.initializer.Constant(3.5)),
        num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                               name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 3))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(initializer=mx.initializer.Zero())
    w = mod._exec_group.execs[0].arg_dict["fcw"].asnumpy()
    assert np.all(w == 3.5), "per-variable init override ignored"


def test_variable_lr_mult_reaches_optimizer():
    """__lr_mult__ set on a Variable must reach the optimizer's
    multiplier table via sym_info (same attr_dict key contract)."""
    import mxnet_tpu as mx
    net = mx.sym.FullyConnected(
        mx.sym.Variable("data"),
        weight=mx.sym.Variable("fcw", lr_mult=0.25),
        num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                               name="softmax")
    opt = mx.optimizer.SGD(learning_rate=1.0, sym=net,
                           param_idx2name={0: "fcw"})
    assert opt._get_lr(0) == 0.25


def test_string_form_init_attr_accepted():
    """Gluon-default string attrs (init="zeros") must initialize like the
    reference's create(name-or-JSON) (ref python/mxnet/initializer.py:134).
    Regression: r4 only parsed the JSON form and crashed Module.init_params
    on baseline workload #4 (inception-v3 multi-device kvstore)."""
    import mxnet_tpu as mx
    net = mx.sym.FullyConnected(
        mx.sym.Variable("data"),
        weight=mx.sym.Variable("fcw", init="ones"),
        num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                               name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 3))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(initializer=mx.initializer.Zero())
    w = mod._exec_group.execs[0].arg_dict["fcw"].asnumpy()
    assert np.all(w == 1.0), "string-form __init__ attr ignored or crashed"
    # create() itself must accept name, JSON, and instance forms.
    assert isinstance(mx.initializer.create("zeros"), mx.initializer.Zero)
    assert isinstance(mx.initializer.create('["uniform", {"scale": 0.1}]'),
                      mx.initializer.Uniform)
    inst = mx.initializer.Normal(0.5)
    assert mx.initializer.create(inst) is inst
