"""Legacy checkpoint-format compatibility (VERDICT r3 #7).

Golden byte-literal fixtures are generated here to the layouts the
reference documents (src/ndarray/ndarray.cc:821-943 LegacyLoad /
LegacyTShapeLoad; src/nnvm/legacy_json_util.cc upgrade chain) — NOT via
this repo's writer, so reader bugs can't cancel writer bugs.
"""
import json
import struct

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu import symbol as S

LIST_MAGIC = 0x112
V1_MAGIC = 0xF993FAC8
V2_MAGIC = 0xF993FAC9


def _file(records, keys=()):
    buf = [struct.pack("<QQQ", LIST_MAGIC, 0, len(records))]
    buf += records
    buf.append(struct.pack("<Q", len(keys)))
    for k in keys:
        kb = k.encode()
        buf.append(struct.pack("<Q", len(kb)) + kb)
    return b"".join(buf)


def _v2_record(arr):
    a = np.asarray(arr, np.float32)
    return (struct.pack("<Ii", V2_MAGIC, 0)
            + struct.pack("<I", a.ndim)
            + struct.pack("<%dq" % a.ndim, *a.shape)
            + struct.pack("<iii", 1, 0, 0)
            + a.tobytes())


def _v1_record(arr):
    a = np.asarray(arr, np.float32)
    return (struct.pack("<I", V1_MAGIC)
            + struct.pack("<I", a.ndim)
            + struct.pack("<%dq" % a.ndim, *a.shape)
            + struct.pack("<iii", 1, 0, 0)   # ctx cpu(0), type_flag f32
            + a.tobytes())


def _v0_record(arr):
    a = np.asarray(arr, np.float32)
    return (struct.pack("<I", a.ndim)                 # no magic: ndim
            + struct.pack("<%dI" % a.ndim, *a.shape)  # uint32 dims
            + struct.pack("<iii", 1, 0, 0)
            + a.tobytes())


def test_v1_ndarray_record_loads(tmp_path):
    ref = np.arange(12, dtype=np.float32).reshape(3, 4)
    f = tmp_path / "v1.params"
    f.write_bytes(_file([_v1_record(ref)]))
    (out,) = nd.load(str(f))
    np.testing.assert_array_equal(out.asnumpy(), ref)


def test_v0_ndarray_record_loads(tmp_path):
    ref = np.arange(6, dtype=np.float32).reshape(2, 3)
    f = tmp_path / "v0.params"
    f.write_bytes(_file([_v0_record(ref)], keys=["arg:w"]))
    loaded = nd.load(str(f))
    np.testing.assert_array_equal(loaded["arg:w"].asnumpy(), ref)


def test_mixed_version_file(tmp_path):
    a = np.ones((2, 2), np.float32)
    b = np.full((3,), 7, np.float32)
    c = np.arange(4, dtype=np.float32)
    f = tmp_path / "mixed.params"
    f.write_bytes(_file([_v2_record(a), _v1_record(b), _v0_record(c)],
                        keys=["x", "y", "z"]))
    loaded = nd.load(str(f))
    np.testing.assert_array_equal(loaded["x"].asnumpy(), a)
    np.testing.assert_array_equal(loaded["y"].asnumpy(), b)
    np.testing.assert_array_equal(loaded["z"].asnumpy(), c)


def test_corrupt_magic_rejected(tmp_path):
    f = tmp_path / "bad.params"
    f.write_bytes(_file([struct.pack("<I", 0xDEAD0000) + b"\0" * 64]))
    try:
        nd.load(str(f))
    except mx.MXNetError:
        return
    raise AssertionError("corrupt magic should raise MXNetError")


def _legacy_json(attr_key, version=None):
    """An FC->relu graph in the older JSON dialects: node attrs under
    *attr_key* ('attr' for ~0.9-1.x, 'param' for pre-0.9)."""
    graph = {
        "nodes": [
            {"op": "null", "name": "data", attr_key: {}, "inputs": []},
            {"op": "null", "name": "fc_weight", attr_key: {}, "inputs": []},
            {"op": "null", "name": "fc_bias", attr_key: {}, "inputs": []},
            {"op": "FullyConnected", "name": "fc",
             attr_key: {"num_hidden": "8"},
             "inputs": [[0, 0], [1, 0], [2, 0]]},
            {"op": "Activation", "name": "relu",
             attr_key: {"act_type": "relu"}, "inputs": [[3, 0]]},
        ],
        "arg_nodes": [0, 1, 2],
        "heads": [[4, 0]],
    }
    if version is not None:
        graph["attrs"] = {"mxnet_version": ["int", version]}
    return json.dumps(graph)


def test_attr_key_json_loads():
    sym = S.load_json(_legacy_json("attr", version=905))
    assert sym.list_arguments() == ["data", "fc_weight", "fc_bias"]
    ex = sym.simple_bind(mx.cpu(), grad_req="null", data=(2, 4))
    ex.arg_dict["data"][:] = -np.ones((2, 4), np.float32)
    out = ex.forward()[0]
    assert out.shape == (2, 8)


def test_param_key_json_loads():
    sym = S.load_json(_legacy_json("param"))
    assert sym.list_outputs() == ["relu_output"]
    a, o, _ = sym.infer_shape(data=(3, 5))
    assert o[0] == (3, 8)


def test_pre090_var_attr_hoist():
    """Pre-0.9 JSONs kept lr_mult etc. on the consuming op node; the
    upgrade shim hoists them into __key__ form (legacy_json_util.cc
    UpgradeJSON_FixParsing)."""
    graph = json.loads(_legacy_json("param"))
    graph["nodes"][3]["param"]["lr_mult"] = "0.5"
    sym = S.load_json(json.dumps(graph))
    node = sym._outputs[0][0].inputs[0][0]
    assert node.attrs.get("__lr_mult__") == 0.5
    assert "lr_mult" not in node.attrs


def test_op_dtype_param_not_clobbered():
    """dtype/shape on an OP node are real op params (e.g. Cast) and must
    survive the upgrade shim untouched."""
    graph = {
        "nodes": [
            {"op": "null", "name": "data", "param": {}, "inputs": []},
            {"op": "Cast", "name": "c", "param": {"dtype": "float16"},
             "inputs": [[0, 0]]},
        ],
        "arg_nodes": [0], "heads": [[1, 0]],
    }
    sym = S.load_json(json.dumps(graph))
    _, o, _ = sym.infer_type(data=np.float32)
    assert np.dtype(o[0]) == np.float16


def test_variable_flat_metadata_hoisted():
    """Legacy variable nodes stored shape/lr_mult flat — must land in the
    namespaced form _infer and the optimizer read."""
    graph = {
        "nodes": [
            {"op": "null", "name": "w",
             "attr": {"shape": "(3, 4)", "lr_mult": "2.0"}, "inputs": []},
        ],
        "arg_nodes": [0], "heads": [[0, 0]],
    }
    sym = S.load_json(json.dumps(graph))
    node = sym._outputs[0][0]
    assert node.attrs.get("__shape__") == (3, 4)
    assert node.attrs.get("__lr_mult__") == 2.0
    a, _, _ = sym.infer_shape()
    assert a[0] == (3, 4)


def test_roundtrip_still_modern():
    x = S.Variable("data")
    y = S.Activation(x, act_type="tanh", name="t")
    again = S.load_json(y.tojson())
    assert again.tojson() == y.tojson()
