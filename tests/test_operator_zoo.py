"""Operator breadth: conv/pool shape zoo, dtype sweeps, numeric gradients.

Widens operator coverage toward the reference's 130-test
``tests/python/unittest/test_operator.py`` + the fp16 sweep of
``tests/python/train/test_dtype.py`` (VERDICT r2 weak #8): stride/pad/
dilate/group combinations for Convolution, kernel/stride/pool_type
combinations for Pooling, bf16/fp16 forward consistency vs float32, and
finite-difference gradient checks on representative ops.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_numeric_gradient


def _expected_conv_dim(size, kernel, stride, pad, dilate):
    eff = dilate * (kernel - 1) + 1
    return (size + 2 * pad - eff) // stride + 1


CONV_CASES = [
    # (in_hw, num_filter, kernel, stride, pad, dilate, groups)
    (9, 4, 3, 1, 0, 1, 1),
    (9, 4, 3, 2, 1, 1, 1),
    (12, 6, 5, 2, 2, 1, 1),
    (11, 4, 3, 1, 1, 2, 1),
    (8, 4, 1, 1, 0, 1, 1),
    (10, 8, 3, 1, 1, 1, 2),      # grouped
    (13, 4, (3, 5), (2, 1), (1, 2), 1, 1),   # asymmetric
]


@pytest.mark.parametrize("hw,nf,k,s,p,d,g", CONV_CASES)
def test_convolution_shape_zoo(hw, nf, k, s, p, d, g):
    kh, kw = (k, k) if isinstance(k, int) else k
    sh, sw = (s, s) if isinstance(s, int) else s
    ph, pw = (p, p) if isinstance(p, int) else p
    cin = 4
    x = nd.array(np.random.randn(2, cin, hw, hw).astype(np.float32))
    w = nd.array(np.random.randn(nf, cin // g, kh, kw).astype(np.float32))
    b = nd.array(np.zeros(nf, np.float32))
    out = nd.Convolution(x, w, b, kernel=(kh, kw), stride=(sh, sw),
                         pad=(ph, pw), dilate=(d, d), num_filter=nf,
                         num_group=g)
    eh = _expected_conv_dim(hw, kh, sh, ph, d)
    ew = _expected_conv_dim(hw, kw, sw, pw, d)
    assert out.shape == (2, nf, eh, ew), out.shape
    assert np.isfinite(out.asnumpy()).all()


POOL_CASES = [
    ("max", 2, 2, 0, False),
    ("max", 3, 2, 1, False),
    ("avg", 2, 2, 0, False),
    ("avg", 3, 1, 1, False),
    ("max", 3, 2, 0, True),      # global ignores kernel
]


@pytest.mark.parametrize("ptype,k,s,p,global_pool", POOL_CASES)
def test_pooling_shape_zoo(ptype, k, s, p, global_pool):
    x = nd.array(np.random.randn(2, 3, 9, 9).astype(np.float32))
    out = nd.Pooling(x, pool_type=ptype, kernel=(k, k), stride=(s, s),
                     pad=(p, p), global_pool=global_pool)
    if global_pool:
        assert out.shape == (2, 3, 1, 1)
    else:
        e = (9 + 2 * p - k) // s + 1
        assert out.shape == (2, 3, e, e)
    # avg pooling of ones is exactly one wherever the window fits fully
    if ptype == "avg" and p == 0 and not global_pool:
        ones = nd.Pooling(nd.ones((1, 1, 8, 8)), pool_type="avg",
                          kernel=(k, k), stride=(s, s))
        np.testing.assert_allclose(ones.asnumpy(), 1.0, rtol=1e-6)


def test_deconvolution_inverts_shape():
    x = nd.array(np.random.randn(1, 3, 5, 5).astype(np.float32))
    w = nd.array(np.random.randn(3, 4, 3, 3).astype(np.float32))
    out = nd.Deconvolution(x, w, kernel=(3, 3), stride=(2, 2),
                           num_filter=4, no_bias=True)
    assert out.shape[2] == (5 - 1) * 2 + 3


# ---- dtype sweeps (the MXU design point is bf16; fp16 for parity) ----

_ELEMWISE = ["relu", "sigmoid", "tanh", "exp", "sqrt", "square"]


@pytest.mark.parametrize("dtype", ["float16", "bfloat16"])
@pytest.mark.parametrize("opname", _ELEMWISE)
def test_unary_low_precision_consistency(dtype, opname):
    """Low-precision forward within a precision-scaled tolerance of fp32
    (reference check_consistency doctrine, test_utils.py:1203)."""
    x32 = np.abs(np.random.randn(4, 16).astype(np.float32)) + 0.1
    fn = getattr(nd, opname)
    ref = fn(nd.array(x32)).asnumpy()
    low = fn(nd.array(x32).astype(dtype)).astype("float32").asnumpy()
    tol = 2e-2 if dtype in ("float16", "bfloat16") else 1e-5
    np.testing.assert_allclose(low, ref, rtol=tol, atol=tol)


@pytest.mark.parametrize("dtype", ["float16", "bfloat16"])
def test_fc_low_precision_consistency(dtype):
    x = np.random.randn(8, 32).astype(np.float32)
    w = np.random.randn(16, 32).astype(np.float32) * 0.1
    b = np.zeros(16, np.float32)
    ref = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b),
                            num_hidden=16).asnumpy()
    low = nd.FullyConnected(nd.array(x).astype(dtype),
                            nd.array(w).astype(dtype),
                            nd.array(b).astype(dtype),
                            num_hidden=16).astype("float32").asnumpy()
    np.testing.assert_allclose(low, ref, rtol=5e-2, atol=5e-2)


def test_conv_bf16_trains_finite():
    """bf16 conv fwd+bwd stays finite (the bench dtype)."""
    x = nd.array(np.random.randn(2, 3, 8, 8).astype(np.float32)) \
        .astype("bfloat16")
    w = nd.array((np.random.randn(4, 3, 3, 3) * 0.1).astype(np.float32)) \
        .astype("bfloat16")
    w.attach_grad()
    with mx.autograd.record():
        y = nd.Convolution(x, w, kernel=(3, 3), num_filter=4, no_bias=True)
        loss = (y.astype("float32") ** 2).sum()
    loss.backward()
    assert np.isfinite(w.grad.astype("float32").asnumpy()).all()


# ---- numeric-gradient oracle on more ops ----

@pytest.mark.parametrize("sym_fn", [
    lambda d: mx.sym.Activation(d, act_type="tanh"),
    lambda d: mx.sym.LeakyReLU(d, act_type="leaky", slope=0.1),
    lambda d: mx.sym.log_softmax(d),
    lambda d: mx.sym.L2Normalization(d),
    lambda d: mx.sym.sum(mx.sym.broadcast_mul(d, d)),
])
def test_numeric_gradient_zoo(sym_fn):
    data = mx.sym.Variable("data")
    sym = sym_fn(data)
    loc = {"data": np.random.randn(3, 7).astype(np.float64) * 0.5}
    # forward evaluates in float32, so FD round-off noise is
    # ~machine_eps*|loss|/eps ≈ 5e-4 at eps=1e-2 (|loss| up to ~50 for
    # log_softmax) while central-difference truncation stays O(eps^2);
    # eps=1e-3 left the noise above atol and flaked on log_softmax
    check_numeric_gradient(sym, loc, numeric_eps=1e-2, rtol=2e-2, atol=2e-3)


def test_numeric_gradient_conv():
    data = mx.sym.Variable("data")
    weight = mx.sym.Variable("weight")
    sym = mx.sym.Convolution(data, weight, kernel=(3, 3), num_filter=2,
                             no_bias=True)
    loc = {"data": np.random.randn(1, 2, 5, 5) * 0.5,
           "weight": np.random.randn(2, 2, 3, 3) * 0.5}
    check_numeric_gradient(sym, loc, numeric_eps=1e-3, rtol=2e-2, atol=2e-2)


def test_numeric_gradient_batchnorm_like():
    data = mx.sym.Variable("data")
    gamma = mx.sym.Variable("gamma")
    beta = mx.sym.Variable("beta")
    sym = mx.sym.InstanceNorm(data, gamma, beta)
    loc = {"data": np.random.randn(2, 3, 3, 3) * 0.5 + 1.0,
           "gamma": np.random.rand(3) + 0.5,
           "beta": np.random.randn(3) * 0.1}
    check_numeric_gradient(sym, loc, numeric_eps=1e-3, rtol=2e-2, atol=2e-2)
