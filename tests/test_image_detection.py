"""Detection augmenter tests (reference python/mxnet/image/detection.py +
src/io/image_det_aug_default.cc)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.image import detection as det


def _img(h=40, w=60):
    return nd.array((np.random.rand(h, w, 3) * 255).astype(np.uint8))


def _label():
    # one object: class 0 box (0.25, 0.25)-(0.5, 0.75)
    return np.array([[0.0, 0.25, 0.25, 0.5, 0.75],
                     [-1.0, 0, 0, 0, 0]], np.float32)


def test_det_horizontal_flip_flips_boxes():
    aug = det.DetHorizontalFlipAug(p=1.0)
    img, lab = aug(_img(), _label())
    np.testing.assert_allclose(lab[0, [1, 3]], [0.5, 0.75], atol=1e-6)
    np.testing.assert_allclose(lab[0, [2, 4]], [0.25, 0.75], atol=1e-6)
    assert lab[1, 0] == -1.0


def test_det_borrow_aug_passes_label():
    from mxnet_tpu.image.image import CastAug
    aug = det.DetBorrowAug(CastAug())
    img, lab = aug(_img(), _label())
    np.testing.assert_allclose(lab, _label())
    assert img.dtype == np.float32


def test_det_random_pad_shrinks_boxes():
    np.random.seed(0)
    import random
    random.seed(0)
    aug = det.DetRandomPadAug(area_range=(2.0, 2.0))
    img, lab = aug(_img(40, 60), _label())
    # canvas grew by sqrt(2): box extent shrinks by the same factor
    w_new = lab[0, 3] - lab[0, 1]
    assert w_new == pytest.approx(0.25 / np.sqrt(2), rel=0.1)
    assert 40 < img.shape[0] <= 57


def test_det_random_crop_keeps_object():
    import random
    random.seed(3)
    aug = det.DetRandomCropAug(min_object_covered=0.5,
                               area_range=(0.5, 0.9), max_attempts=30)
    img, lab = aug(_img(), _label())
    valid = lab[lab[:, 0] >= 0]
    assert len(valid) >= 1
    assert (valid[:, 1:] >= 0).all() and (valid[:, 1:] <= 1).all()


def test_create_det_augmenter_pipeline_runs():
    import random
    random.seed(1)
    augs = det.CreateDetAugmenter((3, 32, 32), rand_crop=0.5, rand_pad=0.5,
                                  rand_mirror=True, mean=True, std=True,
                                  brightness=0.1, contrast=0.1,
                                  saturation=0.1)
    img, lab = _img(), _label()
    for aug in augs:
        img, lab = aug(img, lab)
    assert tuple(img.shape) == (32, 32, 3)
    assert lab.shape[1] == 5
