"""Parallelism tests on the 8-device virtual CPU mesh (conftest sets
xla_force_host_platform_device_count=8; SURVEY §4 doctrine: multi-device
paths exercised without accelerator hardware)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu import parallel as par


def test_mesh_factor():
    assert par.factor_devices(8, 1) == (8,)
    assert par.factor_devices(8, 2) == (4, 2)
    assert par.factor_devices(8, 3) == (2, 2, 2)
    assert par.factor_devices(6, 2) == (3, 2)
    assert par.factor_devices(1, 2) == (1, 1)


def test_make_mesh():
    m = par.make_mesh({"data": 4, "model": 2})
    assert m.shape == {"data": 4, "model": 2}
    m = par.make_mesh({"data": -1, "model": 2})
    assert m.shape["data"] == 4
    m2 = par.auto_mesh(("data",))
    assert m2.shape["data"] == 8


def test_collectives_shard_map():
    mesh = par.auto_mesh(("x",))
    x = jnp.arange(8.0)

    def f(s):
        return par.psum(s, "x")
    out = par.shard_map(f, mesh=mesh, in_specs=P("x"), out_specs=P("x"))(x)
    assert np.allclose(np.asarray(out), np.full(8, x.sum()))

    def g(s):
        return par.ppermute_shift(s, "x", 1)
    out = par.shard_map(g, mesh=mesh, in_specs=P("x"), out_specs=P("x"))(x)
    assert np.allclose(np.asarray(out), np.roll(np.arange(8.0), 1))

    def h(s):
        return par.all_gather(s, "x", axis=0)
    out = par.shard_map(h, mesh=mesh, in_specs=P("x"), out_specs=P(None),
                        check=False)(x)
    assert np.allclose(np.asarray(out), np.arange(8.0))


def test_ring_attention_matches_local():
    np.random.seed(0)
    b, h, s, d = 2, 3, 16, 8
    q = np.random.randn(b, h, s, d).astype(np.float32)
    k = np.random.randn(b, h, s, d).astype(np.float32)
    v = np.random.randn(b, h, s, d).astype(np.float32)
    ref = par.local_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    mesh = par.auto_mesh(("seq",))
    from mxnet_tpu.parallel.ring_attention import ring_attention_sharded
    out = ring_attention_sharded(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v), mesh)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_causal():
    np.random.seed(1)
    b, h, s, d = 1, 2, 16, 4
    q = np.random.randn(b, h, s, d).astype(np.float32)
    k = np.random.randn(b, h, s, d).astype(np.float32)
    v = np.random.randn(b, h, s, d).astype(np.float32)
    ref = par.local_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal=True)
    mesh = par.auto_mesh(("seq",))
    from mxnet_tpu.parallel.ring_attention import ring_attention_sharded
    out = ring_attention_sharded(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v), mesh, causal=True)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def _make_mlp():
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(32, activation="relu"))
        net.add(gluon.nn.Dense(10))
    return net


def test_sharded_trainer_loss_decreases():
    np.random.seed(0)
    net = _make_mlp()
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((8, 16)))  # shape-infer deferred params
    trainer = par.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.5})
    x = np.random.randn(64, 16).astype(np.float32)
    y = (np.arange(64) % 10).astype(np.float32)
    losses = [trainer.step(x, y) for _ in range(40)]
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_sharded_trainer_matches_serial():
    """DP over 8 virtual devices must match single-device Gluon training."""
    np.random.seed(0)
    x = np.random.randn(32, 8).astype(np.float32)
    y = np.random.randn(32, 1).astype(np.float32)

    def build():
        mx.random.seed(0)
        np.random.seed(42)
        net = gluon.nn.Dense(1)
        net.initialize(mx.init.Xavier())
        net(mx.nd.zeros((1, 8)))
        return net

    # serial reference via gluon Trainer
    net_a = build()
    tr = gluon.Trainer(net_a.collect_params(), "sgd",
                       {"learning_rate": 0.05})
    loss_fn = gluon.loss.L2Loss()
    for _ in range(5):
        with mx.autograd.record():
            l = loss_fn(net_a(mx.nd.array(x)), mx.nd.array(y))
        l.backward()
        tr.step(batch_size=32)

    # sharded
    net_b = build()
    st = par.ShardedTrainer(net_b, loss_fn, "sgd",
                            optimizer_params={"learning_rate": 0.05,
                                              "rescale_grad": 1.0})
    for _ in range(5):
        st.step(x, y)
    st.sync_to_block()

    wa = net_a.collect_params()
    wb = net_b.collect_params()
    for (na, pa), (nb, pb) in zip(sorted(wa.items()), sorted(wb.items())):
        assert np.allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                           atol=1e-4), (na, nb)


def test_sharded_trainer_tensor_parallel():
    """TP: shard the hidden dim of the MLP over the model axis."""
    np.random.seed(0)
    net = _make_mlp()
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((8, 16)))
    mesh = par.make_mesh({"data": 4, "model": 2})
    rules = [(r"dense0_weight", P("model", None)),
             (r"dense0_bias", P("model")),
             (r"dense1_weight", P(None, "model"))]
    trainer = par.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd", mesh=mesh,
        param_rules=rules, optimizer_params={"learning_rate": 0.5})
    x = np.random.randn(64, 16).astype(np.float32)
    y = (np.arange(64) % 10).astype(np.float32)
    losses = [trainer.step(x, y) for _ in range(40)]
    assert losses[-1] < losses[0] * 0.7, losses[::10]
    # param sharding was honored
    w0 = next(v for k, v in trainer.params.items()
              if k.endswith("dense0_weight"))
    assert w0.sharding.spec in (P("model"), P("model", None))


def test_sharded_adam_bias_correction_not_frozen():
    """Adam's t must advance across cached-jit steps (bias correction)."""
    np.random.seed(0)
    net = gluon.nn.Dense(1)
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((1, 4)))
    st = par.ShardedTrainer(net, gluon.loss.L2Loss(), "adam",
                            optimizer_params={"learning_rate": 0.01})
    x = np.random.randn(16, 4).astype(np.float32)
    y = np.random.randn(16, 1).astype(np.float32)

    # serial adam reference
    net2 = gluon.nn.Dense(1)
    net2.initialize(mx.init.Xavier())
    net2(mx.nd.zeros((1, 4)))
    for pa, pb in zip(net2.collect_params().values(),
                      net.collect_params().values()):
        pa._data._set_data(pb.data()._data)
    tr = gluon.Trainer(net2.collect_params(), "adam",
                       {"learning_rate": 0.01, "rescale_grad": 1.0})
    loss_fn = gluon.loss.L2Loss()
    for _ in range(4):
        st.step(x, y)
        with mx.autograd.record():
            l = loss_fn(net2(mx.nd.array(x)), mx.nd.array(y))
        l.backward()
        tr.step(batch_size=1)
    st.sync_to_block()
    for (_, pa), (_, pb) in zip(sorted(net.collect_params().items()),
                                sorted(net2.collect_params().items())):
        assert np.allclose(pa.data().asnumpy(), pb.data().asnumpy(),
                           atol=1e-4)


@pytest.mark.parametrize("opt_name,opt_kw,tol", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9}, 1e-5),
    # adam divides by sqrt(v)+eps with v ~ 0 early on, amplifying
    # fusion-order float32 rounding; tolerance reflects that
    ("adam", {"learning_rate": 0.01}, 3e-4),
])
def test_sharded_optimizer_matches_eager(opt_name, opt_kw, tol):
    """ShardedTrainer and the eager Updater run the SAME pure
    update_step core: after identical steps the parameters agree
    (VERDICT r2 task 10 'done' criterion)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.parallel.sharded import ShardedTrainer

    np.random.seed(0)
    mx.random.seed(0)
    X = np.random.randn(16, 6).astype(np.float32)
    Y = np.random.randint(0, 3, 16).astype(np.float32)

    def build():
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(8, activation="tanh"))
        net.add(gluon.nn.Dense(3))
        net.collect_params().initialize(mx.init.Xavier(), force_reinit=True)
        net(nd.array(X))        # materialise deferred shapes
        return net

    mx.random.seed(7)     # initializers draw from random.host_rng()
    net_eager = build()
    mx.random.seed(7)
    net_sharded = build()
    # pair params structurally (creation order): the global name counters
    # make lexicographic sorting unstable across test ordering
    def pairs():
        return zip(net_eager.collect_params().values(),
                   net_sharded.collect_params().values())

    for p1, p2 in pairs():
        np.testing.assert_allclose(p1.data().asnumpy(), p2.data().asnumpy())

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # eager path: gluon Trainer (Updater -> optimizer.update -> update_step)
    trainer = gluon.Trainer(net_eager.collect_params(), opt_name,
                            dict(opt_kw), kvstore=None)
    for _ in range(3):
        with autograd.record():
            loss = loss_fn(net_eager(nd.array(X)), nd.array(Y))
        loss.backward()
        trainer.step(16)

    # sharded path: one jitted step over the (single-device) mesh.
    # ShardedTrainer's loss is already a batch MEAN (the eager Trainer
    # divides the summed grad by batch_size via rescale_grad instead), so
    # rescale_grad stays 1.
    st = ShardedTrainer(net_sharded, loss_fn, opt_name,
                        optimizer_params=dict(opt_kw, rescale_grad=1.0))
    for _ in range(3):
        st.step(nd.array(X), nd.array(Y))
    st.sync_to_block()

    for p1, p2 in pairs():
        # same pure update core; residual diffs are XLA fusion-order
        # float32 rounding (the eager path runs per-op programs)
        np.testing.assert_allclose(p1.data().asnumpy(), p2.data().asnumpy(),
                                   rtol=tol, atol=tol, err_msg=p1.name)


def test_weight_update_sharding_matches_replicated():
    """ZeRO-1 cross-replica weight-update sharding (arXiv:2004.13336)
    is a placement change, not a math change: parameters after N steps
    match the replicated-update trainer."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.parallel.sharded import ShardedTrainer

    np.random.seed(0)
    mx.random.seed(0)
    X = np.random.randn(16, 6).astype(np.float32)
    Y = np.random.randint(0, 3, 16).astype(np.float32)

    def build():
        net = gluon.nn.HybridSequential()
        # first Dense: weight dim0 = 8, divisible by the 8-device mesh
        # -> sharded update; second: dim0 = 3 -> replicated fallback
        net.add(gluon.nn.Dense(8, activation="tanh"))
        net.add(gluon.nn.Dense(3))
        net.collect_params().initialize(mx.init.Xavier(),
                                        force_reinit=True)
        net(nd.array(X))
        return net

    mx.random.seed(7)     # initializers draw from random.host_rng()
    net_a = build()
    mx.random.seed(7)
    net_b = build()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    opt_kw = {"learning_rate": 0.05, "momentum": 0.9, "rescale_grad": 1.0}
    mx.random.seed(3)
    plain = ShardedTrainer(net_a, loss_fn, "sgd",
                           optimizer_params=dict(opt_kw))
    for _ in range(3):
        plain.step(nd.array(X), nd.array(Y))
    plain.sync_to_block()

    mx.random.seed(3)
    zero1 = ShardedTrainer(net_b, loss_fn, "sgd",
                           optimizer_params=dict(opt_kw),
                           shard_weight_update=True)
    assert zero1._update_shardings, "no parameter qualified for ZeRO-1"
    for _ in range(3):
        zero1.step(nd.array(X), nd.array(Y))
    zero1.sync_to_block()

    for pa, pb in zip(net_a.collect_params().values(),
                      net_b.collect_params().values()):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(),
                                   rtol=1e-5, atol=1e-5, err_msg=pa.name)
    # the sharded state really is split: one row of 8 per device
    name = next(iter(zero1._update_shardings))
    leaf = jax.tree_util.tree_leaves(zero1.states[name])[0]
    assert not leaf.sharding.is_fully_replicated


def test_weight_update_sharding_nadam_scalar_state():
    """Optimizers with non-weight-shaped state leaves (nadam's scalar
    mu-product) work under ZeRO-1: odd leaves stay replicated."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.parallel.sharded import ShardedTrainer

    np.random.seed(0)
    mx.random.seed(0)
    X = np.random.randn(16, 6).astype(np.float32)
    Y = np.random.randint(0, 3, 16).astype(np.float32)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="tanh"))
    net.add(gluon.nn.Dense(3))
    net.collect_params().initialize(mx.init.Xavier(), force_reinit=True)
    net(nd.array(X))
    st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        "nadam", optimizer_params={"learning_rate": 0.01,
                                                   "rescale_grad": 1.0},
                        shard_weight_update=True)
    assert st._update_shardings
    l0 = st.step(nd.array(X), nd.array(Y))
    l1 = st.step(nd.array(X), nd.array(Y))
    assert np.isfinite(l0) and np.isfinite(l1)
