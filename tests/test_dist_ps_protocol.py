"""Direct tests for the dist_ps wire-protocol defenses (ISSUE 9
satellite): the ``_RestrictedUnpickler`` allowlist and every
``ProtocolError`` arm — wrong magic, wrong version, oversized frame,
disallowed global — exercised on purpose rather than incidentally."""
import pickle
import socket
import struct

import numpy as np
import pytest

from mxnet_tpu import dist_ps


def _pair(timeout=1.0):
    a, b = socket.socketpair()
    return a, b, dist_ps.Conn(b, timeout=timeout)


# ---------------------------------------------------------------------------
# the allowlist itself
# ---------------------------------------------------------------------------

def test_allowlist_admits_numpy_containers_and_framework_classes():
    payloads = [
        np.arange(12, dtype=np.float32).reshape(3, 4),
        {"a": (1, 2.5, b"x"), "b": [True, None, frozenset({3})]},
        ("push", "w", 0, np.ones(3), None),
    ]
    for obj in payloads:
        got = dist_ps._restricted_loads(
            pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
        if isinstance(obj, np.ndarray):
            assert np.array_equal(got, obj)
        elif isinstance(obj, tuple):
            assert got[0] == obj[0] and len(got) == len(obj)
            assert np.array_equal(got[3], obj[3])
        else:
            assert got == obj


def test_allowlist_admits_mxnet_optimizer():
    import mxnet_tpu as mx
    opt = mx.optimizer.create("sgd", learning_rate=0.25)
    got = dist_ps._restricted_loads(
        pickle.dumps(opt, protocol=pickle.HIGHEST_PROTOCOL))
    assert type(got) is type(opt)
    assert got.lr == 0.25


def test_allowlist_refuses_code_exec_globals():
    class Evil:
        def __reduce__(self):
            import os as _os
            return (_os.system, ("true",))

    with pytest.raises(pickle.UnpicklingError, match="disallowed"):
        dist_ps._restricted_loads(pickle.dumps(Evil()))

    # subprocess / builtins.eval style gadgets are refused the same way
    # (direct find_class probes — the refusal is at name-resolution)
    up = dist_ps._RestrictedUnpickler.__new__(
        dist_ps._RestrictedUnpickler)
    for module, name in (("subprocess", "Popen"), ("builtins", "eval"),
                         ("builtins", "exec"), ("shutil", "rmtree")):
        with pytest.raises(pickle.UnpicklingError, match="disallowed"):
            up.find_class(module, name)


def test_allowlist_admits_safe_builtins_only():
    up = dist_ps._RestrictedUnpickler.__new__(dist_ps._RestrictedUnpickler)
    assert up.find_class("builtins", "dict") is dict
    assert up.find_class("builtins", "bytearray") is bytearray
    with pytest.raises(pickle.UnpicklingError):
        up.find_class("builtins", "getattr")
    with pytest.raises(pickle.UnpicklingError):
        up.find_class("importlib", "import_module")


# ---------------------------------------------------------------------------
# frame-level ProtocolError arms
# ---------------------------------------------------------------------------

def test_wrong_magic_is_protocol_error():
    a, b, conn = _pair()
    a.sendall(b"EVIL" + struct.pack("<HQ", 1, 4) + b"xxxx")
    with pytest.raises(dist_ps.ProtocolError, match="magic"):
        conn.recv()
    a.close(); b.close()


def test_wrong_wire_version_is_protocol_error():
    a, b, conn = _pair()
    blob = pickle.dumps(("barrier",))
    a.sendall(struct.pack("<4sHQ", b"MXPS", 999, len(blob)) + blob)
    with pytest.raises(dist_ps.ProtocolError, match="version"):
        conn.recv()
    a.close(); b.close()


def test_oversized_frame_is_rejected_before_any_read():
    """A header claiming a >16GiB payload must be refused from the
    header alone — never allocated, never read."""
    a, b, conn = _pair()
    a.sendall(struct.pack("<4sHQ", b"MXPS", 1, (1 << 34) + 1))
    with pytest.raises(dist_ps.ProtocolError, match="exceeds"):
        conn.recv()
    a.close(); b.close()


def test_disallowed_global_over_the_wire_is_protocol_error():
    class Evil:
        def __reduce__(self):
            import os as _os
            return (_os.system, ("true",))

    a, b, conn = _pair()
    blob = pickle.dumps(Evil())
    a.sendall(struct.pack("<4sHQ", b"MXPS", 1, len(blob)) + blob)
    with pytest.raises(dist_ps.ProtocolError, match="disallowed"):
        conn.recv()
    a.close(); b.close()


def test_truncated_pickle_is_protocol_error_not_crash():
    a, b, conn = _pair()
    blob = pickle.dumps(("push", np.ones(4)))[:10]   # torn payload
    a.sendall(struct.pack("<4sHQ", b"MXPS", 1, len(blob)) + blob)
    with pytest.raises(dist_ps.ProtocolError,
                       match="undecodable|truncated|pickle"):
        conn.recv()
    a.close(); b.close()


def test_set_state_inner_updater_blob_is_restricted(tmp_path):
    """The checkpoint-state restore path must not smuggle a raw pickle
    past the allowlist: the inner updater blob crossed the wire too."""
    import os
    import mxnet_tpu as mx
    server = dist_ps.Server(nworkers=1)
    server.updater = mx.optimizer.get_updater(mx.optimizer.create("sgd"))
    marker = str(tmp_path / "pwned")

    class Evil:
        def __reduce__(self):
            import os as _os
            return (_os.system, ("touch %s" % marker,))

    inner = pickle.dumps(Evil())
    outer = pickle.dumps({"version": 1, "store": {}, "shapes": {},
                          "ranges": {}, "sync": True, "updater": inner,
                          "index_update_count": None, "num_update": None})
    with pytest.raises(pickle.UnpicklingError, match="disallowed"):
        server._set_state(outer)
    assert not os.path.exists(marker), "code-exec gadget ran!"
    # and a LEGITIMATE updater payload still round-trips
    w, g = mx.nd.ones((4,)), mx.nd.ones((4,))
    server.updater(0, g, w)
    good = server._get_state()
    server2 = dist_ps.Server(nworkers=1)
    server2.updater = mx.optimizer.get_updater(mx.optimizer.create("sgd"))
    server2._set_state(good)
    assert set(server2.updater.states) == set(server.updater.states)


def test_protocol_error_is_not_retried_as_peer_loss():
    """ProtocolError subclasses ConnectionError but must NOT be eaten by
    the PeerLost retry machinery — garbage is a bug, not a dead peer."""
    assert issubclass(dist_ps.ProtocolError, ConnectionError)
    assert not issubclass(dist_ps.ProtocolError, dist_ps.PeerLost)
    assert not issubclass(dist_ps.PeerLost, dist_ps.ProtocolError)


def test_connect_rejects_tcp_self_connect(monkeypatch):
    """Dialing a port with no listener can "succeed" via TCP
    self-connect (kernel picks the target port as the source port —
    preferentially, right after that port's owner died).  Both ends are
    the same socket, so a dial-verify against a killed server's address
    would wrongly pass and commit a stale address list.  Conn.connect
    must refuse the trap."""
    # build a deterministic self-connected socket (simultaneous open)
    sock = socket.socket()
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.connect(("127.0.0.1", port))
    assert sock.getsockname() == sock.getpeername(), \
        "platform does not self-connect; guard untestable this way"
    monkeypatch.setattr(dist_ps.socket, "create_connection",
                        lambda addr, timeout=None: sock)
    with pytest.raises(ConnectionError, match="self-connected"):
        dist_ps.Conn.connect(("127.0.0.1", port), retries=1, delay=0)
    # the trap socket was closed by the guard
    with pytest.raises(OSError):
        sock.getpeername()


def test_server_answers_liveness_ping():
    """The refresh_servers dial-verify rides on a ping round trip — a
    bare TCP connect is not proof of life (the kernel completes
    handshakes into a killed process's accept queue for a brief
    teardown window)."""
    server = dist_ps.Server(nworkers=1)
    assert server.handle(("ping",)) == ("pong",)
