"""Worker: sparse linear classification against a dist_async PS with
row_sparse weight pulls (the load-bearing sparse workload, SURVEY §2.2;
reference example/sparse/linear_classification.py run under the nightly
dist doctrine).

Run through the launcher:

    python tools/launch.py -n 2 python tests/sparse_linear_worker.py
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "examples"))

import sparse_linear_classification as slc  # noqa: E402


class Args:
    num_epochs = 3
    batch_size = 64
    kvstore = "dist_async"
    optimizer = "sgd"
    lr = 0.5
    num_features = 300
    num_obs = 512
    data_libsvm = None


def main():
    first, last, acc = slc.train(Args())
    assert last < first, "rank loss did not improve (%.4f -> %.4f)" % (
        first, last)
    assert acc > 0.5, "accuracy %.4f not above chance" % acc


if __name__ == "__main__":
    main()
