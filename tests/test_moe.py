"""Expert-parallel MoE FFN: routing exactness, sharded equivalence,
capacity-drop semantics, gradient flow."""
import math

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu  # noqa: F401  (pins the virtual CPU mesh via conftest)
from mxnet_tpu.parallel.mesh import make_mesh
from mxnet_tpu.models.moe import init_moe_params, moe_ffn


def _setup(b=2, s=8, d=6, d_ff=10, n_experts=4, seed=0):
    params = init_moe_params(jax.random.PRNGKey(seed), d, d_ff, n_experts)
    x = jax.random.normal(jax.random.PRNGKey(seed + 1), (b, s, d),
                          jnp.float32)
    return x, params


def test_moe_matches_per_token_direct_compute():
    """With capacity >= tokens (nothing drops) the routed output equals
    gate_prob * FFN_argmax_expert(token), computed directly."""
    x, params = _setup()
    out = moe_ffn(x, params, capacity_factor=float(x.shape[0] * x.shape[1]))
    flat = np.asarray(x).reshape(-1, x.shape[-1])
    logits = flat @ np.asarray(params["gate_w"])
    gates = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    expect = np.zeros_like(flat)
    for i, tok in enumerate(flat):
        e = int(np.argmax(gates[i]))
        h = np.asarray(jax.nn.gelu(jnp.asarray(
            tok @ np.asarray(params["expert_w1"][e])
            + np.asarray(params["expert_b1"][e]))))
        expect[i] = float(gates[i, e]) * (
            h @ np.asarray(params["expert_w2"][e]))
    np.testing.assert_allclose(np.asarray(out).reshape(flat.shape),
                               expect, rtol=1e-4, atol=1e-5)


def test_moe_sharded_matches_unsharded():
    """Expert-parallel placement is numerics-neutral."""
    x, _ = _setup(b=4, s=8, d=8, d_ff=16)
    ref_params = init_moe_params(jax.random.PRNGKey(0), 8, 16, 4)
    ref = moe_ffn(x, ref_params)

    mesh = make_mesh({"data": 2, "model": 4})
    ep_params = init_moe_params(jax.random.PRNGKey(0), 8, 16, 4, mesh=mesh)
    leaf = ep_params["expert_w1"]
    assert "model" in tuple(leaf.sharding.spec)      # EP really applied
    out = jax.jit(lambda xx, pp: moe_ffn(xx, pp, mesh=mesh))(x, ep_params)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_moe_capacity_drops_overflow_tokens():
    """Tokens beyond an expert's capacity contribute zero (they ride the
    residual); with capacity ~1 token per expert some rows must drop."""
    x, params = _setup(b=2, s=16, d=6)
    out_full = moe_ffn(x, params, capacity_factor=32.0)
    out_tight = moe_ffn(x, params, capacity_factor=0.125)  # C = 1
    full = np.asarray(out_full).reshape(-1, 6)
    tight = np.asarray(out_tight).reshape(-1, 6)
    zero_rows = (np.abs(tight).max(axis=1) == 0)
    assert zero_rows.any(), "tight capacity dropped nothing"
    # surviving rows agree with the uncapped routing
    kept = ~zero_rows
    np.testing.assert_allclose(tight[kept], full[kept], rtol=1e-4,
                               atol=1e-5)


def test_moe_gradients_flow_to_all_param_groups():
    x, params = _setup()

    def loss(p, xx):
        return jnp.sum(moe_ffn(xx, p) ** 2)

    grads = jax.jit(jax.grad(loss))(params, x)
    for name, g in grads.items():
        assert np.isfinite(np.asarray(g)).all(), name
        if name != "gate_w":
            assert float(jnp.abs(g).sum()) > 0, name
