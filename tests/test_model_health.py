"""In-program model health (ISSUE 17): the fused stats side-output is
bitwise-free, costs no extra programs, feeds the Monitor's compiled
mode, and the drift gate consumes the exports with CI exit codes.

Acceptance contract: stats-on training is bitwise-identical to
stats-off on the fused, ZeRO-1, and guardian-NaN-retry paths with
``program_calls_per_step`` unchanged; ``tools/health_gate.py`` passes a
recorded envelope and exits nonzero on injected loss divergence.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, chaos, gluon, guardian, model_stats
from mxnet_tpu import profiler, telemetry
from mxnet_tpu.gluon import fused_trainer, nn
from mxnet_tpu.telemetry import timeseries as ts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_env():
    model_stats.recorder().reset()
    ts.reset()
    yield
    for key in ("MXNET_MODEL_STATS", "MXNET_FUSED_TRAINER",
                "MXNET_ZERO", "MXNET_ZERO_SHARDS"):
        os.environ.pop(key, None)
    model_stats.refresh_from_env()
    fused_trainer.refresh_from_env()
    model_stats.recorder().reset()
    ts.reset()
    g = guardian.current()
    if g is not None:
        guardian.uninstall(g)
    chaos.configure(None)


def _set_mode(fused=True, zero=None):
    os.environ["MXNET_FUSED_TRAINER"] = "1" if fused else "0"
    if zero is None:
        os.environ.pop("MXNET_ZERO", None)
        os.environ.pop("MXNET_ZERO_SHARDS", None)
    else:
        os.environ["MXNET_ZERO"] = "1"
        os.environ["MXNET_ZERO_SHARDS"] = str(zero)
    fused_trainer.refresh_from_env()


def _train(stats=0, fused=True, zero=None, guard=False, poison=None,
           steps=5, seed=0):
    """Seeded mini-run; returns (params, states, per-step call counts)."""
    _set_mode(fused=fused, zero=zero)
    model_stats.configure(interval=stats)
    model_stats.recorder().reset()
    ts.reset()
    g = None
    try:
        if poison is not None:
            chaos.configure(poison)
        if guard:
            g = guardian.TrainingGuardian()
            guardian.install(g)
        np.random.seed(seed)
        mx.random.seed(seed)
        rng = np.random.RandomState(seed + 1)
        net = nn.Sequential()
        net.add(nn.Dense(8, activation="relu"))
        net.add(nn.Dense(8, activation="relu"))
        net.add(nn.Dense(4))
        net.initialize(init=mx.initializer.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.05, "momentum": 0.9},
                                kvstore="device")
        loss_fn = gluon.loss.L2Loss()
        X = rng.randn(steps, 8, 6).astype(np.float32)
        Y = rng.randn(steps, 8, 4).astype(np.float32)
        calls = []
        for step in range(steps):
            attempt = 0
            while True:
                with autograd.record():
                    loss = loss_fn(net(mx.nd.array(X[step])),
                                   mx.nd.array(Y[step]))
                    scaled = g.scale_loss(loss) if g is not None else loss
                scaled.backward()
                before = profiler.counter("xla_program_calls")
                trainer.step(8)
                calls.append(profiler.counter("xla_program_calls")
                             - before)
                # the retrying-loop contract: a skipped update redoes
                # the SAME batch (tools/guardian_smoke.py)
                if g is not None and g.last_action() == "skipped" \
                        and attempt < 2:
                    attempt += 1
                    continue
                break
        params = [p.data().asnumpy()
                  for p in net.collect_params().values()]
        names = [p.name for p in net.collect_params().values()]
        return params, names, calls
    finally:
        if g is not None:
            guardian.uninstall(g)
        if poison is not None:
            chaos.configure(None)
        model_stats.configure(interval=0)
        _set_mode(fused=True, zero=None)


def _assert_bitwise(a, b, what):
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        np.testing.assert_array_equal(x, y, err_msg="%s[%d]" % (what, i))


# ---------------------------------------------------------------------------
# bitwise: the optimization_barrier isolation holds on every path
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["fused", "zero1", "oracle"])
def test_stats_on_is_bitwise_identical(mode):
    kw = {"fused": mode != "oracle",
          "zero": 2 if mode == "zero1" else None}
    off, _, calls_off = _train(stats=0, **kw)
    on, _, calls_on = _train(stats=1, **kw)
    _assert_bitwise(off, on, "%s params" % mode)
    assert model_stats.recorder().latest() is not None, \
        "stats-on run recorded nothing (vacuous bitwise pass)"
    if mode != "oracle":
        # the side-output rides the ONE donated program: no extra calls
        assert calls_on[-1] == calls_off[-1] == 1


def test_stats_bitwise_under_guardian_nan_retry():
    skipped0 = telemetry.counter("guardian_skipped_steps")
    off, _, _ = _train(stats=0, guard=True, poison="grad.bucket:nan@2")
    mid = telemetry.counter("guardian_skipped_steps")
    assert mid - skipped0 == 1, "chaos NaN never skipped (vacuous)"
    on, _, _ = _train(stats=1, guard=True, poison="grad.bucket:nan@2")
    assert telemetry.counter("guardian_skipped_steps") - mid == 1
    _assert_bitwise(off, on, "guarded params")
    # the skipped attempt is IN the record: its update_ratio is zero
    # (weights untouched) — exactly what a drift table should show
    rows = model_stats.recorder().drain()
    ratios = [float(stats[:, 2].max()) for _, _, stats, _ in rows]
    assert any(r == 0.0 for r in ratios), \
        "the skipped step's zero update_ratio was not recorded"


# ---------------------------------------------------------------------------
# program budget + retrace discipline
# ---------------------------------------------------------------------------

def test_oracle_extra_program_only_on_due_steps():
    """MXNET_FUSED_TRAINER=0 + interval 2: the one extra model_stats
    program launches on steps 0/2/4 only."""
    _, _, calls = _train(stats=2, fused=False, steps=5)
    assert len(model_stats.recorder().drain()) == 3
    # steady state (compile noise settled): a due step costs exactly
    # one launch more than its non-due neighbor
    assert calls[4] == calls[3] + 1


def test_interval_change_never_retraces():
    """The program computes stats unconditionally when enabled; the
    interval rations the HOST fetch — so flipping it reuses the cached
    step program (one signature, no recompile)."""
    _set_mode(fused=True)
    model_stats.configure(interval=1)
    model_stats.recorder().reset()
    try:
        np.random.seed(3)
        mx.random.seed(3)
        rng = np.random.RandomState(4)
        net = nn.Sequential()
        net.add(nn.Dense(4))
        net.initialize(init=mx.initializer.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.05})
        loss_fn = gluon.loss.L2Loss()
        X = rng.randn(6, 4, 3).astype(np.float32)
        Y = rng.randn(6, 4, 4).astype(np.float32)

        def one(step):
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(X[step])),
                               mx.nd.array(Y[step]))
            loss.backward()
            trainer.step(4)

        one(0)
        cached = len(fused_trainer._STEP_CACHE)
        model_stats.configure(interval=3)
        for step in range(1, 6):
            one(step)
        assert len(fused_trainer._STEP_CACHE) == cached, \
            "interval flip retraced the step program"
        # fetches follow the live interval: steps 0 (int 1), 3 (int 3)
        assert [r[0] for r in model_stats.recorder().drain()] == [0, 3]
    finally:
        model_stats.configure(interval=0)


# ---------------------------------------------------------------------------
# recorder -> timeseries -> Monitor compiled mode
# ---------------------------------------------------------------------------

def test_recorder_feeds_timeseries():
    _, names, _ = _train(stats=1, guard=True, steps=3)
    step, rnames, stats, loss = model_stats.recorder().latest()
    assert list(rnames) == names
    assert stats.shape == (len(names), len(model_stats.STAT_NAMES))
    assert np.isfinite(stats).all()
    assert loss is not None and np.isfinite(loss)
    assert ts.series("model/loss")[-1] == (step, loss)
    got = ts.series("model/%s/grad_norm_sq" % names[0])
    assert got[-1][0] == step


def test_monitor_compiled_mode_parity():
    """An installed Monitor under MXNET_MODEL_STATS drains the SAME
    numbers the recorder holds, as <param>:<stat> rows, pattern-filtered
    — and never flips the executor onto the eager path."""
    from mxnet_tpu.monitor import Monitor
    _set_mode(fused=True)
    model_stats.configure(interval=1)
    model_stats.recorder().reset()
    try:
        mon = Monitor(interval=1, pattern=".*weight.*grad_norm_sq",
                      sort=True)
        assert not mon.stat_helper.is_active(), \
            "compiled mode must not arm the eager executor tap"
        np.random.seed(5)
        mx.random.seed(5)
        rng = np.random.RandomState(6)
        net = nn.Sequential()
        net.add(nn.Dense(4))
        net.initialize(init=mx.initializer.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.05})
        loss_fn = gluon.loss.L2Loss()
        mon.tic()
        with autograd.record():
            loss = loss_fn(net(mx.nd.array(rng.randn(4, 3)
                                           .astype(np.float32))),
                           mx.nd.array(rng.randn(4, 4)
                                       .astype(np.float32)))
        loss.backward()
        trainer.step(4)
        rows = mon.toc()
        _, names, stats, _ = model_stats.recorder().latest()
        # rows carry the monitor's batch clock (tic() already ticked it)
        want = [(mon.step, "%s:grad_norm_sq" % n, "%s\t" % stats[i][0])
                for i, n in enumerate(names) if "weight" in n]
        assert rows == sorted(want, key=lambda r: r[1])
    finally:
        model_stats.configure(interval=0)


def test_monitor_eager_tap_reactivates_when_stats_off():
    from mxnet_tpu.monitor import Monitor
    mon = Monitor(interval=1)
    mon.activated = True
    model_stats.configure(interval=1)
    assert not mon.stat_helper.is_active()
    model_stats.configure(interval=0)
    assert mon.stat_helper.is_active()


def test_monitor_render_is_sanctioned_host_sync():
    """Monitor._render's asnumpy inside an open trace is deliberate:
    allow_host_sync exempts the sync check, but a real tracer leak
    still raises."""
    import jax
    from mxnet_tpu import nd
    from mxnet_tpu.lint import sanitizer
    from mxnet_tpu.monitor import _render
    sanitizer.configure(mode="raise")
    try:
        const = nd.array(np.ones((2, 2), np.float32))

        def f(v):
            _render(const)            # sync under trace: sanctioned
            return v + 1

        jax.jit(f)(np.ones(3, np.float32))

        def g(v):
            return (_render(nd.NDArray(v)), v * 2)[1]   # tracer leak

        with pytest.raises(sanitizer.SanitizerError, match="tracer"):
            jax.jit(g)(np.ones(3, np.float32))
    finally:
        sanitizer.configure(mode="off")


# ---------------------------------------------------------------------------
# the drift gate CLI
# ---------------------------------------------------------------------------

def _synthetic_export(steps=8):
    series = {"model/loss": [[s, 2.0 / (s + 2)] for s in range(steps)]}
    for p in ("dense0_weight", "dense0_bias"):
        series["model/%s/grad_norm_sq" % p] = \
            [[s, 4.0 / (s + 1)] for s in range(steps)]
        series["model/%s/weight_norm_sq" % p] = \
            [[s, 1.0 + 0.01 * s] for s in range(steps)]
        series["model/%s/update_ratio" % p] = \
            [[s, 0.01] for s in range(steps)]
        series["model/%s/grad_absmax" % p] = \
            [[s, 0.5] for s in range(steps)]
    return {"version": 1, "cap": 4096, "steps_seen": steps,
            "series": series}


def _gate(tmp_path, run, *extra):
    run_path = tmp_path / "run.json"
    run_path.write_text(json.dumps(run))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "health_gate.py"),
         str(run_path), "--envelope", str(tmp_path / "env.json")]
        + list(extra),
        capture_output=True, text=True, cwd=REPO, timeout=120)
    return proc


def test_health_gate_record_then_pass(tmp_path):
    ref = _synthetic_export()
    assert _gate(tmp_path, ref, "--record").returncode == 0
    proc = _gate(tmp_path, ref)
    assert proc.returncode == 0, proc.stderr
    assert "ok" in proc.stdout


def test_health_gate_catches_loss_divergence(tmp_path):
    ref = _synthetic_export()
    assert _gate(tmp_path, ref, "--record").returncode == 0
    bad = _synthetic_export()
    bad["series"]["model/loss"][-1][1] *= 10.0
    proc = _gate(tmp_path, bad)
    assert proc.returncode == 3
    assert "loss off-envelope" in proc.stderr


def test_health_gate_catches_grad_spike_and_band_escape(tmp_path):
    ref = _synthetic_export()
    assert _gate(tmp_path, ref, "--record").returncode == 0
    bad = _synthetic_export()
    bad["series"]["model/dense0_weight/grad_norm_sq"][-1][1] = 1e9
    proc = _gate(tmp_path, bad)
    assert proc.returncode == 3
    assert "grad-norm spike" in proc.stderr
    bad = _synthetic_export()
    bad["series"]["model/dense0_bias/update_ratio"][-1][1] = 50.0
    proc = _gate(tmp_path, bad)
    assert proc.returncode == 3
    assert "update_ratio out of band" in proc.stderr


def test_health_gate_unmeasurable_and_usage(tmp_path):
    ref = _synthetic_export()
    assert _gate(tmp_path, ref, "--record").returncode == 0
    bare = {"version": 1, "steps_seen": 2,
            "series": {"step_time_us": [[0, 9.0], [1, 8.0]]}}
    assert _gate(tmp_path, bare).returncode == 4
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "health_gate.py"),
         str(tmp_path / "missing.json"),
         "--envelope", str(tmp_path / "env.json")],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 2


def test_health_gate_refuses_spiking_reference(tmp_path):
    ref = _synthetic_export()
    ref["series"]["model/dense0_weight/grad_norm_sq"][-1][1] = 1e9
    proc = _gate(tmp_path, ref, "--record")
    assert proc.returncode == 3
    assert "refusing to record" in proc.stderr
    assert not (tmp_path / "env.json").exists()


def test_trace_report_health_renders(tmp_path):
    run = tmp_path / "run.json"
    run.write_text(json.dumps(_synthetic_export()))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         "--health", str(run)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "model health" in proc.stdout
    assert "dense0_weight" in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         "--health", str(run), "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout)
    assert set(report["params"]) == {"dense0_weight", "dense0_bias"}
    assert report["loss"]["n"] == 8
