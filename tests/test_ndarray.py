"""NDArray semantics tests (modeled on reference tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_create_and_dtype_defaults():
    a = nd.array([[1, 2], [3, 4]])
    assert a.dtype == np.float32  # reference default
    assert a.shape == (2, 2)
    b = nd.array(np.arange(6, dtype=np.int64), dtype=np.int64)
    assert b.dtype == np.int64


def test_basic_arith_and_broadcast():
    a = nd.array([[1., 2.], [3., 4.]])
    b = nd.array([10., 20.])
    np.testing.assert_allclose((a + b).asnumpy(), [[11, 22], [13, 24]])
    np.testing.assert_allclose((a - 1).asnumpy(), [[0, 1], [2, 3]])
    np.testing.assert_allclose((2 / a).asnumpy(), 2 / a.asnumpy())
    np.testing.assert_allclose((a ** 2).asnumpy(), a.asnumpy() ** 2)
    np.testing.assert_allclose((-a).asnumpy(), -a.asnumpy())


def test_inplace_ops():
    a = nd.ones((2, 2))
    a += 2
    np.testing.assert_allclose(a.asnumpy(), 3 * np.ones((2, 2)))
    a *= 2
    np.testing.assert_allclose(a.asnumpy(), 6 * np.ones((2, 2)))


def test_indexing():
    a = nd.array(np.arange(12).reshape(3, 4))
    np.testing.assert_allclose(a[1].asnumpy(), np.arange(4) + 4)
    np.testing.assert_allclose(a[1:3].asnumpy(), np.arange(12).reshape(3, 4)[1:3])
    a[0] = 7.0
    np.testing.assert_allclose(a.asnumpy()[0], 7 * np.ones(4))
    a[1:3, 1] = 0.0
    assert a.asnumpy()[2, 1] == 0


def test_reductions_match_numpy():
    x = np.random.uniform(-1, 1, (3, 4, 5)).astype(np.float32)
    a = nd.array(x)
    np.testing.assert_allclose(a.sum().asnumpy(), x.sum().reshape(()), rtol=1e-5)
    np.testing.assert_allclose(a.sum(axis=1).asnumpy(), x.sum(axis=1), rtol=1e-5)
    np.testing.assert_allclose(a.max(axis=(0, 2)).asnumpy(), x.max(axis=(0, 2)))
    np.testing.assert_allclose(a.mean(axis=2, keepdims=True).asnumpy(),
                               x.mean(axis=2, keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(a.argmax(axis=1).asnumpy(), x.argmax(axis=1))


def test_reshape_semantics():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((2, -4, 3, 1, 4)).shape == (2, 3, 1, 4)


def test_copy_and_context():
    a = nd.ones((2, 3))
    b = a.copyto(mx.cpu(0))
    b[:] = 5.0
    assert a.asnumpy().sum() == 6  # copy is deep
    c = a.as_in_context(mx.cpu(0))
    assert c is a


def test_concat_stack_split():
    a, b = nd.ones((2, 3)), nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(c, num_outputs=2, axis=0)
    np.testing.assert_allclose(parts[0].asnumpy(), a.asnumpy())


def test_save_load_roundtrip(tmp_path):
    f = str(tmp_path / "x.params")
    data = {"arg:w": nd.array(np.random.rand(3, 4)),
            "aux:m": nd.array(np.arange(5), dtype=np.int32)}
    nd.save(f, data)
    back = nd.load(f)
    assert set(back) == set(data)
    for k in data:
        np.testing.assert_allclose(back[k].asnumpy(), data[k].asnumpy())
        assert back[k].dtype == data[k].dtype


def test_save_load_list(tmp_path):
    f = str(tmp_path / "l.params")
    data = [nd.ones((2,)), nd.zeros((3, 3))]
    nd.save(f, data)
    back = nd.load(f)
    assert isinstance(back, list) and len(back) == 2
    np.testing.assert_allclose(back[1].asnumpy(), np.zeros((3, 3)))


def test_wait_and_asscalar():
    a = nd.ones((1,))
    a.wait_to_read()
    assert a.asscalar() == 1.0
    nd.waitall()


def test_astype_and_T():
    a = nd.array([[1, 2], [3, 4]])
    assert a.astype(np.int32).dtype == np.int32
    np.testing.assert_allclose(a.T.asnumpy(), a.asnumpy().T)


def test_take_onehot_pick():
    w = nd.array(np.arange(12).reshape(4, 3))
    idx = nd.array([0, 2], dtype=np.int32)
    np.testing.assert_allclose(w.take(idx).asnumpy(),
                               w.asnumpy()[[0, 2]])
    oh = nd.one_hot(idx, depth=4)
    assert oh.shape == (2, 4)
    x = nd.array([[1., 2.], [3., 4.]])
    p = x.pick(nd.array([1, 0]), axis=1)
    np.testing.assert_allclose(p.asnumpy(), [2., 3.])


def test_topk_sort():
    x = nd.array([[3., 1., 2.], [0., 5., 4.]])
    np.testing.assert_allclose(x.sort(axis=1).asnumpy(),
                               np.sort(x.asnumpy(), axis=1))
    k = x.topk(k=2, axis=1, ret_typ="value")
    np.testing.assert_allclose(k[0].asnumpy() if isinstance(k, list) else k.asnumpy(),
                               [[3., 2.], [5., 4.]])


def test_random_reproducible():
    mx.random.seed(42)
    a = nd.random.uniform(shape=(3, 3)).asnumpy()
    mx.random.seed(42)
    b = nd.random.uniform(shape=(3, 3)).asnumpy()
    np.testing.assert_allclose(a, b)
    assert ((a >= 0) & (a < 1)).all()


def test_random_moments():
    x = nd.random.normal(loc=2.0, scale=0.5, shape=(20000,)).asnumpy()
    assert abs(x.mean() - 2.0) < 0.05
    assert abs(x.std() - 0.5) < 0.05


def test_sparse_row_sparse():
    rsp = nd.row_sparse_array(([[1., 2.], [3., 4.]], [0, 2]), shape=(4, 2))
    assert rsp.stype == "row_sparse"
    np.testing.assert_allclose(rsp.indices.asnumpy(), [0, 2])
    np.testing.assert_allclose(rsp.data.asnumpy(), [[1, 2], [3, 4]])
    dense = rsp.tostype("default")
    assert dense.stype == "default"
    np.testing.assert_allclose(dense.asnumpy(),
                               [[1, 2], [0, 0], [3, 4], [0, 0]])


def test_sparse_csr():
    m = nd.csr_matrix(([1., 2., 3.], [0, 2, 1], [0, 2, 3]), shape=(2, 3))
    assert m.stype == "csr"
    np.testing.assert_allclose(m.asnumpy(), [[1, 0, 2], [0, 3, 0]])
    np.testing.assert_allclose(m.indptr.asnumpy(), [0, 2, 3])


def test_row_sparse_metadata_device_path():
    """RowSparse carries explicit index+values metadata (SURVEY §7):
    constructor-seeded, mutation-invalidated, device-recomputed."""
    r = nd.sparse.row_sparse_array((np.full((2, 3), 5.0, np.float32),
                                    [1, 4]), shape=(6, 3))
    np.testing.assert_array_equal(r.indices.asnumpy(), [1, 4])
    np.testing.assert_allclose(r.data.asnumpy(), 5.0)
    # mutation invalidates cached metadata and recomputes correctly
    r[:] = r * 3
    np.testing.assert_array_equal(r.indices.asnumpy(), [1, 4])
    np.testing.assert_allclose(r.data.asnumpy(), 15.0)
    # dense write adding a new active row shows up
    r[0, 0] = 1.0
    np.testing.assert_array_equal(r.indices.asnumpy(), [0, 1, 4])


def test_kvstore_row_sparse_pull_seeds_metadata():
    kv = mx.kv.create("local")
    kv.init("w", nd.array(np.arange(12, dtype=np.float32).reshape(6, 2)))
    out = nd.sparse.zeros("row_sparse", (6, 2))
    kv.row_sparse_pull("w", out=out, row_ids=nd.array(
        np.array([1, 3], np.int64)))
    np.testing.assert_array_equal(out.indices.asnumpy(), [1, 3])
    got = out.asnumpy()
    assert got[0].sum() == 0 and got[2].sum() == 0
    np.testing.assert_allclose(got[1], [2, 3])
    np.testing.assert_allclose(got[3], [6, 7])


def test_csr_metadata_seeded_and_invalidated():
    """csr_matrix((data, indices, indptr)) keeps the given metadata
    without a recompute round-trip; mutation invalidates it (same
    design as RowSparse index+values caching)."""
    from mxnet_tpu.ndarray import sparse
    data = np.array([1.0, 2.0, 3.0], np.float32)
    indices = np.array([0, 2, 1], np.int64)
    indptr = np.array([0, 2, 2, 3], np.int64)
    m = sparse.csr_matrix((data, indices, indptr), shape=(3, 4))
    np.testing.assert_array_equal(m.indices.asnumpy(), indices)
    np.testing.assert_array_equal(m.indptr.asnumpy(), indptr)
    np.testing.assert_allclose(m.data.asnumpy(), data)
    np.testing.assert_allclose(
        m.asnumpy(),
        [[1, 0, 2, 0], [0, 0, 0, 0], [0, 3, 0, 0]])
    # mutation drops the seeded metadata; recompute reflects new values
    m[:] = m * 0 + np.array([[0, 5, 0, 0]] * 3, np.float32)
    np.testing.assert_array_equal(m.indices.asnumpy(), [1, 1, 1])
    np.testing.assert_array_equal(m.indptr.asnumpy(), [0, 1, 2, 3])


def test_csr_constructor_edge_cases():
    """Seeded metadata never aliases caller buffers, and duplicate
    column indices sum (scipy convention) with canonical recompute."""
    from mxnet_tpu.ndarray import sparse
    d = np.array([1.0, 2.0, 3.0], np.float32)
    m = sparse.csr_matrix((d, [0, 1, 2], [0, 1, 2, 3]), shape=(3, 3))
    d[0] = 99.0                      # caller mutates its own buffer
    np.testing.assert_allclose(m.data.asnumpy(), [1.0, 2.0, 3.0])
    assert m.asnumpy()[0, 0] == 1.0

    dup = sparse.csr_matrix(
        (np.array([1.0, 2.0], np.float32), [0, 0], [0, 2, 2]),
        shape=(2, 3))
    assert dup.asnumpy()[0, 0] == 3.0           # duplicates sum
    np.testing.assert_allclose(dup.data.asnumpy(), [3.0])
    np.testing.assert_array_equal(dup.indices.asnumpy(), [0])
