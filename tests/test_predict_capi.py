"""C predict API end-to-end: a plain C program loads a saved checkpoint
through libmxpredict.so and must reproduce the Python Predictor's output.

Reference analogue: the amalgamation deployment path over
``include/mxnet/c_predict_api.h`` (MXPredCreate/SetInput/Forward/
GetOutputShape/GetOutput/Free) exercised by a host binary that links no
Python — SURVEY §2.4's predict-only surface.
"""
import os
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SO = os.path.join(REPO, "mxnet_tpu", "_native", "libmxpredict.so")

DRIVER_C = r"""
#include <stdio.h>
#include <stdlib.h>

typedef unsigned int mx_uint;
extern const char* MXGetLastError(void);
extern int MXPredCreate(const char*, const void*, int, int, int, mx_uint,
                        const char**, const mx_uint*, const mx_uint*,
                        void**);
extern int MXPredSetInput(void*, const char*, const float*, mx_uint);
extern int MXPredForward(void*);
extern int MXPredGetOutputShape(void*, mx_uint, mx_uint**, mx_uint*);
extern int MXPredGetOutput(void*, mx_uint, float*, mx_uint);
extern int MXPredFree(void*);

static char* slurp(const char* path, long* size) {
  FILE* f = fopen(path, "rb");
  if (!f) return NULL;
  fseek(f, 0, SEEK_END);
  *size = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* buf = (char*)malloc(*size + 1);
  if (fread(buf, 1, *size, f) != (size_t)*size) { fclose(f); return NULL; }
  buf[*size] = 0;
  fclose(f);
  return buf;
}

int main(int argc, char** argv) {
  if (argc != 4) { fprintf(stderr, "usage: driver sym params out\n"); return 2; }
  long jsize, psize;
  char* json = slurp(argv[1], &jsize);
  char* params = slurp(argv[2], &psize);
  if (!json || !params) { fprintf(stderr, "read failed\n"); return 2; }

  const char* keys[] = {"data"};
  mx_uint indptr[] = {0, 2};
  mx_uint shape[] = {2, 8};
  void* pred = NULL;
  if (MXPredCreate(json, params, (int)psize, 1, 0, 1, keys, indptr, shape,
                   &pred) != 0) {
    fprintf(stderr, "create failed: %s\n", MXGetLastError());
    return 1;
  }
  float input[16];
  for (int i = 0; i < 16; ++i) input[i] = 0.1f * (float)i - 0.5f;
  if (MXPredSetInput(pred, "data", input, 16) != 0 ||
      MXPredForward(pred) != 0) {
    fprintf(stderr, "forward failed: %s\n", MXGetLastError());
    return 1;
  }
  mx_uint* oshape; mx_uint ondim;
  if (MXPredGetOutputShape(pred, 0, &oshape, &ondim) != 0) {
    fprintf(stderr, "shape failed: %s\n", MXGetLastError());
    return 1;
  }
  mx_uint total = 1;
  for (mx_uint i = 0; i < ondim; ++i) total *= oshape[i];
  float* out = (float*)malloc(total * sizeof(float));
  if (MXPredGetOutput(pred, 0, out, total) != 0) {
    fprintf(stderr, "output failed: %s\n", MXGetLastError());
    return 1;
  }
  FILE* fo = fopen(argv[3], "w");
  for (mx_uint i = 0; i < total; ++i) fprintf(fo, "%.6f\n", out[i]);
  fclose(fo);
  MXPredFree(pred);
  printf("ok %u\n", total);
  return 0;
}
"""


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    """A tiny trained symbolic net saved in reference checkpoint format."""
    tmp = tmp_path_factory.mktemp("capi")
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="tanh")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(h, name="softmax")

    from mxnet_tpu.io import NDArrayIter
    X = np.random.RandomState(0).randn(64, 8).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32)
    mod = mx.mod.Module(net)
    mod.fit(NDArrayIter(X, Y, batch_size=16), num_epoch=1,
            initializer=mx.init.Xavier(), optimizer="sgd")
    prefix = str(tmp / "capi_mlp")
    mod.save_checkpoint(prefix, 1)
    return prefix


def _compile_driver(tmp_path, source, compiler="gcc", suffix=".c",
                    extra_flags=()):
    src = tmp_path / ("driver" + suffix)
    src.write_text(source)
    exe = tmp_path / ("driver_" + compiler)
    cmd = [compiler, *extra_flags, str(src), "-o", str(exe),
           "-L", os.path.dirname(SO), "-lmxpredict",
           "-Wl,-rpath," + os.path.dirname(SO)]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
    except FileNotFoundError as exc:     # compiler absent: environment gap
        pytest.skip("no %s compiler: %s" % (compiler, exc))
    # a CalledProcessError propagates: ABI drift must fail, not skip
    return exe


def _run_driver_and_compare(exe, checkpoint, tmp_path):
    """Run a compiled driver on the checkpoint; assert its output file
    matches the Python Predictor on the same fixed input."""
    out_file = tmp_path / "out.txt"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [str(exe), checkpoint + "-symbol.json", checkpoint + "-0001.params",
         str(out_file)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    got = np.array([float(x) for x in out_file.read_text().split()],
                   np.float32).reshape(2, 4)
    from mxnet_tpu.predict import Predictor
    pred = Predictor.load(checkpoint, 1, {"data": (2, 8)})
    x = (0.1 * np.arange(16, dtype=np.float32) - 0.5).reshape(2, 8)
    want = pred.forward(data=x)[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_c_driver_matches_python_predictor(checkpoint, tmp_path):
    if not os.path.exists(SO):
        pytest.skip("libmxpredict.so not built")
    exe = _compile_driver(tmp_path, DRIVER_C)
    _run_driver_and_compare(exe, checkpoint, tmp_path)


def test_predictor_rejects_missing_weight(checkpoint):
    """Zero-binding is reserved for *_label args: a genuinely missing
    weight still raises instead of silently predicting garbage."""
    from mxnet_tpu.model import load_checkpoint
    from mxnet_tpu.predict import Predictor
    symbol, arg_params, aux_params = load_checkpoint(checkpoint, 1)
    del arg_params["fc1_weight"]
    with pytest.raises(mx.base.MXNetError, match="fc1_weight"):
        Predictor(symbol, arg_params, aux_params, {"data": (2, 8)})


def test_embedded_predictor_rejects_unnamed_params(checkpoint):
    """A list-format (unnamed) params blob is a hard error, not silent
    zero weights."""
    from mxnet_tpu.predict import _EmbeddedPredictor
    from mxnet_tpu.ndarray import utils as nd_utils
    sym_json = open(checkpoint + "-symbol.json").read()
    raw = nd_utils.save_to_bytes([mx.nd.zeros((3, 3))])
    with pytest.raises(mx.base.MXNetError, match="unnamed"):
        _EmbeddedPredictor(sym_json, raw, ["data"], [(2, 8)])


CPP_DRIVER = r"""
#include <cstdio>
#include <fstream>
#include <sstream>
#include "mxnet_tpu_predict.h"

static std::string slurp(const char* p) {
  std::ifstream f(p, std::ios::binary);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

int main(int argc, char** argv) {
  if (argc != 4) return 2;
  try {
    mxnet_tpu::Predictor pred(slurp(argv[1]), slurp(argv[2]),
                              {"data"}, {{2, 8}});
    std::vector<float> input(16);
    for (int i = 0; i < 16; ++i) input[i] = 0.1f * i - 0.5f;
    pred.SetInput("data", input);
    pred.Forward();
    std::vector<float> out = pred.GetOutput(0);
    std::ofstream fo(argv[3]);
    for (float v : out) { char b[32]; snprintf(b, 32, "%.6f\n", v); fo << b; }
  } catch (const std::exception& e) {
    fprintf(stderr, "cpp driver failed: %s\n", e.what());
    return 1;
  }
  return 0;
}
"""


def test_cpp_raii_wrapper_matches_python(checkpoint, tmp_path):
    """Header-only C++ wrapper (cpp-package analogue) end-to-end."""
    if not os.path.exists(SO):
        pytest.skip("libmxpredict.so not built")
    include_dir = os.path.join(REPO, "native", "include")
    exe = _compile_driver(tmp_path, CPP_DRIVER, compiler="g++",
                          suffix=".cc",
                          extra_flags=("-std=c++17", "-I", include_dir))
    _run_driver_and_compare(exe, checkpoint, tmp_path)
