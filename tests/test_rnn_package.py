"""Symbolic mx.rnn package tests (reference tests/python/unittest/test_rnn.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _run_sym(sym, shapes, seed=0):
    np.random.seed(seed)
    args = {}
    arg_shapes, out_shapes, _ = sym.infer_shape(**shapes)
    for name, shape in zip(sym.list_arguments(), arg_shapes):
        args[name] = nd.array(np.random.randn(*shape).astype(np.float32)
                              * 0.1)
    exe = sym.bind(mx.cpu(), args)
    return exe.forward()[0], out_shapes


def test_rnn_cell_unroll_shapes():
    cell = mx.rnn.RNNCell(num_hidden=16, prefix="r_")
    data = mx.sym.Variable("data")
    outs, states = cell.unroll(3, data, merge_outputs=True)
    out, shapes = _run_sym(outs, {"data": (2, 3, 8)})
    assert out.shape == (2, 3, 16)


def test_lstm_gru_unroll_and_states():
    for make, n_states in ((lambda: mx.rnn.LSTMCell(12, prefix="l_"), 2),
                           (lambda: mx.rnn.GRUCell(12, prefix="g_"), 1)):
        cell = make()
        data = mx.sym.Variable("data")
        outs, states = cell.unroll(4, data, merge_outputs=True)
        assert len(states) == n_states
        out, _ = _run_sym(outs, {"data": (3, 4, 6)})
        assert out.shape == (3, 4, 12)
        assert np.isfinite(out.asnumpy()).all()


def test_sequential_and_residual_cells():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(8, prefix="s0_"))
    stack.add(mx.rnn.ResidualCell(mx.rnn.LSTMCell(8, prefix="s1_")))
    data = mx.sym.Variable("data")
    outs, states = stack.unroll(3, data, merge_outputs=True)
    out, _ = _run_sym(outs, {"data": (2, 3, 8)})
    assert out.shape == (2, 3, 8)
    assert len(states) == 4


def test_bidirectional_cell():
    cell = mx.rnn.BidirectionalCell(mx.rnn.LSTMCell(8, prefix="fw_"),
                                    mx.rnn.LSTMCell(8, prefix="bw_"))
    data = mx.sym.Variable("data")
    outs, _ = cell.unroll(3, data, merge_outputs=True)
    out, _ = _run_sym(outs, {"data": (2, 3, 5)})
    assert out.shape == (2, 3, 16)     # fwd+bwd concat


def test_fused_rnn_cell_unroll_and_unfuse():
    fused = mx.rnn.FusedRNNCell(10, num_layers=2, mode="lstm",
                                prefix="f_")
    data = mx.sym.Variable("data")
    outs, _ = fused.unroll(5, data, layout="NTC", merge_outputs=True)
    out, _ = _run_sym(outs, {"data": (3, 5, 7)})
    assert out.shape == (3, 5, 10)
    assert np.isfinite(out.asnumpy()).all()

    stack = fused.unfuse()
    outs2, _ = stack.unroll(5, data, merge_outputs=True)
    out2, _ = _run_sym(outs2, {"data": (3, 5, 7)})
    assert out2.shape == (3, 5, 10)


def test_bucket_sentence_iter_contract():
    sents = [[1, 2, 3], [4, 5, 6, 7, 8], [1, 2], [3, 4, 5, 6]]
    it = mx.rnn.BucketSentenceIter(sents, batch_size=2, buckets=[4, 8],
                                   invalid_label=0)
    batch = it.next()
    assert batch.bucket_key in (4, 8)
    data = batch.data[0].asnumpy()
    label = batch.label[0].asnumpy()
    assert data.shape == (2, batch.bucket_key)
    # label is data shifted one step left
    np.testing.assert_array_equal(label[:, :-1], data[:, 1:])


def test_encode_sentences_grows_vocab():
    enc, vocab = mx.rnn.encode_sentences([["a", "b"], ["b", "c"]],
                                         start_label=1)
    assert sorted(set(sum(enc, []))) == [1, 2, 3]
    assert set(vocab) >= {"a", "b", "c"}
