"""graftlock: JG009/010/011 rule fixtures + the runtime lock witness.

Static side: every concurrency rule gets a firing fixture and a clean
twin through ``lint_source``/``lint_sources`` — including the two-module
lock-order cycle that only exists once ``link_project`` stitches the
cross-module call graph.  Runtime side: a deterministically sequenced
ABBA inversion across two threads must produce a violation that names
both locks and both acquisition sites, the off path must hand back plain
stdlib primitives, and ``reset`` must clear the recorded graph.
"""
import textwrap
import threading
import warnings

import pytest

from mxnet_tpu.lint import lint_source, lint_sources
from mxnet_tpu.lint import lockwitness


def codes(src, select=None):
    findings = lint_source(textwrap.dedent(src), path="fixture.py",
                           select=select)
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# JG009 lock-order-cycle
# ---------------------------------------------------------------------------

def test_jg009_fires_on_abba_order():
    src = """
    import threading

    LOCK_A = threading.Lock()
    LOCK_B = threading.Lock()

    def forward():
        with LOCK_A:
            with LOCK_B:
                pass

    def backward():
        with LOCK_B:
            with LOCK_A:
                pass
    """
    found = codes(src, {"JG009"})
    assert found == ["JG009"]


def test_jg009_clean_on_consistent_order():
    src = """
    import threading

    LOCK_A = threading.Lock()
    LOCK_B = threading.Lock()

    def forward():
        with LOCK_A:
            with LOCK_B:
                pass

    def also_forward():
        with LOCK_A:
            with LOCK_B:
                pass
    """
    assert codes(src, {"JG009"}) == []


MOD_A = """
import threading
from pkg.b import with_b

LOCK_A = threading.Lock()

def with_a():
    with LOCK_A:
        pass

def a_then_b():
    with LOCK_A:
        with_b()
"""

MOD_B = """
import threading
from pkg.a import with_a

LOCK_B = threading.Lock()

def with_b():
    with LOCK_B:
        pass

def b_then_a():
    with LOCK_B:
        with_a()
"""


def test_jg009_sees_cycle_across_modules():
    """The ISSUE 20 acceptance fixture: neither module has a cycle on
    its own; only the linked project (a holds A while calling into b's
    B-acquirer, b holds B while calling into a's A-acquirer) does."""
    findings = lint_sources([("pkg/a.py", MOD_A), ("pkg/b.py", MOD_B)],
                            select={"JG009"})
    assert [f.rule for f in findings] == ["JG009"]
    msg = findings[0].message
    assert "LOCK_A" in msg and "LOCK_B" in msg


def test_jg009_single_modules_are_clean_alone():
    for path, src in (("pkg/a.py", MOD_A), ("pkg/b.py", MOD_B)):
        assert [f.rule for f in lint_sources([(path, src)],
                                             select={"JG009"})] == []


# ---------------------------------------------------------------------------
# JG010 blocking-under-lock
# ---------------------------------------------------------------------------

def test_jg010_fires_on_recv_under_lock():
    src = """
    import threading

    class Server:
        def __init__(self, conn):
            self._lock = threading.Lock()
            self.conn = conn

        def handle(self):
            with self._lock:
                return self.conn.recv()
    """
    assert codes(src, {"JG010"}) == ["JG010"]


def test_jg010_fires_on_queue_get_through_callee():
    """The closure direction: the lock holder never blocks itself, it
    calls a helper whose body does."""
    src = """
    import threading
    import queue

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()
            self.inbox = queue.Queue()

        def _take(self):
            return self.inbox.get()

        def step(self):
            with self._lock:
                return self._take()
    """
    assert "JG010" in codes(src, {"JG010"})


def test_jg010_clean_when_call_moves_outside():
    src = """
    import threading

    class Server:
        def __init__(self, conn):
            self._lock = threading.Lock()
            self.conn = conn

        def handle(self):
            with self._lock:
                conn = self.conn
            return conn.recv()
    """
    assert codes(src, {"JG010"}) == []


def test_jg010_exempts_wait_on_own_condition():
    """Condition.wait RELEASES the lock it is built over — waiting on
    your own condition while holding exactly that lock is the legal
    release-and-wait idiom, not a blocking call under the lock."""
    src = """
    import threading

    class Waiter:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition(self._lock)
            self.ready = False

        def wait_ready(self):
            with self._lock:
                while not self.ready:
                    self._cv.wait()
    """
    assert codes(src, {"JG010"}) == []


# ---------------------------------------------------------------------------
# JG011 unguarded-shared-mutation
# ---------------------------------------------------------------------------

def test_jg011_fires_on_unguarded_two_sided_write():
    src = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.value = 0
            self.thread = threading.Thread(target=self._run)

        def _run(self):
            self.value += 1

        def reset(self):
            self.value = 0
    """
    assert codes(src, {"JG011"}) == ["JG011"]


def test_jg011_clean_when_both_sides_share_the_lock():
    src = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.value = 0
            self.thread = threading.Thread(target=self._run)

        def _run(self):
            with self._lock:
                self.value += 1

        def reset(self):
            with self._lock:
                self.value = 0
    """
    assert codes(src, {"JG011"}) == []


# ---------------------------------------------------------------------------
# the runtime witness
# ---------------------------------------------------------------------------

@pytest.fixture
def witness():
    lockwitness.reset()
    lockwitness.configure("warn")
    yield lockwitness
    lockwitness.reset()
    lockwitness.refresh_from_env()


def test_witness_off_path_returns_plain_primitives():
    lockwitness.reset()
    lockwitness.configure("off")
    lock = lockwitness.make_lock("plain")
    assert type(lock) is type(threading.Lock())
    rlock = lockwitness.make_rlock("plain_r")
    assert type(rlock) is type(threading.RLock())
    cond = lockwitness.make_condition(name="plain_cv")
    assert isinstance(cond, threading.Condition)


def test_witness_names_both_locks_and_sites_on_abba(witness):
    """Two threads, deterministically sequenced (t1 fully finishes
    before t2 starts): t1 establishes A -> B, t2's B -> A closes the
    cycle — the violation must name both locks and both sites."""
    a = lockwitness.make_lock("fixture.A")
    b = lockwitness.make_lock("fixture.B")

    def t1_establish_ab():
        with a:
            with b:
                pass

    def t2_invert_ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=t1_establish_ab)
    t1.start()
    t1.join()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        t2 = threading.Thread(target=t2_invert_ba)
        t2.start()
        t2.join()

    snap = lockwitness.snapshot()
    assert not snap["cycle_free"]
    (violation,) = snap["violations"]
    assert violation["edge"] == "fixture.B -> fixture.A"
    assert "fixture.A" in violation["cycle"] \
        and "fixture.B" in violation["cycle"]
    assert "t2_invert_ba" in violation["site"]
    assert "t1_establish_ab" in violation["prior_site"]
    edges = {(e["from"], e["to"]) for e in snap["edges"]}
    assert ("fixture.A", "fixture.B") in edges
    assert ("fixture.B", "fixture.A") in edges


def test_witness_raise_mode_raises_before_taking_the_lock(witness):
    lockwitness.configure("raise")
    a = lockwitness.make_lock("raise.A")
    b = lockwitness.make_lock("raise.B")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(lockwitness.LockOrderError) as exc:
            with a:
                pass
    assert "raise.B -> raise.A" in str(exc.value)
    # the raise happened BEFORE the inner acquire: nothing leaked into
    # the thread's held stack and both locks are free again
    assert lockwitness.held_locks() == []
    assert a.acquire(blocking=False)
    a.release()


def test_witness_condition_wait_keeps_held_stack_truthful(witness):
    done = []
    cv = lockwitness.make_condition(name="fixture.cv")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)

        def waiter():
            with cv:
                while not done:
                    cv.wait(timeout=5.0)

        t = threading.Thread(target=waiter)
        t.start()
        with cv:
            done.append(True)
            cv.notify_all()
        t.join(timeout=10.0)
    assert not t.is_alive()
    assert lockwitness.held_locks() == []
    assert lockwitness.snapshot()["cycle_free"]


def test_witness_reset_clears_the_graph(witness):
    a = lockwitness.make_lock("reset.A")
    b = lockwitness.make_lock("reset.B")
    with a:
        with b:
            pass
    assert lockwitness.snapshot()["edges"]
    lockwitness.reset()
    snap = lockwitness.snapshot()
    assert snap["edges"] == [] and snap["violations"] == []
    assert snap["cycle_free"]
