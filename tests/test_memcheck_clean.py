"""Tier-1 memcheck gate: the owned-program ledger is SPMD- and
memory-budget-clean, and the budget gate actually bites.

Three layers, one sweep (module-scoped — tracing + compiling all owned
specimens costs seconds, not minutes, but only once):

* every owned program passes the JX2xx rules with ZERO findings — the
  collective-safety invariants (no divergent rendezvous, canonical lane
  order, no replicated-gather outputs) are proven properties of the
  shipped ledger, not aspirations;
* MEM_BASELINE.json is fresh: present, topology-matched to the pinned
  8-device test mesh, every program budgeted, nothing stale;
* ``trace_report.py --gate-memory`` exits 0 on the real report and 3 on
  a deliberately over-budget twin — the CI wire, not just the library.
"""
import json
import os
import subprocess
import sys

import pytest

from mxnet_tpu.lint import tracecheck

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACE_REPORT = os.path.join(REPO, "tools", "trace_report.py")

# the ledger floor: shrinking coverage must fail this gate, not slide
MIN_PROGRAMS = 32


@pytest.fixture(scope="module")
def sweep():
    findings, names, report = tracecheck.analyze_entry_points()
    assert report is not None, "memory pass did not run"
    return findings, names, report


def gate(report, tmp_path, extra=()):
    path = tmp_path / "mem.json"
    path.write_text(json.dumps(report))
    proc = subprocess.run(
        [sys.executable, TRACE_REPORT, "--memory", str(path),
         "--gate-memory", *extra],
        capture_output=True, text=True)
    return proc.returncode, proc.stdout, proc.stderr


def test_owned_programs_are_spmd_clean(sweep):
    findings, names, _report = sweep
    spmd = [f for f in findings
            if f.rule.startswith("JX2") or f.rule == "JX000"]
    assert spmd == [], (
        "JX2xx findings on owned programs (fix the program or suppress "
        "with justification — do NOT grow the baseline):\n"
        + "\n".join("  %s %s: %s" % (f.rule, f.path, f.message)
                    for f in spmd))
    assert len(set(names)) >= MIN_PROGRAMS


def test_memory_budgets_are_fresh(sweep):
    _findings, _names, report = sweep
    assert report["baseline_present"], \
        "MEM_BASELINE.json missing — run graftcheck --write-mem-baseline"
    assert report["topology_match"], (
        "baseline captured on %s devices, test mesh has %s — the pinned "
        "conftest topology and the committed baseline must agree"
        % (report["baseline_n_devices"], report["n_devices"]))
    assert report["stale_budgets"] == []
    bad = [p["name"] for p in report["programs"]
           if p["over_budget"] or p["unbudgeted"]]
    assert bad == [], "over/unbudgeted programs: %s" % bad
    assert len(report["programs"]) >= MIN_PROGRAMS


def test_gate_memory_passes_on_real_report(sweep, tmp_path):
    _f, _n, report = sweep
    rc, out, _err = gate(report, tmp_path)
    assert rc == 0 and "gate-memory: ok" in out


def test_gate_memory_exits_3_on_over_budget(sweep, tmp_path):
    """The injected regression: shrink one program's budget to a tenth
    and re-run the REAL comparison (check_memory, not a doctored flag) —
    the gate must exit 3 and name the program."""
    _f, _n, report = sweep
    victim = max(report["programs"], key=lambda p: p["total_bytes"])
    baseline = tracecheck.load_mem_baseline()
    doctored = json.loads(json.dumps(baseline))
    doctored["programs"][victim["name"]]["total_bytes"] //= 10
    recs = [item for _g, item in tracecheck.iter_owned_programs(
        entries=tracecheck.groups_for_paths([victim["origin"]]))
            if not isinstance(item, tracecheck.Finding)
            and item.name == victim["name"]]
    assert recs, "victim program %r not re-traceable" % victim["name"]
    findings, bad_report = tracecheck.check_memory(recs, doctored,
                                                   full=False)
    assert any(f.snippet == "mem:over" for f in findings)
    rc, _out, err = gate(bad_report, tmp_path)
    assert rc == 3
    assert "gate-memory: FAIL" in err and victim["name"] in err


def test_gate_memory_exits_3_on_unbudgeted(sweep, tmp_path):
    _f, _n, report = sweep
    doctored = json.loads(json.dumps(report))
    doctored["programs"][0]["unbudgeted"] = True
    rc, _out, err = gate(doctored, tmp_path)
    assert rc == 3 and "unbudgeted" in err


def test_gate_memory_exits_4_when_unmeasurable(sweep, tmp_path):
    """A topology mismatch means the gate cannot compare — it must fail
    loudly as UNMEASURABLE (4), never silently pass."""
    _f, _n, report = sweep
    doctored = json.loads(json.dumps(report))
    doctored["topology_match"] = False
    rc, _out, err = gate(doctored, tmp_path)
    assert rc == 4 and "UNMEASURABLE" in err


def test_gate_memory_requires_memory_json(tmp_path):
    proc = subprocess.run(
        [sys.executable, TRACE_REPORT, "--gate-memory"],
        capture_output=True, text=True)
    assert proc.returncode == 2
