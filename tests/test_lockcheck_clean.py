"""Tier-1 gate: the repo's threaded tier stays graftlock-clean.

Static half: zero unsuppressed JG009/JG010/JG011 findings across
``mxnet_tpu/``, ``tools/``, and ``examples/`` — the concurrency rules
are held to the same zero-new-findings bar as the TPU footgun rules,
and the LINT_BASELINE.json escape hatch is closed to them entirely
(only justified inline ``# graftlint: disable=`` suppressions remain,
each carrying its reason at the site).

Runtime half: a 3-thread engine + kvstore smoke under
``MXNET_LOCKCHECK=1`` (raise mode) must finish with a cycle-free
acquisition-order graph that actually recorded edges — the live witness
agreeing with the static proof, not vacuously passing.
"""
import json
import os
import subprocess
import sys

from mxnet_tpu.lint import (default_baseline_path, lint_paths,
                            repo_root)

REPO = repo_root()
SCAN_ROOTS = [os.path.join(REPO, d)
              for d in ("mxnet_tpu", "tools", "examples")]
LOCK_RULES = {"JG009", "JG010", "JG011"}

_WITNESS_SMOKE = r"""
import json
import threading

import mxnet_tpu as mx
from mxnet_tpu import engine
from mxnet_tpu.lint import lockwitness

assert lockwitness.mode() == "raise", lockwitness.mode()

kv = mx.kv.create("local")
kv.init("w", mx.nd.zeros((8,)))

def worker(rank):
    for step in range(20):
        out = engine.push(lambda r=rank, s=step: mx.nd.ones((8,))
                          * (r + s))
        kv.push("w", out)
        pulled = mx.nd.zeros((8,))
        kv.pull("w", out=pulled)
        pulled.asnumpy()

threads = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
for t in threads:
    t.start()
for t in threads:
    t.join()
engine.wait_for_all()
# the funnel was live: the engine's own core lock is a tracked wrapper,
# so the clean graph below is a real witness, not an unplugged one
core = engine.engine()._core
assert type(core.lock).__name__ == "_TrackedLock", type(core.lock)
print(json.dumps(lockwitness.snapshot()))
"""


def test_zero_unsuppressed_lock_findings_repo_wide():
    findings = lint_paths(SCAN_ROOTS, select=LOCK_RULES, rel_root=REPO)
    assert not findings, (
        "concurrency findings in the repo (fix the lock discipline or "
        "suppress inline with a justification comment — the baseline "
        "is closed to JG009-011):\n"
        + "\n".join(f.format_text() for f in findings))


def test_baseline_is_closed_to_lock_rules():
    with open(default_baseline_path()) as f:
        entries = json.load(f)["entries"]
    lock_entries = [e for e in entries if e["rule"] in LOCK_RULES]
    assert lock_entries == [], (
        "JG009-011 never go in LINT_BASELINE.json (fix or suppress "
        "inline at the site): %s"
        % [(e["rule"], e["path"]) for e in lock_entries])


def test_runtime_witness_is_cycle_free_on_threaded_smoke():
    """3 threads hammering engine.push + local kvstore push/pull under
    MXNET_LOCKCHECK=1: any acquisition-order inversion raises inside the
    subprocess (nonzero exit).  The subprocess proves the funnel was
    live (the engine core lock is a tracked wrapper) and the exported
    graph must come back cycle-free."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_LOCKCHECK="1")
    out = subprocess.run([sys.executable, "-c", _WITNESS_SMOKE],
                         capture_output=True, text=True, env=env,
                         cwd=REPO, timeout=300)
    assert out.returncode == 0, (
        "witness smoke failed (a lock-order inversion raises under "
        "MXNET_LOCKCHECK=1):\n" + out.stdout + out.stderr)
    snap = json.loads(out.stdout.strip().splitlines()[-1])
    assert snap["mode"] == "raise"
    assert snap["cycle_free"], snap["violations"]
    assert snap["violations"] == []
