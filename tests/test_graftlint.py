"""graftlint: per-rule fixtures, suppressions, baseline, runtime sanitizer.

Every JG rule gets a firing (positive) and a non-firing (negative) fixture
snippet run through ``lint_source``; the sanitizer tests assert a planted
tracer leak raises under MXNET_SANITIZE=1 and is silent otherwise — the
same footgun the static JG001 fixture catches at review time (ISSUE 3
acceptance).
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.lint import (Baseline, RULES, lint_source, load_baseline,
                            repo_root)
from mxnet_tpu.lint import sanitizer

REPO = repo_root()


def codes(src, select=None):
    findings = lint_source(textwrap.dedent(src), path="fixture.py",
                          select=select)
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# JG001 host-sync-under-trace
# ---------------------------------------------------------------------------

def test_jg001_fires_on_host_sync_in_jitted_fn():
    src = """
    import jax

    def step(x, arr):
        lr = float(arr.mean())        # host sync while tracing
        return x * lr

    step_jit = jax.jit(step)
    """
    assert "JG001" in codes(src, {"JG001"})


def test_jg001_fires_on_asnumpy_and_item():
    src = """
    import jax

    @jax.jit
    def fwd(x):
        host = x.asnumpy()
        s = x.item()
        return host, s
    """
    assert codes(src, {"JG001"}).count("JG001") == 2


def test_jg001_fires_in_nested_def():
    src = """
    import jax

    def build():
        def step(x):
            def inner(y):
                return y.asnumpy()
            return inner(x)
        return jax.jit(step)
    """
    assert "JG001" in codes(src, {"JG001"})


def test_jg001_silent_outside_trace_and_on_shapes():
    src = """
    import jax

    def step(x):
        n = int(x.shape[0])          # static under jit: fine
        return x * n

    step_jit = jax.jit(step)

    def eager(arr):
        return arr.asnumpy()          # not jitted: fine
    """
    assert codes(src, {"JG001"}) == []


# ---------------------------------------------------------------------------
# JG002 naked-jit
# ---------------------------------------------------------------------------

def test_jg002_fires_on_naked_jit_call_and_decorator():
    src = """
    import jax

    def f(x):
        return x + 1

    g = jax.jit(f)

    @jax.jit
    def h(x):
        return x * 2
    """
    assert codes(src, {"JG002"}).count("JG002") == 2


def test_jg002_silent_when_watched():
    src = """
    import jax
    from mxnet_tpu import telemetry as _tel

    def f(x):
        return x + 1

    g = _tel.watch_jit(jax.jit(f), "f_step")
    """
    assert codes(src, {"JG002"}) == []


# ---------------------------------------------------------------------------
# JG003 retrace-hazard
# ---------------------------------------------------------------------------

def test_jg003_fires_on_str_default_not_static():
    src = """
    import jax

    def step(x, mode="train", cfg={}):
        return x

    step_jit = jax.jit(step)
    """
    assert codes(src, {"JG003"}).count("JG003") == 2


def test_jg003_fires_on_kwonly_default():
    src = """
    import jax

    def step(x, *, mode="train"):
        return x

    step_jit = jax.jit(step)
    safe_jit = jax.jit(step, static_argnames=("mode",))
    """
    assert codes(src, {"JG003"}).count("JG003") == 1


def test_jg003_silent_when_declared_static():
    src = """
    import jax

    def step(x, mode="train"):
        return x

    step_jit = jax.jit(step, static_argnames=("mode",))
    other = jax.jit(lambda x: x)
    """
    assert codes(src, {"JG003"}) == []


# ---------------------------------------------------------------------------
# JG004 donation-after-use
# ---------------------------------------------------------------------------

def test_jg004_fires_on_read_after_donation():
    src = """
    import jax

    def step(p, g):
        return p - g

    step_jit = jax.jit(step, donate_argnums=(0,))

    def train(params, grads):
        out = step_jit(params, grads)
        return params.sum() + out     # params was donated!
    """
    assert "JG004" in codes(src, {"JG004"})


def test_jg004_silent_on_nested_def_rebinding_name():
    src = """
    import jax

    def step(p, g):
        return p - g

    step_jit = jax.jit(step, donate_argnums=(0,))

    def train(params, grads):
        out = step_jit(params, grads)
        def helper(params):          # fresh binding, not the donated buf
            return params * 2
        return helper(out)
    """
    assert codes(src, {"JG004"}) == []


def test_jg004_silent_on_rebind_idiom():
    src = """
    import jax

    def step(p, g):
        return p - g

    step_jit = jax.jit(step, donate_argnums=(0,))

    def train(params, grads):
        params = step_jit(params, grads)   # rebound from result: fine
        return params.sum()
    """
    assert codes(src, {"JG004"}) == []


# ---------------------------------------------------------------------------
# JG005 global-PRNG
# ---------------------------------------------------------------------------

def test_jg005_fires_on_module_state_rng():
    src = """
    import random
    import numpy as np

    def draw(shape):
        a = np.random.uniform(-1, 1, shape)
        random.shuffle(a)
        return a
    """
    assert codes(src, {"JG005"}).count("JG005") == 2


def test_jg005_silent_on_generators_and_framework_rng():
    src = """
    import numpy as np
    from mxnet_tpu import random as _random

    def draw(shape, seed):
        rng = np.random.default_rng(seed)
        st = np.random.RandomState(seed)
        host = _random.host_rng().uniform(-1, 1, shape)
        return rng.uniform(-1, 1, shape), st.rand(4), host
    """
    assert codes(src, {"JG005"}) == []


# ---------------------------------------------------------------------------
# JG006 env-read-in-hot-path
# ---------------------------------------------------------------------------

def test_jg006_fires_in_hot_function_and_loop():
    src = """
    import os

    def _limit():
        return int(os.environ.get("X_LIMIT", "8"))

    def step(xs):
        for x in xs:
            flag = os.environ.get("X_FLAG")       # in a loop
        return _limit()                           # helper on the step path
    """
    assert codes(src, {"JG006"}).count("JG006") == 2


def test_jg006_silent_for_module_level_cached_bool():
    src = """
    import os

    def _env_enabled():
        return os.environ.get("X_TELEMETRY", "0") == "1"

    _ENABLED = _env_enabled()

    def step(x):
        if _ENABLED:
            return x * 2
        return x
    """
    assert codes(src, {"JG006"}) == []


# ---------------------------------------------------------------------------
# JG007 unbounded-blocking-call (dist/engine/serving scope)
# ---------------------------------------------------------------------------

def _codes_at(src, path, select=None):
    return [f.rule for f in lint_source(textwrap.dedent(src), path=path,
                                        select=select)]


def test_jg007_fires_on_unbounded_recv_and_queue_get():
    src = """
    def pump(conn, task_queue):
        msg = conn.recv()
        item = task_queue.get()
        return msg, item
    """
    assert _codes_at(src, "mxnet_tpu/dist_ps.py",
                     {"JG007"}) == ["JG007", "JG007"]
    # same patterns inside the serving tier
    assert _codes_at(src, "mxnet_tpu/serving/batcher.py",
                     {"JG007"}) == ["JG007", "JG007"]


def test_jg007_silent_with_deadline_or_explicit_none():
    src = """
    def pump(conn, task_queue, d):
        a = conn.recv(timeout=5.0)
        b = conn.recv(timeout=None)      # documented-deliberate wait
        c = task_queue.get(timeout=1.0)
        e = task_queue.get(block=False)
        f = d.get("key")                 # dict .get, not a queue
        g = d.get("key", None)
        return a, b, c, e, f, g
    """
    assert _codes_at(src, "mxnet_tpu/dist_ps.py", {"JG007"}) == []


def test_jg007_scoped_to_dist_engine_serving():
    src = """
    def pump(conn, queue):
        return conn.recv(), queue.get()
    """
    # outside the transport/scheduling tier the rule stays quiet
    assert _codes_at(src, "mxnet_tpu/io.py", {"JG007"}) == []
    assert _codes_at(src, "tools/launch.py", {"JG007"}) == []
    assert _codes_at(src, "mxnet_tpu/engine.py",
                     {"JG007"}) == ["JG007", "JG007"]


# ---------------------------------------------------------------------------
# JG008 shard-map-outside-substrate
# ---------------------------------------------------------------------------

def test_jg008_fires_on_shard_map_import_forms():
    assert codes("""
    from jax.experimental.shard_map import shard_map
    """, {"JG008"}) == ["JG008"]
    assert codes("""
    from jax.experimental import shard_map
    """, {"JG008"}) == ["JG008"]
    assert codes("""
    import jax.experimental.shard_map as shmap
    """, {"JG008"}) == ["JG008"]


def test_jg008_fires_on_attribute_use():
    src = """
    import jax

    def split(fn, mesh, specs):
        return jax.experimental.shard_map.shard_map(
            fn, mesh=mesh, in_specs=specs, out_specs=specs)
    """
    assert codes(src, {"JG008"}) == ["JG008"]


def test_jg008_quiet_on_the_substrate_wrapper():
    # the blessed spelling: every caller goes through parallel/mesh.py
    src = """
    from mxnet_tpu.parallel import mesh as mesh_mod

    def split(fn, mesh, specs):
        return mesh_mod.shard_map(fn, mesh=mesh, in_specs=specs,
                                  out_specs=specs)
    """
    assert codes(src, {"JG008"}) == []


def test_jg008_exempt_inside_parallel_mesh():
    """parallel/mesh.py IS the substrate: the one module allowed to
    touch jax's shard_map surface."""
    src = """
    from jax.experimental.shard_map import shard_map
    """
    assert _codes_at(src, "mxnet_tpu/parallel/mesh.py", {"JG008"}) == []
    assert _codes_at(src, "mxnet_tpu/parallel/sharded.py",
                     {"JG008"}) == ["JG008"]


def test_jg008_inline_suppression():
    src = """
    from jax.experimental.shard_map import shard_map  # graftlint: disable=JG008
    """
    assert codes(src, {"JG008"}) == []


def test_jg007_repo_has_no_unannotated_blocking_calls():
    """The tentpole burn-down: every remaining unbounded wait in the
    dist/engine/serving tier is either deadline-bounded, an explicit
    ``timeout=None``, or carries a justified inline suppression —
    nothing is baselined."""
    from mxnet_tpu.lint import lint_paths
    findings = lint_paths([os.path.join(REPO, "mxnet_tpu")],
                          select={"JG007"}, rel_root=REPO)
    assert not findings, "\n".join(f.format_text() for f in findings)


# ---------------------------------------------------------------------------
# suppressions / baseline / CLI
# ---------------------------------------------------------------------------

def test_inline_suppression_same_line_and_line_above():
    src = """
    import numpy as np

    def draw(shape):
        a = np.random.uniform(0, 1, shape)  # graftlint: disable=JG005
        # graftlint: disable=JG005
        b = np.random.normal(0, 1, shape)
        c = np.random.rand(4)               # graftlint: disable=JG001
        return a, b, c
    """
    found = codes(src, {"JG005"})
    assert found == ["JG005"]          # only the un-suppressed c-line


def test_suppression_skips_interleaved_comment_and_blank_lines():
    src = """
    import numpy as np

    def draw(shape):
        # graftlint: disable=JG005
        # justification may also come AFTER the directive

        a = np.random.uniform(0, 1, shape)
        return a
    """
    assert codes(src, {"JG005"}) == []


def test_suppression_on_wrapped_statement_and_with_justification():
    src = """
    import numpy as np

    def draw(shape):
        a = np.random.uniform(
            -1, 1, shape)  # graftlint: disable=JG005
        b = np.random.rand(4)  # graftlint: disable=JG005 legacy draw
        return a, b
    """
    assert codes(src, {"JG005"}) == []


def test_suppression_disable_all():
    src = """
    import numpy as np
    a = np.random.rand(4)  # graftlint: disable=all
    """
    assert codes(src) == []


def test_baseline_round_trip(tmp_path):
    src = textwrap.dedent("""
    import numpy as np
    a = np.random.rand(4)
    b = np.random.rand(4)
    """)
    findings = lint_source(src, path="mod.py")
    assert len(findings) == 2
    bl = Baseline.from_findings(findings)
    path = tmp_path / "bl.json"
    bl.save(str(path))
    loaded = load_baseline(str(path))
    new, matched, stale = loaded.apply(findings)
    assert new == [] and len(matched) == 2 and stale == {}
    # a third identical draw exceeds the baselined count and fires
    findings3 = lint_source(src + "c = np.random.rand(4)\n", path="mod.py")
    new, matched, stale = loaded.apply(findings3)
    assert len(new) == 1 and len(matched) == 2
    # removing all draws leaves the baseline stale
    new, matched, stale = loaded.apply([])
    assert new == [] and matched == [] and sum(stale.values()) == 2


def test_every_rule_registered_with_rationale():
    assert set(RULES) == {"JG001", "JG002", "JG003", "JG004", "JG005",
                          "JG006", "JG007", "JG008", "JG009", "JG010",
                          "JG011"}
    for rule in RULES.values():
        assert rule.name and rule.rationale


def test_cli_clean_against_checked_in_baseline():
    """ISSUE 3 acceptance: the tools CLI exits 0 on mxnet_tpu/ against the
    checked-in LINT_BASELINE.json, and --check-baseline finds no rot."""
    tool = os.path.join(REPO, "tools", "graftlint.py")
    for args in (["mxnet_tpu"], ["--check-baseline"]):
        proc = subprocess.run([sys.executable, tool] + args, cwd=REPO,
                              capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_json_format(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import numpy as np\nx = np.random.rand(3)\n")
    tool = os.path.join(REPO, "tools", "graftlint.py")
    proc = subprocess.run(
        [sys.executable, tool, str(bad), "--no-baseline", "-f", "json"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["new"] and payload["new"][0]["rule"] == "JG005"


def test_check_baseline_detects_stale(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    stale_bl = tmp_path / "bl.json"
    stale_bl.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "JG005", "path": "gone.py",
         "snippet": "x = np.random.rand(3)", "count": 1}]}))
    tool = os.path.join(REPO, "tools", "graftlint.py")
    proc = subprocess.run(
        [sys.executable, tool, str(clean), "--baseline", str(stale_bl),
         "--check-baseline"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "stale" in proc.stdout


# ---------------------------------------------------------------------------
# runtime sanitizer
# ---------------------------------------------------------------------------

@pytest.fixture
def sanitize_raise():
    sanitizer.configure(mode="raise")
    yield
    sanitizer.configure(mode="off")


def test_sanitizer_off_is_silent():
    """The planted sync-under-trace passes silently with MXNET_SANITIZE
    unset — the hazard jax itself never reports."""
    import jax
    assert sanitizer.mode() == "off"
    const = nd.array(np.ones((2, 2)))

    def f(v):
        _ = const.asnumpy()           # concrete under trace: silently baked
        return v + 1

    jax.jit(f)(jax.numpy.ones(3))     # no error


def test_sanitizer_catches_sync_under_trace(sanitize_raise):
    import jax
    const = nd.array(np.ones((2, 2)))

    def f(v):
        _ = const.asnumpy()
        return v + 1

    with pytest.raises(sanitizer.SanitizerError, match="under trace"):
        jax.jit(f)(jax.numpy.ones(5))


def test_sanitizer_catches_tracer_leak(sanitize_raise):
    import jax
    leaked = []

    def f(v):
        leaked.append(nd.NDArray(v))
        return v * 2

    jax.jit(f)(jax.numpy.ones(3))
    with pytest.raises(sanitizer.SanitizerError, match="tracer leak"):
        leaked[0].asnumpy()


def test_sanitizer_env_gate_subprocess(tmp_path):
    """MXNET_SANITIZE=1 in the environment arms the check at import."""
    script = tmp_path / "leak.py"
    script.write_text(textwrap.dedent("""
        import jax, numpy as np
        import mxnet_tpu as mx
        from mxnet_tpu import nd
        from mxnet_tpu.lint.sanitizer import SanitizerError
        const = nd.array(np.ones((2, 2)))
        def f(v):
            _ = const.asnumpy()
            return v + 1
        try:
            jax.jit(f)(jax.numpy.ones(3))
        except SanitizerError:
            print("CAUGHT")
        else:
            print("MISSED")
    """))
    env = dict(os.environ, MXNET_SANITIZE="1", JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=300)
    assert "CAUGHT" in proc.stdout, proc.stdout + proc.stderr


def test_sanitizer_warn_mode_logs_instead(sanitize_raise, caplog):
    import jax
    sanitizer.configure(mode="warn")
    const = nd.array(np.ones((2, 2)))

    def f(v):
        _ = const.asnumpy()
        return v + 1

    import logging
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.sanitizer"):
        jax.jit(f)(jax.numpy.ones(7))
    assert any("under trace" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# engine happens-before checker
# ---------------------------------------------------------------------------

def test_engine_hb_clean_under_sanitizer(sanitize_raise):
    """A well-declared task graph runs clean under the checker."""
    eng = mx.engine.ThreadedEngine(num_workers=2)
    try:
        v1, v2 = eng.new_variable(), eng.new_variable()
        order = []
        for i in range(8):
            eng.push(lambda i=i: order.append(i), mutable_vars=(v1,))
        eng.push(lambda: order.append("r"), const_vars=(v1,),
                 mutable_vars=(v2,))
        eng.wait_for_all()
        assert order[:8] == list(range(8))     # write serialization held
    finally:
        eng.close()


def test_engine_hb_concurrent_pushers_no_false_positive(sanitize_raise):
    """Ticket issuance and the native enqueue share one push scope, so
    racing pushers can't interleave ticket order against engine order
    (which would raise on a perfectly correct program)."""
    import threading
    eng = mx.engine.ThreadedEngine(num_workers=4)
    try:
        v = eng.new_variable()
        out = []
        def pusher(tid):
            for i in range(25):
                eng.push(lambda t=tid, i=i: out.append((t, i)),
                         mutable_vars=(v,))
        threads = [threading.Thread(target=pusher, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        eng.wait_for_all()            # raises on any spurious violation
        assert len(out) == 100
    finally:
        eng.close()


def test_engine_hb_catches_out_of_order_write(sanitize_raise):
    """Violations surface at the next wait point: simulate a scheduler bug
    by running guarded tasks directly out of push order."""
    eng = mx.engine.ThreadedEngine(num_workers=1)
    try:
        v = eng.new_variable()
        t1 = sanitizer.guard_task(eng, lambda: None, (), (v,))
        t2 = sanitizer.guard_task(eng, lambda: None, (), (v,))
        with pytest.raises(sanitizer.SanitizerError,
                           match="out of push order"):
            t2()                      # write 1 landing before write 0
        del t1
    finally:
        eng.close()


def test_engine_hb_cancelled_push_does_not_poison_ordering(sanitize_raise):
    """A push that fails before reaching the engine rolls its ticket back
    (engine.push's except path calls guarded.cancel()), so later writes to
    the same var don't read as out-of-order forever."""
    eng = mx.engine.ThreadedEngine(num_workers=1)
    try:
        v = eng.new_variable()
        dead = sanitizer.guard_task(eng, lambda: None, (), (v,))
        dead.cancel()                 # the native enqueue "raised"
        ran = []
        nxt = sanitizer.guard_task(eng, lambda: ran.append(1), (), (v,))
        nxt()                         # must NOT raise out-of-push-order
        assert ran == [1]
        # delete_variable prunes the (drained) ledger entry
        eng.delete_variable(v)
        assert int(v) not in getattr(eng, "_graftlint_hb").vars
        # deletion with a pending write defers until that write drains
        w = eng.new_variable()
        t1 = sanitizer.guard_task(eng, lambda: None, (), (w,))
        t2 = sanitizer.guard_task(eng, lambda: None, (), (w,))
        t1()
        eng.delete_variable(w)        # t2 still holds ticket 1
        assert int(w) in eng._graftlint_hb.vars
        t2()                          # must not misreport push order...
        assert int(w) not in eng._graftlint_hb.vars   # ...and reaps
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# cross-module project linking (ISSUE 5 satellite)
# ---------------------------------------------------------------------------

from mxnet_tpu.lint import lint_sources  # noqa: E402


def project_codes(named, select=None):
    findings = lint_sources(
        [(path, textwrap.dedent(src)) for path, src in named], select)
    return [(f.path, f.rule) for f in findings]


def test_cross_module_jg001_through_import_edge():
    """A jitted step in one file calls a helper imported from another:
    the host sync inside the helper fires JG001 in the helper's file."""
    helper = """
    def normalize(x):
        scale = float(x.mean())       # host sync when called under trace
        return x / scale
    """
    step = """
    import jax
    from mxnet_tpu.helpers_mod import normalize

    @jax.jit
    def step(x):
        return normalize(x) * 2.0
    """
    found = project_codes([("mxnet_tpu/helpers_mod.py", helper),
                           ("mxnet_tpu/step_mod.py", step)], {"JG001"})
    assert ("mxnet_tpu/helpers_mod.py", "JG001") in found


def test_cross_module_jg001_quiet_without_traced_caller():
    """Same two files, but the caller is NOT jitted: the helper's float()
    is ordinary eager host code — no finding in either file."""
    helper = """
    def normalize(x):
        scale = float(x.mean())
        return x / scale
    """
    caller = """
    from mxnet_tpu.helpers_mod import normalize

    def evaluate(x):
        return normalize(x) * 2.0
    """
    assert project_codes([("mxnet_tpu/helpers_mod.py", helper),
                          ("mxnet_tpu/eval_mod.py", caller)],
                         {"JG001"}) == []


def test_cross_module_jg006_hot_path_through_import_edge():
    """step() in one file calls a flag helper imported from another: the
    env read inside the helper is now on the step path -> JG006 there."""
    flags = """
    import os

    def fused_enabled():
        return os.environ.get("FUSED", "1") == "1"
    """
    trainer = """
    from mxnet_tpu.flags_mod import fused_enabled

    def step(batch):
        if fused_enabled():
            return batch
        return None
    """
    found = project_codes([("mxnet_tpu/flags_mod.py", flags),
                           ("mxnet_tpu/trainer_mod.py", trainer)],
                          {"JG006"})
    assert ("mxnet_tpu/flags_mod.py", "JG006") in found


def test_cross_module_jg006_quiet_off_the_hot_path():
    flags = """
    import os

    def fused_enabled():
        return os.environ.get("FUSED", "1") == "1"
    """
    setup = """
    from mxnet_tpu.flags_mod import fused_enabled

    def build_config():
        return {"fused": fused_enabled()}
    """
    assert project_codes([("mxnet_tpu/flags_mod.py", flags),
                          ("mxnet_tpu/setup_mod.py", setup)],
                         {"JG006"}) == []


def test_cross_module_relative_import_from_package_init():
    """An __init__.py IS its package: ``from .flags_mod import f`` there
    resolves against the package itself, not its parent — the edge from a
    hot def in __init__.py must reach the helper's file."""
    flags = """
    import os

    def fused_enabled():
        return os.environ.get("FUSED", "1") == "1"
    """
    init = """
    from .flags_mod import fused_enabled

    def step(batch):
        if fused_enabled():
            return batch
        return None
    """
    found = project_codes([("mxnet_tpu/flags_mod.py", flags),
                           ("mxnet_tpu/__init__.py", init)],
                          {"JG006"})
    assert ("mxnet_tpu/flags_mod.py", "JG006") in found


def test_cross_module_linking_is_def_precise():
    """A jitted inner `def step` must not smear traced-ness onto an
    unrelated same-named eager method (the ShardedTrainer.step false
    positive): the eager step's float() stays quiet, in a linked
    multi-module project."""
    sharded = """
    import jax

    def make_step(fn):
        def step(params, batch):
            return fn(params, batch)
        return jax.jit(step)

    class Trainer:
        def step(self, batch):
            loss = self._fn(batch)
            return float(loss)        # step-boundary sync: legitimate
    """
    other = """
    from mxnet_tpu.sharded_mod import make_step

    def build(fn):
        return make_step(fn)
    """
    assert project_codes([("mxnet_tpu/sharded_mod.py", sharded),
                          ("mxnet_tpu/build_mod.py", other)],
                         {"JG001"}) == []


def test_single_file_scan_has_no_cross_module_annotations():
    """lint_source (one module) must behave exactly as before the
    project linker existed — linking requires >= 2 modules."""
    src = """
    import os

    def helper():
        return os.environ.get("FLAG")
    """
    assert codes(src, {"JG006"}) == []


# ---------------------------------------------------------------------------
# --diff mode (ISSUE 5 satellite): pre-commit-speed scans
# ---------------------------------------------------------------------------

def _git(repo, *argv):
    subprocess.run(
        ["git", "-C", str(repo)] + list(argv), check=True,
        capture_output=True,
        env=dict(os.environ, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                 GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t"))


def _run_cli(argv):
    import io
    from contextlib import redirect_stdout
    from mxnet_tpu.lint import cli
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(argv)
    return rc, buf.getvalue()


def test_diff_mode_lints_only_changed_files(tmp_path, monkeypatch):
    """--diff <ref> scans exactly the .py files changed vs the ref: a
    committed-dirty-but-untouched file is skipped, a working-tree edit is
    caught — the contract that makes it safe as a fast pre-commit hook."""
    from mxnet_tpu.lint import cli
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    _git(tmp_path, "init", "-q")
    (pkg / "changed.py").write_text("x = 1\n")
    (pkg / "legacy.py").write_text(
        "import numpy as np\nv = np.random.rand(3)\n")       # JG005
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    monkeypatch.setattr(cli, "repo_root", lambda: str(tmp_path))

    # nothing changed: clean exit, nothing scanned (NOT a usage error)
    rc, out = _run_cli(["--diff", "HEAD", "--no-baseline"])
    assert rc == 0 and "no changed Python files" in out

    # a working-tree edit introduces a finding -> caught; legacy.py's
    # pre-existing finding is out of the diff -> not reported
    (pkg / "changed.py").write_text(
        "import numpy as np\ny = np.random.rand(3)\n")
    rc, out = _run_cli(["--diff", "HEAD", "--no-baseline", "-f", "json"])
    assert rc == 1
    paths = {f["path"] for f in json.loads(out)["new"]}
    assert paths == {"mxnet_tpu/changed.py"}


def test_diff_mode_bad_ref_is_usage_error(tmp_path, monkeypatch):
    from mxnet_tpu.lint import cli
    (tmp_path / "mxnet_tpu").mkdir()
    _git(tmp_path, "init", "-q")
    monkeypatch.setattr(cli, "repo_root", lambda: str(tmp_path))
    rc, _out = _run_cli(["--diff", "no-such-ref", "--no-baseline"])
    assert rc == 2


def test_diff_mode_bad_path_is_usage_error(tmp_path, monkeypatch):
    """A typo'd scan root under --diff must stay exit 2 — falling through
    to 'no changed Python files' + exit 0 would silently disable lint in
    a pre-commit hook forever."""
    from mxnet_tpu.lint import cli
    (tmp_path / "mxnet_tpu").mkdir()
    _git(tmp_path, "init", "-q")
    monkeypatch.setattr(cli, "repo_root", lambda: str(tmp_path))
    rc, _out = _run_cli(["--diff", "HEAD", "--no-baseline",
                         str(tmp_path / "mxnet_tpo")])
    assert rc == 2


def test_diff_mode_catches_untracked_files(tmp_path, monkeypatch):
    """A brand-new file that was never ``git add``-ed is exactly what a
    pre-commit run must see — ``git diff`` alone would skip it."""
    from mxnet_tpu.lint import cli
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    _git(tmp_path, "init", "-q")
    (pkg / "old.py").write_text("x = 1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    monkeypatch.setattr(cli, "repo_root", lambda: str(tmp_path))

    (pkg / "brand_new.py").write_text(
        "import numpy as np\nz = np.random.rand(3)\n")        # JG005
    rc, out = _run_cli(["--diff", "HEAD", "--no-baseline", "-f", "json"])
    assert rc == 1
    paths = {f["path"] for f in json.loads(out)["new"]}
    assert paths == {"mxnet_tpu/brand_new.py"}


def test_trace_rejects_paths_plus_diff_as_usage_error(capsys):
    """Two scopes (entry groups AND --diff) would silently intersect —
    the CLI must refuse rather than guess."""
    rc, _out = _run_cli(["--trace", "--diff", "HEAD", "guardian"])
    assert rc == 2
    assert "OR --diff" in capsys.readouterr().err


def test_groups_for_paths_maps_providers_to_entry_groups():
    from mxnet_tpu.lint import tracecheck
    assert tracecheck.groups_for_paths(["mxnet_tpu/guardian.py"]) \
        == {"guardian"}
    assert tracecheck.groups_for_paths(
        ["mxnet_tpu/models/transformer.py", "README.md"]) \
        == {"transformer"}
    assert tracecheck.groups_for_paths(["docs/LINT.md"]) == set()
    # a change to the analyzer itself dirties every verdict
    assert tracecheck.groups_for_paths(["mxnet_tpu/lint/tracecheck.py"]) \
        == {g for g, _m in tracecheck.ENTRY_POINTS}


def test_groups_for_paths_full_sweep_for_opprof():
    """A cost-model or attribution change invalidates EVERY perf
    verdict, not one entry group — opprof/costs edits map to the full
    re-sweep exactly like an analyzer edit does."""
    from mxnet_tpu.lint import tracecheck
    every = {g for g, _m in tracecheck.ENTRY_POINTS}
    assert tracecheck.groups_for_paths(
        ["mxnet_tpu/telemetry/opprof.py"]) == every
    assert tracecheck.groups_for_paths(
        ["mxnet_tpu/telemetry/costs.py", "README.md"]) == every
    # other telemetry modules stay out of the blast radius
    assert tracecheck.groups_for_paths(
        ["mxnet_tpu/telemetry/flight.py"]) == set()


def _tmp_trace_repo(tmp_path):
    """A throwaway git repo whose file layout mirrors the provider
    paths groups_for_paths keys on (content never imported — the trace
    tier loads the REAL modules; only the diff scoping is under test)."""
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    _git(tmp_path, "init", "-q")
    (pkg / "guardian.py").write_text("# provider stand-in\n")
    (tmp_path / "README.md").write_text("seed\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    return pkg


def test_trace_diff_scopes_to_changed_providers(tmp_path, monkeypatch,
                                                capsys):
    """--diff parity for the trace tier: a working-tree edit to a
    provider module re-checks exactly that entry group's programs."""
    from mxnet_tpu.lint import cli
    pkg = _tmp_trace_repo(tmp_path)
    monkeypatch.setattr(cli, "repo_root", lambda: str(tmp_path))

    (pkg / "guardian.py").write_text("# provider stand-in, edited\n")
    rc, _out = _run_cli(["--trace", "--diff", "HEAD", "--no-baseline"])
    err = capsys.readouterr().err
    assert rc == 0
    assert "entry group(s): guardian" in err
    assert "guardian_verdict" in err          # the group's program ran
    assert "transformer_train_step" not in err  # out-of-scope group didn't


def test_trace_diff_with_no_changed_providers_is_clean_noop(
        tmp_path, monkeypatch, capsys):
    """An edit that touches no provider (docs, README) exits 0 with an
    explicit 'nothing to trace' note — NOT a full sweep, NOT an error."""
    from mxnet_tpu.lint import cli
    _tmp_trace_repo(tmp_path)
    monkeypatch.setattr(cli, "repo_root", lambda: str(tmp_path))

    (tmp_path / "README.md").write_text("edited\n")
    rc, out = _run_cli(["--trace", "--diff", "HEAD", "--no-baseline"])
    capsys.readouterr()
    assert rc == 0
    assert "no changed trace providers" in out


def test_trace_diff_bad_ref_is_usage_error(tmp_path, monkeypatch,
                                           capsys):
    from mxnet_tpu.lint import cli
    _tmp_trace_repo(tmp_path)
    monkeypatch.setattr(cli, "repo_root", lambda: str(tmp_path))
    rc, _out = _run_cli(["--trace", "--diff", "no-such-ref",
                         "--no-baseline"])
    capsys.readouterr()
    assert rc == 2
