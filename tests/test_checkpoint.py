"""Preemption-safe training (ISSUE 7): async sharded checkpoints,
SIGTERM-to-resume, elastic restart.

Acceptance contract: ``kill -TERM`` mid-run in a subprocess → final
synchronous checkpoint at the next step boundary → resume → bitwise-
identical loss trajectory on CPU, including a resume with a different
(faked, ``MXNET_CKPT_SHARDS``) device count; a corrupt shard falls back
to the previous complete checkpoint without crashing; and no
``flight_*.json`` is ever tracked at the repo root.
"""
import glob
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon, telemetry
from mxnet_tpu.checkpoint import hooks, reshard
from mxnet_tpu.gluon import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# in-process helpers
# ---------------------------------------------------------------------------

def _build(seed=7):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.Sequential()
    net.add(nn.Dense(8, activation="relu"))
    net.add(nn.Dense(4))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    rs = np.random.RandomState(3)
    data = mx.nd.array(rs.randn(32, 6).astype(np.float32))
    label = mx.nd.array(rs.randn(32, 4).astype(np.float32))
    it = mx.io.NDArrayIter(data, label, batch_size=8, shuffle=True,
                           last_batch_handle="discard")
    return net, trainer, it


def _run_steps(net, trainer, it, n):
    loss_fn = gluon.loss.L2Loss()
    losses = []
    for _ in range(n):
        try:
            batch = it.next()
        except StopIteration:
            it.reset()
            batch = it.next()
        with autograd.record():
            loss = loss_fn(net(batch.data[0]), batch.label[0])
        loss.backward()
        trainer.step(8)
        losses.append(float(np.float64(loss.asnumpy().sum())))
    return losses


@pytest.fixture(autouse=True)
def _detach_manager():
    """No CheckpointManager may leak into other tests' Trainer.step."""
    yield
    m = hooks.active()
    if m is not None:
        hooks.unregister(m)


# ---------------------------------------------------------------------------
# async snapshot + elastic restore (in-process)
# ---------------------------------------------------------------------------

def test_async_save_restore_bitwise(tmp_path):
    """Resume from an async snapshot — with a CHANGED shard count — and
    the loss trajectory is bitwise-identical to an uninterrupted run."""
    net, tr, it = _build()
    ref = _run_steps(net, tr, it, 8)

    d = str(tmp_path / "ckpt")
    net, tr, it = _build()
    first = _run_steps(net, tr, it, 4)
    mgr = checkpoint.CheckpointManager(d, trainer=tr, data_iter=it,
                                       num_shards=4)
    assert mgr.save(4, sync=True), mgr.last_error
    mgr.close()

    net2, tr2, it2 = _build()
    mgr2 = checkpoint.CheckpointManager(d, trainer=tr2, data_iter=it2,
                                        num_shards=2)   # elastic: 4 -> 2
    assert mgr2.restore() == 4
    rest = _run_steps(net2, tr2, it2, 4)
    mgr2.close()
    assert first + rest == ref


def test_manifest_shards_and_checksums(tmp_path):
    net, tr, it = _build()
    _run_steps(net, tr, it, 2)
    mgr = checkpoint.CheckpointManager(str(tmp_path), trainer=tr,
                                       data_iter=it, num_shards=4)
    assert mgr.save(2, sync=True)
    view = checkpoint.http_view()
    assert view["active"] and view["checkpoints"][0]["step"] == 2
    mgr.close()
    (cdir,) = glob.glob(str(tmp_path / "ckpt-*"))
    manifest = json.loads(open(os.path.join(cdir, "manifest.json")).read())
    assert manifest["complete"] and manifest["step"] == 2
    assert manifest["n_shards"] == 4
    optim_shards = [n for n in manifest["files"] if n.startswith("optim-")]
    assert len(optim_shards) == 4          # one shard per (faked) replica
    for name, meta in manifest["files"].items():
        path = os.path.join(cdir, name)
        assert os.path.getsize(path) == meta["bytes"]
    assert telemetry.gauge("checkpoint_last_step") == 2
    assert telemetry.gauge("checkpoint_bytes") > 0


def test_corrupt_shard_falls_back_to_previous(tmp_path):
    """A torn/corrupt newest checkpoint is skipped, not fatal."""
    d = str(tmp_path)
    net, tr, it = _build()
    _run_steps(net, tr, it, 2)
    mgr = checkpoint.CheckpointManager(d, trainer=tr, data_iter=it,
                                       num_shards=2, keep=5)
    assert mgr.save(2, sync=True)
    want = {i: p.data().asnumpy().copy()
            for i, p in enumerate(tr._params)}
    _run_steps(net, tr, it, 2)
    assert mgr.save(4, sync=True)
    mgr.close()

    # flip one byte in the newest checkpoint's first optimizer shard
    (shard,) = glob.glob(os.path.join(d, "ckpt-*4", "optim-00000-*"))
    blob = bytearray(open(shard, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(shard, "wb").write(bytes(blob))

    before = telemetry.counter("checkpoint_restore_fallbacks")
    net2, tr2, it2 = _build()
    mgr2 = checkpoint.CheckpointManager(d, trainer=tr2, data_iter=it2,
                                        num_shards=2)
    assert mgr2.restore() == 2             # fell back, did not crash
    mgr2.close()
    assert telemetry.counter("checkpoint_restore_fallbacks") > before
    for i, p in enumerate(tr2._params):
        np.testing.assert_array_equal(p.data().asnumpy(), want[i])


def test_missing_manifest_falls_back(tmp_path):
    d = str(tmp_path)
    net, tr, it = _build()
    _run_steps(net, tr, it, 1)
    mgr = checkpoint.CheckpointManager(d, trainer=tr, data_iter=it,
                                       num_shards=1, keep=5)
    assert mgr.save(1, sync=True)
    _run_steps(net, tr, it, 1)
    assert mgr.save(2, sync=True)
    mgr.close()
    os.remove(glob.glob(os.path.join(d, "ckpt-*2", "manifest.json"))[0])
    net2, tr2, it2 = _build()
    mgr2 = checkpoint.CheckpointManager(d, trainer=tr2, data_iter=it2,
                                        num_shards=1)
    assert mgr2.restore() == 1
    mgr2.close()


def test_retention_keeps_newest_complete(tmp_path):
    net, tr, it = _build()
    mgr = checkpoint.CheckpointManager(str(tmp_path), trainer=tr,
                                       data_iter=it, num_shards=1, keep=2)
    for step in (1, 2, 3, 4):
        _run_steps(net, tr, it, 1)
        assert mgr.save(step, sync=True)
    mgr.close()
    steps = sorted(int(os.path.basename(p).split("-")[1])
                   for p in glob.glob(str(tmp_path / "ckpt-*")))
    assert steps == [3, 4]


def test_write_retries_with_backoff(tmp_path, monkeypatch):
    """A transient commit failure retries (with the counter bumped) and
    the checkpoint still lands."""
    net, tr, it = _build()
    _run_steps(net, tr, it, 1)
    mgr = checkpoint.CheckpointManager(str(tmp_path), trainer=tr,
                                       data_iter=it, num_shards=1,
                                       retries=3)
    real = mgr._commit
    calls = {"n": 0}

    def flaky(snap):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("transient ENOSPC")
        return real(snap)

    monkeypatch.setattr(mgr, "_commit", flaky)
    before = telemetry.counter("checkpoint_write_retries")
    assert mgr.save(1, sync=True)
    mgr.close()
    assert calls["n"] == 2
    assert telemetry.counter("checkpoint_write_retries") == before + 1


def test_failed_save_can_be_reattempted(tmp_path, monkeypatch):
    """Exhausting all retries must not dedupe the step forever: an
    explicit later save of the same step re-captures and commits."""
    net, tr, it = _build()
    _run_steps(net, tr, it, 1)
    mgr = checkpoint.CheckpointManager(str(tmp_path), trainer=tr,
                                       data_iter=it, num_shards=1,
                                       retries=2)
    real = mgr._commit
    fail = {"on": True}

    def flaky(snap):
        if fail["on"]:
            raise OSError("transient ENOSPC")
        return real(snap)

    monkeypatch.setattr(mgr, "_commit", flaky)
    monkeypatch.setattr(checkpoint.manager.time, "sleep", lambda s: None)
    assert not mgr.save(1, sync=True)      # both attempts fail
    assert mgr.last_error is not None
    fail["on"] = False                     # "disk freed"
    assert mgr.save(1, sync=True), "retry of a failed step was deduped"
    assert mgr.last_committed_step == 1
    mgr.close()


def test_restore_survives_incompatible_iterator_state(tmp_path):
    """A checkpoint whose cursor cannot be applied to the CURRENT
    iterator type still restores the model state (no fallback onto
    already-applied params, no crash) — the stream just restarts."""
    net, tr, it = _build()
    _run_steps(net, tr, it, 2)
    mgr = checkpoint.CheckpointManager(str(tmp_path), trainer=tr,
                                       data_iter=it, num_shards=1)
    assert mgr.save(2, sync=True)
    want = {i: p.data().asnumpy().copy() for i, p in enumerate(tr._params)}
    mgr.close()

    class AlienIter:
        def get_checkpoint_state(self):
            return {"alien": True}

        def set_checkpoint_state(self, state):
            raise KeyError("cur")          # foreign cursor dict

    net2, tr2, _ = _build()
    mgr2 = checkpoint.CheckpointManager(str(tmp_path), trainer=tr2,
                                        data_iter=AlienIter(),
                                        num_shards=1)
    assert mgr2.restore() == 2
    mgr2.close()
    for i, p in enumerate(tr2._params):
        np.testing.assert_array_equal(p.data().asnumpy(), want[i])


def test_periodic_saves_from_step_boundaries(tmp_path):
    """every_steps rides the Trainer.step hook: no manual save calls."""
    net, tr, it = _build()
    mgr = checkpoint.CheckpointManager(str(tmp_path), trainer=tr,
                                       data_iter=it, num_shards=1,
                                       every_steps=2, keep=10)
    _run_steps(net, tr, it, 5)
    mgr.wait()
    mgr.close()
    steps = sorted(int(os.path.basename(p).split("-")[1])
                   for p in glob.glob(str(tmp_path / "ckpt-*")))
    assert steps == [2, 4]
    assert mgr.step == 5


def test_close_restores_sigterm_chain(tmp_path):
    """A closed manager must not keep owning SIGTERM: its boundaries
    will never fire again, so the signal must flow to the previous
    handler (the flight recorder's) instead of being swallowed."""
    net, tr, it = _build()
    prev = signal.getsignal(signal.SIGTERM)
    mgr = checkpoint.CheckpointManager(str(tmp_path), trainer=tr,
                                       data_iter=it, num_shards=1)
    mgr.install_preemption_handler()
    assert signal.getsignal(signal.SIGTERM) == mgr._on_sigterm
    mgr._grace_secs = 3600                 # regression must not kill pytest
    mgr._on_sigterm(signal.SIGTERM, None)  # preemption pending, timer armed
    assert mgr.preempt_pending()
    mgr.close()
    assert signal.getsignal(signal.SIGTERM) == prev
    assert not mgr._writer.is_alive()     # thread actually stopped
    # the armed grace timer must die with the manager, not os._exit a
    # process that moved on to post-run work
    assert mgr._grace_timer is None and not mgr.preempt_pending()


def test_restore_nothing_returns_none(tmp_path):
    net, tr, it = _build()
    mgr = checkpoint.CheckpointManager(str(tmp_path), trainer=tr,
                                       data_iter=it, num_shards=1)
    assert mgr.restore() is None
    mgr.close()


# ---------------------------------------------------------------------------
# reshard layout (pure)
# ---------------------------------------------------------------------------

def test_reshard_layout_deterministic_and_complete():
    slots = [9, 1, 5, 0, 3]
    # layout is a pure function of (slots, n): round-robin over sorted ids
    assert reshard.assign_slots(slots, 3) == [[0, 5], [1, 9], [3]]
    assert sorted(sum(reshard.assign_slots(slots, 3), [])) == sorted(slots)
    # every slot lands in exactly one target shard for any m/n
    for n_from in (1, 2, 4, 8):
        for n_to in (1, 3, 5):
            parts = reshard.assign_slots(range(11), n_to)
            seen = sum(parts, [])
            assert sorted(seen) == list(range(11))
            moves = reshard.redistribution_plan(range(11), n_from, n_to)
            assert all(src != dst for _, src, dst in moves)


def test_reshard_merge_rejects_duplicate_slots():
    with pytest.raises(ValueError):
        reshard.merge_into({0: "a"}, {0: "b"})


def test_module_path_snapshot_restore(tmp_path):
    """The module/ fit-loop wiring: boundary saves fire from fit, and a
    module checkpoint restores params + optimizer state into a fresh
    Module (kvstore-resident updater included)."""
    from mxnet_tpu import symbol as sym

    def _mlp():
        net = sym.var("data")
        net = sym.FullyConnected(net, num_hidden=8, name="fc1")
        net = sym.Activation(net, act_type="relu", name="relu1")
        net = sym.FullyConnected(net, num_hidden=4, name="fc2")
        return sym.SoftmaxOutput(net, name="softmax")

    rng = np.random.RandomState(0)
    X = rng.randn(40, 6).astype(np.float32)
    y = rng.randint(0, 4, 40).astype(np.float32)
    train = mx.io.NDArrayIter(X, y, batch_size=10)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mgr = checkpoint.CheckpointManager(str(tmp_path), module=mod,
                                       data_iter=train, num_shards=2,
                                       every_steps=2, keep=10)
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            num_epoch=1)
    mgr.wait()
    assert mgr.step == 4                  # fit-loop boundaries observed
    assert glob.glob(str(tmp_path / "ckpt-*")), "no boundary saves"
    assert mgr.save(mgr.step, sync=True), mgr.last_error
    mgr.close()
    want_arg, want_aux = mod.get_params()

    mod2 = mx.mod.Module(_mlp(), context=mx.cpu())
    mod2.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label, for_training=True)
    mod2.init_params()
    mod2.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": 0.1,
                                          "momentum": 0.9})
    mgr2 = checkpoint.CheckpointManager(str(tmp_path), module=mod2,
                                        data_iter=None, num_shards=1)
    assert mgr2.restore() == 4
    mgr2.close()
    got_arg, _ = mod2.get_params()
    for k in want_arg:
        np.testing.assert_array_equal(got_arg[k].asnumpy(),
                                      want_arg[k].asnumpy())


def test_kvstore_checkpoint_state_string_keyed_updater():
    """update_on_kvstore updaters key by param NAME (kvstore._updater_key
    falls through to the string): the checkpoint blob must round-trip
    string-keyed update counts, not assume int slots."""
    from mxnet_tpu import kvstore as kvs, optimizer as opt_mod

    def make_store():
        store = kvs.create("local")
        store.set_optimizer(opt_mod.create("adam", learning_rate=0.01))
        store.init("fc1_weight", mx.nd.ones((4, 3)))
        return store

    store = make_store()
    g = mx.nd.ones((4, 3))
    store.push("fc1_weight", [g])      # updater runs, t -> 1 (str key)
    blob = store.get_checkpoint_state()
    assert blob is not None

    fresh = make_store()
    fresh.set_checkpoint_state(blob)
    srv_opt = fresh._updater.optimizer
    assert srv_opt._index_update_count == {"fc1_weight": 1}
    assert srv_opt.num_update == 1
    st = store._updater.states["fc1_weight"]
    st2 = fresh._updater.states["fc1_weight"]
    np.testing.assert_array_equal(st[0].asnumpy(), st2[0].asnumpy())


def test_iterator_checkpoint_state_roundtrip():
    _, _, it = _build()
    it.next()
    it.next()
    state = it.get_checkpoint_state()
    a = it.next().data[0].asnumpy()
    it.set_checkpoint_state(state)
    b = it.next().data[0].asnumpy()
    np.testing.assert_array_equal(a, b)


def test_iterator_rejects_cursor_after_dataset_resize():
    """A cursor saved over N samples must not be silently applied to an
    M-sample dataset (stale permutation → garbage batches); the raise
    routes into the manager's non-fatal stream restart."""
    _, _, it = _build()
    state = it.get_checkpoint_state()
    rs = np.random.RandomState(0)
    bigger = mx.io.NDArrayIter(rs.randn(48, 6).astype(np.float32),
                               rs.randn(48, 4).astype(np.float32),
                               batch_size=8)
    with pytest.raises(ValueError):
        bigger.set_checkpoint_state(state)


# ---------------------------------------------------------------------------
# satellite: no flight dump may ever be tracked at the repo root
# ---------------------------------------------------------------------------

def test_no_flight_dumps_tracked_at_root():
    try:
        out = subprocess.run(["git", "-C", REPO, "ls-files"],
                             capture_output=True, text=True, timeout=60,
                             check=True).stdout
    except Exception:
        pytest.skip("git unavailable")
    tracked = [line for line in out.splitlines()
               if "/" not in line and line.startswith("flight_")
               and line.endswith(".json")]
    assert not tracked, "stray flight dumps tracked at repo root: %s" \
        % tracked
    # and the ignore rule that keeps them untracked must stay in place
    with open(os.path.join(REPO, ".gitignore")) as fh:
        assert "flight_*.json" in fh.read().split()


# ---------------------------------------------------------------------------
# SIGTERM fault injection (subprocess): the acceptance criteria
# ---------------------------------------------------------------------------

_TRAIN_SCRIPT = """
import json, os, sys, time
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon
from mxnet_tpu.gluon import nn

total = int(os.environ["CKPT_TOTAL_STEPS"])
sleep_s = float(os.environ.get("CKPT_SLEEP_S", "0"))
mx.random.seed(11)
np.random.seed(11)
net = nn.Sequential()
net.add(nn.Dense(8, activation="relu"))
net.add(nn.Dense(4))
net.initialize()
trainer = gluon.Trainer(net.collect_params(), "adam",
                        {"learning_rate": 0.05})
rs = np.random.RandomState(3)
data = mx.nd.array(rs.randn(32, 6).astype(np.float32))
label = mx.nd.array(rs.randn(32, 4).astype(np.float32))
it = mx.io.NDArrayIter(data, label, batch_size=8, shuffle=True,
                       last_batch_handle="discard")
loss_fn = gluon.loss.L2Loss()
mgr = checkpoint.CheckpointManager(os.environ["CKPT_DIR"],
                                   trainer=trainer, data_iter=it,
                                   every_steps=1)
start = mgr.restore() or 0
checkpoint.install_preemption_handler(mgr)
out = open(os.environ["CKPT_LOSS_FILE"], "a")
print("START %d" % start, flush=True)
step = start
while step < total:
    try:
        batch = it.next()
    except StopIteration:
        it.reset()
        batch = it.next()
    with autograd.record():
        loss = loss_fn(net(batch.data[0]), batch.label[0])
    loss.backward()
    trainer.step(8)
    step += 1
    out.write(json.dumps({"step": step,
                          "loss": float(np.float64(
                              loss.asnumpy().sum()))}) + "\\n")
    out.flush()
    os.fsync(out.fileno())
    if sleep_s:
        time.sleep(sleep_s)
mgr.wait()
print("DONE", flush=True)
"""

_HANG_SCRIPT = """
import os, time
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon
from mxnet_tpu.gluon import nn

net = nn.Sequential()
net.add(nn.Dense(4))
net.initialize()
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1})
mgr = checkpoint.CheckpointManager(os.environ["CKPT_DIR"],
                                   trainer=trainer)
checkpoint.install_preemption_handler(mgr)
x = mx.nd.array(np.ones((2, 3), np.float32))
y = mx.nd.array(np.ones((2, 4), np.float32))
loss_fn = gluon.loss.L2Loss()
with autograd.record():
    loss = loss_fn(net(x), y)
loss.backward()
trainer.step(2)
print("READY", flush=True)
time.sleep(300)          # wedged: no step boundary will ever arrive
"""


def _spawn(tmp_path, body, name, extra_env=None):
    script = tmp_path / ("%s.py" % name)
    script.write_text(body)
    env = dict(os.environ,
               CKPT_DIR=str(tmp_path / "ckpt"),
               CKPT_LOSS_FILE=str(tmp_path / "losses.jsonl"),
               MXNET_FLIGHT_DIR=str(tmp_path),
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.update(extra_env or {})
    return subprocess.Popen([sys.executable, str(script)],
                            cwd=str(tmp_path), env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)


def _losses(path):
    if not os.path.exists(path):
        return {}
    table = {}
    with open(path) as fh:
        for line in fh:
            if line.strip():
                rec = json.loads(line)
                table[rec["step"]] = rec["loss"]
    return table


def test_kill_term_resume_bitwise_trajectory(tmp_path):
    """The acceptance run: SIGTERM mid-step → final checkpoint → resume
    with a DIFFERENT faked device count → bitwise-matching loss
    trajectory vs an uninterrupted run."""
    total = 10
    # uninterrupted reference
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    proc = _spawn(ref_dir, _TRAIN_SCRIPT, "ref",
                  {"CKPT_TOTAL_STEPS": str(total)})
    out, err = proc.communicate(timeout=240)
    assert proc.returncode == 0, err.decode()[-2000:]
    ref = _losses(str(ref_dir / "losses.jsonl"))
    assert sorted(ref) == list(range(1, total + 1))

    # interrupted run: 4 optimizer shards, SIGTERM after a few steps
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    loss_file = str(run_dir / "losses.jsonl")
    proc = _spawn(run_dir, _TRAIN_SCRIPT, "victim",
                  {"CKPT_TOTAL_STEPS": str(total), "CKPT_SLEEP_S": "0.3",
                   "MXNET_CKPT_SHARDS": "4"})
    try:
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline and len(_losses(loss_file)) < 3:
            if proc.poll() is not None:
                raise AssertionError("victim died early: %s"
                                     % proc.communicate()[1][-2000:])
            time.sleep(0.05)
        assert len(_losses(loss_file)) >= 3, "victim made no progress"
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
    # final checkpoint written at the step boundary, then the chained
    # flight handler re-raised: exit status still says SIGTERM
    assert proc.returncode == -signal.SIGTERM, (proc.returncode,
                                                err.decode()[-2000:])
    assert glob.glob(str(run_dir / "flight_*.json")), \
        "chained flight dump missing"
    manifests = glob.glob(str(run_dir / "ckpt" / "ckpt-*" / "manifest.json"))
    assert manifests, "no final checkpoint committed"
    interrupted = _losses(loss_file)

    # resume in a fresh process with a DIFFERENT faked device count
    proc = _spawn(run_dir, _TRAIN_SCRIPT, "resume",
                  {"CKPT_TOTAL_STEPS": str(total),
                   "MXNET_CKPT_SHARDS": "2"})
    out, err = proc.communicate(timeout=240)
    assert proc.returncode == 0, err.decode()[-2000:]
    first_line = out.decode().splitlines()[0]
    resumed_from = int(first_line.split()[1])
    assert resumed_from >= 3, first_line   # resumed, not restarted

    merged = _losses(loss_file)
    # at most one step's loss line is missing: the boundary that
    # performed the final checkpoint died before its write
    assert len(merged) >= total - 1
    for step, loss in merged.items():
        assert loss == ref[step], \
            "step %d diverged after resume: %r != %r" \
            % (step, loss, ref[step])


def test_sigterm_grace_window_never_hangs(tmp_path):
    """A job wedged outside step boundaries (mid-collective, stuck
    engine push) still dies within the grace window — with a flight
    dump — instead of hanging the preemption."""
    proc = _spawn(tmp_path, _HANG_SCRIPT, "wedged",
                  {"MXNET_CKPT_GRACE_SECS": "1"})
    try:
        assert proc.stdout.readline().strip() == b"READY"
        t0 = time.monotonic()
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=60)
        took = time.monotonic() - t0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
    assert proc.returncode == 128 + signal.SIGTERM, \
        (proc.returncode, err.decode()[-2000:])
    assert took < 30, "grace expiry took %.1fs" % took
    dumps = glob.glob(str(tmp_path / "flight_*.json"))
    assert dumps
    dump = json.loads(open(dumps[0]).read())
    assert dump["reason"] == "preempt:grace-expired"


# ---------------------------------------------------------------------------
# last-good pinning + targeted restore (ISSUE 10, guardian rollback)
# ---------------------------------------------------------------------------

def test_targeted_restore_past_newer_checkpoints(tmp_path):
    """restore(step=) loads the TARGET even when newer checkpoints
    exist, and the continuation is bitwise-identical to the original
    run from that step."""
    d = str(tmp_path)
    net, tr, it = _build()
    _run_steps(net, tr, it, 2)
    mgr = checkpoint.CheckpointManager(d, trainer=tr, data_iter=it,
                                       num_shards=2, keep=5)
    assert mgr.save(2, sync=True)
    later = _run_steps(net, tr, it, 2)       # steps 3-4 of the original
    assert mgr.save(4, sync=True)
    mgr.close()

    net2, tr2, it2 = _build()
    mgr2 = checkpoint.CheckpointManager(d, trainer=tr2, data_iter=it2,
                                        num_shards=2)
    assert mgr2.restore(step=2) == 2
    assert mgr2.step == 2
    rest = _run_steps(net2, tr2, it2, 2)
    mgr2.close()
    assert rest == later


def test_pin_survives_retention_and_restart(tmp_path):
    """The last_good pin protects its checkpoint from the MXNET_CKPT_KEEP
    sweep and survives a process restart via the marker file."""
    net, tr, it = _build()
    mgr = checkpoint.CheckpointManager(str(tmp_path), trainer=tr,
                                       data_iter=it, num_shards=1, keep=2)
    for step in (1, 2, 3, 4, 5):
        _run_steps(net, tr, it, 1)
        assert mgr.save(step, sync=True)
        if step == 1:
            assert mgr.pin_last_good() == 1      # defaults to newest
    assert mgr.last_good_step == 1
    assert mgr.describe()["last_good_step"] == 1
    mgr.close()
    steps = sorted(int(os.path.basename(p).split("-")[1])
                   for p in glob.glob(str(tmp_path / "ckpt-*")))
    assert steps == [1, 4, 5]                    # pinned + newest keep=2

    net2, tr2, it2 = _build()
    mgr2 = checkpoint.CheckpointManager(str(tmp_path), trainer=tr2,
                                        data_iter=it2, num_shards=1)
    assert mgr2.last_good_step == 1              # marker file reloaded
    mgr2.close()


def test_corrupt_pinned_falls_back_nonfatally(tmp_path):
    """A corrupt pinned checkpoint must not crash the rollback: the
    targeted restore falls back to the remaining checkpoints."""
    d = str(tmp_path)
    net, tr, it = _build()
    _run_steps(net, tr, it, 2)
    mgr = checkpoint.CheckpointManager(d, trainer=tr, data_iter=it,
                                       num_shards=1, keep=5)
    assert mgr.save(2, sync=True)
    mgr.pin_last_good(2)
    _run_steps(net, tr, it, 2)
    assert mgr.save(4, sync=True)
    mgr.close()

    (params,) = glob.glob(os.path.join(d, "ckpt-*2", "params.pkl"))
    blob = bytearray(open(params, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(params, "wb").write(bytes(blob))

    before = telemetry.counter("checkpoint_restore_fallbacks")
    net2, tr2, it2 = _build()
    mgr2 = checkpoint.CheckpointManager(d, trainer=tr2, data_iter=it2,
                                        num_shards=1)
    assert mgr2.restore(step=2) == 4             # fell back, non-fatal
    mgr2.close()
    assert telemetry.counter("checkpoint_restore_fallbacks") > before


def test_restore_step_prefers_older_fallback_over_newer(tmp_path):
    """With the target corrupt, the fallback order is older-first (the
    newer checkpoints are exactly the unverified ones a rollback is
    fleeing) — newer only as the last resort."""
    d = str(tmp_path)
    net, tr, it = _build()
    _run_steps(net, tr, it, 1)
    mgr = checkpoint.CheckpointManager(d, trainer=tr, data_iter=it,
                                       num_shards=1, keep=5)
    assert mgr.save(1, sync=True)
    _run_steps(net, tr, it, 1)
    assert mgr.save(2, sync=True)
    _run_steps(net, tr, it, 1)
    assert mgr.save(3, sync=True)
    mgr.close()
    (params,) = glob.glob(os.path.join(d, "ckpt-*2", "params.pkl"))
    os.remove(params)

    net2, tr2, it2 = _build()
    mgr2 = checkpoint.CheckpointManager(d, trainer=tr2, data_iter=it2,
                                        num_shards=1)
    assert mgr2.restore(step=2) == 1             # older beats newer
    mgr2.close()


def test_restore_step_newer_last_resort_is_oldest_first(tmp_path):
    """No older checkpoint survives and the target is corrupt: the
    newer-group fallback takes the OLDEST newer checkpoint (closest to
    the last verified state), not the newest."""
    d = str(tmp_path)
    net, tr, it = _build()
    _run_steps(net, tr, it, 1)
    mgr = checkpoint.CheckpointManager(d, trainer=tr, data_iter=it,
                                       num_shards=1, keep=5)
    assert mgr.save(1, sync=True)
    _run_steps(net, tr, it, 1)
    assert mgr.save(2, sync=True)
    _run_steps(net, tr, it, 1)
    assert mgr.save(3, sync=True)
    mgr.close()
    (params,) = glob.glob(os.path.join(d, "ckpt-*1", "params.pkl"))
    os.remove(params)

    net2, tr2, it2 = _build()
    mgr2 = checkpoint.CheckpointManager(d, trainer=tr2, data_iter=it2,
                                        num_shards=1)
    assert mgr2.restore(step=1) == 2             # oldest of {2, 3}
    mgr2.close()
