"""ssh launcher command construction (VERDICT r3 #6; reference
tools/launch.py:22-30 + dmlc-core ssh tracker). No hosts are contacted —
only the argv/env contract is checked."""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import launch as launch_mod  # noqa: E402


def test_parse_hostfile(tmp_path):
    hf = tmp_path / "hosts.txt"
    hf.write_text("# cluster\nnode1 2\nnode2\n\nnode3 4  # big box\n")
    assert launch_mod.parse_hostfile(str(hf)) == [
        ("node1", 2), ("node2", 1), ("node3", 4)]


def test_assign_hosts_slots_are_hard_capacity():
    hosts = [("a", 2), ("b", 1)]
    assert launch_mod._assign_hosts(hosts, 3) == ["a", "a", "b"]
    assert launch_mod._assign_hosts(hosts, 2) == ["a", "a"]
    # over-request returns short so build_ssh_commands fails loudly
    # instead of silently oversubscribing a host (r4 advice)
    assert launch_mod._assign_hosts(hosts, 5) == ["a", "a", "b"]
    with pytest.raises(ValueError, match="usable slots"):
        launch_mod.build_ssh_commands(
            5, 0, ["python", "x.py"], hosts=hosts, scheduler_host="head",
            sched_port=9000, coord_port=9001)


def test_build_ssh_commands_contract():
    plans = launch_mod.build_ssh_commands(
        3, 2, ["python", "train.py", "--kv-store", "dist_sync"],
        hosts=[("node1", 2), ("node2", 2)],
        scheduler_host="head", sched_port=9000, coord_port=9001,
        cwd="/work dir")
    roles = [r for r, _, _ in plans]
    assert roles == ["scheduler", "server", "server",
                     "worker", "worker", "worker"]
    sched = plans[0]
    assert sched[1] == "head"
    workers = [p for p in plans if p[0] == "worker"]
    assert [h for _, h, _ in workers] == ["node1", "node1", "node2"]

    for i, (_, host, argv) in enumerate(workers):
        assert argv[0] == "ssh" and argv[-2] == host
        payload = argv[-1]
        # PS contract
        assert "DMLC_ROLE=worker" in payload
        assert "DMLC_PS_ROOT_URI=head" in payload
        assert "DMLC_PS_ROOT_PORT=9000" in payload
        assert "DMLC_NUM_WORKER=3" in payload
        assert "DMLC_NUM_SERVER=2" in payload
        assert "DMLC_WORKER_RANK=%d" % i in payload
        # jax.distributed contract
        assert "MXNET_COORDINATOR=head:9001" in payload
        assert "MXNET_PROCESS_ID=%d" % i in payload
        assert "MXNET_NUM_PROCESSES=3" in payload
        # command + cwd quoting
        assert payload.endswith("python train.py --kv-store dist_sync")
        assert "cd '/work dir'" in payload

    sched_payload = sched[2][-1]
    assert "DMLC_ROLE=scheduler" in sched_payload
    assert "DMLC_WORKER_RANK" not in sched_payload


def test_main_requires_hostfile_for_ssh(tmp_path, monkeypatch):
    monkeypatch.setattr(sys, "argv",
                        ["launch.py", "-n", "2", "--launcher", "ssh",
                         "python", "x.py"])
    with pytest.raises(SystemExit):
        launch_mod.main()


def test_build_mpi_command_contract():
    """mpi mode: one mpirun per role group with the DMLC_*/MXNET_* env
    exported via -x (ref launch.py mpi mode + dmlc_tracker/mpi.py)."""
    plans = launch_mod.build_mpi_command(
        4, 2, ["python", "train.py"], hostfile="hosts.txt",
        scheduler_host="head", sched_port=9000, coord_port=9001)
    assert len(plans) == 3
    sched, server, worker = plans
    for argv in plans:
        assert argv[0] == "mpirun"
        assert argv[-2:] == ["python", "train.py"]
        assert "--hostfile" in argv and "hosts.txt" in argv
        joined = " ".join(argv)
        assert "-x DMLC_PS_ROOT_URI=head" in joined
        assert "-x DMLC_PS_ROOT_PORT=9000" in joined
        assert "-x DMLC_NUM_WORKER=4" in joined
        assert "-x MXNET_COORDINATOR=head:9001" in joined
    assert sched[sched.index("-n") + 1] == "1"
    assert "-x DMLC_ROLE=scheduler" in " ".join(sched)
    assert server[server.index("-n") + 1] == "2"
    assert "-x DMLC_ROLE=server" in " ".join(server)
    assert worker[worker.index("-n") + 1] == "4"
    assert "-x DMLC_ROLE=worker" in " ".join(worker)
