"""Step time-series store (ISSUE 17 tentpole 2): bounded rings, export/
merge round-trips, the step-span exit hook, and the live ``/timeseries``
endpoint."""
import json
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, telemetry
from mxnet_tpu.telemetry import core as tcore
from mxnet_tpu.telemetry import server
from mxnet_tpu.telemetry import timeseries as ts


@pytest.fixture(autouse=True)
def clean_store():
    ts.reset()
    ts.configure(ts._DEFAULT_CAP)
    yield
    ts.reset()
    ts.configure(ts._DEFAULT_CAP)


def test_ring_wraparound_bounded_and_counted():
    ts.configure(steps=8)
    before = telemetry.counter("timeseries_evictions")
    for step in range(20):
        ts.record("step_time_us", step, 100.0 + step)
    pts = ts.series("step_time_us")
    assert len(pts) == 8, "ring must stay at MXNET_TIMESERIES_STEPS"
    # oldest points dropped first: the survivors are the last 8 steps
    assert [s for s, _ in pts] == list(range(12, 20))
    assert telemetry.counter("timeseries_evictions") - before == 12


def test_configure_shrink_rebounds_in_place():
    for step in range(10):
        ts.record("m", step, float(step))
    ts.configure(steps=4)
    assert [s for s, _ in ts.series("m")] == [6, 7, 8, 9]
    assert ts.cap() == 4


def test_refresh_from_env_parses_cap(monkeypatch):
    monkeypatch.setenv("MXNET_TIMESERIES_STEPS", "16")
    ts.refresh_from_env()
    assert ts.cap() == 16
    monkeypatch.setenv("MXNET_TIMESERIES_STEPS", "garbage")
    ts.refresh_from_env()
    assert ts.cap() == ts._DEFAULT_CAP
    monkeypatch.setenv("MXNET_TIMESERIES_STEPS", "0")
    ts.refresh_from_env()
    assert ts.cap() == ts._DEFAULT_CAP


def test_export_json_round_trip(tmp_path):
    ts.record("a", 0, 1.5)
    ts.record("a", 1, 2.5)
    ts.record("b", 0, -3.0)
    path = str(tmp_path / "run.json")
    ts.export_json(path)
    loaded = ts.load_export(path)
    assert loaded["version"] == 1
    assert loaded["series"]["a"] == [[0, 1.5], [1, 2.5]]
    assert loaded["series"]["b"] == [[0, -3.0]]
    with pytest.raises(ValueError):
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as fh:
            json.dump({"not": "an export"}, fh)
        ts.load_export(bad)


def test_merge_concatenates_and_sorts_by_step():
    exp1 = {"steps_seen": 3,
            "series": {"a": [[0, 1.0], [2, 3.0]], "only1": [[0, 9.0]]}}
    exp2 = {"steps_seen": 5,
            "series": {"a": [[1, 2.0], [3, 4.0]], "only2": [[1, 8.0]]}}
    merged = ts.merge([exp1, exp2])
    assert merged["steps_seen"] == 5
    assert merged["series"]["a"] == [[0, 1.0], [1, 2.0], [2, 3.0],
                                     [3, 4.0]]
    assert merged["series"]["only1"] == [[0, 9.0]]
    assert merged["series"]["only2"] == [[1, 8.0]]


def test_note_step_exit_books_time_and_live_gauges():
    telemetry.set_gauge("io_batch_wait_us", 17.0)
    try:
        ts.note_step_exit(1234.0)
        ts.note_step_exit(5678.0)
    finally:
        with tcore._mlock:
            tcore._gauges.pop("io_batch_wait_us", None)
    assert ts.series("step_time_us") == [(0, 1234.0), (1, 5678.0)]
    assert ts.series("io_batch_wait_us") == [(0, 17.0), (1, 17.0)]
    # gauges never set this run record nothing (no phantom zeros)
    assert ts.series("overlap_ratio") == []
    assert ts.export()["steps_seen"] == 2


def test_step_span_exit_feeds_timeseries(monkeypatch):
    """The integration seam: closing a real telemetry step span lands a
    step_time_us point — core._close_step_window calls the hook."""
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    telemetry.refresh_from_env()
    telemetry.reset()
    try:
        with telemetry.span("train_step", cat="step"):
            nd.array(np.ones((2, 2), np.float32)).sum().asnumpy()
        assert len(ts.series("step_time_us")) == 1
    finally:
        telemetry.reset()
        monkeypatch.delenv("MXNET_TELEMETRY", raising=False)
        telemetry.refresh_from_env()


def test_record_model_stats_series_names():
    from mxnet_tpu import model_stats
    stats = [[1.0, 4.0, 0.1, 2.0], [9.0, 16.0, 0.2, 3.0]]
    ts.record_model_stats(5, ["w", "b"], stats, loss=0.5)
    assert ts.series("model/w/grad_norm_sq") == [(5, 1.0)]
    assert ts.series("model/b/weight_norm_sq") == [(5, 16.0)]
    assert ts.series("model/w/update_ratio") == [(5, 0.1)]
    assert ts.series("model/b/grad_absmax") == [(5, 3.0)]
    assert ts.series("model/loss") == [(5, 0.5)]
    assert len(ts.names()) == 2 * len(model_stats.STAT_NAMES) + 1


def test_timeseries_endpoint_live(monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    telemetry.refresh_from_env()
    telemetry.reset()
    srv = server.start_server(port=0, sample_ms=100)
    try:
        ts.record("step_time_us", 0, 111.0)
        ts.record("model/loss", 0, 0.25)

        def get(path):
            url = "http://127.0.0.1:%d%s" % (srv.port, path)
            with urllib.request.urlopen(url, timeout=10) as resp:
                return resp.status, json.loads(resp.read().decode())

        status, body = get("/timeseries")
        assert status == 200
        assert body["n_series"] == 2
        assert body["series"]["model/loss"]["last_value"] == 0.25
        assert "points" in body["series"]["step_time_us"]

        status, full = get("/timeseries?full=1")
        assert status == 200
        assert full["series"]["model/loss"] == [[0, 0.25]]

        # the endpoint is observe-only: scraping must not create series
        assert ts.names() == ["model/loss", "step_time_us"]
    finally:
        server.stop_server()
        telemetry.reset()
        monkeypatch.delenv("MXNET_TELEMETRY", raising=False)
        telemetry.refresh_from_env()
