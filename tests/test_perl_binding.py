"""Perl binding (perl-package/ AI::MXNetTPU) — the reference's
AI-MXNet perl-package analogue, an XS module over the general C ABI.

Builds the XS extension with the in-image toolchain and runs the Perl
test suite end-to-end (NDArray math, imperative invoke, symbol load ->
bind -> checkpoint load -> forward). Opens VERDICT r4 Missing #6
(non-Python bindings), previously the one consciously deferred layer.
"""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "perl-package")
SO = os.path.join(REPO, "mxnet_tpu", "_native", "libmxnet_c.so")


def _perl_ready():
    if not os.path.exists(SO) or shutil.which("perl") is None:
        return False
    probe = subprocess.run(
        ["perl", "-MExtUtils::MakeMaker", "-MTest::More", "-e", "1"],
        capture_output=True)
    return probe.returncode == 0


pytestmark = pytest.mark.skipif(not _perl_ready(),
                                reason="perl/XS toolchain unavailable")


@pytest.fixture(scope="module")
def built_pkg(tmp_path_factory):
    """Build the XS module out-of-tree so the repo stays clean."""
    bld = str(tmp_path_factory.mktemp("perlbld"))
    for name in ("MXNetTPU.xs", "Makefile.PL"):
        shutil.copy(os.path.join(PKG, name), bld)
    shutil.copytree(os.path.join(PKG, "lib"), os.path.join(bld, "lib"))
    shutil.copytree(os.path.join(PKG, "t"), os.path.join(bld, "t"))
    # Makefile.PL resolves the repo root relative to ITSELF, which is
    # wrong for this temp copy — the INC=/LIBS= command-line overrides
    # below repoint it (MakeMaker gives CLI args precedence). The baked
    # rpath is still temp-relative; the runner compensates with
    # LD_LIBRARY_PATH.
    subprocess.run(["perl", "Makefile.PL",
                    "INC=-I%s" % os.path.join(REPO, "native", "include"),
                    "LIBS=-L%s -lmxnet_c" % os.path.dirname(SO)],
                   cwd=bld, check=True, capture_output=True)
    subprocess.run(["make"], cwd=bld, check=True, capture_output=True)
    return bld


def test_perl_binding_end_to_end(built_pkg, tmp_path):
    import numpy as np  # noqa: F401
    import mxnet_tpu as mx

    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3,
                                name="fc")
    net = mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                               name="softmax")
    sym_file = str(tmp_path / "net-symbol.json")
    net.save(sym_file)
    param_file = str(tmp_path / "net.params")
    mx.nd.save(param_file, {"arg:fc_weight": mx.nd.ones((3, 4)) * 0.1,
                            "arg:fc_bias": mx.nd.zeros((3,))})

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep +
               os.environ.get("PYTHONPATH", ""),
               # out-of-tree build: the baked rpath points at the temp
               # copy's parent, so resolve libmxnet_c.so explicitly
               LD_LIBRARY_PATH=os.path.dirname(SO) + os.pathsep +
               os.environ.get("LD_LIBRARY_PATH", ""))
    out = subprocess.run(
        ["perl", "-Mblib", os.path.join("t", "basic.t"), sym_file,
         param_file],
        cwd=built_pkg, capture_output=True, text=True, timeout=600,
        env=env)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "ok 8" in out.stdout and "not ok" not in out.stdout, out.stdout
