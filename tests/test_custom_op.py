"""CustomOp API tests (reference tests/python/unittest/test_operator.py
test_custom_op + example/numpy-ops patterns)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


class _Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        y = 1.0 / (1.0 + np.exp(-in_data[0].asnumpy()))
        self.assign(out_data[0], req[0], y)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        g = out_grad[0].asnumpy() * y * (1.0 - y)
        self.assign(in_grad[0], req[0], g)


@mx.operator.register("t_sigmoid")
class _SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, shapes, dtypes):
        return _Sigmoid()


class _NumpySoftmax(mx.operator.CustomOp):
    """The canonical example/numpy-ops/numpy_softmax.py op."""

    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        e = np.exp(x - x.max(axis=1, keepdims=True))
        self.assign(out_data[0], req[0], e / e.sum(axis=1, keepdims=True))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        lbl = in_data[1].asnumpy().ravel().astype(np.int64)
        y = out_data[0].asnumpy().copy()
        y[np.arange(lbl.shape[0]), lbl] -= 1.0
        self.assign(in_grad[0], req[0], y)


@mx.operator.register("t_numpy_softmax")
class _NumpySoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return _NumpySoftmax()


def test_custom_registered():
    assert "t_sigmoid" in mx.operator.get_all_registered_operators()


def test_custom_forward_eager():
    x = nd.array(np.array([[0.0, 1.0], [-1.0, 2.0]], np.float32))
    y = nd.Custom(x, op_type="t_sigmoid")
    expect = 1.0 / (1.0 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(y.asnumpy(), expect, rtol=1e-6)


def test_custom_backward():
    x = nd.array(np.array([[0.5, -0.25]], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = nd.Custom(x, op_type="t_sigmoid")
        loss = y.sum()
    loss.backward()
    s = 1.0 / (1.0 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_custom_softmax_two_inputs():
    data = nd.array(np.random.randn(4, 5).astype(np.float32))
    label = nd.array(np.array([0, 1, 2, 3], np.float32))
    out = nd.Custom(data, label, op_type="t_numpy_softmax")
    got = out.asnumpy()
    np.testing.assert_allclose(got.sum(axis=1), np.ones(4), rtol=1e-5)

    data.attach_grad()
    with mx.autograd.record():
        y = nd.Custom(data, label, op_type="t_numpy_softmax")
    y.backward()
    expect = got.copy()
    expect[np.arange(4), label.asnumpy().astype(np.int64)] -= 1.0
    np.testing.assert_allclose(data.grad.asnumpy(), expect, rtol=1e-5)


def test_custom_in_symbol():
    sym_x = mx.sym.Variable("data")
    sym_y = mx.sym.Custom(sym_x, op_type="t_sigmoid", name="sig")
    exe = sym_y.bind(mx.cpu(), {"data": nd.array(
        np.array([[0.0, 1.0]], np.float32))})
    out = exe.forward()[0]
    expect = 1.0 / (1.0 + np.exp(-np.array([[0.0, 1.0]])))
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-6)


def test_custom_train_small_net():
    """A tiny net with a Custom head trains (the numpy-ops demo's point)."""
    np.random.seed(0)
    w = nd.array(np.random.randn(3, 4).astype(np.float32) * 0.1)
    w.attach_grad()
    data = nd.array(np.random.randn(8, 3).astype(np.float32))
    label = nd.array(np.random.randint(0, 4, (8,)).astype(np.float32))
    first = None
    for _ in range(5):
        with mx.autograd.record():
            logits = nd.dot(data, w)
            prob = nd.Custom(logits, label, op_type="t_numpy_softmax")
        prob.backward()
        idx = label.asnumpy().astype(np.int64)
        loss = -np.log(prob.asnumpy()[np.arange(8), idx] + 1e-9).mean()
        if first is None:
            first = loss
        w -= 0.5 * w.grad
        w.grad[:] = 0
    assert loss < first, (first, loss)
