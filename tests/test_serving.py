"""Serving tier (ISSUE 6 tentpole): AOT bucket programs, continuous
batching, multi-tenant slots, and the /v1 ops surface.

Acceptance contract (ISSUE 6): `/v1/models/<name>/predict` round-trips
through the LIVE introspection server; concurrent clients sustain zero
retraces after warmup, asserted via the retrace-watchdog counters (both
in-process and through ``tools/serve_bench.py``); and the batching edge
cases — timeout flush, oversize straight-through, overload 503, bitwise
equality of padded vs single-shot forward — are pinned here.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
import mxnet_tpu.serving as serving
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.model import save_checkpoint
from mxnet_tpu.predict import Predictor
from mxnet_tpu.serving.batcher import Overloaded
from mxnet_tpu.serving.program import bucket_sizes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FEATURES = 8
CLASSES = 4


def _save_mlp(prefix, epoch=0, seed=0):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="sv_fc1")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="sv_fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    shapes = {"data": (1, FEATURES)}
    arg_shapes, _, _ = net.infer_shape(**shapes)
    host = np.random.RandomState(seed)
    args = {name: mx.nd.array((host.randn(*shape) * 0.2)
                              .astype(np.float32))
            for name, shape in zip(net.list_arguments(), arg_shapes)
            if name not in shapes and not name.endswith("_label")}
    save_checkpoint(prefix, epoch, net, args, {})
    return prefix


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("serving")
    return _save_mlp(str(tmp / "mlp"))


@pytest.fixture
def registry():
    serving.reset_registry()
    yield serving.get_registry()
    serving.reset_registry()


def _load(registry, checkpoint, name="mlp", **kwargs):
    kwargs.setdefault("input_shapes", {"data": (1, FEATURES)})
    kwargs.setdefault("max_batch", 8)
    kwargs.setdefault("epoch", 0)
    return registry.load(name, prefix=checkpoint, **kwargs)


# ---------------------------------------------------------------------------
# bucket policy + program
# ---------------------------------------------------------------------------

def test_bucket_sizes_policy():
    assert bucket_sizes(max_batch=32) == (1, 2, 4, 8, 16, 32)
    assert bucket_sizes(max_batch=12) == (1, 2, 4, 8, 12)
    assert bucket_sizes(max_batch=1) == (1,)
    assert bucket_sizes(buckets=(16, 4, 4)) == (4, 16)


def test_bucketed_padded_matches_single_shot_bitwise(registry, checkpoint):
    """The satellite contract: padding a batch to its bucket changes
    NOTHING about the first n rows — bitwise, not allclose."""
    slot = _load(registry, checkpoint)
    rng = np.random.RandomState(3)
    for n in (1, 2, 3, 5, 8):
        x = rng.randn(n, FEATURES).astype(np.float32)
        got = slot.predict({"data": x})[0]
        ref = Predictor.load(checkpoint, 0, {"data": (n, FEATURES)})
        want = ref.forward(data=x)[0].asnumpy()
        assert got.dtype == want.dtype
        assert np.array_equal(got, want), \
            "bucketed row values drifted at n=%d" % n


def test_aot_warmup_compiles_every_bucket(registry, checkpoint):
    before = telemetry.counter("serving_warmup_compiles")
    slot = _load(registry, checkpoint, name="warm")
    assert slot.program.buckets == (1, 2, 4, 8)
    assert telemetry.counter("serving_warmup_compiles") - before == 4
    costs = slot.program.costs()
    assert set(costs) == {1, 2, 4, 8}


# ---------------------------------------------------------------------------
# batching edge cases
# ---------------------------------------------------------------------------

def test_empty_queue_timeout_flush(registry, checkpoint):
    """A lone below-bucket request must flush at the coalescing deadline
    instead of waiting for rows that never come."""
    slot = _load(registry, checkpoint, timeout_ms=40.0)
    before = telemetry.counter("serving_batches")
    t0 = time.perf_counter()
    out = slot.predict({"data": np.ones((1, FEATURES), np.float32)},
                       timeout=10.0)
    wall = time.perf_counter() - t0
    assert out[0].shape == (1, CLASSES)
    assert telemetry.counter("serving_batches") == before + 1
    # flushed by the deadline (generous bound: deadline + dispatch)
    assert wall < 5.0


def test_oversize_request_takes_straight_through_path(registry,
                                                      checkpoint,
                                                      watchdog_on):
    slot = _load(registry, checkpoint, max_batch=4)
    before = telemetry.counter("serving_straight_through")
    compiles = telemetry.counter("jit_compiles")
    rng = np.random.RandomState(5)
    x = rng.randn(9, FEATURES).astype(np.float32)   # > max bucket 4
    got = slot.predict({"data": x})[0]
    assert telemetry.counter("serving_straight_through") == before + 1
    # the escape hatch is WATCHED: its fresh trace books a compile event
    # (this is also what proves the zero-retrace assertions elsewhere are
    # not vacuous — the detector demonstrably sees this path)
    assert telemetry.counter("jit_compiles") > compiles
    assert got.shape == (9, CLASSES)
    ref = Predictor.load(checkpoint, 0, {"data": (9, FEATURES)})
    assert np.array_equal(got, ref.forward(data=x)[0].asnumpy())


def test_overload_sheds_with_bounded_queue(registry, checkpoint):
    """Queue cap reached -> Overloaded immediately (backpressure), and
    the queued requests still complete once the scheduler drains."""
    slot = _load(registry, checkpoint, name="tiny",
                 queue_cap=2, timeout_ms=2000.0)
    x = np.ones((1, FEATURES), np.float32)
    before = telemetry.counter("serving_overloads")
    r1 = slot.submit({"data": x})
    r2 = slot.submit({"data": x})
    with pytest.raises(Overloaded):
        slot.submit({"data": x})
    assert telemetry.counter("serving_overloads") == before + 1
    assert slot.stats()["overloads"] == 1
    # unload(drain=True) flushes the long coalescing deadline immediately
    registry.unload("tiny")
    assert r1.wait(10.0)[0].shape == (1, CLASSES)
    assert r2.wait(10.0)[0].shape == (1, CLASSES)


def test_batch_occupancy_and_padding_accounting(registry, checkpoint):
    slot = _load(registry, checkpoint, name="occ", timeout_ms=1.0)
    slot.predict({"data": np.ones((3, FEATURES), np.float32)})
    stats = slot.stats()
    # 3 rows into the 4-bucket: 1 padded row, 75% occupancy
    assert stats["rows"] == 3
    assert stats["padded_rows"] == 1
    assert stats["batch_occupancy_mean"] == pytest.approx(0.75)
    assert stats["latency_us"]["count"] == 1


def test_ragged_and_unknown_inputs_rejected(registry, checkpoint):
    slot = _load(registry, checkpoint)
    with pytest.raises(MXNetError, match="missing input"):
        slot.submit({})
    with pytest.raises(MXNetError, match="unknown inputs"):
        slot.predict({"data": np.ones((1, FEATURES), np.float32),
                      "bogus": np.ones((1, 2), np.float32)})
    with pytest.raises(MXNetError, match="shape"):
        slot.predict({"data": np.ones((1, FEATURES + 1), np.float32)})


# ---------------------------------------------------------------------------
# zero retraces after warmup (acceptance)
# ---------------------------------------------------------------------------

@pytest.fixture
def watchdog_on():
    """Compile-event detection requires telemetry (or MXNET_TRACECHECK)
    ON — without it the zero-retrace assertion would pass vacuously."""
    telemetry.set_enabled(True)
    yield
    telemetry.refresh_from_env()


def test_concurrent_clients_zero_retraces_after_warmup(registry,
                                                       checkpoint,
                                                       watchdog_on):
    """The tentpole property: every request-path batch lands on an AOT
    bucket executable; the retrace-watchdog counters must not move under
    concurrent mixed-size load."""
    slot = _load(registry, checkpoint, timeout_ms=2.0)
    # settle: one request through the full path
    slot.predict({"data": np.zeros((2, FEATURES), np.float32)})
    compiles = (telemetry.counter("jit_compiles")
                + telemetry.counter("serving_warmup_compiles"))
    requests_before = telemetry.counter("serving_requests")
    errors = []

    def client(seed):
        rng = np.random.RandomState(seed)
        try:
            for _ in range(12):
                n = int(rng.randint(1, 9))       # all within buckets
                out = slot.predict(
                    {"data": rng.randn(n, FEATURES).astype(np.float32)},
                    timeout=30.0)
                assert out[0].shape == (n, CLASSES)
        except Exception as exc:                  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert telemetry.counter("serving_requests") - requests_before == 72
    after = (telemetry.counter("jit_compiles")
             + telemetry.counter("serving_warmup_compiles"))
    assert after == compiles, \
        "the serving request path traced/compiled something after warmup"


def test_serve_bench_zero_retraces(tmp_path):
    """tools/serve_bench.py end-to-end on CPU (tier-1 acceptance):
    concurrent clients, one JSON line, zero retraces after warmup."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--clients", "3", "--requests", "8", "--qps", "50",
         "--duration", "1"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    report = json.loads(line)
    assert report["retraces_after_warmup"] == 0
    assert report["closed_loop"]["errors"] == 0
    assert report["closed_loop"]["qps"] > 0
    assert report["open_loop"]["completed"] > 0
    assert 0 < report["mean_batch_occupancy"] <= 1.0


# ---------------------------------------------------------------------------
# multi-tenant slots
# ---------------------------------------------------------------------------

def test_multi_tenant_slots_are_independent(registry, checkpoint,
                                            tmp_path):
    other = _save_mlp(str(tmp_path / "other"), seed=7)
    a = _load(registry, checkpoint, name="a")
    b = _load(registry, other, name="b")
    x = np.ones((2, FEATURES), np.float32)
    ya = a.predict({"data": x})[0]
    yb = b.predict({"data": x})[0]
    assert not np.array_equal(ya, yb)      # different weights
    assert registry.names() == ["a", "b"]
    registry.unload("a")
    assert registry.names() == ["b"]
    with pytest.raises(MXNetError, match="not loaded"):
        registry.predict("a", {"data": x})
    assert np.array_equal(b.predict({"data": x})[0], yb)


def test_reload_swaps_weights_without_unload(registry, checkpoint,
                                             tmp_path):
    prefix = _save_mlp(str(tmp_path / "re"), epoch=0, seed=1)
    slot = _load(registry, prefix, name="re")
    x = np.ones((2, FEATURES), np.float32)
    y0 = slot.predict({"data": x})[0]
    _save_mlp(prefix, epoch=1, seed=42)
    registry.reload("re", epoch=1)
    y1 = slot.predict({"data": x})[0]
    assert not np.array_equal(y0, y1)
    ref = Predictor.load(prefix, 1, {"data": (2, FEATURES)})
    assert np.array_equal(y1, ref.forward(data=x)[0].asnumpy())


def test_duplicate_load_rejected(registry, checkpoint):
    _load(registry, checkpoint)
    with pytest.raises(MXNetError, match="already loaded"):
        _load(registry, checkpoint)


# ---------------------------------------------------------------------------
# /v1 ops surface over the LIVE introspection server (acceptance)
# ---------------------------------------------------------------------------

@pytest.fixture
def live_server(registry):
    from mxnet_tpu.telemetry import server
    srv = server.start_server(port=0, sample_ms=100)
    yield srv
    server.stop_server()


def _http(srv, method, path, obj=None):
    data = json.dumps(obj).encode() if obj is not None else None
    req = urllib.request.Request(
        "http://127.0.0.1:%d%s" % (srv.port, path), data=data,
        method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def test_http_predict_round_trip(registry, checkpoint, live_server):
    slot = _load(registry, checkpoint)
    rng = np.random.RandomState(11)
    x = rng.randn(3, FEATURES).astype(np.float32)
    code, body = _http(live_server, "POST", "/v1/models/mlp/predict",
                       {"inputs": {"data": x.tolist()}})
    assert code == 200
    assert body["model"] == "mlp" and body["batch"] == 3
    got = np.asarray(body["outputs"]["softmax_output"], np.float32)
    want = slot.predict({"data": x})[0]
    assert np.array_equal(got, want)     # JSON round-trip is exact for f32
    assert body["latency_us"] > 0

    code, body = _http(live_server, "GET", "/v1/models")
    assert code == 200
    detail = body["models"]["mlp"]
    assert detail["requests"] >= 2
    assert detail["buckets"] == [1, 2, 4, 8]
    assert "p99" in detail["latency_us"]
    assert detail["queue_depth"] == 0

    code, body = _http(live_server, "GET", "/v1/models/mlp")
    assert code == 200 and "mlp" in body


def test_http_edges_404_400_503(registry, checkpoint, live_server):
    _load(registry, checkpoint, name="edge", queue_cap=1,
          timeout_ms=2000.0)
    x = np.ones((1, FEATURES), np.float32)
    code, body = _http(live_server, "POST", "/v1/models/ghost/predict",
                       {"inputs": {"data": x.tolist()}})
    assert code == 404 and "not loaded" in body["error"]
    code, body = _http(live_server, "POST", "/v1/models/edge/predict",
                       {"inputs": {}})
    assert code == 400
    code, body = _http(live_server, "GET", "/v1/bogus")
    assert code == 404

    # fill the 1-deep queue, then the next HTTP predict must shed 503
    held = serving.submit("edge", {"data": x})
    code, body = _http(live_server, "POST", "/v1/models/edge/predict",
                       {"inputs": {"data": x.tolist()}})
    assert code == 503 and "full" in body["error"]
    serving.get_registry().unload("edge")       # drains `held`
    held.wait(10.0)


def test_http_load_unload_management(registry, checkpoint, live_server):
    code, body = _http(live_server, "POST", "/v1/models/ops/load",
                       {"prefix": checkpoint, "epoch": 0,
                        "input_shapes": {"data": [1, FEATURES]},
                        "max_batch": 4})
    assert code == 200 and body["buckets"] == [1, 2, 4]
    x = np.ones((2, FEATURES), np.float32)
    code, body = _http(live_server, "POST", "/v1/models/ops/predict",
                       {"inputs": {"data": x.tolist()}})
    assert code == 200
    code, body = _http(live_server, "POST", "/v1/models/ops/unload")
    assert code == 200
    code, body = _http(live_server, "GET", "/v1/models/ops")
    assert code == 404


def test_serving_gauges_feed_metrics_endpoint(registry, checkpoint,
                                              live_server):
    from mxnet_tpu.telemetry import server as tserver
    _load(registry, checkpoint, name="g")
    serving.refresh_gauges()
    tserver.sample_once()
    assert telemetry.gauge("serving_models_loaded") == 1
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % live_server.port,
            timeout=10) as resp:
        text = resp.read().decode()
    assert "serving_models_loaded 1" in text
    assert "serving_queue_depth" in text
