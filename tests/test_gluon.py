"""Gluon API contract tests (modeled on reference
tests/python/unittest/test_gluon.py and test_loss.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.gluon import nn


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier")
    assert len(p.list_data()) == 1
    assert len(p.list_grad()) == 1
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)


def test_paramdict():
    params = gluon.ParameterDict("net_")
    params.get("weight", shape=(10, 10))
    assert list(params.keys()) == ["net_weight"]
    params.initialize(ctx=mx.cpu())
    params.save("/tmp/test_paramdict.params")
    params.load("/tmp/test_paramdict.params", mx.cpu())


def test_basic():
    model = nn.Sequential()
    model.add(nn.Dense(128, activation="tanh", in_units=10, flatten=False))
    model.add(nn.Dropout(0.5))
    model.add(nn.Dense(64, activation="tanh", in_units=256))
    model.add(nn.Dense(32, in_units=64))
    model.add(nn.Activation("relu"))

    # symbol-free eager execution
    model.initialize()
    x = mx.nd.zeros((32, 2, 10))
    out = model(x)
    assert out.shape == (32, 32)

    # params of nested blocks collected
    params = model.collect_params()
    assert len(params) == 6  # 3 dense layers x (weight, bias)


def test_dense():
    model = nn.Dense(128, activation="tanh", in_units=10, flatten=False,
                     prefix="test1_")
    inputs = mx.sym.Variable("data")
    outputs = model(inputs)
    assert set(model.collect_params().keys()) == \
        {"test1_weight", "test1_bias"}
    x = mx.nd.array(np.random.rand(17, 2, 10).astype("float32"))
    model.initialize()
    assert model(x).shape == (17, 2, 128)

    model2 = nn.Dense(128, activation="relu", in_units=30, flatten=True,
                      prefix="test2_")
    model2.initialize()
    x = mx.nd.array(np.random.rand(17, 2, 15).astype("float32"))
    assert model2(x).shape == (17, 128)


def test_dense_deferred_init():
    model = nn.Dense(8)
    model.initialize()
    x = mx.nd.ones((4, 3))
    out = model(x)
    assert out.shape == (4, 8)
    assert model.weight.shape == (8, 3)


@pytest.mark.parametrize("hybridize", [False, True])
def test_conv_pool_net(hybridize):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, kernel_size=3, padding=1, activation="relu"))
        net.add(nn.MaxPool2D(2, 2))
        net.add(nn.Conv2D(16, kernel_size=3, padding=1))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.GlobalAvgPool2D())
        net.add(nn.Flatten())
        net.add(nn.Dense(10))
    net.initialize()
    if hybridize:
        net.hybridize()
    x = mx.nd.array(np.random.randn(2, 3, 16, 16).astype("float32"))
    out = net(x)
    assert out.shape == (2, 10)


def test_hybrid_eager_consistency():
    def make():
        net = nn.HybridSequential(prefix="c_")
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"))
            net.add(nn.Dense(4))
        return net
    net = make()
    net.initialize()
    x = mx.nd.array(np.random.randn(3, 7).astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-6)


def test_hybrid_grad_consistency():
    x = mx.nd.array(np.random.randn(4, 5).astype("float32"))
    y = mx.nd.array(np.random.randn(4, 2).astype("float32"))
    loss_fn = gluon.loss.L2Loss()

    grads = []
    for hyb in (False, True):
        net = nn.HybridSequential(prefix="g_")
        with net.name_scope():
            net.add(nn.Dense(8, activation="tanh", in_units=5))
            net.add(nn.Dense(2, in_units=8))
        net.initialize(mx.init.Constant(0.1))
        if hyb:
            net.hybridize()
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        grads.append({k: p.grad().asnumpy()
                      for k, p in net.collect_params().items()})
    for k in grads[0]:
        np.testing.assert_allclose(grads[0][k], grads[1][k],
                                   rtol=1e-4, atol=1e-5)


def test_batchnorm_running_stats():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    x = mx.nd.array(np.random.randn(4, 3, 5, 5).astype("float32"))
    with autograd.record():
        bn(x)
    assert abs(bn.running_mean.data().asnumpy()).sum() > 0
    # inference mode must use (not update) running stats
    rm = bn.running_mean.data().asnumpy().copy()
    bn(x)
    np.testing.assert_allclose(bn.running_mean.data().asnumpy(), rm)


def test_trainer_step_converges():
    # tiny linear regression must converge (reference train-test doctrine)
    np.random.seed(0)
    w_true = np.array([[2.0, -3.4]], dtype=np.float32)
    b_true = 4.2
    X = np.random.randn(200, 2).astype(np.float32)
    Y = X.dot(w_true.T) + b_true

    net = nn.Dense(1)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.L2Loss()
    for _ in range(100):
        with autograd.record():
            loss = loss_fn(net(mx.nd.array(X)), mx.nd.array(Y))
        loss.backward()
        trainer.step(X.shape[0])
    w = net.weight.data().asnumpy()
    b = net.bias.data().asnumpy()
    np.testing.assert_allclose(w, w_true, atol=1e-1)
    np.testing.assert_allclose(b, [b_true], atol=1e-1)


def test_losses():
    pred = mx.nd.array(np.random.randn(4, 5).astype("float32"))
    label = mx.nd.array(np.random.randn(4, 5).astype("float32"))
    l1 = gluon.loss.L1Loss()(pred, label).asnumpy()
    np.testing.assert_allclose(
        l1, np.abs(pred.asnumpy() - label.asnumpy()).mean(axis=1),
        rtol=1e-5)
    l2 = gluon.loss.L2Loss()(pred, label).asnumpy()
    np.testing.assert_allclose(
        l2, 0.5 * ((pred.asnumpy() - label.asnumpy()) ** 2).mean(axis=1),
        rtol=1e-5)
    cls = mx.nd.array(np.array([1, 0, 2, 4], dtype=np.float32))
    sce = gluon.loss.SoftmaxCrossEntropyLoss()(pred, cls).asnumpy()
    p = pred.asnumpy()
    logp = p - p.max(1, keepdims=True)
    logp = logp - np.log(np.exp(logp).sum(1, keepdims=True))
    expected = -logp[np.arange(4), cls.asnumpy().astype(int)]
    np.testing.assert_allclose(sce, expected, rtol=1e-4)


def test_bce_loss():
    pred = mx.nd.array(np.random.randn(4, 3).astype("float32"))
    label = mx.nd.array((np.random.rand(4, 3) > 0.5).astype("float32"))
    loss = gluon.loss.SigmoidBinaryCrossEntropyLoss()(pred, label).asnumpy()
    p = pred.asnumpy()
    l = label.asnumpy()
    expected = (np.maximum(p, 0) - p * l +
                np.log1p(np.exp(-np.abs(p)))).mean(axis=1)
    np.testing.assert_allclose(loss, expected, rtol=1e-4, atol=1e-5)


def test_ctc_loss():
    # uniform activations over alphabet of 5 (+blank at 0), T=10
    T, N, C = 10, 2, 6
    pred = mx.nd.zeros((N, T, C))
    label = mx.nd.array(np.array([[1, 2, 0, 0], [1, 2, 3, 0]],
                                 dtype=np.float32))
    loss = gluon.loss.CTCLoss(layout="NTC")(pred, label)
    assert loss.shape == (N,)
    out = loss.asnumpy()
    assert np.all(np.isfinite(out)) and np.all(out > 0)


def test_rnn_cells_and_layers():
    # fused LSTM vs manual cell unroll consistency
    np.random.seed(0)
    T, N, C, H = 4, 2, 3, 5
    x = mx.nd.array(np.random.randn(T, N, C).astype("float32"))

    lstm = gluon.rnn.LSTM(H, input_size=C)
    lstm.initialize(mx.init.Xavier())
    out = lstm(x)
    assert out.shape == (T, N, H)

    cell = gluon.rnn.LSTMCell(H, input_size=C,
                              params=None, prefix="c_")
    # copy fused weights into the cell
    cell.initialize()
    cell.i2h_weight.set_data(lstm.l0_i2h_weight.data())
    cell.h2h_weight.set_data(lstm.l0_h2h_weight.data())
    cell.i2h_bias.set_data(lstm.l0_i2h_bias.data())
    cell.h2h_bias.set_data(lstm.l0_h2h_bias.data())
    xs = mx.nd.swapaxes(x, 0, 1)  # NTC
    outs, _ = cell.unroll(T, xs, layout="NTC")
    manual = np.stack([o.asnumpy() for o in outs], axis=0)
    np.testing.assert_allclose(manual, out.asnumpy(), rtol=1e-4,
                               atol=1e-5)


def test_gru_rnn_layers():
    x = mx.nd.array(np.random.randn(4, 2, 3).astype("float32"))
    for layer, h in ((gluon.rnn.GRU(5), 5),
                     (gluon.rnn.RNN(5, activation="tanh"), 5)):
        layer.initialize()
        assert layer(x).shape == (4, 2, h)
    bi = gluon.rnn.LSTM(5, num_layers=2, bidirectional=True)
    bi.initialize()
    out, states = bi(x, bi.begin_state(2))
    assert out.shape == (4, 2, 10)
    assert states[0].shape == (4, 2, 5)


def test_block_save_load_params():
    net = nn.HybridSequential(prefix="sl_")
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3))
    net.initialize()
    net.save_params("/tmp/test_block.params")

    net2 = nn.HybridSequential(prefix="sl_")
    with net2.name_scope():
        net2.add(nn.Dense(4, in_units=3))
    net2.load_params("/tmp/test_block.params")
    x = mx.nd.ones((2, 3))
    np.testing.assert_allclose(net(x).asnumpy(), net2(x).asnumpy())


def test_data_api():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    X = np.random.randn(11, 3).astype("float32")
    Y = np.arange(11).astype("float32")
    ds = ArrayDataset(X, Y)
    assert len(ds) == 11
    dl = DataLoader(ds, batch_size=4, last_batch="keep")
    batches = list(dl)
    assert len(batches) == 3
    assert batches[0][0].shape == (4, 3)
    assert batches[2][0].shape == (3, 3)
    dl = DataLoader(ds, batch_size=4, shuffle=True, last_batch="discard")
    assert len(list(dl)) == 2
    # threaded prefetch path
    dl = DataLoader(ds, batch_size=4, num_workers=2)
    assert len(list(dl)) == 3


def test_vision_datasets():
    from mxnet_tpu.gluon.data.vision import MNIST, CIFAR10
    m = MNIST(root="/tmp/mxtpu_mnist")
    assert m[0][0].shape == (28, 28, 1)
    c = CIFAR10(root="/tmp/mxtpu_cifar")
    assert c[0][0].shape == (32, 32, 3)


def test_model_zoo_smoke():
    from mxnet_tpu.gluon.model_zoo import get_model
    x = mx.nd.array(np.random.randn(1, 3, 32, 32).astype("float32"))
    net = get_model("resnet18_v1", classes=10, thumbnail=True)
    net.initialize()
    assert net(x).shape == (1, 10)
    net = get_model("resnet18_v2", classes=10, thumbnail=True)
    net.initialize()
    assert net(x).shape == (1, 10)


def test_split_and_load():
    from mxnet_tpu.gluon.utils import split_data, clip_global_norm
    x = mx.nd.array(np.random.randn(8, 3).astype("float32"))
    slices = split_data(x, 4)
    assert len(slices) == 4 and slices[0].shape == (2, 3)
    arrs = [mx.nd.ones((2, 2)) * 10 for _ in range(2)]
    norm = clip_global_norm(arrs, 1.0)
    assert norm > 1.0
    total = sum((a.asnumpy() ** 2).sum() for a in arrs)
    np.testing.assert_allclose(np.sqrt(total), 1.0, rtol=1e-4)
