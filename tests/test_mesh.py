"""The sharding substrate itself (parallel/mesh.py, PR 16).

Topology construction (single device, N local devices, faked multi-host),
the MXNET_MESH_* env selection, spec/sharding round-trips, the
version-adaptive shard_map entry point, and the bitwise port gate: the
transformer train steps built through the substrate must match a plain
``jax.jit`` of the same math exactly — porting onto the substrate is a
refactor, not a numerics change.  Also enforces the single-substrate
rule: no module outside parallel/mesh.py touches jax's shard_map surface
directly.
"""
import os
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from mxnet_tpu.parallel import mesh as mesh_mod


# ---------------------------------------------------------------------------
# topology construction
# ---------------------------------------------------------------------------

def test_topology_report():
    topo = mesh_mod.topology()
    assert topo["n_devices"] == len(jax.devices())
    assert topo["n_local_devices"] == len(jax.local_devices())
    assert topo["n_hosts"] == jax.process_count()
    assert topo["process_index"] == jax.process_index()
    assert topo["platform"] == "cpu"


def test_make_mesh_single_device():
    mesh = mesh_mod.make_mesh({"data": -1}, devices=jax.devices()[:1])
    assert dict(mesh.shape) == {"data": 1}


def test_make_mesh_infers_minus_one():
    n = len(jax.devices())
    mesh = mesh_mod.make_mesh({"data": -1, "model": 2})
    assert dict(mesh.shape) == {"data": n // 2, "model": 2}
    with pytest.raises(ValueError):
        mesh_mod.make_mesh({"data": -1, "model": 3})   # 8 % 3 != 0


def test_auto_mesh_balances_local_devices():
    mesh = mesh_mod.auto_mesh(("data", "model"))
    shape = dict(mesh.shape)
    assert shape["data"] * shape["model"] == len(jax.devices())
    assert shape["data"] >= shape["model"]             # largest-first


def test_multihost_mesh_faked_fleet():
    # one process, 4 virtual hosts over the 8 tier-1 CPU devices: the
    # injectable devices/n_hosts make the dist_ps topology testable here
    mesh = mesh_mod.multihost_mesh({"data": -1}, devices=jax.devices(),
                                   n_hosts=4)
    assert mesh.axis_names == ("host", "data")
    assert dict(mesh.shape) == {"host": 4,
                                "data": len(jax.devices()) // 4}


def test_multihost_mesh_rejects_uneven_fleet():
    with pytest.raises(ValueError):
        mesh_mod.multihost_mesh({"data": -1}, devices=jax.devices(),
                                n_hosts=3)
    with pytest.raises(ValueError):
        mesh_mod.multihost_mesh({"host": 2}, devices=jax.devices(),
                                n_hosts=2)             # axis-name collision


def test_multihost_mesh_live_fleet_is_single_host():
    # no injection: the live jax.distributed view (1 process under tier-1)
    mesh = mesh_mod.multihost_mesh()
    assert dict(mesh.shape) == {"host": 1, "data": len(jax.devices())}


# ---------------------------------------------------------------------------
# MXNET_MESH_* env selection
# ---------------------------------------------------------------------------

@pytest.fixture
def _mesh_env(monkeypatch):
    yield monkeypatch
    # monkeypatch restored the env; re-sync the import-time cache
    mesh_mod.refresh_from_env()


def test_mesh_from_env_unset_is_none(_mesh_env):
    _mesh_env.delenv("MXNET_MESH_SHAPE", raising=False)
    mesh_mod.refresh_from_env()
    assert mesh_mod.mesh_from_env() is None


def test_mesh_from_env_shape(_mesh_env):
    _mesh_env.setenv("MXNET_MESH_SHAPE", "data=-1,model=2")
    _mesh_env.setenv("MXNET_MESH_SPAN_HOSTS", "0")
    mesh_mod.refresh_from_env()
    mesh = mesh_mod.mesh_from_env()
    assert dict(mesh.shape) == {"data": len(jax.devices()) // 2,
                                "model": 2}


def test_mesh_from_env_span_hosts(_mesh_env):
    _mesh_env.setenv("MXNET_MESH_SHAPE", "data=-1")
    _mesh_env.setenv("MXNET_MESH_SPAN_HOSTS", "1")
    mesh_mod.refresh_from_env()
    mesh = mesh_mod.mesh_from_env()
    assert mesh.axis_names == ("host", "data")
    assert mesh.shape["host"] == jax.process_count()


def test_mesh_from_env_rejects_garbage(_mesh_env):
    _mesh_env.setenv("MXNET_MESH_SHAPE", "data:4")
    with pytest.raises(ValueError):
        mesh_mod.refresh_from_env()
    _mesh_env.setenv("MXNET_MESH_SHAPE", "data=-1")
    mesh_mod.refresh_from_env()    # leave the cache in a valid state


def test_default_mesh_precedence(_mesh_env):
    _mesh_env.setenv("MXNET_MESH_SHAPE", "data=2")
    mesh_mod.refresh_from_env()
    scoped = mesh_mod.auto_mesh(("data", "model"))
    with mesh_mod.using_mesh(scoped):
        assert mesh_mod.default_mesh() is scoped       # scope beats env
    assert dict(mesh_mod.default_mesh().shape) == {"data": 2}
    _mesh_env.delenv("MXNET_MESH_SHAPE")
    mesh_mod.refresh_from_env()
    auto = mesh_mod.default_mesh(("data",))            # fallback: all devices
    assert dict(auto.shape) == {"data": len(jax.devices())}


# ---------------------------------------------------------------------------
# spec / sharding round-trips
# ---------------------------------------------------------------------------

def test_filter_spec_drops_absent_axes():
    mesh = mesh_mod.make_mesh({"data": -1})
    assert (mesh_mod.filter_spec(P("data", "model", "seq"), mesh)
            == P("data", None, None))
    assert mesh_mod.filter_spec(P("model"), mesh) == P(None)
    assert mesh_mod.filter_spec(P("data"), None) == P("data")


def test_named_sharding_and_shard_put_round_trip():
    mesh = mesh_mod.auto_mesh(("data", "model"))
    host = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    sharding = mesh_mod.named_sharding(mesh, P("data", "seq"))
    arr = mesh_mod.shard_put(host, sharding)
    assert arr.sharding.spec == P("data", None)        # 'seq' filtered out
    np.testing.assert_array_equal(np.asarray(arr), host)
    # Mesh + spec spelling, and the replicated helper
    arr2 = mesh_mod.shard_put(host, mesh, spec=P("data", None))
    assert arr2.sharding.spec == P("data", None)
    rep = mesh_mod.shard_put(host, mesh_mod.replicated(mesh))
    assert rep.sharding.spec == P()
    np.testing.assert_array_equal(np.asarray(rep), host)


# ---------------------------------------------------------------------------
# the shard_map entry point
# ---------------------------------------------------------------------------

def test_shard_map_psum():
    mesh = mesh_mod.make_mesh({"data": -1})
    n = mesh.shape["data"]
    x = np.arange(4 * n, dtype=np.float32).reshape(4 * n)

    fn = mesh_mod.shard_map(
        lambda a: lax.psum(jnp.sum(a), "data") * jnp.ones_like(a),
        mesh=mesh, in_specs=(P("data"),), out_specs=P("data"),
        check=False)
    out = np.asarray(fn(x))
    np.testing.assert_allclose(out, np.full_like(x, x.sum()))


def test_shard_map_uses_scope_mesh():
    mesh = mesh_mod.make_mesh({"data": -1})
    with mesh_mod.using_mesh(mesh):
        fn = mesh_mod.shard_map(lambda a: a * 2.0,
                                in_specs=(P("data"),),
                                out_specs=P("data"), check=False)
    x = np.ones(len(jax.devices()), np.float32)
    np.testing.assert_array_equal(np.asarray(fn(x)), x * 2.0)
    with pytest.raises(ValueError):
        mesh_mod.shard_map(lambda a: a, in_specs=(P("data"),),
                           out_specs=P("data"))        # no mesh anywhere


def test_no_shard_map_outside_the_substrate():
    """The single-substrate rule (ISSUE 16 acceptance): parallel/mesh.py
    is the only module that touches jax's shard_map surface."""
    root = os.path.join(os.path.dirname(__file__), os.pardir)
    offenders = []
    pat = re.compile(
        r"jax\.shard_map|jax\.experimental\.shard_map"
        r"|from\s+jax\.experimental\.shard_map|from\s+jax\s+import\s+"
        r"[^\n]*\bshard_map\b")
    for base in ("mxnet_tpu", "tools"):
        for dirpath, _, files in os.walk(os.path.join(root, base)):
            for fname in files:
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                if path.endswith(os.path.join("parallel", "mesh.py")):
                    continue
                # the analyzer (JG008) names the forbidden spellings in
                # its matcher strings — mentions, not uses
                if os.sep + "lint" + os.sep in path:
                    continue
                with open(path) as f:
                    if pat.search(f.read()):
                        offenders.append(os.path.relpath(path, root))
    assert not offenders, (
        "direct jax shard_map use outside parallel/mesh.py: %s"
        % sorted(offenders))


def test_single_substrate_rule_is_a_lint_rule():
    """ISSUE 18 satellite: the grep above is promoted to graftlint JG008
    — the rule must fire on the exact spellings the regex hunts, so the
    invariant is enforced at lint time (pre-commit, --diff) too, not
    only when this test file runs."""
    from mxnet_tpu.lint import lint_source
    bad = "from jax.experimental.shard_map import shard_map\n"
    assert [f.rule for f in lint_source(bad, path="mxnet_tpu/foo.py",
                                        select={"JG008"})] == ["JG008"]
    # and the substrate module itself stays exempt
    assert lint_source(bad, path="mxnet_tpu/parallel/mesh.py",
                       select={"JG008"}) == []


# ---------------------------------------------------------------------------
# the bitwise port gate: substrate-built programs == plain jax.jit
# ---------------------------------------------------------------------------

def _tiny_lm(mesh):
    from mxnet_tpu.models.transformer import (
        TransformerLMConfig, init_transformer_params, place_batch)
    dp = mesh.shape.get("data", 1)
    sp = mesh.shape.get("seq", 1)
    tp = mesh.shape.get("model", 1)
    cfg = TransformerLMConfig(vocab=32, d_model=8 * max(tp, 1),
                              n_heads=max(tp, 2), d_ff=16 * max(tp, 1),
                              n_layers=1, max_len=8 * max(sp, 1))
    params = init_transformer_params(jax.random.PRNGKey(0), cfg, mesh)
    rng = np.random.RandomState(0)
    b, s = 2 * dp, 8 * sp
    tokens = rng.randint(0, cfg.vocab, (b, s)).astype(np.int32)
    labels = rng.randint(0, cfg.vocab, (b, s)).astype(np.int32)
    tokens, labels = place_batch(tokens, labels, mesh)
    return cfg, params, tokens, labels


def test_transformer_step_bitwise_matches_plain_jit():
    from mxnet_tpu.models import transformer as tfm
    mesh = mesh_mod.auto_mesh(("data", "seq", "model"))
    cfg, params, tokens, labels = _tiny_lm(mesh)

    # the pre-port spelling: plain jax.jit around the identical math
    # (no watch_jit, no substrate) — the port must not change a bit
    loss_of = tfm._lm_loss_fn(cfg, mesh, "seq")

    def raw_step(ps, tk, lb):
        loss, grads = jax.value_and_grad(loss_of)(ps, tk, lb)
        new = jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g.astype(p.dtype), ps, grads)
        return new, loss

    ref_params, ref_loss = jax.jit(raw_step)(params, tokens, labels)
    jax.block_until_ready(ref_loss)

    step = tfm.make_train_step(cfg, mesh, lr=0.1)      # donates params
    new_params, loss = step(params, tokens, labels)
    assert np.asarray(loss).tobytes() == np.asarray(ref_loss).tobytes()
    for name in ref_params:
        assert (np.asarray(new_params[name]).tobytes()
                == np.asarray(ref_params[name]).tobytes()), name


def test_transformer_zero1_step_bitwise_matches_plain_jit():
    from mxnet_tpu.models import transformer as tfm
    from mxnet_tpu.parallel.zero import sharded_update, update_sharding
    mesh = mesh_mod.auto_mesh(("data", "seq", "model"))
    cfg, params, tokens, labels = _tiny_lm(mesh)

    loss_of = tfm._lm_loss_fn(cfg, mesh, "seq")
    upd = {n: update_sharding(mesh, p.shape, "data",
                              getattr(p.sharding, "spec", P()))
           for n, p in params.items()}
    pshard = {n: p.sharding for n, p in params.items()}
    momenta = {n: jax.device_put(jnp.zeros_like(p), upd[n] or p.sharding)
               for n, p in params.items()}

    def momentum_sgd(p, g, m, hyper):
        new_m = 0.9 * m + g.astype(m.dtype)
        return p - 0.1 * new_m.astype(p.dtype), new_m

    def raw_step(ps, ms, tk, lb):
        loss, grads = jax.value_and_grad(loss_of)(ps, tk, lb)
        new_p, new_m = {}, {}
        for n in ps:
            new_p[n], new_m[n] = sharded_update(
                momentum_sgd, ps[n], grads[n], ms[n], {}, upd[n],
                pshard[n])
        return new_p, new_m, loss

    ref_p, ref_m, ref_loss = jax.jit(raw_step)(params, momenta, tokens,
                                               labels)
    jax.block_until_ready(ref_loss)

    step, momenta2 = tfm.make_train_step_zero1(cfg, mesh, params, lr=0.1)
    new_p, new_m, loss = step(params, momenta2, tokens, labels)
    assert np.asarray(loss).tobytes() == np.asarray(ref_loss).tobytes()
    for name in ref_p:
        assert (np.asarray(new_p[name]).tobytes()
                == np.asarray(ref_p[name]).tobytes()), name
        assert (np.asarray(new_m[name]).tobytes()
                == np.asarray(ref_m[name]).tobytes()), name
