"""ZeRO-1 sharded weight update in the fused Trainer (ISSUE 11).

Acceptance contract: under ``MXNET_ZERO=1`` the fused step is
bitwise-identical to the replicated fused path AND the
``MXNET_FUSED_TRAINER=0`` per-slot oracle on a 20+-parameter model over
1/2/4 faked replicas, still launches exactly ONE XLA program per step,
keeps the guardian's skip/retry semantics, persists optimizer state
physically sharded 1/N per device (the ``zero_optimizer_bytes_*``
gauges), and checkpoints the sharded state natively — shard files in
the ``reshard.py`` round-robin layout with no device gather, elastic
across a changed shard count.
"""
import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, chaos, gluon, guardian, profiler, telemetry
from mxnet_tpu.checkpoint import CheckpointManager, reshard
from mxnet_tpu.gluon import fused_trainer, nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_env():
    """Every test leaves the zero/fused env and the guardian pristine."""
    yield
    for key in ("MXNET_ZERO", "MXNET_ZERO_SHARDS", "MXNET_FUSED_TRAINER"):
        os.environ.pop(key, None)
    fused_trainer.refresh_from_env()
    g = guardian.current()
    if g is not None:
        guardian.uninstall(g)
    chaos.configure(None)
    from mxnet_tpu.checkpoint import hooks
    m = hooks.active()
    if m is not None:
        hooks.unregister(m)


def _set_mode(fused=True, zero=None):
    os.environ["MXNET_FUSED_TRAINER"] = "1" if fused else "0"
    if zero is None:
        os.environ.pop("MXNET_ZERO", None)
        os.environ.pop("MXNET_ZERO_SHARDS", None)
    else:
        os.environ["MXNET_ZERO"] = "1"
        os.environ["MXNET_ZERO_SHARDS"] = str(zero)
    fused_trainer.refresh_from_env()


def _net(n_layers=12, width=8):
    net = nn.Sequential()
    for _ in range(n_layers - 1):
        net.add(nn.Dense(width, activation="relu"))
    net.add(nn.Dense(4))
    return net


def _state_arrays(trainer):
    out = {}
    for idx, st in trainer._updater.states.items():
        leaves = []

        def _collect(s):
            if s is None:
                leaves.append(None)
            elif isinstance(s, (tuple, list)):
                for x in s:
                    _collect(x)
            else:
                leaves.append(s.asnumpy())

        _collect(st)
        out[idx] = leaves
    return out


def _train(optimizer, fused=True, zero=None, steps=3, n_layers=12,
           width=8, seed=0, kvstore="device"):
    """Seeded mini-run; returns (params, states, trainer, calls/step)."""
    _set_mode(fused=fused, zero=zero)
    try:
        np.random.seed(seed)
        mx.random.seed(seed)
        rng = np.random.RandomState(seed + 1)
        net = _net(n_layers, width)
        net.initialize(init=mx.initializer.Xavier())
        trainer = gluon.Trainer(net.collect_params(), optimizer,
                                {"learning_rate": 0.05}, kvstore=kvstore)
        loss_fn = gluon.loss.L2Loss()
        X = rng.randn(steps, 8, 6).astype(np.float32)
        Y = rng.randn(steps, 8, 4).astype(np.float32)
        calls = 0
        for step in range(steps):
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(X[step])),
                               mx.nd.array(Y[step]))
            loss.backward()
            before = profiler.counter("xla_program_calls")
            trainer.step(8)
            calls = profiler.counter("xla_program_calls") - before
        params = {i: p.data().asnumpy()
                  for i, p in enumerate(net.collect_params().values())}
        return params, _state_arrays(trainer), trainer, calls
    finally:
        _set_mode(fused=True, zero=None)


def _assert_bitwise(a, b, what):
    assert a.keys() == b.keys()
    for k in a:
        fa, fb = a[k], b[k]
        if isinstance(fa, list):
            for i, (x, y) in enumerate(zip(fa, fb)):
                if x is None:
                    assert y is None
                    continue
                np.testing.assert_array_equal(
                    x, y, err_msg="%s[%s][%d]" % (what, k, i))
        else:
            np.testing.assert_array_equal(fa, fb,
                                          err_msg="%s[%s]" % (what, k))


# ---------------------------------------------------------------------------
# the bitwise gate: sharded == replicated fused == per-slot loop
# ---------------------------------------------------------------------------

_REF = {}       # optimizer -> (params, states) of the replicated runs


def _refs(optimizer):
    if optimizer not in _REF:
        fp, fs, _, _ = _train(optimizer, fused=True)
        lp, ls, _, _ = _train(optimizer, fused=False)
        _assert_bitwise(fp, lp, "fused-vs-loop param")
        _assert_bitwise(fs, ls, "fused-vs-loop state")
        _REF[optimizer] = (fp, fs)
    return _REF[optimizer]


@pytest.mark.parametrize("shards", [1, 2, 4])
def test_sharded_matches_replicated_bitwise(shards):
    """20+-param adam model: MXNET_ZERO=1 over 1/2/4 faked replicas is
    bitwise-identical (params AND optimizer state) to the replicated
    fused path, which itself matches the MXNET_FUSED_TRAINER=0 oracle."""
    ref_p, ref_s = _refs("adam")
    zp, zs, trainer, _ = _train("adam", zero=shards)
    assert len(ref_p) >= 20
    _assert_bitwise(zp, ref_p, "param[shards=%d]" % shards)
    _assert_bitwise(zs, ref_s, "state[shards=%d]" % shards)
    assert trainer._zero_plan is not None \
        and trainer._zero_plan.n == shards


def test_sharded_momentum_sgd_bitwise_no_kvstore():
    """The no-kvstore direct-scatter leg, with single-slot-state sgd."""
    fp, fs, _, _ = _train("sgd", fused=True, kvstore=None)
    zp, zs, _, _ = _train("sgd", zero=4, kvstore=None)
    _assert_bitwise(zp, fp, "param")
    _assert_bitwise(zs, fs, "state")


def test_one_program_call_per_step_and_physical_sharding():
    """Steady state under MXNET_ZERO: exactly ONE XLA program per step;
    every dividing state leaf physically holds 1/N per device; the
    memory gauges report the 1/N shrink."""
    import jax
    from jax.sharding import NamedSharding
    zp, zs, trainer, calls = _train("adam", zero=4)
    assert calls == 1, "zero step issued %d program calls" % calls
    assert profiler.counter("trainer_zero_step") > 0
    plan = trainer._zero_plan
    n_sharded = 0
    for st in trainer._updater.states.values():
        for leaf in plan._state_nds(st):
            sh = leaf._data.sharding
            assert isinstance(sh, NamedSharding)
            if any(a is not None for a in sh.spec):
                n_sharded += 1
                shard0 = leaf._data.addressable_shards[0].data
                assert shard0.nbytes * plan.n == leaf._data.nbytes
    assert n_sharded >= 20
    per_dev = telemetry.gauge("zero_optimizer_bytes_per_device")
    total = telemetry.gauge("zero_optimizer_bytes_replicated")
    assert total > 0 and per_dev <= total / 4 * 1.01


def test_guardian_transient_nan_recovers_bitwise_under_zero():
    """The PR-9 contract under MXNET_ZERO=1: a chaos NaN at step 2 skips
    exactly once in-program, the retry recovers, and the final params
    are bitwise-identical to the clean replicated run."""
    rs = np.random.RandomState(1)
    X = rs.randn(8, 8, 6).astype(np.float32)
    Y = rs.randn(8, 8, 4).astype(np.float32)

    def run(zero, guard=None, poison=None, retry=False, steps=5):
        _set_mode(fused=True, zero=zero)
        chaos.configure(poison)
        try:
            mx.random.seed(0)
            np.random.seed(0)
            net = _net(3, 8)
            net.initialize()
            tr = gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 0.05})
            loss_fn = gluon.loss.L2Loss()
            losses, actions = [], []
            for i in range(steps):
                while True:
                    with autograd.record():
                        loss = loss_fn(net(mx.nd.array(X[i])),
                                       mx.nd.array(Y[i]))
                        scaled = guard.scale_loss(loss) if guard else loss
                    scaled.backward()
                    tr.step(8)
                    if guard is not None:
                        actions.append(guard.last_action())
                        if retry and guard.last_action() == "skipped":
                            continue
                    break
                losses.append(float(np.float64(loss.asnumpy().sum())))
            params = {i: p.data().asnumpy()
                      for i, p in enumerate(
                          net.collect_params().values())}
            return losses, params, actions
        finally:
            chaos.configure(None)
            _set_mode(fused=True, zero=None)

    ref_l, ref_p, _ = run(zero=None)
    g = guardian.TrainingGuardian()
    try:
        zl, zp, za = run(zero=4, guard=g,
                         poison="seed=3;grad.bucket:nan@2", retry=True)
    finally:
        g.close()
    assert za.count("skipped") == 1
    assert zl == ref_l
    _assert_bitwise(zp, ref_p, "param")


# ---------------------------------------------------------------------------
# checkpointing: native sharded save, elastic restore
# ---------------------------------------------------------------------------

def _ckpt_run(tmp_path, shards, total_steps, restore_at=None,
              restore_shards=None, subdir="ck"):
    """Adam run under MXNET_ZERO=*shards*; optionally rebuild the world
    at *restore_at* (fresh net/trainer/manager on *restore_shards*
    replicas) and restore from the newest checkpoint."""
    rng = np.random.RandomState(7)
    X = rng.randn(total_steps, 8, 6).astype(np.float32)
    Y = rng.randn(total_steps, 8, 4).astype(np.float32)
    ckdir = str(tmp_path / subdir)

    def fresh(n):
        _set_mode(fused=True, zero=n)
        mx.random.seed(0)
        np.random.seed(0)
        net = _net(3, 8)
        net.initialize(init=mx.initializer.Xavier())
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.05})
        mgr = CheckpointManager(ckdir, trainer=tr)
        return net, tr, mgr

    net, tr, mgr = fresh(shards)
    loss_fn = gluon.loss.L2Loss()
    try:
        for step in range(total_steps):
            if restore_at is not None and step == restore_at:
                mgr.close()
                net, tr, mgr = fresh(restore_shards)
                restored = mgr.restore()
                assert restored == restore_at
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(X[step])),
                               mx.nd.array(Y[step]))
            loss.backward()
            tr.step(8)
            save_at = (restore_at - 1) if restore_at is not None \
                else total_steps // 2
            if step == save_at:
                assert mgr.save(sync=True)
        params = {i: p.data().asnumpy()
                  for i, p in enumerate(net.collect_params().values())}
        return params, _state_arrays(tr), mgr
    finally:
        mgr.close()
        _set_mode(fused=True, zero=None)


def test_checkpoint_sharded_native_no_gather(tmp_path):
    """Saving under MXNET_ZERO launches no XLA program (each replica's
    slots stream host-side), the manifest shard count tracks the zero
    plan, and every shard file holds exactly its round-robin slots."""
    _set_mode(fused=True, zero=4)
    mx.random.seed(0)
    np.random.seed(0)
    net = _net(3, 8)
    net.initialize(init=mx.initializer.Xavier())
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.05})
    mgr = CheckpointManager(str(tmp_path / "ck"), trainer=tr)
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(7)
    try:
        for _ in range(2):
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(
                    rng.randn(8, 6).astype(np.float32))),
                    mx.nd.array(rng.randn(8, 4).astype(np.float32)))
            loss.backward()
            tr.step(8)
        before = profiler.counter("xla_program_calls")
        assert mgr.save(sync=True)
        assert profiler.counter("xla_program_calls") == before, \
            "sharded checkpoint save launched an XLA program (gather?)"
        ckpts = [d for d in os.listdir(str(tmp_path / "ck"))
                 if d.startswith("ckpt-")]
        assert len(ckpts) == 1
        ckdir = str(tmp_path / "ck" / ckpts[0])
        with open(os.path.join(ckdir, "manifest.json")) as fh:
            manifest = json.load(fh)
        assert manifest["n_shards"] == 4
        slot_ids = sorted(tr._updater.states)
        expect = reshard.assign_slots(slot_ids, 4)
        for k in range(4):
            with open(os.path.join(
                    ckdir, "optim-%05d-of-%05d.pkl" % (k, 4)), "rb") as fh:
                payload = pickle.load(fh)
            assert sorted(payload) == expect[k], \
                "shard %d holds %s, round-robin expects %s" \
                % (k, sorted(payload), expect[k])
    finally:
        mgr.close()
        _set_mode(fused=True, zero=None)


def test_checkpoint_restore_across_changed_shard_count(tmp_path):
    """Save on 4 replicas, restore onto 2: the restore re-buckets and
    the continued trajectory is bitwise-identical to the uninterrupted
    4-replica run (which is itself bitwise == replicated)."""
    ref_p, ref_s, _ = _ckpt_run(tmp_path, shards=4, total_steps=5,
                                subdir="ref")
    got_p, got_s, _ = _ckpt_run(tmp_path, shards=4, total_steps=5,
                                restore_at=3, restore_shards=2,
                                subdir="elastic")
    _assert_bitwise(got_p, ref_p, "param")
    _assert_bitwise(got_s, ref_s, "state")


def test_save_load_states_roundtrip_under_zero(tmp_path):
    """Trainer.save_states serializes the (sharded) state via the host;
    a fresh trainer load_states + re-placement continues bitwise."""
    _set_mode(fused=True, zero=2)
    try:
        np.random.seed(0)
        mx.random.seed(0)
        rng = np.random.RandomState(3)
        X = rng.randn(4, 8, 6).astype(np.float32)
        Y = rng.randn(4, 8, 4).astype(np.float32)

        def fresh():
            net = _net(3, 8)
            net.initialize(init=mx.initializer.Xavier())
            tr = gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 0.05})
            return net, tr

        def run(reload_at=None):
            mx.random.seed(0)
            np.random.seed(0)
            net, tr = fresh()
            loss_fn = gluon.loss.L2Loss()
            for step in range(4):
                if reload_at is not None and step == reload_at:
                    f = str(tmp_path / "tr.states")
                    tr.save_states(f)
                    weights = [p.data().asnumpy()
                               for p in net.collect_params().values()]
                    net, tr = fresh()
                    for p, w in zip(net.collect_params().values(),
                                    weights):
                        p.set_data(mx.nd.array(w))
                    tr.load_states(f)
                with autograd.record():
                    loss = loss_fn(net(mx.nd.array(X[step])),
                                   mx.nd.array(Y[step]))
                loss.backward()
                tr.step(8)
            return {i: p.data().asnumpy() for i, p in
                    enumerate(net.collect_params().values())}

        ref = run()
        got = run(reload_at=2)
        _assert_bitwise(got, ref, "param")
    finally:
        _set_mode(fused=True, zero=None)


# ---------------------------------------------------------------------------
# kvstore collectives + mode plumbing
# ---------------------------------------------------------------------------

def test_kvstore_reduce_scatter_and_all_gather():
    """reduce_scatter_all reduces bitwise like push_pull_all and places
    each divisible value sharded; all_gather_all materializes it back
    on the context device."""
    import jax
    from jax.sharding import NamedSharding
    from mxnet_tpu import kvstore as kvs
    from mxnet_tpu.gluon.fused_trainer import _ZeroPlan
    plan = _ZeroPlan(4)
    rng = np.random.RandomState(0)
    vals = [rng.randn(8, 4).astype(np.float32) for _ in range(3)]

    kv = kvs.create("device")
    kv2 = kvs.create("device")
    keys = list(range(3))
    for k, v in zip(keys, vals):
        kv.init(k, mx.nd.array(v))
        kv2.init(k, mx.nd.array(v))
    copies = [[mx.nd.array(v), mx.nd.array(v * 0.5)] for v in vals]
    copies2 = [[mx.nd.array(v), mx.nd.array(v * 0.5)] for v in vals]
    expect = kv2.push_pull_all(keys, copies2)
    shardings = plan.grad_shardings([v.shape for v in vals])
    before = profiler.counter("kvstore_reduce_scatter")
    got = kv.reduce_scatter_all(keys, copies, shardings)
    assert profiler.counter("kvstore_reduce_scatter") == before + 1
    for e, g, s in zip(expect, got, shardings):
        np.testing.assert_array_equal(e.asnumpy(), g.asnumpy())
        assert isinstance(g._data.sharding, NamedSharding)
        assert g._data.sharding == s
    gathered = kv.all_gather_all(keys, [[g] for g in got])
    for e, g in zip(expect, gathered):
        np.testing.assert_array_equal(e.asnumpy(), g.asnumpy())
        assert len(g._data.sharding.device_set) == 1


@pytest.mark.parametrize("flip_to_loop", [False, True])
def test_zero_off_is_default_and_flip_off_unplaces(flip_to_loop):
    """MXNET_ZERO unset: no plan is built.  Flipping it off mid-run —
    onto the fused replicated path OR the ``MXNET_FUSED_TRAINER=0``
    eager loop — pulls the state back to the weight's own device and
    zeroes the ``zero_*`` gauges (their '0/absent when replicated'
    contract)."""
    _, _, tr, _ = _train("adam", fused=True)
    assert getattr(tr, "_zero_plan", None) is None

    _set_mode(fused=True, zero=2)
    try:
        np.random.seed(0)
        mx.random.seed(0)
        rng = np.random.RandomState(5)
        net = _net(3, 8)
        net.initialize(init=mx.initializer.Xavier())
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.05})
        loss_fn = gluon.loss.L2Loss()
        for step in range(2):
            if step == 1:
                # flip off mid-run (optionally onto the eager loop,
                # which must de-shard before any per-slot dispatch)
                _set_mode(fused=not flip_to_loop, zero=None)
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(
                    rng.randn(8, 6).astype(np.float32))),
                    mx.nd.array(rng.randn(8, 4).astype(np.float32)))
            loss.backward()
            tr.step(8)
        assert tr._zero_plan is None
        for st in tr._updater.states.values():
            if st is None:
                continue
            leaves = st if isinstance(st, tuple) else (st,)
            for leaf in leaves:
                assert len(leaf._data.sharding.device_set) == 1
        assert telemetry.gauge("zero_shards") == 0
        assert telemetry.gauge("zero_optimizer_bytes_per_device") == 0
    finally:
        _set_mode(fused=True, zero=None)


def test_zero_bench_fast_subprocess():
    """tools/zero_bench.py --fast: the tier-1 acceptance gate — per-
    device optimizer bytes shrink ~1/N, one program per step, exit 0."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "zero_bench.py"),
         "--fast", "--shards", "4", "--steps", "2", "--warmup", "1"],
        capture_output=True, text=True, timeout=240,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert payload["ok"] is True
    assert payload["bytes_ratio"] <= 0.3
    assert payload["sharded"]["program_calls"] == 1
    assert payload["replicated"]["program_calls"] == 1
