"""Pluggable stream opener (mxnet_tpu.stream): the dmlc-Stream parity
hook that lets every save/load/RecordIO path accept scheme URIs
(reference include/mxnet/ndarray.h:340 Save/Load over dmlc::Stream,
dmlc/io.h Stream::Create scheme dispatch; SURVEY §5.4)."""
import io
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, recordio, stream


def test_split_scheme():
    assert stream.split_scheme("s3://bucket/k") == ("s3", "bucket/k")
    assert stream.split_scheme("mem://a/b.params") == ("mem", "a/b.params")
    assert stream.split_scheme("/tmp/x.params") == (None, "/tmp/x.params")
    assert stream.split_scheme("relative.rec") == (None, "relative.rec")
    assert stream.split_scheme("C:/windows/path") == (None, "C:/windows/path")


def test_unknown_scheme_is_loud():
    with pytest.raises(mx.MXNetError, match="register_scheme"):
        stream.open_stream("s3://bucket/key", "rb")


def test_custom_scheme_ndarray_roundtrip():
    """A user-registered fsspec-style opener carries nd.save/load."""
    store = {}

    class _W(io.BytesIO):
        def __init__(self, key):
            super().__init__()
            self._key = key

        def close(self):
            store[self._key] = self.getvalue()
            super().close()

    def opener(uri, mode):
        key = stream.split_scheme(uri)[1]
        if "w" in mode:
            return _W(key)
        return io.BytesIO(store[key])

    stream.register_scheme("fake", opener)
    try:
        w = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
        nd.save("fake://ckpt/model.params", {"w": w})
        assert "ckpt/model.params" in store
        back = nd.load("fake://ckpt/model.params")
        np.testing.assert_array_equal(back["w"].asnumpy(), w.asnumpy())
    finally:
        stream.unregister_scheme("fake")
    with pytest.raises(mx.MXNetError):
        nd.load("fake://ckpt/model.params")


def test_mem_scheme_symbol_and_checkpoint():
    """Built-in mem:// carries the full -symbol.json + .params pair."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    net.save("mem://m-symbol.json")
    loaded = mx.sym.load("mem://m-symbol.json")
    assert loaded.tojson() == net.tojson()
    nd.save("mem://m.params", {"arg:fc_weight": nd.ones((3, 5))})
    got = nd.load("mem://m.params")
    assert got["arg:fc_weight"].shape == (3, 5)


def test_recordio_over_mem_scheme():
    """RecordIO write/read through a scheme URI (bypasses the native
    local-path codec, same byte format)."""
    rec = recordio.MXRecordIO("mem://data/train.rec", "w")
    for i in range(5):
        rec.write(b"payload-%d" % i)
    rec.close()
    rd = recordio.MXRecordIO("mem://data/train.rec", "r")
    got = []
    while True:
        item = rd.read()
        if item is None:
            break
        got.append(bytes(item))
    rd.close()
    assert got == [b"payload-%d" % i for i in range(5)]


def test_indexed_recordio_over_mem_scheme():
    w = recordio.MXIndexedRecordIO("mem://data/t.idx", "mem://data/t.rec",
                                   "w")
    for i in range(4):
        w.write_idx(i, b"rec%d" % i)
    w.close()
    r = recordio.MXIndexedRecordIO("mem://data/t.idx", "mem://data/t.rec",
                                   "r")
    assert bytes(r.read_idx(2)) == b"rec2"
    assert bytes(r.read_idx(0)) == b"rec0"
    r.close()


def test_local_paths_unaffected(tmp_path):
    p = os.path.join(str(tmp_path), "x.params")
    nd.save(p, [nd.zeros((2, 2))])
    assert nd.load(p)[0].shape == (2, 2)


def test_recordio_file_scheme_uri(tmp_path):
    """file:// URIs must reach the native codec as plain paths."""
    uri = "file://" + os.path.join(str(tmp_path), "f.rec")
    w = recordio.MXRecordIO(uri, "w")
    w.write(b"abc")
    w.close()
    r = recordio.MXRecordIO(uri, "r")
    assert bytes(r.read()) == b"abc"
    r.close()
