"""Comm/compute overlap (ISSUE 15): bucket-ready gradient reduction
under backward + the chunked device-side collective path.

Contract: with ``MXNET_OVERLAP`` on (default) backward dispatches each
gradient bucket's kvstore reduce as an engine task the moment the
bucket's gradients exist, ``Trainer.step`` drains the in-flight buckets
instead of launching them, and the loss/param trajectory is BITWISE
identical to ``MXNET_OVERLAP=0`` across {fused, fused+zero1,
fused+guardian}.  A dead peer mid-overlap surfaces as a structured
``PeerLost`` within the PR-8 deadline — no hang, params untouched.
``tools/trace_report.py --gate-overlap`` turns the win-condition
``overlap_ratio`` into a CI-checkable exit code.
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, chaos, gluon, profiler
from mxnet_tpu.gluon import fused_trainer, nn, overlap
from mxnet_tpu.parallel import collective

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _set_env(name, value, refresh):
    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = value
    refresh()


@pytest.fixture(autouse=True)
def _clean_overlap_env():
    prev = {k: os.environ.get(k)
            for k in ("MXNET_OVERLAP", "MXNET_ZERO", "MXNET_ZERO_SHARDS",
                      "MXNET_KVSTORE_BUCKET_BYTES",
                      "MXNET_OVERLAP_CHUNK_BYTES")}
    yield
    for k, v in prev.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    overlap.refresh_from_env()
    fused_trainer.refresh_from_env()
    collective.refresh_from_env()
    from mxnet_tpu import kvstore as kvs
    kvs.refresh_from_env()
    chaos.configure(None)


def _net(n_layers=4, width=16, out=3):
    net = nn.Sequential()
    for _ in range(n_layers - 1):
        net.add(nn.Dense(width, activation="relu"))
    net.add(nn.Dense(out))
    return net


def _train(overlap_on, steps=5, optimizer="sgd",
           opt_params=None, seed=0, guard=None, poison=None,
           batch=8):
    """Run a small regression net; returns (params, states, losses)."""
    _set_env("MXNET_OVERLAP", "1" if overlap_on else "0",
             overlap.refresh_from_env)
    chaos.configure(poison)
    np.random.seed(seed)
    mx.random.seed(seed)
    rng = np.random.RandomState(seed + 1)
    net = _net()
    net.initialize(init=mx.initializer.Xavier())
    trainer = gluon.Trainer(
        net.collect_params(), optimizer,
        dict(opt_params or {"learning_rate": 0.05, "momentum": 0.9}),
        kvstore="device")
    loss_fn = gluon.loss.L2Loss()
    X = rng.randn(steps, batch, 6).astype(np.float32)
    Y = rng.randn(steps, batch, 3).astype(np.float32)
    losses = []
    for step in range(steps):
        with autograd.record():
            loss = loss_fn(net(mx.nd.array(X[step])),
                           mx.nd.array(Y[step]))
        if guard is not None:
            guard.observe_loss(loss)
        loss.backward()
        trainer.step(batch)
        losses.append(loss.asnumpy().tobytes())
    overlap.abandon_session(trainer)
    params = {i: p.data().asnumpy()
              for i, p in enumerate(net.collect_params().values())}
    states = {}
    for idx, st in trainer._updater.states.items():
        leaves = []

        def _collect(s):
            if s is None:
                leaves.append(None)
            elif isinstance(s, (tuple, list)):
                for x in s:
                    _collect(x)
            else:
                leaves.append(s.asnumpy())
        _collect(st)
        states[idx] = leaves
    return params, states, losses


def _assert_bitwise(a, b, what):
    assert a.keys() == b.keys()
    for k in a:
        if isinstance(a[k], list):
            for i, (x, y) in enumerate(zip(a[k], b[k])):
                if x is None:
                    assert y is None
                else:
                    np.testing.assert_array_equal(
                        x, y, err_msg="%s[%s][%d]" % (what, k, i))
        else:
            np.testing.assert_array_equal(a[k], b[k],
                                          err_msg="%s[%s]" % (what, k))


# ---------------------------------------------------------------------------
# grad-ready notification order
# ---------------------------------------------------------------------------

def test_grad_ready_hook_fires_during_backward_in_reverse_order():
    """Backward finalizes later layers' gradients FIRST (their last
    consumer sits deepest in the tape), and the hook fires while the
    sweep is still running — the seam overlap dispatch hangs off."""
    order = []
    prev = autograd.set_grad_ready_hook(lambda v: order.append(id(v)))
    try:
        net = _net(n_layers=3, width=8)
        net.initialize(init=mx.initializer.Xavier())
        params = list(net.collect_params().values())
        with autograd.record():
            loss = gluon.loss.L2Loss()(net(mx.nd.array(
                np.random.randn(4, 6).astype(np.float32))),
                mx.nd.array(np.random.randn(4, 3).astype(np.float32)))
        loss.backward()
    finally:
        autograd.set_grad_ready_hook(prev)
    ids = {id(p.data()): i for i, p in enumerate(params)}
    ranked = [ids[x] for x in order if x in ids]
    assert len(ranked) == len(params), "every param grad notified"
    # the FIRST notification comes from the last layer, not the first
    assert ranked[0] >= len(params) - 2, \
        "expected output-layer grads first, got slot order %r" % ranked
    assert ranked[-1] <= 1, \
        "expected input-layer grads last, got slot order %r" % ranked


# ---------------------------------------------------------------------------
# bitwise oracles (the acceptance identity)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
])
def test_overlap_bitwise_vs_oracle(optimizer, opt_params):
    ref = _train(False, optimizer=optimizer, opt_params=opt_params)
    drained0 = profiler.counter("overlap_steps")
    got = _train(True, optimizer=optimizer, opt_params=opt_params)
    assert profiler.counter("overlap_steps") > drained0, \
        "overlap never engaged — the comparison proved nothing"
    assert got[2] == ref[2], "loss trajectory diverged"
    _assert_bitwise(got[0], ref[0], "param")
    _assert_bitwise(got[1], ref[1], "state")


def test_overlap_bitwise_vs_oracle_zero1():
    import jax
    if jax.local_device_count() < 2:
        pytest.skip("needs >1 local device")
    _set_env("MXNET_ZERO", "1", fused_trainer.refresh_from_env)
    _set_env("MXNET_ZERO_SHARDS", "2", fused_trainer.refresh_from_env)
    ref = _train(False)
    drained0 = profiler.counter("overlap_steps")
    got = _train(True)
    assert profiler.counter("overlap_steps") > drained0
    assert got[2] == ref[2]
    _assert_bitwise(got[0], ref[0], "param")
    _assert_bitwise(got[1], ref[1], "state")


def test_overlap_bitwise_vs_oracle_guardian_transient_nan():
    """Guardian + overlap: the poisoned step is skipped on both paths,
    the verdict reads only after every bucket landed, and the
    trajectories stay bitwise identical."""
    from mxnet_tpu import guardian, telemetry
    results = []
    for overlap_on in (False, True):
        before = telemetry.counter("guardian_skipped_steps")
        g = guardian.TrainingGuardian()
        try:
            results.append(_train(overlap_on, guard=g,
                                  poison="grad.bucket:nan@3"))
        finally:
            g.close()
        assert telemetry.counter("guardian_skipped_steps") == before + 1
    ref, got = results
    assert got[2] == ref[2]
    _assert_bitwise(got[0], ref[0], "param")
    _assert_bitwise(got[1], ref[1], "state")


# ---------------------------------------------------------------------------
# the overlap actually overlaps
# ---------------------------------------------------------------------------

def test_buckets_dispatch_under_backward_and_drain():
    d0 = profiler.counter("overlap_bucket_dispatches")
    s0 = profiler.counter("overlap_steps")
    f0 = profiler.counter("overlap_fallbacks")
    steps = 5
    _train(True, steps=steps)
    # session arms at the end of step k for step k+1: steps-1 drains
    assert profiler.counter("overlap_steps") - s0 == steps - 1
    assert profiler.counter("overlap_bucket_dispatches") - d0 >= steps - 1
    assert profiler.counter("overlap_fallbacks") == f0
    stats = overlap.last_step_stats()
    assert stats is not None and stats["buckets"] >= 1
    assert stats["hidden_us"] >= 0.0 and stats["exposed_us"] >= 0.0


def test_rewritten_grad_falls_back_not_wrong():
    """A gradient re-written after its bucket dispatched (double
    backward) dirties the session: the step falls back to the
    synchronous round — counted, and still bitwise-correct."""
    _set_env("MXNET_OVERLAP", "1", overlap.refresh_from_env)
    np.random.seed(0)
    mx.random.seed(0)
    net = _net()
    net.initialize(init=mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore="device")
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(1)
    X = mx.nd.array(rng.randn(4, 6).astype(np.float32))
    Y = mx.nd.array(rng.randn(4, 3).astype(np.float32))
    # step 1 arms the session for step 2
    with autograd.record():
        loss = loss_fn(net(X), Y)
    loss.backward()
    trainer.step(4)
    f0 = profiler.counter("overlap_fallbacks")
    with autograd.record():
        loss = loss_fn(net(X), Y)
    autograd.backward([loss], retain_graph=True)
    autograd.backward([loss])            # re-writes every gradient
    trainer.step(4)
    assert profiler.counter("overlap_fallbacks") == f0 + 1
    for p in net.collect_params().values():
        assert np.isfinite(p.data().asnumpy()).all()
    overlap.abandon_session(trainer)


def test_defused_step_abandons_armed_session():
    """Flipping MXNET_FUSED_TRAINER off mid-run routes the next step
    through the per-slot loop: the armed session must be discarded, not
    half-consumed."""
    _set_env("MXNET_OVERLAP", "1", overlap.refresh_from_env)
    np.random.seed(0)
    mx.random.seed(0)
    net = _net()
    net.initialize(init=mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore="device")
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(1)
    for _ in range(2):
        with autograd.record():
            loss = loss_fn(net(mx.nd.array(
                rng.randn(4, 6).astype(np.float32))),
                mx.nd.array(rng.randn(4, 3).astype(np.float32)))
        loss.backward()
        trainer.step(4)
    assert getattr(trainer, "_overlap_session", None) is not None
    _set_env("MXNET_FUSED_TRAINER", "0", fused_trainer.refresh_from_env)
    try:
        with autograd.record():
            loss = loss_fn(net(mx.nd.array(
                rng.randn(4, 6).astype(np.float32))),
                mx.nd.array(rng.randn(4, 3).astype(np.float32)))
        loss.backward()
        trainer.step(4)
    finally:
        _set_env("MXNET_FUSED_TRAINER", None,
                 fused_trainer.refresh_from_env)
    assert getattr(trainer, "_overlap_session", None) is None


# ---------------------------------------------------------------------------
# dead peer mid-overlap: structured failure within the deadline
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_overlapped_reduce_on_dead_peer_raises_peerlost(monkeypatch):
    """An overlapped bucket push whose server never acks (the dead-peer
    shape, injected as a chaos `drop` of the push frame) must surface a
    structured PeerLost/RPCTimeout from Trainer.step within the PR-8
    deadline — engine task errors re-raise at the drain — with the
    params untouched.  No hang, no half-reduced state."""
    from mxnet_tpu import dist_ps
    port = _free_port()
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_ROLE", "worker")
    monkeypatch.setenv("MXNET_PS_RPC_TIMEOUT_S", "1.0")
    monkeypatch.delenv("DMLC_WORKER_RANK", raising=False)
    dist_ps.refresh_from_env()
    _set_env("MXNET_OVERLAP", "1", overlap.refresh_from_env)
    sched = dist_ps.Scheduler(1, 1, port=port)
    threading.Thread(target=sched.run, daemon=True).start()
    threading.Thread(target=dist_ps.run_server, daemon=True).start()
    kv = mx.kv.KVStoreDist("dist_sync")
    try:
        np.random.seed(0)
        mx.random.seed(0)
        net = _net(n_layers=2, width=8)
        net.initialize(init=mx.initializer.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.05}, kvstore=kv)
        loss_fn = gluon.loss.L2Loss()
        rng = np.random.RandomState(1)

        def one_step():
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(
                    rng.randn(4, 6).astype(np.float32))),
                    mx.nd.array(rng.randn(4, 3).astype(np.float32)))
            loss.backward()
            trainer.step(4)

        one_step()                      # arms the overlap session
        # drop the next push of every bucket key (counting starts at
        # configure): the overlapped push's ack never comes, the PR-8
        # per-RPC deadline fires in-task
        chaos.configure("conn.send.push:drop@1")
        before = {i: p.data().asnumpy()
                  for i, p in enumerate(net.collect_params().values())}
        t0 = time.monotonic()
        with pytest.raises(dist_ps.PeerLost):
            one_step()
        elapsed = time.monotonic() - t0
        assert elapsed < 2 * 1.0 + 2.0, \
            "PeerLost took %.1fs (deadline contract: <= 2x timeout)" \
            % elapsed
        chaos.configure(None)
        after = {i: p.data().asnumpy()
                 for i, p in enumerate(net.collect_params().values())}
        _assert_bitwise(after, before, "params-after-failed-drain")
        overlap.abandon_session(trainer)
    finally:
        chaos.configure(None)
        kv._finalize()


# ---------------------------------------------------------------------------
# the chunked collective module
# ---------------------------------------------------------------------------

def test_chunked_reduce_bitwise_and_padless_tail():
    import jax.numpy as jnp
    from mxnet_tpu.kvstore import _stack_sum
    rng = np.random.RandomState(3)
    n = 10_003                               # uneven vs any chunk size
    flats = [jnp.asarray(rng.randn(n).astype(np.float32))
             for _ in range(3)]
    ref = np.asarray(_stack_sum(flats))
    c0 = profiler.counter("collective_chunk_programs")
    out = np.asarray(collective.chunked_reduce(flats, limit=4096))
    assert profiler.counter("collective_chunk_programs") - c0 > 1
    np.testing.assert_array_equal(out, ref)
    assert out.shape == (n,), "padding leaked past the tail"


def test_chunked_reduce_scatter_uneven_tail_and_gather():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.kvstore import _stack_sum
    rng = np.random.RandomState(4)
    n = 5_001                                # 5001 % 4 != 0
    flats = [jnp.asarray(rng.randn(n).astype(np.float32))
             for _ in range(2)]
    ref = np.asarray(_stack_sum(flats))
    segs = collective.chunked_reduce_scatter(flats, 4, limit=2048)
    assert len(segs) == 4
    assert sum(int(s.shape[0]) for s in segs) == n
    np.testing.assert_array_equal(
        np.asarray(jnp.concatenate(segs)), ref)
    full = collective.chunked_all_gather(segs, device=jax.devices()[0],
                                         limit=2048)
    np.testing.assert_array_equal(np.asarray(full), ref)


def test_redistribution_schedule_every_element_exactly_once():
    for n, nf, nt, ch in [(101, 4, 3, 17), (64, 2, 8, 9), (7, 3, 5, 100)]:
        covered = np.zeros(n, bool)
        for src, dst, lo, hi in collective.redistribution_schedule(
                n, nf, nt, ch):
            assert hi - lo <= ch
            assert not covered[lo:hi].any(), "element moved twice"
            covered[lo:hi] = True
        assert covered.all(), "elements dropped by the schedule"


def test_redistribute_and_gather_home_round_trip():
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    if jax.local_device_count() < 4:
        pytest.skip("needs 4 local devices")
    rng = np.random.RandomState(5)
    mesh = Mesh(np.array(jax.devices()[:4]), ("zero",))
    arr = jax.numpy.asarray(rng.randn(16, 7).astype(np.float32))
    sh = NamedSharding(mesh, P("zero"))
    placed = collective.redistribute(arr, sh, limit=64)
    assert placed.sharding == sh
    np.testing.assert_array_equal(np.asarray(placed), np.asarray(arr))
    home = collective.gather_home(placed, jax.devices()[0], limit=64)
    np.testing.assert_array_equal(np.asarray(home), np.asarray(arr))


# ---------------------------------------------------------------------------
# the CI gate
# ---------------------------------------------------------------------------

def _run_gate(snapshot, threshold):
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        snap = os.path.join(tmp, "snap.json")
        trace = os.path.join(tmp, "trace.json")
        with open(snap, "w") as fh:
            json.dump(snapshot, fh)
        with open(trace, "w") as fh:
            json.dump({"traceEvents": []}, fh)
        return subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "trace_report.py"), trace,
             "--snapshot", snap, "--gate-overlap", str(threshold)],
            capture_output=True, text=True, timeout=120)


def test_gate_overlap_exit_codes():
    tl = [{"wall_us": 100.0, "data_wait_us": 0.0, "host_us": 10.0,
           "device_us": 60.0, "collective_us": 30.0,
           "overlap_ratio": r, "overlap_hidden_us": 30.0 * r,
           "overlap_exposed_us": 30.0 * (1 - r)}
          for r in (0.5, 0.7)]
    snap = {"device": {"enabled": True, "sample_period": 1,
                       "timelines": tl, "last_step": tl[-1],
                       "programs": {}}}
    ok = _run_gate(snap, 0.4)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "gate-overlap: ok" in ok.stdout
    low = _run_gate(snap, 0.9)
    assert low.returncode == 3, low.stdout + low.stderr
    assert "FAIL" in low.stderr
    empty = _run_gate({"device": {"enabled": False, "timelines": [],
                                  "last_step": None, "programs": {}}},
                      0.1)
    assert empty.returncode == 4, \
        "a gate that cannot measure must fail loudly"
