"""Multi-device Module fast path: one SPMD program vs per-device
executor group, with a numerics-equality proof (VERDICT r2 weak #4)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.io import NDArrayIter


def _net():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="tanh")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _train(ctxs, fused, epochs=2):
    import os
    os.environ["MXNET_MODULE_FUSED"] = "1" if fused else "0"
    try:
        np.random.seed(0)
        mx.random.seed(0)
        X = np.random.randn(64, 8).astype(np.float32)
        Y = np.random.randint(0, 4, 64).astype(np.float32)
        it = NDArrayIter(X, Y, batch_size=16)
        mod = mx.mod.Module(_net(), context=ctxs)
        mod.fit(it, num_epoch=epochs, optimizer="sgd",
                initializer=mx.init.Xavier(),
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
        args, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in args.items()}, mod
    finally:
        os.environ.pop("MXNET_MODULE_FUSED", None)


def test_fused_group_selected():
    ctxs = [mx.cpu(0), mx.cpu(1)]
    _, mod = _train(ctxs, fused=True, epochs=1)
    from mxnet_tpu.module.fused_group import FusedExecutorGroup
    assert isinstance(mod._exec_group, FusedExecutorGroup)


def test_fused_matches_executor_group():
    """Trained parameters agree between the fused SPMD path and the
    per-device executor-group path (stateless net, same seed)."""
    ctxs = [mx.cpu(0), mx.cpu(1)]
    fused_params, _ = _train(ctxs, fused=True)
    slow_params, mod = _train(ctxs, fused=False)
    from mxnet_tpu.module.fused_group import FusedExecutorGroup
    assert not isinstance(mod._exec_group, FusedExecutorGroup)
    assert set(fused_params) == set(slow_params)
    for k in fused_params:
        np.testing.assert_allclose(fused_params[k], slow_params[k],
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_fused_single_device_unaffected():
    params, mod = _train(mx.cpu(), fused=True, epochs=1)
    from mxnet_tpu.module.fused_group import FusedExecutorGroup
    assert not isinstance(mod._exec_group, FusedExecutorGroup)
