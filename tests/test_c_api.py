"""General C API end-to-end: a plain C program drives NDArray creation,
imperative op invocation, and save/load through libmxnet_c.so.

Reference analogue: include/mxnet/c_api.h core (MXNDArrayCreateEx /
SyncCopy / MXImperativeInvoke / MXListAllOpNames / MXNDArraySave/Load)
exercised by a host binary that links no Python (SURVEY §2.1 C API row).
"""
import os
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SO = os.path.join(REPO, "mxnet_tpu", "_native", "libmxnet_c.so")

pytestmark = pytest.mark.skipif(not os.path.exists(SO),
                                reason="libmxnet_c.so not built")

DRIVER_C = r"""
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "mxnet_tpu_c.h"

#define CHECK(x) do { if ((x) != 0) { \
  fprintf(stderr, "FAIL %s: %s\n", #x, MXGetLastError()); return 1; } \
} while (0)

int main(int argc, char** argv) {
  /* 2x3 ones + 2x3 twos -> broadcast_add -> sum = 18 */
  mx_uint shape[2] = {2, 3};
  NDArrayHandle a, b;
  CHECK(MXNDArrayCreateEx(shape, 2, 1, 0, 0, 0, &a));
  CHECK(MXNDArrayCreateEx(shape, 2, 1, 0, 0, 0, &b));
  float ones[6] = {1, 1, 1, 1, 1, 1};
  float twos[6] = {2, 2, 2, 2, 2, 2};
  CHECK(MXNDArraySyncCopyFromCPU(a, ones, 6));
  CHECK(MXNDArraySyncCopyFromCPU(b, twos, 6));

  NDArrayHandle ins[2];
  ins[0] = a; ins[1] = b;
  int n_out = 0;
  NDArrayHandle* outs = NULL;
  CHECK(MXImperativeInvoke("broadcast_add", 2, ins, &n_out, &outs,
                           0, NULL, NULL));
  if (n_out != 1) { fprintf(stderr, "n_out=%d\n", n_out); return 1; }

  mx_uint ndim = 0;
  const mx_uint* dims = NULL;
  CHECK(MXNDArrayGetShape(outs[0], &ndim, &dims));
  if (ndim != 2 || dims[0] != 2 || dims[1] != 3) return 1;
  int dtype = -1;
  CHECK(MXNDArrayGetDType(outs[0], &dtype));
  if (dtype != 0) return 1;

  float result[6];
  CHECK(MXNDArraySyncCopyToCPU(outs[0], result, 6));
  float total = 0;
  for (int i = 0; i < 6; ++i) total += result[i];
  if (total != 18.0f) { fprintf(stderr, "sum=%f\n", total); return 1; }

  /* attrs travel stringified: transpose with axes */
  const char* keys[1] = {"axes"};
  const char* vals[1] = {"(1, 0)"};
  int n_t = 0;
  NDArrayHandle* touts = NULL;
  CHECK(MXImperativeInvoke("transpose", 1, &outs[0], &n_t, &touts,
                           1, keys, vals));
  CHECK(MXNDArrayGetShape(touts[0], &ndim, &dims));
  if (ndim != 2 || dims[0] != 3 || dims[1] != 2) return 1;

  /* op registry is reachable */
  mx_uint n_ops = 0;
  const char** op_names = NULL;
  CHECK(MXListAllOpNames(&n_ops, &op_names));
  if (n_ops < 300) { fprintf(stderr, "n_ops=%u\n", n_ops); return 1; }

  /* save -> load roundtrip with names */
  const char* save_keys[1] = {"x"};
  CHECK(MXNDArraySave(argv[1], 1, &outs[0], save_keys));
  mx_uint n_loaded = 0, n_names = 0;
  NDArrayHandle* loaded = NULL;
  const char** names = NULL;
  CHECK(MXNDArrayLoad(argv[1], &n_loaded, &loaded, &n_names, &names));
  if (n_loaded != 1 || n_names != 1 || strcmp(names[0], "x") != 0)
    return 1;
  float back[6];
  CHECK(MXNDArraySyncCopyToCPU(loaded[0], back, 6));
  for (int i = 0; i < 6; ++i)
    if (back[i] != 3.0f) return 1;

  CHECK(MXNDArrayWaitAll());
  MXNDArrayFree(a);
  MXNDArrayFree(b);
  MXNDArrayFree(outs[0]);
  free(outs);
  MXNDArrayFree(touts[0]);
  free(touts);
  MXNDArrayFree(loaded[0]);
  free(loaded);
  printf("C-API-OK\n");
  return 0;
}
"""


def test_c_driver_end_to_end(tmp_path):
    driver = tmp_path / "driver.c"
    driver.write_text(DRIVER_C)
    exe = tmp_path / "driver"
    subprocess.run(
        ["gcc", str(driver), "-I", os.path.join(REPO, "native", "include"),
         "-o", str(exe), str(SO), "-Wl,-rpath," + os.path.dirname(SO)],
        check=True, capture_output=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    out = subprocess.run([str(exe), str(tmp_path / "arrs.params")],
                         capture_output=True, text=True, timeout=300,
                         env=env)
    assert out.returncode == 0, out.stderr
    assert "C-API-OK" in out.stdout


def test_ctypes_in_process_invoke():
    """The same ABI loaded into a live Python process must reuse the
    existing interpreter (GILState path) instead of re-initializing."""
    import ctypes
    import mxnet_tpu  # noqa: F401  (interpreter already has the package)
    lib = ctypes.CDLL(SO)
    lib.MXGetLastError.restype = ctypes.c_char_p
    # declare pointer args: bare ints from POINTER(c_void_p)[i] would
    # otherwise truncate to 32 bits
    lib.MXNDArraySyncCopyFromCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.MXNDArraySyncCopyToCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.MXNDArrayFree.argtypes = [ctypes.c_void_p]
    shape = (ctypes.c_uint * 2)(4, 4)
    h = ctypes.c_void_p()
    rc = lib.MXNDArrayCreateEx(shape, 2, 1, 0, 0, 0, ctypes.byref(h))
    assert rc == 0, lib.MXGetLastError()
    buf = (ctypes.c_float * 16)(*([2.0] * 16))
    assert lib.MXNDArraySyncCopyFromCPU(h, buf, 16) == 0
    n_out = ctypes.c_int(0)
    outs = ctypes.POINTER(ctypes.c_void_p)()
    rc = lib.MXImperativeInvoke(b"sqrt", 1, ctypes.byref(h),
                                ctypes.byref(n_out), ctypes.byref(outs),
                                0, None, None)
    assert rc == 0, lib.MXGetLastError()
    assert n_out.value == 1
    out_buf = (ctypes.c_float * 16)()
    assert lib.MXNDArraySyncCopyToCPU(outs[0], out_buf, 16) == 0
    np.testing.assert_allclose(list(out_buf), [2.0 ** 0.5] * 16,
                               rtol=1e-6)
    lib.MXNDArrayFree(h)
    lib.MXNDArrayFree(outs[0])


DRIVER_CPP = r"""
#include <cstdio>
#include "mxnet_tpu_c.h"

int main() {
  using mxnet_tpu::NDArray;
  NDArray a({2, 3});
  a.CopyFrom({1, 2, 3, 4, 5, 6});
  auto outs = mxnet_tpu::Invoke("transpose", {&a},
                                {{"axes", "(1, 0)"}});
  if (outs.size() != 1) return 1;
  auto shp = outs[0].Shape();
  if (shp.size() != 2 || shp[0] != 3 || shp[1] != 2) return 1;
  auto vals = outs[0].CopyTo();
  float expect[6] = {1, 4, 2, 5, 3, 6};
  for (int i = 0; i < 6; ++i)
    if (vals[i] != expect[i]) return 1;
  std::printf("CPP-API-OK\n");
  return 0;
}
"""


def test_cpp_raii_wrapper(tmp_path):
    driver = tmp_path / "driver.cc"
    driver.write_text(DRIVER_CPP)
    exe = tmp_path / "driver_cpp"
    subprocess.run(
        ["g++", "-std=c++17", str(driver),
         "-I", os.path.join(REPO, "native", "include"),
         "-o", str(exe), str(SO), "-Wl,-rpath," + os.path.dirname(SO)],
        check=True, capture_output=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    out = subprocess.run([str(exe)], capture_output=True, text=True,
                         timeout=300, env=env)
    assert out.returncode == 0, out.stderr
    assert "CPP-API-OK" in out.stdout


def test_imperative_invoke_preallocated_outputs():
    """*num_outputs != 0 on entry means the caller preallocated the output
    handles and the op must write INTO them (reference out-array
    semantics) — r4 advice: they used to be leaked and replaced."""
    import ctypes
    import mxnet_tpu  # noqa: F401
    lib = ctypes.CDLL(SO)
    lib.MXGetLastError.restype = ctypes.c_char_p
    lib.MXNDArraySyncCopyFromCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.MXNDArraySyncCopyToCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.MXNDArrayFree.argtypes = [ctypes.c_void_p]
    shape = (ctypes.c_uint * 1)(4)
    src, dst = ctypes.c_void_p(), ctypes.c_void_p()
    assert lib.MXNDArrayCreateEx(shape, 1, 1, 0, 0, 0,
                                 ctypes.byref(src)) == 0
    assert lib.MXNDArrayCreateEx(shape, 1, 1, 0, 0, 0,
                                 ctypes.byref(dst)) == 0
    buf = (ctypes.c_float * 4)(4.0, 9.0, 16.0, 25.0)
    assert lib.MXNDArraySyncCopyFromCPU(src, buf, 4) == 0

    n_out = ctypes.c_int(1)                       # preallocated!
    out_arr = (ctypes.c_void_p * 1)(dst.value)
    outs = ctypes.cast(out_arr, ctypes.POINTER(ctypes.c_void_p))
    rc = lib.MXImperativeInvoke(b"sqrt", 1, ctypes.byref(src),
                                ctypes.byref(n_out), ctypes.byref(outs),
                                0, None, None)
    assert rc == 0, lib.MXGetLastError()
    assert n_out.value == 1
    assert outs[0] == dst.value, "handle must be written into, not replaced"
    got = (ctypes.c_float * 4)()
    assert lib.MXNDArraySyncCopyToCPU(dst, got, 4) == 0
    np.testing.assert_allclose(list(got), [2.0, 3.0, 4.0, 5.0], rtol=1e-6)

    # count mismatch is a loud error, not silent replacement
    n_bad = ctypes.c_int(2)
    rc = lib.MXImperativeInvoke(b"sqrt", 1, ctypes.byref(src),
                                ctypes.byref(n_bad), ctypes.byref(outs),
                                0, None, None)
    assert rc != 0
    assert b"preallocated" in lib.MXGetLastError()
    lib.MXNDArrayFree(src)
    lib.MXNDArrayFree(dst)


def test_version_seed_shutdown():
    """Library-level C fns: MXGetVersion / MXRandomSeed (determinism) /
    MXNotifyShutdown (ref c_api.h:202-240)."""
    import ctypes
    import mxnet_tpu  # noqa: F401
    lib = ctypes.CDLL(SO)
    lib.MXGetLastError.restype = ctypes.c_char_p
    lib.MXNDArraySyncCopyToCPU.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
    lib.MXNDArrayFree.argtypes = [ctypes.c_void_p]
    v = ctypes.c_int(-1)
    assert lib.MXGetVersion(ctypes.byref(v)) == 0
    assert v.value == 100        # 0.1.0

    def draw():
        assert lib.MXRandomSeed(1234) == 0
        n_out = ctypes.c_int(0)
        outs = ctypes.POINTER(ctypes.c_void_p)()
        keys = (ctypes.c_char_p * 1)(b"shape")
        vals = (ctypes.c_char_p * 1)(b"(4,)")
        assert lib.MXImperativeInvoke(b"random_uniform", 0, None,
                                      ctypes.byref(n_out),
                                      ctypes.byref(outs), 1, keys,
                                      vals) == 0, lib.MXGetLastError()
        buf = (ctypes.c_float * 4)()
        assert lib.MXNDArraySyncCopyToCPU(outs[0], buf, 4) == 0
        vals_out = list(buf)
        lib.MXNDArrayFree(outs[0])
        return vals_out

    a, b = draw(), draw()
    assert a == b, "MXRandomSeed must make draws deterministic"
    assert lib.MXNotifyShutdown() == 0
