"""Module tests (modeled on reference tests/python/unittest/test_module.py)
plus a small convergence run (reference tests/python/train/test_mlp.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd, io


def _mlp_sym():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _blob_data(n=600, d=50, k=10, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, k, n)
    centers = rng.randn(k, d).astype(np.float32) * 2
    X = centers[y] + rng.randn(n, d).astype(np.float32) * 0.4
    return X, y.astype(np.float32)


def test_module_bind_init_forward():
    net = _mlp_sym()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 50))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    batch = io.DataBatch([nd.ones((8, 50))], [nd.zeros((8,))])
    mod.forward(batch, is_train=False)
    outs = mod.get_outputs()
    assert outs[0].shape == (8, 10)
    np.testing.assert_allclose(outs[0].asnumpy().sum(axis=1),
                               np.ones(8), rtol=1e-5)


def test_module_fit_convergence():
    X, y = _blob_data()
    train = io.NDArrayIter(X[:500], y[:500], batch_size=50, shuffle=True)
    val = io.NDArrayIter(X[500:], y[500:], batch_size=50)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
            num_epoch=4, eval_metric="acc")
    score = mod.score(val, "acc")
    assert score[0][1] > 0.9, score


def test_module_checkpoint_roundtrip(tmp_path):
    X, y = _blob_data(n=200)
    train = io.NDArrayIter(X, y, batch_size=50)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=1, optimizer_params={"learning_rate": 0.1})
    prefix = str(tmp_path / "chk")
    mod.save_checkpoint(prefix, 1, save_optimizer_states=True)

    mod2 = mx.mod.Module.load(prefix, 1)
    mod2.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label, for_training=False)
    p1, _ = mod.get_params()
    p2, _ = mod2.get_params()
    for k in p1:
        np.testing.assert_allclose(p1[k].asnumpy(), p2[k].asnumpy())


def test_module_predict_and_score():
    X, y = _blob_data(n=200)
    it = io.NDArrayIter(X, y, batch_size=40)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    preds = mod.predict(it)
    assert preds.shape == (200, 10)
    res = mod.score(it, "acc")
    assert 0.0 <= res[0][1] <= 1.0


def test_module_multi_device_data_parallel():
    """ctx list → batch sliced per device (reference executor_group)."""
    X, y = _blob_data(n=400)
    ctxs = [mx.cpu(0), mx.cpu(1)]
    train = io.NDArrayIter(X, y, batch_size=40, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=ctxs)
    mod.fit(train, num_epoch=2, kvstore="local",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9})
    score = mod.score(io.NDArrayIter(X, y, batch_size=40), "acc")
    assert score[0][1] > 0.9, score


def test_module_input_grads():
    net = _mlp_sym()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 50))],
             label_shapes=[("softmax_label", (4,))],
             for_training=True, inputs_need_grad=True)
    mod.init_params()
    batch = io.DataBatch([nd.ones((4, 50))], [nd.zeros((4,))])
    mod.forward(batch, is_train=True)
    mod.backward()
    ig = mod.get_input_grads()
    assert ig[0].shape == (4, 50)
    assert np.abs(ig[0].asnumpy()).sum() > 0


def test_bucketing_module():
    """Shared params across per-length buckets (reference test_bucketing)."""
    def sym_gen(seq_len):
        data = sym.var("data")
        net = sym.FullyConnected(data, num_hidden=8, name="fc_shared")
        net = sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10,
                                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})

    b1 = io.DataBatch([nd.ones((4, 10))], [nd.zeros((4,))], bucket_key=10,
                      provide_data=[io.DataDesc("data", (4, 10))],
                      provide_label=[io.DataDesc("softmax_label", (4,))])
    mod.forward(b1, is_train=True)
    mod.backward()
    mod.update()
    # params live in the shared pool; switching buckets keeps them
    mod.switch_bucket(10, [io.DataDesc("data", (4, 10))],
                      [io.DataDesc("softmax_label", (4,))])
    arg, _ = mod.get_params()
    assert "fc_shared_weight" in arg


def test_fixed_params_not_updated():
    net = _mlp_sym()
    mod = mx.mod.Module(net, context=mx.cpu(),
                        fixed_param_names=["fc1_weight"])
    X, y = _blob_data(n=100)
    train = io.NDArrayIter(X, y, batch_size=50)
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params()
    before = mod._exec_group.execs[0].arg_dict["fc1_weight"].asnumpy().copy()
    mod.init_optimizer(optimizer_params={"learning_rate": 1.0})
    batch = next(iter(train))
    mod.forward_backward(batch)
    mod.update()
    after = mod._exec_group.execs[0].arg_dict["fc1_weight"].asnumpy()
    np.testing.assert_allclose(before, after)


def test_feedforward_legacy_api():
    X, y = _blob_data(n=200)
    model = mx.model.FeedForward(_mlp_sym(), ctx=mx.cpu(), num_epoch=4,
                                 learning_rate=0.2, momentum=0.9,
                                 numpy_batch_size=50)
    model.fit(X, y)
    acc = model.score(io.NDArrayIter(X, y, batch_size=50))
    assert acc > 0.8


def test_sequential_module_trains():
    """SequentialModule chains two symbol stages end-to-end
    (ref module/sequential_module.py:29)."""
    np.random.seed(0)
    X = np.random.randn(64, 8).astype(np.float32)
    Y = np.random.randint(0, 3, 64).astype(np.float32)
    X[np.arange(64), Y.astype(int)] += 2.5
    it = io.NDArrayIter(X, Y, batch_size=16)

    d1 = mx.sym.Variable("data")
    stage1 = mx.sym.Activation(
        mx.sym.FullyConnected(d1, num_hidden=16, name="s1fc"),
        act_type="tanh")
    d2 = mx.sym.Variable("data")
    stage2 = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(d2, num_hidden=3, name="s2fc"),
        name="softmax")

    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(stage1, label_names=()), auto_wiring=True)
    seq.add(mx.mod.Module(stage2), take_labels=True)
    seq.fit(it, num_epoch=12, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.2})
    acc = seq.score(io.NDArrayIter(X, Y, batch_size=16), "acc")[0][1]
    assert acc > 0.8, acc


def test_python_loss_module_chain():
    """PythonLossModule supplies a hand-written gradient at the end of a
    SequentialModule chain (ref module/python_module.py:185)."""
    np.random.seed(1)
    X = np.random.randn(32, 6).astype(np.float32)
    Y = np.random.randint(0, 2, 32).astype(np.float32)
    X[:, 0] += (Y * 2 - 1) * 2.0
    it = io.NDArrayIter(X, Y, batch_size=8)

    d = mx.sym.Variable("data")
    logits = mx.sym.FullyConnected(d, num_hidden=2, name="fc")

    def softmax_grad(scores, labels):
        s = scores.asnumpy()
        e = np.exp(s - s.max(axis=1, keepdims=True))
        p = e / e.sum(axis=1, keepdims=True)
        p[np.arange(p.shape[0]), labels.asnumpy().astype(np.int64)] -= 1.0
        return mx.nd.array(p / p.shape[0])

    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(logits, label_names=()), auto_wiring=True)
    seq.add(mx.mod.PythonLossModule(grad_func=softmax_grad),
            take_labels=True)
    seq.fit(it, num_epoch=8, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    # logits argmax accuracy
    seq_out = []
    it.reset()
    for batch in it:
        seq.forward(batch, is_train=False)
        seq_out.append(seq.get_outputs()[0].asnumpy())
    pred = np.concatenate(seq_out).argmax(axis=1)
    acc = (pred == Y).mean()
    assert acc > 0.8, acc


def test_predictor_from_checkpoint(tmp_path):
    """Predict-only surface (ref c_predict_api.cc MXPredCreate/Forward):
    save a trained Module, reload through Predictor, outputs match."""
    np.random.seed(0)
    X = np.random.randn(32, 6).astype(np.float32)
    Y = np.random.randint(0, 3, 32).astype(np.float32)
    it = io.NDArrayIter(X, Y, batch_size=8)
    data = mx.sym.Variable("data")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=3, name="pfc"),
        name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer_params={"learning_rate": 0.1})
    prefix = str(tmp_path / "pred")
    mod.save_checkpoint(prefix, 2)

    pred = mx.Predictor.load(prefix, 2,
                             input_shapes={"data": (8, 6),
                                           "softmax_label": (8,)})
    out = pred.forward(data=X[:8])[0].asnumpy()
    mod_out = mod.predict(io.NDArrayIter(X[:8], Y[:8], batch_size=8))
    np.testing.assert_allclose(out, mod_out.asnumpy(), rtol=1e-5, atol=1e-6)


def test_bucketing_shares_device_params_no_recompile():
    """Bucket switches must be zero-copy and compile-free after warmup:
    every bucket's executors alias the SAME device param NDArrays (the
    XLA analogue of the reference's shared memory pool,
    module/bucketing_module.py:35-106 + graph_executor.cc:868), and a
    revisited bucket reuses its compiled programs (VERDICT r4 weak #5)."""
    np.random.seed(0)
    mx.random.seed(0)
    vocab, buckets = 15, [4, 6, 8, 10]
    rng = np.random.RandomState(3)
    sents = []
    for _ in range(160):
        length = rng.choice(buckets) - rng.randint(0, 2)
        start = rng.randint(1, vocab - 1)
        sents.append([(start + t) % (vocab - 1) + 1 for t in range(length)])
    it = mx.rnn.BucketSentenceIter(sents, batch_size=8, buckets=buckets,
                                   invalid_label=0)

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=8,
                                 name="embed")
        cell = mx.rnn.LSTMCell(num_hidden=16, prefix="lstm_")
        outputs, _ = cell.unroll(seq_len, inputs=embed, merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, 16))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
        lab = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, lab, use_ignore=True,
                                    ignore_label=0, name="softmax")
        return pred, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10,
                                 context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            eval_metric=mx.metric.Perplexity(ignore_label=0))
    assert len(mod._by_key) == len(buckets), sorted(mod._by_key)

    # (a) every bucket aliases the leader's device param arrays
    leader_ex = mod._leader._exec_group.execs[0]
    for key, m in mod._by_key.items():
        assert getattr(m, "_shares_device_params", True) or m is mod._leader
        ex = m._exec_group.execs[0]
        for pname in ("embed_weight", "pred_weight", "lstm_i2h_weight"):
            assert ex.arg_dict[pname] is leader_ex.arg_dict[pname], \
                "bucket %s copies param %s" % (key, pname)

    # (b) warm: every bucket compiled once. More epochs must add ZERO new
    # jit cache entries anywhere (no per-switch recompile).
    def cache_sizes():
        out = {}
        for key, m in mod._by_key.items():
            ex = m._exec_group.execs[0]
            for attr in ("_fwd_train_jit", "_fwd_bwd_ones_jit", "_eval_jit"):
                fn = getattr(ex, attr, None)
                if fn is not None and hasattr(fn, "_cache_size"):
                    out[(key, attr)] = fn._cache_size()
            step = m._cached_step
            if step is not None and hasattr(step._step_jit, "_cache_size"):
                out[(key, "step")] = step._step_jit._cache_size()
        return out

    warm = cache_sizes()
    it.reset()
    mod.fit(it, num_epoch=2, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            eval_metric=mx.metric.Perplexity(ignore_label=0))
    assert cache_sizes() == warm, "bucket switches recompiled after warmup"

    # (c) it still learns across buckets
    it.reset()
    ppl = mod.score(it, mx.metric.Perplexity(ignore_label=0))[0][1]
    assert ppl < 8.0, "perplexity %.2f: sharing broke training" % ppl
