"""Test config: force an 8-device virtual CPU mesh before jax initializes.

Mirrors the reference's test doctrine (SURVEY §4): tests must run without
accelerator hardware; multi-device paths are exercised on a virtual mesh
(the reference used multi-GPU hosts; we use XLA's forced host device count).

Environment note: the axon TPU plugin registers itself via sitecustomize at
interpreter start and force-selects "axon,cpu"; overriding the config *after*
jax import (but before backend init) pins tests to CPU and avoids touching
the TPU tunnel.
"""
import os

prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# flight-recorder dumps (crashing worker subprocesses in dist tests,
# timeout SIGTERMs) go to a session temp dir, not the repo checkout;
# tests that assert on the dump location override this per-subprocess
if "MXNET_FLIGHT_DIR" not in os.environ:
    import tempfile
    os.environ["MXNET_FLIGHT_DIR"] = tempfile.mkdtemp(
        prefix="mxnet-flight-")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_rngs():
    import mxnet_tpu as mx
    np.random.seed(0)
    mx.random.seed(0)
    yield


def pytest_configure(config):
    """Build the native pieces (librecordio.so + im2rec) once per session
    so the native-IO tests run instead of skipping (VERDICT r2 weak #10)."""
    import os
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    wanted = [os.path.join(repo, "mxnet_tpu", "_native", "librecordio.so"),
              os.path.join(repo, "mxnet_tpu", "_native",
                           "libimageloader.so"),
              os.path.join(repo, "mxnet_tpu", "_native", "libengine.so"),
              os.path.join(repo, "mxnet_tpu", "_native", "libmxpredict.so"),
              os.path.join(repo, "mxnet_tpu", "_native", "libmxnet_c.so"),
              os.path.join(repo, "native", "bin", "im2rec")]
    if not all(os.path.exists(p) for p in wanted):
        try:
            subprocess.run(["make", "-C", os.path.join(repo, "native")],
                           check=True, capture_output=True, timeout=300)
        except Exception as exc:  # tests will skip; don't block the run
            print("native build failed: %s" % exc)

    # a previous suite run killed by the CI timeout leaves its own
    # flight_<pid>.json at the repo root; sweep those so the
    # dump-policing test only sees leaks from THIS session
    for name in os.listdir(repo):
        if (name.startswith("flight_") and name.endswith(".json")
                and name[7:-5].isdigit()):
            try:
                os.unlink(os.path.join(repo, name))
            except OSError:
                pass
