"""Test config: force an 8-device virtual CPU mesh before jax initializes.

Mirrors the reference's test doctrine (SURVEY §4): tests must run without
accelerator hardware; multi-device paths are exercised on a virtual mesh
(the reference used multi-GPU hosts; we use XLA's forced host device count).
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_rngs():
    import mxnet_tpu as mx
    np.random.seed(0)
    mx.random.seed(0)
    yield
