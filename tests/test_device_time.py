"""Device-time attribution (ISSUE 12) + the rider satellites.

Covers the MXNET_DEVICE_TIME sampler: per-program blocked timing through
the watched-jit wrapper, the step-timeline decomposition (data-wait /
host / device / collective + overlap_ratio) resolved at step-span exits,
sampling-rate periods, the zero-extra-compiles contract — plus the
flight-dump retention sweep and the guardian-aware /healthz verdict.
"""
import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import device, flight, server


@pytest.fixture
def tel():
    telemetry.reset()
    telemetry.set_enabled(True)
    device.configure(1)
    yield telemetry
    device.configure(0)
    telemetry.set_enabled(False)
    telemetry.reset()


def _watched(name, fn=None):
    return telemetry.watch_jit(jax.jit(fn or (lambda x: x * 2)), name)


def _steps(fns, n, x):
    for _ in range(n):
        with telemetry.span("trainer_step", cat="step",
                            hist="step_time_us"):
            for f in fns:
                f(x)


# ---- sampler ------------------------------------------------------------

def test_device_time_off_by_default(tel):
    device.configure(0)
    assert not device.enabled()
    f = _watched("dt_off_prog")
    _steps([f], 3, jnp.ones((8, 8)))
    assert telemetry.counter("device_time_samples") == 0
    assert telemetry.histogram("device_time_us").count == 0
    assert "device" not in telemetry.snapshot()


def test_sampled_step_decomposition(tel):
    f = _watched("dt_compute_prog")
    g = _watched("kvstore_dt_reduce", lambda x: x + 1)   # collective name
    x = jnp.ones((32, 32))
    telemetry.set_gauge("io_batch_wait_us", 123.0)
    _steps([f, g], 4, x)
    # first step carries the compiles (excluded from device timing);
    # later steps sample both programs
    assert telemetry.counter("device_time_samples") >= 6
    assert telemetry.histogram("device_time_us").count >= 6
    report = device.device_report()
    assert report["programs"]["dt_compute_prog"]["samples"] >= 3
    assert report["programs"]["kvstore_dt_reduce"]["collective"] is True
    assert report["programs"]["dt_compute_prog"]["collective"] is False
    last = report["last_step"]
    assert last["device_us"] > 0 and last["collective_us"] > 0
    assert last["data_wait_us"] == pytest.approx(123.0)
    # the decomposition tiles the step wall (entries are rounded to
    # 0.1us, so three roundings may disagree with the wall by 0.15)
    assert last["host_us"] + last["device_us"] + last["collective_us"] \
        == pytest.approx(last["wall_us"], rel=1e-6, abs=0.31)
    for gauge in ("step_device_us", "step_collective_us", "step_host_us",
                  "step_data_wait_us", "overlap_ratio"):
        assert gauge in telemetry.snapshot()["gauges"]
    snap = telemetry.snapshot()
    assert snap["device"]["sample_period"] == 1
    assert snap["device"]["timelines"]


def test_sample_rate_period(tel):
    device.configure(0.5)                       # every 2nd step
    assert device.sample_period() == 2
    f = _watched("dt_rate_prog")
    x = jnp.ones((8, 8))
    f(x)                                        # compile outside any step
    _steps([f], 6, x)
    report = device.device_report()
    assert len(report["timelines"]) == 3        # steps 1, 3, 5 sampled
    # the un-sampled steps fed the free-running-wall baseline
    assert report["free_wall_ewma_us"] is not None
    assert report["programs"]["dt_rate_prog"]["samples"] == 3


def test_device_timing_adds_zero_compiles(tel):
    """The acceptance contract: turning the sampler on compiles nothing
    — block_until_ready only waits on programs that already ran."""
    device.configure(0)
    f = _watched("dt_nocompile_prog")
    x = jnp.ones((16, 16))
    _steps([f], 2, x)                           # warm
    compiles = telemetry.counter("jit_compiles")
    calls = telemetry.counter("xla_program_calls")
    device.configure(1)
    _steps([f], 3, x)
    assert telemetry.counter("jit_compiles") == compiles
    assert telemetry.counter("xla_program_calls") == calls
    assert telemetry.counter("device_time_samples") >= 3


def test_device_time_works_with_telemetry_off(tel):
    """MXNET_DEVICE_TIME is its own knob: spans off, sampler on — the
    decomposition still lands in the (always-on) gauges."""
    telemetry.set_enabled(False)
    assert not telemetry.trace_active()
    f = _watched("dt_teloff_prog")
    x = jnp.ones((8, 8))
    _steps([f], 3, x)
    assert telemetry.counter("device_time_samples") >= 2
    assert telemetry.gauge("step_device_us") > 0


def test_step_span_mints_trace_id(tel):
    assert telemetry.trace_context() is None
    with telemetry.span("trainer_step", cat="step"):
        tid = telemetry.trace_context()
        assert tid and len(tid) == 16
    assert telemetry.trace_context() is None
    events = [e for e in telemetry.core._events if e.get("cat") == "step"]
    assert events and events[-1]["args"]["trace_id"] == tid
    # steps are trace ROOTS: an ambient id adopted from a wire recv
    # must be shadowed by a fresh per-step id, then restored — else
    # every step of a fleet run glues into one trace
    tok = telemetry.set_trace_context("ffffffffffffffff")
    try:
        seen = []
        for _ in range(2):
            with telemetry.span("trainer_step", cat="step"):
                seen.append(telemetry.trace_context())
        assert "ffffffffffffffff" not in seen
        assert len(set(seen)) == 2
        assert telemetry.trace_context() == "ffffffffffffffff"
    finally:
        telemetry.reset_trace_context(tok)


def test_trace_report_prints_step_timeline(tel, tmp_path):
    import subprocess
    import sys
    f = _watched("dt_report_prog")
    _steps([f], 3, jnp.ones((8, 8)))
    trace = tmp_path / "trace.json"
    snap = tmp_path / "snap.json"
    telemetry.dump_chrome_trace(str(trace))
    telemetry.dump_snapshot(str(snap))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "trace_report.py"),
         str(trace), "--snapshot", str(snap)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "== step timeline" in proc.stdout
    for label in ("data-wait", "device", "collective", "overlap"):
        assert label in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "trace_report.py"),
         str(trace), "--snapshot", str(snap), "--json"],
        capture_output=True, text=True, timeout=60)
    report = json.loads(proc.stdout)
    assert report["timeline"]["last_step"]["device_us"] > 0


# ---- satellite: flight-dump retention -----------------------------------

def test_flight_keep_sweeps_oldest(tmp_path):
    for i in range(6):
        path = tmp_path / ("flight_%d.json" % (1000 + i))
        path.write_text("{}")
        t = time.time() - 600 + i
        os.utime(path, (t, t))
    stale = tmp_path / "flight_notes.json"      # non-matching: untouched
    stale.write_text("{}")
    flight.configure(keep=3)
    try:
        flight.dump(directory=str(tmp_path))
    finally:
        flight.configure(keep=flight.DEFAULT_KEEP)
    names = sorted(p.name for p in tmp_path.glob("flight_*.json"))
    assert "flight_%d.json" % os.getpid() in names
    assert "flight_notes.json" in names
    kept = [n for n in names if n[7:-5].isdigit()]
    assert len(kept) == 3                       # newest 2 fakes + ours
    assert "flight_1004.json" in kept and "flight_1005.json" in kept


def test_flight_keep_zero_disables_sweep(tmp_path):
    for i in range(4):
        (tmp_path / ("flight_%d.json" % (2000 + i))).write_text("{}")
    flight.configure(keep=0)
    try:
        flight.dump(directory=str(tmp_path))
    finally:
        flight.configure(keep=flight.DEFAULT_KEEP)
    kept = [p for p in tmp_path.glob("flight_*.json")]
    assert len(kept) == 5


def test_no_flight_dumps_left_at_repo_root():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    stale = [n for n in os.listdir(repo)
             if n.startswith("flight_") and n.endswith(".json")
             and n[7:-5].isdigit()]
    assert not stale, "stale flight dumps at repo root: %s" % stale


# ---- satellite: guardian folds into /healthz ----------------------------

def test_healthz_unhealthy_on_exhausted_skip_budget(tel):
    from mxnet_tpu import guardian
    g = guardian.TrainingGuardian(max_skips=1)
    guardian.install(g)
    try:
        ok, detail = server.health()
        assert ok and detail["guardian"]["ok"]
        g.after_step(False)             # budget 1 exhausted, no manager
        ok, detail = server.health()
        assert not ok
        assert detail["guardian"]["skip_budget_exhausted"]
        g.after_step(True)              # an applied step recovers
        ok, detail = server.health()
        assert ok
    finally:
        guardian.uninstall(g)
    ok, detail = server.health()
    assert ok and detail["guardian"] is None


# ---- satellite: serve_bench span budget gate ----------------------------

def test_serve_bench_decomposition_and_budget_gate():
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "serve_bench.py"),
         "--clients", "2", "--requests", "5", "--qps", "50",
         "--duration", "0.5", "--max-queue-ms", "0.000001"],
        capture_output=True, text=True, timeout=600, env=env)
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["spans"]["queue_wait"]["count"] > 0
    assert report["spans"]["execute"]["count"] > 0
    assert report["queue_wait_over_budget"] is True
    assert proc.returncode == 1     # the (absurd) budget gate fired
