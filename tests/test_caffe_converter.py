"""Caffe prototxt -> symbol converter (ref tools/caffe_converter/
convert_symbol.py). The fixture prototxts are authored here in the
public text format; the converted symbols must bind and run."""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import caffe_converter as cc  # noqa: E402
import mxnet_tpu as mx  # noqa: E402

LENET = """
name: "LeNet"
layer { name: "data" type: "Input" top: "data" }
layer {
  name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 20 kernel_size: 5 stride: 1 }
}
layer {
  name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "conv2" type: "Convolution" bottom: "pool1" top: "conv2"
  convolution_param { num_output: 50 kernel_size: 5 }
}
layer {
  name: "pool2" type: "Pooling" bottom: "conv2" top: "pool2"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "ip1" type: "InnerProduct" bottom: "pool2" top: "ip1"
  inner_product_param { num_output: 500 }
}
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer {
  name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
  inner_product_param { num_output: 10 }
}
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip2" top: "loss" }
"""


def test_parse_prototxt_structure():
    net = cc.parse_prototxt(LENET)
    assert net["name"] == "LeNet"
    layers = net["layer"]
    assert len(layers) == 9
    assert layers[1]["convolution_param"]["num_output"] == 20
    assert layers[2]["pooling_param"]["pool"] == "MAX"


def test_lenet_converts_binds_and_runs(tmp_path):
    proto = tmp_path / "lenet.prototxt"
    proto.write_text(LENET)
    out = str(tmp_path / "lenet-symbol.json")
    sym = cc.convert(str(proto), out)
    args = sym.list_arguments()
    assert "conv1_weight" in args and "ip2_bias" in args
    # round-trips through the standard json loader and runs forward
    loaded = mx.sym.load(out)
    ex = loaded.simple_bind(mx.cpu(), data=(2, 1, 28, 28),
                            softmax_label=(2,))
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "softmax_label"):
            mx.initializer.Xavier()(mx.initializer.InitDesc(name), arr)
    ex.forward(is_train=False)
    probs = ex.outputs[0].asnumpy()
    assert probs.shape == (2, 10)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)


def test_eltwise_concat_lrn_paths():
    proto = """
layer { name: "data" type: "Input" top: "data" }
layer { name: "c1" type: "Convolution" bottom: "data" top: "c1"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 } }
layer { name: "c2" type: "Convolution" bottom: "data" top: "c2"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 } }
layer { name: "sum" type: "Eltwise" bottom: "c1" bottom: "c2" top: "sum"
  eltwise_param { operation: SUM } }
layer { name: "cat" type: "Concat" bottom: "sum" bottom: "c1" top: "cat" }
layer { name: "n" type: "LRN" bottom: "cat" top: "n"
  lrn_param { local_size: 3 } }
layer { name: "gp" type: "Pooling" bottom: "n" top: "gp"
  pooling_param { pool: AVE global_pooling: true } }
layer { name: "fc" type: "InnerProduct" bottom: "gp" top: "fc"
  inner_product_param { num_output: 3 } }
layer { name: "sm" type: "Softmax" bottom: "fc" top: "sm" }
"""
    sym = cc.prototxt_to_symbol(proto)
    arg_shapes, out_shapes, _ = sym.infer_shape(data=(2, 3, 8, 8),
                                                softmax_label=(2,))
    assert out_shapes[0] == (2, 3)


def test_unknown_layer_is_loud():
    proto = """
layer { name: "data" type: "Input" top: "data" }
layer { name: "x" type: "SPPLayer" bottom: "data" top: "x" }
"""
    with pytest.raises(NotImplementedError, match="SPPLayer"):
        cc.prototxt_to_symbol(proto)


def test_group_dilation_rect_kernels_and_coeff():
    """AlexNet-style grouped conv, rectangular kernels, dilation, and
    Eltwise coefficient sums must convert faithfully (silent drops were
    r5 review findings)."""
    proto = """
layer { name: "data" type: "Input" top: "data" }
layer { name: "g" type: "Convolution" bottom: "data" top: "g"
  convolution_param { num_output: 8 kernel_size: 3 pad: 1 group: 2 } }
layer { name: "r" type: "Convolution" bottom: "g" top: "r"
  convolution_param { num_output: 8 kernel_h: 3 kernel_w: 5
                      pad_h: 1 pad_w: 2 } }
layer { name: "d" type: "Convolution" bottom: "r" top: "d"
  convolution_param { num_output: 8 kernel_size: 3 pad: 2 dilation: 2 } }
layer { name: "diff" type: "Eltwise" bottom: "d" bottom: "g" top: "diff"
  eltwise_param { operation: SUM coeff: 1 coeff: -1 } }
"""
    sym = cc.prototxt_to_symbol(proto)
    arg_shapes, out_shapes, _ = sym.infer_shape(data=(2, 4, 8, 8))
    args = dict(zip(sym.list_arguments(), arg_shapes))
    assert args["g_weight"] == (8, 2, 3, 3), args["g_weight"]   # group=2
    assert args["r_weight"] == (8, 8, 3, 5), args["r_weight"]   # rect
    assert out_shapes[0] == (2, 8, 8, 8)
    # coeff: diff = d - g, check numerically
    ex = sym.simple_bind(mx.cpu(), data=(1, 4, 4, 4))
    for n, a in ex.arg_dict.items():
        if n != "data":
            a[:] = np.random.RandomState(0).rand(*a.shape).astype(a.dtype)
    ex.forward(is_train=False)
    import mxnet_tpu as mxx
    # rebuild the two branches by hand to check the subtraction
    internals = sym.get_internals()
    d_out = internals["d_output"]
    g_out = internals["g_output"]
    exd = d_out.bind(mx.cpu(), {n: ex.arg_dict[n]
                                for n in d_out.list_arguments()})
    exg = g_out.bind(mx.cpu(), {n: ex.arg_dict[n]
                                for n in g_out.list_arguments()})
    exd.forward(); exg.forward()
    np.testing.assert_allclose(
        ex.outputs[0].asnumpy(),
        exd.outputs[0].asnumpy() - exg.outputs[0].asnumpy(), rtol=1e-5)


def test_hash_inside_quoted_name():
    """'#' inside a quoted layer name is data, not a comment."""
    proto = '''
layer { name: "fire#1/squeeze" type: "Input" top: "data" }  # real comment
layer { name: "fc" type: "InnerProduct" bottom: "data" top: "fc"
  inner_product_param { num_output: 2 } }
'''
    net = cc.parse_prototxt(proto)
    assert net["layer"][0]["name"] == "fire#1/squeeze"
    sym = cc.prototxt_to_symbol(proto)
    assert "fc_weight" in sym.list_arguments()
