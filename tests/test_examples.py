"""Example-script smoke gates: every shipped example must run end-to-end
on the CI backend (virtual 8-device CPU mesh) with tiny arguments.

Reference analogue: the runnable ``example/`` surface (SURVEY Appendix
B) that doubles as integration coverage — here executed in-process via
runpy so the scripts inherit the conftest-pinned backend.

The heavier examples (train_mnist / train_cifar10 / lstm_bucketing /
train_ssd_toy / numpy_ops) are exercised with real convergence
thresholds in test_train_convergence.py and test_custom_op.py; this
file covers the rest of the surface cheaply.
"""
import os
import runpy
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def run_example(script, argv, capsys):
    old_argv = sys.argv
    sys.argv = [script] + argv
    try:
        runpy.run_path(os.path.join(EXAMPLES, script), run_name="__main__")
    finally:
        sys.argv = old_argv
    return capsys.readouterr().out


def test_matrix_factorization_learns(capsys):
    out = run_example("matrix_factorization.py",
                      ["--num-epochs", "2", "--num-obs", "4096"], capsys)
    rmse = float(out.strip().rsplit(" ", 1)[-1])
    assert rmse < 0.2          # planted-model noise floor is ~0.05


def test_word_language_model_beats_uniform(capsys):
    out = run_example("word_language_model.py",
                      ["--num-epochs", "1", "--max-batches", "20"], capsys)
    ppl = float(out.strip().rsplit(" ", 1)[-1])
    assert ppl < 64.0          # uniform baseline on the synthetic vocab


def test_model_parallel_lstm_group2ctx(capsys):
    out = run_example("model_parallel_lstm.py", ["--num-steps", "40"],
                      capsys)
    assert "final-loss" in out


@pytest.mark.slow
def test_inception_v3_multi_device_kvstore_device(capsys):
    """BASELINE workload #4: inception-v3, ctx list, kvstore='device'
    (shrunken input so CPU CI stays fast)."""
    out = run_example(
        "train_inception_v3.py",
        ["--num-devices", "2", "--num-batches", "2", "--batch-size", "4",
         "--image-size", "147", "--num-classes", "4"], capsys)
    assert "final-throughput" in out


def test_actor_critic_policy_improves(capsys):
    out = run_example("actor_critic.py", ["--num-episodes", "100"], capsys)
    ret = float(out.strip().rsplit(" ", 1)[-1])
    assert ret > 0.5          # corridor optimum is ~0.97; chance is < 0


def test_dcgan_adversarial_loop_runs(capsys):
    """GAN training is too unstable for a convergence gate at this
    scale; the gate is: the adversarial loop completes with finite
    losses and produces the metric line (ref example/gluon/dcgan.py)."""
    out = run_example("dcgan.py", ["--num-iters", "12"], capsys)
    assert "final-mean-gap" in out


def test_fine_tune_beats_scratch(capsys):
    """Checkpoint-based transfer: fine-tuned features beat from-scratch
    on the same small budget (ref fine-tune workflow, README.md:199)."""
    out = run_example("fine_tune.py", [], capsys)
    last = out.strip().splitlines()[-1]
    tuned = float(last.split()[1])
    scratch = float(last.split()[-1].rstrip(")"))
    assert tuned > scratch + 0.05


def test_super_resolution_beats_nearest(capsys):
    """ESPCN sub-pixel conv beats nearest-neighbour upsampling in PSNR
    on held-out images (ref example/gluon/super_resolution.py)."""
    out = run_example("super_resolution.py", [], capsys)
    last = out.strip().splitlines()[-1]
    model = float(last.split()[1])
    base = float(last.split()[-1].rstrip(")"))
    assert model > base + 0.5


def test_sparse_linear_classification_learns(capsys):
    out = run_example("sparse_linear_classification.py",
                      ["--num-epochs", "3", "--num-obs", "512",
                       "--num-features", "300"], capsys)
    line = [l for l in out.splitlines() if l.startswith("FINAL")][-1]
    fields = dict(kv.split("=") for kv in line.split()[1:])
    assert float(fields["last_nll"]) < float(fields["first_nll"])
    assert float(fields["acc"]) > 0.5


def test_rcnn_toy_detector_learns(capsys):
    """Proposal -> ROIPooling -> head end-to-end learnability
    (reference example/rcnn/train_end2end.py skeleton)."""
    out = run_example("train_rcnn_toy.py",
                      ["--num-epochs", "4", "--lr", "4e-3"], capsys)
    miou = float(out.strip().rsplit(" ", 1)[-1])
    assert miou > 0.3, "refined-proposal IoU %.3f too low" % miou


def test_cnn_text_classification_learns(capsys):
    out = run_example("cnn_text_classification.py",
                      ["--num-epochs", "3"], capsys)
    acc = float(out.strip().rsplit(" ", 1)[-1])
    assert acc > 0.8


def test_nce_word_embeddings_cluster(capsys):
    out = run_example("nce_word_embeddings.py", ["--num-epochs", "4"],
                      capsys)
    margin = float(out.strip().rsplit(" ", 1)[-1])
    assert margin > 0.2, "topic clustering margin %.3f" % margin


def test_vae_toy_elbo_improves(capsys):
    out = run_example("vae_toy.py", ["--num-epochs", "8"], capsys)
    line = out.strip().splitlines()[-1].split()
    untrained, trained = float(line[2]), float(line[4])
    assert trained > untrained + 5.0


def test_publish_and_serve_zoo_artifact(capsys, tmp_path, monkeypatch):
    """Zoo artifact round trip: train -> publish (gluon .params + symbol
    JSON + V2 checkpoint) -> model_store resolves it -> both load paths
    reproduce the recorded accuracy surface (VERDICT r3 #10)."""
    import json
    import numpy as np
    # lr tuned so 3 epochs clears the bar with margin (0.91 on the
    # seeded corpus) — each mobilenet epoch costs ~40s on the 1-core CI
    out = run_example("train_publish_cifar.py",
                      ["--num-epochs", "3", "--lr", "0.01",
                       "--publish", str(tmp_path),
                       "--min-acc", "0.5"], capsys)
    assert "published" in out
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.gluon.model_zoo.model_store import get_model_file
    sys.path.insert(0, EXAMPLES)
    from train_publish_cifar import NAME
    from train_cifar10 import synthetic_cifar

    meta = json.load(open(tmp_path / (NAME + ".json")))
    _, (va_x, va_y) = synthetic_cifar()
    va_x = np.repeat(np.repeat(va_x, 2, axis=2), 2, axis=3)  # per meta

    # gluon path through model_store (MXNET_GLUON_REPO as local dir)
    monkeypatch.setenv("MXNET_GLUON_REPO", str(tmp_path))
    net = vision.get_model("mobilenet0.25", classes=10)
    net.load_params(get_model_file(NAME), ctx=mx.cpu())
    out = net(mx.nd.array(va_x[:256])).asnumpy()
    acc = float((out.argmax(axis=1) == va_y[:256]).mean())
    assert abs(acc - meta["val_accuracy"]) < 0.08

    # symbolic path: Module.load from the published checkpoint
    mod = mx.mod.Module.load(str(tmp_path / NAME), 0,
                             context=mx.cpu())
    mod.bind(data_shapes=[("data", (256, 3, 64, 64))], for_training=False)
    mod.forward(mx.io.DataBatch([mx.nd.array(va_x[:256])], None),
                is_train=False)
    out2 = mod.get_outputs()[0].asnumpy()
    acc2 = float((out2.argmax(axis=1) == va_y[:256]).mean())
    assert abs(acc2 - acc) < 0.02


def test_ctc_ocr_learns(capsys):
    """LSTM + CTC through the symbolic Module path (reference lstm_ocr);
    greedy decode must reach near-zero label error."""
    out = run_example("ctc_ocr_toy.py", ["--num-epochs", "40"], capsys)
    rate = float(out.strip().rsplit(" ", 1)[-1])
    assert rate < 0.15, "label error rate %.3f" % rate


def test_bi_lstm_sort_learns(capsys):
    out = run_example("bi_lstm_sort.py", ["--num-epochs", "40"], capsys)
    token_acc = float(out.split("token acc")[1].split()[0])
    assert token_acc > 0.85, "token accuracy %.3f" % token_acc


def test_adversary_fgsm_attack_works(capsys):
    out = run_example("adversary_fgsm.py", ["--num-epochs", "6"], capsys)
    parts = out.split()
    clean = float(parts[parts.index("acc") + 1])
    adv = float(parts[parts.index("acc", parts.index("acc") + 1) + 1])
    assert clean > 0.9, "clean accuracy %.3f" % clean
    assert adv < clean - 0.5, "FGSM barely moved accuracy (%.3f -> %.3f)" \
        % (clean, adv)


def test_multi_task_both_heads_learn(capsys):
    out = run_example("multi_task.py", ["--num-epochs", "8"], capsys)
    digit = float(out.split("digit acc")[1].split()[0])
    parity = float(out.split("parity acc")[1].split()[0])
    assert digit > 0.9 and parity > 0.9


def test_svm_mnist_learns(capsys):
    out = run_example("svm_mnist.py", ["--num-epochs", "6"], capsys)
    acc = float(out.strip().rsplit(" ", 1)[-1])
    assert acc > 0.9, "svm accuracy %.3f" % acc


def test_factorization_machine_learns_interactions(capsys):
    out = run_example("factorization_machine.py",
                      ["--num-epochs", "8"], capsys)
    parts = out.split()
    first = float(parts[parts.index("first_loss") + 1])
    last = float(parts[parts.index("last_loss") + 1])
    acc = float(parts[parts.index("acc") + 1])
    assert last < first * 0.5
    assert acc > 0.8


@pytest.mark.slow
def test_lstm_crf_learns_tags_and_transitions(capsys):
    out = run_example("lstm_crf.py",
                      ["--num-epochs", "6", "--lr", "0.01"], capsys)
    parts = out.split()
    crf = float(parts[parts.index("acc") + 1])
    margin = float(parts[parts.index("margin") + 1])
    assert crf > 0.7, "crf tag accuracy %.3f" % crf
    assert margin > 0.3, "transition matrix did not learn stickiness"


# ---- round-5 example families (VERDICT r4 Missing #2) ----

def test_fcn_xs_segmentation_learns(capsys):
    """fcn8s skip-fusion segmentation beats the majority-class baseline
    on pixel accuracy and triples chance mIoU (ref example/fcn-xs/)."""
    out = run_example("fcn_xs.py",
                      ["--num-epochs", "3", "--num-images", "256"], capsys)
    lines = dict(l.rsplit(" ", 1) for l in out.strip().splitlines())
    majority = float(lines["majority-baseline"])
    assert float(lines["final-pixel-acc"]) > majority + 0.03
    assert float(lines["final-miou"]) > 0.40


@pytest.mark.slow
def test_tree_lstm_pearson(capsys):
    """Child-sum Tree-LSTM relatedness: Pearson r on held-out tree pairs
    (ref example/gluon/tree_lstm/ main.py metric). The levelized forest
    batching is what makes this trainable in test time."""
    out = run_example("tree_lstm.py",
                      ["--num-pairs", "400", "--num-epochs", "10"], capsys)
    r = float(out.strip().rsplit(" ", 1)[-1])
    assert r > 0.55, "pearson %.3f" % r


def test_dqn_windy_grid(capsys):
    """DQN with replay + target net reaches the goal reliably
    (ref example/reinforcement-learning/dqn/)."""
    out = run_example("dqn.py", ["--num-episodes", "200"], capsys)
    ret = float(out.strip().rsplit(" ", 1)[-1])
    assert ret > 0.5, "greedy return %.3f" % ret


def test_a3c_parallel_envs(capsys):
    """Batched advantage actor-critic: mean per-step reward climbs well
    above the random-walk level (ref example/reinforcement-learning/
    a3c + parallel_actor_critic)."""
    out = run_example("a3c_parallel.py", ["--num-updates", "120"], capsys)
    r = float(out.strip().rsplit(" ", 1)[-1])
    assert r > 0.08, "mean step reward %.4f" % r


def test_autoencoder_dec_clusters(capsys):
    """Stacked-AE pretrain + DEC: reconstruction error drops 3x and the
    DEC refinement does not regress k-means accuracy
    (ref example/autoencoder + example/dec)."""
    out = run_example("autoencoder_dec.py",
                      ["--num-points", "500", "--dec-epochs", "40"], capsys)
    lines = dict(l.rsplit(" ", 1) for l in out.strip().splitlines()
                 if " " in l)
    e0, e1 = (float(v) for v in
              [w for w in out.splitlines() if w.startswith("recon")][0]
              .split()[1::2])
    assert e1 < e0 / 3.0, "recon %.4f -> %.4f" % (e0, e1)
    kacc = float(lines["kmeans-acc"])
    dacc = float(lines["final-dec-acc"])
    assert dacc >= kacc - 1e-6 and dacc > 0.6, (kacc, dacc)


def test_stochastic_depth_trains(capsys):
    """Randomly-dropped residual blocks still train to well above chance
    on the 4-class texture task (ref example/stochastic-depth/)."""
    out = run_example("stochastic_depth.py",
                      ["--num-epochs", "2", "--num-images", "512"], capsys)
    acc = float(out.strip().rsplit(" ", 1)[-1])
    assert acc > 0.6, "accuracy %.3f vs 0.25 chance" % acc


def test_rnn_time_major_layout_equivalence(capsys):
    """Time-major and batch-major training reach close perplexities on
    the deterministic corpus, and both learn it (ref
    example/rnn-time-major/)."""
    out = run_example("rnn_time_major.py", ["--num-epochs", "2"], capsys)
    lines = dict(l.rsplit(" ", 1) for l in out.strip().splitlines()
                 if " " in l)
    assert float(lines["final-time-major-ppl"]) < 12.0   # uniform = 16
    assert float(lines["layout-ppl-gap"]) < 1.5


def test_bayesian_sgld_calibrated(capsys):
    """SGLD posterior predictive matches grid-quadrature truth and the
    chain explores (ref example/bayesian-methods/)."""
    out = run_example("bayesian_sgld.py", [], capsys)
    lines = dict(l.rsplit(" ", 1) for l in out.strip().splitlines())
    assert float(lines["predictive-gap"]) < 0.08
    assert float(lines["mean-gap"]) < 0.8
    assert float(lines["sample-std"]) > 0.1, "sampler collapsed to MAP"


def test_captcha_multi_head(capsys):
    """Grouped 4-head captcha CNN: per-char accuracy well above the 0.1
    chance level (ref example/captcha/)."""
    out = run_example("captcha.py",
                      ["--num-epochs", "6", "--num-images", "1024"],
                      capsys)
    acc = float(out.strip().rsplit(" ", 1)[-1])
    assert acc > 0.6, "char acc %.3f" % acc


def test_dsd_training_flow(capsys):
    """Dense->Sparse->Dense: pruning to 30% density barely hurts, and
    the final dense retrain matches or beats the dense baseline
    (ref example/dsd/)."""
    out = run_example("dsd_training.py", [], capsys)
    lines = dict(l.rsplit(" ", 1) for l in out.strip().splitlines())
    assert abs(float(lines["density-after-prune"]) - 0.30) < 0.02
    dense = float(lines["acc-dense"])
    sparse = float(lines["acc-sparse"])
    dsd = float(lines["final-dsd-acc"])
    assert sparse > dense - 0.06, (dense, sparse)
    assert dsd >= dense - 0.02, (dense, dsd)


def test_neural_collaborative_filtering(capsys):
    """NeuMF with negative sampling: HR@10 well above the 0.1 chance
    level under the leave-one-out protocol (ref example/recommenders/)."""
    out = run_example("neural_collaborative_filtering.py", [], capsys)
    lines = dict(l.rsplit(" ", 1) for l in out.strip().splitlines())
    assert float(lines["final-hr10"]) > 0.3
    assert float(lines["final-ndcg10"]) > 0.15


def test_speech_acoustic_model(capsys):
    """BiLSTM frame-wise phoneme posteriors: near-ceiling accuracy on
    the synthetic formant corpus (ref example/speech-demo +
    example/speech_recognition)."""
    out = run_example("speech_acoustic_model.py", [], capsys)
    acc = float(out.strip().rsplit(" ", 1)[-1])
    assert acc > 0.9, "frame acc %.3f" % acc


@pytest.mark.slow
def test_long_context_ring_attention(capsys):
    """Sequence-parallel ring attention: exact vs dense, and the model
    recalls a needle planted in a DIFFERENT sequence shard — cross-shard
    attention demonstrably works (parallel/ring_attention.py; beyond the
    reference's capability set, SURVEY §2.5)."""
    out = run_example("long_context_ring_attention.py", [], capsys)
    lines = dict(l.rsplit(" ", 1) for l in out.strip().splitlines()
                 if " " in l)
    assert float(lines["ring-vs-dense-max-gap"]) < 1e-3
    assert float(lines["final-needle-accuracy"]) > 0.9


@pytest.mark.slow
def test_ddpg_continuous_control(capsys):
    """DDPG with target networks + replay: deterministic eval return far
    above the random baseline on the docking task
    (ref example/reinforcement-learning/ddpg/)."""
    out = run_example("ddpg.py", ["--num-episodes", "60"], capsys)
    ret = float(out.strip().rsplit(" ", 1)[-1])
    assert ret > -10.0, "eval return %.2f (random ~ -25)" % ret


def test_kaggle_ndsb_pipeline(capsys):
    """Full rec pipeline: pack_img -> .rec -> native threaded decode ->
    Module CNN; val accuracy well above 0.25 chance
    (ref example/kaggle-ndsb1/)."""
    out = run_example("kaggle_ndsb_pipeline.py",
                      ["--num-epochs", "10"], capsys)
    acc = float(out.strip().rsplit(" ", 1)[-1])
    assert acc > 0.55, "val acc %.3f vs 0.25 chance" % acc


def test_memcost_remat_saves_memory(capsys):
    """jax.checkpoint on the scanned residual body (the
    MXNET_BACKWARD_DO_MIRROR analogue) must cut XLA's measured temp
    allocation with bit-identical gradients (ref example/memcost/)."""
    out = run_example("memcost.py", [], capsys)
    lines = dict(l.rsplit(" ", 1) for l in out.strip().splitlines())
    assert float(lines["grad-max-gap"]) < 1e-5
    assert float(lines["final-memory-ratio"]) < 0.7


def test_profiling_demo(capsys, tmp_path):
    """Chrome-trace profiler walkthrough: eager per-op spans, Module
    per-program spans, user markers, valid trace JSON
    (ref example/profiler/)."""
    out = run_example("profiling_demo.py",
                      ["--out", str(tmp_path / "p.json")], capsys)
    lines = dict(l.rsplit(" ", 1) for l in out.strip().splitlines())
    assert int(lines["final-total-events"]) > 20
    assert int(lines["has-marker"]) == 1
    assert int(lines["spans operator"]) > 0
    assert int(lines["spans program"]) > 0
