"""Example-script smoke gates: every shipped example must run end-to-end
on the CI backend (virtual 8-device CPU mesh) with tiny arguments.

Reference analogue: the runnable ``example/`` surface (SURVEY Appendix
B) that doubles as integration coverage — here executed in-process via
runpy so the scripts inherit the conftest-pinned backend.

The heavier examples (train_mnist / train_cifar10 / lstm_bucketing /
train_ssd_toy / numpy_ops) are exercised with real convergence
thresholds in test_train_convergence.py and test_custom_op.py; this
file covers the rest of the surface cheaply.
"""
import os
import runpy
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def run_example(script, argv, capsys):
    old_argv = sys.argv
    sys.argv = [script] + argv
    try:
        runpy.run_path(os.path.join(EXAMPLES, script), run_name="__main__")
    finally:
        sys.argv = old_argv
    return capsys.readouterr().out


def test_matrix_factorization_learns(capsys):
    out = run_example("matrix_factorization.py",
                      ["--num-epochs", "2", "--num-obs", "4096"], capsys)
    rmse = float(out.strip().rsplit(" ", 1)[-1])
    assert rmse < 0.2          # planted-model noise floor is ~0.05


def test_word_language_model_beats_uniform(capsys):
    out = run_example("word_language_model.py",
                      ["--num-epochs", "1", "--max-batches", "30"], capsys)
    ppl = float(out.strip().rsplit(" ", 1)[-1])
    assert ppl < 64.0          # uniform baseline on the synthetic vocab


def test_model_parallel_lstm_group2ctx(capsys):
    out = run_example("model_parallel_lstm.py", ["--num-steps", "60"],
                      capsys)
    assert "final-loss" in out


@pytest.mark.slow
def test_inception_v3_multi_device_kvstore_device(capsys):
    """BASELINE workload #4: inception-v3, ctx list, kvstore='device'
    (shrunken input so CPU CI stays fast)."""
    out = run_example(
        "train_inception_v3.py",
        ["--num-devices", "2", "--num-batches", "2", "--batch-size", "4",
         "--image-size", "147", "--num-classes", "4"], capsys)
    assert "final-throughput" in out


def test_actor_critic_policy_improves(capsys):
    out = run_example("actor_critic.py", ["--num-episodes", "100"], capsys)
    ret = float(out.strip().rsplit(" ", 1)[-1])
    assert ret > 0.5          # corridor optimum is ~0.97; chance is < 0


def test_dcgan_adversarial_loop_runs(capsys):
    """GAN training is too unstable for a convergence gate at this
    scale; the gate is: the adversarial loop completes with finite
    losses and produces the metric line (ref example/gluon/dcgan.py)."""
    out = run_example("dcgan.py", ["--num-iters", "20"], capsys)
    assert "final-mean-gap" in out


def test_fine_tune_beats_scratch(capsys):
    """Checkpoint-based transfer: fine-tuned features beat from-scratch
    on the same small budget (ref fine-tune workflow, README.md:199)."""
    out = run_example("fine_tune.py", [], capsys)
    last = out.strip().splitlines()[-1]
    tuned = float(last.split()[1])
    scratch = float(last.split()[-1].rstrip(")"))
    assert tuned > scratch + 0.05


def test_super_resolution_beats_nearest(capsys):
    """ESPCN sub-pixel conv beats nearest-neighbour upsampling in PSNR
    on held-out images (ref example/gluon/super_resolution.py)."""
    out = run_example("super_resolution.py", [], capsys)
    last = out.strip().splitlines()[-1]
    model = float(last.split()[1])
    base = float(last.split()[-1].rstrip(")"))
    assert model > base + 0.5


def test_sparse_linear_classification_learns(capsys):
    out = run_example("sparse_linear_classification.py",
                      ["--num-epochs", "3", "--num-obs", "512",
                       "--num-features", "300"], capsys)
    line = [l for l in out.splitlines() if l.startswith("FINAL")][-1]
    fields = dict(kv.split("=") for kv in line.split()[1:])
    assert float(fields["last_nll"]) < float(fields["first_nll"])
    assert float(fields["acc"]) > 0.5
