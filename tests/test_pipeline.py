"""Pipeline parallelism: GPipe microbatch streaming over a 'pipe' mesh
axis must be numerically identical to sequential stage application and
differentiable end to end."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu  # noqa: F401  (pins the virtual CPU mesh via conftest)
from mxnet_tpu.parallel.mesh import make_mesh
from mxnet_tpu.parallel.pipeline import pipeline_apply


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _setup(n_stages, d=6, batch=8, seed=0):
    rng = np.random.RandomState(seed)
    params = {
        "w": jnp.asarray((rng.randn(n_stages, d, d)
                          / np.sqrt(d)).astype(np.float32)),
        "b": jnp.asarray((rng.randn(n_stages, d) * 0.1).astype(np.float32)),
    }
    x = jnp.asarray(rng.randn(batch, d).astype(np.float32))
    return params, x


def _sequential(params, x, n_stages):
    for i in range(n_stages):
        x = _stage_fn(jax.tree_util.tree_map(lambda p: p[i], params), x)
    return x


@pytest.mark.parametrize("n_stages,n_micro", [(2, 2), (2, 4), (4, 4),
                                              (4, 8), (8, 8)])
def test_pipeline_matches_sequential(n_stages, n_micro):
    if len(jax.devices()) < n_stages:
        pytest.skip("needs %d devices" % n_stages)
    mesh = make_mesh({"pipe": n_stages},
                     jax.devices()[:n_stages])
    params, x = _setup(n_stages)
    ref = _sequential(params, x, n_stages)
    out = pipeline_apply(_stage_fn, params, x, mesh, n_micro)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_is_differentiable():
    n_stages = 4
    if len(jax.devices()) < n_stages:
        pytest.skip("needs 4 devices")
    mesh = make_mesh({"pipe": n_stages}, jax.devices()[:n_stages])
    params, x = _setup(n_stages)

    def loss(p):
        return jnp.sum(pipeline_apply(_stage_fn, p, x, mesh, 4) ** 2)

    def ref_loss(p):
        return jnp.sum(_sequential(p, x, n_stages) ** 2)

    g = jax.jit(jax.grad(loss))(params)
    g_ref = jax.grad(ref_loss)(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g[k]), np.asarray(g_ref[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_pipeline_trains():
    """A pipelined regression net actually learns (end-to-end SGD)."""
    n_stages = 2
    if len(jax.devices()) < n_stages:
        pytest.skip("needs 2 devices")
    mesh = make_mesh({"pipe": n_stages}, jax.devices()[:n_stages])
    params, x = _setup(n_stages, batch=16)
    rng = np.random.RandomState(1)
    target = jnp.asarray(rng.randn(16, 6).astype(np.float32)) * 0.3

    @jax.jit
    def step(p):
        def loss(p):
            out = pipeline_apply(_stage_fn, p, x, mesh, 4)
            return jnp.mean((out - target) ** 2)
        l, g = jax.value_and_grad(loss)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, g), l

    losses = []
    for _ in range(25):
        params, l = step(params)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.6, losses[::6]
