"""KVStore semantics (reference tests/python/unittest/test_kvstore.py:
single-process multi-device aggregation vs numpy, updater mode, sparse)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def _init_kv(kind="local"):
    kv = mx.kv.create(kind)
    kv.init(3, nd.zeros(SHAPE))
    kv.init(KEYS, [nd.zeros(SHAPE)] * len(KEYS))
    return kv


def test_single_kv_pair():
    kv = _init_kv()
    kv.push(3, nd.ones(SHAPE) * 4)
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.ones(SHAPE) * 4)


def test_aggregator_multiple_devs():
    """Push a list of 'device' arrays; they must be summed (Comm::Reduce)."""
    kv = _init_kv()
    num_devs = 4
    vals = [nd.ones(SHAPE)] * num_devs
    kv.push(3, vals)
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.ones(SHAPE) * num_devs)

    kv.push(KEYS, [[nd.ones(SHAPE) * 2] * num_devs] * len(KEYS))
    outs = [nd.empty(SHAPE) for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for o in outs:
        assert_almost_equal(o.asnumpy(), np.ones(SHAPE) * 2 * num_devs)


def test_updater_runs_on_push():
    kv = _init_kv()
    updates = []

    def updater(key, grad, weight):
        updates.append(key)
        weight += grad * 2  # noqa: PLW2901

    kv.set_updater(updater)
    kv.push(3, nd.ones(SHAPE))
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.ones(SHAPE) * 2)
    assert updates == [3]


def test_set_optimizer_update_on_kvstore():
    kv = _init_kv()
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0,
                                      wd=0.0))
    kv.push(3, nd.ones(SHAPE))
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    # w = 0 - lr * grad = -0.1
    assert_almost_equal(out.asnumpy(), np.full(SHAPE, -0.1), rtol=1e-5)


def test_row_sparse_pull():
    kv = mx.kv.create("local")
    w = np.random.randn(6, 3).astype(np.float32)
    kv.init("w", nd.array(w))
    out = nd.zeros((6, 3))
    rows = nd.array(np.array([1, 4], dtype=np.int64))
    kv.row_sparse_pull("w", out=out, row_ids=rows)
    expect = np.zeros_like(w)
    expect[[1, 4]] = w[[1, 4]]
    assert_almost_equal(out.asnumpy(), expect)


def test_get_type_rank():
    kv = mx.kv.create("local")
    assert kv.type == "local"
    assert kv.rank == 0
    assert kv.num_workers == 1


def test_optimizer_states_roundtrip(tmp_path):
    kv = _init_kv()
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv.push(3, nd.ones(SHAPE))
    fname = str(tmp_path / "opt.states")
    kv.save_optimizer_states(fname)
    kv.load_optimizer_states(fname)
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert np.isfinite(out.asnumpy()).all()


# ---- bucketed batched push/pull (fused Trainer front end) -----------------

def test_plan_buckets_dtype_homogeneous_and_capped():
    from mxnet_tpu.kvstore import _plan_buckets
    f, h = "float32", "float16"
    metas = [(f, 100), (f, 100), (h, 50), (f, 300), (h, 50), (f, 100)]
    plan = _plan_buckets(metas, limit=250)
    # every bucket homogeneous in group key
    for bucket in plan:
        assert len({metas[i][0] for i in bucket}) == 1
    # payload cap respected (oversize singleton allowed)
    for bucket in plan:
        total = sum(metas[i][1] for i in bucket)
        assert total <= 250 or len(bucket) == 1
    # all slots covered exactly once, order preserved within dtype
    flat = sorted(i for b in plan for i in b)
    assert flat == list(range(len(metas)))
    f_order = [i for b in plan for i in b if metas[i][0] == f]
    assert f_order == sorted(f_order)
    # oversize tensor gets its own bucket
    assert [3] in plan


def test_push_pull_all_matches_per_key():
    """Bucketed reduce must be bitwise equal to the per-key reduce."""
    rng = np.random.RandomState(0)
    shapes = [(4, 4), (3,), (2, 5), (7,), (1, 1)]
    copies = [[rng.randn(*s).astype(np.float32) for _ in range(3)]
              for s in shapes]

    kv_a = mx.kv.create("device")
    kv_b = mx.kv.create("device")
    keys = list(range(len(shapes)))
    for k, s in zip(keys, shapes):
        kv_a.init(k, nd.zeros(s))
        kv_b.init(k, nd.zeros(s))

    # per-key oracle
    outs_a = []
    for k, cps in zip(keys, copies):
        kv_a.push(k, [nd.array(c) for c in cps])
        out = nd.empty(shapes[k])
        kv_a.pull(k, out=out)
        outs_a.append(out.asnumpy())

    # bucketed batch
    reduced = kv_b.push_pull_all(
        keys, [[nd.array(c) for c in cps] for cps in copies])
    for a, r in zip(outs_a, reduced):
        np.testing.assert_array_equal(a, r.asnumpy())


def test_push_pull_all_issues_one_program_per_bucket():
    from mxnet_tpu import profiler
    rng = np.random.RandomState(1)
    kv = mx.kv.create("device")
    keys = list(range(24))
    vals = [[nd.array(rng.randn(8, 8).astype(np.float32))
             for _ in range(2)] for _ in keys]
    for k in keys:
        kv.init(k, nd.zeros((8, 8)))
    before = profiler.counter("kvstore_bucket_reduce")
    kv.push_pull_all(keys, vals)
    n_buckets = profiler.counter("kvstore_bucket_reduce") - before
    # 24 * 8*8*4B = 6 KiB total: far under the bucket cap -> ONE program
    assert n_buckets == 1


def test_push_pull_all_single_copy_is_identity():
    """The degenerate 1-copy case (fused Trainer on one device) must not
    launch any reduce program and must return the values unchanged."""
    from mxnet_tpu import profiler
    kv = mx.kv.create("device")
    kv.init(0, nd.zeros(SHAPE))
    g = nd.ones(SHAPE) * 3
    before = profiler.counter("kvstore_bucket_reduce")
    (out,) = kv.push_pull_all([0], [[g]])
    assert profiler.counter("kvstore_bucket_reduce") == before
    assert out is g


def test_program_call_accounting_symmetry():
    """ISSUE 2 satellite: push and pull book their programs the same way.
    The reduce leg bumps once per multi-copy reduce; the broadcast leg
    bumps once per destination copy — so a push/pull round's
    ``xla_program_calls`` delta is deterministic, not push-only."""
    from mxnet_tpu import profiler
    kv = _init_kv()

    # reduce leg: 4 copies -> ONE reduce program; single copy -> none
    before = profiler.counter("xla_program_calls")
    kv.push(3, [nd.ones(SHAPE)] * 4)
    assert profiler.counter("xla_program_calls") - before == 1
    before = profiler.counter("xla_program_calls")
    kv.push(3, nd.ones(SHAPE))
    assert profiler.counter("xla_program_calls") - before == 0

    # broadcast leg: one program per destination
    out = nd.empty(SHAPE)
    before = profiler.counter("xla_program_calls")
    before_pull = profiler.counter("kvstore_pull")
    kv.pull(3, out=out)
    assert profiler.counter("xla_program_calls") - before == 1
    assert profiler.counter("kvstore_pull") - before_pull == 1

    two = [nd.empty(SHAPE), nd.empty(SHAPE)]
    before = profiler.counter("xla_program_calls")
    kv.pull(3, out=two)
    assert profiler.counter("xla_program_calls") - before == 2

    # batched pull books one program per key, same as per-key pulls
    outs = [nd.empty(SHAPE) for _ in KEYS]
    before = profiler.counter("xla_program_calls")
    kv.pull_all(KEYS, outs)
    assert profiler.counter("xla_program_calls") - before == len(KEYS)


def test_push_pull_all_outs_accounting():
    """The fused round: one bucket-reduce program + one broadcast copy
    per explicit out; no outs (the fused-Trainer case) adds nothing."""
    from mxnet_tpu import profiler
    kv = mx.kv.create("device")
    keys = list(range(4))
    for k in keys:
        kv.init(k, nd.zeros(SHAPE))
    vals = [[nd.ones(SHAPE)] * 2 for _ in keys]

    before = profiler.counter("xla_program_calls")
    kv.push_pull_all(keys, vals)
    assert profiler.counter("xla_program_calls") - before == 1  # 1 bucket

    outs = [nd.empty(SHAPE) for _ in keys]
    before = profiler.counter("xla_program_calls")
    kv.push_pull_all(keys, [[nd.ones(SHAPE)] * 2 for _ in keys], outs=outs)
    # one bucket reduce + one copy per destination
    assert profiler.counter("xla_program_calls") - before == 1 + len(keys)


def test_oversize_single_tensor_bucket_reduces_chunked_bitwise():
    """A single-oversize-tensor bucket (payload > chunk budget) routes
    through the pipelined chunked reduce (parallel/collective.py) —
    bitwise equal to the per-key oracle, uneven tail included, with no
    zero-padding leaking out of the chunk machinery."""
    import os
    from mxnet_tpu import profiler
    from mxnet_tpu.parallel import collective
    prev = os.environ.get("MXNET_OVERLAP_CHUNK_BYTES")
    os.environ["MXNET_OVERLAP_CHUNK_BYTES"] = "4096"
    collective.refresh_from_env()
    try:
        rng = np.random.RandomState(7)
        shape = (2473, 3)               # 29676 B payload, uneven tail
        copies = [rng.randn(*shape).astype(np.float32)
                  for _ in range(3)]
        kv_a = mx.kv.create("device")
        kv_a.init("big", nd.zeros(shape))
        kv_a.push("big", [nd.array(c) for c in copies])
        oracle = nd.empty(shape)
        kv_a.pull("big", out=oracle)

        kv_b = mx.kv.create("device")
        kv_b.init("big", nd.zeros(shape))
        before = profiler.counter("collective_chunk_programs")
        (out,) = kv_b.push_pull_all(
            ["big"], [[nd.array(c) for c in copies]])
        assert profiler.counter("collective_chunk_programs") \
            - before > 1, "oversize bucket did not take the chunked path"
        np.testing.assert_array_equal(out.asnumpy(), oracle.asnumpy())
        assert out.shape == shape, "padding leaked past the tail"
    finally:
        if prev is None:
            os.environ.pop("MXNET_OVERLAP_CHUNK_BYTES", None)
        else:
            os.environ["MXNET_OVERLAP_CHUNK_BYTES"] = prev
        collective.refresh_from_env()


def test_reduce_scatter_all_uneven_tails_and_mixed_dtype():
    """ISSUE-15 satellite: ``reduce_scatter_all`` over a model whose
    bucket payloads don't divide the shard count, with an oversize
    tensor and mixed dtypes — reductions bitwise-match the per-key
    oracle, indivisible leading dims fall back to the replicated
    sharding (never a padded one), and no padding row reaches a result.
    """
    import jax
    from mxnet_tpu.parallel import zero as z
    if jax.local_device_count() < 4:
        import pytest
        pytest.skip("needs 4 local devices")
    mesh = z.zero1_axis_mesh(4, "zero")
    rng = np.random.RandomState(11)
    # (div by 4, indivisible 10 % 4, odd vector, f16 pair)
    shapes = [(8, 3), (10, 3), (5,), (8, 2), (6, 2)]
    dtypes = [np.float32, np.float32, np.float32, np.float16,
              np.float16]
    copies = [[(rng.randn(*s) * 0.1).astype(dt) for _ in range(2)]
              for s, dt in zip(shapes, dtypes)]
    shardings = [z.update_sharding(mesh, s, "zero") for s in shapes]
    assert shardings[0] is not None          # divisible: sharded
    assert shardings[1] is None              # 10 % 4: replicated

    kv = mx.kv.create("device")
    keys = list(range(len(shapes)))
    for k, s, dt in zip(keys, shapes, dtypes):
        kv.init(k, nd.zeros(s, dtype=dt))
    results = kv.reduce_scatter_all(
        keys, [[nd.array(c, dtype=c.dtype) for c in cps]
               for cps in copies], shardings)

    kv_o = mx.kv.create("device")
    for k, s, dt in zip(keys, shapes, dtypes):
        kv_o.init(k, nd.zeros(s, dtype=dt))
    for k, cps, r, s, dt in zip(keys, copies, results, shapes, dtypes):
        kv_o.push(k, [nd.array(c, dtype=c.dtype) for c in cps])
        oracle = nd.empty(s, dtype=dt)
        kv_o.pull(k, out=oracle)
        got = np.asarray(r._data)            # gathers sharded results
        assert got.dtype == np.dtype(dt)
        assert got.shape == tuple(s), "padding rows leaked into weights"
        np.testing.assert_array_equal(got, oracle.asnumpy())


def test_push_all_runs_updater_per_key():
    kv = _init_kv()
    seen = []

    def updater(key, grad, weight):
        seen.append(key)
        weight += grad

    kv.set_updater(updater)
    kv.push_all(KEYS, [[nd.ones(SHAPE)] * 2 for _ in KEYS])
    outs = [nd.empty(SHAPE) for _ in KEYS]
    kv.pull_all(KEYS, outs)
    for o in outs:
        assert_almost_equal(o.asnumpy(), np.ones(SHAPE) * 2)
    assert sorted(seen) == sorted(KEYS)
