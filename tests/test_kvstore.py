"""KVStore semantics (reference tests/python/unittest/test_kvstore.py:
single-process multi-device aggregation vs numpy, updater mode, sparse)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def _init_kv(kind="local"):
    kv = mx.kv.create(kind)
    kv.init(3, nd.zeros(SHAPE))
    kv.init(KEYS, [nd.zeros(SHAPE)] * len(KEYS))
    return kv


def test_single_kv_pair():
    kv = _init_kv()
    kv.push(3, nd.ones(SHAPE) * 4)
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.ones(SHAPE) * 4)


def test_aggregator_multiple_devs():
    """Push a list of 'device' arrays; they must be summed (Comm::Reduce)."""
    kv = _init_kv()
    num_devs = 4
    vals = [nd.ones(SHAPE)] * num_devs
    kv.push(3, vals)
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.ones(SHAPE) * num_devs)

    kv.push(KEYS, [[nd.ones(SHAPE) * 2] * num_devs] * len(KEYS))
    outs = [nd.empty(SHAPE) for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for o in outs:
        assert_almost_equal(o.asnumpy(), np.ones(SHAPE) * 2 * num_devs)


def test_updater_runs_on_push():
    kv = _init_kv()
    updates = []

    def updater(key, grad, weight):
        updates.append(key)
        weight += grad * 2  # noqa: PLW2901

    kv.set_updater(updater)
    kv.push(3, nd.ones(SHAPE))
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.ones(SHAPE) * 2)
    assert updates == [3]


def test_set_optimizer_update_on_kvstore():
    kv = _init_kv()
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0,
                                      wd=0.0))
    kv.push(3, nd.ones(SHAPE))
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    # w = 0 - lr * grad = -0.1
    assert_almost_equal(out.asnumpy(), np.full(SHAPE, -0.1), rtol=1e-5)


def test_row_sparse_pull():
    kv = mx.kv.create("local")
    w = np.random.randn(6, 3).astype(np.float32)
    kv.init("w", nd.array(w))
    out = nd.zeros((6, 3))
    rows = nd.array(np.array([1, 4], dtype=np.int64))
    kv.row_sparse_pull("w", out=out, row_ids=rows)
    expect = np.zeros_like(w)
    expect[[1, 4]] = w[[1, 4]]
    assert_almost_equal(out.asnumpy(), expect)


def test_get_type_rank():
    kv = mx.kv.create("local")
    assert kv.type == "local"
    assert kv.rank == 0
    assert kv.num_workers == 1


def test_optimizer_states_roundtrip(tmp_path):
    kv = _init_kv()
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv.push(3, nd.ones(SHAPE))
    fname = str(tmp_path / "opt.states")
    kv.save_optimizer_states(fname)
    kv.load_optimizer_states(fname)
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert np.isfinite(out.asnumpy()).all()
