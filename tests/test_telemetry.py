"""Runtime telemetry layer (ISSUE 2): hierarchical spans, metrics
registry, retrace watchdog, exporters, and the trace_report tool.

Acceptance contract: a 3-step train loop under MXNET_TELEMETRY=1 produces
a trace where ``trainer_step`` spans contain nested kvstore/optimizer
child spans; ``trace_report.py`` prints step-time percentiles + top ops +
the retrace table from it; an intentional shape-changing input triggers
exactly ONE retrace-storm warning; and with telemetry off the
``xla_program_calls`` accounting (tests/test_fused_trainer.py) is
untouched — the watchdog/span off path is a cached-bool check.
"""
import json
import logging
import os
import re
import subprocess
import sys
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, telemetry
from mxnet_tpu.gluon import nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tel(monkeypatch):
    """Telemetry enabled via the env gate, state isolated per test."""
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    telemetry.refresh_from_env()
    telemetry.reset()
    yield telemetry
    telemetry.reset()
    monkeypatch.delenv("MXNET_TELEMETRY", raising=False)
    telemetry.refresh_from_env()


def _train_loop(steps=3, width=8):
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.Sequential()
    net.add(nn.Dense(width, activation="relu"))
    net.add(nn.Dense(3))
    net.initialize(init=mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9},
                            kvstore="device")
    loss_fn = gluon.loss.L2Loss()
    for _ in range(steps):
        x = mx.nd.array(np.random.randn(8, 6).astype(np.float32))
        y = mx.nd.array(np.random.randn(8, 3).astype(np.float32))
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(8)
    return trainer


def _contained(child, parent):
    return (parent["ts"] <= child["ts"]
            and child["ts"] + child["dur"] <= parent["ts"] + parent["dur"])


# ---- acceptance: 3-step loop -> nested spans -> trace_report -------------

def test_train_loop_nested_spans(tel, tmp_path):
    _train_loop(steps=3)
    trace = json.load(open(tel.dump_chrome_trace(
        str(tmp_path / "trace.json"))))
    ev = trace["traceEvents"]

    steps = [e for e in ev if e["name"] == "trainer_step"]
    assert len(steps) == 3
    assert all(e["cat"] == "step" for e in steps)

    kids = [e for e in ev
            if e.get("args", {}).get("parent") == "trainer_step"]
    kid_names = {e["name"] for e in kids}
    assert "kvstore_push_pull" in kid_names
    assert "fused_optimizer_step" in kid_names
    # structural parentage is backed by temporal containment on the track
    for child in kids:
        assert any(_contained(child, s) for s in steps), child

    # ph:"M" metadata labels the tracks (satellite: Perfetto track names)
    meta = [e for e in ev if e.get("ph") == "M"]
    assert any(e["name"] == "process_name" for e in meta)
    assert any(e["name"] == "thread_name" for e in meta)

    # step-time histogram observed once per step
    assert tel.histogram("step_time_us").count == 3
    # memory watermarks sampled at the step boundary
    assert tel.gauge("host_rss_peak_bytes") > 0


def test_trace_report_renders_all_sections(tel, tmp_path, capsys):
    _train_loop(steps=3)
    trace = tel.dump_chrome_trace(str(tmp_path / "trace.json"))
    snap = tel.dump_snapshot(str(tmp_path / "snap.json"))

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import trace_report
    finally:
        sys.path.pop(0)
    assert trace_report.main([trace, "--snapshot", snap, "--top", "5"]) == 0
    out = capsys.readouterr().out
    assert "== step time ==" in out and "p50" in out
    assert "== top 5 ops by self time ==" in out
    assert "trainer_step" in out
    assert "== retrace report ==" in out
    assert "fused_trainer_step" in out       # the step program compiled once


def test_trace_report_smoke_cli(tel, tmp_path):
    """Satellite: the CLI runs against a freshly dumped trace (separate
    interpreter, no framework import)."""
    _train_loop(steps=2)
    trace = tel.dump_chrome_trace(str(tmp_path / "trace.json"))
    snap = tel.dump_snapshot(str(tmp_path / "snap.json"))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         trace, "--snapshot", snap],
        capture_output=True, timeout=60)
    assert proc.returncode == 0, proc.stderr.decode()
    assert b"step time" in proc.stdout
    assert b"retrace report" in proc.stdout


# ---- retrace watchdog ----------------------------------------------------

def test_shape_change_triggers_one_retrace_storm(tel, caplog):
    """Shape-unstable input recompiles the per-slot optimizer program every
    call; crossing the limit must log exactly ONE structured warning."""
    tel.configure(retrace_limit=3)
    opt = mx.optimizer.create("sgd", learning_rate=0.1)
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.telemetry"):
        for n in range(1, 7):                     # 6 shapes -> 6 compiles
            w = nd.array(np.zeros(n, np.float32))
            g = nd.array(np.ones(n, np.float32))
            opt.update(0, w, g, opt.create_state(0, w))
    storms = [r for r in caplog.records if "retrace-storm" in r.getMessage()]
    assert len(storms) == 1, [r.getMessage() for r in storms]
    payload = json.loads(storms[0].getMessage().split(" ", 1)[1])
    assert payload["callable"] == "optimizer_update_step"
    assert payload["compiles"] == 4               # fired when limit crossed
    report = tel.retrace_report()["optimizer_update_step"]
    assert report["count"] == 6
    assert report["storm"] is True
    assert report["total_ms"] > 0
    assert tel.counter("jit_compiles") >= 6
    assert tel.counter("retrace_storms") == 1


def test_stable_shapes_do_not_storm(tel, caplog):
    tel.configure(retrace_limit=3)
    opt = mx.optimizer.create("sgd", learning_rate=0.1)
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.telemetry"):
        for _ in range(8):                        # same shape: one compile
            w = nd.array(np.zeros(4, np.float32))
            g = nd.array(np.ones(4, np.float32))
            opt.update(0, w, g, opt.create_state(0, w))
    assert not [r for r in caplog.records
                if "retrace-storm" in r.getMessage()]
    assert tel.retrace_report()["optimizer_update_step"]["count"] == 1


def test_watch_jit_off_path_is_passthrough():
    """Telemetry off: the watchdog neither times nor records, and cache
    introspection still proxies to the jitted callable."""
    import jax
    telemetry.reset()
    telemetry.set_enabled(False)
    fn = telemetry.watch_jit(jax.jit(lambda x: x + 1), "passthrough_test")
    np.testing.assert_allclose(np.asarray(fn(np.ones(3))), 2 * np.ones(3))
    assert fn._cache_size() == 1                  # proxied attribute
    assert "passthrough_test" not in telemetry.retrace_report()
    assert telemetry.counter("jit_compiles") == 0


# ---- metrics registry ----------------------------------------------------

def test_typed_metrics_and_exposition(tel):
    tel.bump("xla_program_calls", 3)
    tel.set_gauge("io_batch_wait_us", 123.5)
    for v in (10, 60, 60, 5000):
        tel.observe("eager_dispatch_us", v)

    h = tel.histogram("eager_dispatch_us")
    assert h.count == 4 and h.total == 5130
    assert h.percentile(50) >= 60

    text = tel.prometheus_text()
    assert "# TYPE xla_program_calls counter" in text
    assert "xla_program_calls 3" in text
    assert "# TYPE io_batch_wait_us gauge" in text
    assert "# TYPE eager_dispatch_us histogram" in text
    assert 'eager_dispatch_us_bucket{le="+Inf"} 4' in text
    assert "eager_dispatch_us_count 4" in text

    snap = tel.snapshot()
    assert snap["counters"]["xla_program_calls"] == 3
    assert snap["gauges"]["io_batch_wait_us"] == 123.5
    assert snap["histograms"]["eager_dispatch_us"]["count"] == 4
    json.dumps(snap)                              # fully serialisable

    c = tel.Counter("xla_program_calls")
    c.inc(2)
    assert c.value == 5
    g = tel.Gauge("io_batch_wait_us")
    g.set(7)
    assert g.value == 7.0


def test_eager_dispatch_histogram(tel):
    a = nd.array(np.random.randn(4, 4).astype(np.float32))
    before = tel.counter("eager_invocations")
    nd.dot(a, a).wait_to_read()
    assert tel.counter("eager_invocations") > before
    assert tel.histogram("eager_dispatch_us").count > 0


def test_io_batch_wait_gauge(tel):
    from mxnet_tpu import io
    data = np.random.randn(32, 4).astype(np.float32)
    it = io.NDArrayIter(data, np.zeros(32, np.float32), batch_size=8)
    n = sum(1 for _ in it)
    assert n == 4
    assert tel.counter("io_batches") == 4
    assert tel.gauge("io_batch_wait_us") > 0


def test_prefetch_counts_consumer_batches_only(tel):
    """Producer-thread fetches are excluded: a healthy prefetched pipeline
    must not double-count batches or book the producer's full fetch time
    as consumer wait (which would fake a DATA-STARVED verdict)."""
    from mxnet_tpu import io
    data = np.random.randn(32, 4).astype(np.float32)
    inner = io.NDArrayIter(data, np.zeros(32, np.float32), batch_size=8)
    pf = io.PrefetchingIter(inner)
    n = sum(1 for _ in pf)
    assert n == 4
    assert tel.counter("io_batches") == 4


def test_nested_iterators_count_each_batch_once(tel):
    """Same-thread composition (ResizeIter over NDArrayIter) must book
    one io_batches per logical batch, not one per nesting level."""
    from mxnet_tpu import io
    data = np.random.randn(32, 4).astype(np.float32)
    inner = io.NDArrayIter(data, np.zeros(32, np.float32), batch_size=8)
    rit = io.ResizeIter(inner, 6)        # rewinds the inner on exhaustion
    n = sum(1 for _ in rit)
    assert n == 6
    assert tel.counter("io_batches") == 6


def test_kvstore_bucket_bytes_accounting(tel, tmp_path):
    rng = np.random.RandomState(0)
    kv = mx.kv.create("device")
    keys = list(range(6))
    for k in keys:
        kv.init(k, nd.zeros((8, 8)))
    vals = [[nd.array(rng.randn(8, 8).astype(np.float32))
             for _ in range(2)] for _ in keys]
    kv.push_pull_all(keys, vals)

    per_key = 8 * 8 * 4
    assert tel.counter("kvstore_reduce_bytes") == per_key * len(keys)
    assert tel.histogram("bucket_bytes").count == 1    # one flat bucket

    trace = json.load(open(tel.dump_chrome_trace(
        str(tmp_path / "kv.json"))))
    buckets = [e for e in trace["traceEvents"]
               if e["name"] == "kvstore_bucket_reduce"
               and e.get("ph") == "X"]
    assert len(buckets) == 1
    assert buckets[0]["args"]["bytes"] == per_key * len(keys)
    assert buckets[0]["args"]["copies"] == 2


def test_event_ring_buffer_is_bounded(tel, tmp_path):
    """Always-on telemetry must not grow host RSS without bound: the
    trace buffer is a ring — newest spans win, evictions are counted."""
    tel.configure(max_events=16)
    try:
        for i in range(40):
            tel.add_event("ev%d" % i, "user", float(i), 1.0)
        assert tel.counter("trace_events_dropped") == 40 - 16
        snap_names = [e["name"] for e in
                      json.load(open(tel.dump_chrome_trace(
                          str(tmp_path / "ring.json"))))["traceEvents"]
                      if e["ph"] == "X"]
        assert len(snap_names) == 16
        assert snap_names[-1] == "ev39" and "ev0" not in snap_names
    finally:
        tel.configure(max_events=200_000)


def test_off_path_records_nothing():
    """MXNET_TELEMETRY unset: spans are inert, histograms empty — but the
    always-on counters (the perf-contract currency) still count."""
    telemetry.reset()
    telemetry.set_enabled(False)
    assert not telemetry.trace_active()
    with telemetry.span("should_not_record", cat="step",
                        hist="step_time_us"):
        pass
    assert telemetry.histogram("step_time_us").count == 0
    before = telemetry.counter("xla_program_calls")
    telemetry.bump("xla_program_calls")
    assert telemetry.counter("xla_program_calls") == before + 1
    snap = telemetry.snapshot()
    assert snap["enabled"] is False


# ---- satellite: every metric name used in mxnet_tpu/ is declared ---------

_METRIC_USE = re.compile(
    r'(?:\bbump|\bcounter|\bobserve|\bset_gauge|\bgauge|\bhistogram)'
    r'\(\s*["\']([A-Za-z0-9_]+)["\']'
    r'|hist=["\']([A-Za-z0-9_]+)["\']'
    r'|\bspan\(\s*["\']([A-Za-z0-9_]+)["\']'
    r'|\badd_event\(\s*["\']([A-Za-z0-9_]+)["\']')


def test_all_metric_names_declared():
    """Static check: a typo'd counter OR span name silently splits a
    time series / trace_report table — every literal used inside
    mxnet_tpu/ (bump/observe/set_gauge/histogram, ``span("...")``,
    ``add_event("...")``) must be declared in telemetry.METRIC_NAMES
    (which folds in core.SPANS; tools/tests may use ad-hoc names).
    Dynamic names — e.g. the executor's per-program span labels and the
    ``ps_send:<op>`` rpc events — go through watch_jit names or carry a
    declared prefix and are outside the literal scan by construction."""
    used = {}
    pkg = os.path.join(REPO, "mxnet_tpu")
    for dirpath, _, files in os.walk(pkg):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path) as f:
                src = f.read()
            for m in _METRIC_USE.finditer(src):
                name = next(g for g in m.groups() if g)
                used.setdefault(name, []).append(
                    os.path.relpath(path, REPO))
    assert used, "scan found no metric uses — regex rotted?"
    undeclared = {n: ps for n, ps in used.items()
                  if n not in telemetry.METRIC_NAMES}
    assert not undeclared, (
        "span/metric names used but not declared in telemetry.core: %r"
        % undeclared)
    # the new-code gate is live: the serving/device names are declared
    for name in ("serving_run_batch", "device_time_us", "overlap_ratio"):
        assert name in telemetry.METRIC_NAMES


# ---- counters contract stays intact with telemetry ON --------------------

def test_fused_step_program_calls_unchanged_under_telemetry(tel):
    """Turning telemetry on must observe, not perturb: the fused step
    still issues <= 4 XLA programs (the PR-1 contract)."""
    from mxnet_tpu import profiler
    np.random.seed(1)
    loss_fn = gluon.loss.L2Loss()
    net = nn.Sequential()
    net.add(nn.Dense(8, activation="relu"))
    net.add(nn.Dense(3))
    net.initialize(init=mx.initializer.Xavier())
    tr2 = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1}, kvstore="device")
    for _ in range(2):
        xx = mx.nd.array(np.random.randn(8, 6).astype(np.float32))
        yy = mx.nd.array(np.random.randn(8, 3).astype(np.float32))
        with autograd.record():
            ll = loss_fn(net(xx), yy)
        ll.backward()
        before = profiler.counter("xla_program_calls")
        tr2.step(8)
        delta = profiler.counter("xla_program_calls") - before
    assert delta <= 4, "telemetry perturbed the program-call contract"
