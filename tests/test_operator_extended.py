"""Extended operator suite: the reference test_operator.py areas not yet
covered by the core/zoo/indexing files — vision-specific layers, linalg,
contrib transforms, and loss heads, each against a numpy oracle.

Reference analogue: tests/python/unittest/test_operator.py (svm, roi,
instance_norm, l2_normalization, correlation, stn/grid/bilinear, pad,
crop, upsampling, laop*, quantization_op, special math).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _rand(*shape):
    return np.random.RandomState(hash(shape) % 2**31).rand(
        *shape).astype(np.float32)


def test_svm_output_forward_and_margin_grad():
    """SVMOutput forward is identity; backward applies the hinge margin
    rule (ref test_operator.py support_vector_machine_l1_svm)."""
    x = _rand(8, 5) * 2 - 1
    y = np.array([0, 1, 2, 3, 4, 0, 1, 2], np.float32)
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    sym = mx.sym.SVMOutput(data, label=label, margin=1.0,
                           regularization_coefficient=1.0)
    args = {"data": nd.array(x), "label": nd.array(y)}
    grads = {"data": nd.zeros((8, 5))}
    exe = sym.bind(mx.cpu(), args, args_grad=grads,
                   grad_req={"data": "write", "label": "null"})
    out = exe.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out, x, rtol=1e-5)
    exe.backward()
    g = grads["data"].asnumpy()
    assert np.abs(g).sum() > 0
    # the true-class gradient column is non-positive (pull up), others
    # non-negative (push down) under the hinge rule
    for i, yi in enumerate(y.astype(int)):
        assert g[i, yi] <= 1e-6
        others = np.delete(g[i], yi)
        assert (others >= -1e-6).all()


def test_roipooling_max_pools_region():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], np.float32)     # whole image
    out = nd.ROIPooling(nd.array(x), nd.array(rois), pooled_size=(2, 2),
                        spatial_scale=1.0).asnumpy()
    np.testing.assert_allclose(out[0, 0], [[5, 7], [13, 15]])


def test_instance_norm_normalizes_per_instance():
    x = _rand(4, 3, 8, 8) * 5 + 2
    out = nd.InstanceNorm(nd.array(x), nd.ones((3,)), nd.zeros((3,)),
                          eps=1e-5).asnumpy()
    m = out.mean(axis=(2, 3))
    s = out.std(axis=(2, 3))
    np.testing.assert_allclose(m, np.zeros_like(m), atol=1e-4)
    np.testing.assert_allclose(s, np.ones_like(s), atol=1e-3)


def test_l2_normalization_modes():
    x = _rand(4, 3, 5, 5) + 0.1
    for mode, axes in (("instance", (1, 2, 3)), ("channel", (1,)),
                       ("spatial", (2, 3))):
        out = nd.L2Normalization(nd.array(x), mode=mode).asnumpy()
        norm = np.sqrt((x ** 2).sum(axis=axes, keepdims=True))
        np.testing.assert_allclose(out, x / norm, rtol=1e-4, atol=1e-5)


def test_lrn_matches_formula():
    x = _rand(2, 6, 4, 4)
    alpha, beta, knorm, nsize = 1e-4, 0.75, 2.0, 3
    out = nd.LRN(nd.array(x), alpha=alpha, beta=beta, knorm=knorm,
                 nsize=nsize).asnumpy()
    half = nsize // 2
    expect = np.empty_like(x)
    for c in range(6):
        lo, hi = max(0, c - half), min(6, c + half + 1)
        sq = (x[:, lo:hi] ** 2).sum(axis=1)
        expect[:, c] = x[:, c] / (knorm + alpha * sq) ** beta
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_correlation_self_peak_at_zero_displacement():
    """Correlating an image with itself peaks at zero displacement
    (ref test_operator.py correlation)."""
    x = _rand(1, 2, 6, 6)
    out = nd.Correlation(nd.array(x), nd.array(x), kernel_size=1,
                         max_displacement=1, stride1=1, stride2=1,
                         pad_size=1).asnumpy()
    # channel layout: (2d+1)^2 displacements; center channel = (d, d).
    # Pointwise the self-term can lose to a larger-magnitude neighbour,
    # but summed over the image Cauchy-Schwarz guarantees the zero-
    # displacement channel dominates.
    totals = out[0].sum(axis=(1, 2))
    assert totals[4] >= totals.max() - 1e-4


def test_grid_generator_affine_identity_plus_sampler():
    """Identity affine grid through BilinearSampler reproduces the input
    (ref stn/grid_generator/bilinear_sampler tests)."""
    x = _rand(2, 3, 8, 8)
    ident = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
    grid = nd.GridGenerator(nd.array(ident), transform_type="affine",
                            target_shape=(8, 8))
    out = nd.BilinearSampler(nd.array(x), grid).asnumpy()
    np.testing.assert_allclose(out, x, rtol=1e-4, atol=1e-4)


def test_spatial_transformer_identity():
    x = _rand(2, 3, 6, 6)
    loc = nd.array(np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32),
                           (2, 1)))
    out = nd.SpatialTransformer(nd.array(x), loc,
                                target_shape=(6, 6),
                                transform_type="affine",
                                sampler_type="bilinear").asnumpy()
    np.testing.assert_allclose(out, x, rtol=1e-4, atol=1e-4)


def test_pad_modes_match_numpy():
    x = _rand(2, 3, 4, 5)
    pw = (0, 0, 0, 0, 1, 2, 2, 1)
    np_pad = ((0, 0), (0, 0), (1, 2), (2, 1))
    np.testing.assert_allclose(
        nd.Pad(nd.array(x), mode="constant", pad_width=pw,
               constant_value=3.5).asnumpy(),
        np.pad(x, np_pad, mode="constant", constant_values=3.5))
    np.testing.assert_allclose(
        nd.Pad(nd.array(x), mode="edge", pad_width=pw).asnumpy(),
        np.pad(x, np_pad, mode="edge"))
    np.testing.assert_allclose(
        nd.Pad(nd.array(x), mode="reflect", pad_width=pw).asnumpy(),
        np.pad(x, np_pad, mode="reflect"))


def test_crop_center_and_offset():
    x = _rand(1, 1, 8, 8)
    out = nd.Crop(nd.array(x), h_w=(4, 4), center_crop=True).asnumpy()
    np.testing.assert_allclose(out[0, 0], x[0, 0, 2:6, 2:6])
    out = nd.Crop(nd.array(x), h_w=(4, 4), offset=(1, 3)).asnumpy()
    np.testing.assert_allclose(out[0, 0], x[0, 0, 1:5, 3:7])


def test_upsampling_nearest_matches_repeat():
    x = _rand(2, 3, 4, 4)
    out = nd.UpSampling(nd.array(x), scale=2,
                        sample_type="nearest").asnumpy()
    expect = x.repeat(2, axis=2).repeat(2, axis=3)
    np.testing.assert_allclose(out, expect)


# -- linalg family (ref laop/laop_2/laop_3/laop_4) -------------------------

def _spd(b, n, seed=0):
    a = np.random.RandomState(seed).rand(b, n, n).astype(np.float32)
    return a @ a.transpose(0, 2, 1) + n * np.eye(n, dtype=np.float32)


def test_linalg_potrf_potri_sumlogdiag():
    spd = _spd(2, 4)
    l = nd.linalg_potrf(nd.array(spd)).asnumpy()
    np.testing.assert_allclose(l @ l.transpose(0, 2, 1), spd, rtol=1e-3,
                               atol=1e-3)
    assert (np.triu(l[0], 1) == 0).all()          # lower triangular
    # potri consumes the Cholesky factor and returns inv(L L^T)
    # (ref la_op.cc linalg_potri docs)
    inv = nd.linalg_potri(nd.array(l)).asnumpy()
    np.testing.assert_allclose(inv, np.linalg.inv(spd), rtol=1e-2,
                               atol=1e-3)
    sld = nd.linalg_sumlogdiag(nd.array(np.abs(l) + 1e-3)).asnumpy()
    expect = np.log(np.abs(np.diagonal(np.abs(l) + 1e-3, axis1=1,
                                       axis2=2))).sum(1)
    np.testing.assert_allclose(sld, expect, rtol=1e-4)


def test_linalg_gemm_trmm_trsm():
    a, b = _rand(2, 3, 4), _rand(2, 3, 4)
    out = nd.linalg_gemm2(nd.array(a), nd.array(b),
                          transpose_b=True).asnumpy()
    np.testing.assert_allclose(out, a @ b.transpose(0, 2, 1), rtol=1e-4)
    c = _rand(2, 3, 3)
    out = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c),
                         transpose_b=True, alpha=2.0, beta=0.5).asnumpy()
    np.testing.assert_allclose(out, 2.0 * (a @ b.transpose(0, 2, 1))
                               + 0.5 * c, rtol=1e-4)
    l = np.linalg.cholesky(_spd(2, 3))
    x = _rand(2, 3, 4)
    y = nd.linalg_trmm(nd.array(l), nd.array(x)).asnumpy()   # L @ x
    np.testing.assert_allclose(y, l @ x, rtol=1e-4)
    back = nd.linalg_trsm(nd.array(l), nd.array(y)).asnumpy()
    np.testing.assert_allclose(back, x, rtol=1e-3, atol=1e-4)


def test_linalg_syrk_syevd():
    a = _rand(2, 3, 4)
    out = nd.linalg_syrk(nd.array(a), alpha=1.0).asnumpy()
    np.testing.assert_allclose(out, a @ a.transpose(0, 2, 1), rtol=1e-4)
    spd = _spd(1, 4)
    u, lam = nd.linalg_syevd(nd.array(spd))
    u, lam = u.asnumpy(), lam.asnumpy()
    # reconstruct: U^T diag(lam) U
    rec = u.transpose(0, 2, 1) @ (lam[:, :, None] * u)
    np.testing.assert_allclose(rec, spd, rtol=1e-2, atol=1e-2)


# -- contrib transforms ----------------------------------------------------

def test_fft_ifft_roundtrip():
    x = _rand(3, 8)
    f = nd.contrib.fft(nd.array(x))
    assert f.shape == (3, 16)                     # interleaved re/im
    back = nd.contrib.ifft(f).asnumpy()
    # the reference ifft is unnormalized (cuFFT semantics): scale by n
    np.testing.assert_allclose(back, x * 8, rtol=1e-4, atol=1e-4)


def test_quantize_dequantize_roundtrip():
    x = _rand(4, 5)
    mn = nd.array(np.array([0.0], np.float32))
    mx_ = nd.array(np.array([1.0], np.float32))
    q, qmin, qmax = nd.contrib.quantize(nd.array(x), mn, mx_,
                                        out_type="uint8")
    deq = nd.contrib.dequantize(q, qmin, qmax,
                                out_type="float32").asnumpy()
    np.testing.assert_allclose(deq, x, atol=1.0 / 255 + 1e-4)


def test_count_sketch_preserves_inner_products():
    """Count sketch is an approximate isometry in expectation; with one
    fixed hash just check shape + determinism (ref _contrib_count_sketch)."""
    x = _rand(4, 32)
    h = nd.array(np.random.RandomState(0).randint(
        0, 16, (1, 32)).astype(np.float32))
    s = nd.array((np.random.RandomState(1).randint(
        0, 2, (1, 32)) * 2 - 1).astype(np.float32))
    out1 = nd.contrib.count_sketch(nd.array(x), h, s,
                                   out_dim=16).asnumpy()
    out2 = nd.contrib.count_sketch(nd.array(x), h, s,
                                   out_dim=16).asnumpy()
    assert out1.shape == (4, 16)
    np.testing.assert_allclose(out1, out2)
    # energy is preserved exactly per row for sign-hash sketches
    np.testing.assert_allclose((out1 ** 2).sum(), (x ** 2).sum(),
                               rtol=0.5)


# -- misc heads ------------------------------------------------------------

def test_smooth_l1_piecewise():
    x = np.array([-2.0, -0.5, 0.0, 0.5, 2.0], np.float32)
    out = nd.smooth_l1(nd.array(x), scalar=1.0).asnumpy()
    expect = np.where(np.abs(x) < 1.0, 0.5 * x ** 2, np.abs(x) - 0.5)
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_special_math_functions():
    x = _rand(10) * 4 + 0.5
    import scipy.special as sp
    np.testing.assert_allclose(nd.gamma(nd.array(x)).asnumpy(),
                               sp.gamma(x), rtol=1e-3)
    np.testing.assert_allclose(nd.gammaln(nd.array(x)).asnumpy(),
                               sp.gammaln(x), rtol=1e-3, atol=1e-5)


def test_dropout_train_vs_inference():
    x = nd.ones((200, 200))
    data = mx.sym.Variable("data")
    sym = mx.sym.Dropout(data, p=0.5)
    exe = sym.bind(mx.cpu(), {"data": x})
    train_out = exe.forward(is_train=True)[0].asnumpy()
    frac = (train_out == 0).mean()
    assert 0.4 < frac < 0.6
    kept = train_out[train_out != 0]
    np.testing.assert_allclose(kept, np.full_like(kept, 2.0), rtol=1e-5)
    infer_out = exe.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(infer_out, np.ones((200, 200)))
