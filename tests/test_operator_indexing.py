"""Indexing / ordering / sequence operator tests vs numpy oracles
(widening toward reference test_operator.py's take/one_hot/topk/sort/
sequence-op coverage)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_take_axis0_matches_numpy():
    src = np.random.randn(6, 4).astype(np.float32)
    idx = np.array([0, 5, 2], np.float32)
    out = nd.take(nd.array(src), nd.array(idx)).asnumpy()
    np.testing.assert_allclose(out, src[[0, 5, 2]])


def test_batch_take():
    src = np.random.randn(3, 5).astype(np.float32)
    idx = np.array([1, 0, 4], np.float32)
    out = nd.batch_take(nd.array(src), nd.array(idx)).asnumpy()
    np.testing.assert_allclose(out, src[np.arange(3), [1, 0, 4]])


def test_gather_scatter_nd_roundtrip():
    data = np.random.randn(4, 5).astype(np.float32)
    indices = np.array([[0, 2, 3], [1, 4, 0]], np.float32)  # (2, M)
    picked = nd.gather_nd(nd.array(data), nd.array(indices)).asnumpy()
    np.testing.assert_allclose(picked, data[[0, 2, 3], [1, 4, 0]])
    scattered = nd.scatter_nd(nd.array(picked), nd.array(indices),
                              shape=(4, 5)).asnumpy()
    expect = np.zeros((4, 5), np.float32)
    expect[[0, 2, 3], [1, 4, 0]] = picked
    np.testing.assert_allclose(scattered, expect)


def test_one_hot_and_pick_inverse():
    labels = np.array([0, 3, 1], np.float32)
    oh = nd.one_hot(nd.array(labels), depth=4).asnumpy()
    np.testing.assert_allclose(oh.argmax(axis=1), labels)
    probs = np.random.rand(3, 4).astype(np.float32)
    picked = nd.pick(nd.array(probs), nd.array(labels), axis=1).asnumpy()
    np.testing.assert_allclose(picked,
                               probs[np.arange(3), labels.astype(int)])


@pytest.mark.parametrize("k", [1, 3])
def test_topk_matches_numpy(k):
    x = np.random.randn(4, 7).astype(np.float32)
    vals = nd.topk(nd.array(x), k=k, ret_typ="value").asnumpy()
    expect = np.sort(x, axis=1)[:, ::-1][:, :k]
    np.testing.assert_allclose(vals, expect, rtol=1e-6)


def test_sort_and_argsort():
    x = np.random.randn(3, 6).astype(np.float32)
    np.testing.assert_allclose(nd.sort(nd.array(x)).asnumpy(),
                               np.sort(x, axis=-1), rtol=1e-6)
    np.testing.assert_array_equal(
        nd.argsort(nd.array(x)).asnumpy().astype(np.int64),
        np.argsort(x, axis=-1, kind="stable"))


def test_where_broadcast_and_grad():
    cond = np.array([[1, 0], [0, 1]], np.float32)
    a = np.random.randn(2, 2).astype(np.float32)
    b = np.random.randn(2, 2).astype(np.float32)
    out = nd.where(nd.array(cond), nd.array(a), nd.array(b)).asnumpy()
    np.testing.assert_allclose(out, np.where(cond > 0, a, b))


def test_sequence_mask_last_reverse():
    # (T, N, C) = (4, 2, 3)
    x = np.arange(24, dtype=np.float32).reshape(4, 2, 3)
    lengths = np.array([2, 4], np.float32)

    masked = nd.SequenceMask(nd.array(x), nd.array(lengths),
                             use_sequence_length=True, value=-1.0).asnumpy()
    np.testing.assert_allclose(masked[:2, 0], x[:2, 0])
    assert (masked[2:, 0] == -1.0).all()
    np.testing.assert_allclose(masked[:, 1], x[:, 1])

    last = nd.SequenceLast(nd.array(x), nd.array(lengths),
                           use_sequence_length=True).asnumpy()
    np.testing.assert_allclose(last[0], x[1, 0])     # length 2 -> step 1
    np.testing.assert_allclose(last[1], x[3, 1])

    rev = nd.SequenceReverse(nd.array(x), nd.array(lengths),
                             use_sequence_length=True).asnumpy()
    np.testing.assert_allclose(rev[0, 0], x[1, 0])
    np.testing.assert_allclose(rev[1, 0], x[0, 0])
    np.testing.assert_allclose(rev[2:, 0], x[2:, 0])  # beyond length: keep
    np.testing.assert_allclose(rev[:, 1], x[::-1, 1])


def test_reverse_tile_repeat():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    np.testing.assert_allclose(nd.reverse(nd.array(x), axis=1).asnumpy(),
                               x[:, ::-1])
    np.testing.assert_allclose(nd.tile(nd.array(x), reps=(2, 1)).asnumpy(),
                               np.tile(x, (2, 1)))
    np.testing.assert_allclose(nd.repeat(nd.array(x), repeats=2,
                                         axis=0).asnumpy(),
                               np.repeat(x, 2, axis=0))


def test_embedding_grad_is_row_scatter():
    """Embedding backward accumulates per-row gradients (the row_sparse
    gradient pattern, ref indexing_op.cc Embedding)."""
    weight = nd.array(np.random.randn(5, 3).astype(np.float32))
    weight.attach_grad()
    idx = nd.array(np.array([1, 1, 4], np.float32))
    with mx.autograd.record():
        out = nd.Embedding(idx, weight, input_dim=5, output_dim=3)
        loss = out.sum()
    loss.backward()
    g = weight.grad.asnumpy()
    np.testing.assert_allclose(g[1], 2.0)     # row 1 used twice
    np.testing.assert_allclose(g[4], 1.0)
    np.testing.assert_allclose(g[[0, 2, 3]], 0.0)


def test_bilinear_sampler_identity_grid():
    x = np.random.randn(1, 2, 4, 4).astype(np.float32)
    ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                         indexing="ij")
    grid = np.stack([xs, ys])[None].astype(np.float32)   # (1, 2, 4, 4)
    out = nd.BilinearSampler(nd.array(x), nd.array(grid)).asnumpy()
    np.testing.assert_allclose(out, x, atol=1e-5)
