"""Headline benchmark: ResNet-50 inference throughput (img/s), batch 32.

Baseline (BASELINE.md / reference example/image-classification/README.md:
149-155): 109 img/s on 1x K80 at batch 32.  Prints ONE JSON line.

Compute runs in bfloat16 (the MXU design point); the driver executes this
on the real TPU chip.
"""
import json
import time

import numpy as np
import jax
import jax.numpy as jnp

BATCH = 32
BASELINE_IMG_S = 109.0


def main():
    import mxnet_tpu as mx
    from __graft_entry__ import _build_flagship

    # num_tpus() returns 0 (not raises) on backend-init failure; resolving
    # the cpu context can still hit a broken accelerator platform, so guard
    # the whole device pick and fall back to the host CPU backend.
    try:
        dev = (mx.tpu() if mx.context.num_tpus() else mx.cpu()).jax_device
    except RuntimeError:
        dev = jax.devices("cpu")[0]

    # CPU fallback (no chip reachable): shrink the workload so a JSON line
    # still comes out instead of a timeout; bf16 emulation on host is slow
    on_cpu = dev.platform == "cpu"
    batch = 8 if on_cpu else BATCH
    dtype = jnp.float32 if on_cpu else jnp.bfloat16

    forward, params, aux, _ = _build_flagship(
        batch=batch, dtype=dtype, device=dev)
    fwd = jax.jit(forward)

    rng = np.random.RandomState(0)
    x = jax.device_put(jnp.asarray(rng.randn(batch, 3, 224, 224),
                                   dtype), dev)

    # warmup + compile; time the second (cached) call to size the run
    jax.block_until_ready(fwd(params, aux, x))
    t0 = time.perf_counter()
    jax.block_until_ready(fwd(params, aux, x))
    per_iter = time.perf_counter() - t0

    # ~15s of steady-state measurement, capped so the CPU fallback path
    # (seconds per iteration) still reports instead of timing out
    iters = max(2, min(30, int(15.0 / max(per_iter, 1e-4))))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fwd(params, aux, x)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    img_s = batch * iters / dt
    print(json.dumps({
        # distinct metric name on the CPU fallback so the bs32-bf16 chip
        # series is never polluted with bs8-fp32 host numbers
        "metric": ("resnet50_infer_bs32" if not on_cpu
                   else "resnet50_infer_cpu_fallback"),
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": (round(img_s / BASELINE_IMG_S, 2) if not on_cpu
                        else None),
        "device": dev.platform,
        "batch": batch,
    }))


if __name__ == "__main__":
    main()
