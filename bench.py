"""Headline benchmarks: ResNet-50 train + inference throughput, batch 32.

Prints ONE JSON line. The primary metric is the *training* step rate
(fwd + bwd + SGD-momentum update, one jitted donated XLA program) — the
number the reference's own headline tables report
(``example/image-classification/README.md:255-260,293-320``); the same
line also carries the inference img/s and an MFU estimate.

Baselines (BASELINE.md, 1x K80):
 - inference resnet-50 bs32: 109 img/s (README.md:149-155)
 - training: the reference publishes resnet-152 bs32 at 20.08 img/s
   (README.md:309). Scaling by the fwd FLOP ratio (resnet-152 ~11.5 GMAC
   vs resnet-50 ~4.1 GMAC) gives a derived resnet-50 K80 training
   baseline of ~56.3 img/s, used for vs_baseline.

MFU: achieved FLOP/s over chip peak. FLOPs per step come from XLA's own
cost analysis of the compiled train step when available, else from the
analytic 3 x 8.2 GFLOP/img model (fwd 2*4.1 GMAC, bwd ~2x fwd). Peak is
looked up from the device kind (bf16).

Process architecture (round-5 fix of the double-tunnel-open flaw): the
axon tunnel is single-client and wedges if a client dies uncleanly, so
the parent process NEVER imports jax. It spawns ONE child per attempt
(``BENCH_ROLE=chip``) that opens the tunnel, runs the ENTIRE bench, and
prints the JSON; a timed-out child gets SIGTERM + a grace period before
SIGKILL so it can close the tunnel cleanly. The parent falls back to a
CPU child (``BENCH_ROLE=cpu``, JAX_PLATFORMS pinned) only when no chip
JSON ever appeared, and embeds probe forensics in that fallback line.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

BATCH = 32
INFER_BASELINE_IMG_S = 109.0
TRAIN_BASELINE_IMG_S = 56.3       # derived: 20.08 img/s (rn152) * 11.5/4.1
FWD_FLOPS_PER_IMG = 8.2e9         # 2 * ~4.1 GMAC
TRAIN_FLOPS_PER_IMG = 3.0 * FWD_FLOPS_PER_IMG

# bf16 peak FLOP/s by TPU generation (device_kind substring -> peak)
_PEAKS = [
    ("v6e", 918e12), ("v6", 918e12),
    ("v5p", 459e12), ("v5lite", 197e12), ("v5e", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
]


def _chip_peak(device):
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    for tag, peak in _PEAKS:
        if tag in kind:
            return peak
    return None


def _timed_rate(run, batch, target_s=5.0, max_iters=2000, repeats=3):
    """Median img/s over `repeats` windows of ~target_s each."""
    run()                                    # warmup / compile
    t0 = time.perf_counter()
    run()
    per_iter = max(time.perf_counter() - t0, 1e-5)
    iters = max(2, min(max_iters, int(target_s / per_iter)))
    rates = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            run()
        dt = time.perf_counter() - t0
        rates.append(batch * iters / dt)
    return float(np.median(rates)), iters


def _build_train_step(forward, params, aux, dtype, device):
    """One fused train step using the framework's pure optimizer core."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import optimizer as opt_mod
    sgd = opt_mod.create("sgd", learning_rate=0.1, momentum=0.9, wd=1e-4,
                         rescale_grad=1.0)
    train_fwd = forward.train_forward
    hyper = {"lr": 0.1, "wd": 1e-4, "t": 1}

    def loss_fn(p, aux, x, y):
        logits, new_aux = train_fwd(p, aux, x)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        nll = -jnp.take_along_axis(logp, y[:, None], axis=1)
        return jnp.mean(nll), new_aux

    def step(p, m, aux, x, y):
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, aux, x, y)
        new_p, new_m = {}, {}
        for n in p:
            new_p[n], new_m[n] = sgd.update_step(p[n], grads[n], m[n], hyper)
        return new_p, new_m, new_aux, loss

    momenta = {n: jax.device_put(jnp.zeros_like(v), device)
               for n, v in params.items()}
    return jax.jit(step, donate_argnums=(0, 1, 2)), momenta


def _module_train_rate(mx, batch, dtype, window):
    """ResNet-50 training img/s through the framework's own path:
    symbol bind -> Module -> CachedTrainStep (one donated XLA program per
    step). Reference analogue: train_imagenet.py --benchmark 1
    (example/image-classification/README.md:255-260)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import symbol as S
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.io import DataBatch, DataDesc
    from mxnet_tpu.module import Module

    net = vision.get_model("resnet50_v1", classes=1000)
    if dtype == jnp.bfloat16:
        net.cast("bfloat16")
    out = net(S.Variable("data"))
    out = S.Cast(out, dtype="float32")
    out = S.SoftmaxOutput(out, S.Variable("softmax_label"), name="softmax")

    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    mod = Module(out, context=ctx)
    mod.bind(
        data_shapes=[DataDesc("data", (batch, 3, 224, 224), dtype=dtype)],
        label_shapes=[DataDesc("softmax_label", (batch,),
                               dtype=np.float32)])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),
                                         ("momentum", 0.9), ("wd", 1e-4)))
    rng = np.random.RandomState(0)
    db = DataBatch(
        [mx.nd.array(rng.rand(batch, 3, 224, 224).astype(np.float32),
                     dtype=dtype)],
        [mx.nd.array(rng.randint(0, 1000, (batch,)).astype(np.float32))])

    ex = mod._exec_group.execs[0]
    wname = next(n for n in ex.arg_names if n.endswith("weight"))

    def run():
        mod._fit_step(db)
        jax.block_until_ready(ex.arg_dict[wname]._data)

    rate, iters = _timed_rate(run, batch, target_s=window)
    if mod._cached_step is None:
        raise RuntimeError("module bench fell off the fused-step fast path")
    return rate, iters


def _measure(require_chip, probe_error=None):
    """Run the bench in THIS process (child role). Prints the JSON line."""
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # env-var pinning alone can hang under the axon sitecustomize;
        # the config update is what actually keeps the tunnel untouched
        jax.config.update("jax_platforms", "cpu")

    if require_chip:
        # Fail fast (parent retries) rather than silently measuring host.
        assert jax.devices()[0].platform != "cpu", "no accelerator visible"

    import jax.numpy as jnp
    import mxnet_tpu as mx
    from __graft_entry__ import _build_flagship

    try:
        dev = (mx.tpu() if mx.context.num_tpus() else mx.cpu()).jax_device
    except RuntimeError:
        dev = jax.devices("cpu")[0]

    # CPU fallback (no chip reachable): shrink the workload so a JSON line
    # still comes out instead of a timeout; bf16 emulation on host is slow
    on_cpu = dev.platform == "cpu"
    batch = 8 if on_cpu else BATCH
    dtype = jnp.float32 if on_cpu else jnp.bfloat16
    window = 1.0 if on_cpu else 5.0

    forward, params, aux, _ = _build_flagship(batch=batch, dtype=dtype,
                                              device=dev)
    rng = np.random.RandomState(0)
    x = jax.device_put(jnp.asarray(rng.randn(batch, 3, 224, 224), dtype),
                       dev)
    y = jax.device_put(jnp.asarray(rng.randint(0, 1000, (batch,)),
                                   jnp.int32), dev)

    # ---- inference ----
    fwd = jax.jit(forward)

    def run_infer():
        jax.block_until_ready(fwd(params, aux, x))

    infer_rate, _ = _timed_rate(run_infer, batch, target_s=window)

    if on_cpu:
        # CPU fallback: fwd-only so a JSON line always comes out quickly;
        # the train series stays chip-only. probe_error marks this as a
        # FAILED measurement, not a result; probe_forensics (structured,
        # from the parent's pre-fallback sweep) says WHY it failed.
        raw_forensics = os.environ.get("BENCH_PROBE_FORENSICS", "")
        try:
            forensics = json.loads(raw_forensics) if raw_forensics else None
        except ValueError:
            forensics = {"unparseable": raw_forensics[:400]}
        print(json.dumps({
            "metric": "resnet50_infer_cpu_fallback",
            "value": round(infer_rate, 2),
            "unit": "img/s",
            "vs_baseline": None,
            "device": "cpu",
            "batch": batch,
            "probe_error": probe_error or "unknown probe failure",
            "probe_forensics": forensics,
        }))
        return

    # ---- training (fwd + bwd + SGD update, donated) ----
    step, momenta = _build_train_step(forward, params, aux, dtype, dev)
    state = {"p": params, "m": momenta, "a": aux}

    # Compile ONCE ahead of time; reuse the executable for both the FLOP
    # count and the timed loop (jit dispatch would recompile separately).
    step_flops = None
    compiled = None
    try:
        compiled = step.lower(state["p"], state["m"], state["a"], x, y) \
            .compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        if cost and cost.get("flops"):
            step_flops = float(cost["flops"])
    except Exception:
        compiled = None
    run_step = compiled if compiled is not None else step
    if not step_flops or step_flops <= 0:
        step_flops = TRAIN_FLOPS_PER_IMG * batch

    def run_train():
        state["p"], state["m"], state["a"], loss = run_step(
            state["p"], state["m"], state["a"], x, y)
        jax.block_until_ready(loss)

    train_rate, train_iters = _timed_rate(run_train, batch, target_s=window)

    # ---- training through the framework's own Module path ----
    # (Module.bind -> CachedTrainStep: fwd+bwd+SGD as one donated program;
    #  the number the reference reports via train_imagenet.py --benchmark 1)
    module_rate = None
    try:
        module_rate, _ = _module_train_rate(mx, batch, dtype, window)
    except Exception as exc:  # never lose the raw series to a module bug
        print("bench: module-path series failed: %r" % exc, file=sys.stderr)

    peak = _chip_peak(dev)
    achieved = step_flops * train_rate / batch        # FLOP/s
    mfu = round(achieved / peak, 4) if peak else None

    print(json.dumps({
        # distinct metric names on the CPU fallback so the bs32-bf16 chip
        # series is never polluted with bs8-fp32 host numbers
        "metric": "resnet50_train_bs32",
        "value": round(train_rate, 2),
        "unit": "img/s",
        "vs_baseline": round(train_rate / TRAIN_BASELINE_IMG_S, 2),
        "device": dev.platform,
        "device_kind": getattr(dev, "device_kind", None),
        "batch": batch,
        "infer_img_s": round(infer_rate, 2),
        "infer_vs_baseline": round(infer_rate / INFER_BASELINE_IMG_S, 2),
        "mfu": mfu,
        "step_gflops": round(step_flops / 1e9, 1),
        "tflops_achieved": round(achieved / 1e12, 1),
        "measure_iters": train_iters,
        "module_train_img_s": round(module_rate, 2) if module_rate else None,
        "module_vs_raw": round(module_rate / train_rate, 3)
        if module_rate else None,
    }))


# ---------------------------------------------------------------------------
# Parent orchestration: one tunnel client per attempt, SIGTERM before KILL.
# ---------------------------------------------------------------------------

def _extract_json(text):
    """Last parseable JSON object line in `text`, or None."""
    for line in reversed(text.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def _run_child(role, timeout, extra_env=None):
    """Spawn one bench child; returns (json_dict|None, error_string)."""
    env = dict(os.environ)
    env["BENCH_ROLE"] = role
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True)
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        # A SIGKILLed client is exactly what wedges the tunnel for the next
        # attempt: give the child a chance to close it cleanly first.
        proc.send_signal(signal.SIGTERM)
        try:
            out, err = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
        # the child may have finished measuring and printed its JSON but
        # wedged closing the tunnel at exit — don't discard a banked result
        parsed = _extract_json(out or "")
        if parsed is not None:
            sys.stdout.write(out)
            sys.stdout.flush()
            return parsed, ""
        return None, "timed out after %ds" % int(timeout)
    parsed = _extract_json(out or "")
    if parsed is not None:
        # Accept a printed measurement even on nonzero rc: a chip child
        # that crashes tearing down the wedged tunnel AFTER printing its
        # JSON still produced a valid result.
        sys.stdout.write(out)
        sys.stdout.flush()
        return parsed, ""
    return None, "rc=%d: %s" % (
        proc.returncode, (err or "")[-300:].strip().replace("\n", " | "))


def _enum_devices_once(timeout):
    """One fresh-child enumeration attempt; returns the parsed dict or a
    classified error dict.  It separates 'tunnel never answered' from
    'tunnel answered with zero TPU devices' from 'plugin import
    crashed'."""
    env = dict(os.environ)
    env["BENCH_ROLE"] = "enum"
    env.pop("JAX_PLATFORMS", None)       # probe what the plugin offers
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return {"error": "device enumeration hung for %ds (backend init "
                         "never returned: tunnel accepted the client but "
                         "served no PJRT)" % timeout}
    parsed = _extract_json(proc.stdout or "")
    if parsed is not None:
        return parsed
    return {"error": "enum child died rc=%d: %s"
            % (proc.returncode,
               (proc.stderr or "")[-300:].strip().replace("\n", " | "))}


# Cached-success fast path (r03–r05 carry-over): the expensive failure
# mode is re-probing a wedged tunnel over and over.  The first GOOD
# enumeration of the run is cached here (and in the environment, so
# child re-invocations of this script inherit it) and reused by every
# later caller — forensics, retry decisions, the enum smoke — instead
# of spending another hard timeout on a fresh child.
_ENUM_CACHE_ENV = "BENCH_ENUM_CACHE"
_ENUM_CACHE = None


def _enum_cached():
    """The last good enumeration of this run, or None."""
    global _ENUM_CACHE
    if _ENUM_CACHE is not None:
        return _ENUM_CACHE
    raw = os.environ.get(_ENUM_CACHE_ENV, "")
    if raw:
        try:
            parsed = json.loads(raw)
            if isinstance(parsed, dict) and "error" not in parsed:
                _ENUM_CACHE = parsed
        except ValueError:
            pass
    return _ENUM_CACHE


def _enum_remember(result):
    """Bank a successful enumeration for the rest of the run."""
    global _ENUM_CACHE
    if isinstance(result, dict) and "error" not in result:
        _ENUM_CACHE = dict(result)
        os.environ[_ENUM_CACHE_ENV] = json.dumps(_ENUM_CACHE)
    return result


def _enum_devices(timeout=45, attempts=2, backoff=5.0, use_cache=True):
    """Ask a FRESH child process what jax can actually see, with a hard
    per-attempt timeout — the r03-r05 failure mode IS backend init
    hanging, so the enumeration itself must be expendable.

    A transiently wedged tunnel often recovers within seconds, so the
    probe retries with exponential backoff (*attempts* total) before the
    caller falls back to CPU; EVERY attempt's outcome is recorded in the
    returned dict so the probe_forensics block shows the retry history,
    not just the last word.  A good result from earlier in the run is
    returned straight from the cache (``use_cache=False`` forces a
    fresh probe).
    """
    if use_cache:
        cached = _enum_cached()
        if cached is not None:
            return dict(cached, cached=True)
    history = []
    for i in range(max(1, attempts)):
        result = _enum_devices_once(timeout)
        history.append(dict(result, attempt=i + 1))
        if "error" not in result:
            _enum_remember(result)
            break
        if i + 1 < attempts:
            delay = backoff * (2 ** i)
            print("bench: device enumeration attempt %d/%d failed (%s); "
                  "retrying in %.0fs" % (i + 1, attempts,
                                         result["error"], delay),
                  file=sys.stderr)
            time.sleep(delay)
    final = dict(history[-1])
    final.pop("attempt", None)
    final["attempts"] = history
    return final


def _smoke_enum():
    """``BENCH_SMOKE=enum``: enum-only smoke — one bounded fresh-child
    enumeration (cache-aware), ONE JSON line, never the measurement
    path.  Lets a driver record whether a TPU is visible at all in
    seconds instead of burning the full probe budget against a wedged
    tunnel."""
    result = _enum_devices()
    platform = result.get("platform")
    on_tpu = "error" not in result and platform not in (None, "cpu")
    print(json.dumps({
        "metric": "bench_enum_smoke",
        "value": int(result.get("device_count", 0)) if on_tpu else 0,
        "unit": "tpu_devices",
        "platform": platform,
        "device_kinds": result.get("device_kinds"),
        "cached": bool(result.get("cached")),
        "error": result.get("error"),
    }))


def _enum_role():
    """BENCH_ROLE=enum child body: one JSON line, nothing else."""
    out = {}
    try:
        import jax
        devs = jax.devices()
        out = {"platform": devs[0].platform if devs else None,
               "device_count": len(devs),
               "device_kinds": sorted({str(getattr(d, "device_kind", "?"))
                                       for d in devs})}
    except Exception as exc:
        out = {"error": repr(exc)[:400]}
    print(json.dumps(out))


def _forensics():
    """Why is the tunnel wedged? Cheap evidence for the fallback JSON."""
    notes = []
    try:
        out = subprocess.run(["ss", "-tnp"], capture_output=True, text=True,
                             timeout=10).stdout
        hits = [l.strip() for l in out.splitlines() if "python" in l]
        notes.append("ss: %d python sockets" % len(hits))
        notes.extend(hits[:3])
    except Exception as exc:
        notes.append("ss failed: %r" % exc)
    site = "/root/.axon_site/axon"
    try:
        logs = sorted(
            (os.path.join(dp, f) for dp, _, fs in os.walk(site) for f in fs
             if f.endswith(".log")),
            key=lambda p: os.path.getmtime(p), reverse=True)
        if logs:
            with open(logs[0], "rb") as fh:
                fh.seek(max(0, os.path.getsize(logs[0]) - 600))
                tail = fh.read().decode(errors="replace")
            notes.append("%s tail: %s" % (logs[0], tail[-300:]))
        else:
            notes.append("no axon logs under %s" % site)
    except Exception as exc:
        notes.append("axon log scan failed: %r" % exc)
    return " ;; ".join(notes)[:900]


def main():
    role = os.environ.get("BENCH_ROLE", "")
    if role == "enum":
        _enum_role()
        return
    if os.environ.get("BENCH_SMOKE", "") == "enum":
        _smoke_enum()
        return
    if role == "chip":
        _measure(require_chip=True)
        return
    if role == "cpu" or os.environ.get("JAX_PLATFORMS", "") == "cpu":
        _measure(require_chip=False,
                 probe_error=os.environ.get(
                     "BENCH_PROBE_ERROR",
                     "skipped: JAX_PLATFORMS=cpu pinned by caller"))
        return

    total_budget = float(os.environ.get("BENCH_PROBE_BUDGET", "900"))
    deadline = time.time() + total_budget
    attempt, last_err = 0, "no attempts made"
    # Pre-flight (r03-r05 carry-over): ONE bounded enumeration decides
    # whether chip attempts are worth their timeouts at all.  A wedged
    # tunnel now costs ~45s instead of the whole probe budget, and a
    # good answer is cached for every later probe of this run.
    preflight = _enum_devices()
    tpu_visible = "error" not in preflight \
        and preflight.get("platform") not in (None, "cpu")
    if not tpu_visible:
        last_err = "preflight enumeration found no accelerator: %s" \
            % json.dumps({k: preflight.get(k)
                          for k in ("platform", "device_count", "error")})
        print("bench: %s; skipping chip attempts" % last_err,
              file=sys.stderr)
    while tpu_visible and time.time() < deadline:
        attempt += 1
        # The chip child compiles (~40s) + measures (~60s); give it most of
        # the remaining budget but keep one retry's worth in reserve.
        per_try = max(120.0, min(480.0, deadline - time.time()))
        parsed, err = _run_child("chip", per_try)
        if parsed is not None:
            return
        last_err = "attempt %d: %s" % (attempt, err)
        print("bench: chip attempt failed (%s); retrying" % last_err,
              file=sys.stderr)
        time.sleep(min(10.0, max(0.0, deadline - time.time())))

    # Structured forensics BEFORE the CPU fallback runs: the probe's
    # timeout cause, what a fresh child can enumerate, and the host
    # socket/log evidence — so a "10 img/s" artifact explains itself.
    # The enumeration here is deliberately CACHE-BYPASSING: a tunnel
    # that wedged after a good preflight must show up as wedged.
    forensics = {
        "cause": last_err,
        "attempts": attempt,
        "probe_budget_s": total_budget,
        "device_enum": _enum_devices(use_cache=False),
        "env": {k: os.environ[k] for k in
                ("JAX_PLATFORMS", "BENCH_PROBE_BUDGET") if k in os.environ},
        "host": _forensics(),
    }
    print("bench: probe forensics: %s" % json.dumps(forensics,
                                                    sort_keys=True),
          file=sys.stderr)
    probe_error = "%s ;; forensics: %s" % (last_err, forensics["host"])
    parsed, err = _run_child(
        "cpu", 600,
        {"JAX_PLATFORMS": "cpu", "BENCH_PROBE_ERROR": probe_error,
         "BENCH_PROBE_FORENSICS": json.dumps(forensics)})
    if parsed is None:
        # Last resort: a JSON line must always come out for the driver.
        print(json.dumps({
            "metric": "bench_failed", "value": 0, "unit": "img/s",
            "vs_baseline": None, "probe_error": probe_error,
            "probe_forensics": forensics,
            "cpu_fallback_error": err,
        }))


if __name__ == "__main__":
    main()
