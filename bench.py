"""Headline benchmark: ResNet-50 inference throughput (img/s), batch 32.

Baseline (BASELINE.md / reference example/image-classification/README.md:
149-155): 109 img/s on 1x K80 at batch 32.  Prints ONE JSON line.

Compute runs in bfloat16 (the MXU design point); the driver executes this
on the real TPU chip.
"""
import json
import time

import numpy as np
import jax
import jax.numpy as jnp

BATCH = 32
BASELINE_IMG_S = 109.0


def main():
    import mxnet_tpu as mx
    from __graft_entry__ import _build_flagship

    dev = (mx.tpu() if mx.context.num_tpus() else mx.cpu()).jax_device
    forward, params, aux, _ = _build_flagship(
        batch=BATCH, dtype=jnp.bfloat16, device=dev)
    fwd = jax.jit(forward)

    rng = np.random.RandomState(0)
    x = jax.device_put(jnp.asarray(rng.randn(BATCH, 3, 224, 224),
                                   jnp.bfloat16), dev)

    # warmup + compile
    jax.block_until_ready(fwd(params, aux, x))
    jax.block_until_ready(fwd(params, aux, x))

    iters = 30
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fwd(params, aux, x)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0

    img_s = BATCH * iters / dt
    print(json.dumps({
        "metric": "resnet50_infer_bs32",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 2),
    }))


if __name__ == "__main__":
    main()
