// im2rec: pack a dataset listed in a .lst file into a RecordIO shard.
//
// Reference analogue: tools/im2rec.cc (SURVEY §2.1 "im2rec tool").  This
// build packs files as-is (pass-through; JPEG bytes stay JPEG — the same
// behavior as the reference's --pass-through / python im2rec with
// pre-encoded images; decode+augment happens at load time on host).
//
// .lst line format (reference tools/im2rec.py make_list):
//   <index>\t<label...>\t<relative/path>
// Output: <prefix>.rec (+ <prefix>.idx with "<index>\t<byte offset>").
//
// IRHeader wire layout matches python/mxnet-style recordio.pack:
//   uint32 flag; float label; uint64 id; uint64 id2  (flag>0 => flag floats
//   of label vector follow the header).
//
// Build: `make -C native` → native/bin/im2rec
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <iostream>
#include <sstream>
#include <string>
#include <vector>

extern "C" {
void* MXRIOWriterCreate(const char* path);
int MXRIOWrite(void* handle, const char* data, uint64_t len);
int64_t MXRIOWriterTell(void* handle);
void MXRIOWriterFree(void* handle);
}

namespace {

#pragma pack(push, 1)
struct IRHeader {
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};
#pragma pack(pop)

bool read_file(const std::string& path, std::vector<char>* out) {
  std::ifstream f(path, std::ios::binary | std::ios::ate);
  if (!f) return false;
  std::streamsize n = f.tellg();
  f.seekg(0);
  out->resize(static_cast<size_t>(n));
  return static_cast<bool>(f.read(out->data(), n));
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 4) {
    std::cerr << "usage: im2rec <list.lst> <image-root> <out-prefix>\n"
              << "packs files from the .lst (pass-through) into "
              << "<out-prefix>.rec + .idx\n";
    return 1;
  }
  std::string lst = argv[1], root = argv[2], prefix = argv[3];
  std::ifstream flst(lst);
  if (!flst) {
    std::cerr << "cannot open list file " << lst << "\n";
    return 1;
  }
  void* w = MXRIOWriterCreate((prefix + ".rec").c_str());
  if (!w) {
    std::cerr << "cannot open output " << prefix << ".rec\n";
    return 1;
  }
  std::ofstream fidx(prefix + ".idx");

  std::string line;
  size_t count = 0, errors = 0;
  std::vector<char> payload, record;
  while (std::getline(flst, line)) {
    if (line.empty()) continue;
    std::istringstream ss(line);
    std::vector<std::string> fields;
    std::string tok;
    while (std::getline(ss, tok, '\t')) fields.push_back(tok);
    if (fields.size() < 3) { ++errors; continue; }
    uint64_t index = strtoull(fields[0].c_str(), nullptr, 10);
    const std::string& relpath = fields.back();
    std::vector<float> labels;
    for (size_t i = 1; i + 1 < fields.size(); ++i)
      labels.push_back(strtof(fields[i].c_str(), nullptr));

    std::string path = root.empty() ? relpath : root + "/" + relpath;
    if (!read_file(path, &payload)) {
      std::cerr << "skip unreadable " << path << "\n";
      ++errors;
      continue;
    }
    IRHeader hdr;
    hdr.id = index;
    hdr.id2 = 0;
    if (labels.size() == 1) {
      hdr.flag = 0;
      hdr.label = labels[0];
    } else {
      hdr.flag = static_cast<uint32_t>(labels.size());
      hdr.label = 0.0f;
    }
    record.clear();
    record.insert(record.end(), reinterpret_cast<char*>(&hdr),
                  reinterpret_cast<char*>(&hdr) + sizeof(hdr));
    if (hdr.flag > 0)
      record.insert(record.end(),
                    reinterpret_cast<char*>(labels.data()),
                    reinterpret_cast<char*>(labels.data()) +
                        labels.size() * sizeof(float));
    record.insert(record.end(), payload.begin(), payload.end());

    fidx << index << "\t" << MXRIOWriterTell(w) << "\n";
    MXRIOWrite(w, record.data(), record.size());
    ++count;
    if (count % 1000 == 0)
      std::cerr << "packed " << count << " records\n";
  }
  MXRIOWriterFree(w);
  std::cerr << "done: " << count << " records, " << errors << " errors -> "
            << prefix << ".rec\n";
  return errors && !count ? 1 : 0;
}
