// C predict API: the standalone deployment surface.
//
// Reference analogue: include/mxnet/c_predict_api.h +
// src/c_api/c_predict_api.cc — the amalgamation's predict-only C ABI
// (MXPredCreate / MXPredSetInput / MXPredForward / MXPredGetOutputShape /
// MXPredGetOutput / MXPredFree, thread-local MXGetLastError), letting a
// plain C/C++ application run a saved `-symbol.json` + `.params`
// checkpoint without linking any Python.
//
// TPU-native mechanism: the library embeds CPython and drives
// mxnet_tpu.predict._EmbeddedPredictor, whose bind step compiles the
// whole graph into one jitted XLA program; all data crosses the
// boundary as raw float32 buffers, so no numpy C API is required.
//
// Build: native/Makefile target libmxpredict.so (links libpython).
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

// Public ABI declarations — keeps implementation and header signatures
// in lockstep at compile time. The embedded-interpreter plumbing
// (EnsurePython / Gil / error slot) is shared with c_api.cc.
#include "embedded_python.h"
#include "mxnet_tpu_predict.h"

using mxtpu::EnsurePython;
using mxtpu::Gil;
using mxtpu::SetError;
using mxtpu::SetErrorFromPython;

namespace {

struct PredictorState {
  PyObject* obj = nullptr;                       // _EmbeddedPredictor
  std::vector<std::vector<mx_uint>> out_shapes;  // cached per forward
};

}  // namespace

extern "C" {

const char* MXGetLastError() { return mxtpu::last_error().c_str(); }

int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char** input_keys,
                 const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, void** out) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* mod = PyImport_ImportModule("mxnet_tpu.predict");
  if (!mod) {
    SetErrorFromPython();
    return -1;
  }
  PyObject* cls = PyObject_GetAttrString(mod, "_EmbeddedPredictor");
  Py_DECREF(mod);
  if (!cls) {
    SetErrorFromPython();
    return -1;
  }
  PyObject* names = PyList_New(num_input_nodes);
  PyObject* shapes = PyList_New(num_input_nodes);
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(input_keys[i]));
    mx_uint lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject* shp = PyTuple_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyTuple_SetItem(shp, j - lo,
                      PyLong_FromUnsignedLong(input_shape_data[j]));
    PyList_SetItem(shapes, i, shp);
  }
  PyObject* json = PyUnicode_FromString(symbol_json_str);
  PyObject* params = PyBytes_FromStringAndSize(
      static_cast<const char*>(param_bytes), param_size);
  PyObject* obj = PyObject_CallFunction(cls, "OOOOii", json, params, names,
                                        shapes, dev_type, dev_id);
  Py_DECREF(cls);
  Py_DECREF(json);
  Py_DECREF(params);
  Py_DECREF(names);
  Py_DECREF(shapes);
  if (!obj) {
    SetErrorFromPython();
    return -1;
  }
  PredictorState* st = new PredictorState();
  st->obj = obj;
  *out = st;
  return 0;
}

int MXPredSetInput(void* handle, const char* key, const float* data,
                   mx_uint size) {
  PredictorState* st = static_cast<PredictorState*>(handle);
  Gil gil;
  PyObject* raw = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(size) * sizeof(float));
  PyObject* r = PyObject_CallMethod(st->obj, "set_input", "sO", key, raw);
  Py_DECREF(raw);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXPredForward(void* handle) {
  PredictorState* st = static_cast<PredictorState*>(handle);
  Gil gil;
  PyObject* r = PyObject_CallMethod(st->obj, "forward", nullptr);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);
  // Cache output shapes so GetOutputShape can hand out stable pointers.
  // Build into a local and swap only on full success: a caller that
  // ignores a mid-loop error must never observe a half-filled cache.
  std::vector<std::vector<mx_uint>> shapes;
  PyObject* n = PyObject_CallMethod(st->obj, "num_outputs", nullptr);
  if (!n) {
    st->out_shapes.clear();
    SetErrorFromPython();
    return -1;
  }
  long nout = PyLong_AsLong(n);
  Py_DECREF(n);
  for (long i = 0; i < nout; ++i) {
    PyObject* shp =
        PyObject_CallMethod(st->obj, "get_output_shape", "l", i);
    if (!shp) {
      st->out_shapes.clear();
      SetErrorFromPython();
      return -1;
    }
    std::vector<mx_uint> dims;
    for (Py_ssize_t j = 0; j < PyTuple_Size(shp); ++j)
      dims.push_back(static_cast<mx_uint>(
          PyLong_AsUnsignedLong(PyTuple_GetItem(shp, j))));
    Py_DECREF(shp);
    shapes.push_back(std::move(dims));
  }
  st->out_shapes.swap(shapes);
  return 0;
}

int MXPredGetOutputShape(void* handle, mx_uint index, mx_uint** shape_data,
                         mx_uint* shape_ndim) {
  PredictorState* st = static_cast<PredictorState*>(handle);
  if (index >= st->out_shapes.size()) {
    SetError("output index out of range (run MXPredForward first)");
    return -1;
  }
  *shape_data = st->out_shapes[index].data();
  *shape_ndim = static_cast<mx_uint>(st->out_shapes[index].size());
  return 0;
}

int MXPredGetOutput(void* handle, mx_uint index, float* data, mx_uint size) {
  PredictorState* st = static_cast<PredictorState*>(handle);
  Gil gil;
  PyObject* raw =
      PyObject_CallMethod(st->obj, "get_output_bytes", "I", index);
  if (!raw) {
    SetErrorFromPython();
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(raw, &buf, &len) != 0 ||
      static_cast<size_t>(len) != static_cast<size_t>(size) * sizeof(float)) {
    Py_DECREF(raw);
    SetError("output size mismatch");
    return -1;
  }
  std::memcpy(data, buf, len);
  Py_DECREF(raw);
  return 0;
}

int MXPredFree(void* handle) {
  PredictorState* st = static_cast<PredictorState*>(handle);
  {
    Gil gil;
    Py_XDECREF(st->obj);
  }
  delete st;
  return 0;
}

}  // extern "C"
