// General C API over the embedded interpreter: NDArray CRUD +
// MXImperativeInvoke (any registered op callable from plain C) +
// save/load. See include/mxnet_tpu_c.h for the ABI contract and
// mxnet_tpu/c_api_shim.py for the Python half.
//
// Reference analogue: src/c_api/c_api.cc over include/mxnet/c_api.h —
// here each NDArrayHandle is a strong PyObject* reference to an
// mxnet_tpu NDArray, wrapped so shape queries hand out stable pointers.
#include <Python.h>

#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "embedded_python.h"
#include "mxnet_tpu_c.h"

using mxtpu::EnsurePython;
using mxtpu::Gil;
using mxtpu::SetError;
using mxtpu::SetErrorFromPython;

namespace {

struct Handle {
  PyObject* obj = nullptr;          // mxnet_tpu NDArray
  std::vector<mx_uint> shape;       // cached for MXNDArrayGetShape
};

PyObject* Shim() {
  PyObject* mod = PyImport_ImportModule("mxnet_tpu.c_api_shim");
  if (!mod) SetErrorFromPython();
  return mod;
}

// Call shim.<fn>(...) returning a new reference (nullptr on error,
// error slot already set).
PyObject* CallShim(const char* fn, const char* fmt, ...) {
  PyObject* mod = Shim();
  if (!mod) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (!f) {
    SetErrorFromPython();
    return nullptr;
  }
  va_list va;
  va_start(va, fmt);
  PyObject* args = Py_VaBuildValue(fmt, va);
  va_end(va);
  if (!args) {
    Py_DECREF(f);
    SetErrorFromPython();
    return nullptr;
  }
  PyObject* r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_DECREF(args);
  if (!r) SetErrorFromPython();
  return r;
}

Handle* Wrap(PyObject* nd) {
  Handle* h = new Handle();
  h->obj = nd;  // takes the reference
  return h;
}

bool FillShape(Handle* h) {
  PyObject* shp = PyObject_GetAttrString(h->obj, "shape");
  if (!shp) {
    SetErrorFromPython();
    return false;
  }
  h->shape.clear();
  Py_ssize_t n = PyTuple_Size(shp);
  for (Py_ssize_t i = 0; i < n; ++i)
    h->shape.push_back(static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(shp, i))));
  Py_DECREF(shp);
  return true;
}

}  // namespace

extern "C" {

const char* MXGetLastError() { return mxtpu::last_error().c_str(); }

int MXNDArrayCreateEx(const mx_uint* shape, mx_uint ndim, int dev_type,
                      int dev_id, int /*delay_alloc*/, int dtype,
                      NDArrayHandle* out) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* shp = PyTuple_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i)
    PyTuple_SetItem(shp, i, PyLong_FromUnsignedLong(shape[i]));
  PyObject* nd = CallShim("create", "(Oiii)", shp, dev_type, dev_id,
                          dtype);
  Py_DECREF(shp);
  if (!nd) return -1;
  *out = Wrap(nd);
  return 0;
}

int MXNDArrayFree(NDArrayHandle handle) {
  if (!handle) return 0;
  Handle* h = static_cast<Handle*>(handle);
  {
    Gil gil;
    Py_XDECREF(h->obj);
  }
  delete h;
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                      const mx_uint** out_pdata) {
  Handle* h = static_cast<Handle*>(handle);
  Gil gil;
  if (!FillShape(h)) return -1;
  *out_dim = static_cast<mx_uint>(h->shape.size());
  *out_pdata = h->shape.data();
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype) {
  Handle* h = static_cast<Handle*>(handle);
  Gil gil;
  PyObject* code = CallShim("dtype_code", "(O)", h->obj);
  if (!code) return -1;
  *out_dtype = static_cast<int>(PyLong_AsLong(code));
  Py_DECREF(code);
  return 0;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size) {
  // size is an element count (reference contract); bytes follow from
  // the array's dtype itemsize.
  Handle* h = static_cast<Handle*>(handle);
  Gil gil;
  PyObject* item_o = CallShim("itemsize", "(O)", h->obj);
  if (!item_o) return -1;
  long item = PyLong_AsLong(item_o);
  Py_DECREF(item_o);
  PyObject* raw = PyBytes_FromStringAndSize(
      static_cast<const char*>(data),
      static_cast<Py_ssize_t>(size) * item);
  PyObject* r = CallShim("copy_from_bytes", "(OO)", h->obj, raw);
  Py_DECREF(raw);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t size) {
  // size is an element count (reference contract) and must equal the
  // array's element count; the full buffer is copied out.
  Handle* h = static_cast<Handle*>(handle);
  Gil gil;
  PyObject* raw = CallShim("to_bytes", "(O)", h->obj);
  if (!raw) return -1;
  char* buf = nullptr;
  Py_ssize_t nbytes = 0;
  if (PyBytes_AsStringAndSize(raw, &buf, &nbytes) != 0) {
    Py_DECREF(raw);
    SetErrorFromPython();
    return -1;
  }
  if (!FillShape(h)) {
    Py_DECREF(raw);
    return -1;
  }
  size_t count = 1;
  for (mx_uint d : h->shape) count *= d;
  if (size != count) {
    SetError("SyncCopyToCPU: buffer holds " + std::to_string(size) +
             " elements, array has " + std::to_string(count));
    Py_DECREF(raw);
    return -1;
  }
  std::memcpy(data, buf, static_cast<size_t>(nbytes));
  Py_DECREF(raw);
  return 0;
}

int MXNDArrayWaitAll(void) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* mod = PyImport_ImportModule("mxnet_tpu.ndarray");
  if (!mod) {
    SetErrorFromPython();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(mod, "waitall", nullptr);
  Py_DECREF(mod);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXImperativeInvoke(const char* op_name, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys, const char** param_vals) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyObject* o = static_cast<Handle*>(inputs[i])->obj;
    Py_INCREF(o);
    PyList_SetItem(ins, i, o);
  }
  PyObject* keys = PyList_New(num_params);
  PyObject* vals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SetItem(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject* res = CallShim("imperative_invoke", "(sOOO)", op_name, ins,
                           keys, vals);
  Py_DECREF(ins);
  Py_DECREF(keys);
  Py_DECREF(vals);
  if (!res) return -1;
  Py_ssize_t n = PyList_Size(res);
  if (*num_outputs != 0) {
    // Reference contract: a nonzero *num_outputs on entry means *outputs
    // points to caller-preallocated handles the op must write INTO
    // (ref src/imperative/imperative.cc out-array path).
    if (*num_outputs != static_cast<int>(n)) {
      SetError("MXImperativeInvoke: op produced " + std::to_string(n) +
               " outputs but caller preallocated " +
               std::to_string(*num_outputs));
      Py_DECREF(res);
      return -1;
    }
    for (Py_ssize_t i = 0; i < n; ++i) {
      Handle* dst = static_cast<Handle*>((*outputs)[i]);
      PyObject* r = CallShim("copy_into", "(OO)", dst->obj,
                             PyList_GetItem(res, i));
      if (!r) {
        Py_DECREF(res);
        return -1;
      }
      Py_DECREF(r);
    }
    Py_DECREF(res);
    return 0;
  }
  NDArrayHandle* arr = static_cast<NDArrayHandle*>(
      std::malloc(sizeof(NDArrayHandle) * n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GetItem(res, i);
    Py_INCREF(o);
    arr[i] = Wrap(o);
  }
  Py_DECREF(res);
  *num_outputs = static_cast<int>(n);
  *outputs = arr;
  return 0;
}

int MXListAllOpNames(mx_uint* out_size, const char*** out_array) {
  if (!EnsurePython()) return -1;
  Gil gil;
  // Per-thread ret store (matches the per-thread MXGetLastError contract;
  // ref keeps these in MXAPIThreadLocalEntry): pointers handed to one
  // thread survive other threads' calls.
  thread_local std::vector<std::string> names;
  thread_local std::vector<const char*> ptrs;
  PyObject* res = CallShim("all_op_names", "()");
  if (!res) return -1;
  names.clear();
  ptrs.clear();
  Py_ssize_t n = PyList_Size(res);
  for (Py_ssize_t i = 0; i < n; ++i)
    names.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(res, i)));
  Py_DECREF(res);
  for (auto& s : names) ptrs.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(ptrs.size());
  *out_array = ptrs.data();
  return 0;
}

int MXNDArraySave(const char* fname, mx_uint num_args,
                  NDArrayHandle* args, const char** keys) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* arrays = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyObject* o = static_cast<Handle*>(args[i])->obj;
    Py_INCREF(o);
    PyList_SetItem(arrays, i, o);
  }
  PyObject* names = PyList_New(keys ? num_args : 0);
  if (keys)
    for (mx_uint i = 0; i < num_args; ++i)
      PyList_SetItem(names, i, PyUnicode_FromString(keys[i]));
  PyObject* r = CallShim("save_list", "(sOO)", fname, arrays, names);
  Py_DECREF(arrays);
  Py_DECREF(names);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                  NDArrayHandle** out_arr, mx_uint* out_name_size,
                  const char*** out_names) {
  if (!EnsurePython()) return -1;
  Gil gil;
  thread_local std::vector<std::string> names;       // per-thread ret store
  thread_local std::vector<const char*> name_ptrs;
  PyObject* res = CallShim("load_file", "(s)", fname);
  if (!res) return -1;
  PyObject* arrays = PyTuple_GetItem(res, 0);
  PyObject* keys = PyTuple_GetItem(res, 1);
  Py_ssize_t n = PyList_Size(arrays);
  NDArrayHandle* arr = static_cast<NDArrayHandle*>(
      std::malloc(sizeof(NDArrayHandle) * n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GetItem(arrays, i);
    Py_INCREF(o);
    arr[i] = Wrap(o);
  }
  names.clear();
  name_ptrs.clear();
  Py_ssize_t nk = PyList_Size(keys);
  for (Py_ssize_t i = 0; i < nk; ++i)
    names.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(keys, i)));
  for (auto& s : names) name_ptrs.push_back(s.c_str());
  Py_DECREF(res);
  *out_size = static_cast<mx_uint>(n);
  *out_arr = arr;
  *out_name_size = static_cast<mx_uint>(name_ptrs.size());
  *out_names = name_ptrs.data();
  return 0;
}

}  // extern "C"
