// General C API over the embedded interpreter: NDArray CRUD +
// MXImperativeInvoke (any registered op callable from plain C) +
// save/load. See include/mxnet_tpu_c.h for the ABI contract and
// mxnet_tpu/c_api_shim.py for the Python half.
//
// Reference analogue: src/c_api/c_api.cc over include/mxnet/c_api.h —
// here each NDArrayHandle is a strong PyObject* reference to an
// mxnet_tpu NDArray, wrapped so shape queries hand out stable pointers.
#include <Python.h>

#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "embedded_python.h"
#include "mxnet_tpu_c.h"

using mxtpu::EnsurePython;
using mxtpu::Gil;
using mxtpu::SetError;
using mxtpu::SetErrorFromPython;

namespace {

struct Handle {
  PyObject* obj = nullptr;          // mxnet_tpu NDArray
  std::vector<mx_uint> shape;       // cached for MXNDArrayGetShape
};

PyObject* Shim() {
  PyObject* mod = PyImport_ImportModule("mxnet_tpu.c_api_shim");
  if (!mod) SetErrorFromPython();
  return mod;
}

// Call shim.<fn>(...) returning a new reference (nullptr on error,
// error slot already set).
PyObject* CallShim(const char* fn, const char* fmt, ...) {
  PyObject* mod = Shim();
  if (!mod) return nullptr;
  PyObject* f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (!f) {
    SetErrorFromPython();
    return nullptr;
  }
  va_list va;
  va_start(va, fmt);
  PyObject* args = Py_VaBuildValue(fmt, va);
  va_end(va);
  if (!args) {
    Py_DECREF(f);
    SetErrorFromPython();
    return nullptr;
  }
  PyObject* r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_DECREF(args);
  if (!r) SetErrorFromPython();
  return r;
}

Handle* Wrap(PyObject* nd) {
  Handle* h = new Handle();
  h->obj = nd;  // takes the reference
  return h;
}

bool FillShape(Handle* h) {
  PyObject* shp = PyObject_GetAttrString(h->obj, "shape");
  if (!shp) {
    SetErrorFromPython();
    return false;
  }
  h->shape.clear();
  Py_ssize_t n = PyTuple_Size(shp);
  for (Py_ssize_t i = 0; i < n; ++i)
    h->shape.push_back(static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(shp, i))));
  Py_DECREF(shp);
  return true;
}

}  // namespace

extern "C" {

const char* MXGetLastError() { return mxtpu::last_error().c_str(); }

int MXGetVersion(int* out) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* r = CallShim("version_number", "()");
  if (!r) return -1;
  long v = PyLong_AsLong(r);
  Py_DECREF(r);
  if (v == -1 && PyErr_Occurred()) {
    SetErrorFromPython();
    return -1;
  }
  *out = static_cast<int>(v);
  return 0;
}

int MXRandomSeed(int seed) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* r = CallShim("random_seed", "(i)", seed);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int MXNotifyShutdown(void) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* r = CallShim("notify_shutdown", "()");
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int MXNDArrayCreateEx(const mx_uint* shape, mx_uint ndim, int dev_type,
                      int dev_id, int /*delay_alloc*/, int dtype,
                      NDArrayHandle* out) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* shp = PyTuple_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i)
    PyTuple_SetItem(shp, i, PyLong_FromUnsignedLong(shape[i]));
  PyObject* nd = CallShim("create", "(Oiii)", shp, dev_type, dev_id,
                          dtype);
  Py_DECREF(shp);
  if (!nd) return -1;
  *out = Wrap(nd);
  return 0;
}

int MXNDArrayFree(NDArrayHandle handle) {
  if (!handle) return 0;
  Handle* h = static_cast<Handle*>(handle);
  {
    Gil gil;
    Py_XDECREF(h->obj);
  }
  delete h;
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                      const mx_uint** out_pdata) {
  Handle* h = static_cast<Handle*>(handle);
  Gil gil;
  if (!FillShape(h)) return -1;
  *out_dim = static_cast<mx_uint>(h->shape.size());
  *out_pdata = h->shape.data();
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype) {
  Handle* h = static_cast<Handle*>(handle);
  Gil gil;
  PyObject* code = CallShim("dtype_code", "(O)", h->obj);
  if (!code) return -1;
  *out_dtype = static_cast<int>(PyLong_AsLong(code));
  Py_DECREF(code);
  return 0;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size) {
  // size is an element count (reference contract); bytes follow from
  // the array's dtype itemsize.
  Handle* h = static_cast<Handle*>(handle);
  Gil gil;
  PyObject* item_o = CallShim("itemsize", "(O)", h->obj);
  if (!item_o) return -1;
  long item = PyLong_AsLong(item_o);
  Py_DECREF(item_o);
  PyObject* raw = PyBytes_FromStringAndSize(
      static_cast<const char*>(data),
      static_cast<Py_ssize_t>(size) * item);
  PyObject* r = CallShim("copy_from_bytes", "(OO)", h->obj, raw);
  Py_DECREF(raw);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t size) {
  // size is an element count (reference contract) and must equal the
  // array's element count; the full buffer is copied out.
  Handle* h = static_cast<Handle*>(handle);
  Gil gil;
  PyObject* raw = CallShim("to_bytes", "(O)", h->obj);
  if (!raw) return -1;
  char* buf = nullptr;
  Py_ssize_t nbytes = 0;
  if (PyBytes_AsStringAndSize(raw, &buf, &nbytes) != 0) {
    Py_DECREF(raw);
    SetErrorFromPython();
    return -1;
  }
  if (!FillShape(h)) {
    Py_DECREF(raw);
    return -1;
  }
  size_t count = 1;
  for (mx_uint d : h->shape) count *= d;
  if (size != count) {
    SetError("SyncCopyToCPU: buffer holds " + std::to_string(size) +
             " elements, array has " + std::to_string(count));
    Py_DECREF(raw);
    return -1;
  }
  std::memcpy(data, buf, static_cast<size_t>(nbytes));
  Py_DECREF(raw);
  return 0;
}

int MXNDArrayWaitAll(void) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* mod = PyImport_ImportModule("mxnet_tpu.ndarray");
  if (!mod) {
    SetErrorFromPython();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(mod, "waitall", nullptr);
  Py_DECREF(mod);
  if (!r) {
    SetErrorFromPython();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXImperativeInvoke(const char* op_name, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys, const char** param_vals) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyObject* o = static_cast<Handle*>(inputs[i])->obj;
    Py_INCREF(o);
    PyList_SetItem(ins, i, o);
  }
  PyObject* keys = PyList_New(num_params);
  PyObject* vals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SetItem(vals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject* res = CallShim("imperative_invoke", "(sOOO)", op_name, ins,
                           keys, vals);
  Py_DECREF(ins);
  Py_DECREF(keys);
  Py_DECREF(vals);
  if (!res) return -1;
  Py_ssize_t n = PyList_Size(res);
  if (*num_outputs != 0) {
    // Reference contract: a nonzero *num_outputs on entry means *outputs
    // points to caller-preallocated handles the op must write INTO
    // (ref src/imperative/imperative.cc out-array path).
    if (*num_outputs != static_cast<int>(n)) {
      SetError("MXImperativeInvoke: op produced " + std::to_string(n) +
               " outputs but caller preallocated " +
               std::to_string(*num_outputs));
      Py_DECREF(res);
      return -1;
    }
    for (Py_ssize_t i = 0; i < n; ++i) {
      Handle* dst = static_cast<Handle*>((*outputs)[i]);
      PyObject* r = CallShim("copy_into", "(OO)", dst->obj,
                             PyList_GetItem(res, i));
      if (!r) {
        Py_DECREF(res);
        return -1;
      }
      Py_DECREF(r);
    }
    Py_DECREF(res);
    return 0;
  }
  NDArrayHandle* arr = static_cast<NDArrayHandle*>(
      std::malloc(sizeof(NDArrayHandle) * n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GetItem(res, i);
    Py_INCREF(o);
    arr[i] = Wrap(o);
  }
  Py_DECREF(res);
  *num_outputs = static_cast<int>(n);
  *outputs = arr;
  return 0;
}

int MXListAllOpNames(mx_uint* out_size, const char*** out_array) {
  if (!EnsurePython()) return -1;
  Gil gil;
  // Per-thread ret store (matches the per-thread MXGetLastError contract;
  // ref keeps these in MXAPIThreadLocalEntry): pointers handed to one
  // thread survive other threads' calls.
  thread_local std::vector<std::string> names;
  thread_local std::vector<const char*> ptrs;
  PyObject* res = CallShim("all_op_names", "()");
  if (!res) return -1;
  names.clear();
  ptrs.clear();
  Py_ssize_t n = PyList_Size(res);
  for (Py_ssize_t i = 0; i < n; ++i)
    names.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(res, i)));
  Py_DECREF(res);
  for (auto& s : names) ptrs.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(ptrs.size());
  *out_array = ptrs.data();
  return 0;
}

int MXNDArraySave(const char* fname, mx_uint num_args,
                  NDArrayHandle* args, const char** keys) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* arrays = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyObject* o = static_cast<Handle*>(args[i])->obj;
    Py_INCREF(o);
    PyList_SetItem(arrays, i, o);
  }
  PyObject* names = PyList_New(keys ? num_args : 0);
  if (keys)
    for (mx_uint i = 0; i < num_args; ++i)
      PyList_SetItem(names, i, PyUnicode_FromString(keys[i]));
  PyObject* r = CallShim("save_list", "(sOO)", fname, arrays, names);
  Py_DECREF(arrays);
  Py_DECREF(names);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                  NDArrayHandle** out_arr, mx_uint* out_name_size,
                  const char*** out_names) {
  if (!EnsurePython()) return -1;
  Gil gil;
  thread_local std::vector<std::string> names;       // per-thread ret store
  thread_local std::vector<const char*> name_ptrs;
  PyObject* res = CallShim("load_file", "(s)", fname);
  if (!res) return -1;
  PyObject* arrays = PyTuple_GetItem(res, 0);
  PyObject* keys = PyTuple_GetItem(res, 1);
  Py_ssize_t n = PyList_Size(arrays);
  NDArrayHandle* arr = static_cast<NDArrayHandle*>(
      std::malloc(sizeof(NDArrayHandle) * n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GetItem(arrays, i);
    Py_INCREF(o);
    arr[i] = Wrap(o);
  }
  names.clear();
  name_ptrs.clear();
  Py_ssize_t nk = PyList_Size(keys);
  for (Py_ssize_t i = 0; i < nk; ++i)
    names.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(keys, i)));
  for (auto& s : names) name_ptrs.push_back(s.c_str());
  Py_DECREF(res);
  *out_size = static_cast<mx_uint>(n);
  *out_arr = arr;
  *out_name_size = static_cast<mx_uint>(name_ptrs.size());
  *out_names = name_ptrs.data();
  return 0;
}

}  // extern "C"

// ---- symbol + executor surface (ref c_api.h MXSymbol* / MXExecutor*
// groups; handles are strong PyObject refs like NDArrayHandle) ----

namespace {

// Per-thread ret store for one string-list-returning call site.
struct StrRet {
  std::vector<std::string> strs;
  std::vector<const char*> ptrs;
  void Fill(PyObject* list) {
    strs.clear();
    ptrs.clear();
    Py_ssize_t n = PyList_Size(list);
    for (Py_ssize_t i = 0; i < n; ++i)
      strs.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(list, i)));
    for (auto& s : strs) ptrs.push_back(s.c_str());
  }
};

// Per-thread ret store for one shape-tuple-list (InferShape group).
struct ShapeRet {
  std::vector<std::vector<mx_uint>> dims;
  std::vector<mx_uint> ndims;
  std::vector<const mx_uint*> ptrs;
  void Fill(PyObject* list) {  // list[tuple[int]]
    dims.clear();
    ndims.clear();
    ptrs.clear();
    Py_ssize_t n = PyList_Size(list);
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* t = PyList_GetItem(list, i);
      Py_ssize_t nd = PyTuple_Size(t);
      std::vector<mx_uint> shape;
      for (Py_ssize_t j = 0; j < nd; ++j)
        shape.push_back(static_cast<mx_uint>(
            PyLong_AsUnsignedLong(PyTuple_GetItem(t, j))));
      dims.push_back(std::move(shape));
      ndims.push_back(static_cast<mx_uint>(nd));
    }
    for (auto& d : dims) ptrs.push_back(d.data());
  }
};

int WrapResult(PyObject* obj, void** out) {
  if (!obj) return -1;
  *out = Wrap(obj);
  return 0;
}

PyObject* ShapesToPyList(mx_uint num, const mx_uint* ndims,
                         const mx_uint* flat) {
  PyObject* shapes = PyList_New(num);
  mx_uint off = 0;
  for (mx_uint i = 0; i < num; ++i) {
    PyObject* t = PyTuple_New(ndims[i]);
    for (mx_uint j = 0; j < ndims[i]; ++j)
      PyTuple_SetItem(t, j, PyLong_FromUnsignedLong(flat[off + j]));
    off += ndims[i];
    PyList_SetItem(shapes, i, t);
  }
  return shapes;
}

}  // namespace

extern "C" {

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  if (!EnsurePython()) return -1;
  Gil gil;
  return WrapResult(CallShim("symbol_from_json", "(s)", json), out);
}

int MXSymbolCreateFromFile(const char* fname, SymbolHandle* out) {
  if (!EnsurePython()) return -1;
  Gil gil;
  return WrapResult(CallShim("symbol_from_file", "(s)", fname), out);
}

int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json) {
  Gil gil;
  thread_local std::string json;
  PyObject* r = CallShim("symbol_to_json", "(O)",
                         static_cast<Handle*>(sym)->obj);
  if (!r) return -1;
  json = PyUnicode_AsUTF8(r);
  Py_DECREF(r);
  *out_json = json.c_str();
  return 0;
}

int MXSymbolSaveToFile(SymbolHandle sym, const char* fname) {
  Gil gil;
  PyObject* r = CallShim("symbol_save_file", "(Os)",
                         static_cast<Handle*>(sym)->obj, fname);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int MXSymbolFree(SymbolHandle sym) { return MXNDArrayFree(sym); }

int MXSymbolCreateVariable(const char* name, SymbolHandle* out) {
  if (!EnsurePython()) return -1;
  Gil gil;
  return WrapResult(CallShim("symbol_variable", "(s)", name), out);
}

int MXSymbolCreateAtomicSymbol(const char* op_name, mx_uint num_param,
                               const char** keys, const char** vals,
                               SymbolHandle* out) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* k = PyList_New(num_param);
  PyObject* v = PyList_New(num_param);
  for (mx_uint i = 0; i < num_param; ++i) {
    PyList_SetItem(k, i, PyUnicode_FromString(keys[i]));
    PyList_SetItem(v, i, PyUnicode_FromString(vals[i]));
  }
  PyObject* r = CallShim("symbol_create_atomic", "(sOO)", op_name, k, v);
  Py_DECREF(k);
  Py_DECREF(v);
  return WrapResult(r, out);
}

int MXSymbolCompose(SymbolHandle sym, const char* name, mx_uint num_args,
                    const char** keys, SymbolHandle* args) {
  Gil gil;
  Handle* h = static_cast<Handle*>(sym);
  PyObject* k = PyList_New(keys ? num_args : 0);
  if (keys)
    for (mx_uint i = 0; i < num_args; ++i)
      PyList_SetItem(k, i, PyUnicode_FromString(keys[i]));
  PyObject* a = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyObject* o = static_cast<Handle*>(args[i])->obj;
    Py_INCREF(o);
    PyList_SetItem(a, i, o);
  }
  PyObject* r = CallShim("symbol_compose", "(OsOO)", h->obj,
                         name ? name : "", k, a);
  Py_DECREF(k);
  Py_DECREF(a);
  if (!r) return -1;
  Py_DECREF(h->obj);   // in-place rebind, reference Compose semantics
  h->obj = r;
  return 0;
}

static int SymbolListImpl(SymbolHandle sym, const char* what, StrRet& ret,
                          mx_uint* out_size, const char*** out_array) {
  Gil gil;
  PyObject* r = CallShim("symbol_list", "(Os)",
                         static_cast<Handle*>(sym)->obj, what);
  if (!r) return -1;
  ret.Fill(r);
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(ret.ptrs.size());
  *out_array = ret.ptrs.data();
  return 0;
}

int MXSymbolListArguments(SymbolHandle sym, mx_uint* out_size,
                          const char*** out_array) {
  thread_local StrRet ret;
  return SymbolListImpl(sym, "arguments", ret, out_size, out_array);
}

int MXSymbolListOutputs(SymbolHandle sym, mx_uint* out_size,
                        const char*** out_array) {
  thread_local StrRet ret;
  return SymbolListImpl(sym, "outputs", ret, out_size, out_array);
}

int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint* out_size,
                                const char*** out_array) {
  thread_local StrRet ret;
  return SymbolListImpl(sym, "auxiliary", ret, out_size, out_array);
}

int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                       const char** keys, const mx_uint* arg_ind_ptr,
                       const mx_uint* arg_shape_data,
                       mx_uint* in_shape_size,
                       const mx_uint** in_shape_ndim,
                       const mx_uint*** in_shape_data,
                       mx_uint* out_shape_size,
                       const mx_uint** out_shape_ndim,
                       const mx_uint*** out_shape_data,
                       mx_uint* aux_shape_size,
                       const mx_uint** aux_shape_ndim,
                       const mx_uint*** aux_shape_data, int* complete) {
  Gil gil;
  thread_local ShapeRet in_ret, out_ret, aux_ret;
  PyObject* k = PyList_New(num_args);
  PyObject* shapes = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SetItem(k, i, PyUnicode_FromString(keys[i]));
    mx_uint nd = arg_ind_ptr[i + 1] - arg_ind_ptr[i];
    PyObject* t = PyTuple_New(nd);
    for (mx_uint j = 0; j < nd; ++j)
      PyTuple_SetItem(t, j, PyLong_FromUnsignedLong(
          arg_shape_data[arg_ind_ptr[i] + j]));
    PyList_SetItem(shapes, i, t);
  }
  PyObject* r = CallShim("symbol_infer_shape", "(OOO)",
                         static_cast<Handle*>(sym)->obj, k, shapes);
  Py_DECREF(k);
  Py_DECREF(shapes);
  if (!r) return -1;
  in_ret.Fill(PyTuple_GetItem(r, 0));
  out_ret.Fill(PyTuple_GetItem(r, 1));
  aux_ret.Fill(PyTuple_GetItem(r, 2));
  *complete = PyObject_IsTrue(PyTuple_GetItem(r, 3));
  Py_DECREF(r);
  *in_shape_size = static_cast<mx_uint>(in_ret.ndims.size());
  *in_shape_ndim = in_ret.ndims.data();
  *in_shape_data = in_ret.ptrs.data();
  *out_shape_size = static_cast<mx_uint>(out_ret.ndims.size());
  *out_shape_ndim = out_ret.ndims.data();
  *out_shape_data = out_ret.ptrs.data();
  *aux_shape_size = static_cast<mx_uint>(aux_ret.ndims.size());
  *aux_shape_ndim = aux_ret.ndims.data();
  *aux_shape_data = aux_ret.ptrs.data();
  return 0;
}

int MXExecutorSimpleBind(SymbolHandle sym, int dev_type, int dev_id,
                         mx_uint num_args, const char** keys,
                         const mx_uint* arg_ndims, const mx_uint* arg_dims,
                         const char* grad_req, ExecutorHandle* out) {
  Gil gil;
  PyObject* k = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i)
    PyList_SetItem(k, i, PyUnicode_FromString(keys[i]));
  PyObject* shapes = ShapesToPyList(num_args, arg_ndims, arg_dims);
  PyObject* r = CallShim("executor_simple_bind", "(OiiOOs)",
                         static_cast<Handle*>(sym)->obj, dev_type, dev_id,
                         k, shapes, grad_req);
  Py_DECREF(k);
  Py_DECREF(shapes);
  return WrapResult(r, out);
}

int MXExecutorFree(ExecutorHandle exec) { return MXNDArrayFree(exec); }

int MXExecutorForward(ExecutorHandle exec, int is_train) {
  Gil gil;
  PyObject* r = CallShim("executor_forward", "(Oi)",
                         static_cast<Handle*>(exec)->obj, is_train);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int MXExecutorBackward(ExecutorHandle exec, mx_uint num_ograds,
                       NDArrayHandle* out_grads) {
  Gil gil;
  PyObject* g = PyList_New(num_ograds);
  for (mx_uint i = 0; i < num_ograds; ++i) {
    PyObject* o = static_cast<Handle*>(out_grads[i])->obj;
    Py_INCREF(o);
    PyList_SetItem(g, i, o);
  }
  PyObject* r = CallShim("executor_backward", "(OO)",
                         static_cast<Handle*>(exec)->obj, g);
  Py_DECREF(g);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int MXExecutorOutputs(ExecutorHandle exec, mx_uint* out_size,
                      NDArrayHandle** out) {
  Gil gil;
  PyObject* r = CallShim("executor_outputs", "(O)",
                         static_cast<Handle*>(exec)->obj);
  if (!r) return -1;
  Py_ssize_t n = PyList_Size(r);
  NDArrayHandle* arr = static_cast<NDArrayHandle*>(
      std::malloc(sizeof(NDArrayHandle) * n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GetItem(r, i);
    Py_INCREF(o);
    arr[i] = Wrap(o);
  }
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(n);
  *out = arr;
  return 0;
}

static int ExecArrayImpl(ExecutorHandle exec, const char* kind,
                         const char* name, NDArrayHandle* out) {
  Gil gil;
  return WrapResult(CallShim("executor_array", "(Oss)",
                             static_cast<Handle*>(exec)->obj, kind, name),
                    out);
}

int MXExecutorArgArray(ExecutorHandle exec, const char* name,
                       NDArrayHandle* out) {
  return ExecArrayImpl(exec, "arg", name, out);
}

int MXExecutorGradArray(ExecutorHandle exec, const char* name,
                        NDArrayHandle* out) {
  return ExecArrayImpl(exec, "grad", name, out);
}

int MXExecutorAuxArray(ExecutorHandle exec, const char* name,
                       NDArrayHandle* out) {
  return ExecArrayImpl(exec, "aux", name, out);
}

// ---- autograd surface (ref c_api.h MXAutograd* group) ----

static int AutogradFlagImpl(const char* fn, int value, int* prev) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* r = CallShim(fn, "(i)", value);
  if (!r) return -1;
  if (prev) *prev = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXAutogradSetIsRecording(int is_recording, int* prev) {
  return AutogradFlagImpl("autograd_set_recording", is_recording, prev);
}

int MXAutogradSetIsTraining(int is_training, int* prev) {
  return AutogradFlagImpl("autograd_set_training", is_training, prev);
}

static int AutogradGetImpl(const char* fn, int* curr) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* r = CallShim(fn, "()");
  if (!r) return -1;
  *curr = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXAutogradIsRecording(int* curr) {
  return AutogradGetImpl("autograd_is_recording", curr);
}

int MXAutogradIsTraining(int* curr) {
  return AutogradGetImpl("autograd_is_training", curr);
}

int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle* var_handles,
                            mx_uint* reqs_array,
                            NDArrayHandle* grad_handles) {
  Gil gil;
  PyObject* vars = PyList_New(num_var);
  PyObject* grads = PyList_New(num_var);
  PyObject* reqs = PyList_New(num_var);
  for (mx_uint i = 0; i < num_var; ++i) {
    PyObject* v = static_cast<Handle*>(var_handles[i])->obj;
    PyObject* g = static_cast<Handle*>(grad_handles[i])->obj;
    Py_INCREF(v);
    Py_INCREF(g);
    PyList_SetItem(vars, i, v);
    PyList_SetItem(grads, i, g);
    // reference OpReqType codes: 0=null, 1=write, 2=write-inplace
    // (treated as write), 3=add
    const char* req = reqs_array[i] == 3 ? "add"
                      : (reqs_array[i] == 0 ? "null" : "write");
    PyList_SetItem(reqs, i, PyUnicode_FromString(req));
  }
  PyObject* r = CallShim("autograd_mark_variables", "(OOO)", vars, grads,
                         reqs);
  Py_DECREF(vars);
  Py_DECREF(grads);
  Py_DECREF(reqs);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int MXAutogradBackwardEx(mx_uint num_output, NDArrayHandle* output_handles,
                         NDArrayHandle* ograd_handles, int retain_graph,
                         int train_mode) {
  Gil gil;
  PyObject* outs = PyList_New(num_output);
  for (mx_uint i = 0; i < num_output; ++i) {
    PyObject* o = static_cast<Handle*>(output_handles[i])->obj;
    Py_INCREF(o);
    PyList_SetItem(outs, i, o);
  }
  PyObject* ogs;
  if (ograd_handles) {
    ogs = PyList_New(num_output);
    for (mx_uint i = 0; i < num_output; ++i) {
      if (ograd_handles[i]) {
        PyObject* o = static_cast<Handle*>(ograd_handles[i])->obj;
        Py_INCREF(o);
        PyList_SetItem(ogs, i, o);
      } else {
        // NULL slot = ones_like default for that head (ref contract)
        Py_INCREF(Py_None);
        PyList_SetItem(ogs, i, Py_None);
      }
    }
  } else {
    ogs = PyList_New(0);
  }
  PyObject* r = CallShim("autograd_backward", "(OOii)", outs, ogs,
                         retain_graph, train_mode);
  Py_DECREF(outs);
  Py_DECREF(ogs);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

// ---- data-iterator surface (ref c_api.h MXDataIter* group) ----

int MXListDataIters(mx_uint* out_size, const char*** out_array) {
  if (!EnsurePython()) return -1;
  Gil gil;
  thread_local StrRet ret;
  PyObject* r = CallShim("list_data_iters", "()");
  if (!r) return -1;
  ret.Fill(r);
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(ret.ptrs.size());
  *out_array = ret.ptrs.data();
  return 0;
}

int MXDataIterCreateIter(const char* name, mx_uint num_param,
                         const char** keys, const char** vals,
                         DataIterHandle* out) {
  if (!EnsurePython()) return -1;
  Gil gil;
  PyObject* k = PyList_New(num_param);
  PyObject* v = PyList_New(num_param);
  for (mx_uint i = 0; i < num_param; ++i) {
    PyList_SetItem(k, i, PyUnicode_FromString(keys[i]));
    PyList_SetItem(v, i, PyUnicode_FromString(vals[i]));
  }
  PyObject* r = CallShim("data_iter_create", "(sOO)", name, k, v);
  Py_DECREF(k);
  Py_DECREF(v);
  return WrapResult(r, out);
}

int MXDataIterFree(DataIterHandle handle) { return MXNDArrayFree(handle); }

int MXDataIterBeforeFirst(DataIterHandle handle) {
  Gil gil;
  PyObject* r = CallShim("data_iter_before_first", "(O)",
                         static_cast<Handle*>(handle)->obj);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int MXDataIterNext(DataIterHandle handle, int* out) {
  Gil gil;
  PyObject* r = CallShim("data_iter_next", "(O)",
                         static_cast<Handle*>(handle)->obj);
  if (!r) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

static int DataIterGetImpl(DataIterHandle handle, const char* what,
                           NDArrayHandle* out) {
  Gil gil;
  return WrapResult(CallShim("data_iter_get", "(Os)",
                             static_cast<Handle*>(handle)->obj, what),
                    out);
}

int MXDataIterGetData(DataIterHandle handle, NDArrayHandle* out) {
  return DataIterGetImpl(handle, "data", out);
}

int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle* out) {
  return DataIterGetImpl(handle, "label", out);
}

int MXDataIterGetPadNum(DataIterHandle handle, int* pad) {
  Gil gil;
  PyObject* r = CallShim("data_iter_pad", "(O)",
                         static_cast<Handle*>(handle)->obj);
  if (!r) return -1;
  *pad = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

// ---- kvstore surface (ref c_api.h MXKVStore* string-key group) ----

namespace {

// (keys, handles) -> (PyList[str], PyList[NDArray]); both new refs.
void KvLists(mx_uint num, const char** keys, NDArrayHandle* arrs,
             PyObject** k_out, PyObject** v_out) {
  PyObject* k = PyList_New(num);
  PyObject* v = PyList_New(num);
  for (mx_uint i = 0; i < num; ++i) {
    PyList_SetItem(k, i, PyUnicode_FromString(keys[i]));
    PyObject* o = static_cast<Handle*>(arrs[i])->obj;
    Py_INCREF(o);
    PyList_SetItem(v, i, o);
  }
  *k_out = k;
  *v_out = v;
}

}  // namespace

int MXKVStoreCreate(const char* type, KVStoreHandle* out) {
  if (!EnsurePython()) return -1;
  Gil gil;
  return WrapResult(CallShim("kv_create", "(s)", type), out);
}

int MXKVStoreFree(KVStoreHandle kv) { return MXNDArrayFree(kv); }

int MXKVStoreGetType(KVStoreHandle kv, const char** out_type) {
  Gil gil;
  thread_local std::string type;
  PyObject* r = CallShim("kv_type", "(O)", static_cast<Handle*>(kv)->obj);
  if (!r) return -1;
  type = PyUnicode_AsUTF8(r);
  Py_DECREF(r);
  *out_type = type.c_str();
  return 0;
}

static int KvIntImpl(KVStoreHandle kv, const char* fn, int* out) {
  Gil gil;
  PyObject* r = CallShim(fn, "(O)", static_cast<Handle*>(kv)->obj);
  if (!r) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXKVStoreGetRank(KVStoreHandle kv, int* out_rank) {
  return KvIntImpl(kv, "kv_rank", out_rank);
}

int MXKVStoreGetGroupSize(KVStoreHandle kv, int* out_size) {
  return KvIntImpl(kv, "kv_group_size", out_size);
}

static int KvOpImpl(KVStoreHandle kv, const char* fn, mx_uint num,
                    const char** keys, NDArrayHandle* arrs, int priority,
                    bool with_priority) {
  Gil gil;
  PyObject *k, *v;
  KvLists(num, keys, arrs, &k, &v);
  PyObject* r = with_priority
      ? CallShim(fn, "(OOOi)", static_cast<Handle*>(kv)->obj, k, v,
                 priority)
      : CallShim(fn, "(OOO)", static_cast<Handle*>(kv)->obj, k, v);
  Py_DECREF(k);
  Py_DECREF(v);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int MXKVStoreInitEx(KVStoreHandle kv, mx_uint num, const char** keys,
                    NDArrayHandle* values) {
  return KvOpImpl(kv, "kv_init", num, keys, values, 0, false);
}

int MXKVStorePushEx(KVStoreHandle kv, mx_uint num, const char** keys,
                    NDArrayHandle* values, int priority) {
  return KvOpImpl(kv, "kv_push", num, keys, values, priority, true);
}

int MXKVStorePullEx(KVStoreHandle kv, mx_uint num, const char** keys,
                    NDArrayHandle* outs, int priority) {
  return KvOpImpl(kv, "kv_pull", num, keys, outs, priority, true);
}

int MXKVStoreBarrier(KVStoreHandle kv) {
  Gil gil;
  PyObject* r = CallShim("kv_barrier", "(O)",
                         static_cast<Handle*>(kv)->obj);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

int MXExecutorCopyParamsFrom(ExecutorHandle exec, mx_uint num,
                             const char** names, NDArrayHandle* arrays) {
  Gil gil;
  PyObject* n = PyList_New(num);
  PyObject* a = PyList_New(num);
  for (mx_uint i = 0; i < num; ++i) {
    PyList_SetItem(n, i, PyUnicode_FromString(names[i]));
    PyObject* o = static_cast<Handle*>(arrays[i])->obj;
    Py_INCREF(o);
    PyList_SetItem(a, i, o);
  }
  PyObject* r = CallShim("executor_copy_params", "(OOO)",
                         static_cast<Handle*>(exec)->obj, n, a);
  Py_DECREF(n);
  Py_DECREF(a);
  if (!r) return -1;
  Py_DECREF(r);
  return 0;
}

}  // extern "C"
