// Threaded dependency engine for host-side task scheduling.
//
// Reference analogue: the dependency engine of
// include/mxnet/engine.h:95-280 and src/engine/threaded_engine.{h,cc} —
// every async task declares const (read) and mutable (write) variables;
// the engine keeps a per-variable FIFO of pending blocks and dispatches a
// task once all of its dependencies resolve.  Observable contract
// (SURVEY §3.3): tasks issue asynchronously; writes to one variable
// serialize in push order; reads between writes run in parallel;
// WaitForVar blocks until pending writes land; WaitForAll drains; deleted
// variables are garbage-collected only after their last pending task.
//
// TPU-native scope: device-side scheduling belongs to XLA/PJRT (async
// dispatch, buffer liveness).  This engine schedules *host-side* work —
// prefetch/decode pipelines, checkpoint IO, parameter-server transport —
// under the same protocol, replacing the reference's use of the engine for
// IO and kvstore tasks.  Exposed as a flat C ABI (the C-API layer of
// SURVEY §1 row 9) and bound from Python via ctypes.
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

typedef void (*EngineTaskFn)(void* arg);

struct Task;

// One scheduling block in a variable's pending queue.
struct VarBlock {
  Task* task;
  bool write;
};

// A scheduling variable.  `reads_live` counts dispatched-but-unfinished
// readers at the queue head; `write_live` marks a dispatched writer.
struct Var {
  std::deque<VarBlock> pending;
  int reads_live = 0;
  bool write_live = false;
  bool doomed = false;  // delete requested; GC once drained
};

struct Task {
  EngineTaskFn fn = nullptr;
  void* arg = nullptr;
  std::vector<int64_t> reads;
  std::vector<int64_t> writes;
  int deps = 0;        // unresolved dependency count (+1 setup sentinel)
  int priority = 0;
  uint64_t seq = 0;    // FIFO tiebreak
  bool is_waiter = false;          // internal WaitForVar marker task
  std::condition_variable* done_cv = nullptr;
  bool* done_flag = nullptr;
};

struct TaskOrder {
  bool operator()(const Task* a, const Task* b) const {
    if (a->priority != b->priority) return a->priority < b->priority;
    return a->seq > b->seq;  // lower seq first
  }
};

class Engine {
 public:
  explicit Engine(int num_workers, bool sync)
      : sync_(sync) {
    if (num_workers < 1) num_workers = 1;
    for (int i = 0; i < num_workers; ++i)
      workers_.emplace_back([this]() { WorkerLoop(); });
    workers_.emplace_back([this]() { InlineLoop(); });
  }

  ~Engine() {
    WaitForAll();
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
      ready_cv_.notify_all();
      inline_cv_.notify_all();
    }
    for (auto& t : workers_) t.join();
  }

  int64_t NewVar() {
    std::unique_lock<std::mutex> lk(mu_);
    int64_t id = next_var_++;
    vars_.emplace(id, Var());
    return id;
  }

  // Queue deletion behind everything already pushed on the variable.
  void DeleteVar(int64_t var) {
    Task* t = new Task();
    t->writes.push_back(var);
    t->fn = nullptr;
    {
      std::unique_lock<std::mutex> lk(mu_);
      auto it = vars_.find(var);
      if (it == vars_.end()) { delete t; return; }
      it->second.doomed = true;
    }
    Push(t);
  }

  void PushTask(EngineTaskFn fn, void* arg,
                const int64_t* reads, int nreads,
                const int64_t* writes, int nwrites, int priority) {
    if (sync_) {
      // NaiveEngine semantics (ref naive_engine.cc:95-130): execute
      // inline, serially, in push order.  Drain any async backlog first
      // — except when pushed from inside one of THIS engine's running
      // tasks, where waiting on ourselves would deadlock; serial order
      // is preserved anyway because the parent task runs inline too.
      if (tls_worker_engine_ != this) WaitForAll();
      if (fn) fn(arg);
      return;
    }
    Task* t = new Task();
    t->fn = fn;
    t->arg = arg;
    t->reads.assign(reads, reads + nreads);
    t->writes.assign(writes, writes + nwrites);
    t->priority = priority;
    Push(t);
  }

  void WaitForVar(int64_t var) {
    std::condition_variable cv;
    bool done = false;
    Task* t = new Task();
    t->reads.push_back(var);  // runs only after queued writes complete
    t->is_waiter = true;
    t->done_cv = &cv;
    t->done_flag = &done;
    Push(t);
    std::unique_lock<std::mutex> lk(mu_);
    cv.wait(lk, [&]() { return done; });
  }

  void WaitForAll() {
    std::unique_lock<std::mutex> lk(mu_);
    drained_cv_.wait(lk, [this]() { return live_tasks_ == 0; });
  }

  int PendingTasks() {
    std::unique_lock<std::mutex> lk(mu_);
    return live_tasks_;
  }

  void SetSync(bool sync) { sync_ = sync; }

 private:
  // Resolve dependencies and hand the task to the scheduler.  A +1
  // sentinel on `deps` keeps the task from firing while its own
  // dependency list is still being walked.  Dependency lists are
  // normalized first (the reference's Engine::DeduplicateVarHandle,
  // engine.h:251-269): duplicate vars collapse, and a var that appears
  // in both lists counts only as a write — otherwise the task would
  // deadlock waiting on its own read.
  void Push(Task* t) {
    Dedupe(&t->writes);
    Dedupe(&t->reads);
    t->reads.erase(
        std::remove_if(t->reads.begin(), t->reads.end(),
                       [&](int64_t r) {
                         return std::find(t->writes.begin(), t->writes.end(),
                                          r) != t->writes.end();
                       }),
        t->reads.end());
    std::unique_lock<std::mutex> lk(mu_);
    ++live_tasks_;
    t->seq = next_seq_++;
    t->deps = 1;
    for (int64_t v : t->reads) AddRead(v, t);
    for (int64_t v : t->writes) AddWrite(v, t);
    if (--t->deps == 0) Ready(t);
  }

  static void Dedupe(std::vector<int64_t>* v) {
    std::sort(v->begin(), v->end());
    v->erase(std::unique(v->begin(), v->end()), v->end());
  }

  void AddRead(int64_t vid, Task* t) {
    auto it = vars_.find(vid);
    if (it == vars_.end()) return;  // unknown/GC'd var: no dependency
    Var& v = it->second;
    if (v.pending.empty() && !v.write_live) {
      ++v.reads_live;  // no write ahead: read proceeds immediately
    } else {
      ++t->deps;
      v.pending.push_back({t, false});
    }
  }

  void AddWrite(int64_t vid, Task* t) {
    auto it = vars_.find(vid);
    if (it == vars_.end()) return;  // unknown/GC'd var: no dependency
    Var& v = it->second;
    if (v.pending.empty() && !v.write_live && v.reads_live == 0) {
      v.write_live = true;
    } else {
      ++t->deps;
      v.pending.push_back({t, true});
    }
  }

  void Ready(Task* t) {  // mu_ held
    if (t->is_waiter || t->fn == nullptr) {
      // Waiter/GC tasks carry no user work: a dedicated completion thread
      // handles them so a saturated worker pool can never stall WaitForVar.
      inline_ready_.push_back(t);
      inline_cv_.notify_one();
      return;
    }
    ready_.push(t);
    ready_cv_.notify_one();
  }

  // Dependency completion: mirror of the reference's
  // CompleteReadDependency / CompleteWriteDependency.
  void FinishRead(int64_t vid) {
    auto it = vars_.find(vid);
    if (it == vars_.end()) return;
    Var& v = it->second;
    --v.reads_live;
    if (v.reads_live == 0 && !v.pending.empty() && v.pending.front().write) {
      Task* nxt = v.pending.front().task;
      v.pending.pop_front();
      v.write_live = true;
      if (--nxt->deps == 0) Ready(nxt);
    }
    if (v.doomed && v.pending.empty() && !v.write_live && v.reads_live == 0)
      vars_.erase(it);
  }

  void FinishWrite(int64_t vid) {
    auto it = vars_.find(vid);
    if (it == vars_.end()) return;
    Var& v = it->second;
    v.write_live = false;
    // Release the run of reads at the head; stop at (or dispatch) the
    // next write.
    while (!v.pending.empty()) {
      VarBlock blk = v.pending.front();
      if (blk.write) {
        if (v.reads_live == 0) {
          v.pending.pop_front();
          v.write_live = true;
          if (--blk.task->deps == 0) Ready(blk.task);
        }
        break;
      }
      v.pending.pop_front();
      ++v.reads_live;
      if (--blk.task->deps == 0) Ready(blk.task);
    }
    if (v.doomed && v.pending.empty() && !v.write_live && v.reads_live == 0)
      vars_.erase(it);
  }

  void Complete(Task* t) {
    std::unique_lock<std::mutex> lk(mu_);
    for (int64_t v : t->reads) FinishRead(v);
    for (int64_t v : t->writes) FinishWrite(v);
    if (t->done_flag) {
      *t->done_flag = true;
      t->done_cv->notify_all();
    }
    --live_tasks_;
    if (live_tasks_ == 0) drained_cv_.notify_all();
    delete t;
  }

  void WorkerLoop() {
    for (;;) {
      Task* t;
      {
        std::unique_lock<std::mutex> lk(mu_);
        ready_cv_.wait(lk, [this]() { return stop_ || !ready_.empty(); });
        if (stop_ && ready_.empty()) return;
        t = ready_.top();
        ready_.pop();
      }
      tls_worker_engine_ = this;
      if (t->fn) t->fn(t->arg);
      tls_worker_engine_ = nullptr;
      Complete(t);
    }
  }

  // Waiter/GC tasks complete here so a full worker pool can never
  // deadlock a WaitForVar behind user tasks it depends on.
  void InlineLoop() {
    for (;;) {
      Task* t;
      {
        std::unique_lock<std::mutex> lk(mu_);
        inline_cv_.wait(lk,
                        [this]() { return stop_ || !inline_ready_.empty(); });
        if (stop_ && inline_ready_.empty()) return;
        t = inline_ready_.front();
        inline_ready_.pop_front();
      }
      Complete(t);
    }
  }

  std::mutex mu_;
  std::condition_variable ready_cv_, drained_cv_, inline_cv_;
  std::priority_queue<Task*, std::vector<Task*>, TaskOrder> ready_;
  std::deque<Task*> inline_ready_;
  std::unordered_map<int64_t, Var> vars_;
  std::vector<std::thread> workers_;
  int64_t next_var_ = 1;
  uint64_t next_seq_ = 0;
  int live_tasks_ = 0;
  bool stop_ = false;
  std::atomic<bool> sync_;
  static thread_local Engine* tls_worker_engine_;
};

thread_local Engine* Engine::tls_worker_engine_ = nullptr;

}  // namespace

extern "C" {

void* MXEngineCreate(int num_workers, int sync) {
  return new Engine(num_workers, sync != 0);
}

void MXEngineFree(void* h) { delete static_cast<Engine*>(h); }

// Drain + free on a detached thread.  Safe to call from anywhere —
// including one of the engine's own worker threads (a GC finalizer can
// fire mid-task), where a synchronous drain would self-deadlock.
void MXEngineFreeAsync(void* h) {
  std::thread([h]() { delete static_cast<Engine*>(h); }).detach();
}

int64_t MXEngineNewVariable(void* h) {
  return static_cast<Engine*>(h)->NewVar();
}

void MXEngineDeleteVariable(void* h, int64_t var) {
  static_cast<Engine*>(h)->DeleteVar(var);
}

void MXEnginePushAsync(void* h, EngineTaskFn fn, void* arg,
                       const int64_t* const_vars, int n_const,
                       const int64_t* mutable_vars, int n_mutable,
                       int priority) {
  static_cast<Engine*>(h)->PushTask(fn, arg, const_vars, n_const,
                                    mutable_vars, n_mutable, priority);
}

void MXEngineWaitForVar(void* h, int64_t var) {
  static_cast<Engine*>(h)->WaitForVar(var);
}

void MXEngineWaitForAll(void* h) { static_cast<Engine*>(h)->WaitForAll(); }

int MXEnginePendingTasks(void* h) {
  return static_cast<Engine*>(h)->PendingTasks();
}

void MXEngineSetSync(void* h, int sync) {
  static_cast<Engine*>(h)->SetSync(sync != 0);
}

}  // extern "C"
