// Native threaded image-record loader: the TPU build's equivalent of the
// reference's multithreaded decode pipeline (src/io/iter_image_recordio_2.cc
// — M decoder threads + prefetcher, SURVEY §2.1 "Data IO (native)").
//
// One pass at create() indexes the .rec file (record offsets/lengths).
// next() hands back the batch assembled in the background and immediately
// starts decoding the following batch: N worker threads each pread() their
// records, parse the IRHeader (recordio.py layout: <I flag><f label>
// <Q id><Q id2>[flag * float extra labels]<jpeg bytes>), JPEG-decode via
// libjpeg, bilinear-resize to the target geometry, optionally mirror, and
// write float32 CHW rows scaled to [0, 1].
//
// C ABI (ctypes-consumed by mxnet_tpu/image/native_iter.py):
//   mx_imgloader_create(rec, batch, h, w, c, threads, shuffle, seed, mirror)
//   mx_imgloader_num_samples(h)
//   mx_imgloader_next(h, float* data, float* labels) -> n valid (0 = epoch end)
//   mx_imgloader_last_failed(h) -> decode failures behind the last next()
//   mx_imgloader_failures(h)    -> cumulative decode failures
//   mx_imgloader_reset(h)
//   mx_imgloader_destroy(h)
//
// Build: make -C native  →  mxnet_tpu/_native/libimageloader.so
#include <fcntl.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <future>
#include <random>
#include <thread>
#include <vector>

#include <csetjmp>
#include <jpeglib.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void jpeg_bail(j_common_ptr cinfo) {
  longjmp(reinterpret_cast<JpegErr*>(cinfo->err)->jump, 1);
}

// Decode JPEG bytes to packed RGB; returns false on corrupt input.
bool decode_jpeg(const unsigned char* buf, size_t len,
                 std::vector<unsigned char>* rgb, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JpegErr err;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = jpeg_bail;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  rgb->resize(static_cast<size_t>(*w) * *h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = rgb->data() +
        static_cast<size_t>(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// Bilinear src(RGB, sh x sw) → dst float CHW (c x dh x dw), scaled 1/255.
void resize_to_chw(const unsigned char* src, int sw, int sh, float* dst,
                   int dw, int dh, int channels, bool mirror) {
  const float sx = static_cast<float>(sw) / dw;
  const float sy = static_cast<float>(sh) / dh;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    int y0 = fy < 0 ? 0 : static_cast<int>(fy);
    if (y0 > sh - 1) y0 = sh - 1;
    int y1 = y0 + 1 > sh - 1 ? sh - 1 : y0 + 1;
    float wy = fy - y0;
    if (wy < 0) wy = 0;
    for (int x = 0; x < dw; ++x) {
      int xe = mirror ? (dw - 1 - x) : x;
      float fx = (xe + 0.5f) * sx - 0.5f;
      int x0 = fx < 0 ? 0 : static_cast<int>(fx);
      if (x0 > sw - 1) x0 = sw - 1;
      int x1 = x0 + 1 > sw - 1 ? sw - 1 : x0 + 1;
      float wx = fx - x0;
      if (wx < 0) wx = 0;
      for (int ch = 0; ch < channels; ++ch) {
        int c3 = ch < 3 ? ch : 2;
        float v00 = src[(static_cast<size_t>(y0) * sw + x0) * 3 + c3];
        float v01 = src[(static_cast<size_t>(y0) * sw + x1) * 3 + c3];
        float v10 = src[(static_cast<size_t>(y1) * sw + x0) * 3 + c3];
        float v11 = src[(static_cast<size_t>(y1) * sw + x1) * 3 + c3];
        float v = (1 - wy) * ((1 - wx) * v00 + wx * v01) +
                  wy * ((1 - wx) * v10 + wx * v11);
        dst[(static_cast<size_t>(ch) * dh + y) * dw + x] = v / 255.0f;
      }
    }
  }
}

struct Rec {
  int64_t off;
  uint32_t len;
};

struct Batch {
  std::vector<float> data;
  std::vector<float> labels;
  int n = 0;
  int failed = 0;   // records of THIS batch that failed to decode
};

struct Loader {
  int fd = -1;
  int batch, h, w, c, threads, shuffle, mirror;
  std::atomic<long> failures{0};   // cumulative decode failures
  int last_failed = 0;             // failures of the batch last returned
  std::mt19937 rng;
  std::vector<Rec> recs;
  std::vector<uint32_t> order;
  size_t cursor = 0;
  Batch bufs[2];
  int cur = 0;
  std::future<void> pending;

  ~Loader() {
    if (pending.valid()) pending.wait();
    if (fd >= 0) close(fd);
  }

  void index_records() {
    FILE* f = fdopen(dup(fd), "rb");
    if (!f) return;
    setvbuf(f, nullptr, _IOFBF, 1 << 20);
    int64_t pos = 0;
    uint32_t head[2];
    while (fread(head, sizeof(uint32_t), 2, f) == 2) {
      if (head[0] != kMagic) break;
      uint32_t len = head[1] & ((1u << 29) - 1);
      recs.push_back({pos + 8, len});
      uint32_t pad = (4 - (len % 4)) % 4;
      pos += 8 + len + pad;
      if (fseek(f, pos, SEEK_SET) != 0) break;
    }
    fclose(f);
    order.resize(recs.size());
    for (uint32_t i = 0; i < order.size(); ++i) order[i] = i;
  }

  // Returns true on success; failures leave the slot zeroed (the
  // caller compacts them out of the batch).
  bool decode_one(uint32_t rec_idx, Batch* out, int slot, bool flip) {
    const Rec& r = recs[rec_idx];
    std::vector<unsigned char> raw(r.len);
    if (pread(fd, raw.data(), r.len, r.off) !=
        static_cast<ssize_t>(r.len))
      return false;
    if (r.len < 24) return false;
    uint32_t flag;
    float label;
    std::memcpy(&flag, raw.data(), 4);
    std::memcpy(&label, raw.data() + 4, 4);
    size_t skip = 24 + static_cast<size_t>(flag > 0 ? flag : 0) * 4;
    if (flag > 0 && r.len >= skip)
      std::memcpy(&label, raw.data() + 24, 4);   // first extended label
    if (r.len <= skip) return false;
    std::vector<unsigned char> rgb;
    int sw = 0, sh = 0;
    if (!decode_jpeg(raw.data() + skip, r.len - skip, &rgb, &sw, &sh))
      return false;   // corrupt or non-JPEG payload
    float* dst = out->data.data() +
        static_cast<size_t>(slot) * c * h * w;
    resize_to_chw(rgb.data(), sw, sh, dst, w, h, c, flip);
    out->labels[slot] = label;
    return true;
  }

  // Assemble one batch into *out (parallel across `threads` workers).
  // Corrupt records are dropped and the batch is TOPPED UP from the
  // records that follow (the reference iterator's read-ahead-past-
  // corrupt behavior): out->n is short only at true end-of-data.
  void fill(Batch* out) {
    out->data.assign(static_cast<size_t>(batch) * c * h * w, 0.0f);
    out->labels.assign(batch, 0.0f);
    out->failed = 0;
    size_t plane = static_cast<size_t>(c) * h * w;
    size_t filled = 0;
    std::bernoulli_distribution coin(0.5);
    while (filled < static_cast<size_t>(batch) && cursor < recs.size()) {
      size_t take = std::min<size_t>(batch - filled,
                                     recs.size() - cursor);
      std::vector<uint32_t> picked(order.begin() + cursor,
                                   order.begin() + cursor + take);
      std::vector<char> flips(take, 0);
      if (mirror)
        for (auto& fl : flips) fl = coin(rng) ? 1 : 0;
      cursor += take;
      std::vector<char> ok(take, 0);
      std::atomic<size_t> next_slot{0};
      auto work = [&]() {
        for (;;) {
          size_t slot = next_slot.fetch_add(1);
          if (slot >= take) return;
          ok[slot] = decode_one(picked[slot], out,
                                static_cast<int>(filled + slot),
                                flips[slot] != 0) ? 1 : 0;
        }
      };
      int nthreads = std::max(1, threads);
      std::vector<std::thread> pool;
      for (int i = 1; i < nthreads; ++i) pool.emplace_back(work);
      work();
      for (auto& t : pool) t.join();
      // compact this round's failed slots, then loop to top up
      size_t dst = filled;
      for (size_t src = 0; src < take; ++src) {
        if (!ok[src]) continue;
        size_t s = filled + src;
        if (dst != s) {
          std::memcpy(out->data.data() + dst * plane,
                      out->data.data() + s * plane,
                      plane * sizeof(float));
          out->labels[dst] = out->labels[s];
        }
        ++dst;
      }
      out->failed += static_cast<int>(filled + take - dst);
      filled = dst;
    }
    out->n = static_cast<int>(filled);
    failures.fetch_add(out->failed);
    // zero any tail so padded slots are deterministic
    for (size_t s = filled; s < static_cast<size_t>(batch); ++s) {
      std::memset(out->data.data() + s * plane, 0, plane * sizeof(float));
      out->labels[s] = 0.0f;
    }
  }

  void start_prefetch() {
    Batch* target = &bufs[1 - cur];
    pending = std::async(std::launch::async,
                         [this, target]() { fill(target); });
  }

  void reset() {
    if (pending.valid()) pending.wait();
    cursor = 0;
    if (shuffle) std::shuffle(order.begin(), order.end(), rng);
    cur = 0;
    fill(&bufs[cur]);
    start_prefetch();
  }
};

}  // namespace

extern "C" {

void* mx_imgloader_create(const char* rec_path, int batch, int h, int w,
                          int c, int threads, int shuffle, unsigned seed,
                          int mirror) {
  int fd = open(rec_path, O_RDONLY);
  if (fd < 0) return nullptr;
  auto* L = new Loader();
  L->fd = fd;
  L->batch = batch;
  L->h = h;
  L->w = w;
  L->c = c;
  L->threads = threads;
  L->shuffle = shuffle;
  L->mirror = mirror;
  L->rng.seed(seed);
  L->index_records();
  if (L->recs.empty()) {
    delete L;
    return nullptr;
  }
  L->reset();
  return L;
}

int64_t mx_imgloader_num_samples(void* handle) {
  return static_cast<Loader*>(handle)->recs.size();
}

int mx_imgloader_next(void* handle, float* data, float* labels) {
  auto* L = static_cast<Loader*>(handle);
  L->last_failed = 0;
  for (;;) {
    Batch& b = L->bufs[L->cur];
    L->last_failed += b.failed;
    if (b.n == 0 && b.failed > 0) {
      // every record of this batch was corrupt: advance rather than
      // reporting a spurious epoch end
      if (L->pending.valid()) L->pending.wait();
      L->cur = 1 - L->cur;
      L->start_prefetch();
      continue;
    }
    if (b.n == 0) return 0;        // true epoch end
    std::memcpy(data, b.data.data(), b.data.size() * sizeof(float));
    std::memcpy(labels, b.labels.data(),
                b.labels.size() * sizeof(float));
    int n = b.n;
    // rotate: the prefetched batch becomes current, refill the other
    if (L->pending.valid()) L->pending.wait();
    L->cur = 1 - L->cur;
    L->start_prefetch();
    return n;
  }
}

void mx_imgloader_reset(void* handle) {
  static_cast<Loader*>(handle)->reset();
}

long mx_imgloader_failures(void* handle) {
  return static_cast<Loader*>(handle)->failures.load();
}

// Failures attributable to the batch most recently returned by
// mx_imgloader_next (race-free, unlike polling the cumulative count
// while prefetch runs).
int mx_imgloader_last_failed(void* handle) {
  return static_cast<Loader*>(handle)->last_failed;
}

void mx_imgloader_destroy(void* handle) {
  delete static_cast<Loader*>(handle);
}

}  // extern "C"
