/* General C API: NDArray CRUD + imperative op invocation + save/load.
 *
 * Reference analogue: the core of include/mxnet/c_api.h —
 * MXNDArrayCreateEx, MXNDArrayFree, MXNDArrayGetShape, MXNDArrayGetDType,
 * MXNDArraySyncCopyFromCPU/ToCPU, MXNDArrayWaitAll, MXImperativeInvoke,
 * MXListAllOpNames, MXNDArraySave/Load — enough for a C host to drive
 * the full eager operator corpus without linking Python.
 *
 * Conventions (reference-compatible):
 *  - every function returns 0 on success, -1 on error;
 *    MXGetLastError() describes the last failure on this thread.
 *  - NDArrayHandle owns a reference; release with MXNDArrayFree.
 *  - MXImperativeInvoke: *num_outputs MUST be initialized on entry.
 *    0 means "allocate": *outputs is malloc'd and the caller frees each
 *    handle with MXNDArrayFree and the array itself with free().
 *    Nonzero means "preallocated" (reference out-array semantics): the
 *    op writes INTO the *num_outputs valid handles at *outputs; a count
 *    or shape mismatch is an error. Garbage in *num_outputs routes into
 *    the preallocated path and is undefined behavior.
 *  - dtype codes: 0=float32 1=float64 2=float16 3=uint8 4=int32
 *    5=int8 6=int64 (reference mshadow type flags).
 *  - dev_type: 1=cpu 2=gpu 3=cpu_pinned 6=tpu.
 *
 * Build: native/Makefile target libmxnet_c.so (embeds CPython).
 */
#ifndef MXNET_TPU_C_H_
#define MXNET_TPU_C_H_

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned int mx_uint;
typedef void* NDArrayHandle;

const char* MXGetLastError(void);

/* Library-level controls (ref c_api.h:202-240). */
int MXGetVersion(int* out);  /* MAJOR*10000+MINOR*100+PATCH: 100 = 0.1.0 */
int MXRandomSeed(int seed);            /* global RNG chain reseed */
int MXNotifyShutdown(void);            /* engine drain before exit */

int MXNDArrayCreateEx(const mx_uint* shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle* out);
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                      const mx_uint** out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t size);
int MXNDArrayWaitAll(void);

int MXImperativeInvoke(const char* op_name, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys, const char** param_vals);

int MXListAllOpNames(mx_uint* out_size, const char*** out_array);

int MXNDArraySave(const char* fname, mx_uint num_args,
                  NDArrayHandle* args, const char** keys);
int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                  NDArrayHandle** out_arr, mx_uint* out_name_size,
                  const char*** out_names);

/* ---- symbol surface (ref c_api.h MXSymbol* group, 29 fns; the subset
 * here lets a C host compose a graph or load a -symbol.json) ---- */
typedef void* SymbolHandle;

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out);
int MXSymbolCreateFromFile(const char* fname, SymbolHandle* out);
int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json);
int MXSymbolSaveToFile(SymbolHandle sym, const char* fname);
int MXSymbolFree(SymbolHandle sym);
int MXSymbolCreateVariable(const char* name, SymbolHandle* out);
/* Two-step atomic-create + compose, the reference construction flow
 * (c_api.h:882 MXSymbolCreateAtomicSymbol + :1083 MXSymbolCompose);
 * the creator is addressed by op name instead of an opaque pointer.
 * Compose REBINDS *sym in place to the composed node. */
int MXSymbolCreateAtomicSymbol(const char* op_name, mx_uint num_param,
                               const char** keys, const char** vals,
                               SymbolHandle* out);
int MXSymbolCompose(SymbolHandle sym, const char* name, mx_uint num_args,
                    const char** keys, SymbolHandle* args);
int MXSymbolListArguments(SymbolHandle sym, mx_uint* out_size,
                          const char*** out_array);
int MXSymbolListOutputs(SymbolHandle sym, mx_uint* out_size,
                        const char*** out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint* out_size,
                                const char*** out_array);
/* Shapes in (keys, csr-style ind, flat dims) form like the reference
 * (c_api.h:1123); outputs land in per-thread ret stores. complete=1
 * when every shape was inferred. */
int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                       const char** keys, const mx_uint* arg_ind_ptr,
                       const mx_uint* arg_shape_data,
                       mx_uint* in_shape_size,
                       const mx_uint** in_shape_ndim,
                       const mx_uint*** in_shape_data,
                       mx_uint* out_shape_size,
                       const mx_uint** out_shape_ndim,
                       const mx_uint*** out_shape_data,
                       mx_uint* aux_shape_size,
                       const mx_uint** aux_shape_ndim,
                       const mx_uint*** aux_shape_data, int* complete);

/* ---- executor surface (ref c_api.h MXExecutor* group, 11 fns) ---- */
typedef void* ExecutorHandle;

/* simple_bind: shapes as (keys, ndims, flat dims); grad_req is one of
 * "null" / "write" / "add" applied to every param (ref
 * MXExecutorSimpleBind c_api.h:1371, collapsed to the common case). */
int MXExecutorSimpleBind(SymbolHandle sym, int dev_type, int dev_id,
                         mx_uint num_args, const char** keys,
                         const mx_uint* arg_ndims, const mx_uint* arg_dims,
                         const char* grad_req, ExecutorHandle* out);
int MXExecutorFree(ExecutorHandle exec);
int MXExecutorForward(ExecutorHandle exec, int is_train);
/* out_grads may be NULL (ones-like head grads, the training default). */
int MXExecutorBackward(ExecutorHandle exec, mx_uint num_ograds,
                       NDArrayHandle* out_grads);
int MXExecutorOutputs(ExecutorHandle exec, mx_uint* out_size,
                      NDArrayHandle** out);
/* Live views into the executor's buffers (new references; the arg view
 * aliases the bound buffer, so SyncCopyFromCPU into it feeds the next
 * forward). Grad of a "null"-req arg is an error. */
int MXExecutorArgArray(ExecutorHandle exec, const char* name,
                       NDArrayHandle* out);
int MXExecutorGradArray(ExecutorHandle exec, const char* name,
                        NDArrayHandle* out);
int MXExecutorAuxArray(ExecutorHandle exec, const char* name,
                       NDArrayHandle* out);
/* Copy a loaded checkpoint into the executor ("arg:"/"aux:" prefixes
 * accepted — the save_checkpoint layout); extra names are ignored. */
int MXExecutorCopyParamsFrom(ExecutorHandle exec, mx_uint num,
                             const char** names, NDArrayHandle* arrays);

/* ---- kvstore surface (ref c_api.h MXKVStore* group, string-key
 * variants: CreateKVStore/KVStoreInitEx/PushEx/PullEx/GetRank/
 * GetGroupSize/Barrier/GetType) ---- */
typedef void* KVStoreHandle;

int MXKVStoreCreate(const char* type, KVStoreHandle* out);
int MXKVStoreFree(KVStoreHandle kv);
int MXKVStoreGetType(KVStoreHandle kv, const char** out_type);
int MXKVStoreGetRank(KVStoreHandle kv, int* out_rank);
int MXKVStoreGetGroupSize(KVStoreHandle kv, int* out_size);
int MXKVStoreInitEx(KVStoreHandle kv, mx_uint num, const char** keys,
                    NDArrayHandle* values);
int MXKVStorePushEx(KVStoreHandle kv, mx_uint num, const char** keys,
                    NDArrayHandle* values, int priority);
int MXKVStorePullEx(KVStoreHandle kv, mx_uint num, const char** keys,
                    NDArrayHandle* outs, int priority);
int MXKVStoreBarrier(KVStoreHandle kv);

/* ---- autograd surface (ref c_api.h MXAutograd* group,
 * c_api.h:702-778: recording/training scopes, mark-variables, tape
 * backward). grad_reqs use the reference OpReqType codes: 0=null,
 * 1=write, 2=write-inplace (treated as write), 3=add; marked gradients
 * are written into the passed grad handles. In BackwardEx a NULL slot
 * in ograd_handles means ones_like for that head. ---- */
int MXAutogradSetIsRecording(int is_recording, int* prev);
int MXAutogradSetIsTraining(int is_training, int* prev);
int MXAutogradIsRecording(int* curr);
int MXAutogradIsTraining(int* curr);
int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle* var_handles,
                            mx_uint* reqs_array,
                            NDArrayHandle* grad_handles);
int MXAutogradBackwardEx(mx_uint num_output, NDArrayHandle* output_handles,
                         NDArrayHandle* ograd_handles, int retain_graph,
                         int train_mode);

/* ---- data-iterator surface (ref c_api.h MXDataIter* group,
 * c_api.h:1420-1500: param-string creators, Next/BeforeFirst cursor,
 * GetData/GetLabel views). ---- */
typedef void* DataIterHandle;

int MXListDataIters(mx_uint* out_size, const char*** out_array);
int MXDataIterCreateIter(const char* name, mx_uint num_param,
                         const char** keys, const char** vals,
                         DataIterHandle* out);
int MXDataIterFree(DataIterHandle handle);
int MXDataIterBeforeFirst(DataIterHandle handle);
int MXDataIterNext(DataIterHandle handle, int* out);
int MXDataIterGetData(DataIterHandle handle, NDArrayHandle* out);
int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle* out);
int MXDataIterGetPadNum(DataIterHandle handle, int* pad);

#ifdef __cplusplus
}

/* Header-only C++ RAII layer (cpp-package style, matching the Predictor
 * wrapper in mxnet_tpu_predict.h): NDArray value type + Invoke(). */
#include <cstdlib>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace mxnet_tpu {

class NDArray {
 public:
  NDArray() = default;

  NDArray(const std::vector<mx_uint>& shape, int dev_type = 1,
          int dev_id = 0, int dtype = 0) {
    if (MXNDArrayCreateEx(shape.data(),
                          static_cast<mx_uint>(shape.size()), dev_type,
                          dev_id, 0, dtype, &handle_) != 0)
      throw std::runtime_error(MXGetLastError());
  }

  explicit NDArray(NDArrayHandle owned) : handle_(owned) {}

  ~NDArray() {
    if (handle_) MXNDArrayFree(handle_);
  }

  NDArray(NDArray&& other) noexcept : handle_(other.handle_) {
    other.handle_ = nullptr;
  }
  NDArray& operator=(NDArray&& other) noexcept {
    if (this != &other) {
      if (handle_) MXNDArrayFree(handle_);
      handle_ = other.handle_;
      other.handle_ = nullptr;
    }
    return *this;
  }
  NDArray(const NDArray&) = delete;
  NDArray& operator=(const NDArray&) = delete;

  NDArrayHandle handle() const { return handle_; }

  std::vector<mx_uint> Shape() const {
    mx_uint ndim = 0;
    const mx_uint* dims = nullptr;
    if (MXNDArrayGetShape(handle_, &ndim, &dims) != 0)
      throw std::runtime_error(MXGetLastError());
    return std::vector<mx_uint>(dims, dims + ndim);
  }

  size_t Size() const {
    size_t n = 1;
    for (mx_uint d : Shape()) n *= d;
    return n;
  }

  void CopyFrom(const std::vector<float>& data) {
    if (MXNDArraySyncCopyFromCPU(handle_, data.data(), data.size()) != 0)
      throw std::runtime_error(MXGetLastError());
  }

  std::vector<float> CopyTo() const {
    std::vector<float> out(Size());
    if (MXNDArraySyncCopyToCPU(handle_, out.data(), out.size()) != 0)
      throw std::runtime_error(MXGetLastError());
    return out;
  }

 private:
  NDArrayHandle handle_ = nullptr;
};

/* Run any registered operator by name (MXImperativeInvoke). */
inline std::vector<NDArray> Invoke(
    const std::string& op_name, const std::vector<const NDArray*>& inputs,
    const std::vector<std::pair<std::string, std::string>>& attrs = {}) {
  std::vector<NDArrayHandle> in;
  for (const NDArray* a : inputs) in.push_back(a->handle());
  std::vector<const char*> keys, vals;
  for (const auto& kv : attrs) {
    keys.push_back(kv.first.c_str());
    vals.push_back(kv.second.c_str());
  }
  int n_out = 0;
  NDArrayHandle* outs = nullptr;
  if (MXImperativeInvoke(op_name.c_str(), static_cast<int>(in.size()),
                         in.data(), &n_out, &outs,
                         static_cast<int>(keys.size()), keys.data(),
                         vals.data()) != 0)
    throw std::runtime_error(MXGetLastError());
  std::vector<NDArray> result;
  for (int i = 0; i < n_out; ++i) result.emplace_back(outs[i]);
  std::free(outs);
  return result;
}

}  // namespace mxnet_tpu
#endif  /* __cplusplus */

#endif /* MXNET_TPU_C_H_ */
