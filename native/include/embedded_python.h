// Shared embedded-CPython plumbing for the C ABI libraries
// (predict_api.cc, c_api.cc): one-shot interpreter init, GIL guard,
// thread-local error slot. Mirrors the reference's c_api error contract
// (MXGetLastError returns the last failure on this thread).
#ifndef MXNET_TPU_EMBEDDED_PYTHON_H_
#define MXNET_TPU_EMBEDDED_PYTHON_H_

#include <Python.h>

#include <dlfcn.h>

#include <mutex>
#include <string>

namespace mxtpu {

inline std::string& last_error() {
  thread_local std::string err;
  return err;
}

inline void SetError(const std::string& msg) { last_error() = msg; }

// Record the pending Python exception into the error slot.
inline void SetErrorFromPython() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  SetError(msg);
}

// Ensure an interpreter exists. When loaded into a host C program,
// initialize exactly once; when loaded into a Python process, reuse the
// existing interpreter via GILState.
inline bool EnsurePython() {
  static std::once_flag once;
  static bool ok = true;
  std::call_once(once, []() {
    if (Py_IsInitialized()) return;
    // Hosts that dlopen us with RTLD_LOCAL (Perl's DynaLoader, JNI, …)
    // leave libpython's symbols invisible to CPython extension modules
    // (math.so etc. fail with "undefined symbol: PyFloat_Type").
    // Promote libpython to global visibility before interpreter init;
    // harmless when the host already linked it globally.
    {
      char soname[64];
      snprintf(soname, sizeof(soname), "libpython%d.%d.so.1.0",
               PY_MAJOR_VERSION, PY_MINOR_VERSION);
      if (!dlopen(soname, RTLD_NOW | RTLD_GLOBAL)) {
        snprintf(soname, sizeof(soname), "libpython%d.%d.so",
                 PY_MAJOR_VERSION, PY_MINOR_VERSION);
        dlopen(soname, RTLD_NOW | RTLD_GLOBAL);   // best effort
      }
    }
    Py_InitializeEx(0);
    if (!Py_IsInitialized()) {
      ok = false;
      return;
    }
    // Pin CPU explicitly when requested (axon plugin races otherwise).
    PyRun_SimpleString(
        "import os\n"
        "if os.environ.get('JAX_PLATFORMS', '').startswith('cpu'):\n"
        "    import jax\n"
        "    jax.config.update('jax_platforms', 'cpu')\n");
    // Release the GIL acquired by Py_Initialize so later
    // PyGILState_Ensure calls work uniformly from any thread.
    PyEval_SaveThread();
  });
  if (!ok) SetError("failed to initialize embedded Python");
  return ok && Py_IsInitialized();
}

class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

}  // namespace mxtpu

#endif  // MXNET_TPU_EMBEDDED_PYTHON_H_
