/*
 * C predict API — public header for libmxpredict.so.
 *
 * Reference analogue: include/mxnet/c_predict_api.h (the amalgamation's
 * deployment ABI) plus the header-only C++ convenience layer in the
 * spirit of cpp-package/include/mxnet-cpp.
 *
 * Usage (C):
 *   void* pred;
 *   MXPredCreate(symbol_json, param_bytes, param_size, 1, 0,
 *                1, keys, indptr, shapes, &pred);
 *   MXPredSetInput(pred, "data", buf, n);
 *   MXPredForward(pred);
 *   MXPredGetOutputShape(pred, 0, &shape, &ndim);
 *   MXPredGetOutput(pred, 0, out, total);
 *   MXPredFree(pred);
 *
 * All functions return 0 on success, -1 on error; MXGetLastError()
 * returns a thread-local description of the last failure.
 */
#ifndef MXNET_TPU_PREDICT_H_
#define MXNET_TPU_PREDICT_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned int mx_uint;
typedef void* PredictorHandle;

const char* MXGetLastError(void);

int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char** input_keys,
                 const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, PredictorHandle* out);

int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, mx_uint size);

int MXPredForward(PredictorHandle handle);

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint** shape_data, mx_uint* shape_ndim);

int MXPredGetOutput(PredictorHandle handle, mx_uint index, float* data,
                    mx_uint size);

int MXPredFree(PredictorHandle handle);

#ifdef __cplusplus
}  /* extern "C" */

#include <stdexcept>
#include <string>
#include <vector>

namespace mxnet_tpu {

/* RAII wrapper over the C ABI (cpp-package style). */
class Predictor {
 public:
  Predictor(const std::string& symbol_json, const std::string& params,
            const std::vector<std::string>& input_names,
            const std::vector<std::vector<mx_uint>>& input_shapes,
            int dev_type = 1, int dev_id = 0) {
    if (input_names.size() != input_shapes.size())
      throw std::invalid_argument(
          "input_names and input_shapes must have the same length");
    std::vector<const char*> keys;
    std::vector<mx_uint> indptr(1, 0), dims;
    for (size_t i = 0; i < input_names.size(); ++i) {
      keys.push_back(input_names[i].c_str());
      for (mx_uint d : input_shapes[i]) dims.push_back(d);
      indptr.push_back(static_cast<mx_uint>(dims.size()));
    }
    if (MXPredCreate(symbol_json.c_str(), params.data(),
                     static_cast<int>(params.size()), dev_type, dev_id,
                     static_cast<mx_uint>(keys.size()), keys.data(),
                     indptr.data(), dims.data(), &handle_) != 0)
      throw std::runtime_error(MXGetLastError());
  }

  ~Predictor() {
    if (handle_) MXPredFree(handle_);
  }

  Predictor(const Predictor&) = delete;
  Predictor& operator=(const Predictor&) = delete;

  void SetInput(const std::string& key, const std::vector<float>& data) {
    if (MXPredSetInput(handle_, key.c_str(), data.data(),
                       static_cast<mx_uint>(data.size())) != 0)
      throw std::runtime_error(MXGetLastError());
  }

  void Forward() {
    if (MXPredForward(handle_) != 0)
      throw std::runtime_error(MXGetLastError());
  }

  std::vector<mx_uint> GetOutputShape(mx_uint index = 0) {
    mx_uint* shape = nullptr;
    mx_uint ndim = 0;
    if (MXPredGetOutputShape(handle_, index, &shape, &ndim) != 0)
      throw std::runtime_error(MXGetLastError());
    return std::vector<mx_uint>(shape, shape + ndim);
  }

  std::vector<float> GetOutput(mx_uint index = 0) {
    mx_uint total = 1;
    for (mx_uint d : GetOutputShape(index)) total *= d;
    std::vector<float> out(total);
    if (MXPredGetOutput(handle_, index, out.data(), total) != 0)
      throw std::runtime_error(MXGetLastError());
    return out;
  }

 private:
  PredictorHandle handle_ = nullptr;
};

}  // namespace mxnet_tpu
#endif  /* __cplusplus */

#endif  /* MXNET_TPU_PREDICT_H_ */
