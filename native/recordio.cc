// Native RecordIO codec: buffered reader/writer of the dmlc recordio wire
// format ([kMagic:u32][lrec:u32][payload][pad4], lrec = cflag<<29 | len).
//
// Reference analogue: dmlc-core's recordio split/chunk reader used by
// src/io/iter_image_recordio_2.cc and python/mxnet/recordio.py (SURVEY §2.1
// "Data IO (native)").  This is the TPU build's native IO substrate: the
// Python MXRecordIO/MXIndexedRecordIO classes bind to it via ctypes and
// fall back to pure python when the shared object is absent.
//
// Build: `make -C native` → mxnet_tpu/_native/librecordio.so
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr size_t kBufSize = 4 << 20;  // 4 MB buffered IO

struct Writer {
  FILE* f;
  std::vector<char> buf;
  explicit Writer(FILE* fp) : f(fp) { buf.reserve(kBufSize); }
  void flush() {
    if (!buf.empty()) {
      fwrite(buf.data(), 1, buf.size(), f);
      buf.clear();
    }
  }
  void append(const void* p, size_t n) {
    if (buf.size() + n > kBufSize) flush();
    if (n > kBufSize) {
      fwrite(p, 1, n, f);
    } else {
      const char* c = static_cast<const char*>(p);
      buf.insert(buf.end(), c, c + n);
    }
  }
};

struct Reader {
  FILE* f;
  std::vector<char> record;  // last read payload (owned)
};

}  // namespace

extern "C" {

void* MXRIOWriterCreate(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  return new Writer(f);
}

int MXRIOWrite(void* handle, const char* data, uint64_t len) {
  auto* w = static_cast<Writer*>(handle);
  if (!w || len >= (1ull << 29)) return -1;
  uint32_t head[2] = {kMagic, static_cast<uint32_t>(len)};  // cflag 0
  w->append(head, sizeof(head));
  w->append(data, len);
  static const char zeros[4] = {0, 0, 0, 0};
  size_t pad = (4 - (len % 4)) % 4;
  if (pad) w->append(zeros, pad);
  return 0;
}

int64_t MXRIOWriterTell(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  return static_cast<int64_t>(ftell(w->f)) +
         static_cast<int64_t>(w->buf.size());
}

void MXRIOWriterFree(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  if (!w) return;
  w->flush();
  fclose(w->f);
  delete w;
}

void* MXRIOReaderCreate(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* r = new Reader();
  r->f = f;
  // large stdio buffer: sequential scan of sharded .rec files is the
  // data-pipeline hot path
  setvbuf(f, nullptr, _IOFBF, kBufSize);
  return r;
}

// Returns 1 on success (payload in *out / *len), 0 on EOF, -1 on corrupt
// stream. *out points at memory owned by the reader, valid until the next
// call. Length goes via *len so zero-length records are distinct from EOF.
int MXRIORead(void* handle, const char** out, uint64_t* len_out) {
  auto* r = static_cast<Reader*>(handle);
  uint32_t head[2];
  if (fread(head, sizeof(uint32_t), 2, r->f) != 2) return 0;  // EOF
  if (head[0] != kMagic) return -1;
  uint32_t len = head[1] & ((1u << 29) - 1);
  uint32_t cflag = head[1] >> 29;
  if (cflag != 0) return -1;  // python writer emits complete records only
  r->record.resize(len ? len : 1);
  if (len && fread(r->record.data(), 1, len, r->f) != len) return -1;
  size_t pad = (4 - (len % 4)) % 4;
  if (pad) fseek(r->f, static_cast<long>(pad), SEEK_CUR);
  *out = r->record.data();
  *len_out = len;
  return 1;
}

int64_t MXRIOReaderTell(void* handle) {
  return ftell(static_cast<Reader*>(handle)->f);
}

int MXRIOReaderSeek(void* handle, int64_t pos) {
  return fseek(static_cast<Reader*>(handle)->f, static_cast<long>(pos),
               SEEK_SET);
}

void MXRIOReaderFree(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  if (!r) return;
  fclose(r->f);
  delete r;
}

}  // extern "C"
