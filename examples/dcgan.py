#!/usr/bin/env python
"""DCGAN: adversarial training with Gluon (generator vs discriminator).

Parity target: reference ``example/gluon/dcgan.py`` — ConvTranspose
generator, strided-conv discriminator, alternating SigmoidBCE updates
with separate trainers, label smoothing off.

Synthetic data (a unimodal "ring" image distribution) keeps the script
hermetic; success is measured the only stable way for a tiny GAN: both
losses stay finite and the generator's output statistics move toward
the data distribution's.

    python examples/dcgan.py --num-iters 60
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def real_batch(rng, n, size=16):
    """Images of a bright centered disc with noise — an easy target
    distribution whose mean/variance a generator can match quickly."""
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    d = np.sqrt((yy - size / 2) ** 2 + (xx - size / 2) ** 2)
    disc = (d < size / 4).astype(np.float32) * 2 - 1         # in [-1, 1]
    batch = np.tile(disc, (n, 1, 1, 1))
    batch += 0.1 * rng.randn(n, 1, size, size).astype(np.float32)
    return np.clip(batch, -1, 1)


def build_nets(ngf=16, ndf=16, nz=32):
    from mxnet_tpu import gluon
    netG = gluon.nn.HybridSequential()
    with netG.name_scope():
        # nz x 1 x 1 -> 1 x 16 x 16
        netG.add(gluon.nn.Conv2DTranspose(ngf * 2, 4, 1, 0,
                                          use_bias=False))
        netG.add(gluon.nn.BatchNorm())
        netG.add(gluon.nn.Activation("relu"))
        netG.add(gluon.nn.Conv2DTranspose(ngf, 4, 2, 1, use_bias=False))
        netG.add(gluon.nn.BatchNorm())
        netG.add(gluon.nn.Activation("relu"))
        netG.add(gluon.nn.Conv2DTranspose(1, 4, 2, 1, use_bias=False))
        netG.add(gluon.nn.Activation("tanh"))
    netD = gluon.nn.HybridSequential()
    with netD.name_scope():
        netD.add(gluon.nn.Conv2D(ndf, 4, 2, 1, use_bias=False))
        netD.add(gluon.nn.LeakyReLU(0.2))
        netD.add(gluon.nn.Conv2D(ndf * 2, 4, 2, 1, use_bias=False))
        netD.add(gluon.nn.BatchNorm())
        netD.add(gluon.nn.LeakyReLU(0.2))
        netD.add(gluon.nn.Conv2D(1, 4, 1, 0, use_bias=False))
    return netG, netD


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--nz", type=int, default=32)
    ap.add_argument("--num-iters", type=int, default=60)
    ap.add_argument("--lr", type=float, default=2e-4)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(0)
    netG, netD = build_nets(nz=args.nz)
    netG.collect_params().initialize(mx.init.Normal(0.02))
    netD.collect_params().initialize(mx.init.Normal(0.02))
    trainerG = gluon.Trainer(netG.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
    trainerD = gluon.Trainer(netD.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    B = args.batch_size
    real_label = nd.ones((B,))
    fake_label = nd.zeros((B,))
    for it in range(args.num_iters):
        real = nd.array(real_batch(rng, B))
        noise = nd.array(rng.randn(B, args.nz, 1, 1).astype(np.float32))
        # --- D step: maximize log D(x) + log(1 - D(G(z))) ---
        with autograd.record():
            out_real = netD(real).reshape((B,))
            fake = netG(noise)
            out_fake = netD(fake.detach()).reshape((B,))
            lossD = loss_fn(out_real, real_label) + \
                loss_fn(out_fake, fake_label)
        lossD.backward()
        trainerD.step(B)
        # --- G step: maximize log D(G(z)) ---
        with autograd.record():
            fake = netG(noise)
            out = netD(fake).reshape((B,))
            lossG = loss_fn(out, real_label)
        lossG.backward()
        trainerG.step(B)
        if it % 20 == 0:
            logging.info("iter %d: lossD %.3f lossG %.3f", it,
                         float(lossD.asnumpy().mean()),
                         float(lossG.asnumpy().mean()))

    # generator stats should approach the data's (disc mean ~ -0.55)
    sample = netG(nd.array(
        rng.randn(64, args.nz, 1, 1).astype(np.float32))).asnumpy()
    data_mean = real_batch(rng, 64).mean()
    gap = abs(sample.mean() - data_mean)
    init_gap = abs(0.0 - data_mean)       # untrained tanh output ~ 0-mean
    logging.info("generator mean %.3f vs data mean %.3f (init gap %.3f)",
                 sample.mean(), data_mean, init_gap)
    assert np.isfinite(sample).all()
    # the generator must have moved its output statistics toward the
    # data's relative to the untrained tanh output
    assert gap < init_gap, \
        "generator stats did not move toward the data distribution"
    print("final-mean-gap: %.4f" % gap)
    return gap


if __name__ == "__main__":
    main()
