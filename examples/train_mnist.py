#!/usr/bin/env python
"""Train an MLP (or LeNet) on MNIST with the symbolic Module API.

Parity target: reference ``example/image-classification/train_mnist.py``
(BASELINE workload #1: LeNet/MNIST via mx.mod.Module). Uses the real MNIST
idx files when present, else a synthetic-digits fallback so the script runs
hermetically.

    python examples/train_mnist.py --network mlp --num-epochs 5
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def build_mlp(mx, num_classes=10):
    data = mx.sym.Variable("data")
    flat = mx.sym.Flatten(data)
    h1 = mx.sym.FullyConnected(flat, num_hidden=128, name="fc1")
    a1 = mx.sym.Activation(h1, act_type="relu")
    h2 = mx.sym.FullyConnected(a1, num_hidden=64, name="fc2")
    a2 = mx.sym.Activation(h2, act_type="relu")
    out = mx.sym.FullyConnected(a2, num_hidden=num_classes, name="fc3")
    return mx.sym.SoftmaxOutput(out, name="softmax")


def build_lenet(mx, num_classes=10):
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20, name="conv1")
    a1 = mx.sym.Activation(c1, act_type="tanh")
    p1 = mx.sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = mx.sym.Convolution(p1, kernel=(5, 5), num_filter=50, name="conv2")
    a2 = mx.sym.Activation(c2, act_type="tanh")
    p2 = mx.sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    flat = mx.sym.Flatten(p2)
    f1 = mx.sym.FullyConnected(flat, num_hidden=500, name="fc1")
    a3 = mx.sym.Activation(f1, act_type="tanh")
    out = mx.sym.FullyConnected(a3, num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(out, name="softmax")


def get_iters(mx, batch_size, flat):
    """Real MNIST if the idx files are on disk, else synthetic digits."""
    from mxnet_tpu.test_utils import get_mnist_iterator
    return get_mnist_iterator(batch_size=batch_size, flat=flat)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", choices=("mlp", "lenet"), default="mlp")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--kv-store", default="local")
    ap.add_argument("--model-prefix", default=None)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    import mxnet_tpu as mx

    net = build_mlp(mx) if args.network == "mlp" else build_lenet(mx)
    train_iter, val_iter = get_iters(mx, args.batch_size,
                                     flat=(args.network == "mlp"))

    mod = mx.mod.Module(net, context=mx.context.current_context())
    callbacks = [mx.callback.Speedometer(args.batch_size, 50)]
    epoch_cb = (mx.callback.do_checkpoint(args.model_prefix)
                if args.model_prefix else None)
    mod.fit(train_iter, eval_data=val_iter, eval_metric="acc",
            optimizer="sgd", initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            kvstore=args.kv_store, num_epoch=args.num_epochs,
            batch_end_callback=callbacks, epoch_end_callback=epoch_cb)
    score = mod.score(val_iter, "acc")[0][1]
    print("final validation accuracy: %.4f" % score)
    return score


if __name__ == "__main__":
    main()
