#!/usr/bin/env python
"""Toy Faster-RCNN trained end-to-end on synthetic images.

Parity target: reference ``example/rcnn/train_end2end.py`` reduced to its
skeleton: conv backbone -> RPN (cls + bbox heads over an anchor grid) ->
``contrib.Proposal`` -> ``ROIPooling`` -> RCNN head (cls + bbox refine),
all trained jointly — the anchor-target and proposal-target assignment
steps done host-side like the reference's AnchorTarget/ProposalTarget
custom ops. Synthetic data: one bright axis-aligned rectangle per image;
the detector learns to propose and refine it.

    python examples/train_rcnn_toy.py --num-epochs 6
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

IMG = 32
STRIDE = 8                       # 3 stride-2 convs
FEAT = IMG // STRIDE
SCALES = (1.0, 2.0, 3.0)         # anchor sides 8/16/24 px
RATIOS = (1.0,)
A = len(SCALES) * len(RATIOS)
POST_NMS = 8


def grid_anchors():
    """Numpy twin of ops/rcnn.py:_grid_anchors (position-major HW*A)."""
    base = float(STRIDE)
    cx = cy = (base - 1.0) / 2.0
    area = base * base
    anchors = []
    for r in RATIOS:
        w = np.round(np.sqrt(area / r))
        h = np.round(w * r)
        for s in SCALES:
            ws, hs = w * s, h * s
            anchors.append([cx - (ws - 1) / 2, cy - (hs - 1) / 2,
                            cx + (ws - 1) / 2, cy + (hs - 1) / 2])
    base_a = np.array(anchors, np.float32)                    # (A, 4)
    sx = np.arange(FEAT, dtype=np.float32) * STRIDE
    sy = np.arange(FEAT, dtype=np.float32) * STRIDE
    shift_y, shift_x = np.meshgrid(sy, sx, indexing="ij")
    shifts = np.stack([shift_x, shift_y, shift_x, shift_y],
                      axis=-1).reshape(-1, 1, 4)
    return (shifts + base_a[None]).reshape(-1, 4)             # (HW*A, 4)


def iou(boxes, gt):
    """IoU of (K,4) pixel boxes vs a single (4,) gt box."""
    ix0 = np.maximum(boxes[:, 0], gt[0])
    iy0 = np.maximum(boxes[:, 1], gt[1])
    ix1 = np.minimum(boxes[:, 2], gt[2])
    iy1 = np.minimum(boxes[:, 3], gt[3])
    inter = np.clip(ix1 - ix0 + 1, 0, None) * np.clip(iy1 - iy0 + 1, 0,
                                                      None)
    area_b = (boxes[:, 2] - boxes[:, 0] + 1) * (boxes[:, 3] - boxes[:, 1]
                                                + 1)
    area_g = (gt[2] - gt[0] + 1) * (gt[3] - gt[1] + 1)
    return inter / np.maximum(area_b + area_g - inter, 1e-6)


def bbox_targets(boxes, gt):
    """(dx, dy, dw, dh) regression targets (reference bbox_transform)."""
    bw = boxes[:, 2] - boxes[:, 0] + 1.0
    bh = boxes[:, 3] - boxes[:, 1] + 1.0
    bx = boxes[:, 0] + 0.5 * (bw - 1)
    by = boxes[:, 1] + 0.5 * (bh - 1)
    gw = gt[2] - gt[0] + 1.0
    gh = gt[3] - gt[1] + 1.0
    gx = gt[0] + 0.5 * (gw - 1)
    gy = gt[1] + 0.5 * (gh - 1)
    return np.stack([(gx - bx) / bw, (gy - by) / bh,
                     np.log(gw / bw), np.log(gh / bh)], axis=1)


def anchor_target_batch(anchors, gts):
    """AnchorTarget analogue: labels (N, HW*A) in {1 fg, 0 bg, -1 ignore}
    + bbox targets (N, HW*A, 4)."""
    n = len(gts)
    labels = np.full((n, len(anchors)), -1, np.float32)
    targets = np.zeros((n, len(anchors), 4), np.float32)
    for i, gt in enumerate(gts):
        ious = iou(anchors, gt)
        labels[i, ious < 0.3] = 0
        labels[i, ious >= 0.5] = 1
        labels[i, np.argmax(ious)] = 1
        fg = labels[i] == 1
        targets[i, fg] = bbox_targets(anchors[fg], gt)
    return labels, targets


def synthetic_set(n, rng=None):
    rng = rng or np.random.RandomState(5)
    xs = rng.rand(n, 1, IMG, IMG).astype(np.float32) * 0.2
    gts = np.zeros((n, 4), np.float32)
    for i in range(n):
        w = rng.randint(8, 20)
        h = rng.randint(8, 20)
        x0 = rng.randint(0, IMG - w)
        y0 = rng.randint(0, IMG - h)
        xs[i, 0, y0:y0 + h, x0:x0 + w] += 0.8
        gts[i] = [x0, y0, x0 + w - 1, y0 + h - 1]
    return xs, gts


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=2e-3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    class ToyRCNN(gluon.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.backbone = gluon.nn.Sequential(prefix="")
                for ch in (16, 32, 32):
                    self.backbone.add(gluon.nn.Conv2D(
                        ch, 3, strides=2, padding=1, activation="relu"))
                self.rpn_conv = gluon.nn.Conv2D(32, 3, padding=1,
                                                activation="relu")
                self.rpn_cls = gluon.nn.Conv2D(2 * A, 1)
                self.rpn_bbox = gluon.nn.Conv2D(4 * A, 1)
                self.head = gluon.nn.Sequential(prefix="")
                self.head.add(gluon.nn.Dense(64, activation="relu"))
                self.head_cls = gluon.nn.Dense(2)
                self.head_bbox = gluon.nn.Dense(4)

        def forward(self, x):
            feat = self.backbone(x)
            r = self.rpn_conv(feat)
            return feat, self.rpn_cls(r), self.rpn_bbox(r)

        def rois_and_head(self, feat, rpn_cls, rpn_bbox):
            n = rpn_cls.shape[0]
            score = nd.reshape(rpn_cls, (n, 2, A * FEAT * FEAT))
            prob = nd.reshape(nd.softmax(score, axis=1), (n, 2 * A, FEAT,
                                                          FEAT))
            im_info = nd.array(np.tile([IMG, IMG, 1.0], (n, 1)))
            rois = nd.contrib.Proposal(
                prob, rpn_bbox, im_info, feature_stride=STRIDE,
                scales=SCALES, ratios=RATIOS, rpn_pre_nms_top_n=48,
                rpn_post_nms_top_n=POST_NMS, threshold=0.7, rpn_min_size=4)
            pooled = nd.ROIPooling(feat, rois, pooled_size=(2, 2),
                                   spatial_scale=1.0 / STRIDE)
            flat = nd.reshape(pooled, (pooled.shape[0], -1))
            h = self.head(flat)
            return rois, self.head_cls(h), self.head_bbox(h)

    net = ToyRCNN()
    net.collect_params().initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    anchors = grid_anchors()
    train_x, train_gt = synthetic_set(192)
    ce = gluon.loss.SoftmaxCrossEntropyLoss(axis=1)
    huber = gluon.loss.HuberLoss()
    bs = args.batch_size

    for epoch in range(args.num_epochs):
        total = 0.0
        nb = 0
        for i in range(0, len(train_x), bs):
            xb = train_x[i:i + bs]
            gtb = train_gt[i:i + bs]
            n = len(xb)
            lab, tgt = anchor_target_batch(anchors, gtb)
            lab_nd = nd.array(lab)          # (N, HWA) position-major
            tgt_nd = nd.array(tgt)
            with autograd.record():
                feat, rpn_cls, rpn_bbox = net(nd.array(xb))
                # (N,2A,H,W): first A channels bg, last A fg (Proposal
                # layout, ops/rcnn.py:129); pair logits per anchor,
                # position-major to match the anchor grid
                lg = nd.reshape(rpn_cls, (n, 2, A, FEAT, FEAT))
                lg = nd.transpose(lg, axes=(0, 1, 3, 4, 2))   # (N,2,H,W,A)
                lg = nd.reshape(lg, (n, 2, -1))
                mask = (lab_nd >= 0)
                logp = nd.log_softmax(lg, axis=1)             # (N,2,HWA)
                nll = -nd.pick(logp, nd.relu(lab_nd), axis=1)  # (N,HWA)
                cls_l = nd.sum(nll * mask) \
                    / nd.clip(nd.sum(mask), 1.0, 1e9)
                bb = nd.reshape(rpn_bbox, (n, A, 4, FEAT, FEAT))
                bb = nd.transpose(bb, axes=(0, 3, 4, 1, 2))   # (N,H,W,A,4)
                bb = nd.reshape(bb, (n, -1, 4))
                fg = nd.reshape(lab_nd == 1, (n, -1, 1))
                bb_l = nd.sum(huber(bb * fg, tgt_nd * fg)) \
                    / nd.clip(nd.sum(fg), 1.0, 1e9)

                # proposal-target: match ROIs to gt host-side like the
                # reference's ProposalTarget op, then the RCNN head
                rois, hc, hb = net.rois_and_head(feat, rpn_cls, rpn_bbox)
                rois_np = rois.asnumpy()
                hl = np.zeros((len(rois_np),), np.float32)
                ht = np.zeros((len(rois_np), 4), np.float32)
                for b in range(n):
                    sel = np.where(rois_np[:, 0] == b)[0]
                    boxes = rois_np[sel, 1:]
                    ious = iou(boxes, gtb[b])
                    labs = (ious >= 0.4).astype(np.float32)
                    labs[np.argmax(ious)] = 1.0   # best ROI always fg
                    hl[sel] = labs
                    ht[sel] = bbox_targets(boxes, gtb[b])
                hfg = nd.reshape(nd.array(hl), (-1, 1))
                hcls_l = nd.mean(ce(hc, nd.array(hl)))
                hbb_l = nd.sum(huber(hb * hfg, nd.array(ht) * hfg)) \
                    / nd.clip(nd.sum(hfg), 1.0, 1e9)
                loss = cls_l + bb_l + hcls_l + hbb_l
            loss.backward()
            trainer.step(n)
            total += float(loss.asnumpy())
            nb += 1
        logging.info("epoch %d loss %.4f", epoch, total / nb)

    # ---- evaluate: refine the best-scoring proposal, measure IoU ----
    val_x, val_gt = synthetic_set(48, rng=np.random.RandomState(99))
    feat, rpn_cls, rpn_bbox = net(nd.array(val_x))
    rois, hc, hb = net.rois_and_head(feat, rpn_cls, rpn_bbox)
    probs = nd.softmax(hc, axis=1).asnumpy()[:, 1]
    rois_np = rois.asnumpy()
    hb_np = hb.asnumpy()
    ious = []
    for b in range(len(val_x)):
        sel = np.where(rois_np[:, 0] == b)[0]
        best = sel[np.argmax(probs[sel])]
        box = rois_np[best, 1:]
        d = hb_np[best]
        w = box[2] - box[0] + 1
        h = box[3] - box[1] + 1
        cx = box[0] + 0.5 * (w - 1) + d[0] * w
        cy = box[1] + 0.5 * (h - 1) + d[1] * h
        pw = np.exp(np.clip(d[2], -2, 2)) * w
        ph = np.exp(np.clip(d[3], -2, 2)) * h
        refined = np.array([cx - 0.5 * (pw - 1), cy - 0.5 * (ph - 1),
                            cx + 0.5 * (pw - 1), cy + 0.5 * (ph - 1)])
        ious.append(float(iou(refined[None], val_gt[b])[0]))
    miou = float(np.mean(ious))
    print("mean IoU of refined top proposal: %.3f" % miou)
    return miou


if __name__ == "__main__":
    main()
