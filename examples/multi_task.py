#!/usr/bin/env python
"""Multi-task learning: one trunk, two softmax heads, joint loss.

Parity target: reference ``example/multi-task`` — classify the digit AND
a parity/odd-even label from the same input with a shared trunk, using a
Group symbol with two SoftmaxOutputs and a multi-metric Module.

    python examples/multi_task.py --num-epochs 8
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


_PROTOS = np.random.RandomState(321).rand(10, 32).astype(np.float32)


def make_set(n, rng=None):
    rng = rng or np.random.RandomState(17)
    protos = _PROTOS
    y = rng.randint(0, 10, n)
    x = protos[y] + rng.normal(0, 0.25, (n, 32)).astype(np.float32)
    return x, y.astype(np.float32), (y % 2).astype(np.float32)


def build():
    import mxnet_tpu as mx
    S = mx.sym
    data = S.Variable("data")
    trunk = S.Activation(S.FullyConnected(data, num_hidden=64,
                                          name="trunk1"),
                         act_type="relu")
    digit = S.SoftmaxOutput(
        S.FullyConnected(trunk, num_hidden=10, name="digit_fc"),
        S.Variable("digit_label"), name="digit")
    parity = S.SoftmaxOutput(
        S.FullyConnected(trunk, num_hidden=2, name="parity_fc"),
        S.Variable("parity_label"), name="parity")
    return S.Group([digit, parity])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.2)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import mxnet_tpu as mx
    from mxnet_tpu.io import DataBatch, DataDesc

    x, yd, yp = make_set(2048)
    bs = args.batch_size
    mod = mx.mod.Module(build(), data_names=["data"],
                        label_names=["digit_label", "parity_label"],
                        context=mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (bs, 32))],
             label_shapes=[DataDesc("digit_label", (bs,)),
                           DataDesc("parity_label", (bs,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", args.lr),))
    for epoch in range(args.num_epochs):
        for i in range(0, len(x) - bs + 1, bs):
            batch = DataBatch([mx.nd.array(x[i:i + bs])],
                              [mx.nd.array(yd[i:i + bs]),
                               mx.nd.array(yp[i:i + bs])])
            mod._fit_step(batch)
        logging.info("epoch %d", epoch)

    vx, vyd, vyp = make_set(512, rng=np.random.RandomState(5))
    accs = []
    for i in range(0, 512 - bs + 1, bs):
        batch = DataBatch([mx.nd.array(vx[i:i + bs])],
                          [mx.nd.array(vyd[i:i + bs]),
                           mx.nd.array(vyp[i:i + bs])])
        mod.forward(batch, is_train=False)
        od, op = [o.asnumpy() for o in mod.get_outputs()]
        accs.append(((od.argmax(axis=1) == vyd[i:i + bs]).mean(),
                     (op.argmax(axis=1) == vyp[i:i + bs]).mean()))
    digit_acc = float(np.mean([a for a, _ in accs]))
    parity_acc = float(np.mean([b for _, b in accs]))
    print("digit acc %.3f parity acc %.3f" % (digit_acc, parity_acc))
    return digit_acc, parity_acc


if __name__ == "__main__":
    main()
