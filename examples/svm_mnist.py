#!/usr/bin/env python
"""Multiclass SVM head instead of softmax on an MNIST-like task.

Parity target: reference ``example/svm_mnist`` — the same MLP trained
with ``SVMOutput`` (squared hinge loss against the margin, the semantic
gradient living in the op) instead of ``SoftmaxOutput``, through Module.

    python examples/svm_mnist.py --num-epochs 6
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

_PROTOS = np.random.RandomState(55).rand(10, 64).astype(np.float32)


def make_set(n, rng=None):
    rng = rng or np.random.RandomState(1)
    y = rng.randint(0, 10, n)
    x = _PROTOS[y] + rng.normal(0, 0.3, (n, 64)).astype(np.float32)
    return x.astype(np.float32), y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--linear", action="store_true",
                    help="linear (L1) hinge instead of squared")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import mxnet_tpu as mx
    from mxnet_tpu.io import NDArrayIter

    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=128,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    net = mx.sym.SVMOutput(net, mx.sym.Variable("svm_label"), margin=1.0,
                           regularization_coefficient=1.0,
                           use_linear=args.linear, name="svm")

    train_x, train_y = make_set(2048)
    it = NDArrayIter(train_x, train_y, batch_size=args.batch_size,
                     shuffle=True, label_name="svm_label")
    mod = mx.mod.Module(net, data_names=["data"],
                        label_names=["svm_label"], context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", args.lr),))
    for epoch in range(args.num_epochs):
        it.reset()
        for batch in it:
            mod._fit_step(batch)
        logging.info("epoch %d", epoch)

    val_x, val_y = make_set(512, rng=np.random.RandomState(42))
    from mxnet_tpu.io import DataBatch
    scores = []
    for i in range(0, 512, args.batch_size):
        b = DataBatch([mx.nd.array(val_x[i:i + args.batch_size])],
                      [mx.nd.array(val_y[i:i + args.batch_size])])
        mod.forward(b, is_train=False)
        scores.append(mod.get_outputs()[0].asnumpy())
    pred = np.concatenate(scores).argmax(axis=1)
    acc = float((pred == val_y[:len(pred)]).mean())
    print("svm val accuracy: %.3f" % acc)
    return acc


if __name__ == "__main__":
    main()
