#!/usr/bin/env python
"""ImageNet-scale training driver (ResNet/Inception zoo).

Parity target: reference ``example/image-classification/train_imagenet.py``
including its synthetic-data benchmark mode (``--benchmark 1``,
README.md:255-260) — the harness behind the headline throughput tables
(README.md:293-320).

Real data: point --data-train at a RecordIO file packed by
``native/bin/im2rec`` (read through the native threaded decode pipeline).
Benchmark mode feeds synthetic batches so it measures pure train-step
throughput.

    python examples/train_imagenet.py --benchmark 1 --network resnet50_v1 \
        --batch-size 32 --num-batches 50
"""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


class SyntheticIter(object):
    """Fixed random batch, served repeatedly (the reference's benchmark
    dummy iterator)."""

    def __init__(self, batch_size, image_shape, num_classes, num_batches):
        import mxnet_tpu as mx
        from mxnet_tpu.io import DataBatch, DataDesc
        rng = np.random.RandomState(0)
        data = rng.rand(batch_size, *image_shape).astype(np.float32)
        label = rng.randint(0, num_classes, batch_size).astype(np.float32)
        self._batch = DataBatch(
            [mx.nd.array(data)], [mx.nd.array(label)], pad=0,
            provide_data=[DataDesc("data", (batch_size,) + image_shape)],
            provide_label=[DataDesc("softmax_label", (batch_size,))])
        self.provide_data = self._batch.provide_data
        self.provide_label = self._batch.provide_label
        self.batch_size = batch_size
        self._total = num_batches
        self._served = 0

    def reset(self):
        self._served = 0

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        if self._served >= self._total:
            raise StopIteration
        self._served += 1
        return self._batch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="resnet50_v1")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--num-epochs", type=int, default=1)
    ap.add_argument("--num-batches", type=int, default=50,
                    help="benchmark batches per epoch")
    ap.add_argument("--benchmark", type=int, default=0)
    ap.add_argument("--data-train", default=None,
                    help=".rec file for real training data")
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--kv-store", default="local")
    ap.add_argument("--dtype", default="float32",
                    choices=("float32", "bfloat16"))
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.model_zoo import vision

    image_shape = tuple(int(x) for x in args.image_shape.split(","))

    if args.benchmark:
        train_iter = SyntheticIter(args.batch_size, image_shape,
                                   args.num_classes, args.num_batches)
    elif args.data_train:
        from mxnet_tpu.image import ImageRecordIter
        train_iter = ImageRecordIter(
            path_imgrec=args.data_train, data_shape=image_shape,
            batch_size=args.batch_size, shuffle=True, rand_mirror=True,
            preprocess_threads=4)
    else:
        ap.error("need --benchmark 1 or --data-train")

    net = vision.get_model(args.network, classes=args.num_classes)
    net.collect_params().initialize(mx.init.Xavier())
    if args.dtype == "bfloat16":
        net.cast("bfloat16")
    net.hybridize()

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9,
                             "wd": 1e-4}, kvstore=args.kv_store or None)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.num_epochs):
        train_iter.reset()
        tic = time.time()
        n_img = 0
        warm_done = 0.0
        for i, batch in enumerate(train_iter):
            x, y = batch.data[0], batch.label[0]
            if args.dtype == "bfloat16":
                x = x.astype("bfloat16")
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            if i == 2:          # exclude compile/warmup from the rate
                loss.wait_to_read()
                warm_done = time.time()
                n_img = 0
            n_img += x.shape[0]
        loss.wait_to_read()
        toc = time.time()
        span = toc - (warm_done or tic)
        logging.info("epoch %d: %.1f img/s (%d images, %.1fs)",
                     epoch, n_img / span, n_img, span)
    print("final-throughput: %.2f img/s" % (n_img / span))
    return n_img / span


if __name__ == "__main__":
    main()
