#!/usr/bin/env python
"""Inception-v3 multi-device symbolic training (BASELINE workload #4).

Parity target: reference ``example/image-classification/train_imagenet.py
--network inception-v3 --kv-store device`` — the multi-device
``kvstore='device'`` configuration of the headline tables
(``example/image-classification/README.md:309-320``).

The model-zoo Gluon inception-v3 is traced into a Symbol (HybridBlock
called on ``mx.sym.Variable``) and driven through ``mx.mod.Module`` with
a context list; gradients reduce through the device kvstore (one jitted
on-device sum — the CommDevice analogue). Synthetic data keeps the
script hermetic.

    python examples/train_inception_v3.py --num-devices 2 --num-batches 8
"""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-devices", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=8,
                    help="global batch (split across devices)")
    ap.add_argument("--image-size", type=int, default=299)
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--num-batches", type=int, default=8)
    ap.add_argument("--num-epochs", type=int, default=1)
    ap.add_argument("--kv-store", default="device")
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.io import NDArrayIter

    n_tpu = mx.context.num_tpus()
    if n_tpu:
        ctxs = [mx.tpu(i) for i in range(min(args.num_devices, n_tpu))]
    else:
        import jax
        n_cpu = len(jax.devices("cpu"))
        ctxs = [mx.cpu(i) for i in range(min(args.num_devices, n_cpu))]

    # Trace the Gluon zoo net into a Symbol, reference-style.
    net = vision.get_model("inceptionv3", classes=args.num_classes)
    data = mx.sym.Variable("data")
    sym = mx.sym.SoftmaxOutput(net(data), name="softmax")

    rng = np.random.RandomState(0)
    shape = (3, args.image_size, args.image_size)
    n = args.batch_size * args.num_batches
    X = rng.rand(n, *shape).astype(np.float32)
    Y = rng.randint(0, args.num_classes, n).astype(np.float32)
    it = NDArrayIter(X, Y, batch_size=args.batch_size,
                     label_name="softmax_label")

    mod = mx.mod.Module(sym, context=ctxs)
    tic = time.time()
    mod.fit(it, num_epoch=args.num_epochs,
            kvstore=args.kv_store,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-4},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 2))
    span = time.time() - tic
    rate = n * args.num_epochs / span
    logging.info("devices=%d kvstore=%s: %.2f img/s", len(ctxs),
                 args.kv_store, rate)
    print("final-throughput: %.2f img/s" % rate)


if __name__ == "__main__":
    main()
