#!/usr/bin/env python
"""Factorization machine on sparse LibSVM-style data.

Parity target: reference ``example/sparse/factorization_machine`` — the
degree-2 FM (Rendle 2010): score = w0 + w.x + 0.5 * sum_f ((Vx)_f^2 -
(V^2 x^2)_f), where only interaction FACTORS (not the full feature-pair
matrix) are learned, built from symbol algebra over CSR batches and
trained with Module on a logistic loss.

Synthetic task: labels depend on a planted pairwise interaction between
feature groups, so a linear model underfits and the FM factors must pick
up the cross terms.

    python examples/factorization_machine.py --num-epochs 10
"""
import argparse
import logging
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def synthetic_fm_libsvm(path, n=2048, dim=200, nnz=12, seed=3):
    """Sparse rows; label = sign of a planted pairwise interaction."""
    rng = np.random.RandomState(seed)
    v_true = rng.randn(dim, 4) * 0.6
    with open(path, "w") as fh:
        for _ in range(n):
            ids = rng.choice(dim, size=nnz, replace=False)
            vals = rng.rand(nnz).astype(np.float32)
            x = np.zeros(dim, np.float32)
            x[ids] = vals
            vx = v_true.T @ x
            score = 0.5 * float((vx ** 2).sum() - ((v_true ** 2).T @
                                                   (x ** 2)).sum())
            y = int(score > 0.15)
            row = " ".join("%d:%.4f" % (i, v)
                           for i, v in zip(sorted(ids), x[sorted(ids)]))
            fh.write("%d %s\n" % (y, row))


def fm_model(num_features, factor_dim):
    import mxnet_tpu as mx
    S = mx.sym
    x = S.Variable("data", stype="csr")                 # (N, D)
    w = S.Variable("w", shape=(num_features, 1),
                   init=mx.initializer.Normal(sigma=0.01))
    v = S.Variable("v", shape=(num_features, factor_dim),
                   init=mx.initializer.Normal(sigma=0.05))
    w0 = S.Variable("w0", shape=(1,),
                    init=mx.initializer.Zero())
    linear = S.dot(x, w)                                # (N, 1)
    vx = S.dot(x, v)                                    # (N, F)
    x2 = x * x
    v2 = v * v
    inter = 0.5 * (S.sum(vx * vx, axis=1, keepdims=True)
                   - S.sum(S.dot(x2, v2), axis=1, keepdims=True))
    score = S.broadcast_add(linear + inter, S.Reshape(w0, shape=(1, 1)))
    label = S.Variable("softmax_label")
    # logistic loss via the stable formulation
    z = S.Reshape(score, shape=(-1,))
    loss = S.mean(S.relu(z) - z * label + S.log(1 + S.exp(-S.abs(z))))
    return S.Group([S.MakeLoss(loss, name="logloss"),
                    S.BlockGrad(S.sigmoid(z), name="prob")])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--factor-dim", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--num-features", type=int, default=200)
    ap.add_argument("--num-obs", type=int, default=2048)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import mxnet_tpu as mx

    tmp = tempfile.NamedTemporaryFile("w", suffix=".libsvm", delete=False)
    tmp.close()
    synthetic_fm_libsvm(tmp.name, n=args.num_obs, dim=args.num_features)
    it = mx.io.LibSVMIter(data_libsvm=tmp.name,
                          data_shape=(args.num_features,),
                          batch_size=args.batch_size)

    mod = mx.mod.Module(fm_model(args.num_features, args.factor_dim),
                        data_names=["data"],
                        label_names=["softmax_label"], context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="adam",
                       optimizer_params=(("learning_rate", args.lr),))

    first = last = None
    for epoch in range(args.num_epochs):
        it.reset()
        tot = nb = 0
        for batch in it:
            mod._fit_step(batch)
            tot += float(mod.get_outputs()[0].asnumpy())
            nb += 1
        mean = tot / nb
        first = mean if first is None else first
        last = mean
        logging.info("epoch %d logloss %.4f", epoch, mean)

    # held-in accuracy
    it.reset()
    correct = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        prob = mod.get_outputs()[1].asnumpy()
        y = batch.label[0].asnumpy()
        correct += int(((prob > 0.5) == y).sum())
        total += len(y)
    acc = correct / max(total, 1)
    print("fm first_loss %.4f last_loss %.4f acc %.4f"
          % (first, last, acc))
    os.unlink(tmp.name)
    return first, last, acc


if __name__ == "__main__":
    main()
