#!/usr/bin/env python
"""CNN text classification (Kim 2014) on synthetic token sequences.

Parity target: reference ``example/cnn_text_classification`` — embedding
-> parallel Conv1D banks of widths (3, 4, 5) -> max-over-time pooling ->
dropout -> dense softmax. Synthetic task: each class has a set of
signature trigrams planted into random token noise; the conv filters must
learn to detect them. Gate: held-out accuracy well above chance.

    python examples/cnn_text_classification.py --num-epochs 6
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

VOCAB = 200
SEQ = 24
CLASSES = 3
EMBED = 16


_SIG_RNG = np.random.RandomState(123)
# 2 signature trigrams per class over a reserved token range — fixed
# across train AND validation sets
SIGS = {c: [_SIG_RNG.randint(0, 60, 3) + 1 for _ in range(2)]
        for c in range(CLASSES)}


def make_set(n, rng=None):
    rng = rng or np.random.RandomState(9)
    sigs = SIGS
    xs = rng.randint(61, VOCAB, (n, SEQ)).astype(np.float32)
    ys = rng.randint(0, CLASSES, n).astype(np.float32)
    for i in range(n):
        sig = sigs[int(ys[i])][rng.randint(2)]
        pos = rng.randint(0, SEQ - 3)
        xs[i, pos:pos + 3] = sig
    return xs, ys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--dropout", type=float, default=0.3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    class TextCNN(gluon.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = gluon.nn.Embedding(VOCAB, EMBED)
                self.convs = []
                for i, width in enumerate((3, 4, 5)):
                    conv = gluon.nn.Conv2D(24, kernel_size=(width, EMBED),
                                           activation="relu")
                    setattr(self, "conv%d" % i, conv)
                    self.convs.append((width, conv))
                self.drop = gluon.nn.Dropout(args.dropout)
                self.out = gluon.nn.Dense(CLASSES)

        def forward(self, tokens):                      # (N, SEQ)
            e = self.embed(tokens)                      # (N, SEQ, E)
            e = nd.expand_dims(e, axis=1)               # (N, 1, SEQ, E)
            pooled = []
            for width, conv in self.convs:
                c = conv(e)                             # (N, F, SEQ-w+1, 1)
                pooled.append(nd.max(c, axis=(2, 3)))   # max over time
            h = nd.concat(*pooled, dim=1)
            return self.out(self.drop(h))

    net = TextCNN()
    net.collect_params().initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    train_x, train_y = make_set(768)
    bs = args.batch_size
    for epoch in range(args.num_epochs):
        tot = 0.0
        nb = 0
        for i in range(0, len(train_x), bs):
            x = nd.array(train_x[i:i + bs])
            y = nd.array(train_y[i:i + bs])
            with autograd.record():
                loss = nd.mean(loss_fn(net(x), y))
            loss.backward()
            trainer.step(x.shape[0])
            tot += float(loss.asnumpy())
            nb += 1
        logging.info("epoch %d loss %.4f", epoch, tot / nb)

    val_x, val_y = make_set(256, rng=np.random.RandomState(77))
    pred = net(nd.array(val_x)).asnumpy().argmax(axis=1)
    acc = float((pred == val_y).mean())
    print("val accuracy: %.3f" % acc)
    return acc


if __name__ == "__main__":
    main()
