#!/usr/bin/env python
"""CustomOp demo: a numpy softmax loss layer inside a Module-trained net.

Parity target: reference ``example/numpy-ops/numpy_softmax.py`` — the
canonical CustomOp walkthrough (python/mxnet/operator.py). The op's
forward/backward are plain numpy; they run on host behind
``jax.pure_callback`` while the rest of the graph compiles to XLA.

    python examples/numpy_ops_softmax.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


class NumpySoftmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        e = np.exp(x - x.max(axis=1, keepdims=True))
        self.assign(out_data[0], req[0], e / e.sum(axis=1, keepdims=True))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        label = in_data[1].asnumpy().ravel().astype(np.int64)
        grad = out_data[0].asnumpy().copy()
        grad[np.arange(label.shape[0]), label] -= 1.0
        self.assign(in_grad[0], req[0], grad)


@mx.operator.register("numpy_softmax")
class NumpySoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return NumpySoftmax()


def main():
    from mxnet_tpu.test_utils import get_mnist_iterator
    import logging
    logging.basicConfig(level=logging.INFO)

    train_iter, val_iter = get_mnist_iterator(batch_size=64, flat=True)

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    h = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    net = mx.sym.Custom(h, label, op_type="numpy_softmax", name="softmax")

    mod = mx.mod.Module(net, context=mx.context.current_context())
    mod.fit(train_iter, eval_data=val_iter, eval_metric="acc",
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            num_epoch=3)
    acc = mod.score(val_iter, "acc")[0][1]
    print("final validation accuracy with numpy CustomOp head: %.4f" % acc)
    return acc


if __name__ == "__main__":
    main()
