#!/usr/bin/env python
"""Toy sequence recognition with LSTM + CTC.

Parity target: reference ``example/ctc`` (LSTM-OCR on captchas) reduced to
its skeleton: a synthetic "stripe image" per digit string (each digit
renders as a distinctive column pattern with variable width) -> LSTM over
columns -> per-frame logits -> ``CTCLoss`` -> greedy CTC decode. The gate
is label error rate: untrained LER ~1.0, trained well below.

    python examples/ctc_ocr_toy.py --num-epochs 10
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

N_CLASS = 5          # digits 1..5 (0 = CTC blank, blank_label="first")
T = 20               # frames (columns)
H = 8                # column height
MAXLEN = 4


def render(seq, rng):
    """Each digit d occupies 2-4 columns lighting row d (+ a faint row
    d+2 texture); gaps of 1-2 blank columns between digits. Returns the
    image AND the digits actually drawn (a digit that would overflow the
    T frames is dropped from the label too)."""
    img = np.zeros((T, H), np.float32)
    t = rng.randint(0, 2)
    drawn = []
    for d in seq:
        w = rng.randint(2, 5)
        if t + w > T:
            break
        drawn.append(int(d))
        for _ in range(w):
            img[t, d] = 1.0
            img[t, (d + 2) % H] = 0.4
            t += 1
        t += rng.randint(1, 3)
    img += rng.randn(T, H).astype(np.float32) * 0.05
    return img, drawn


def make_set(n, rng=None):
    rng = rng or np.random.RandomState(3)
    xs = np.zeros((n, T, H), np.float32)
    labels = np.zeros((n, MAXLEN), np.float32)   # 0-padded
    for i in range(n):
        k = rng.randint(1, MAXLEN + 1)
        seq = rng.randint(1, N_CLASS + 1, size=k)
        xs[i], drawn = render(seq, rng)
        if not drawn:           # ensure at least one digit rendered
            xs[i, 2:4, 1] = 1.0
            drawn = [1]
        labels[i, :len(drawn)] = drawn
    return xs, labels


def greedy_decode(logits):
    """logits (T, N, C) -> list of label lists (collapse repeats, drop
    blank=0)."""
    ids = logits.argmax(axis=2).T      # (N, T)
    out = []
    for row in ids:
        seq, prev = [], -1
        for c in row:
            if c != prev and c != 0:
                seq.append(int(c))
            prev = c
        out.append(seq)
    return out


def ler(pred, truth):
    """Mean normalized edit distance."""
    def edit(a, b):
        dp = np.arange(len(b) + 1, dtype=np.int32)
        for i, ca in enumerate(a, 1):
            prev, dp[0] = dp[0], i
            for j, cb in enumerate(b, 1):
                prev, dp[j] = dp[j], min(dp[j] + 1, dp[j - 1] + 1,
                                         prev + (ca != cb))
        return dp[-1]
    return float(np.mean([edit(p, t) / max(len(t), 1)
                          for p, t in zip(pred, truth)]))


def build_symbols(hidden=32):
    """LSTM -> per-frame logits -> CTCLoss, all symbolic (the reference
    lstm_ocr pattern: sym unroll + WarpCTC + Module). Returns
    (train_symbol, logits_symbol) sharing parameter names."""
    import mxnet_tpu as mx
    S = mx.sym
    data = S.Variable("data")                       # (N, T, H)
    label = S.Variable("label")                     # (N, MAXLEN)
    cell = mx.rnn.LSTMCell(num_hidden=hidden, prefix="lstm_")
    outputs, _ = cell.unroll(T, inputs=data, layout="NTC",
                             merge_outputs=True)    # (N, T, hidden)
    pred = S.Reshape(outputs, shape=(-1, hidden))
    pred = S.FullyConnected(pred, num_hidden=N_CLASS + 1, name="proj")
    logits = S.transpose(S.Reshape(pred, shape=(-1, T, N_CLASS + 1)),
                         axes=(1, 0, 2))            # (T, N, C)
    loss = S.contrib.CTCLoss(logits, label, blank_label="first",
                             name="ctc")
    return S.MakeLoss(S.mean(loss), name="ctc_loss"), logits


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.io import NDArrayIter

    train_x, train_y = make_set(512)
    it = NDArrayIter(train_x, train_y, batch_size=args.batch_size,
                     shuffle=True, label_name="label")
    train_sym, logits_sym = build_symbols()
    mod = mx.mod.Module(train_sym, data_names=["data"],
                        label_names=["label"], context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params=(("learning_rate", args.lr),))

    for epoch in range(args.num_epochs):
        it.reset()
        tot = nb = 0
        for batch in it:
            mod._fit_step(batch)        # ONE compiled fwd+bwd+adam program
            tot += float(mod.get_outputs()[0].asnumpy())
            nb += 1
        logging.info("epoch %d ctc loss %.4f", epoch, tot / nb)

    # decode through a shared-weight logits executor
    val_x, val_y = make_set(128, rng=np.random.RandomState(42))
    arg_params, aux_params = mod.get_params()
    ex = logits_sym.simple_bind(mx.cpu(), grad_req="null",
                                data=(len(val_x), T, H))
    ex.copy_params_from(arg_params, aux_params, allow_extra_params=True)
    ex.arg_dict["data"][:] = val_x
    logits = ex.forward()[0].asnumpy()
    pred = greedy_decode(logits)
    truth = [[int(c) for c in row if c != 0] for row in val_y]
    rate = ler(pred, truth)
    print("label error rate: %.3f" % rate)
    return rate


if __name__ == "__main__":
    main()
