#!/usr/bin/env python
"""Variational autoencoder on synthetic 8x8 two-mode images.

Parity target: reference ``example/autoencoder`` / VAE notebooks — MLP
encoder to (mu, log-var), reparameterized sample, MLP decoder with
Bernoulli likelihood, trained on the ELBO. Synthetic data mixes two
structured modes (horizontal vs vertical bars) plus noise; the gate is
that the trained ELBO beats the untrained one by a wide margin and that
reconstructions beat a mean-image baseline.

    python examples/vae_toy.py --num-epochs 15
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

D = 64          # 8x8
LATENT = 4


def make_set(n, rng=None):
    rng = rng or np.random.RandomState(13)
    xs = np.zeros((n, D), np.float32)
    for i in range(n):
        img = np.zeros((8, 8), np.float32)
        if rng.rand() < 0.5:
            img[rng.randint(8), :] = 1.0        # horizontal bar
            img[rng.randint(8), :] = 1.0
        else:
            img[:, rng.randint(8)] = 1.0        # vertical bar
            img[:, rng.randint(8)] = 1.0
        flip = rng.rand(8, 8) < 0.02
        img = np.where(flip, 1.0 - img, img)
        xs[i] = img.reshape(-1)
    return xs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=15)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    class VAE(gluon.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.enc = gluon.nn.Dense(32, activation="relu")
                self.mu = gluon.nn.Dense(LATENT)
                self.logvar = gluon.nn.Dense(LATENT)
                self.dec1 = gluon.nn.Dense(32, activation="relu")
                self.dec2 = gluon.nn.Dense(D)

        def forward(self, x):
            h = self.enc(x)
            mu, logvar = self.mu(h), self.logvar(h)
            eps = nd.array(mx.random.host_rng()
                           .standard_normal(mu.shape)
                           .astype(np.float32))
            z = mu + nd.exp(0.5 * logvar) * eps     # reparameterization
            logits = self.dec2(self.dec1(z))
            return logits, mu, logvar

    def elbo_terms(net, x):
        logits, mu, logvar = net(x)
        # Bernoulli log-likelihood via numerically stable logistic CE
        ll = -(nd.relu(logits) - logits * x
               + nd.log(1 + nd.exp(-nd.abs(logits))))
        recon = nd.sum(ll, axis=1)
        kl = -0.5 * nd.sum(1 + logvar - mu * mu - nd.exp(logvar), axis=1)
        return nd.mean(recon - kl), logits

    net = VAE()
    net.collect_params().initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    train_x = make_set(1024)
    val_x = make_set(256, rng=np.random.RandomState(91))
    mx.random.seed(0)
    elbo0, _ = elbo_terms(net, nd.array(val_x))
    elbo0 = float(elbo0.asnumpy())

    bs = args.batch_size
    for epoch in range(args.num_epochs):
        tot = 0.0
        nb = 0
        for i in range(0, len(train_x), bs):
            x = nd.array(train_x[i:i + bs])
            with autograd.record():
                elbo, _ = elbo_terms(net, x)
                loss = -elbo
            loss.backward()
            trainer.step(x.shape[0])
            tot += float(elbo.asnumpy())
            nb += 1
        logging.info("epoch %d elbo %.2f", epoch, tot / nb)

    elbo1, logits = elbo_terms(net, nd.array(val_x))
    elbo1 = float(elbo1.asnumpy())
    recon = 1 / (1 + np.exp(-logits.asnumpy()))
    err = float(np.mean((recon - val_x) ** 2))
    base = float(np.mean((train_x.mean(axis=0)[None] - val_x) ** 2))
    print("elbo untrained %.2f trained %.2f recon mse %.4f baseline %.4f"
          % (elbo0, elbo1, err, base))
    return elbo1 - elbo0, base - err


if __name__ == "__main__":
    main()
