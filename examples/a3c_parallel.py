#!/usr/bin/env python
"""Parallel advantage actor-critic: batched envs, one forward per step.

Parity target: reference ``example/reinforcement-learning/a3c/`` +
``parallel_actor_critic/`` — N environments advanced in lockstep, ONE
batched policy/value forward per timestep (train.py:31-75), trajectories
accumulated per env, discounted returns + advantage (R - V) driving the
policy-gradient loss and an L2 value loss, with an entropy bonus for
exploration (model.py loss assembly). The reference's async multi-worker
variant shards envs over processes; here env parallelism is a BATCH
dimension — the TPU-native layout, where one XLA program serves all envs
and scaling envs means growing the batch, not forking workers.

Gym/Atari is replaced by a vectorized windy-corridor (zero-egress).

    python examples/a3c_parallel.py --num-updates 150
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


class VectorCorridor(object):
    """N independent 1-D corridors advanced in lockstep (numpy-batched).
    +1 at the right end, -1 at the left, -0.01 per step, cap 4n steps."""

    def __init__(self, num_envs, n=9, seed=0):
        self.num_envs, self.n = num_envs, n
        self.rng = np.random.RandomState(seed)
        self.pos = np.full(num_envs, n // 2)
        self.t = np.zeros(num_envs, np.int32)

    def obs(self):
        one = np.zeros((self.num_envs, self.n), np.float32)
        one[np.arange(self.num_envs), self.pos] = 1.0
        return one

    def step(self, actions):
        self.pos += np.where(actions == 1, 1, -1)
        # stochastic headwind near the goal
        wind = (self.pos >= self.n - 3) & (self.rng.rand(self.num_envs) < 0.2)
        self.pos = np.clip(self.pos - wind, 0, self.n - 1)
        self.t += 1
        reward = np.full(self.num_envs, -0.01, np.float32)
        done = np.zeros(self.num_envs, bool)
        done |= self.pos <= 0
        reward[self.pos <= 0] = -1.0
        done |= self.pos >= self.n - 1
        reward[self.pos >= self.n - 1] = 1.0
        done |= self.t >= 4 * self.n
        if done.any():             # auto-reset finished envs
            self.pos[done] = self.n // 2
            self.t[done] = 0
        return self.obs(), reward, done


class ACNet(gluon.Block):
    """Shared trunk + policy/value heads (ref a3c/sym.py:24-39)."""

    def __init__(self, obs_dim, n_actions, hidden=64):
        super().__init__()
        self.trunk = nn.Dense(hidden, in_units=obs_dim, activation="relu")
        self.policy = nn.Dense(n_actions, in_units=hidden)
        self.value = nn.Dense(1, in_units=hidden)

    def forward(self, x):
        h = self.trunk(x)
        return self.policy(h), self.value(h)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-updates", type=int, default=150)
    ap.add_argument("--num-envs", type=int, default=16)
    ap.add_argument("--t-max", type=int, default=20)   # rollout length
    ap.add_argument("--gamma", type=float, default=0.97)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--entropy-beta", type=float, default=0.01)
    args = ap.parse_args()

    envs = VectorCorridor(args.num_envs, seed=3)
    rng = np.random.RandomState(4)
    net = ACNet(envs.n, 2)
    net.collect_params().initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    obs = envs.obs()
    recent = []
    for update in range(args.num_updates):
        # ---- rollout: t_max lockstep env steps, one batched fwd each ----
        traj_obs, traj_act, traj_rew, traj_done = [], [], [], []
        for _ in range(args.t_max):
            logits, _ = net(mx.nd.array(obs))
            probs = np.asarray(
                mx.nd.softmax(logits).asnumpy(), np.float64)
            probs /= probs.sum(axis=1, keepdims=True)
            acts = np.array([rng.choice(2, p=p) for p in probs])
            nxt, rew, done = envs.step(acts)
            traj_obs.append(obs)
            traj_act.append(acts)
            traj_rew.append(rew)
            traj_done.append(done)
            obs = nxt
        recent.append(np.concatenate(traj_rew).mean())

        # ---- n-step discounted returns, zeroed at episode ends ----
        _, v_last = net(mx.nd.array(obs))
        ret = v_last.asnumpy()[:, 0]
        returns = np.zeros((args.t_max, args.num_envs), np.float32)
        for t in reversed(range(args.t_max)):
            ret = np.where(traj_done[t], 0.0, ret)
            ret = traj_rew[t] + args.gamma * ret
            returns[t] = ret

        flat_obs = np.concatenate(traj_obs)                 # (T*N, obs)
        flat_act = np.concatenate(traj_act).astype(np.float32)
        flat_ret = returns.reshape(-1)

        # ---- ONE batched policy-gradient + value + entropy update ----
        with autograd.record():
            logits, values = net(mx.nd.array(flat_obs))
            logp = mx.nd.log_softmax(logits)
            p = mx.nd.softmax(logits)
            chosen = mx.nd.sum(
                logp * mx.nd.one_hot(mx.nd.array(flat_act), 2), axis=1)
            adv = mx.nd.array(flat_ret) - mx.nd.reshape(values, (-1,))
            pg_loss = -mx.nd.mean(chosen * mx.nd.BlockGrad(adv))
            v_loss = mx.nd.mean(mx.nd.square(adv))
            entropy = -mx.nd.mean(mx.nd.sum(p * logp, axis=1))
            loss = pg_loss + 0.5 * v_loss - args.entropy_beta * entropy
        loss.backward()
        trainer.step(1)

        if (update + 1) % 30 == 0:
            print("update %d mean-step-reward %.4f"
                  % (update + 1, np.mean(recent[-30:])))

    print("final-mean-step-reward %.4f" % np.mean(recent[-30:]))


if __name__ == "__main__":
    main()
