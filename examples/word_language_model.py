#!/usr/bin/env python
"""Word-level language model: Gluon Embedding + LSTM + truncated BPTT.

Parity target: reference ``example/gluon/word_language_model/train.py``
(Embedding -> N-layer LSTM -> Dense decoder, hidden state carried across
unrolled segments and detached between them, grad clipping, perplexity
reporting).

Without ``--data`` (a whitespace-tokenized text file) a synthetic
Markov-chain corpus is generated so the script runs hermetically; its
structure is learnable, so perplexity drops well below the uniform
baseline within an epoch.

    python examples/word_language_model.py --num-epochs 2
"""
import argparse
import logging
import math
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def synthetic_corpus(vocab=64, length=20000, seed=3):
    """First-order Markov chain with a sparse transition matrix: each
    token admits only 4 successors, so an LSTM can reach ppl ~4 while a
    uniform model sits at `vocab`."""
    rng = np.random.RandomState(seed)
    succ = np.stack([rng.choice(vocab, size=4, replace=False)
                     for _ in range(vocab)])
    toks = np.empty(length, np.int64)
    toks[0] = 0
    for t in range(1, length):
        toks[t] = succ[toks[t - 1]][rng.randint(4)]
    return toks, vocab


def batchify(tokens, batch_size):
    """Fold the corpus into (steps, batch_size) columns (ref train.py)."""
    nstep = len(tokens) // batch_size
    return tokens[:nstep * batch_size].reshape(batch_size, nstep).T


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None, help="tokenized text file")
    ap.add_argument("--emsize", type=int, default=64)
    ap.add_argument("--nhid", type=int, default=128)
    ap.add_argument("--nlayers", type=int, default=1)
    ap.add_argument("--bptt", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--clip", type=float, default=0.25)
    ap.add_argument("--max-batches", type=int, default=0,
                    help="cap batches per epoch (0 = all)")
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    if args.data:
        words = open(args.data).read().split()
        idx = {w: i for i, w in enumerate(sorted(set(words)))}
        tokens = np.array([idx[w] for w in words], np.int64)
        vocab = len(idx)
    else:
        tokens, vocab = synthetic_corpus()

    class RNNModel(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = gluon.nn.Embedding(vocab, args.emsize)
                self.rnn = gluon.rnn.LSTM(args.nhid, args.nlayers,
                                          layout="TNC")
                self.decoder = gluon.nn.Dense(vocab, flatten=False)

        def hybrid_forward(self, F, x, *state):
            emb = self.embed(x)
            out, state = self.rnn(emb, list(state))
            return self.decoder(out), state

    model = RNNModel()
    model.collect_params().initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    data = batchify(tokens, args.batch_size)     # (steps, B)
    nbatch = (data.shape[0] - 1) // args.bptt
    if args.max_batches:
        nbatch = min(nbatch, args.max_batches)

    for epoch in range(args.num_epochs):
        state = model.rnn.begin_state(args.batch_size)
        total_nll, total_tok = 0.0, 0
        for i in range(nbatch):
            seg = data[i * args.bptt:(i + 1) * args.bptt + 1]
            x = nd.array(seg[:-1])
            y = nd.array(seg[1:])
            # truncated BPTT: carry state values, cut the graph
            state = [s.detach() for s in state]
            with autograd.record():
                logits, state = model(x, *state)
                loss = loss_fn(logits.reshape((-1, vocab)),
                               y.reshape((-1,)))
            loss.backward()
            grads = [p.grad() for p in model.collect_params().values()
                     if p.grad_req != "null"]
            gluon.utils.clip_global_norm(grads,
                                         args.clip * args.bptt *
                                         args.batch_size)
            trainer.step(args.bptt * args.batch_size)
            total_nll += float(loss.asnumpy().sum())
            total_tok += args.bptt * args.batch_size
        ppl = math.exp(total_nll / total_tok)
        logging.info("epoch %d: train ppl %.2f (uniform baseline %.1f)",
                     epoch, ppl, vocab)
    print("final-perplexity: %.3f" % ppl)
    return ppl


if __name__ == "__main__":
    main()
