#!/usr/bin/env python
"""Fine-tuning: load a pretrained checkpoint, swap the classifier head,
and continue training on a new task.

Parity target: the reference fine-tune workflow
(``example/image-classification/fine-tune.py``, README.md:199-206 —
caltech256 from an ImageNet checkpoint): take `prefix-symbol.json` +
`.params`, cut the graph at the feature layer, attach a fresh
FullyConnected head for the new label space, and `fit` with
``arg_params`` carried over and ``allow_missing=True`` so only the new
head is freshly initialized.

Hermetic: stage 1 pretrains a small conv net on synthetic task A
(4-way prototype patterns); task B's 3 classes are *mixtures of task
A's prototypes* under heavier noise, so the pretrained features
genuinely transfer — the gate is that fine-tuning beats training the
same net from scratch on the same small budget.

    python examples/fine_tune.py --pretrain-epochs 3 --tune-epochs 1
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

_SIZE = 12
_PROTOS_A = np.random.RandomState(1).rand(
    4, 1, _SIZE, _SIZE).astype(np.float32)
# task B classes are combinations of task A's prototypes: shared
# low-level structure is what makes transfer meaningful
_COMB = np.array([[.7, .3, 0, 0], [0, .7, .3, 0], [0, 0, .7, .3]],
                 np.float32)
_PROTOS_B = np.einsum("ij,jchw->ichw", _COMB, _PROTOS_A)


def make_task(protos, n, seed, mix):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, len(protos), n)
    x = mix * protos[y] + (1 - mix) * rng.rand(
        n, 1, _SIZE, _SIZE).astype(np.float32)
    return x, y.astype(np.float32)


def feature_net():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    h = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3), name="c1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type="max")
    h = mx.sym.Convolution(h, num_filter=16, kernel=(3, 3), name="c2")
    h = mx.sym.Activation(h, act_type="relu", name="features")
    return mx.sym.Flatten(h)


def with_head(features, num_classes, name):
    import mxnet_tpu as mx
    fc = mx.sym.FullyConnected(features, num_hidden=num_classes,
                               name=name)
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def fit_and_score(sym, train, val, epochs, arg_params=None,
                  allow_missing=False, lr=0.05):
    import mxnet_tpu as mx
    from mxnet_tpu.io import NDArrayIter
    Xt, Yt = train
    Xv, Yv = val
    it = NDArrayIter(Xt, Yt, batch_size=32, shuffle=True)
    vit = NDArrayIter(Xv, Yv, batch_size=32)
    mod = mx.mod.Module(sym)
    mod.fit(it, num_epoch=epochs, optimizer="sgd",
            optimizer_params={"learning_rate": lr, "momentum": 0.9},
            initializer=mx.init.Xavier(),
            arg_params=arg_params, allow_missing=allow_missing)
    return mod, mod.score(vit, "acc")[0][1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pretrain-epochs", type=int, default=3)
    ap.add_argument("--tune-epochs", type=int, default=1)
    ap.add_argument("--tune-samples", type=int, default=128)
    ap.add_argument("--checkpoint-prefix", default=None,
                    help="where to save/load the stage-1 checkpoint "
                         "(default: temp dir)")
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    import tempfile
    import mxnet_tpu as mx
    from mxnet_tpu.model import load_checkpoint

    tmp_dir = None
    if args.checkpoint_prefix:
        prefix = args.checkpoint_prefix
    else:
        tmp_dir = tempfile.TemporaryDirectory()
        prefix = os.path.join(tmp_dir.name, "pretrained")

    # --- stage 1: pretrain on task A, save reference-format checkpoint
    XA, YA = make_task(_PROTOS_A, 2048, seed=11, mix=0.7)
    base = with_head(feature_net(), 4, name="head_a")
    mod, acc_a = fit_and_score(base, (XA[:1792], YA[:1792]),
                               (XA[1792:], YA[1792:]),
                               args.pretrain_epochs)
    mod.save_checkpoint(prefix, args.pretrain_epochs)
    logging.info("stage 1 (task A) val acc: %.3f", acc_a)

    # --- stage 2: fine-tune to task B with a fresh head
    _, arg_params, _ = load_checkpoint(prefix, args.pretrain_epochs)
    arg_params = {k: v for k, v in arg_params.items()
                  if not k.startswith("head_a")}
    nt = args.tune_samples
    XB, YB = make_task(_PROTOS_B, nt + 256, seed=22, mix=0.5)
    train_b, val_b = (XB[:nt], YB[:nt]), (XB[nt:], YB[nt:])
    tuned_sym = with_head(feature_net(), 3, name="head_b")
    _, acc_tuned = fit_and_score(
        tuned_sym, train_b, val_b, args.tune_epochs,
        arg_params=arg_params, allow_missing=True)

    # --- control: same budget from scratch
    _, acc_scratch = fit_and_score(tuned_sym, train_b, val_b,
                                   args.tune_epochs)

    logging.info("task B val acc: fine-tuned %.3f vs scratch %.3f",
                 acc_tuned, acc_scratch)
    print("final-finetune-acc: %.4f (scratch %.4f)"
          % (acc_tuned, acc_scratch))
    if tmp_dir is not None:
        tmp_dir.cleanup()
    return acc_tuned, acc_scratch


if __name__ == "__main__":
    main()
