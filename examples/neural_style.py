#!/usr/bin/env python
"""Neural style transfer: optimize the INPUT image by gradient descent.

Parity target: reference ``example/neural-style`` — content + style
(Gram-matrix) losses over conv features, minimized with respect to the
image pixels while the network weights stay fixed. The reference uses
pretrained VGG; with zero egress this uses a fixed random conv feature
bank (random-filter Gram matching is a known-good texture statistic) —
the mechanism under test is identical: autograd with respect to the
input through a deep conv stack, an optimizer stepping the image.

    python examples/neural_style.py --num-steps 60
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

SIZE = 32


def make_style(rng):
    """Diagonal-stripe texture as the style image."""
    y, x = np.mgrid[0:SIZE, 0:SIZE]
    img = (np.sin((x + y) * 0.7) > 0).astype(np.float32)
    return np.stack([img, 1 - img, img * 0.5])[None]   # (1, 3, H, W)


def make_content(rng):
    """A bright square as the content image."""
    img = np.zeros((3, SIZE, SIZE), np.float32)
    img[:, 8:24, 8:24] = 0.9
    return img[None] + rng.rand(1, 3, SIZE, SIZE).astype(np.float32) * 0.05


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-steps", type=int, default=60)
    ap.add_argument("--lr", type=float, default=2.0)
    ap.add_argument("--style-weight", type=float, default=1e4)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(0)
    feat_net = gluon.nn.Sequential()
    for ch in (16, 32):
        feat_net.add(gluon.nn.Conv2D(ch, 3, padding=1, activation="relu"),
                     gluon.nn.MaxPool2D(2))
    feat_net.initialize(mx.init.Xavier(rnd_type="gaussian", magnitude=2))

    def features(img):
        """Taps after each conv block."""
        taps = []
        h = img
        for layer in feat_net._children:
            h = layer(h)
            if h.shape[2] != (taps[-1].shape[2] if taps else -1):
                taps.append(h)
        return taps[:2]

    def gram(f):
        n, c, hh, ww = f.shape
        flat = nd.reshape(f, (c, hh * ww))
        return nd.dot(flat, flat.T) / (c * hh * ww)

    style = nd.array(make_style(rng))
    content = nd.array(make_content(rng))
    style_grams = [gram(f) for f in features(style)]
    content_feats = features(content)

    def style_distance(image):
        return sum(float(nd.mean((gram(f) - g) ** 2).asnumpy())
                   for f, g in zip(features(image), style_grams))

    img = content.copy()
    img.attach_grad()
    d0 = style_distance(img)
    for step in range(args.num_steps):
        with autograd.record():
            feats = features(img)
            c_loss = nd.mean((feats[0] - content_feats[0]) ** 2)
            s_loss = 0
            for f, g_target in zip(feats, style_grams):
                g = gram(f)
                s_loss = s_loss + nd.mean((g - g_target) ** 2)
            loss = c_loss + args.style_weight * s_loss
        loss.backward()
        img[:] = nd.clip(img - args.lr * img.grad, 0.0, 1.0)
        img.attach_grad()
        if step % 20 == 0:
            logging.info("step %d loss %.5f", step,
                         float(loss.asnumpy()))
    d1 = style_distance(img)
    print("style gram distance start %.6f end %.6f ratio %.3f"
          % (d0, d1, d1 / max(d0, 1e-12)))
    return d0, d1


if __name__ == "__main__":
    main()
