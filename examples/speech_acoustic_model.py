#!/usr/bin/env python
"""Speech acoustic model: bidirectional LSTM over spectrogram frames.

Parity target: reference ``example/speech-demo/`` +
``example/speech_recognition/`` — LSTM/BiLSTM acoustic models mapping
frame features to per-frame senone/phoneme posteriors
(``speech-demo/lstm_proj.py``, ``train_lstm_proj.py``: stacked LSTM +
frame-wise softmax over Kaldi features; ``speech_recognition/arch.py``:
the BiLSTM front of DeepSpeech). The Kaldi/LibriSpeech pipeline is
replaced by a procedural corpus: each "phoneme" is a characteristic
spectral envelope (formant bumps) + noise, utterances are random
phoneme strings with varying dwell times, labels are per-frame
(zero-egress).

The model is the framework's symbolic BiLSTM (two ``mx.rnn`` unrolls,
one on reversed frames) with a frame-wise SoftmaxOutput — the
speech-demo topology — trained through Module with bucketing-free
fixed-length batches.

    python examples/speech_acoustic_model.py --num-epochs 3
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx

N_PHONE = 6
N_MEL = 20


def phoneme_bank(rng):
    """Each phoneme: 2 formant bumps over the mel axis."""
    bank = np.zeros((N_PHONE, N_MEL), np.float32)
    mel = np.arange(N_MEL)
    for p in range(N_PHONE):
        for _ in range(2):
            center = rng.randint(2, N_MEL - 2)
            bank[p] += np.exp(-0.5 * ((mel - center) / 1.5) ** 2)
    return bank


def make_utterances(n, frames, bank, rng):
    x = np.zeros((n, frames, N_MEL), np.float32)
    y = np.zeros((n, frames), np.float32)
    for i in range(n):
        t = 0
        while t < frames:
            p = rng.randint(N_PHONE)
            dwell = rng.randint(3, 8)
            for _ in range(dwell):
                if t >= frames:
                    break
                x[i, t] = bank[p] + 0.3 * rng.randn(N_MEL)
                y[i, t] = p
                t += 1
    return x, y


def bilstm_symbol(frames, hidden):
    """Frame-wise BiLSTM posteriors (ref speech-demo/lstm_proj.py
    topology: stacked recurrence + per-frame softmax)."""
    data = mx.sym.Variable("data")                        # (N, T, F)
    label = mx.sym.Variable("softmax_label")              # (N, T)
    fwd_cell = mx.rnn.LSTMCell(num_hidden=hidden, prefix="fw_")
    bwd_cell = mx.rnn.LSTMCell(num_hidden=hidden, prefix="bw_")
    fwd, _ = fwd_cell.unroll(frames, inputs=data, merge_outputs=True)
    rev = mx.sym.SequenceReverse(mx.sym.transpose(data, axes=(1, 0, 2)))
    rev = mx.sym.transpose(rev, axes=(1, 0, 2))
    bwd, _ = bwd_cell.unroll(frames, inputs=rev, merge_outputs=True)
    bwd = mx.sym.transpose(
        mx.sym.SequenceReverse(mx.sym.transpose(bwd, axes=(1, 0, 2))),
        axes=(1, 0, 2))
    both = mx.sym.Concat(fwd, bwd, dim=2)                 # (N, T, 2H)
    pred = mx.sym.Reshape(both, shape=(-1, 2 * hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=N_PHONE, name="post")
    lab = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, lab, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--num-utts", type=int, default=256)
    ap.add_argument("--frames", type=int, default=24)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    mx.random.seed(4)
    rng = np.random.RandomState(14)
    bank = phoneme_bank(rng)
    x, y = make_utterances(args.num_utts, args.frames, bank, rng)
    xv, yv = make_utterances(64, args.frames, bank, rng)

    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(bilstm_symbol(args.frames, args.hidden),
                        context=mx.context.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params=(("learning_rate", args.lr),))
    for epoch in range(args.num_epochs):
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()

    vit = mx.io.NDArrayIter(xv, yv, batch_size=args.batch_size,
                            label_name="softmax_label")
    correct = total = 0
    for batch in vit:
        mod.forward(batch, is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
        lab = batch.label[0].asnumpy().reshape(-1)
        correct += (pred == lab).sum()
        total += lab.size
    print("final-frame-acc %.4f" % (correct / total))


if __name__ == "__main__":
    main()
