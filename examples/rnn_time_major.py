#!/usr/bin/env python
"""Time-major RNN training: the layout experiment.

Parity target: reference ``example/rnn-time-major/`` —
``rnn_cell_demo.py`` + ``bucket_io.py`` train the same LSTM language
task with time-major (T, N, C) batches instead of batch-major
(N, T, C), because the fused CUDA RNN kernels want the time axis
leading; the README frames it as a layout-for-speed demo.

On TPU the same holds for a different reason: the unrolled cell is a
``lax.scan`` over the TIME axis, so time-major feeds ``scan`` its
natural leading-axis layout and batch-major pays one transpose on the
way in and out. This example trains the identical model under both
layouts, checks the losses agree (same math, same init), and reports
the per-epoch wall-clock ratio.

    python examples/rnn_time_major.py --num-epochs 2
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx


def make_corpus(n_seq, seq_len, vocab, rng):
    """Deterministic next-token sequences: x_{t+1} = (x_t + step) mod v."""
    data = np.zeros((n_seq, seq_len), np.float32)
    target = np.zeros((n_seq, seq_len), np.float32)
    for i in range(n_seq):
        step = rng.randint(1, 4)
        start = rng.randint(0, vocab)
        seq = (start + step * np.arange(seq_len + 1)) % vocab
        data[i] = seq[:-1]
        target[i] = seq[1:]
    return data, target


def build(seq_len, vocab, hidden, layout):
    """Same graph in either layout; the cell's unroll handles the axis
    bookkeeping (rnn/rnn_cell.py _slice_steps/_merge_steps)."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=16,
                             name="embed")
    if layout == "TNC":
        embed = mx.sym.transpose(embed, axes=(1, 0, 2))   # N,T,C -> T,N,C
    cell = mx.rnn.LSTMCell(num_hidden=hidden, prefix="lstm_")
    outputs, _ = cell.unroll(seq_len, inputs=embed, layout=layout,
                             merge_outputs=True)
    if layout == "TNC":
        outputs = mx.sym.transpose(outputs, axes=(1, 0, 2))
    pred = mx.sym.Reshape(outputs, shape=(-1, hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
    lab = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, lab, name="softmax")


def train(layout, data, target, args, vocab):
    mx.random.seed(100)   # identical init across layouts
    it = mx.io.NDArrayIter(data, target, batch_size=args.batch_size,
                           label_name="softmax_label")
    sym = build(args.seq_len, vocab, args.hidden, layout)
    mod = mx.mod.Module(sym, context=mx.context.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params=(("learning_rate", args.lr),))
    metric = mx.metric.Perplexity(ignore_label=None)
    wall = 0.0
    for epoch in range(args.num_epochs):
        it.reset()
        metric.reset()
        t0 = time.perf_counter()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
        wall += time.perf_counter() - t0
    return metric.get()[1], wall / args.num_epochs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=2)
    ap.add_argument("--num-seq", type=int, default=256)
    ap.add_argument("--seq-len", type=int, default=12)
    ap.add_argument("--vocab", type=int, default=16)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    rng = np.random.RandomState(17)
    data, target = make_corpus(args.num_seq, args.seq_len, args.vocab, rng)

    ppl_tm, t_tm = train("TNC", data, target, args, args.vocab)
    ppl_bm, t_bm = train("NTC", data, target, args, args.vocab)
    print("batch-major ppl %.4f (%.2fs/epoch)" % (ppl_bm, t_bm))
    print("time-major  ppl %.4f (%.2fs/epoch)" % (ppl_tm, t_tm))
    print("layout-ppl-gap %.4f" % abs(ppl_tm - ppl_bm))
    print("final-time-major-ppl %.4f" % ppl_tm)


if __name__ == "__main__":
    main()
