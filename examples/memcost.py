#!/usr/bin/env python
"""Memory-cost study: rematerialization vs stored activations.

Parity target: reference ``example/memcost/`` — scripts that measure
training memory under ``MXNET_BACKWARD_DO_MIRROR`` (recompute
activations in backward instead of storing them, trading ~30% more
compute for O(sqrt(N)) activation memory).

TPU-native version: the mirror flag maps to ``jax.checkpoint`` on
residual-block boundaries (the same policy `tests/test_recompute.py`
gates), and the measurement comes from XLA itself —
``jit(...).lower().compile().memory_analysis()`` reports the compiled
program's temp/argument/output allocation exactly, no device probing
or allocator shims needed.

    python examples/memcost.py --depth 12
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp


def block(params, x):
    w1, w2 = params
    h = jax.nn.relu(x @ w1)
    return x + h @ w2


def make_loss(remat):
    """Depth as a lax.scan over stacked block params — the TPU-idiomatic
    deep-residual form (compile time independent of depth). Without
    remat the scan's backward stores every per-iteration residual in a
    stacked buffer; jax.checkpoint on the body drops them and replays."""
    blk = jax.checkpoint(block) if remat else block

    def loss(stacked, x):
        def step(carry, p):
            return blk(p, carry), None

        out, _ = jax.lax.scan(step, x, stacked)
        return jnp.sum(out * out)

    return loss


def temp_bytes(fn, *args):
    # AOT lower/compile probe: the executable is inspected for its
    # memory_analysis() and never dispatched, so there is no retrace
    # stream for the watchdog to book
    # graftlint: disable=JG002
    compiled = jax.jit(fn).lower(*args).compile()
    mem = compiled.memory_analysis()
    return int(getattr(mem, "temp_size_in_bytes", 0))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--depth", type=int, default=24)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--width", type=int, default=512)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    stacked = (jnp.asarray(rng.randn(args.depth, args.width, args.width)
                           * 0.05, jnp.float32),
               jnp.asarray(rng.randn(args.depth, args.width, args.width)
                           * 0.05, jnp.float32))
    x = jnp.asarray(rng.randn(args.batch, args.width), jnp.float32)

    stored = temp_bytes(jax.grad(make_loss(remat=False)), stacked, x)
    remat = temp_bytes(jax.grad(make_loss(remat=True)), stacked, x)
    if stored <= 0:
        raise RuntimeError("memory_analysis reported no temp allocation; "
                           "the measurement is not working on this backend")
    # gradients must agree: remat is a pure memory/compute trade
    g0 = jax.grad(make_loss(False))(stacked, x)
    g1 = jax.grad(make_loss(True))(stacked, x)
    gap = max(float(jnp.max(jnp.abs(a - b)))
              for a, b in zip(g0, g1))

    print("stored-activations-temp-bytes %d" % stored)
    print("remat-temp-bytes %d" % remat)
    print("grad-max-gap %.3e" % gap)
    print("final-memory-ratio %.3f" % (remat / max(stored, 1)))


if __name__ == "__main__":
    main()
