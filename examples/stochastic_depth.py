#!/usr/bin/env python
"""Stochastic depth: residual blocks randomly dropped during training.

Parity target: reference ``example/stochastic-depth/`` —
``sd_module.py`` wraps each residual block in a module that skips the
block with probability ``death_rate`` during training and scales the
block's contribution by ``1 - death_rate`` at inference;
``sd_cifar10.py:60-108`` ramps the death rate linearly with depth
(death_rate * i / len) over a CIFAR ResNet.

Rebuild: a gluon ``StochasticDepthBlock`` drawing one Bernoulli gate per
block per batch (Huang et al. 2016 linear-decay rule), trained on a
synthetic CIFAR-shaped 4-class texture task (zero-egress).

TPU note: the gate multiplies the residual branch by 0/1 inside the
same jitted program — dropping is data, not control flow, so one XLA
executable covers every gate outcome (no per-pattern retrace; the
reference's module-level skip rebuilds the execution plan instead).

    python examples/stochastic_depth.py --num-epochs 3
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def make_texture_data(n, size, rng):
    """4 classes: horizontal stripes, vertical stripes, checker, blobs."""
    x = rng.randn(n, 3, size, size).astype(np.float32) * 0.3
    y = rng.randint(0, 4, n)
    row = np.arange(size)[:, None]
    col = np.arange(size)[None, :]
    for i in range(n):
        f = rng.randint(2, 5)
        if y[i] == 0:
            pat = np.sin(row * f * np.pi / size) * np.ones((1, size))
        elif y[i] == 1:
            pat = np.ones((size, 1)) * np.sin(col * f * np.pi / size)
        elif y[i] == 2:
            pat = np.sin(row * f * np.pi / size) * \
                np.sin(col * f * np.pi / size)
        else:
            cy, cx = rng.randint(size // 4, 3 * size // 4, 2)
            pat = np.exp(-((row - cy) ** 2 + (col - cx) ** 2)
                         / (2.0 * (size / 6) ** 2))
        x[i] += pat[None].astype(np.float32)
    return x, y.astype(np.float32)


class StochasticDepthBlock(gluon.Block):
    """Residual block whose branch survives with prob 1-death_rate in
    training and is scaled by (1-death_rate) at inference
    (ref sd_module.py decision logic + Huang et al. eq. 5)."""

    def __init__(self, channels, death_rate):
        super().__init__()
        self.death_rate = death_rate
        self.body = nn.HybridSequential()
        self.body.add(
            nn.Conv2D(channels, 3, padding=1, use_bias=False),
            nn.BatchNorm(),
            nn.Activation("relu"),
            nn.Conv2D(channels, 3, padding=1, use_bias=False),
            nn.BatchNorm())

    def forward(self, x):
        branch = self.body(x)
        if autograd.is_training():
            gate = float(mx.random.host_rng().random()
                         >= self.death_rate)
            out = x + gate * branch
        else:
            out = x + (1.0 - self.death_rate) * branch
        return mx.nd.relu(out)


class SDResNet(gluon.Block):
    def __init__(self, num_blocks, channels, classes, final_death=0.5):
        super().__init__()
        self.stem = nn.Conv2D(channels, 3, padding=1)
        self.blocks = nn.Sequential()
        for i in range(num_blocks):
            # linear decay: deeper blocks die more (sd_cifar10.py:60-75)
            rate = final_death * (i + 1) / num_blocks
            self.blocks.add(StochasticDepthBlock(channels, rate))
        self.head = nn.HybridSequential()
        self.head.add(nn.GlobalAvgPool2D(), nn.Dense(classes))

    def forward(self, x):
        return self.head(self.blocks(self.stem(x)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--num-images", type=int, default=512)
    ap.add_argument("--image-size", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-blocks", type=int, default=4)
    ap.add_argument("--death-rate", type=float, default=0.5)
    ap.add_argument("--lr", type=float, default=0.005)
    args = ap.parse_args()

    mx.random.seed(13)
    rng = np.random.RandomState(21)
    x, y = make_texture_data(args.num_images, args.image_size, rng)
    xv, yv = make_texture_data(128, args.image_size, rng)

    net = SDResNet(args.num_blocks, 16, 4, args.death_rate)
    net.collect_params().initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    n_batches = len(x) // args.batch_size
    for epoch in range(args.num_epochs):
        order = rng.permutation(len(x))
        total = 0.0
        for b in range(n_batches):
            idx = order[b * args.batch_size:(b + 1) * args.batch_size]
            data = mx.nd.array(x[idx])
            label = mx.nd.array(y[idx])
            with autograd.record():
                loss = loss_fn(net(data), label)
            loss.backward()
            trainer.step(args.batch_size)
            total += float(loss.asnumpy().mean())
        print("epoch %d loss %.4f" % (epoch, total / n_batches))

    preds = net(mx.nd.array(xv)).asnumpy().argmax(axis=1)
    acc = float((preds == yv).mean())
    print("final-accuracy %.4f" % acc)


if __name__ == "__main__":
    main()
