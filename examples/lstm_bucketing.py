#!/usr/bin/env python
"""PTB-style language modelling with BucketingModule + symbolic LSTM cells.

Parity target: reference ``example/rnn/lstm_bucketing.py`` (BASELINE
workload #3). Reads PTB text files when ``--data-dir`` has them; otherwise
generates a synthetic arithmetic-sequence corpus so the script runs
hermetically.

    python examples/lstm_bucketing.py --num-epochs 5
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

BUCKETS = [10, 20, 30, 40]


def read_ptb(path, vocab=None):
    import mxnet_tpu as mx
    with open(path) as fh:
        sentences = [line.split() for line in fh]
    return mx.rnn.encode_sentences(sentences, vocab=vocab, start_label=1)


def synthetic_corpus(n=600, vocab_size=40):
    """Deterministic next-token sequences (x, x+1, x+2, ...)."""
    rng = np.random.RandomState(3)
    sents = []
    for _ in range(n):
        length = rng.randint(5, 41)
        start = rng.randint(1, vocab_size)
        sents.append([(start + t) % (vocab_size - 1) + 1
                      for t in range(length)])
    return sents, vocab_size + 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None,
                    help="directory with ptb.train.txt / ptb.valid.txt")
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-embed", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    import mxnet_tpu as mx

    if args.data_dir:
        train_sents, vocab = read_ptb(
            os.path.join(args.data_dir, "ptb.train.txt"))
        val_sents, vocab = read_ptb(
            os.path.join(args.data_dir, "ptb.valid.txt"), vocab)
        vocab_size = len(vocab) + 1
    else:
        train_sents, vocab_size = synthetic_corpus(600)
        val_sents, _ = synthetic_corpus(150)

    train_iter = mx.rnn.BucketSentenceIter(train_sents, args.batch_size,
                                           buckets=BUCKETS, invalid_label=0)
    val_iter = mx.rnn.BucketSentenceIter(val_sents, args.batch_size,
                                         buckets=BUCKETS, invalid_label=0)

    stack = mx.rnn.SequentialRNNCell()
    for layer in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                  prefix="lstm_l%d_" % layer))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab_size,
                                 output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs, _ = stack.unroll(seq_len, inputs=embed, merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab_size,
                                     name="pred")
        lab = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, lab, use_ignore=True,
                                    ignore_label=0, name="softmax")
        return pred, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(
        sym_gen, default_bucket_key=train_iter.default_bucket_key,
        context=mx.context.current_context())
    mod.fit(train_iter, eval_data=val_iter,
            eval_metric=mx.metric.Perplexity(ignore_label=0),
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))

    val_iter.reset()
    ppl = mod.score(val_iter, mx.metric.Perplexity(ignore_label=0))[0][1]
    print("final validation perplexity: %.3f" % ppl)
    return ppl


if __name__ == "__main__":
    main()
