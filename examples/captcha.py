#!/usr/bin/env python
"""Multi-digit captcha recognition: one CNN trunk, four digit heads.

Parity target: reference ``example/captcha/`` —
``mxnet_captcha.R``/README train a conv net on 4-digit captcha images
with a grouped 4-way softmax (one head per character position) and
report per-character accuracy. The ImageMagick-generated captchas are
replaced by a procedural 5x3 pixel-font renderer with per-image noise,
jitter, and random stroke dropout (zero-egress).

The grouped-output construction exercises ``mx.sym.Group`` +
multi-label NDArrayIter, the same shape as the reference's
``mx.symbol.Group(list(softmax1, ..., softmax4))``.

    python examples/captcha.py --num-epochs 4
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx

# 5x3 pixel font for digits 0-9
_FONT = {
    0: ["111", "101", "101", "101", "111"],
    1: ["010", "110", "010", "010", "111"],
    2: ["111", "001", "111", "100", "111"],
    3: ["111", "001", "111", "001", "111"],
    4: ["101", "101", "111", "001", "001"],
    5: ["111", "100", "111", "001", "111"],
    6: ["111", "100", "111", "101", "111"],
    7: ["111", "001", "010", "010", "010"],
    8: ["111", "101", "111", "101", "111"],
    9: ["111", "101", "111", "001", "111"],
}


def render_captcha(digits, rng, h=16, w=64):
    """4 digits, scaled 2x, jittered, noisy, with stroke dropout."""
    img = rng.rand(h, w).astype(np.float32) * 0.3
    for pos, d in enumerate(digits):
        glyph = np.array([[float(c) for c in row] for row in _FONT[d]],
                         np.float32)
        glyph = np.kron(glyph, np.ones((2, 2), np.float32))   # 10x6
        glyph *= (rng.rand(*glyph.shape) > 0.1)               # dropout
        r0 = rng.randint(0, h - 10)
        c0 = pos * 16 + rng.randint(0, 16 - 6)
        img[r0:r0 + 10, c0:c0 + 6] += glyph * (0.7 + 0.3 * rng.rand())
    return img[None]          # (1, h, w)


def make_dataset(n, rng):
    x = np.zeros((n, 1, 16, 64), np.float32)
    y = np.zeros((n, 4), np.float32)
    for i in range(n):
        digits = rng.randint(0, 10, 4)
        x[i] = render_captcha(digits, rng)
        y[i] = digits
    return x, y


def captcha_symbol():
    """Conv trunk + 4 per-position softmax heads grouped (the reference's
    Group(softmax1..4) topology)."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")          # (N, 4)
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16,
                             pad=(1, 1), name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=32,
                             pad=(1, 1), name="conv2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    heads = []
    for pos in range(4):
        fc = mx.sym.FullyConnected(net, num_hidden=10,
                                   name="digit%d" % pos)
        lab = mx.sym.slice_axis(label, axis=1, begin=pos, end=pos + 1)
        lab = mx.sym.Reshape(lab, shape=(-1,))
        heads.append(mx.sym.SoftmaxOutput(fc, lab,
                                          name="softmax%d" % pos))
    return mx.sym.Group(heads)


def per_char_accuracy(mod, it):
    it.reset()
    correct = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        outs = mod.get_outputs()
        lab = batch.label[0].asnumpy()
        for pos in range(4):
            pred = outs[pos].asnumpy().argmax(axis=1)
            correct += (pred == lab[:, pos]).sum()
            total += len(pred)
    return correct / total


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--num-images", type=int, default=1024)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.005)
    args = ap.parse_args()

    mx.random.seed(1)
    rng = np.random.RandomState(6)
    x, y = make_dataset(args.num_images, rng)
    xv, yv = make_dataset(256, rng)

    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True,
                           label_name="softmax_label")
    vit = mx.io.NDArrayIter(xv, yv, batch_size=args.batch_size,
                            label_name="softmax_label")
    mod = mx.mod.Module(captcha_symbol(),
                        context=mx.context.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params=(("learning_rate", args.lr),))
    for epoch in range(args.num_epochs):
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
        print("epoch %d val-char-acc %.4f"
              % (epoch, per_char_accuracy(mod, vit)))
    print("final-char-acc %.4f" % per_char_accuracy(mod, vit))


if __name__ == "__main__":
    main()
