#!/usr/bin/env python
"""FCN-xs semantic segmentation: fully-convolutional net with staged
skip fusion (32s -> 16s -> 8s) and bilinear-initialized Deconvolution
upsampling.

Parity target: reference ``example/fcn-xs/`` — ``symbol_fcnxs.py`` builds
fcn32s/fcn16s/fcn8s heads over a conv backbone (score heads on pool3/
pool4, Deconvolution upscores fused by summation, per-pixel
``SoftmaxOutput(multi_output=True)``), ``init_fcnxs.py:28-36`` seeds the
deconv kernels with the bilinear upsample filter. This rebuild keeps
that exact architecture shape on a compact backbone and replaces the
pretrained-VGG + PASCAL pipeline with a synthetic shapes corpus
(zero-egress), so the learnability gate runs anywhere.

TPU notes: the whole net is one jitted XLA program through Module; the
deconvolutions lower to conv_transpose on the MXU; static 32x32 shapes
avoid the reference's crop-offset algebra (symbol_fcnxs.py:21-81) that
existed only because VGG pad=100 made shapes dynamic.

    python examples/fcn_xs.py --num-epochs 3
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx


def make_shapes_dataset(n, size, rng):
    """Images with a bright rectangle (class 1) and a darker disk
    (class 2) on noisy background (class 0)."""
    x = rng.rand(n, 3, size, size).astype(np.float32) * 0.2
    y = np.zeros((n, size, size), np.float32)
    yy, xx = np.mgrid[0:size, 0:size]
    for i in range(n):
        # rectangle
        h, w = rng.randint(size // 4, size // 2, 2)
        r0, c0 = rng.randint(0, size - h), rng.randint(0, size - w)
        x[i, :, r0:r0 + h, c0:c0 + w] += 0.8
        y[i, r0:r0 + h, c0:c0 + w] = 1
        # disk (drawn second: occludes)
        rad = rng.randint(size // 8, size // 4)
        cy, cx = rng.randint(rad, size - rad, 2)
        disk = (yy - cy) ** 2 + (xx - cx) ** 2 <= rad ** 2
        x[i, 0][disk] += 0.5
        x[i, 1][disk] -= 0.1
        y[i][disk] = 2
    return x, y


def fcn_symbol(num_classes, style="fcn8s"):
    """Backbone with three pooling stages + staged skip fusion, the
    fcn-xs head topology (ref symbol_fcnxs.py:84-167)."""
    data = mx.sym.Variable("data")

    def block(x, ch, name):
        x = mx.sym.Convolution(x, kernel=(3, 3), pad=(1, 1),
                               num_filter=ch, name="conv_%s" % name)
        x = mx.sym.Activation(x, act_type="relu")
        return mx.sym.Pooling(x, kernel=(2, 2), stride=(2, 2),
                              pool_type="max", name="pool_%s" % name)

    p1 = block(data, 16, "1")                      # /2
    p2 = block(p1, 32, "2")                        # /4
    p3 = block(p2, 64, "3")                        # /8

    score3 = mx.sym.Convolution(p3, kernel=(1, 1), num_filter=num_classes,
                                name="score_pool3")
    if style == "fcn32s":
        up = mx.sym.Deconvolution(
            score3, kernel=(16, 16), stride=(8, 8), pad=(4, 4),
            num_filter=num_classes, no_bias=True, name="bigscore")
        return mx.sym.SoftmaxOutput(up, mx.sym.Variable("softmax_label"),
                                    multi_output=True, name="softmax")

    # 16s: fuse pool2 evidence at /4
    up3 = mx.sym.Deconvolution(
        score3, kernel=(4, 4), stride=(2, 2), pad=(1, 1),
        num_filter=num_classes, no_bias=True, name="score2")
    score2 = mx.sym.Convolution(p2, kernel=(1, 1), num_filter=num_classes,
                                name="score_pool2")
    fuse2 = up3 + score2
    if style == "fcn16s":
        up = mx.sym.Deconvolution(
            fuse2, kernel=(8, 8), stride=(4, 4), pad=(2, 2),
            num_filter=num_classes, no_bias=True, name="bigscore")
        return mx.sym.SoftmaxOutput(up, mx.sym.Variable("softmax_label"),
                                    multi_output=True, name="softmax")

    # 8s: fuse pool1 evidence at /2
    up2 = mx.sym.Deconvolution(
        fuse2, kernel=(4, 4), stride=(2, 2), pad=(1, 1),
        num_filter=num_classes, no_bias=True, name="score4")
    score1 = mx.sym.Convolution(p1, kernel=(1, 1), num_filter=num_classes,
                                name="score_pool1")
    fuse1 = up2 + score1
    up = mx.sym.Deconvolution(
        fuse1, kernel=(4, 4), stride=(2, 2), pad=(1, 1),
        num_filter=num_classes, no_bias=True, name="bigscore")
    return mx.sym.SoftmaxOutput(up, mx.sym.Variable("softmax_label"),
                                multi_output=True, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--style", default="fcn8s",
                    choices=["fcn32s", "fcn16s", "fcn8s"])
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--num-images", type=int, default=256)
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--num-classes", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.003)
    args = ap.parse_args()

    rng = np.random.RandomState(7)
    x, y = make_shapes_dataset(args.num_images, args.image_size, rng)
    xv, yv = make_shapes_dataset(64, args.image_size, rng)

    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True,
                           label_name="softmax_label")
    sym = fcn_symbol(args.num_classes, args.style)

    mod = mx.mod.Module(sym, context=mx.context.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    # bilinear-seeded DECONV kernels only (ref init_fcnxs.py:28-36
    # upsample_filt); the 1x1 score convs stay Xavier
    mod.init_params(initializer=mx.initializer.Mixed(
        ["(score2|score4|bigscore)_weight", ".*"],
        [mx.initializer.Bilinear(), mx.initializer.Xavier()]))
    mod.init_optimizer(optimizer="adam",
                       optimizer_params=(("learning_rate", args.lr),))
    for epoch in range(args.num_epochs):
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()

    # pixel accuracy + mean IoU on held-out images
    vit = mx.io.NDArrayIter(xv, yv, batch_size=args.batch_size,
                            label_name="softmax_label")
    correct = total = 0
    inter = np.zeros(args.num_classes)
    union = np.zeros(args.num_classes)
    for batch in vit:
        mod.forward(batch, is_train=False)
        scores = mod.get_outputs()[0].asnumpy()     # (N, C, H, W)
        pred = scores.argmax(axis=1)
        lab = batch.label[0].asnumpy()
        correct += (pred == lab).sum()
        total += lab.size
        for c in range(args.num_classes):
            inter[c] += ((pred == c) & (lab == c)).sum()
            union[c] += ((pred == c) | (lab == c)).sum()
    miou = float(np.mean(inter / np.maximum(union, 1)))
    majority = max((yv == c).mean() for c in range(args.num_classes))
    print("majority-baseline %.4f" % majority)
    print("final-miou %.4f" % miou)
    print("final-pixel-acc %.4f" % (correct / total))


if __name__ == "__main__":
    main()
