#!/usr/bin/env python
"""DSD: Dense -> Sparse -> Dense training flow (Han et al. 2017).

Parity target: reference ``example/dsd/`` — ``sparse_sgd.py`` subclasses
SGD so each update re-applies a per-weight binary mask built from a
magnitude threshold (keep the top (1-sparsity) fraction), and
``mlp.py``/README run the three phases: dense training, sparse training
under the mask, then dense retraining from the sparse solution.

Rebuild: the mask lives in a thin ``MaskedSGD`` optimizer subclass
registered through the standard optimizer registry (`optimizer.py`
register), so the sparse phase is plain `Module.fit` with
``optimizer="maskedsgd"`` — mirroring the reference's drop-in
``--optimizer sparsesgd`` switch.

TPU note: the mask multiply fuses into the update program (one XLA
kernel); sparsity here is a TRAINING regularizer, not a storage format.

    python examples/dsd_training.py --sparsity 0.7
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt_mod


@opt_mod.register
class MaskedSGD(opt_mod.SGD):
    """SGD whose updates are multiplied by fixed binary masks
    (ref example/dsd/sparse_sgd.py SparseSGD: weights pruned by
    magnitude stay zero for the whole sparse phase)."""

    def __init__(self, masks=None, **kwargs):
        super().__init__(**kwargs)
        self.masks = masks or {}

    def update(self, index, weight, grad, state):
        super().update(index, weight, grad, state)
        mask = self.masks.get(index)
        if mask is not None:
            weight *= mask


def make_data(rng, n=2048, dim=32, classes=4, w=None):
    if w is None:
        w = rng.randn(dim, classes).astype(np.float32)
    x = rng.randn(n, dim).astype(np.float32)
    y = (np.tanh(x @ w) + 0.3 * rng.randn(n, classes)).argmax(1)
    return x, y.astype(np.float32), w


def mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc3")
    return mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                                name="softmax")


def accuracy(mod, it):
    it.reset()
    metric = mx.metric.Accuracy()
    for batch in it:
        mod.forward(batch, is_train=False)
        mod.update_metric(metric, batch.label)
    return metric.get()[1]


def fit(mod, it, epochs, optimizer, opt_params):
    mod.init_optimizer(optimizer=optimizer, optimizer_params=opt_params,
                       force_init=True)
    for _ in range(epochs):
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sparsity", type=float, default=0.7)
    ap.add_argument("--epochs-per-phase", type=int, default=12)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()

    mx.random.seed(2)
    rng = np.random.RandomState(4)
    x, y, w_true = make_data(rng)
    xv, yv, _ = make_data(rng, n=512, w=w_true)
    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True,
                           label_name="softmax_label")
    vit = mx.io.NDArrayIter(xv, yv, batch_size=args.batch_size,
                            label_name="softmax_label")

    mod = mx.mod.Module(mlp(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())

    # ---- phase D: dense ----
    fit(mod, it, args.epochs_per_phase, "sgd",
        (("learning_rate", args.lr), ("momentum", 0.9)))
    acc_dense = accuracy(mod, vit)

    # ---- prune: magnitude masks at the target sparsity ----
    ex = mod._exec_group.execs[0]
    masks, param_order = {}, [n for n in mod._param_names]
    kept = total = 0
    for idx, name in enumerate(param_order):
        if not name.endswith("weight"):
            continue
        w = ex.arg_dict[name].asnumpy()
        thresh = np.quantile(np.abs(w), args.sparsity)
        mask = (np.abs(w) >= thresh).astype(np.float32)
        masks[idx] = mx.nd.array(mask)
        ex.arg_dict[name][:] = w * mask
        kept += mask.sum()
        total += mask.size
    print("density-after-prune %.3f" % (kept / total))

    # ---- phase S: sparse retraining under the mask ----
    # instance-passed optimizers skip Module's automatic
    # rescale_grad=1/batch — set it explicitly or the effective lr is
    # batch_size times larger (reference Module does the same only for
    # string-named optimizers, module/module.py init_optimizer)
    opt = MaskedSGD(masks=masks, learning_rate=args.lr / 2, momentum=0.9,
                    rescale_grad=1.0 / args.batch_size,
                    param_idx2name={i: n for i, n in
                                    enumerate(param_order)})
    mod.init_optimizer(optimizer=opt, force_init=True)
    for _ in range(args.epochs_per_phase):
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
    acc_sparse = accuracy(mod, vit)

    # ---- phase D2: dense retraining from the sparse solution ----
    fit(mod, it, args.epochs_per_phase, "sgd",
        (("learning_rate", args.lr / 4), ("momentum", 0.9)))
    acc_dsd = accuracy(mod, vit)

    print("acc-dense %.4f" % acc_dense)
    print("acc-sparse %.4f" % acc_sparse)
    print("final-dsd-acc %.4f" % acc_dsd)


if __name__ == "__main__":
    main()
