#!/usr/bin/env python
"""Deep Q-Network with replay memory and a target network.

Parity target: reference ``example/reinforcement-learning/dqn/`` —
``replay_memory.py`` (ring-buffer transitions, uniform minibatch
sampling), ``dqn_demo.py:45-180`` (epsilon-greedy exploration with a
linear decay schedule, periodic hard target-network sync, TD(0) targets
``r + gamma * max_a' Q_target(s', a')``, Huber-style clipped loss), and
``base.py``'s policy/target twin-network arrangement.

The Atari emulator is replaced by a windy-gridworld environment
(zero-egress): 6x6 grid, the agent must reach a goal while a stochastic
wind pushes it off course — enough structure that a Q net clearly beats
the random policy within a few hundred episodes.

TPU note: the Q-step (batched forward of policy AND target nets + TD
loss + SGD) is one hybridized gluon program per batch shape — the
replay batch is the unit of compilation, not the single transition.

    python examples/dqn.py --num-episodes 300
"""
import argparse
import os
import sys
from collections import deque

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


class WindyGrid(object):
    """6x6 grid; actions U/D/L/R; wind in middle columns pushes up with
    probability 0.3; +1 at goal, -0.02 per step, episodes cap at 40."""

    def __init__(self, n=6, seed=0):
        self.n = n
        self.rng = np.random.RandomState(seed)
        self.goal = (n - 1, n - 1)
        self.reset()

    def reset(self):
        self.pos = [0, 0]
        self.t = 0
        return self.obs()

    def obs(self):
        one = np.zeros(self.n * self.n, np.float32)
        one[self.pos[0] * self.n + self.pos[1]] = 1.0
        return one

    def step(self, a):
        dr, dc = [(-1, 0), (1, 0), (0, -1), (0, 1)][a]
        self.pos[0] = min(max(self.pos[0] + dr, 0), self.n - 1)
        self.pos[1] = min(max(self.pos[1] + dc, 0), self.n - 1)
        if 2 <= self.pos[1] <= 3 and self.rng.rand() < 0.3:   # wind
            self.pos[0] = max(self.pos[0] - 1, 0)
        self.t += 1
        if tuple(self.pos) == self.goal:
            return self.obs(), 1.0, True
        if self.t >= 40:
            return self.obs(), 0.0, True
        return self.obs(), -0.02, False


class ReplayMemory(object):
    """Uniform-sampling ring buffer (ref replay_memory.py)."""

    def __init__(self, capacity, obs_dim):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.act = np.zeros(capacity, np.int32)
        self.rew = np.zeros(capacity, np.float32)
        self.nxt = np.zeros((capacity, obs_dim), np.float32)
        self.done = np.zeros(capacity, np.float32)
        self.size = self.head = 0

    def push(self, s, a, r, s2, d):
        i = self.head
        self.obs[i], self.act[i], self.rew[i] = s, a, r
        self.nxt[i], self.done[i] = s2, float(d)
        self.head = (self.head + 1) % self.capacity
        self.size = min(self.size + 1, self.capacity)

    def sample(self, rng, batch):
        idx = rng.randint(0, self.size, batch)
        return (self.obs[idx], self.act[idx], self.rew[idx],
                self.nxt[idx], self.done[idx])


def make_qnet(n_actions):
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"),
            nn.Dense(64, activation="relu"),
            nn.Dense(n_actions))
    return net


def sync_target(policy, target):
    """Hard target sync (ref dqn_demo.py periodic copyto)."""
    src = policy.collect_params()
    dst = target.collect_params()
    for (_, p), (_, t) in zip(sorted(src.items()), sorted(dst.items())):
        p.data().copyto(t.data())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-episodes", type=int, default=300)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--gamma", type=float, default=0.98)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--sync-every", type=int, default=200)
    ap.add_argument("--train-every", type=int, default=4)
    ap.add_argument("--eps-decay-episodes", type=int, default=200)
    args = ap.parse_args()

    env = WindyGrid(seed=1)
    rng = np.random.RandomState(2)
    obs_dim, n_actions = env.n * env.n, 4

    policy, target = make_qnet(n_actions), make_qnet(n_actions)
    policy.initialize(mx.init.Xavier())
    target.initialize(mx.init.Xavier())
    policy.hybridize()
    target.hybridize()
    dummy = mx.nd.zeros((1, obs_dim))   # materialize deferred params
    policy(dummy)
    target(dummy)
    sync_target(policy, target)
    trainer = gluon.Trainer(policy.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.HuberLoss()
    memory = ReplayMemory(5000, obs_dim)

    steps, returns = 0, deque(maxlen=50)
    for ep in range(args.num_episodes):
        s = env.reset()
        done, ep_ret = False, 0.0
        eps = max(0.05, 1.0 - ep / float(args.eps_decay_episodes))
        while not done:
            if rng.rand() < eps:
                a = rng.randint(n_actions)
            else:
                q = policy(mx.nd.array(s[None])).asnumpy()
                a = int(q.argmax())
            s2, r, done = env.step(a)
            memory.push(s, a, r, s2, done)
            s, ep_ret = s2, ep_ret + r
            steps += 1

            if memory.size >= 200 and steps % args.train_every == 0:
                bs, ba, br, bn, bd = memory.sample(rng, args.batch_size)
                q_next = target(mx.nd.array(bn)).asnumpy().max(axis=1)
                td = br + args.gamma * q_next * (1.0 - bd)
                tgt = mx.nd.array(td)
                act = mx.nd.array(ba.astype(np.float32))
                with autograd.record():
                    q_all = policy(mx.nd.array(bs))
                    q_sel = mx.nd.sum(
                        q_all * mx.nd.one_hot(act, n_actions), axis=1)
                    loss = loss_fn(q_sel, tgt)
                loss.backward()
                trainer.step(args.batch_size)
            if steps % args.sync_every == 0:
                sync_target(policy, target)
        returns.append(ep_ret)
        if (ep + 1) % 50 == 0:
            print("episode %d eps %.2f mean-return %.3f"
                  % (ep + 1, eps, np.mean(returns)))

    # greedy evaluation
    eval_rets = []
    for _ in range(20):
        s = env.reset()
        done, total = False, 0.0
        while not done:
            a = int(policy(mx.nd.array(s[None])).asnumpy().argmax())
            s, r, done = env.step(a)
            total += r
        eval_rets.append(total)
    print("final-greedy-return %.3f" % np.mean(eval_rets))


if __name__ == "__main__":
    main()
