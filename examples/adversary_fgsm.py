#!/usr/bin/env python
"""Fast-gradient-sign adversarial examples against a trained classifier.

Parity target: reference ``example/adversary`` — train a small MNIST-like
net, then perturb inputs by ``eps * sign(dL/dx)`` (FGSM, Goodfellow 2014)
using input gradients from autograd, and show accuracy collapsing on the
adversarial batch while staying high on the clean one.

    python examples/adversary_fgsm.py --num-epochs 6 --eps 0.4
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


_MASKS = np.random.RandomState(123).rand(10, 8, 8) > 0.5


def make_set(n, rng=None):
    """10-class 'digit' patterns: class k lights a distinct fixed 8x8
    mask (shared across train AND validation sets)."""
    rng = rng or np.random.RandomState(33)
    masks = _MASKS
    y = rng.randint(0, 10, n)
    x = masks[y].astype(np.float32) * 0.8
    x += rng.normal(0, 0.15, x.shape).astype(np.float32)
    return np.clip(x, 0, 1).reshape(n, 64), y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=4)
    ap.add_argument("--eps", type=float, default=0.4)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(64, activation="relu"), gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    train_x, train_y = make_set(2048)
    for epoch in range(args.num_epochs):
        for i in range(0, len(train_x), 64):
            x = nd.array(train_x[i:i + 64])
            y = nd.array(train_y[i:i + 64])
            with autograd.record():
                # per-sample loss + step(batch) = the gluon convention
                # (Trainer.step rescales grads by 1/batch)
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(x.shape[0])
            loss = nd.mean(loss)
        logging.info("epoch %d loss %.4f", epoch, float(loss.asnumpy()))

    val_x, val_y = make_set(512, rng=np.random.RandomState(91))
    xv = nd.array(val_x)
    yv = nd.array(val_y)
    clean_acc = float((net(xv).asnumpy().argmax(axis=1) == val_y).mean())

    # FGSM: ascend the loss wrt the INPUT (x.grad via attach_grad)
    xv.attach_grad()
    with autograd.record():
        loss = nd.mean(loss_fn(net(xv), yv))
    loss.backward()
    x_adv = nd.clip(xv + args.eps * nd.sign(xv.grad), 0.0, 1.0)
    adv_acc = float((net(x_adv).asnumpy().argmax(axis=1) == val_y).mean())
    print("clean acc %.3f adversarial acc %.3f (eps=%.2f)"
          % (clean_acc, adv_acc, args.eps))
    return clean_acc, adv_acc


if __name__ == "__main__":
    main()
