#!/usr/bin/env python
"""Profiler walkthrough: Chrome-trace spans for ops, programs, and user
markers around a real training run.

Parity target: reference ``example/profiler/`` —
``profiler_matmul.py``/``profiler_ndarray.py``/``profiler_executor.py``
set ``mx.profiler.profiler_set_config`` + ``set_state('run')`` around
eager ops and executor runs and dump a ``profile.json`` for
chrome://tracing. Same flow here: eager NDArray math records per-op
spans, a Module fit records per-program spans (the unit of execution
under XLA is the compiled program, SURVEY §5.1), and ``Marker`` scopes
add user annotations; the emitted file is standard Chrome trace JSON.

    python examples/profiling_demo.py --out /tmp/profile.json
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="profile.json")
    ap.add_argument("--num-batches", type=int, default=8)
    args = ap.parse_args()

    profiler.profiler_set_config(mode="all", filename=args.out)
    profiler.set_state("run")

    # ---- eager phase: per-op spans (profiler_ndarray analogue) ----
    with profiler.Marker("eager-phase"):
        a = nd.random_uniform(shape=(256, 256))
        b = nd.random_uniform(shape=(256, 256))
        for _ in range(4):
            c = nd.dot(a, b)
            c = nd.relu(c)
        c.asnumpy()

    # ---- module phase: per-program spans (profiler_executor) ----
    with profiler.Marker("train-phase"):
        rng = np.random.RandomState(0)
        x = rng.rand(args.num_batches * 16, 32).astype(np.float32)
        y = rng.randint(0, 4, len(x)).astype(np.float32)
        it = mx.io.NDArrayIter(x, y, batch_size=16,
                               label_name="softmax_label")
        net = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                    num_hidden=32, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
        net = mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                                   name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu())
        mod.fit(it, num_epoch=2, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1})

    profiler.set_state("stop")
    profiler.dump()

    with open(args.out) as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    cats = {}
    for e in events:
        if e.get("ph") == "X":
            cats[e.get("cat", "?")] = cats.get(e.get("cat", "?"), 0) + 1
    for cat in sorted(cats):
        print("spans %s %d" % (cat, cats[cat]))
    names = {e.get("name") for e in events}
    print("has-marker %d" % int(any("phase" in (n or "") for n in names)))
    print("final-total-events %d" % len(events))


if __name__ == "__main__":
    main()
