#!/usr/bin/env python
"""Manual model parallelism: LSTM layers split across devices by
``ctx_group`` / ``group2ctx``.

Parity target: reference ``example/model-parallel-lstm/lstm.py:65-204`` —
layers are annotated with ``mx.AttrScope(ctx_group=...)`` and the bind
call maps each group to a device, the reference's manual-placement
answer for models too big for one card (PlaceDevice pass,
``graph_executor.cc:403``, ``symbol.py:1397``).

Here each group's subgraph is placed via device shardings on the bound
executor; cross-group edges become device-to-device transfers handled by
XLA. Synthetic sequence-classification data keeps it hermetic.

    python examples/model_parallel_lstm.py --num-batches 10
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def build_stacked_lstm(seq_len, num_hidden, num_classes):
    """Two LSTM layers, each pinned to its own ctx group (unrolled with
    the symbolic rnn package)."""
    import mxnet_tpu as mx
    from mxnet_tpu import rnn

    data = mx.sym.Variable("data")          # (B, T)
    label = mx.sym.Variable("softmax_label")
    with mx.AttrScope(ctx_group="embed"):
        emb = mx.sym.Embedding(data, input_dim=64, output_dim=num_hidden,
                               name="embed")
    with mx.AttrScope(ctx_group="layer0"):
        cell0 = rnn.LSTMCell(num_hidden, prefix="lstm0_")
        out0, _ = cell0.unroll(seq_len, emb, layout="NTC",
                               merge_outputs=True)
    with mx.AttrScope(ctx_group="layer1"):
        cell1 = rnn.LSTMCell(num_hidden, prefix="lstm1_")
        outs, _ = cell1.unroll(seq_len, out0, layout="NTC",
                               merge_outputs=False)
    with mx.AttrScope(ctx_group="head"):
        fc = mx.sym.FullyConnected(outs[-1], num_hidden=num_classes,
                                   name="fc")
        net = mx.sym.SoftmaxOutput(fc, label=label, name="softmax")
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=12)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-classes", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-batches", type=int, default=10,
                    help="distinct batches (cycled --num-steps times)")
    ap.add_argument("--num-steps", type=int, default=60)
    ap.add_argument("--lr", type=float, default=0.02)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    import mxnet_tpu as mx

    # Two "cards": embed+layer0 on dev0, layer1+head on dev1.
    dev0 = mx.cpu(0)
    try:
        dev1 = mx.cpu(1)
        dev1.jax_device          # resolves only if a second device exists
    except Exception:
        dev1 = dev0
    group2ctx = {"embed": dev0, "layer0": dev0,
                 "layer1": dev1, "head": dev1}

    net = build_stacked_lstm(args.seq_len, args.num_hidden,
                             args.num_classes)

    rng = np.random.RandomState(5)
    # class k = sequences dominated by tokens from band k
    Y = rng.randint(0, args.num_classes, args.batch_size * args.num_batches)
    X = np.stack([
        rng.randint(16 * (y % 4), 16 * (y % 4) + 16, args.seq_len)
        for y in Y]).astype(np.float32)

    arg_shapes, _, _ = net.infer_shape(
        data=(args.batch_size, args.seq_len))
    names = net.list_arguments()
    init = mx.init.Xavier()
    args_nd, grads_nd = {}, {}
    for name, shape in zip(names, arg_shapes):
        arr = mx.nd.zeros(shape)
        if name not in ("data", "softmax_label"):
            init(mx.init.InitDesc(name), arr)
            grads_nd[name] = mx.nd.zeros(shape)
        args_nd[name] = arr

    exe = net.bind(dev0, args_nd, args_grad=grads_nd,
                   group2ctx=group2ctx)
    logging.info("bound with group2ctx over %s",
                 sorted({str(c) for c in group2ctx.values()}))

    losses = []
    for step in range(args.num_steps):
        i = step % args.num_batches
        sl = slice(i * args.batch_size, (i + 1) * args.batch_size)
        args_nd["data"][:] = X[sl]
        args_nd["softmax_label"][:] = Y[sl].astype(np.float32)
        out = exe.forward(is_train=True)[0]
        exe.backward()
        p = out.asnumpy()
        nll = -np.log(p[np.arange(args.batch_size), Y[sl]] + 1e-8).mean()
        losses.append(nll)
        if step % 10 == 0:
            logging.info("step %d: nll %.4f", step, nll)
        for name, grad in grads_nd.items():
            args_nd[name][:] = args_nd[name] - args.lr * grad
    head, tail = np.mean(losses[:5]), np.mean(losses[-5:])
    logging.info("loss first5->last5: %.3f -> %.3f", head, tail)
    assert tail < head, "model-parallel training did not learn"
    print("final-loss: %.4f" % tail)


if __name__ == "__main__":
    main()
