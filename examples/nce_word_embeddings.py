#!/usr/bin/env python
"""Word embeddings with noise-contrastive estimation (NCE).

Parity target: reference ``example/nce-loss`` (word2vec with NCE against
a full-softmax bottleneck). Synthetic corpus: a vocabulary partitioned
into topics; sentences draw words from one topic, so words of the same
topic co-occur. Skip-gram pairs are trained with NCE — one logistic
discrimination of the true context word against k noise words drawn from
the unigram distribution — instead of a |V|-way softmax. Gate: mean
cosine similarity within topics beats across topics.

    python examples/nce_word_embeddings.py --num-epochs 5
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

VOCAB = 120
TOPICS = 6
TOPIC_SIZE = VOCAB // TOPICS
DIM = 16


def make_pairs(n_sent, sent_len, rng):
    """Skip-gram (center, context) pairs from topic-clustered sentences."""
    centers, contexts = [], []
    for _ in range(n_sent):
        topic = rng.randint(TOPICS)
        words = topic * TOPIC_SIZE + rng.randint(TOPIC_SIZE, size=sent_len)
        for i in range(sent_len):
            for j in (i - 1, i + 1):
                if 0 <= j < sent_len:
                    centers.append(words[i])
                    contexts.append(words[j])
    return np.array(centers, np.float32), np.array(contexts, np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--num-negative", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    rng = np.random.RandomState(0)
    centers, contexts = make_pairs(400, 6, rng)

    emb_in = gluon.nn.Embedding(VOCAB, DIM)
    emb_out = gluon.nn.Embedding(VOCAB, DIM)
    emb_in.initialize(mx.init.Uniform(0.1))
    emb_out.initialize(mx.init.Uniform(0.1))
    params = gluon.ParameterDict()
    params.update(emb_in.collect_params())
    params.update(emb_out.collect_params())
    trainer = gluon.Trainer(params, "adam", {"learning_rate": args.lr})

    k = args.num_negative
    bs = args.batch_size
    order = np.arange(len(centers))
    for epoch in range(args.num_epochs):
        rng.shuffle(order)
        tot = 0.0
        nb = 0
        for i in range(0, len(order) - bs + 1, bs):
            idx = order[i:i + bs]
            c = nd.array(centers[idx])
            pos = nd.array(contexts[idx])
            # noise words from the (uniform here) unigram distribution
            neg = nd.array(rng.randint(0, VOCAB, (bs, k)).astype(
                np.float32))
            with autograd.record():
                vc = emb_in(c)                       # (B, D)
                vpos = emb_out(pos)                  # (B, D)
                vneg = emb_out(neg)                  # (B, k, D)
                # NCE: log sigma(vc.vpos) + sum log sigma(-vc.vneg)
                pos_score = nd.sum(vc * vpos, axis=1)
                neg_score = nd.sum(nd.expand_dims(vc, axis=1) * vneg,
                                   axis=2)            # (B, k)
                loss = -nd.mean(nd.log(nd.sigmoid(pos_score) + 1e-7)) \
                    - nd.mean(nd.sum(nd.log(nd.sigmoid(-neg_score) + 1e-7),
                                     axis=1))
            loss.backward()
            trainer.step(bs)
            tot += float(loss.asnumpy())
            nb += 1
        logging.info("epoch %d nce loss %.4f", epoch, tot / nb)

    # gate: within-topic cosine similarity > across-topic
    W = emb_in.weight.data().asnumpy()
    W = W / (np.linalg.norm(W, axis=1, keepdims=True) + 1e-8)
    sims = W @ W.T
    topic_of = np.arange(VOCAB) // TOPIC_SIZE
    same = topic_of[:, None] == topic_of[None, :]
    np.fill_diagonal(same, False)
    within = float(sims[same].mean())
    across = float(sims[~same & ~np.eye(VOCAB, dtype=bool)].mean())
    print("within-topic sim %.3f across-topic sim %.3f margin %.3f"
          % (within, across, within - across))
    return within - across


if __name__ == "__main__":
    main()
