#!/usr/bin/env python
"""Kaggle-NDSB-style pipeline: pack images to RecordIO, train from the
native threaded decoder.

Parity target: reference ``example/kaggle-ndsb1/`` — the plankton
competition flow: ``gen_img_list.py`` builds a .lst, ``im2rec`` packs
JPEG images into .rec, ``train_dsb.py`` trains a CNN from
``ImageRecordIter`` with augmentation, and predictions come from the
trained module. The plankton corpus is replaced by procedural
"organism" silhouettes (4 morphology classes: circular, elongated,
star, ring) rendered at random scale/rotation (zero-egress).

The pipeline stages map 1:1:
  1. render images           (gen_img_list analogue)
  2. ``recordio.pack_img`` → .rec/.idx  (im2rec analogue, same format)
  3. ``image.ImageRecordIter``          (native worker-pool JPEG decode)
  4. Module CNN fit + accuracy          (train_dsb analogue)

    python examples/kaggle_ndsb_pipeline.py --num-images 512
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import recordio


def render_organism(cls, size, rng):
    """Grayscale silhouette on noise; classes differ in morphology."""
    img = rng.rand(size, size) * 90.0
    yy, xx = np.mgrid[0:size, 0:size]
    cy, cx = size / 2 + rng.randn(2) * 2
    r = (yy - cy) ** 2 + (xx - cx) ** 2
    theta = np.arctan2(yy - cy, xx - cx) + rng.rand() * np.pi
    scale = rng.uniform(0.18, 0.3) * size
    if cls == 0:                                   # circular blob
        mask = r <= scale ** 2
    elif cls == 1:                                 # elongated
        a, b = scale, scale * 0.35
        rot = rng.rand() * np.pi
        u = (xx - cx) * np.cos(rot) + (yy - cy) * np.sin(rot)
        v = -(xx - cx) * np.sin(rot) + (yy - cy) * np.cos(rot)
        mask = (u / a) ** 2 + (v / b) ** 2 <= 1.0
    elif cls == 2:                                 # 5-arm star
        wobble = 1.0 + 0.45 * np.cos(5 * theta)
        mask = r <= (scale * 0.8 * wobble) ** 2
    else:                                          # ring
        mask = (r <= scale ** 2) & (r >= (scale * 0.55) ** 2)
    img[mask] = 120.0 + rng.randn(mask.sum()) * 35.0
    rgb = np.repeat(img[:, :, None], 3, axis=2)
    return np.clip(rgb, 0, 255).astype(np.uint8)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-images", type=int, default=512)
    ap.add_argument("--image-size", type=int, default=24)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.003)
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()

    mx.random.seed(6)
    workdir = args.workdir or tempfile.mkdtemp(prefix="ndsb_")

    # ---- stage 1+2: render + pack into RecordIO (.rec/.idx) ----
    def pack_split(name, n, seed_off):
        srng = np.random.RandomState(15 + seed_off)
        path = os.path.join(workdir, name)
        w = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
        for i in range(n):
            cls = int(srng.randint(4))
            img = render_organism(cls, args.image_size, srng)
            hdr = recordio.IRHeader(0, float(cls), i, 0)
            w.write_idx(i, recordio.pack_img(hdr, img, quality=95,
                                             img_fmt=".jpg"))
        w.close()
        return path + ".rec"

    train_rec = pack_split("train", args.num_images, 0)
    val_rec = pack_split("val", 160, 1)
    print("packed %s (%d images)" % (train_rec, args.num_images))

    # ---- stage 3: native threaded decode + augmentation ----
    from mxnet_tpu.image import ImageRecordIter
    it = ImageRecordIter(path_imgrec=train_rec,
                         data_shape=(3, args.image_size, args.image_size),
                         batch_size=args.batch_size, shuffle=True,
                         rand_mirror=True, preprocess_threads=2)
    vit = ImageRecordIter(path_imgrec=val_rec,
                          data_shape=(3, args.image_size, args.image_size),
                          batch_size=args.batch_size)

    # ---- stage 4: CNN through Module ----
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16,
                             pad=(1, 1), name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=32,
                             pad=(1, 1), name="c2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=64, name="f1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="f2")
    net = mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                               name="softmax")
    mod = mx.mod.Module(net, context=mx.context.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params=(("learning_rate", args.lr),))
    metric = mx.metric.Accuracy()
    for epoch in range(args.num_epochs):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
        print("epoch %d train-acc %.4f" % (epoch, metric.get()[1]))

    vit.reset()
    metric.reset()
    for batch in vit:
        mod.forward(batch, is_train=False)
        mod.update_metric(metric, batch.label)
    print("final-val-acc %.4f" % metric.get()[1])


if __name__ == "__main__":
    main()
