#!/usr/bin/env python
"""DDPG: deterministic policy gradient for continuous control.

Parity target: reference ``example/reinforcement-learning/ddpg/`` —
``ddpg.py``/``policies.py``/``qfuncs.py``: a deterministic actor
``mu(s)``, a critic ``Q(s, a)``, soft (Polyak) target-network tracking
``theta' <- tau*theta + (1-tau)*theta'``, exploration noise on actions,
and a replay buffer; the critic regresses the TD target
``r + gamma * Q'(s', mu'(s'))`` and the actor ascends ``Q(s, mu(s))``.

The rllab/MuJoCo environment is replaced by a 1-D continuous
"docking" task (zero-egress): the agent applies bounded thrust to
reach and hold the origin; optimal return is near 0, a random policy
scores around -25.

    python examples/ddpg.py --num-episodes 60
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


class Docking(object):
    """State (pos, vel); action = thrust in [-1, 1]; reward = -(pos^2 +
    0.1 vel^2 + 0.01 a^2); episode length 40."""

    def __init__(self, seed=0):
        self.rng = np.random.RandomState(seed)
        self.reset()

    def reset(self):
        self.pos = self.rng.uniform(-2.0, 2.0)
        self.vel = 0.0
        self.t = 0
        return self.obs()

    def obs(self):
        return np.array([self.pos, self.vel], np.float32)

    def step(self, a):
        a = float(np.clip(a, -1.0, 1.0))
        self.vel = 0.9 * self.vel + 0.3 * a
        self.pos += self.vel
        self.t += 1
        r = -(self.pos ** 2 + 0.1 * self.vel ** 2 + 0.01 * a ** 2)
        return self.obs(), r, self.t >= 40


def actor_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"),
            nn.Dense(32, activation="relu"),
            nn.Dense(1, activation="tanh"))     # bounded thrust
    return net


def critic_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"),
            nn.Dense(32, activation="relu"),
            nn.Dense(1))
    return net


def soft_update(src, dst, tau):
    """Polyak tracking (ref ddpg.py soft target update). Pair by
    construction order, not name: auto-generated prefixes differ
    between instances (dense0_ vs dense3_) and sort unreliably."""
    for (_, p), (_, t) in zip(list(src.collect_params().items()),
                              list(dst.collect_params().items())):
        assert p.shape == t.shape, (p.name, t.name)
        t.data()[:] = tau * p.data() + (1.0 - tau) * t.data()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-episodes", type=int, default=60)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--gamma", type=float, default=0.97)
    ap.add_argument("--tau", type=float, default=0.05)
    ap.add_argument("--actor-lr", type=float, default=1e-3)
    ap.add_argument("--critic-lr", type=float, default=2e-3)
    ap.add_argument("--noise", type=float, default=0.3)
    args = ap.parse_args()

    mx.random.seed(10)
    env = Docking(seed=1)
    rng = np.random.RandomState(2)

    actor, critic = actor_net(), critic_net()
    actor_t, critic_t = actor_net(), critic_net()
    for net in (actor, critic, actor_t, critic_t):
        net.initialize(mx.init.Xavier())
    dummy_s, dummy_a = mx.nd.zeros((1, 2)), mx.nd.zeros((1, 3))
    actor(dummy_s); actor_t(dummy_s)
    critic(dummy_a); critic_t(dummy_a)
    soft_update(actor, actor_t, 1.0)
    soft_update(critic, critic_t, 1.0)
    a_tr = gluon.Trainer(actor.collect_params(), "adam",
                         {"learning_rate": args.actor_lr})
    c_tr = gluon.Trainer(critic.collect_params(), "adam",
                         {"learning_rate": args.critic_lr})
    l2 = gluon.loss.L2Loss()

    buf_s = np.zeros((20000, 2), np.float32)
    buf_a = np.zeros((20000, 1), np.float32)
    buf_r = np.zeros(20000, np.float32)
    buf_s2 = np.zeros((20000, 2), np.float32)
    size = head = 0

    def cat(s, a):
        return mx.nd.concat(s, a, dim=1)

    returns = []
    for ep in range(args.num_episodes):
        s = env.reset()
        done, total = False, 0.0
        while not done:
            a = float(actor(mx.nd.array(s[None])).asnumpy()[0, 0])
            a = np.clip(a + args.noise * rng.randn(), -1.0, 1.0)
            s2, r, done = env.step(a)
            buf_s[head], buf_a[head, 0], buf_r[head], buf_s2[head] = \
                s, a, r, s2
            head = (head + 1) % len(buf_s)
            size = min(size + 1, len(buf_s))
            s, total = s2, total + r

            if size >= 500:
                idx = rng.randint(0, size, args.batch_size)
                bs = mx.nd.array(buf_s[idx])
                ba = mx.nd.array(buf_a[idx])
                br = mx.nd.array(buf_r[idx])
                bs2 = mx.nd.array(buf_s2[idx])
                # critic: TD target from TARGET nets
                a2 = actor_t(bs2)
                q2 = critic_t(cat(bs2, a2))[:, 0]
                target = br + args.gamma * q2
                with autograd.record():
                    q = critic(cat(bs, ba))[:, 0]
                    closs = l2(q, mx.nd.BlockGrad(target))
                closs.backward()
                c_tr.step(args.batch_size)
                # actor: ascend Q(s, mu(s)) — grads flow THROUGH the
                # critic into the actor (the deterministic PG)
                with autograd.record():
                    aloss = -mx.nd.mean(critic(cat(bs, actor(bs))))
                aloss.backward()
                a_tr.step(args.batch_size)
                soft_update(actor, actor_t, args.tau)
                soft_update(critic, critic_t, args.tau)
        returns.append(total)
        if (ep + 1) % 20 == 0:
            print("episode %d mean-return %.2f"
                  % (ep + 1, np.mean(returns[-20:])))

    # deterministic evaluation
    evals = []
    for _ in range(10):
        s = env.reset()
        done, total = False, 0.0
        while not done:
            a = float(actor(mx.nd.array(s[None])).asnumpy()[0, 0])
            s, r, done = env.step(a)
            total += r
        evals.append(total)
    print("random-baseline ~ -25")
    print("final-eval-return %.3f" % np.mean(evals))


if __name__ == "__main__":
    main()
