#!/usr/bin/env python
"""Child-Sum Tree-LSTM for tree-pair relatedness (Tai et al. 2015).

Parity target: reference ``example/gluon/tree_lstm/`` — a
``ChildSumLSTMCell`` (tree_lstm.py:22-120: i2h on the node input, hs2h
on the SUM of child hiddens for the i/u/o gates, a per-child forget
gate from hc2h, cell = sum of forgotten child cells + i*u) and a
``Similarity`` head scoring two tree encodings (tree_lstm.py:123-151:
elementwise product + absolute difference → dense → score), trained on
SICK relatedness and evaluated with Pearson correlation
(main.py:144-178).

Two deliberate departures:
- the SICK corpus becomes synthetic random trees whose ground-truth
  relatedness is the Jaccard overlap of their leaf-token multisets
  (zero-egress, structure-sensitive);
- the reference recurses node-by-node in Python (one op dispatch per
  gate per node). Here the tree is LEVELIZED: nodes are grouped by
  depth and each level runs as ONE batched embedding/matmul/gather
  set — the TPU-native layout (a level is a batch; ragged children are
  a padded (node, k) gather + mask). Same math, ~10x fewer dispatches.

    python examples/tree_lstm.py --num-pairs 120 --num-epochs 6
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

KMAX = 3      # max children per node (generator guarantees this)


class Tree(object):
    __slots__ = ("children", "token")

    def __init__(self, token=None, children=()):
        self.token = token
        self.children = list(children)

    def leaves(self):
        if not self.children:
            return [self.token]
        out = []
        for c in self.children:
            out.extend(c.leaves())
        return out


def random_tree(rng, vocab, n_leaves):
    nodes = [Tree(token=int(rng.randint(vocab))) for _ in range(n_leaves)]
    while len(nodes) > 1:
        k = rng.randint(2, min(KMAX, len(nodes)) + 1)
        picked = [nodes.pop(rng.randint(len(nodes))) for _ in range(k)]
        nodes.append(Tree(children=picked))
    return nodes[0]


def jaccard(a, b):
    sa, sb = set(a), set(b)
    return len(sa & sb) / max(len(sa | sb), 1)


def levelize(forest):
    """Flatten a FOREST of trees into joint per-level batches.

    Returns (tokens, levels, roots): ``tokens`` is the int array for
    level 0 (every leaf of every tree); each later level is
    (child_idx, child_mask) with indices into the concatenated node
    order so far, padded to KMAX; ``roots`` indexes each tree's root.
    The whole minibatch is one disconnected graph, so a level is ONE
    batched embedding/matmul/gather set across all trees — the layout
    a TPU wants (and ~100x fewer dispatches than per-node recursion).
    """
    depth = {}

    def d(node):
        if id(node) in depth:
            return depth[id(node)]
        val = 0 if not node.children else 1 + max(d(c) for c in node.children)
        depth[id(node)] = val
        return val

    nodes = []

    def collect(node):
        for c in node.children:
            collect(c)
        nodes.append(node)

    for tree in forest:
        d(tree)
        collect(tree)
    nodes.sort(key=lambda n: depth[id(n)])
    order = {id(n): i for i, n in enumerate(nodes)}
    max_d = max(depth[id(t)] for t in forest)
    tokens = np.array([n.token for n in nodes if depth[id(n)] == 0],
                      np.int32)
    levels = []
    for lvl in range(1, max_d + 1):
        level_nodes = [n for n in nodes if depth[id(n)] == lvl]
        idx = np.zeros((len(level_nodes), KMAX), np.int32)
        mask = np.zeros((len(level_nodes), KMAX), np.float32)
        for i, n in enumerate(level_nodes):
            for j, c in enumerate(n.children):
                idx[i, j] = order[id(c)]
                mask[i, j] = 1.0
        levels.append((idx, mask))
    roots = np.array([order[id(t)] for t in forest], np.int32)
    # pre-stage constant index/mask tensors on device ONCE (they are
    # reused every epoch; rebuilding them per step dominates eager cost)
    staged = [(mx.nd.array(idx.reshape(-1)), mx.nd.array(mask), idx.shape)
              for idx, mask in levels]
    return mx.nd.array(tokens), staged, mx.nd.array(roots)


class ChildSumTreeLSTM(gluon.Block):
    """Levelized child-sum cell — same gate math as the reference's
    recursive node_forward (ref tree_lstm.py:70-120)."""

    def __init__(self, hidden, vocab, embed):
        super().__init__()
        self.hidden = hidden
        self.embed = nn.Embedding(vocab, embed)
        self.i2h = nn.Dense(4 * hidden, in_units=embed)
        self.hs2h = nn.Dense(3 * hidden, in_units=hidden)
        self.hc2h = nn.Dense(hidden, in_units=hidden)
        zero_x = np.zeros((1, embed), np.float32)
        self._zero_x = zero_x        # internal nodes have no token input

    def forward(self, schedule):
        tokens, levels, roots = schedule
        H = self.hidden
        # ---- level 0: every leaf in one batch ----
        x = self.embed(tokens)
        iuox = self.i2h(x)
        i = mx.nd.sigmoid(mx.nd.slice_axis(iuox, 1, 0, H))
        u = mx.nd.tanh(mx.nd.slice_axis(iuox, 1, 2 * H, 3 * H))
        o = mx.nd.sigmoid(mx.nd.slice_axis(iuox, 1, 3 * H, 4 * H))
        c_all = i * u
        h_all = o * mx.nd.tanh(c_all)

        # ---- internal levels: batched gather + masked child-sum ----
        zero_iuox = self.i2h(mx.nd.array(self._zero_x))       # (1, 4H)
        for flat, mask_nd, (n, k) in levels:
            h_kids = mx.nd.reshape(mx.nd.take(h_all, flat), (n, k, H))
            c_kids = mx.nd.reshape(mx.nd.take(c_all, flat), (n, k, H))
            m = mx.nd.expand_dims(mask_nd, 2)                  # (n, k, 1)
            h_kids = h_kids * m
            c_kids = c_kids * m
            hs = mx.nd.sum(h_kids, axis=1)                     # (n, H)
            iuo_h = self.hs2h(hs)                              # (n, 3H)
            i_x = mx.nd.slice_axis(zero_iuox, 1, 0, H)
            f_x = mx.nd.slice_axis(zero_iuox, 1, H, 2 * H)
            u_x = mx.nd.slice_axis(zero_iuox, 1, 2 * H, 3 * H)
            o_x = mx.nd.slice_axis(zero_iuox, 1, 3 * H, 4 * H)
            i = mx.nd.sigmoid(i_x + mx.nd.slice_axis(iuo_h, 1, 0, H))
            u = mx.nd.tanh(u_x + mx.nd.slice_axis(iuo_h, 1, H, 2 * H))
            o = mx.nd.sigmoid(o_x + mx.nd.slice_axis(iuo_h, 1, 2 * H, 3 * H))
            # per-child forget gates, one batched hc2h over (n*k, H)
            f_h = self.hc2h(mx.nd.reshape(h_kids, (n * k, H)))
            f = mx.nd.sigmoid(mx.nd.reshape(f_h, (n, k, H)) +
                              mx.nd.expand_dims(f_x, 0))
            c = i * u + mx.nd.sum(f * c_kids * m, axis=1)
            h = o * mx.nd.tanh(c)
            h_all = mx.nd.concat(h_all, h, dim=0)
            c_all = mx.nd.concat(c_all, c, dim=0)
        return mx.nd.take(h_all, roots)                        # (B, H)


class Similarity(gluon.Block):
    """Relatedness head over two encodings (ref tree_lstm.py:123-151)."""

    def __init__(self, hidden, sim_hidden=32):
        super().__init__()
        self.wh = nn.Dense(sim_hidden, in_units=2 * hidden)
        self.wp = nn.Dense(1, in_units=sim_hidden)

    def forward(self, lh, rh):
        feat = mx.nd.concat(lh * rh, mx.nd.abs(lh - rh), dim=1)
        return mx.nd.sigmoid(self.wp(mx.nd.tanh(self.wh(feat))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-pairs", type=int, default=400)
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=12)
    ap.add_argument("--hidden", type=int, default=24)
    ap.add_argument("--embed", type=int, default=12)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    mx.random.seed(5)
    rng = np.random.RandomState(11)
    lefts, rights, ys = [], [], []
    for _ in range(args.num_pairs):
        lt = random_tree(rng, args.vocab, int(rng.randint(3, 8)))
        rt = random_tree(rng, args.vocab, int(rng.randint(3, 8)))
        lefts.append(lt)
        rights.append(rt)
        ys.append(jaccard(lt.leaves(), rt.leaves()))
    n_train = int(0.8 * args.num_pairs)

    # one joint schedule per minibatch: the forest IS the batch
    bs = args.batch_size
    batches = []
    for s in range(0, n_train, bs):
        ltrees = lefts[s:s + bs]
        rtrees = rights[s:s + bs]
        batches.append((levelize(ltrees + rtrees), len(ltrees),
                        np.asarray(ys[s:s + bs], np.float32)))
    test_sched = (levelize(lefts[n_train:] + rights[n_train:]),
                  args.num_pairs - n_train,
                  np.asarray(ys[n_train:], np.float32))

    cell = ChildSumTreeLSTM(args.hidden, args.vocab, args.embed)
    head = Similarity(args.hidden)
    params = cell.collect_params()
    params.update(head.collect_params())
    params.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(params, "adam", {"learning_rate": args.lr})
    loss_fn = gluon.loss.L2Loss()

    def score(sched):
        fsched, nb, _ = sched
        enc = cell(fsched)                                   # (2B, H)
        lh = mx.nd.slice_axis(enc, 0, 0, nb)
        rh = mx.nd.slice_axis(enc, 0, nb, 2 * nb)
        return head(lh, rh)

    for epoch in range(args.num_epochs):
        total = 0.0
        for sched in batches:
            target = mx.nd.array(sched[2][:, None])
            with autograd.record():
                loss = loss_fn(score(sched), target)
            loss.backward()
            trainer.step(sched[1])
            total += float(loss.asnumpy().mean())
        print("epoch %d train-loss %.4f" % (epoch, total / len(batches)))

    preds = score(test_sched).asnumpy()[:, 0]
    r = float(np.corrcoef(preds, test_sched[2])[0, 1])
    print("final-pearson %.4f" % r)


if __name__ == "__main__":
    main()
