#!/usr/bin/env python
"""Sparse linear classification over LibSVM data (row_sparse weights).

Parity target: reference ``example/sparse/linear_classification.py`` (+
``linear_model.py``) — THE load-bearing sparse workload (SURVEY §2.2):
CSR batches from LibSVMIter, a (num_features, 2) row_sparse weight, a
class-weighted softmax cross-entropy via MakeLoss, trained either locally
or against a ``dist_async`` parameter server pulling only the weight rows
each batch touches (``kv.row_sparse_pull``).

Data: either ``--data-libsvm file`` or a synthetic sparse binary problem
written to a temporary LibSVM file (so the real LibSVMIter text path is
always exercised).

    python examples/sparse_linear_classification.py --num-epochs 3
    python tools/launch.py -n 2 python examples/sparse_linear_classification.py \\
        --kvstore dist_async --num-epochs 3
"""
import argparse
import logging
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def synthetic_libsvm(path, n=2048, dim=1000, density=0.02, seed=7):
    """A linearly separable-ish sparse problem in LibSVM text format.

    Ground truth: a sparse hyperplane w*; y = 1 if x.w* > 0. Feature ids
    are drawn zipf-ish so a few rows are hot (the regime row_sparse
    updates exploit).
    """
    rng = np.random.RandomState(seed)
    w_true = np.zeros(dim)
    support = rng.choice(dim, size=dim // 10, replace=False)
    w_true[support] = rng.randn(len(support))
    nnz = max(1, int(dim * density))
    with open(path, "w") as fh:
        for _ in range(n):
            ids = np.unique(rng.zipf(1.5, nnz * 2) % dim)[:nnz]
            vals = rng.rand(len(ids)).astype(np.float32)
            y = int(np.dot(w_true[ids], vals) > 0)
            row = " ".join("%d:%.4f" % (i, v) for i, v in zip(ids, vals))
            fh.write("%d %s\n" % (y, row))


def linear_model(num_features, positive_cls_weight=1.0):
    """CSR data x row_sparse weight -> class-weighted softmax CE
    (reference linear_model.py:21-35; the custom weighted_softmax_ce op
    becomes plain symbol algebra + MakeLoss)."""
    import mxnet_tpu as mx
    S = mx.sym
    x = S.Variable("data", stype="csr")
    weight = S.Variable("weight", shape=(num_features, 2),
                        init=mx.initializer.Normal(sigma=0.01),
                        stype="row_sparse")
    bias = S.Variable("bias", shape=(2,))
    pred = S.broadcast_add(S.dot(x, weight), bias)
    y = S.Variable("softmax_label")
    logp = S.log_softmax(pred, axis=-1)
    onehot = S.one_hot(y, depth=2)
    # upweight the positive class against imbalance (ref
    # weighted_softmax_ce.py): weight 1 for class 0, w+ for class 1
    cls_w = 1.0 + (positive_cls_weight - 1.0) * y
    nll = -S.sum(logp * onehot, axis=-1) * cls_w
    loss = S.MakeLoss(S.mean(nll), name="weighted_ce")
    return S.Group([loss, S.BlockGrad(S.softmax(pred), name="prob")])


def train(args):
    import mxnet_tpu as mx

    if args.data_libsvm:
        path, dim = args.data_libsvm, args.num_features
    else:
        tmp = tempfile.NamedTemporaryFile("w", suffix=".libsvm",
                                          delete=False)
        tmp.close()
        path, dim = tmp.name, args.num_features
        synthetic_libsvm(path, n=args.num_obs, dim=dim)

    kv = mx.kv.create(args.kvstore) if args.kvstore else None
    rank = kv.rank if kv else 0
    nworker = kv.num_workers if kv else 1

    data_iter = mx.io.LibSVMIter(data_libsvm=path, data_shape=(dim,),
                                 batch_size=args.batch_size)

    model = linear_model(dim, positive_cls_weight=2.0)
    mod = mx.mod.Module(model, data_names=["data"],
                        label_names=["softmax_label"], context=mx.cpu())
    mod.bind(data_shapes=data_iter.provide_data,
             label_shapes=data_iter.provide_label)
    mod.init_params()
    mod.init_optimizer(kvstore=kv if kv else None, optimizer=args.optimizer,
                       optimizer_params=(("learning_rate", args.lr),))

    weight_index = mod._exec_group.param_names.index("weight")
    all_rows = mx.nd.array(np.arange(dim, dtype=np.float32))
    first_nll = last_nll = None
    for epoch in range(args.num_epochs):
        data_iter.reset()
        nll_sum = count = 0
        for batch in data_iter:
            if kv:
                # pull only the rows this CSR batch touches before fwd
                # (ref linear_classification.py:103-108)
                row_ids = batch.data[0].indices
                kv.row_sparse_pull(
                    "weight", mod._exec_group.param_arrays[weight_index],
                    row_ids=[row_ids], priority=-weight_index)
            mod.forward_backward(batch)
            mod.update()
            out = mod.get_outputs()[0].asnumpy()
            nll_sum += float(out.sum())
            count += 1
        mean_nll = nll_sum / max(count, 1)
        if first_nll is None:
            first_nll = mean_nll
        last_nll = mean_nll
        logging.info("rank %d epoch %d weighted-nll %.4f",
                     rank, epoch, mean_nll)
    if kv:
        # pull every row before reporting/checkpointing (ref :120-124)
        kv.row_sparse_pull("weight",
                           mod._exec_group.param_arrays[weight_index],
                           row_ids=[all_rows], priority=-weight_index)

    # held-in accuracy for the gate
    data_iter.reset()
    correct = total = 0
    for batch in data_iter:
        mod.forward(batch, is_train=False)
        prob = mod.get_outputs()[1].asnumpy()
        y = batch.label[0].asnumpy()
        correct += int((prob.argmax(axis=1) == y).sum())
        total += len(y)
    acc = correct / max(total, 1)
    print("FINAL rank=%d first_nll=%.4f last_nll=%.4f acc=%.4f"
          % (rank, first_nll, last_nll, acc))
    if kv:
        kv.barrier()
    return first_nll, last_nll, acc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--kvstore", default=None,
                    choices=[None, "local", "dist_async", "dist_sync"])
    ap.add_argument("--optimizer", default="sgd",
                    choices=["sgd", "ftrl", "adam"])
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--num-features", type=int, default=1000)
    ap.add_argument("--num-obs", type=int, default=2048)
    ap.add_argument("--data-libsvm", default=None)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    first, last, acc = train(args)
    assert last < first, "loss did not improve (%.4f -> %.4f)" % (first,
                                                                  last)


if __name__ == "__main__":
    main()
