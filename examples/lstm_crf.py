#!/usr/bin/env python
"""BiLSTM-CRF sequence tagger on a synthetic tagging task.

Parity target: reference ``example/gluon/lstm_crf`` — LSTM emissions +
a learned transition matrix, trained by maximizing the CRF
log-likelihood (forward-algorithm partition via logsumexp recursion)
and decoded with Viterbi. Eager autograd (the recursions are
data-dependent only in VALUES, so the T-step python loop traces fine).

Synthetic task: tags follow a first-order Markov chain; each tag emits
its id as a noisy feature — so both the emission net AND the learned
transitions matter (a per-step classifier underfits transitions).

    python examples/lstm_crf.py --num-epochs 8
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

T = 8
TAGS = 4
FEAT = 6

# a sticky transition chain: staying is likely, jumps are rare
_TRANS = np.full((TAGS, TAGS), 0.08)
np.fill_diagonal(_TRANS, 1.0 - 0.08 * (TAGS - 1))


def make_set(n, rng=None):
    rng = rng or np.random.RandomState(19)
    xs = np.zeros((n, T, FEAT), np.float32)
    ys = np.zeros((n, T), np.int64)
    for i in range(n):
        tag = rng.randint(TAGS)
        for t in range(T):
            tag = rng.choice(TAGS, p=_TRANS[tag])
            ys[i, t] = tag
            xs[i, t, tag] = 1.0
        xs[i] += rng.normal(0, 0.6, (T, FEAT)).astype(np.float32)
    return xs, ys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.02)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    class BiLSTMCRF(gluon.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.lstm = gluon.rnn.LSTM(16, layout="NTC",
                                           bidirectional=True)
                self.emit = gluon.nn.Dense(TAGS, flatten=False)
                self.trans = self.params.get(
                    "transitions", shape=(TAGS, TAGS),
                    init=mx.initializer.Zero())

        def emissions(self, x):                 # (N, T, TAGS)
            # skip connection: the LSTM adds temporal context on top of
            # the per-frame features instead of having to relearn them
            h = nd.concat(self.lstm(x), x, dim=2)
            return self.emit(h)

        def neg_log_likelihood(self, x, tags_np):
            """-log p(tags | x) = log Z - score(tags)."""
            em = self.emissions(x)              # (N, T, K)
            trans = self.trans.data()           # (K, K)
            n = x.shape[0]
            # numerator: emission + transition score of the gold path
            gold = nd.array(tags_np.astype(np.float32))
            score = nd.sum(nd.pick(em[:, 0, :], gold[:, 0], axis=1))
            for t in range(1, T):
                score = score + nd.sum(nd.pick(em[:, t, :], gold[:, t],
                                               axis=1))
                # transition gold[t-1] -> gold[t]
                flat = gold[:, t - 1] * TAGS + gold[:, t]
                score = score + nd.sum(nd.pick(
                    nd.reshape(trans, (1, -1)).broadcast_to((n, TAGS * TAGS)),
                    flat, axis=1))
            # partition: forward algorithm in log space
            alpha = em[:, 0, :]                 # (N, K)
            for t in range(1, T):
                # alpha_j' = logsumexp_i(alpha_i + trans_ij) + em_tj
                mat = nd.expand_dims(alpha, axis=2) + \
                    nd.expand_dims(trans, axis=0)       # (N, K, K)
                m = nd.max(mat, axis=1, keepdims=True)
                alpha = nd.log(nd.sum(nd.exp(mat - m), axis=1)) \
                    + nd.reshape(m, (n, TAGS)) + em[:, t, :]
            m = nd.max(alpha, axis=1, keepdims=True)
            logz = nd.log(nd.sum(nd.exp(alpha - m), axis=1)) \
                + nd.reshape(m, (n,))
            return (nd.sum(logz) - score) / n

        def viterbi(self, x):
            em = self.emissions(x).asnumpy()
            trans = self.trans.data().asnumpy()
            n = em.shape[0]
            path = np.zeros((n, T), np.int64)
            for i in range(n):
                delta = em[i, 0].copy()
                back = np.zeros((T, TAGS), np.int64)
                for t in range(1, T):
                    cand = delta[:, None] + trans
                    back[t] = cand.argmax(axis=0)
                    delta = cand.max(axis=0) + em[i, t]
                path[i, T - 1] = delta.argmax()
                for t in range(T - 1, 0, -1):
                    path[i, t - 1] = back[t, path[i, t]]
            return path

    net = BiLSTMCRF()
    net.collect_params().initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    train_x, train_y = make_set(512)
    bs = args.batch_size
    for epoch in range(args.num_epochs):
        tot = nb = 0
        for i in range(0, len(train_x), bs):
            x = nd.array(train_x[i:i + bs])
            with autograd.record():
                loss = net.neg_log_likelihood(x, train_y[i:i + bs])
            loss.backward()
            trainer.step(1)     # loss already per-sample-averaged
            tot += float(loss.asnumpy())
            nb += 1
        logging.info("epoch %d nll %.4f", epoch, tot / nb)

    val_x, val_y = make_set(128, rng=np.random.RandomState(88))
    pred = net.viterbi(nd.array(val_x))
    crf_acc = float((pred == val_y).mean())
    # baseline: argmax over emissions only (no transitions)
    em_only = net.emissions(nd.array(val_x)).asnumpy().argmax(axis=2)
    em_acc = float((em_only == val_y).mean())
    learned_stick = net.trans.data().asnumpy()
    diag_margin = float(np.mean(np.diag(learned_stick))
                        - np.mean(learned_stick))
    print("crf tag acc %.3f emission-only acc %.3f diag margin %.3f"
          % (crf_acc, em_acc, diag_margin))
    return crf_acc, em_acc


if __name__ == "__main__":
    main()
