#!/usr/bin/env python
"""Bayesian inference with SGLD (stochastic gradient Langevin dynamics).

Parity target: reference ``example/bayesian-methods/`` —
``sgld.ipynb``/``bdk.ipynb`` run SGLD (Welling & Teh 2011) over MXNet
models: per-step Gaussian noise with variance = learning rate turns SGD
into a posterior sampler, and predictions average over the sampled
weights. The reference demonstrates it on a toy Gaussian model and
MNIST; this rebuild uses Bayesian logistic regression on a synthetic
2-class problem where the true posterior predictive is computable by
quadrature on a grid, so the gate is a calibration check, not eyeballing.

The SGLD optimizer itself is the framework's (`optimizer.py` SGLD:
``w -= lr/2 * grad + N(0, lr)``) driven through the standard Module
path — sampling is just training with a noise-injecting optimizer.

    python examples/bayesian_sgld.py --num-samples 400
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-obs", type=int, default=120)
    ap.add_argument("--num-samples", type=int, default=400)
    ap.add_argument("--burn-in", type=int, default=200)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    mx.random.seed(3)
    rng = np.random.RandomState(8)

    # 2-D logistic regression, separable-ish data
    w_true = np.array([1.5, -2.0], np.float32)
    X = rng.randn(args.num_obs, 2).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    y = (rng.rand(args.num_obs) < p).astype(np.float32)

    # --- SGLD sampling through the Module path ---
    data = mx.sym.Variable("data")
    logit = mx.sym.FullyConnected(data, num_hidden=1, no_bias=True,
                                  name="w")
    out = mx.sym.LogisticRegressionOutput(
        logit, mx.sym.Variable("softmax_label"), name="softmax")
    it = mx.io.NDArrayIter(X, y, batch_size=args.num_obs,
                           label_name="softmax_label")
    mod = mx.mod.Module(out, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Zero())
    # rescale_grad=num_obs: SGLD wants the FULL-data log-likelihood
    # gradient; wd=1 adds the N(0,1) prior term
    mod.init_optimizer(optimizer="sgld",
                       optimizer_params=(("learning_rate", args.lr),
                                         ("wd", 1.0 / args.num_obs),
                                         ("rescale_grad", 1.0)))
    samples = []
    it.reset()
    batch = next(iter(it))
    for step in range(args.num_samples):
        mod.forward_backward(batch)
        mod.update()
        if step >= args.burn_in:
            samples.append(
                mod._exec_group.execs[0].arg_dict["w_weight"]
                .asnumpy().ravel().copy())
    samples = np.array(samples)

    # --- exact posterior predictive by grid quadrature ---
    grid = np.linspace(-6, 6, 81)
    W1, W2 = np.meshgrid(grid, grid)
    Wg = np.stack([W1.ravel(), W2.ravel()], 1)           # (G, 2)
    logits = Wg @ X.T                                     # (G, N)
    loglik = (y * -np.log1p(np.exp(-logits)) +
              (1 - y) * -np.log1p(np.exp(logits))).sum(1)
    logprior = -0.5 * (Wg ** 2).sum(1)
    post = np.exp(loglik + logprior - (loglik + logprior).max())
    post /= post.sum()

    xq = np.array([[1.0, 1.0], [-1.0, 1.0], [0.5, -0.5]], np.float32)
    exact = ((1 / (1 + np.exp(-(Wg @ xq.T)))) * post[:, None]).sum(0)
    sgld = (1 / (1 + np.exp(-(samples @ xq.T)))).mean(0)
    gap = float(np.abs(exact - sgld).max())

    post_mean_exact = (Wg * post[:, None]).sum(0)
    post_mean_sgld = samples.mean(0)
    mean_gap = float(np.abs(post_mean_exact - post_mean_sgld).max())
    print("posterior-mean exact %s sgld %s" %
          (np.round(post_mean_exact, 3), np.round(post_mean_sgld, 3)))
    print("predictive-gap %.4f" % gap)
    print("mean-gap %.4f" % mean_gap)
    # weight spread: the sampler must actually explore, not collapse
    print("sample-std %.4f" % float(samples.std(0).min()))


if __name__ == "__main__":
    main()
