#!/usr/bin/env python
"""Actor-critic policy gradient on a tiny corridor environment.

Parity target: reference ``example/gluon/actor_critic.py`` — a shared
trunk with policy and value heads, REINFORCE-with-baseline updates from
per-episode returns, entropy-free softmax policy.

The built-in environment replaces OpenAI Gym (zero-egress): a 1-D
corridor where the agent starts in the middle and is rewarded at the
right end; optimal return is reachable within a few dozen episodes.

    python examples/actor_critic.py --num-episodes 150
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


class Corridor(object):
    """States 0..n-1; actions {left, right}; +1 at the right end, -1 at
    the left end, small step penalty; episode caps at 4n steps."""

    def __init__(self, n=9):
        self.n = n
        self.reset()

    def reset(self):
        self.pos = self.n // 2
        self.t = 0
        return self._obs()

    def _obs(self):
        one = np.zeros(self.n, np.float32)
        one[self.pos] = 1.0
        return one

    def step(self, action):
        self.pos += 1 if action == 1 else -1
        self.t += 1
        if self.pos <= 0:
            return self._obs(), -1.0, True
        if self.pos >= self.n - 1:
            return self._obs(), 1.0, True
        if self.t >= 4 * self.n:
            return self._obs(), 0.0, True
        return self._obs(), -0.01, False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-episodes", type=int, default=150)
    ap.add_argument("--gamma", type=float, default=0.95)
    ap.add_argument("--lr", type=float, default=0.02)
    ap.add_argument("--corridor", type=int, default=9)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    class Net(gluon.Block):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.trunk = gluon.nn.Dense(32, activation="tanh")
                self.policy = gluon.nn.Dense(2)
                self.value = gluon.nn.Dense(1)

        def forward(self, x):
            h = self.trunk(x)
            return self.policy(h), self.value(h)

    net = Net()
    net.collect_params().initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    env = Corridor(args.corridor)
    rng = np.random.RandomState(0)
    returns_hist = []

    for episode in range(args.num_episodes):
        obs = env.reset()
        observations, actions, rewards = [], [], []
        done = False
        while not done:
            logits, _ = net(nd.array(obs[None]))
            z = logits.asnumpy()[0]
            p = np.exp(z - z.max())          # stabilized softmax
            p = p / p.sum()
            a = int(rng.choice(2, p=p))
            observations.append(obs)
            actions.append(a)
            obs, r, done = env.step(a)
            rewards.append(r)
        # discounted returns
        G, ret = 0.0, []
        for r in reversed(rewards):
            G = r + args.gamma * G
            ret.append(G)
        ret = np.array(ret[::-1], np.float32)
        returns_hist.append(sum(rewards))

        x = nd.array(np.stack(observations))
        a_idx = nd.array(np.array(actions, np.float32))
        g = nd.array(ret)
        T = len(actions)
        with autograd.record():
            logits, values = net(x)
            values = values.reshape((T,))
            logp = nd.log_softmax(logits)
            chosen = nd.pick(logp, a_idx)
            adv = (g - values).detach()
            policy_loss = -(chosen * adv).sum()
            value_loss = ((values - g) ** 2).sum()
            loss = policy_loss + 0.5 * value_loss
        loss.backward()
        trainer.step(T)
        if episode % 25 == 0:
            recent = np.mean(returns_hist[-25:])
            logging.info("episode %d: mean return %.3f", episode, recent)

    # non-overlapping halves so short runs can't compare a window with
    # itself; improvement is judged first half vs second half
    half = max(1, len(returns_hist) // 2)
    early = np.mean(returns_hist[:half])
    late = np.mean(returns_hist[-half:] if len(returns_hist) > 1
                   else returns_hist)
    logging.info("mean return first half %.3f -> second half %.3f",
                 early, late)
    if len(returns_hist) >= 2:
        assert late > early, "policy did not improve"
    print("final-return: %.4f" % late)
    return late


if __name__ == "__main__":
    main()
