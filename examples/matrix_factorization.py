#!/usr/bin/env python
"""Matrix factorization recommender (symbolic Module + Embedding).

Parity target: reference ``example/sparse/matrix_factorization.py`` /
``example/recommenders`` — two Embedding tables (users, items), a dot
scoring head, and squared-error regression on observed ratings.  The
reference's sparse variant pushes row_sparse gradients through the
kvstore; here gradients reduce dense (XLA scatter handles the sparse
update pattern) and the row_sparse path is covered by the kvstore tests.

Synthetic ratings come from a planted low-rank model, so train RMSE
falling well below the rating std proves the factorization learns.

    python examples/matrix_factorization.py --num-epochs 4
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def planted_ratings(num_users, num_items, rank, n_obs, seed=11):
    rng = np.random.RandomState(seed)
    u = rng.randn(num_users, rank).astype(np.float32) / np.sqrt(rank)
    v = rng.randn(num_items, rank).astype(np.float32) / np.sqrt(rank)
    ui = rng.randint(0, num_users, n_obs)
    vi = rng.randint(0, num_items, n_obs)
    r = (u[ui] * v[vi]).sum(1) + 0.05 * rng.randn(n_obs).astype(np.float32)
    return ui.astype(np.float32), vi.astype(np.float32), r


def build_net(num_users, num_items, factor):
    import mxnet_tpu as mx
    user = mx.sym.Variable("user")
    item = mx.sym.Variable("item")
    score = mx.sym.Variable("score")
    p = mx.sym.Embedding(user, input_dim=num_users, output_dim=factor,
                         name="user_embed")
    q = mx.sym.Embedding(item, input_dim=num_items, output_dim=factor,
                         name="item_embed")
    pred = mx.sym.sum(p * q, axis=1)
    return mx.sym.LinearRegressionOutput(pred, label=score, name="lro")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-users", type=int, default=500)
    ap.add_argument("--num-items", type=int, default=300)
    ap.add_argument("--factor", type=int, default=16)
    ap.add_argument("--num-obs", type=int, default=20000)
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--num-epochs", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    import mxnet_tpu as mx
    from mxnet_tpu.io import NDArrayIter

    ui, vi, r = planted_ratings(args.num_users, args.num_items,
                                args.factor, args.num_obs)
    it = NDArrayIter({"user": ui, "item": vi}, {"score": r},
                     batch_size=args.batch_size, shuffle=True,
                     label_name="score")

    net = build_net(args.num_users, args.num_items, args.factor)
    mod = mx.mod.Module(net, data_names=["user", "item"],
                        label_names=["score"])
    rmse = mx.metric.RMSE(label_names=["score"])
    mod.fit(it, num_epoch=args.num_epochs, eval_metric=rmse,
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Normal(0.1))

    sq, n = 0.0, 0
    it.reset()
    for batch in it:
        mod.forward(batch, is_train=False)
        pred = mod.get_outputs()[0].asnumpy()
        lab = batch.label[0].asnumpy()
        sq += float(((pred - lab) ** 2).sum())
        n += len(lab)
    final = np.sqrt(sq / n)
    logging.info("train RMSE %.4f (rating std %.3f)", final, r.std())
    print("final-rmse: %.4f" % final)
    return final


if __name__ == "__main__":
    main()
