#!/usr/bin/env python
"""Single-image super-resolution with sub-pixel (pixel-shuffle) conv.

Parity target: reference ``example/gluon/super_resolution.py`` — the
ESPCN recipe: conv trunk on the low-res image, a final conv producing
``r^2`` channels, and a periodic pixel shuffle rearranging them into an
``r``-times larger image; L2 loss against the high-res target, PSNR
reported.

Hermetic: synthetic band-limited images (random low-frequency Fourier
mixtures) stand in for BSDS; the gate is PSNR beating bicubic-free
baseline (plain nearest-neighbour upsampling) on held-out images.

    python examples/super_resolution.py --num-epochs 30
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def band_limited_images(n, size, seed, k=4):
    """Random smooth images: sum of a few low-frequency 2-D cosines."""
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    imgs = np.zeros((n, 1, size, size), np.float32)
    for i in range(n):
        for _ in range(k):
            fy, fx = rng.randint(1, 4, 2)
            ph = rng.rand(2) * 2 * np.pi
            imgs[i, 0] += rng.randn() * np.cos(
                2 * np.pi * (fy * yy + ph[0])) * np.cos(
                2 * np.pi * (fx * xx + ph[1]))
    imgs -= imgs.min(axis=(2, 3), keepdims=True)
    imgs /= imgs.max(axis=(2, 3), keepdims=True) + 1e-6
    return imgs


def psnr(a, b):
    mse = float(np.mean((a - b) ** 2))
    return 10 * np.log10(1.0 / max(mse, 1e-10))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--upscale", type=int, default=2)
    ap.add_argument("--size", type=int, default=16, help="low-res size")
    ap.add_argument("--num-train", type=int, default=256)
    ap.add_argument("--num-epochs", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    r = args.upscale
    hi = band_limited_images(args.num_train + 32, args.size * r, seed=4)
    # low-res = average-pool of high-res (the degradation model)
    lo = hi.reshape(hi.shape[0], 1, args.size, r, args.size, r).mean(
        axis=(3, 5))
    Xtr, Xva = lo[:args.num_train], lo[args.num_train:]
    Ytr, Yva = hi[:args.num_train], hi[args.num_train:]

    class ESPCN(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.c1 = gluon.nn.Conv2D(32, 5, padding=2,
                                          activation="relu")
                self.c2 = gluon.nn.Conv2D(16, 3, padding=1,
                                          activation="relu")
                self.c3 = gluon.nn.Conv2D(r * r, 3, padding=1)

        def hybrid_forward(self, F, x):
            h = self.c3(self.c2(self.c1(x)))
            # periodic shuffle via reshape/transpose (no dedicated op in
            # the 2017 surface; ref example uses the same trick); -1
            # keeps the batch dim symbolic under hybridize
            h = h.reshape((-1, 1, r, r, args.size, args.size))
            h = h.transpose((0, 1, 4, 2, 5, 3))
            return h.reshape((-1, 1, args.size * r, args.size * r))

    net = ESPCN()
    net.collect_params().initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.L2Loss()

    B = min(args.batch_size, len(Xtr))
    for epoch in range(args.num_epochs):
        perm = np.random.RandomState(epoch).permutation(len(Xtr))
        tot, nb = 0.0, 0
        for i in range(0, len(Xtr) - B + 1, B):
            idx = perm[i:i + B]
            x, y = nd.array(Xtr[idx]), nd.array(Ytr[idx])
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(B)
            tot += float(loss.asnumpy().mean())
            nb += 1
        logging.info("epoch %d: train L2 %.5f", epoch, tot / max(nb, 1))

    pred = net(nd.array(Xva)).asnumpy()
    base = Xva.repeat(r, axis=2).repeat(r, axis=3)   # nearest-neighbour
    p_model = psnr(pred, Yva)
    p_base = psnr(base, Yva)
    logging.info("val PSNR: model %.2f dB vs nearest %.2f dB",
                 p_model, p_base)
    assert p_model > p_base, "super-resolution did not beat nearest"
    print("final-psnr: %.3f (baseline %.3f)" % (p_model, p_base))
    return p_model, p_base


if __name__ == "__main__":
    main()
