#!/usr/bin/env python
"""Train a CIFAR-scale model and publish it as a zoo artifact.

Parity target: the reference's pretrained-model story — a trained
``.params`` file served by the model-store cache so ``pretrained=True``
(gluon) and ``Module.load`` (symbolic) both resolve a real object. This
build has zero network egress, so the training set is the synthetic
CIFAR-10 stand-in from ``train_cifar10.py`` and the artifact records its
own provenance + accuracy in ``zoo/README.md``.

Publishes, for name ``cifar10_synth_mobilenet0.25``:
  zoo/<name>.params          gluon save_params format (model_store path)
  zoo/<name>-symbol.json     symbol graph (Module path)
  zoo/<name>-0000.params     V2 NDArray checkpoint (Module path)

    python examples/train_publish_cifar.py --num-epochs 10 --publish zoo
"""
import argparse
import json
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

NAME = "cifar10_synth_mobilenet0.25"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=15)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--publish", default=None,
                    help="directory to write the artifact into")
    ap.add_argument("--min-acc", type=float, default=0.9)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.io import NDArrayIter, DataDesc, DataBatch
    from train_cifar10 import synthetic_cifar

    (tr_x, tr_y), (va_x, va_y) = synthetic_cifar()
    # ImageNet-family backbones downsample 32px to nothing; the artifact
    # is published at 64px input (2x nearest upsample), recorded in meta
    up = lambda x: np.repeat(np.repeat(x, 2, axis=2), 2, axis=3)
    tr_x, va_x = up(tr_x), up(va_x)
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()

    net = vision.get_model("mobilenet0.25", classes=10)
    net.collect_params().initialize(mx.init.Xavier(), ctx=ctx)
    net.hybridize()

    it = NDArrayIter(tr_x, tr_y, batch_size=args.batch_size, shuffle=True,
                     label_name="softmax_label")
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    from mxnet_tpu import autograd
    for epoch in range(args.num_epochs):
        it.reset()
        tot = n = 0
        for batch in it:
            x = batch.data[0].as_in_context(ctx)
            y = batch.label[0].as_in_context(ctx)
            with autograd.record():
                loss = nd.mean(loss_fn(net(x), y))
            loss.backward()
            trainer.step(x.shape[0])
            tot += float(loss.asnumpy())
            n += 1
        logging.info("epoch %d loss %.4f", epoch, tot / n)

    # validation accuracy
    correct = 0
    for i in range(0, len(va_x), 256):
        out = net(nd.array(va_x[i:i + 256], ctx=ctx)).asnumpy()
        correct += int((out.argmax(axis=1) == va_y[i:i + 256]).sum())
    acc = correct / len(va_x)
    print("val accuracy: %.4f (device %s)" % (acc, ctx.device_type))

    if args.publish:
        assert acc >= args.min_acc, \
            "accuracy %.3f below publish bar %.2f" % (acc, args.min_acc)
        os.makedirs(args.publish, exist_ok=True)
        # gluon artifact (model_store / pretrained=True path)
        gpath = os.path.join(args.publish, NAME + ".params")
        net.save_params(gpath)
        # symbolic artifact (Module.load path): trace to a symbol and
        # save a V2 checkpoint with arg:/aux: keyed params
        data = mx.sym.Variable("data")
        out_sym = mx.sym.SoftmaxOutput(net(data), mx.sym.Variable(
            "softmax_label"), name="softmax")
        arg_params, aux_params = {}, {}
        for pname, p in net.collect_params().items():
            (aux_params if p.grad_req == "null" else arg_params)[pname] = \
                p.data().as_in_context(mx.cpu())
        mx.model.save_checkpoint(os.path.join(args.publish, NAME), 0,
                                 out_sym, arg_params, aux_params)
        meta = {"name": NAME, "val_accuracy": round(acc, 4),
                "dataset": "synthetic CIFAR-10 stand-in "
                           "(train_cifar10.synthetic_cifar, zero-egress)",
                "input_shape": [3, 64, 64],
                "preprocess": "2x nearest upsample of the 32px set",
                "epochs": args.num_epochs, "device": ctx.device_type}
        with open(os.path.join(args.publish, NAME + ".json"), "w") as fh:
            json.dump(meta, fh, indent=1)
        print("published %s (acc %.4f) to %s" % (NAME, acc, args.publish))
    return acc


if __name__ == "__main__":
    main()
